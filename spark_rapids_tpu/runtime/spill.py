"""Spill framework: spillable batches + tiered buffer catalog.

Reference (SURVEY.md §2.5): SpillableColumnarBatch.scala, RapidsBufferCatalog
(DEVICE -> HOST -> DISK demotion chain, RapidsBufferCatalog.scala:638-677),
RapidsDeviceMemoryStore / RapidsHostMemoryStore (bounded) / RapidsDiskStore,
SpillPriorities.

TPU mapping: a DeviceTable's XLA buffers free when the last reference drops,
so "spilling" = copy to host numpy + drop the device reference. The catalog
keeps every registered spillable in a priority order and demotes
device->host->disk until a byte target is met. Host tier is bounded by
spark.rapids.memory.host.spillStorageSize; overflow goes to disk files."""

from __future__ import annotations

import atexit
import glob
import itertools
import os
import pickle
import struct
import tempfile
import threading
import time
import weakref
import zlib
from typing import Dict, List, Optional

from spark_rapids_tpu.columnar import DeviceTable, HostTable
from spark_rapids_tpu.errors import (
    ColumnarProcessingError,
    SpillCorruptionError,
)
from spark_rapids_tpu.obs.metrics import metric_scope
from spark_rapids_tpu.runtime.faults import fault_point
from spark_rapids_tpu.lockorder import ordered_lock, ordered_rlock

TIER_DEVICE = "DEVICE"
TIER_HOST = "HOST"
TIER_DISK = "DISK"

#: CRC32 footer width on disk-tier spill frames (TPAK convention)
_CRC_LEN = 4


class _RawSpill:
    """Raw-buffer host copy of a spilled DeviceTable — the exact device
    arrays as numpy (data, validity, live mask), NO decode/re-encode.
    The reference's RapidsDeviceMemoryStore copies device buffers
    byte-for-byte to host for the same reason this exists: a
    decode->re-encode round trip through HostTable COMPACTS masked
    batches and re-normalizes payload bits, so a batch that spilled
    mid-retry would re-land in a different layout and change the
    accumulation order of the kernel that replays over it — breaking
    the bit-identity contract budget enforcement must preserve.
    Nested columns keep the legacy HostTable detour (their buffers are
    composite); they are never masked."""

    __slots__ = ("names", "cols", "live", "nrows", "capacity")

    def __init__(self, names, cols, live, nrows, capacity):
        self.names = names
        self.cols = cols  # [(dtype, data, validity, dict, sorted, domain)]
        self.live = live
        self.nrows = nrows
        self.capacity = capacity

    def nbytes(self) -> int:
        total = 0 if self.live is None else self.live.nbytes
        for _dt, data, validity, _d, _s, _dom in self.cols:
            total += data.nbytes + validity.nbytes
        return total

    def to_device(self) -> DeviceTable:
        import jax.numpy as jnp
        from spark_rapids_tpu.columnar import DeviceColumn
        cols = [DeviceColumn(dt, jnp.asarray(data), jnp.asarray(validity),
                             dictionary=dictionary, dict_sorted=srt,
                             domain=domain)
                for dt, data, validity, dictionary, srt, domain
                in self.cols]
        live = None if self.live is None else jnp.asarray(self.live)
        return DeviceTable(self.names, cols, self.nrows, self.capacity,
                           live=live)

    @staticmethod
    def from_device(table: DeviceTable) -> "_RawSpill":
        import numpy as np
        cols = [(c.dtype, np.asarray(c.data), np.asarray(c.validity),
                 c.dictionary, c.dict_sorted, c.domain)
                for c in table.columns]
        live = None if table.live is None else np.asarray(table.live)
        return _RawSpill(table.names, cols, live, table.num_rows,
                         table.capacity)


def _check_spill_crc(frame: bytes):
    """Split a disk spill frame into (body, crc_ok)."""
    if len(frame) < _CRC_LEN:
        return b"", False
    body, footer = frame[:-_CRC_LEN], frame[-_CRC_LEN:]
    return body, struct.pack("<I", zlib.crc32(body)) == footer

# Spill priorities (reference: SpillPriorities.scala): lower value spills
# first. Inputs buffered for later re-reads spill before actively-used ones.
PRIORITY_INPUT = 0
PRIORITY_SHUFFLE = 10
PRIORITY_ACTIVE = 100


class SpillableBatch:
    """Handle that makes a batch spillable while not actively in use.

    ``get()`` brings it back to device (unspill) and returns the DeviceTable;
    ``release()`` unregisters it from the catalog."""

    _ids = itertools.count()

    def __init__(self, table: DeviceTable, catalog: "BufferCatalog",
                 priority: int = PRIORITY_INPUT):
        self.id = next(SpillableBatch._ids)
        self.priority = priority
        self.catalog = catalog
        self._device: Optional[DeviceTable] = table
        self._host: Optional[HostTable] = None
        self._disk_path: Optional[str] = None
        #: landing capacity, preserved across spill round trips so an
        #: unspilled table re-buckets to the SAME capacity it left with
        #: (downstream traces and full-outer match bitmaps key on it)
        self._capacity = table.capacity
        self._device_bytes = table.device_nbytes()
        # the device memory arbiter accounts every spillable's resident
        # table (kernel outputs registered here never went through a
        # from_host landing); spilling drops the reference, which
        # releases the bytes through the ledger's weak finalizer
        from spark_rapids_tpu.runtime.memory import MEMORY
        MEMORY.account(table)
        self._host_bytes = 0
        self._lock = ordered_rlock("spill.batch")
        self._pinned = 0
        self.last_touch = time.monotonic()
        catalog.register(self)

    # -- state --------------------------------------------------------------
    # tier/byte reads are LOCK-FREE on purpose: the catalog's spill
    # walk and accounting sums read them while other threads hold
    # batch locks mid-unspill — a blocking read here closes an ABBA
    # cycle (catalog/arbiter pass -> batch lock vs unspill's batch
    # lock -> catalog lock). A torn read costs at most one slightly
    # stale byte count or a wasted spill attempt (the demotion calls
    # re-check under a NON-blocking acquire), never a wrong result.
    @property
    def tier(self) -> str:
        if self._device is not None:
            return TIER_DEVICE
        if self._host is not None:
            return TIER_HOST
        return TIER_DISK

    @property
    def device_bytes(self) -> int:
        return self._device_bytes if self._device is not None else 0

    @property
    def host_bytes(self) -> int:
        return self._host_bytes if self._host is not None else 0

    # -- access -------------------------------------------------------------
    def get(self) -> DeviceTable:
        """Materialize on device (unspilling as needed) and touch LRU.
        Raw-buffer unspill: the table re-lands with the EXACT arrays
        it left with (layout, capacity, mask, padding bits), so a
        kernel replaying over it accumulates bit-identically to the
        never-spilled run."""
        from spark_rapids_tpu.runtime.memory import MEMORY
        with self._lock:
            self.last_touch = time.monotonic()
            if self._device is None:
                # PINNED across the whole rebuild: from_host's budget
                # reserve (legacy path) and account() both may run a
                # spill pass, and the same-thread reentrant RLock would
                # otherwise let that pass demote THIS batch mid-unspill
                # — re-spilling the payload being uploaded (leaking its
                # old disk frame) or nulling _device before the return
                self._pinned += 1
                try:
                    payload = self._ensure_host_locked()
                    if isinstance(payload, _RawSpill):
                        dt = payload.to_device()
                    else:  # legacy HostTable detour (nested columns)
                        cap = (self._capacity
                               if self._capacity >= payload.num_rows
                               else None)
                        dt = DeviceTable.from_host(payload, capacity=cap)
                    self._device = dt
                    self._device_bytes = dt.device_nbytes()
                    self._host = None
                    self._host_bytes = 0
                    self.catalog.on_unspill(self)
                    # from_host accounts its own landings; the raw
                    # re-land needs explicit accounting
                    MEMORY.account(dt)
                finally:
                    self._pinned -= 1
            return self._device

    def get_host(self) -> HostTable:
        """Materialize on host WITHOUT promoting to device when
        possible (a raw-spilled masked payload has no HostTable form
        and takes the device detour)."""
        with self._lock:
            if self._device is not None:
                return self._device.to_host()
            payload = self._ensure_host_locked()
            if isinstance(payload, _RawSpill):
                return self.get().to_host()
            return payload

    def _ensure_host_locked(self) -> HostTable:
        if self._host is None:
            if self._disk_path is None:
                raise ColumnarProcessingError("spillable batch lost all tiers")
            with open(self._disk_path, "rb") as f:
                frame = f.read()
            # injected corruption flips frame bytes BEFORE the CRC
            # check — exactly what bit rot / a torn write looks like
            frame = fault_point("mem.unspill", data=frame)
            body, crc_ok = _check_spill_crc(frame)
            path = self._disk_path
            os.unlink(path)
            self.catalog._untrack_disk_file(path)
            self._disk_path = None
            if not crc_ok:
                # the corrupt frame is DROPPED, never unpickled: the
                # typed error replays the query, which re-lands this
                # data from the scan cache / source lineage
                self.catalog._metrics.add("spillCorruptions", 1)
                from spark_rapids_tpu.runtime.memory import MEM_SCOPE
                MEM_SCOPE.add("spillCorruptions", 1)
                raise SpillCorruptionError(
                    f"disk spill frame {os.path.basename(path)} failed "
                    "its CRC footer on unspill — corrupt bytes dropped; "
                    "replay re-lands from the scan cache")
            self._host = pickle.loads(body)
            self._host_bytes = self._host.nbytes()
        return self._host

    def pin(self):
        """While pinned the catalog will not spill this batch (the reference
        pins buffers during kernel use)."""
        with self._lock:
            self._pinned += 1

    def unpin(self):
        with self._lock:
            self._pinned -= 1

    @property
    def pinned(self) -> bool:
        with self._lock:
            return self._pinned > 0

    # -- demotion -----------------------------------------------------------
    def spill_to_host(self) -> int:
        """DEVICE -> HOST; returns device bytes freed. Non-blocking on
        the batch lock: a batch another thread is actively getting or
        demoting is not IDLE — skipping it (instead of blocking) also
        breaks the lock cycle between an unspill whose device landing
        triggers an arbiter spill pass and a concurrent spill pass
        walking this batch."""
        if not self._lock.acquire(blocking=False):
            return 0
        try:
            return self._spill_to_host_locked()
        finally:
            self._lock.release()

    def _spill_to_host_locked(self) -> int:
        with self._lock:
            if self._device is None or self._pinned:
                return 0
            # the spill-FAILURE injection site ('crash' kind): the
            # demotion path itself dies, the buffer stays resident
            fault_point("mem.spill")
            freed = self._device_bytes
            if any(c.is_nested for c in self._device.columns):
                # nested buffers are composite: the legacy HostTable
                # decode detour (never masked, so layout survives)
                self._host = self._device.to_host_per_column()
            else:
                # raw per-buffer copy: exact arrays, no re-encode —
                # the unspilled table is bit-identical in layout AND
                # padding, and spilling never allocates a table-sized
                # staging buffer on the exhausted device
                self._host = _RawSpill.from_device(self._device)
            self._host_bytes = self._host.nbytes()
            self._device = None
            self._device_bytes = 0
            return freed

    def spill_to_disk(self) -> int:
        """HOST -> DISK; returns host bytes freed. Non-blocking on the
        batch lock like :meth:`spill_to_host` (a busy batch is not
        idle)."""
        if not self._lock.acquire(blocking=False):
            return 0
        try:
            return self._spill_to_disk_locked()
        finally:
            self._lock.release()

    def _spill_to_disk_locked(self) -> int:
        with self._lock:
            if self._host is None or self._pinned:
                return 0
            freed = self._host_bytes
            # pid in the name: the atexit prefix sweep must be able to
            # match THIS process's files only — a shared disk_dir may
            # hold another live engine process's spill tier
            fd, path = tempfile.mkstemp(
                prefix=f"rapids_spill_{os.getpid()}_{self.id}_",
                suffix=".bin", dir=self.catalog.disk_dir)
            # CRC32 footer over the payload (the cluster TPAK frame
            # convention): unspill verifies before unpickling, so a
            # rotted/torn frame raises typed SpillCorruptionError
            # instead of serving wrong bytes
            body = pickle.dumps(self._host,
                                protocol=pickle.HIGHEST_PROTOCOL)
            with os.fdopen(fd, "wb") as f:
                f.write(body + struct.pack("<I", zlib.crc32(body)))
            self._disk_path = path
            self.catalog._track_disk_file(path)
            self._host = None
            self._host_bytes = 0
            return freed

    def release(self):
        with self._lock:
            self.catalog.unregister(self)
            self._device = None
            self._host = None
            if self._disk_path:
                self.catalog._untrack_disk_file(self._disk_path)
                if os.path.exists(self._disk_path):
                    os.unlink(self._disk_path)
            self._disk_path = None

    # context-manager sugar: `with sb.pinned_batch() as dt:`
    def pinned_batch(self):
        sb = self

        class _Pin:
            def __enter__(self):
                sb.pin()
                return sb.get()

            def __exit__(self, *exc):
                sb.unpin()
                return False

        return _Pin()


#: the operator-facing name (ISSUE 15): the hash-join build side and
#: aggregate partials register their device intermediates under this
#: alias so the probe/merge phase streams while idle tables ride the
#: device->host->disk tiers
SpillableDeviceTable = SpillableBatch


class BufferCatalog:
    """Central registry of spillables across tiers (RapidsBufferCatalog
    analog). synchronous_spill demotes lowest-priority / least-recently
    used device buffers until the byte target frees."""

    _instance: Optional["BufferCatalog"] = None
    _instance_lock = ordered_lock("spill.catalog.instance")

    #: per-catalog counters stay instance-local (two catalogs can be
    #: live at once — reset() mid-flight, per-catalog tests — and must
    #: not contaminate each other); every bump ALSO mirrors into the
    #: unified registry's process-wide ``spill`` scope (obs/metrics.py),
    #: which the event log snapshots/diffs per query
    _SCOPE_KEYS = {"spill_device_count": "spillDeviceCount",
                   "spill_disk_count": "spillDiskCount",
                   "device_spilled_bytes": "spillDeviceBytes",
                   "disk_spilled_bytes": "spillDiskBytes"}

    #: every catalog ever constructed (weak): the atexit sweep walks
    #: them so disk-tier spill files from reset()-orphaned catalogs are
    #: removed too, not just the current instance's. Guarded by its OWN
    #: lock — get()/reset() hold _instance_lock while CONSTRUCTING a
    #: catalog, so __init__ must not re-take it (non-reentrant)
    _all_catalogs: "weakref.WeakSet" = weakref.WeakSet()
    _all_catalogs_lock = ordered_lock("spill.catalog.registry")

    def __init__(self, host_limit_bytes: int = 2 << 30,
                 disk_dir: Optional[str] = None):
        self._lock = ordered_rlock("spill.catalog")
        self._buffers: Dict[int, SpillableBatch] = {}
        self.host_limit_bytes = host_limit_bytes
        self.disk_dir = disk_dir
        self._metrics = metric_scope("spill")
        #: live disk-tier spill file paths (cleaned on release/unspill;
        #: whatever survives is removed by shutdown() / the atexit
        #: sweep — before this PR they leaked on process exit)
        self._disk_files: set = set()
        self.spill_device_count = 0
        self.spill_disk_count = 0
        self.device_spilled_bytes = 0
        self.disk_spilled_bytes = 0
        with BufferCatalog._all_catalogs_lock:
            BufferCatalog._all_catalogs.add(self)

    def _bump(self, attr: str, n) -> None:
        # under the catalog lock: spill paths run from concurrent retry
        # frameworks and service workers — an unlocked read-modify-write
        # here loses increments (pinned by the concurrency test)
        with self._lock:
            setattr(self, attr, getattr(self, attr) + n)
        self._metrics.add(self._SCOPE_KEYS[attr], n)
        if attr == "device_spilled_bytes":
            # the memory scope mirrors device bytes freed by spills —
            # the out-of-core work a budgeted query paid (schema v10)
            from spark_rapids_tpu.runtime.memory import MEM_SCOPE
            MEM_SCOPE.add("spillBytes", n)

    def _track_disk_file(self, path: str) -> None:
        with self._lock:
            self._disk_files.add(path)

    def _untrack_disk_file(self, path: str) -> None:
        with self._lock:
            self._disk_files.discard(path)

    @classmethod
    def get(cls) -> "BufferCatalog":
        # double-checked: two concurrent first-users must not build two
        # catalogs (spillables registered in the loser's would never be
        # found by a spill targeting the winner's)
        if cls._instance is None:
            with cls._instance_lock:
                if cls._instance is None:
                    cls._instance = BufferCatalog()
        return cls._instance

    @classmethod
    def reset(cls, host_limit_bytes: int = 2 << 30, disk_dir=None):
        with cls._instance_lock:
            cls._instance = BufferCatalog(host_limit_bytes, disk_dir)
            return cls._instance

    def register(self, sb: SpillableBatch):
        with self._lock:
            self._buffers[sb.id] = sb

    def unregister(self, sb: SpillableBatch):
        with self._lock:
            self._buffers.pop(sb.id, None)

    def on_unspill(self, sb: SpillableBatch):
        # spilled data brought back to the device: the out-of-core
        # round trip completed (memory scope, event-log schema v10)
        from spark_rapids_tpu.runtime.memory import MEM_SCOPE
        MEM_SCOPE.add("unspills", 1)

    # -- accounting ---------------------------------------------------------
    def device_bytes(self) -> int:
        with self._lock:
            return sum(b.device_bytes for b in self._buffers.values())

    def host_bytes(self) -> int:
        with self._lock:
            return sum(b.host_bytes for b in self._buffers.values())

    def _spill_order(self) -> List[SpillableBatch]:
        with self._lock:
            bufs = [b for b in self._buffers.values()]
        return sorted(bufs, key=lambda b: (b.priority, b.last_touch))

    # -- the demotion chain -------------------------------------------------
    def synchronous_spill(self, target_bytes: int) -> int:
        """Free at least target_bytes of device memory by demoting
        device->host (then host->disk if the host tier overflows). Returns
        bytes actually freed (reference: synchronousSpill,
        RapidsBufferCatalog.scala:592)."""
        from spark_rapids_tpu.obs.spans import span
        freed = 0
        t0 = time.monotonic()
        with span("spill.device_to_host", cat="spill"):
            for sb in self._spill_order():
                if freed >= target_bytes:
                    break
                if sb.tier == TIER_DEVICE and not sb.pinned:
                    got = sb.spill_to_host()
                    if got:
                        freed += got
                        self._bump("spill_device_count", 1)
                        self._bump("device_spilled_bytes", got)
            self._enforce_host_limit()
        if freed:
            self._metrics.add("spillTime", time.monotonic() - t0)
        return freed

    def _enforce_host_limit(self):
        if self.host_bytes() <= self.host_limit_bytes:
            return
        for sb in self._spill_order():
            if sb.tier == TIER_HOST and not sb.pinned:
                got = sb.spill_to_disk()
                if got:
                    self._bump("spill_disk_count", 1)
                    self._bump("disk_spilled_bytes", got)
            if self.host_bytes() <= self.host_limit_bytes:
                break

    def spill_all_device(self) -> int:
        return self.synchronous_spill(1 << 62)

    def spill_host_to_disk(self) -> int:
        """Demote the whole HOST tier to disk (HostAlloc's free-host-memory
        hook); returns host bytes freed. Does not touch host_limit_bytes."""
        from spark_rapids_tpu.obs.spans import span
        freed = 0
        t0 = time.monotonic()
        with span("spill.host_to_disk", cat="spill"):
            for sb in self._spill_order():
                if sb.tier == TIER_HOST and not sb.pinned:
                    got = sb.spill_to_disk()
                    if got:
                        freed += got
                        self._bump("spill_disk_count", 1)
                        self._bump("disk_spilled_bytes", got)
        if freed:
            self._metrics.add("spillTime", time.monotonic() - t0)
        return freed

    # -- teardown -------------------------------------------------------------
    def shutdown(self) -> int:
        """Release every registered spillable and remove the disk-tier
        spill files THIS catalog created (the reference deletes its
        RapidsDiskStore files on executor shutdown; before this PR ours
        outlived the process). Tracked files only — another live
        catalog may share the disk_dir, and its files are its own.
        Returns files removed."""
        with self._lock:
            buffers = list(self._buffers.values())
        for sb in buffers:
            sb.release()
        return self._sweep_disk_files(prefix_sweep=False)

    def _sweep_disk_files(self, prefix_sweep: bool = False) -> int:
        """Best-effort removal of any still-tracked disk spill file.
        ``prefix_sweep`` additionally globs a dedicated disk_dir for
        THIS PROCESS's leftovers (pid-scoped prefix — another live
        engine process may share the directory, and its spill tier is
        its own) — the process-exit path only."""
        with self._lock:
            paths = list(self._disk_files)
            self._disk_files.clear()
            disk_dir = self.disk_dir
        if prefix_sweep and disk_dir:
            paths.extend(glob.glob(os.path.join(
                disk_dir, f"rapids_spill_{os.getpid()}_*.bin")))
        removed = 0
        for p in set(paths):
            try:
                os.unlink(p)
                removed += 1
            except OSError:
                pass
        return removed


@atexit.register
def _atexit_spill_sweep() -> None:
    """Process-exit sweep: whatever disk-tier files survive (releases
    skipped on a hard teardown path, reset()-orphaned catalogs) are
    removed so /tmp does not accumulate one generation of spill files
    per process lifetime."""
    with BufferCatalog._all_catalogs_lock:
        catalogs = list(BufferCatalog._all_catalogs)
    for cat in catalogs:
        try:
            cat._sweep_disk_files(prefix_sweep=True)
        except Exception:
            pass  # exit paths never raise
