"""Recursive-descent SQL parser for the supported subset.

Grammar (hand-written, mirroring Spark's SELECT surface this engine can
lower):

  statement   := query | createView | dropView [;]
  createView  := CREATE [OR REPLACE] TEMP[ORARY] VIEW name
                 ( AS query | USING fmt OPTIONS '(' k 'v' [,...] ')' )
  query       := [WITH name AS '(' query ')' [,...]] setExpr
                 [ORDER BY sortItem [,...]] [LIMIT n]
  setExpr     := select (UNION [ALL|DISTINCT] select)*
  select      := SELECT [hint] [DISTINCT] item [,...] [FROM relation]
                 [WHERE expr] [GROUP BY expr [,...]] [HAVING expr]
               | '(' query ')'
  relation    := relPrimary (join)*
  join        := [INNER|LEFT|RIGHT|FULL [OUTER]|CROSS] JOIN relPrimary
                 [ON expr | USING '(' col [,...] ')']
  expr        := precedence-climbing over OR, AND, NOT, predicates
                 (=, <>, <, <=, >, >=, IS [NOT] NULL, [NOT] IN,
                 [NOT] LIKE/RLIKE, [NOT] BETWEEN), ||, additive,
                 multiplicative, unary -, primary
  primary     := literal | DATE/TIMESTAMP/INTERVAL literal | CAST(e AS t)
               | CASE ... END | fn '(' [DISTINCT] args ')' [OVER windowDef]
               | qualified ident | '(' expr ')' | '(' query ')'

Every production records its start position so SqlParseError points at
the offending token."""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

from spark_rapids_tpu.sql import ast as A
from spark_rapids_tpu.sql.errors import SqlParseError
from spark_rapids_tpu.sql.lexer import (
    EOF,
    HINT,
    IDENT,
    NUMBER,
    OP,
    QUOTED,
    STRING,
    Token,
    tokenize,
)

#: words that terminate an expression/alias position (so `FROM t` never
#: parses FROM as an alias)
_RESERVED = {
    "SELECT", "FROM", "WHERE", "GROUP", "HAVING", "ORDER", "LIMIT",
    "JOIN", "INNER", "LEFT", "RIGHT", "FULL", "CROSS", "OUTER", "ON",
    "USING", "UNION", "ALL", "DISTINCT", "AS", "AND", "OR", "NOT", "IN",
    "IS", "NULL", "LIKE", "RLIKE", "BETWEEN", "CASE", "WHEN", "THEN",
    "ELSE", "END", "CAST", "OVER", "PARTITION", "BY", "ROWS", "RANGE",
    "WITH", "ASC", "DESC", "NULLS", "FIRST", "LAST", "EXISTS", "SEMI",
    "ANTI",
}

_INTERVAL_UNITS = {
    "YEAR": ("months", 12), "YEARS": ("months", 12),
    "MONTH": ("months", 1), "MONTHS": ("months", 1),
    "WEEK": ("days", 7), "WEEKS": ("days", 7),
    "DAY": ("days", 1), "DAYS": ("days", 1),
}


class Parser:
    def __init__(self, sql: str):
        self.sql = sql
        self.toks: List[Token] = tokenize(sql)
        self.pos = 0

    # -- token helpers -------------------------------------------------------
    def peek(self, ahead: int = 0) -> Token:
        i = min(self.pos + ahead, len(self.toks) - 1)
        return self.toks[i]

    def next(self) -> Token:
        t = self.peek()
        if t.kind != EOF:
            self.pos += 1
        return t

    def at_kw(self, *words: str) -> bool:
        t = self.peek()
        return t.kind == IDENT and t.value is not QUOTED \
            and t.upper() in words

    def eat_kw(self, *words: str) -> Optional[Token]:
        if self.at_kw(*words):
            return self.next()
        return None

    def expect_kw(self, word: str) -> Token:
        t = self.peek()
        if t.kind == IDENT and t.value is not QUOTED \
                and t.upper() == word:
            return self.next()
        raise self.err(f"expected {word}, found {t.text!r}", t)

    def at_op(self, *ops: str) -> bool:
        t = self.peek()
        return t.kind == OP and t.text in ops

    def eat_op(self, *ops: str) -> Optional[Token]:
        if self.at_op(*ops):
            return self.next()
        return None

    def expect_op(self, op: str) -> Token:
        t = self.peek()
        if t.kind == OP and t.text == op:
            return self.next()
        raise self.err(f"expected {op!r}, found {t.text!r}", t)

    def err(self, msg: str, tok: Optional[Token] = None) -> SqlParseError:
        t = tok or self.peek()
        return SqlParseError(msg, self.sql, t.line, t.col)

    @staticmethod
    def _at(node: A.Node, tok: Token) -> A.Node:
        node.line, node.col = tok.line, tok.col
        return node

    # -- statements ----------------------------------------------------------
    def parse_statement(self) -> A.Node:
        t = self.peek()
        if self.at_kw("CREATE"):
            stmt = self._create_view()
        elif self.at_kw("DROP"):
            stmt = self._drop_view()
        else:
            stmt = self.parse_query()
        self.eat_op(";")
        end = self.peek()
        if end.kind != EOF:
            raise self.err(f"unexpected input {end.text!r} after statement",
                           end)
        return self._at(stmt, t)

    def _create_view(self) -> A.Node:
        self.expect_kw("CREATE")
        replace = False
        if self.eat_kw("OR"):
            self.expect_kw("REPLACE")
            replace = True
        if not (self.eat_kw("TEMP") or self.eat_kw("TEMPORARY")):
            raise self.err("only TEMPORARY views are supported "
                           "(CREATE [OR REPLACE] TEMP VIEW ...)")
        self.expect_kw("VIEW")
        name = self._ident_token("view name").text
        if self.eat_kw("USING"):
            fmt = self._ident_token("format name").text
            options = {}
            if self.eat_kw("OPTIONS"):
                self.expect_op("(")
                while True:
                    k = self._ident_token("option key").text
                    v = self.peek()
                    if v.kind not in (STRING, NUMBER):
                        raise self.err("option value must be a literal", v)
                    self.next()
                    options[k] = v.value
                    if not self.eat_op(","):
                        break
                self.expect_op(")")
            return A.CreateView(name=name, replace=replace, using=fmt,
                                options=options)
        self.expect_kw("AS")
        return A.CreateView(name=name, replace=replace,
                            query=self.parse_query())

    def _drop_view(self) -> A.Node:
        self.expect_kw("DROP")
        self.expect_kw("VIEW")
        if_exists = False
        if self.eat_kw("IF"):
            self.expect_kw("EXISTS")
            if_exists = True
        return A.DropView(name=self._ident_token("view name").text,
                          if_exists=if_exists)

    def _ident_token(self, what: str) -> Token:
        t = self.peek()
        if t.kind != IDENT:
            raise self.err(f"expected {what}, found {t.text!r}", t)
        return self.next()

    # -- query ---------------------------------------------------------------
    def parse_query(self) -> A.Query:
        start = self.peek()
        ctes: List[Tuple[str, A.Query]] = []
        if self.eat_kw("WITH"):
            while True:
                name = self._ident_token("CTE name").text
                self.expect_kw("AS")
                self.expect_op("(")
                ctes.append((name, self.parse_query()))
                self.expect_op(")")
                if not self.eat_op(","):
                    break
        body = self._set_expr()
        order_by: List[A.SortItem] = []
        limit = None
        if self.eat_kw("ORDER"):
            self.expect_kw("BY")
            order_by = self._sort_items()
        if self.eat_kw("LIMIT"):
            t = self.peek()
            if t.kind != NUMBER or not isinstance(t.value, int):
                raise self.err("LIMIT takes an integer literal", t)
            self.next()
            limit = t.value
        q = A.Query(ctes=ctes, body=body, order_by=order_by, limit=limit)
        return self._at(q, start)

    def _set_expr(self) -> A.Node:
        left = self._select_core()
        while self.at_kw("UNION"):
            t = self.next()
            op = "union"
            if self.eat_kw("ALL"):
                op = "unionall"
            elif self.eat_kw("DISTINCT"):
                op = "union"
            right = self._select_core()
            left = self._at(A.SetOp(op=op, left=left, right=right), t)
        return left

    def _select_core(self) -> A.Node:
        if self.at_op("("):
            # parenthesized query as a set-operand
            t = self.next()
            q = self.parse_query()
            self.expect_op(")")
            return self._at(q, t)
        start = self.expect_kw("SELECT")
        hints: List[Tuple[str, Sequence[str]]] = []
        while self.peek().kind == HINT:
            hints.extend(self._parse_hint(self.next()))
        distinct = bool(self.eat_kw("DISTINCT"))
        self.eat_kw("ALL")
        items: List[A.Node] = []
        while True:
            items.append(self._select_item())
            if not self.eat_op(","):
                break
        from_ = None
        if self.eat_kw("FROM"):
            from_ = self._relation()
        where = None
        if self.eat_kw("WHERE"):
            where = self.parse_expr()
        group_by: List[A.Node] = []
        if self.eat_kw("GROUP"):
            self.expect_kw("BY")
            while True:
                group_by.append(self.parse_expr())
                if not self.eat_op(","):
                    break
        having = None
        if self.eat_kw("HAVING"):
            having = self.parse_expr()
        sel = A.Select(distinct=distinct, hints=hints, items=items,
                       from_=from_, where=where, group_by=group_by,
                       having=having)
        return self._at(sel, start)

    def _parse_hint(self, tok: Token) -> List[Tuple[str, Sequence[str]]]:
        """`REPARTITION(8, col)` style hints inside /*+ ... */."""
        sub = Parser(tok.text)
        out: List[Tuple[str, Sequence[str]]] = []
        while sub.peek().kind == IDENT:
            name = sub.next().upper()
            args: List[str] = []
            if sub.eat_op("("):
                while not sub.at_op(")"):
                    a = sub.next()
                    if a.kind == EOF:
                        raise self.err("unterminated hint", tok)
                    if a.kind in (IDENT, NUMBER):
                        args.append(a.text)
                    else:
                        raise self.err(
                            f"unsupported hint argument {a.text!r} "
                            "(identifiers and integers only)", tok)
                    sub.eat_op(",")
                sub.expect_op(")")
            out.append((name, args))
            sub.eat_op(",")
        return out

    def _select_item(self) -> A.Node:
        t = self.peek()
        if self.at_op("*"):
            self.next()
            return self._at(A.Star(), t)
        # tbl.* star
        if (t.kind == IDENT
                and (t.value is QUOTED or t.upper() not in _RESERVED)
                and self.peek(1).kind == OP and self.peek(1).text == "."
                and self.peek(2).kind == OP and self.peek(2).text == "*"):
            self.next(), self.next(), self.next()
            return self._at(A.Star(qualifier=t.text), t)
        e = self.parse_expr()
        alias = None
        if self.eat_kw("AS"):
            alias = self._ident_token("alias").text
        elif (self.peek().kind == IDENT
              and (self.peek().value is QUOTED
                   or self.peek().upper() not in _RESERVED)):
            alias = self.next().text
        return self._at(A.SelectItem(expr=e, alias=alias), t)

    def _sort_items(self) -> List[A.SortItem]:
        out: List[A.SortItem] = []
        while True:
            t = self.peek()
            e = self.parse_expr()
            asc = True
            if self.eat_kw("ASC"):
                asc = True
            elif self.eat_kw("DESC"):
                asc = False
            nulls_first = None
            if self.eat_kw("NULLS"):
                if self.eat_kw("FIRST"):
                    nulls_first = True
                elif self.eat_kw("LAST"):
                    nulls_first = False
                else:
                    raise self.err("expected FIRST or LAST after NULLS")
            out.append(self._at(
                A.SortItem(expr=e, ascending=asc, nulls_first=nulls_first),
                t))
            if not self.eat_op(","):
                break
        return out

    # -- relations -----------------------------------------------------------
    def _relation(self) -> A.Node:
        left = self._rel_primary()
        while True:
            t = self.peek()
            how = None
            if self.at_kw("JOIN"):
                how = "inner"
                self.next()
            elif self.at_kw("INNER"):
                self.next()
                self.expect_kw("JOIN")
                how = "inner"
            elif self.at_kw("CROSS"):
                self.next()
                self.expect_kw("JOIN")
                how = "cross"
            elif self.at_kw("LEFT", "RIGHT", "FULL"):
                how = self.next().upper().lower()
                if not self.eat_kw("OUTER"):
                    # LEFT SEMI / LEFT ANTI
                    if how == "left" and self.eat_kw("SEMI"):
                        how = "leftsemi"
                    elif how == "left" and self.eat_kw("ANTI"):
                        how = "leftanti"
                self.expect_kw("JOIN")
            else:
                return left
            right = self._rel_primary()
            on = None
            using: Sequence[str] = ()
            if how != "cross":
                if self.eat_kw("ON"):
                    on = self.parse_expr()
                elif self.eat_kw("USING"):
                    self.expect_op("(")
                    cols = [self._ident_token("join column").text]
                    while self.eat_op(","):
                        cols.append(self._ident_token("join column").text)
                    self.expect_op(")")
                    using = cols
                else:
                    raise self.err(
                        f"{how.upper()} JOIN requires ON or USING", t)
            left = self._at(A.JoinRel(left=left, right=right, how=how,
                                      on=on, using=using), t)

    def _rel_primary(self) -> A.Node:
        t = self.peek()
        if self.eat_op("("):
            q = self.parse_query()
            self.expect_op(")")
            alias = self._maybe_alias()
            return self._at(A.SubqueryRef(query=q, alias=alias), t)
        name = self._ident_token("table name").text
        return self._at(A.TableRef(name=name, alias=self._maybe_alias()), t)

    def _maybe_alias(self) -> Optional[str]:
        if self.eat_kw("AS"):
            return self._ident_token("alias").text
        t = self.peek()
        if t.kind == IDENT and (t.value is QUOTED
                                or t.upper() not in _RESERVED):
            return self.next().text
        return None

    # -- expressions ---------------------------------------------------------
    def parse_expr(self) -> A.Node:
        return self._or_expr()

    def _or_expr(self) -> A.Node:
        left = self._and_expr()
        while self.at_kw("OR"):
            t = self.next()
            left = self._at(A.BinOp(op="OR", left=left,
                                    right=self._and_expr()), t)
        return left

    def _and_expr(self) -> A.Node:
        left = self._not_expr()
        while self.at_kw("AND"):
            t = self.next()
            left = self._at(A.BinOp(op="AND", left=left,
                                    right=self._not_expr()), t)
        return left

    def _not_expr(self) -> A.Node:
        if self.at_kw("NOT"):
            t = self.next()
            return self._at(A.UnOp(op="NOT", operand=self._not_expr()), t)
        return self._predicate()

    def _predicate(self) -> A.Node:
        left = self._additive()
        t = self.peek()
        if t.kind == OP and t.text in ("=", "==", "<>", "!=", "<", "<=",
                                       ">", ">=", "<=>"):
            self.next()
            op = {"==": "=", "!=": "<>"}.get(t.text, t.text)
            right = self._additive()
            return self._at(A.BinOp(op=op, left=left, right=right), t)
        if self.at_kw("IS"):
            t = self.next()
            negated = bool(self.eat_kw("NOT"))
            self.expect_kw("NULL")
            return self._at(A.IsNull(operand=left, negated=negated), t)
        negated = False
        if self.at_kw("NOT") and self.peek(1).kind == IDENT and \
                self.peek(1).upper() in ("IN", "LIKE", "RLIKE", "BETWEEN"):
            self.next()
            negated = True
        if self.at_kw("IN"):
            t = self.next()
            self.expect_op("(")
            if self.at_kw("SELECT", "WITH"):
                q = self.parse_query()
                self.expect_op(")")
                return self._at(A.InSubquery(operand=left, query=q,
                                             negated=negated), t)
            items = [self.parse_expr()]
            while self.eat_op(","):
                items.append(self.parse_expr())
            self.expect_op(")")
            return self._at(A.InList(operand=left, items=items,
                                     negated=negated), t)
        if self.at_kw("LIKE", "RLIKE"):
            t = self.next()
            kind = t.upper().lower()
            return self._at(A.LikeOp(kind=kind, operand=left,
                                     pattern=self._additive(),
                                     negated=negated), t)
        if self.at_kw("BETWEEN"):
            t = self.next()
            low = self._additive()
            self.expect_kw("AND")
            high = self._additive()
            return self._at(A.Between(operand=left, low=low, high=high,
                                      negated=negated), t)
        if negated:
            raise self.err("expected IN, LIKE, RLIKE or BETWEEN after NOT")
        return left

    def _additive(self) -> A.Node:
        left = self._multiplicative()
        while self.at_op("+", "-", "||"):
            t = self.next()
            left = self._at(A.BinOp(op=t.text, left=left,
                                    right=self._multiplicative()), t)
        return left

    def _multiplicative(self) -> A.Node:
        left = self._unary()
        while self.at_op("*", "/", "%"):
            t = self.next()
            left = self._at(A.BinOp(op=t.text, left=left,
                                    right=self._unary()), t)
        return left

    def _unary(self) -> A.Node:
        if self.at_op("-"):
            t = self.next()
            return self._at(A.UnOp(op="-", operand=self._unary()), t)
        if self.at_op("+"):
            self.next()
            return self._unary()
        return self._primary()

    def _primary(self) -> A.Node:
        t = self.peek()
        if t.kind == NUMBER:
            self.next()
            return self._at(A.Literal(value=t.value), t)
        if t.kind == STRING:
            self.next()
            return self._at(A.Literal(value=t.value), t)
        if self.at_op("("):
            self.next()
            if self.at_kw("SELECT", "WITH"):
                q = self.parse_query()
                self.expect_op(")")
                return self._at(A.ScalarSubquery(query=q), t)
            e = self.parse_expr()
            self.expect_op(")")
            return e
        if t.kind != IDENT:
            raise self.err(f"unexpected {t.text!r} in expression", t)
        if t.value is QUOTED:
            # quoted identifiers are never keywords or literals:
            # `order`, `null`, `case` reference columns with those names
            if self.peek(1).kind == OP and self.peek(1).text == "(":
                return self._func_call()
            self.next()
            parts = [t.text]
            while self.at_op(".") and self.peek(1).kind == IDENT:
                self.next()
                parts.append(self.next().text)
            return self._at(A.Ident(parts=tuple(parts)), t)
        word = t.upper()
        if word == "NULL":
            self.next()
            return self._at(A.Literal(value=None), t)
        if word in ("TRUE", "FALSE"):
            self.next()
            return self._at(A.Literal(value=word == "TRUE"), t)
        if word in ("DATE", "TIMESTAMP") and self.peek(1).kind == STRING:
            self.next()
            s = self.next()
            return self._at(A.TypedLiteral(kind=word.lower(),
                                           text=s.value), t)
        if word == "INTERVAL":
            return self._interval()
        if word == "CAST":
            self.next()
            self.expect_op("(")
            e = self.parse_expr()
            self.expect_kw("AS")
            tn = self._type_name()
            self.expect_op(")")
            return self._at(A.Cast(operand=e, type_name=tn), t)
        if word == "CASE":
            return self._case()
        if word == "EXISTS" and self.peek(1).kind == OP \
                and self.peek(1).text == "(":
            raise self.err("EXISTS subqueries are not supported by the "
                           "SQL front end: use an IN subquery or a "
                           "LEFT SEMI JOIN", t)
        # function call?
        if self.peek(1).kind == OP and self.peek(1).text == "(" \
                and word not in _RESERVED:
            return self._func_call()
        # qualified / bare identifier
        if word in _RESERVED:
            raise self.err(f"unexpected keyword {t.text!r} in expression", t)
        self.next()
        parts = [t.text]
        while self.at_op(".") and self.peek(1).kind == IDENT:
            self.next()
            parts.append(self.next().text)
        return self._at(A.Ident(parts=tuple(parts)), t)

    def _interval(self) -> A.Node:
        t = self.expect_kw("INTERVAL")
        months = days = 0
        saw = False
        while self.peek().kind == NUMBER or (
                self.at_op("-") and self.peek(1).kind == NUMBER):
            sign = 1
            if self.eat_op("-"):
                sign = -1
            num = self.next()
            if not isinstance(num.value, int):
                raise self.err("interval quantity must be an integer", num)
            unit = self.peek()
            if unit.kind != IDENT or unit.upper() not in _INTERVAL_UNITS:
                raise self.err(
                    f"unsupported interval unit {unit.text!r} (supported: "
                    "YEAR/MONTH/WEEK/DAY)", unit)
            self.next()
            field, mult = _INTERVAL_UNITS[unit.upper()]
            if field == "months":
                months += sign * num.value * mult
            else:
                days += sign * num.value * mult
            saw = True
        if not saw:
            raise self.err("INTERVAL requires '<n> <unit>'", t)
        return self._at(A.IntervalLiteral(months=months, days=days), t)

    def _case(self) -> A.Node:
        t = self.expect_kw("CASE")
        operand = None
        if not self.at_kw("WHEN"):
            operand = self.parse_expr()
        branches = []
        while self.eat_kw("WHEN"):
            c = self.parse_expr()
            self.expect_kw("THEN")
            v = self.parse_expr()
            branches.append((c, v))
        if not branches:
            raise self.err("CASE requires at least one WHEN branch", t)
        else_value = None
        if self.eat_kw("ELSE"):
            else_value = self.parse_expr()
        self.expect_kw("END")
        return self._at(A.Case(operand=operand, branches=branches,
                               else_value=else_value), t)

    def _type_name(self) -> str:
        t = self._ident_token("type name")
        name = t.text
        if self.at_op("("):  # decimal(p, s) / varchar(n)
            self.next()
            args = []
            while not self.at_op(")"):
                a = self.next()
                if a.kind == EOF:
                    raise self.err("unterminated type arguments", t)
                if a.kind == NUMBER:
                    args.append(a.text)
                self.eat_op(",")
            self.expect_op(")")
            name += "(" + ", ".join(args) + ")"
        return name

    def _func_call(self) -> A.Node:
        t = self.next()
        name = t.text
        self.expect_op("(")
        distinct = bool(self.eat_kw("DISTINCT"))
        args: List[A.Node] = []
        if not self.at_op(")"):
            while True:
                if self.at_op("*"):
                    st = self.next()
                    args.append(self._at(A.Star(), st))
                else:
                    args.append(self.parse_expr())
                if not self.eat_op(","):
                    break
        self.expect_op(")")
        window = None
        if self.at_kw("OVER"):
            self.next()
            window = self._window_def()
        return self._at(A.FuncCall(name=name, args=args, distinct=distinct,
                                   window=window), t)

    def _window_def(self) -> A.WindowDef:
        t = self.expect_op("(")
        partition: List[A.Node] = []
        order: List[A.SortItem] = []
        frame = None
        if self.eat_kw("PARTITION"):
            self.expect_kw("BY")
            while True:
                partition.append(self.parse_expr())
                if not self.eat_op(","):
                    break
        if self.eat_kw("ORDER"):
            self.expect_kw("BY")
            order = self._sort_items()
        if self.at_kw("ROWS", "RANGE"):
            kind = self.next().upper().lower()
            if self.eat_kw("BETWEEN"):
                t_lo = self.peek()
                lo = self._frame_bound()
                self.expect_kw("AND")
                t_hi = self.peek()
                hi = self._frame_bound()
            else:
                t_lo = t_hi = self.peek()
                lo = self._frame_bound()
                hi = 0
            # Spark rejects backwards unbounded frames at parse time;
            # collapsing both directions to None would silently compute
            # a running aggregate instead
            if lo == "unb_following":
                raise self.err("UNBOUNDED FOLLOWING is not a valid frame "
                               "START bound", t_lo)
            if hi == "unb_preceding":
                raise self.err("UNBOUNDED PRECEDING is not a valid frame "
                               "END bound", t_hi)
            lo = None if lo == "unb_preceding" else lo
            hi = None if hi == "unb_following" else hi
            frame = (kind, lo, hi)
        self.expect_op(")")
        w = A.WindowDef(partition_by=partition, order_by=order, frame=frame)
        return self._at(w, t)

    def _frame_bound(self):
        """int offset, 0 for CURRENT ROW, or the direction-preserving
        sentinels 'unb_preceding'/'unb_following' (validated by the
        caller — which side UNBOUNDED is legal on depends on position)."""
        if self.eat_kw("UNBOUNDED"):
            if self.eat_kw("PRECEDING"):
                return "unb_preceding"
            if self.eat_kw("FOLLOWING"):
                return "unb_following"
            raise self.err(
                "expected PRECEDING or FOLLOWING after UNBOUNDED")
        if self.eat_kw("CURRENT"):
            self.expect_kw("ROW")
            return 0
        t = self.peek()
        if t.kind != NUMBER or not isinstance(t.value, int):
            raise self.err("frame bound must be UNBOUNDED, CURRENT ROW or "
                           "an integer", t)
        self.next()
        if self.eat_kw("PRECEDING"):
            return -t.value
        if self.eat_kw("FOLLOWING"):
            return t.value
        raise self.err("expected PRECEDING or FOLLOWING after frame offset")


def parse_statement(sql: str) -> A.Node:
    return Parser(sql).parse_statement()


def parse_expression(sql: str) -> A.Node:
    """Parse a standalone SQL expression (F.expr analog)."""
    p = Parser(sql)
    e = p.parse_expr()
    end = p.peek()
    if end.kind != EOF:
        raise p.err(f"unexpected input {end.text!r} after expression", end)
    return e
