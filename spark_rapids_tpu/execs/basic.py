"""Basic TPU execs: scan, range, project, filter, limit, union, coalesce,
expand (reference: basicPhysicalOperators.scala, GpuCoalesceBatches.scala,
GpuExpandExec.scala — SURVEY.md §2.3)."""

from __future__ import annotations

from typing import Iterator, List, Sequence

import jax
from spark_rapids_tpu.dispatch import tpu_jit
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar import DeviceColumn, DeviceTable, HostTable, bucket_for
from spark_rapids_tpu.columnar.column import MIN_BUCKET
from spark_rapids_tpu.execs.base import TpuExec
from spark_rapids_tpu.ops.expr import (
    DevVal,
    EvalCtx,
    Expression,
    NodePrep,
    PrepCtx,
    _walk_eval,
    _walk_prep,
    _prep_trace_key,
    compile_project,
    output_name,
)


def _scan_sharding(exec_node: TpuExec):
    """(row sharding, cache token) this scan may land under — (None,
    None) when its tree was not converted mesh-aware. Sharded placement
    is BOUND AT CONVERSION: insert_mesh_relands stamps every scan with
    the mesh generation its re-land boundaries were planned against
    (``_mesh_scan_gen``), and an unstamped or stale-stamped scan lands
    single-device — a tree converted with the mesh off carries no
    boundaries, so feeding it physically sharded batches would let
    GSPMD repartition a wide float kernel and break bit-identity when
    a concurrent session flips the process mesh mid-query. The token
    keys cached device images to the mesh GENERATION, so a
    reconfiguration invalidates every cached placement. Read atomically
    (MeshRuntime.scan_placement) so a concurrent reconfiguration cannot
    pair an old mesh's sharding with the new generation token."""
    gen = getattr(exec_node, "_mesh_scan_gen", None)
    if gen is None:
        return None, None
    from spark_rapids_tpu.parallel.mesh import MESH
    sharding, token = MESH.scan_placement()
    if token != gen:
        return None, None
    return sharding, token


def _upload_sharded(exec_node: TpuExec, host: HostTable,
                    sharding) -> DeviceTable:
    """Land one scan batch — split per device over the mesh row sharding
    when mesh-native execution is on (one jax.device_put per staged
    column delivers every device exactly its row shard, no single-host
    concat) — and account the dispatched shards on both the exec and
    the mesh scope."""
    dt = DeviceTable.from_host(host, sharding=sharding)
    # count what from_host actually DID: nested-type and zero-column
    # batches bypass the staged split and land single-device (no
    # shard_spec), so they must not claim distributed placement. The
    # shard count comes from the sharding the batch LANDED under — a
    # concurrent reconfiguration between the scan's atomic placement
    # read and this point must not pair the old mesh's placement with
    # the new mesh's device count
    if dt.shard_spec is not None:
        from spark_rapids_tpu.parallel.mesh import MESH_SCOPE
        nshards = int(dt.shard_spec.mesh.devices.size)
        MESH_SCOPE.add("shardsDispatched", nshards)
        exec_node.add_metric("shardsDispatched", nshards)
    return dt


class TpuScanExec(TpuExec):
    """Uploads pre-built host batches (LocalScan analog).

    With ``device_cache`` the uploaded DeviceTable is memoized on the host
    table itself, so repeated queries over one in-memory table skip the
    H2D transfer entirely — the GpuInMemoryTableScanExec / DataFrame.cache
    analog (reference: InMemoryTableScanExec override, GpuOverrides.scala).
    Cached images are dropped on device OOM (columnar.table.
    evict_device_caches, wired into the retry framework)."""

    def __init__(self, batches: Sequence[HostTable], device_cache: bool = True):
        super().__init__()
        self.batches = list(batches)
        self.device_cache = device_cache

    def output_schema(self):
        return self.batches[0].schema()

    def execute(self):
        from spark_rapids_tpu.columnar.table import register_device_cache
        from spark_rapids_tpu.runtime.memory import scan_chunks
        from spark_rapids_tpu.runtime.retry import retry_block
        sharding, shard_token = _scan_sharding(self)
        for b in self.batches:
            # out-of-core scan: a batch whose estimated device bytes
            # exceed its budget share lands as bounded partitions
            # (runtime/memory.py scan_chunks); chunked landings bypass
            # the device cache — a multi-chunk image would pin the very
            # budget the chunking protects. Each landing is wrapped in
            # the OOM retry loop so a budget squeeze spills and
            # replays instead of failing the query at the scan.
            chunks = scan_chunks(b)
            if len(chunks) > 1 or not self.device_cache:
                if len(chunks) > 1:
                    self.add_metric("scanChunks", len(chunks))
                for ch in chunks:
                    yield retry_block(
                        lambda c=ch: _upload_sharded(self, c, sharding))
                continue
            entry = b._cache.get("device")
            # the cached image must match the CURRENT mesh layout — a
            # reconfigured (or newly enabled/disabled) mesh re-lands
            # the shards rather than serving a stale placement
            if entry is not None and entry[1] == shard_token:
                self.add_metric("scanCacheHit", 1)
                yield entry[0]
                continue
            dt = retry_block(
                lambda: _upload_sharded(self, b, sharding))
            b._cache["device"] = (dt, shard_token)
            register_device_cache(b)
            self.add_metric("scanCacheMiss", 1)
            yield dt

    def describe(self):
        return f"TpuScan[{len(self.batches)} batches]"


class TpuFileScanExec(TpuExec):
    """File scan on device: the scan node's reader (with its PERFILE /
    COALESCING / MULTITHREADED prefetch behavior) feeds decoded host batches
    that upload to HBM here (reference: GpuFileSourceScanExec +
    MultiFile*PartitionReader — decode output lands in device memory)."""

    def __init__(self, scan_node):
        super().__init__()
        self.scan_node = scan_node
        #: execution-scoped dynamic partition pruning filters — owned by
        #: THIS converted exec, never by the shared logical scan node
        #: (overrides/rules._maybe_install_dpp)
        self._dynamic_prunes: list = []

    def install_dynamic_pruning(self, part_col: str, provider) -> None:
        self._dynamic_prunes.append((part_col, provider))

    def output_schema(self):
        return self.scan_node.output_schema()

    def execute(self):
        import time
        from spark_rapids_tpu.runtime.memory import scan_chunks
        from spark_rapids_tpu.runtime.retry import retry_block
        sharding, _ = _scan_sharding(self)
        for batch in self.scan_node.execute_cpu(
                dynamic_prunes=self._dynamic_prunes or None,
                metrics=self.metrics):
            # out-of-core scan: decoded batches over the budget share
            # land as bounded partitions (runtime/memory.py), each
            # upload OOM-retryable (budget squeezes spill and replay)
            chunks = scan_chunks(batch)
            if len(chunks) > 1:
                self.add_metric("scanChunks", len(chunks))
            for ch in chunks:
                t0 = time.perf_counter()
                # mesh-native: each decoded file/row-group batch lands
                # SPLIT across the mesh (execs/basic._upload_sharded)
                dt = retry_block(
                    lambda c=ch: _upload_sharded(self, c, sharding))
                self.add_metric("scanUploadTime",
                                time.perf_counter() - t0)
                self.add_metric("scanBatches", 1)
                self.add_metric("scanRows", ch.num_rows)
                yield dt

    def describe(self):
        return f"TpuFileScan[{self.scan_node.describe()}]"


class TpuRangeExec(TpuExec):
    """Device-side range generation (reference: GpuRangeExec)."""

    def __init__(self, start: int, end: int, step: int, batch_rows: int, name: str):
        super().__init__()
        self.start, self.end, self.step = start, end, step
        self.batch_rows = batch_rows
        self.col_name = name

    def output_schema(self):
        return [(self.col_name, T.LONG)]

    def execute(self):
        total = max(0, -(-(self.end - self.start) // self.step))
        pos = 0
        while True:
            cnt = min(self.batch_rows, total - pos) if total else 0
            cap = bucket_for(max(cnt, 1))
            data = jnp.arange(cap, dtype=jnp.int64) * self.step + (self.start + pos * self.step)
            validity = jnp.arange(cap, dtype=jnp.int32) < cnt
            yield DeviceTable([self.col_name], [DeviceColumn(T.LONG, data, validity)], cnt, cap)
            pos += cnt
            if pos >= total:
                break


class TpuProjectExec(TpuExec):
    def __init__(self, child: TpuExec, exprs: Sequence[Expression], names: Sequence[str]):
        super().__init__()
        self.children = (child,)
        self.exprs = list(exprs)
        self.names = list(names)

    def output_schema(self):
        return [(n, e.data_type) for n, e in zip(self.names, self.exprs)]

    produces_masked = True

    def execute_masked(self):
        from spark_rapids_tpu.ops.expr import has_position_dependent
        from spark_rapids_tpu.runtime.retry import with_retry
        exprs, names = self.exprs, self.names
        # compact first when slot numbering matters (position-dependent
        # exprs) or when outputs are NESTED (array/struct/map columns have
        # no compaction scatter — they must only ever live in prefix
        # batches; TypeSig keeps nested out of mask-producing execs)
        must_compact = (
            any(has_position_dependent(e) for e in exprs)
            or any(isinstance(e.data_type,
                              (T.ArrayType, T.StructType, T.MapType))
                   for e in exprs))

        def run(dt):
            if must_compact:
                dt = dt.compacted()
            cols = compile_project(exprs, dt)
            return DeviceTable(names, cols, dt.nrows_dev, dt.capacity,
                               live=dt.live)

        for batch in self.children[0].execute_masked():
            yield from with_retry(batch, run)

    def describe(self):
        return f"TpuProject{self.names}"


class _FilterKernel:
    """Fused predicate evaluation + row compaction, one jit per
    (schema, predicate, bucket, prep structure).

    Compaction is O(n): scatter kept rows to cumsum positions (dropped rows
    scatter out of bounds with mode='drop') — no sort needed."""

    def __init__(self, condition: Expression):
        self.condition = condition

    def __call__(self, table: DeviceTable, emit_mask: bool = False):
        """``emit_mask=True`` returns a MASKED table (keep-mask + count, no
        compaction scatter — columnar/table.py DeviceTable.live); otherwise
        the classic compacting filter. Masked INPUT is consumed either
        way (the predicate ANDs with the input's liveness)."""
        from spark_rapids_tpu.ops.expr import has_position_dependent, shared_traces
        if table.live is not None and has_position_dependent(self.condition):
            table = table.compacted()  # slot ids must match prefix form
        pctx = PrepCtx(table)
        preps: List[NodePrep] = []
        _walk_prep(self.condition, pctx, preps)
        cols = tuple(DevVal(c.data, c.validity) for c in table.columns)
        from spark_rapids_tpu.dispatch import ANSI_MODE, prep_aux
        aux = prep_aux(pctx)
        capacity = table.capacity
        has_mask = table.live is not None
        ansi = ANSI_MODE.get()

        from spark_rapids_tpu import kernels
        self._traces = shared_traces(
            ("filter", self.condition.key(), table.schema_key()[0]))
        tkey = (capacity, emit_mask, has_mask, ansi,
                kernels.trace_token(), _prep_trace_key(preps))
        got = self._traces.get(tkey)
        if got is None:
            cond = self.condition
            labels: List[str] = []

            def run(cols, aux, nrows, live_in):
                ctx = EvalCtx(cols, aux, nrows, capacity, live=live_in,
                              ansi=ansi)
                ctx._prep_iter = iter(preps)
                pred = _walk_eval(cond, ctx)
                labels.clear()
                labels.extend(lbl for lbl, _ in ctx.ansi_errors)
                errs = tuple(f for _, f in ctx.ansi_errors)
                if live_in is not None:
                    live = live_in
                else:
                    live = jnp.arange(capacity, dtype=jnp.int32) < nrows
                keep = pred.data & pred.validity & live
                new_n = jnp.sum(keep.astype(jnp.int32))
                if emit_mask:
                    return keep, new_n, errs
                from spark_rapids_tpu.ops.scatter32 import compact_pairs
                outs, new_n = compact_pairs([d for d, _ in cols],
                                            [v for _, v in cols],
                                            keep, capacity)
                return outs, new_n, errs

            got = (tpu_jit(run), labels)
            self._traces[tkey] = got
        fn, labels = got

        from spark_rapids_tpu.ops.expr import deliver_ansi_flags
        if emit_mask:
            keep, new_n, errs = fn(cols, aux, table.nrows_dev, table.live)
            deliver_ansi_flags(labels, errs)
            return DeviceTable(table.names, table.columns, new_n, capacity,
                               live=keep)
        outs, new_n, errs = fn(cols, aux, table.nrows_dev, table.live)
        deliver_ansi_flags(labels, errs)
        new_cols = [c.with_arrays(d, v) for c, (d, v) in zip(table.columns, outs)]
        return DeviceTable(table.names, new_cols, new_n, capacity)


class TpuFilterExec(TpuExec):
    produces_masked = True

    def __init__(self, child: TpuExec, condition: Expression):
        super().__init__()
        self.children = (child,)
        self.condition = condition
        self._kernel = _FilterKernel(condition)

    def output_schema(self):
        return self.children[0].output_schema()

    def execute_masked(self):
        from spark_rapids_tpu.execs.base import MASKED_ENABLED
        from spark_rapids_tpu.runtime.retry import with_retry
        emit = MASKED_ENABLED.get()
        for batch in self.children[0].execute_masked():
            yield from with_retry(
                batch, lambda b: self._kernel(b, emit_mask=emit))

    def describe(self):
        return f"TpuFilter[{self.condition!r}]"


class TpuLimitExec(TpuExec):
    def __init__(self, child: TpuExec, limit: int):
        super().__init__()
        self.children = (child,)
        self.limit = limit

    def output_schema(self):
        return self.children[0].output_schema()

    def execute(self):
        remaining = self.limit
        for batch in self.children[0].execute():
            if remaining <= 0:
                return
            n = batch.num_rows  # host sync at the limit boundary only
            take = min(n, remaining)
            if take == n:
                yield batch
            else:
                yield DeviceTable(batch.names, batch.columns, take, batch.capacity)
            remaining -= take
            if remaining <= 0:
                return

    def describe(self):
        return f"TpuLimit[{self.limit}]"


class TpuUnionExec(TpuExec):
    produces_masked = True

    def __init__(self, children: Sequence[TpuExec]):
        super().__init__()
        self.children = tuple(children)

    def output_schema(self):
        return self.children[0].output_schema()

    def execute_masked(self):
        for c in self.children:
            yield from c.execute_masked()


class TpuExpandExec(TpuExec):
    """Each input batch produces one output batch per projection
    (reference: GpuExpandExec)."""

    def __init__(self, child: TpuExec, projections: Sequence[Sequence[Expression]],
                 names: Sequence[str]):
        super().__init__()
        self.children = (child,)
        self.projections = [list(p) for p in projections]
        self.names = list(names)

    def output_schema(self):
        return [(n, e.data_type) for n, e in zip(self.names, self.projections[0])]

    produces_masked = True

    def execute_masked(self):
        from spark_rapids_tpu.ops.expr import has_position_dependent
        pos_dep = any(has_position_dependent(e)
                      for proj in self.projections for e in proj)
        for batch in self.children[0].execute_masked():
            if pos_dep:
                batch = batch.compacted()
            for proj in self.projections:
                cols = compile_project(proj, batch)
                yield DeviceTable(self.names, cols, batch.nrows_dev,
                                  batch.capacity, live=batch.live)


class TpuCoalesceExec(TpuExec):
    """Concatenate child batches up to a target size — or into ONE batch
    when ``require_single`` (reference: GpuCoalesceBatches with
    TargetSize/RequireSingleBatch goals).

    Multi-batch flushes concat ON DEVICE (columnar/table.concat_device:
    no host round trip; string dictionaries union with O(dict) host
    work; masked inputs fuse their deferred compaction into the concat
    scatter). Two passthroughs: a lone buffered batch, and — under
    TargetSize only — capacity-sharing masked VIEWS from a local shuffle
    split (columnar/table.is_shared_view), which stream un-coalesced
    because concatenating views of one table only multiplies capacity."""

    def __init__(self, child: TpuExec, target_bytes: int = 1 << 30,
                 require_single: bool = False):
        super().__init__()
        self.children = (child,)
        self.target_bytes = target_bytes
        self.require_single = require_single

    def output_schema(self):
        return self.children[0].output_schema()

    produces_masked = True

    def execute_masked(self):
        from spark_rapids_tpu.runtime.memory import MEMORY
        from spark_rapids_tpu.runtime.spill import BufferCatalog, SpillableBatch

        catalog = BufferCatalog.get()
        # spill-aware TargetSize: the flush target never exceeds the
        # device budget's chunk share, so a coalesce below a streaming
        # consumer cannot re-concatenate chunked scans back into one
        # over-budget resident batch (RequireSingleBatch consumers —
        # join builds — still get their single batch; the join then
        # sub-partitions it spillably)
        target = self.target_bytes
        if not self.require_single:
            target = min(target, MEMORY.scan_chunk_bytes())
        pending: List[SpillableBatch] = []
        pending_bytes = 0
        try:
            for batch in self.children[0].execute_masked():
                from spark_rapids_tpu.columnar.table import is_shared_view
                if is_shared_view(batch) and not self.require_single:
                    # capacity-sharing views (a local split's per-partition
                    # masks over ONE table): concatenation would only
                    # multiply capacity and pay the very scatters masking
                    # defers — stream them. Ordinary masked batches
                    # (independent filter outputs) still coalesce.
                    if pending:
                        yield self._flush(pending)
                        pending, pending_bytes = [], 0
                    self.add_metric("maskedPassthrough", 1)
                    yield batch
                    continue
                pending_bytes += batch.device_nbytes()
                # buffered batches are spillable while more input streams in
                # (reference: coalesce inputs are SpillableColumnarBatches)
                pending.append(SpillableBatch(batch, catalog))
                if not self.require_single and pending_bytes >= target:
                    yield self._flush(pending)
                    pending, pending_bytes = [], 0
            if pending:
                yield self._flush(pending)
                pending = []
        finally:
            # abandonment (downstream limit stopped consuming) or an error
            # mid-flush must not leak catalog registrations/spill files
            for b in pending:
                b.release()

    def _flush(self, batches) -> DeviceTable:
        from spark_rapids_tpu.columnar.table import concat_device
        from spark_rapids_tpu.runtime.retry import retry_block
        if len(batches) == 1:
            sb = batches[0]
            out = retry_block(sb.get)
            sb.release()
            return out
        self.add_metric("concatBatches", len(batches))
        try:
            # device-side concat: no host round trip; string dictionaries
            # union-remap with O(dict) host work
            return retry_block(
                lambda: concat_device([b.get() for b in batches]))
        finally:
            for b in batches:
                b.release()

    def describe(self):
        goal = "RequireSingleBatch" if self.require_single else f"TargetSize({self.target_bytes})"
        return f"TpuCoalesce[{goal}]"


class TpuSampleExec(TpuExec):
    """Bernoulli sample (reference: GpuSampleExec). The device kernel uses
    the SAME counter-based RNG stream as the CPU path cannot (numpy
    Philox vs threefry differ), so the mask is drawn ON HOST per batch
    from the plan's seeded generator and shipped as a bitmask — tiny
    (1 byte/row) and bit-identical to the CPU oracle."""

    def __init__(self, child: TpuExec, fraction: float, seed: int):
        super().__init__()
        self.children = (child,)
        self.fraction = float(fraction)
        self.seed = int(seed)

    def output_schema(self):
        return self.children[0].output_schema()

    def describe(self):
        return f"TpuSample[{self.fraction}]"

    def execute(self):
        import numpy as _np
        from spark_rapids_tpu.runtime.retry import with_retry
        rng = _np.random.default_rng(self.seed)

        def make_run(keep_host):
            def run(dt):
                keep = jnp.asarray(keep_host)
                kernel = _compaction_kernel(dt.capacity, dt.schema_key()[0])
                outs, new_n = kernel(
                    tuple(c.data for c in dt.columns),
                    tuple(c.validity for c in dt.columns),
                    keep & dt.row_mask())
                cols = [c.with_arrays(d, v)
                        for c, (d, v) in zip(dt.columns, outs)]
                return DeviceTable(dt.names, cols, new_n, dt.capacity)
            return run

        for batch in self.children[0].execute():
            n = batch.num_rows  # host count drives the CPU-identical draw
            keep_host = np.zeros(batch.capacity, dtype=np.bool_)
            keep_host[:n] = rng.random(n) < self.fraction
            yield from with_retry(batch, make_run(keep_host),
                                  splittable=False)


_COMPACT_KERNELS = {}


def _compaction_kernel(capacity: int, schema_key):
    from spark_rapids_tpu import kernels
    key = (capacity, schema_key, kernels.trace_token())
    fn = _COMPACT_KERNELS.get(key)
    if fn is None:
        def run(datas, valids, keep):
            from spark_rapids_tpu.ops.scatter32 import compact_pairs
            return compact_pairs(datas, valids, keep, capacity)

        fn = tpu_jit(run)
        _COMPACT_KERNELS[key] = fn
    return fn
