"""Write-ahead offset log for streaming checkpoints.

Layout under a checkpoint directory (the structured-streaming analog):

* ``offsets/<batch_id>.json`` — written BEFORE a micro-batch runs; records
  the exact source range the batch will read.
* ``commits/<batch_id>.json`` — written only after the batch's sink commit
  lands.

Exactly-once resume falls out of the two-file protocol: an offsets file
without a matching commit file is a batch that died mid-flight, and the
restarted stream re-runs it over the SAME recorded range (sources read
deterministically from offsets). The sink side dedupes via the Delta
``txn`` watermark (delta/log.SetTransaction), so a batch that died AFTER
the sink commit but before the commit marker replays as a no-op.
"""

from __future__ import annotations

import json
import os
from typing import Optional, Tuple

from spark_rapids_tpu.errors import ColumnarProcessingError

__all__ = ["OffsetLog"]


class OffsetLog:
    """Durable per-stream batch bookkeeping rooted at ``checkpoint_dir``."""

    def __init__(self, checkpoint_dir: str):
        self.checkpoint_dir = os.path.abspath(checkpoint_dir)
        self.offsets_dir = os.path.join(self.checkpoint_dir, "offsets")
        self.commits_dir = os.path.join(self.checkpoint_dir, "commits")
        os.makedirs(self.offsets_dir, exist_ok=True)
        os.makedirs(self.commits_dir, exist_ok=True)

    # -- low level -----------------------------------------------------------
    @staticmethod
    def _ids(d: str):
        out = []
        for f in os.listdir(d):
            if f.endswith(".json"):
                try:
                    out.append(int(f[:-5]))
                except ValueError:
                    continue
        return sorted(out)

    def _write_json(self, d: str, batch_id: int, payload: dict) -> None:
        # tmp + rename so a crash mid-write never leaves a torn entry the
        # resume path would misread as a planned batch
        final = os.path.join(d, f"{batch_id}.json")
        tmp = final + ".tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f, sort_keys=True)
        os.replace(tmp, final)

    def _read_json(self, d: str, batch_id: int) -> dict:
        with open(os.path.join(d, f"{batch_id}.json")) as f:
            return json.load(f)

    # -- offsets -------------------------------------------------------------
    def latest_batch_id(self) -> int:
        """Highest batch id with a planned-offsets entry; -1 if none."""
        ids = self._ids(self.offsets_dir)
        return ids[-1] if ids else -1

    def latest_committed_id(self) -> int:
        ids = self._ids(self.commits_dir)
        return ids[-1] if ids else -1

    def write_offsets(self, batch_id: int, offsets: dict) -> None:
        if batch_id != self.latest_batch_id() + 1:
            raise ColumnarProcessingError(
                f"offset log gap: planning batch {batch_id} but latest "
                f"planned is {self.latest_batch_id()}")
        self._write_json(self.offsets_dir, batch_id, offsets)

    def read_offsets(self, batch_id: int) -> dict:
        return self._read_json(self.offsets_dir, batch_id)

    def write_commit(self, batch_id: int, info: dict) -> None:
        self._write_json(self.commits_dir, batch_id, info)

    def pending_batch(self) -> Optional[Tuple[int, dict]]:
        """The planned-but-uncommitted batch to re-run on resume, if any.
        At most ONE can exist: offsets are written strictly one batch
        ahead of commits."""
        planned, committed = self.latest_batch_id(), self.latest_committed_id()
        if planned > committed:
            return planned, self.read_offsets(planned)
        return None

    def last_end_offset(self):
        """End offset of the newest planned batch (the next batch's start),
        or None if the stream has never planned a batch."""
        planned = self.latest_batch_id()
        if planned < 0:
            return None
        return self.read_offsets(planned).get("end")
