"""TPU hash aggregate (reference: GpuHashAggregateExec / GpuMergeAggregate-
Iterator, GpuAggregateExec.scala — SURVEY.md §2.3).

TPU-first design, two device strategies (neither is a hash table —
pointer-chasing is hostile to the VPU):

FAST PATH (dictionary-code grouping, no sort): when every grouping key is a
dictionary-encoded string or a boolean, the key domain is known on the host
(dict sizes), so each row's group id is a mixed-radix combination of its
codes — ``gid = sum(code_i * stride_i)`` with one extra slot per key for
null. Aggregation is then direct ``segment_*`` reductions with
``num_segments = padded domain product`` (small!), group compaction is a
cumsum scatter, and the live group count stays on device — no sort, no
host sync, no capacity-sized outputs. f64 sums run through the exact-
decomposition blocked f32 path (ops/segsum.py).

SORT-SEGMENT PATH (general keys): lexicographic multi-operand ``lax.sort``
over (live, key-validity, key-data...) with a row-index payload; segment
boundaries -> dense group ids via cumsum; ``jax.ops.segment_*`` reductions.

Input fusion: Project/Filter chains feeding the aggregate are substituted
into the kernel (execs/fuse.py) — predicates become weight masks evaluated
in the same XLA program, so a filter+project+aggregate pipeline is ONE
device dispatch with no intermediate materialization.

Multi-batch inputs STREAM (GpuMergeAggregateIterator analog): one batch in
HBM at a time aggregates to a spillable partial, and a merge aggregation +
finalize projection combines the partials (see _merge_plan)."""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
from spark_rapids_tpu.dispatch import tpu_jit
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar import DeviceColumn, DeviceTable
from spark_rapids_tpu.errors import ColumnarProcessingError
from spark_rapids_tpu.execs.base import TpuExec
from spark_rapids_tpu.ops import aggregates as agg
from spark_rapids_tpu.ops.expr import (
    DevVal,
    EvalCtx,
    Expression,
    NodePrep,
    PrepCtx,
    _prep_trace_key,
    _walk_eval,
    _walk_prep,
)
from spark_rapids_tpu.ops.segsum import batched_segment_sum_f64, segment_sum_f64

DEVICE_SUPPORTED_AGGS = (agg.Sum, agg.Min, agg.Max, agg.Count, agg.Average,
                         agg.First, agg.Last, agg.StddevPop, agg.StddevSamp,
                         agg.VariancePop, agg.VarianceSamp,
                         agg.CollectList, agg.CollectSet, agg.Percentile)

#: aggregates needing the SORT-SEGMENT path (contiguous groups / per-group
#: value order) and a single coalesced input (no streaming merge decomposition)
SORT_ONLY_AGGS = (agg.CollectList, agg.CollectSet, agg.Percentile)


_M32 = 0xFFFFFFFF
_TOP64 = -0x8000000000000000


def _dec_limb_words(sd):
    """Decompose decimal storage into four 32-bit words per row such that
    value = w0 + w1*2^32 + w2*2^64 + w3*2^96 with w0..w2 in [0, 2^32)
    and w3 carrying the sign. Accepts (n, 2) two-limb dec128 columns and
    plain (n,) int64 decimal64 columns (hi = sign extension)."""
    if getattr(sd, "ndim", 1) == 2:
        hi, lo = sd[:, 0], sd[:, 1]
    else:
        lo = sd.astype(jnp.int64)
        hi = lo >> 63  # 0 / -1 sign extension
    return (lo & _M32, (lo >> 32) & _M32, hi & _M32, hi >> 32)


def _dec_wide_sum_segments(sd, sv, gid, nseg):
    """EXACT 128-bit segment sum of unscaled decimal storage: per-word i64
    segment sums (each word < 2^32 and row counts < 2^31, so partials are
    exact), carry-normalized back to (hi, lo) two's-complement limbs.
    Returns (hi, lo, t3) where t3 holds bits >=96 of the TRUE sum for
    overflow detection. Works for decimal64 AND dec128 inputs."""
    words = _dec_limb_words(sd)
    sums = [jax.ops.segment_sum(jnp.where(sv, w, 0), gid,
                                num_segments=nseg) for w in words]
    t0 = sums[0]
    r0, c = t0 & _M32, t0 >> 32
    t1 = sums[1] + c
    r1, c = t1 & _M32, t1 >> 32
    t2 = sums[2] + c
    r2, c = t2 & _M32, t2 >> 32
    t3 = sums[3] + c
    hi = (t3 << 32) | r2
    lo = (r1 << 32) | r0
    return hi, lo, t3


def _dec_wide_to_f64(hi, lo):
    """(hi, lo) i128 -> f64 via sign-magnitude (a direct hi*2^64 + lo
    combine cancels catastrophically for small negatives)."""
    from spark_rapids_tpu.ops.decimal import i128_abs
    ahi, alo, neg = i128_abs(hi, lo.astype(jnp.uint64))
    mag = (ahi.astype(jnp.float64) * float(2.0 ** 64)
           + alo.astype(jnp.float64))
    return jnp.where(neg, -mag, mag)


def _dec_sum_segments(out_type, sd, sv, gid, nseg, has_any):
    """EXACT decimal segment sum (Spark sums decimals exactly; an f64
    ride would round beyond 2^53): 128-bit word sums, overflow -> NULL
    (non-ANSI CheckOverflow semantics)."""
    from spark_rapids_tpu.ops.decimal import i128_abs_fits_pow10
    hi, lo, t3 = _dec_wide_sum_segments(sd, sv, gid, nseg)
    # t3 holds bits >=96 of the TRUE sum (no i64 overflow possible at
    # <2^31 rows), so a t3 outside i32 range means 128-bit overflow
    ovf = (t3 > 0x7FFFFFFF) | (t3 < -0x80000000)
    fits = i128_abs_fits_pow10(hi, lo, out_type.precision)
    valid = has_any & ~ovf & fits
    if out_type.precision > T.DecimalType.MAX_LONG_DIGITS:
        return (jnp.stack([hi, lo], axis=1), valid)
    # result precision fits int64: the low limb IS the two's-complement
    # value when in range
    return (jnp.where(valid, lo, 0), valid)


def _dec128_minmax_segments(is_min, sd, sv, gid, nseg, has_any):
    """Two-limb lexicographic segment min/max: high limbs reduce first
    (signed); rows tying on the winning high limb break on the low limb
    compared as UNSIGNED via a top-bit flip."""
    seg_red = jax.ops.segment_min if is_min else jax.ops.segment_max
    hi, lo = sd[:, 0], sd[:, 1]
    info = jnp.iinfo(jnp.int64)
    ident = info.max if is_min else info.min
    hi_m = seg_red(jnp.where(sv, hi, ident), gid, num_segments=nseg)
    cand = sv & (hi == hi_m[gid])
    lob = lo ^ _TOP64  # unsigned order as signed
    lo_m = seg_red(jnp.where(cand, lob, ident), gid,
                   num_segments=nseg) ^ _TOP64
    data = jnp.stack([jnp.where(has_any, hi_m, 0),
                      jnp.where(has_any, lo_m, 0)], axis=1)
    return (data, has_any)


def _sortable(data, validity):
    """Transform (data, validity) into sort operands grouping nulls
    together: (invalid_first_flag, *native-width key operands). The
    ordering decomposition canonicalizes floats (-0.0 == 0.0, one NaN
    pattern — Spark NormalizeFloatingNumbers groups NaNs together) and
    keeps every compare at <=32 bits (ops/ordering.py)."""
    from spark_rapids_tpu.ops.ordering import comparable_operands, zero_invalid
    return ([(~validity).astype(jnp.int32)]
            + comparable_operands(zero_invalid(data, validity)))


class TpuHashAggregateExec(TpuExec):
    def __init__(self, child: TpuExec, grouping: Sequence[Expression],
                 agg_specs: Sequence[Tuple[str, agg.AggregateFunction]],
                 grouping_names: Sequence[str],
                 filters: Sequence[Expression] = (),
                 use_split: bool = False,
                 max_dict_groups: int = 1 << 16,
                 max_domain_groups: int = 1 << 21):
        super().__init__()
        self.children = (child,)
        self.grouping = list(grouping)
        self.agg_specs = list(agg_specs)
        self.grouping_names = list(grouping_names)
        self.filters = list(filters)
        self.use_split = use_split
        self.max_dict_groups = max_dict_groups
        self.max_domain_groups = max_domain_groups

    def output_schema(self):
        out = [(n, g.data_type) for n, g in zip(self.grouping_names, self.grouping)]
        out += [(n, fn.data_type) for n, fn in self.agg_specs]
        return out

    def execute(self):
        from itertools import chain
        from spark_rapids_tpu.runtime.retry import retry_block
        from spark_rapids_tpu.runtime.spill import BufferCatalog, SpillableBatch

        from spark_rapids_tpu.columnar.table import merge_split_views
        # aggregation is partition-structure-blind: a repartition's
        # same-split views mask-union back into one batch (no data moves)
        it = merge_split_views(self.children[0].execute_masked())
        first = next(it, None)
        if first is None:
            return
        second = next(it, None)
        if second is None:
            # single batch: aggregate directly (spill-and-replay on OOM)
            yield retry_block(lambda: self._aggregate(
                first, self.grouping, self.agg_specs, self.grouping_names,
                self.filters))
            return

        # STREAMING multi-batch path (GpuMergeAggregateIterator analog,
        # GpuAggregateExec.scala:718-950): each input batch aggregates
        # immediately to a per-batch PARTIAL table (bounded HBM — only one
        # input batch is resident at a time), partials are spillable, and
        # one merge aggregation re-groups the concatenated partials with
        # merge semantics (sum-of-sums, min-of-mins, Chan-style moment
        # combination), followed by a finalize projection (avg = s/n, ...).
        plan = self._merge_plan()
        catalog = BufferCatalog.get()
        partials = []
        try:
            for batch in chain([first, second], it):
                pt = retry_block(lambda b=batch: self._aggregate(
                    b, self.grouping, plan.partial_specs,
                    self.grouping_names, self.filters))
                # SHRINK each partial to its live-group bucket before
                # it buffers: a partial carries its input's full
                # capacity for a handful of group rows, and the merge
                # concat below buckets the SUM of partial capacities —
                # unshrunk, a chunked scan's N partials concat into an
                # N-fold over-capacity table, which is exactly the
                # over-budget resident the out-of-core contract
                # forbids. Pays one row-count sync per partial (the
                # merge is a sync point anyway; shrink's docstring
                # case: after cardinality-collapsing ops).
                partials.append(SpillableBatch(pt.shrink(), catalog))
                self.add_metric("partialAggBatches", 1)

            from spark_rapids_tpu.columnar.table import concat_device

            def merge():
                merged = concat_device([p.get() for p in partials])
                return self._aggregate(
                    merged, plan.merge_grouping, plan.merge_specs,
                    self.grouping_names, [])

            mt = retry_block(merge)
        finally:
            for p in partials:
                p.release()

        from spark_rapids_tpu.ops.expr import bind, compile_project
        bound = [bind(e, mt.schema()) for e in plan.final_exprs]
        out_cols = compile_project(bound, mt)
        out_names = self.grouping_names + [n for n, _ in self.agg_specs]
        yield DeviceTable(out_names, out_cols, mt.nrows_dev, mt.capacity)

    # -- streaming merge plan ----------------------------------------------
    def _merge_plan(self):
        """Decompose each aggregate into (partial specs, merge specs, final
        projection) so multi-batch inputs stream:

          Count  -> partial Count          ; merge Sum          ; identity
          Sum    -> partial Sum            ; merge Sum          ; identity
          Min/Max-> partial Min/Max        ; merge Min/Max      ; identity
          First/ -> partial First/Last     ; merge First/Last   ; identity
           Last     (concat preserves batch order, and a group row exists
                     in a partial iff the batch had rows for it)
          Avg    -> partial Sum+Count      ; merge Sum each     ; s / n
          Var*/  -> partial Count+Sum+VarPop; merge N=Σn plus the stable
          Stddev*   Chan combination m2 = Σm2_i + Σn_i(mean_i - mean_tot)²
                    via the internal MergeMoments aggregate (the naive
                    M + Q - S²/N form cancels catastrophically when
                    |mean| >> stddev); finalize m2/denom (+ sqrt)
        """
        from types import SimpleNamespace
        from spark_rapids_tpu.ops.cast import Cast
        from spark_rapids_tpu.ops.expr import BoundReference, Literal, col, lit
        from spark_rapids_tpu.ops.math import Sqrt

        pschema = [(n, g.data_type)
                   for n, g in zip(self.grouping_names, self.grouping)]
        partial_specs: List[Tuple[str, agg.AggregateFunction]] = []
        merge_specs: List[Tuple[str, agg.AggregateFunction]] = []
        final_exprs: List[Expression] = [col(n) for n in self.grouping_names]

        def add_partial(pname, pfn):
            partial_specs.append((pname, pfn))
            pschema.append((pname, pfn.data_type))
            return len(pschema) - 1

        def pref(idx):
            name, dt = pschema[idx]
            return BoundReference(idx, dt, name_hint=name)

        for j, (name, fn) in enumerate(self.agg_specs):
            t = type(fn)
            if isinstance(fn, agg.Count):
                i = add_partial(f"__p{j}c", agg.Count(fn.child))
                merge_specs.append((name, agg.Sum(pref(i))))
                final_exprs.append(col(name))
            elif isinstance(fn, agg.Sum):
                if isinstance(fn.data_type, T.DecimalType):
                    # a PARTIAL whose rows overflowed emits NULL; a plain
                    # sum-of-partials would silently skip it (dropping
                    # that batch's rows from a non-null final). Track it:
                    # rows present + null partial sum == overflow, which
                    # must null the FINAL (Spark non-ANSI CheckOverflow)
                    from spark_rapids_tpu.ops.conditional import If
                    from spark_rapids_tpu.ops.predicates import IsNull
                    si = add_partial(f"__p{j}s", agg.Sum(fn.child))
                    ci = add_partial(f"__p{j}n", agg.Count(fn.child))
                    merge_specs.append((f"__m{j}s", agg.Sum(pref(si))))
                    merge_specs.append((f"__m{j}o", agg.Sum(
                        If(IsNull(pref(si)) & (pref(ci) > lit(0)),
                           lit(1), lit(0)))))
                    final_exprs.append(
                        If(col(f"__m{j}o") > lit(0),
                           Literal(None, fn.data_type),
                           col(f"__m{j}s")).alias(name))
                else:
                    i = add_partial(f"__p{j}s", agg.Sum(fn.child))
                    merge_specs.append((name, agg.Sum(pref(i))))
                    final_exprs.append(col(name))
            elif isinstance(fn, (agg.Min, agg.Max)):
                i = add_partial(f"__p{j}m", t(fn.child))
                merge_specs.append((name, t(pref(i))))
                final_exprs.append(col(name))
            elif isinstance(fn, (agg.First, agg.Last)):
                i = add_partial(f"__p{j}f", t(fn.child, fn.ignore_nulls))
                merge_specs.append((name, t(pref(i), fn.ignore_nulls)))
                final_exprs.append(col(name))
            elif isinstance(fn, agg.Average):
                si = add_partial(f"__p{j}s", agg.Sum(fn.child))
                ci = add_partial(f"__p{j}n", agg.Count(fn.child))
                merge_specs.append((f"__m{j}s", agg.Sum(pref(si))))
                merge_specs.append((f"__m{j}n", agg.Sum(pref(ci))))
                final_exprs.append(
                    (col(f"__m{j}s").cast(T.DOUBLE)
                     / col(f"__m{j}n").cast(T.DOUBLE)).alias(name))
            elif isinstance(fn, (agg.StddevPop, agg.StddevSamp,
                                 agg.VariancePop, agg.VarianceSamp)):
                ni = add_partial(f"__p{j}n", agg.Count(fn.child))
                si = add_partial(f"__p{j}s",
                                 agg.Sum(Cast(fn.child, T.DOUBLE)))
                vi = add_partial(f"__p{j}v", agg.VariancePop(fn.child))
                n_d = Cast(pref(ni), T.DOUBLE)
                merge_specs.append((f"__m{j}n", agg.Sum(pref(ni))))
                merge_specs.append((f"__m{j}m", agg.MergeMoments(
                    pref(ni), pref(si), pref(vi) * n_d)))
                N = col(f"__m{j}n").cast(T.DOUBLE)
                m2 = col(f"__m{j}m")
                if isinstance(fn, (agg.StddevPop, agg.VariancePop)):
                    var = m2 / N
                else:
                    var = m2 / (N - lit(1.0))
                out = Sqrt(var) if isinstance(
                    fn, (agg.StddevPop, agg.StddevSamp)) else var
                final_exprs.append(out.alias(name))
            else:
                raise ColumnarProcessingError(
                    f"no merge decomposition for {t.__name__}")

        merge_grouping = [
            BoundReference(i, g.data_type, name_hint=n)
            for i, (g, n) in enumerate(zip(self.grouping, self.grouping_names))]
        return SimpleNamespace(partial_specs=partial_specs,
                               merge_specs=merge_specs,
                               merge_grouping=merge_grouping,
                               final_exprs=final_exprs)

    # -- core ---------------------------------------------------------------
    def _prep_all(self, table: DeviceTable, grouping, agg_specs, filters):
        pctx = PrepCtx(table)
        filter_preps: List[List[NodePrep]] = []
        for f in filters:
            preps: List[NodePrep] = []
            _walk_prep(f, pctx, preps)
            filter_preps.append(preps)
        key_preps: List[List[NodePrep]] = []
        for g in grouping:
            preps = []
            _walk_prep(g, pctx, preps)
            key_preps.append(preps)
        # per spec: one prep list PER CHILD expression (Count() has none,
        # most aggs have one, MergeMoments has three)
        val_preps: List[List[List[NodePrep]]] = []
        for _, fn in agg_specs:
            per_child = []
            for c in fn.children:
                preps = []
                _walk_prep(c, pctx, preps)
                per_child.append(preps)
            val_preps.append(per_child)
        return pctx, filter_preps, key_preps, val_preps

    def _fast_layout(self, grouping, key_preps, capacity) -> Optional[tuple]:
        """No-sort layout if every key has a small known domain:
        (kinds, sizes, strides, padded_num_segments, bases).

        Three key kinds aggregate by direct segment reduction (no sort):
        dictionary-encoded strings, booleans, and — via upload-time column
        statistics (DeviceColumn.domain) — integer-family keys whose value
        domain is bounded. gid = sum_i (code_i * stride_i) where an int
        key's code is ``value - base_i`` (bases ride as device operands so
        one trace serves any same-shaped domain)."""
        if self.max_dict_groups <= 0:
            return None
        if any(isinstance(fn, SORT_ONLY_AGGS) for _, fn in self.agg_specs):
            return None  # collect/percentile need contiguous sorted groups
        if not grouping:
            # ungrouped aggregate: ONE segment (padded to 8) — the batched
            # one-hot pass beats _agg_one's capacity-segment scatter by ~8x
            # wall on a 1M-row q2-style global sum
            return (), (), (), 8, ()
        kinds: List[str] = []
        sizes: List[int] = []
        bases: List[int] = []
        has_int = False
        for g, preps in zip(grouping, key_preps):
            dt = g.data_type
            root = preps[-1]
            if isinstance(dt, T.StringType) and root.out_dict is not None:
                kinds.append("str")
                sizes.append(len(root.out_dict) + 1)  # +1: null slot
                bases.append(0)
            elif isinstance(dt, T.BooleanType):
                kinds.append("bool")
                sizes.append(3)  # False, True, null
                bases.append(0)
            elif (isinstance(dt, (T.ByteType, T.ShortType, T.IntegerType,
                                  T.LongType, T.DateType, T.TimestampType))
                  and root.out_domain is not None
                  and self.max_domain_groups > 0):
                lo, hi = root.out_domain
                kinds.append("int")
                sizes.append(hi - lo + 2)  # values + null slot
                bases.append(lo)
                has_int = True
            else:
                return None
        total = 1
        for s in sizes:
            total *= max(s, 1)
        cap = self.max_dict_groups
        if has_int:
            # int domains are data-dependent, not cardinality-bounded like
            # a string dictionary: allow larger segment counts (scatter
            # segment ops are O(n + gpad)) but never a domain so sparse it
            # dwarfs the batch itself
            cap = max(cap, min(self.max_domain_groups, 16 * capacity))
        if total > cap:
            return None
        strides = [1] * len(sizes)
        for i in range(len(sizes) - 2, -1, -1):
            strides[i] = strides[i + 1] * sizes[i + 1]
        # tight power-of-two segment count (NOT the 128-row table bucket):
        # one-hot einsum traffic scales with it, and a q1-style 12-slot
        # domain must pad to 16, not 128
        gpad = max(8, 1 << (max(total - 1, 1)).bit_length())
        return tuple(kinds), sizes, strides, gpad, bases

    def _aggregate(self, table: DeviceTable, grouping, agg_specs,
                   grouping_names, filters) -> DeviceTable:
        if table.live is not None:
            from spark_rapids_tpu.ops.expr import has_position_dependent
            exprs = (list(grouping) + list(filters)
                     + [c for _, fn in agg_specs for c in fn.children])
            if any(has_position_dependent(e) for e in exprs):
                table = table.compacted()  # slot ids must match prefix form
        pctx, filter_preps, key_preps, val_preps = self._prep_all(
            table, grouping, agg_specs, filters)
        from spark_rapids_tpu.dispatch import device_const, prep_aux
        cols = tuple(DevVal(c.data, c.validity) for c in table.columns)
        aux = prep_aux(pctx)
        capacity = table.capacity

        fast = self._fast_layout(grouping, key_preps, capacity)

        from spark_rapids_tpu.ops.expr import shared_traces
        self._traces = shared_traces(
            ("agg",
             tuple(g.key() for g in grouping),
             tuple(fn.key() for _, fn in agg_specs),
             tuple(f.key() for f in filters),
             table.schema_key()[0]))
        from spark_rapids_tpu import kernels
        from spark_rapids_tpu.ops import segsum as _ss
        mode_key = ("fast", fast[0], fast[3]) if fast else ("sorted",)
        has_mask = table.live is not None
        tkey = (capacity, self.use_split, _ss.trace_key(),
                kernels.trace_token(), mode_key, has_mask,
                tuple(_prep_trace_key(p) for p in filter_preps),
                tuple(_prep_trace_key(p) for p in key_preps),
                tuple(tuple(_prep_trace_key(p) for p in per_child)
                      for per_child in val_preps))
        fn = self._traces.get(tkey)
        if fn is None:
            if fast:
                fn = tpu_jit(self._build_fast_kernel(
                    capacity, fast[0], fast[3], filter_preps, key_preps,
                    val_preps, grouping, agg_specs, filters))
            else:
                fn = tpu_jit(self._build_kernel(
                    capacity, filter_preps, key_preps, val_preps,
                    grouping, agg_specs, filters))
            self._traces[tkey] = fn

        if fast:
            _, sizes, strides, gpad, bases = fast
            out_arrays, ngroups = fn(
                cols, aux, table.nrows_dev,
                device_const(np.asarray(sizes, dtype=np.int32)),
                device_const(np.asarray(strides, dtype=np.int32)),
                device_const(np.asarray(bases, dtype=np.int64)),
                table.live)
            out_capacity = gpad
        else:
            out_arrays, ngroups = fn(cols, aux, table.nrows_dev, table.live)
            out_capacity = capacity

        out_cols: List[DeviceColumn] = []
        names: List[str] = []
        for i, (g, name) in enumerate(zip(grouping, grouping_names)):
            data, validity = out_arrays[i]
            root = key_preps[i][-1]
            out_cols.append(DeviceColumn(g.data_type, data, validity,
                                         dictionary=root.out_dict,
                                         dict_sorted=root.dict_sorted,
                                         domain=root.out_domain))
            names.append(name)
        for j, (name, fnagg) in enumerate(agg_specs):
            data, validity = out_arrays[len(grouping) + j]
            dictionary = None
            dict_sorted = True
            if isinstance(fnagg.data_type, T.StringType) and val_preps[j]:
                dictionary = val_preps[j][-1][-1].out_dict
                dict_sorted = val_preps[j][-1][-1].dict_sorted
            out_cols.append(DeviceColumn(fnagg.data_type, data, validity,
                                         dictionary=dictionary, dict_sorted=dict_sorted))
            names.append(name)
        out = DeviceTable(names, out_cols, ngroups, out_capacity)
        if fast:
            # outputs are already domain-sized; the group count stays a
            # device scalar (no host sync on the hot path)
            return out
        from spark_rapids_tpu.columnar import bucket_for
        from spark_rapids_tpu.runtime import speculation as spec
        if out_capacity <= DeviceTable.EMBED_NROWS_CAP:
            # small outputs embed their row count in the collect fetch and
            # cost downstream ops little — under async mode shrinking
            # would only add a sync
            return out if spec.current() is not None else out.shrink()
        site = self._spec_site_key() + ":shrink"
        ctx = spec.allowed(site)
        if ctx is None:
            if spec.current() is not None:
                # blocklisted site under async mode: keep the padded
                # capacity rather than paying the sync mid-plan
                return out
            return out.shrink()
        # SPECULATIVE shrink (ADVICE r3): large sorted-path outputs used to
        # keep the INPUT capacity (inflating every downstream kernel) to
        # avoid shrink()'s ~0.1s row-count sync. Speculate that the group
        # count fits a quarter-capacity bucket; the flag rides the collect
        # fetch and a miss replays this site on the exact path.
        spec_cap = max(bucket_for(max(out_capacity // 4, 1)),
                       DeviceTable.EMBED_NROWS_CAP)
        if spec_cap >= out_capacity:
            return out
        flag_key = ("shrinkflag", out_capacity, spec_cap)
        flag_fn = self._traces.get(flag_key)
        if flag_fn is None:
            flag_fn = tpu_jit(
                lambda n: n > jnp.asarray(spec_cap, jnp.int32))
            self._traces[flag_key] = flag_fn
        ctx.add_flag(site, flag_fn(out.nrows_dev))
        cols = [c.sliced_rows(spec_cap) for c in out.columns]
        return DeviceTable(names, cols, out.nrows_dev, spec_cap)

    def _spec_site_key(self) -> str:
        return "agg:{}:{}:op{}".format(
            tuple(g.key() for g in self.grouping),
            tuple(fn.key() for _, fn in self.agg_specs),
            getattr(self, "_lore_id", 0))

    def _eval_live(self, filters, capacity, cols, aux, nrows, filter_preps,
                   live_in=None):
        """Row-liveness mask: in-bounds (or the input's deferred-compaction
        mask) AND every fused predicate true."""
        if live_in is not None:
            live = live_in
        else:
            live = jnp.arange(capacity, dtype=jnp.int32) < nrows
        for f, preps in zip(filters, filter_preps):
            ctx = EvalCtx(cols, aux, nrows, capacity, live=live_in)
            ctx._prep_iter = iter(preps)
            pred = _walk_eval(f, ctx)
            live = live & pred.data & pred.validity
        return live

    # -- fast path: dictionary-code grouping, no sort -----------------------
    def _build_fast_kernel(self, capacity: int, kinds, gpad: int,
                           filter_preps, key_preps, val_preps,
                           grouping, agg_specs, filters):
        value_exprs = [list(fn.children) for _, fn in agg_specs]
        use_split = self.use_split

        def kernel(cols, aux, nrows, sizes, strides, bases, live_in):
            live = self._eval_live(filters, capacity, cols, aux, nrows,
                                   filter_preps, live_in)

            gid = jnp.zeros(capacity, dtype=jnp.int32)
            for i, (g, preps, kind) in enumerate(zip(grouping, key_preps, kinds)):
                ctx = EvalCtx(cols, aux, nrows, capacity, live=live_in)
                ctx._prep_iter = iter(preps)
                kv = _walk_eval(g, ctx)
                if kind == "int":
                    # domain-coded integer key: value - base. The where
                    # runs BEFORE the int32 narrowing — invalid/padding
                    # slots hold arbitrary data, valid ones are inside the
                    # stats bound by the domain superset contract.
                    delta = kv.data.astype(jnp.int64) - bases[i]
                    code = jnp.where(kv.validity, delta,
                                     (sizes[i] - 1).astype(jnp.int64))
                    code = code.astype(jnp.int32)
                else:
                    code = (kv.data.astype(jnp.int32)
                            if kind == "bool" else kv.data)
                    code = jnp.where(kv.validity, code, sizes[i] - 1)
                gid = gid + code * strides[i]

            # ---- batched value aggregation ------------------------------
            # All sum-class f64 reductions (Sum/Average/Stddev/Variance)
            # ride ONE batched device pass (ops/segsum.py); validity counts
            # for every spec plus group existence ride one 2-D i32
            # segment_sum. Min/Max/First/Last and i64 sums stay per-spec
            # (_agg_one).
            vvs = []
            for ves, per_child in zip(value_exprs, val_preps):
                vals = []
                for ve, preps in zip(ves, per_child):
                    ctx = EvalCtx(cols, aux, nrows, capacity, live=live_in)
                    ctx._prep_iter = iter(preps)
                    vals.append(_walk_eval(ve, ctx))
                vvs.append(vals)
            svs = [(vv[0].validity & live) if vv else None for vv in vvs]

            # one scatter for live-count + every spec's nonnull count
            masks = [live] + [sv for sv in svs if sv is not None]
            mix = {}
            k = 1
            for j, sv in enumerate(svs):
                if sv is not None:
                    mix[j] = k
                    k += 1
            if gpad <= 4096:
                # one 2-D scatter: reads the input once; the minor-dim
                # 128-lane padding on the OUTPUT is cheap at small gpad
                mcnt = jax.ops.segment_sum(
                    jnp.stack(masks, axis=1).astype(jnp.int32), gid,
                    num_segments=gpad)
            else:
                # large gpad: the padded (gpad, 128-lane) output dwarfs
                # the input re-reads — per-mask 1-D scatters win
                mcnt = jnp.stack(
                    [jax.ops.segment_sum(mk.astype(jnp.int32), gid,
                                         num_segments=gpad)
                     for mk in masks], axis=1)
            nonnulls = {j: mcnt[:, i] for j, i in mix.items()}

            exists = mcnt[:, 0] > 0
            if not grouping:
                # global aggregate: exactly one output row even when the
                # input is empty (count=0, sums NULL — Spark semantics)
                exists = jnp.arange(gpad, dtype=jnp.int32) == 0
            ngroups = jnp.sum(exists.astype(jnp.int32))

            # every output column compacts slot -> dense rank through ONE
            # shared call (the Pallas compact kernel fuses the whole
            # column set into one gather pass when enabled)
            pairs = []
            slot_ix = jnp.arange(gpad, dtype=jnp.int32)
            for i, kind in enumerate(kinds):
                slot = (slot_ix // strides[i]) % sizes[i]
                kvalid = slot != (sizes[i] - 1)
                if kind == "bool":
                    kdata = slot == 1
                elif kind == "int":
                    kdata = (slot.astype(jnp.int64) + bases[i]).astype(
                        grouping[i].data_type.np_dtype)
                else:
                    kdata = slot
                pairs.append((kdata, kvalid))

            fplan = []  # (spec index, kind) riding a batched f64 pass
            for j, (_, fnagg) in enumerate(agg_specs):
                if isinstance(fnagg, (agg.StddevPop, agg.StddevSamp,
                                      agg.VariancePop, agg.VarianceSamp)):
                    fplan.append((j, "var"))
                elif isinstance(fnagg, agg.Average):
                    # decimal averages sum EXACTLY in i64 unscaled space
                    # (_agg_one; Spark computes avg(decimal) from an exact
                    # decimal sum — the split guard's 1e-6 tolerance is not
                    # decimal semantics), so they skip the f64 ride
                    if not isinstance(fnagg.child.data_type, T.DecimalType):
                        fplan.append((j, "avg"))
                elif isinstance(fnagg, agg.Sum) and not isinstance(
                        fnagg.data_type, (T.LongType, T.DecimalType)):
                    # decimal sums are EXACT limb sums (_agg_one), never
                    # the f64 ride
                    fplan.append((j, "sum"))
            # sum/avg ride the split pass; variance means must be EXACT —
            # a mean error d inflates the centered pass by n*d^2 (quadratic
            # amplification the split guard cannot bound)
            splan = [(j, kind) for j, kind in fplan if kind != "var"]
            vplan_j = [j for j, kind in fplan if kind == "var"]
            fcols = [jnp.where(svs[j], vvs[j][0].data.astype(jnp.float64), 0.0)
                     for j, _ in splan]
            # nonnull counts are already scattered (mcnt) — the split
            # guard reuses them instead of scattering its own
            scnt = (jnp.stack([nonnulls[j] for j, _ in splan], axis=1)
                    if splan else None)
            fsums_s = batched_segment_sum_f64(fcols, gid, gpad, capacity,
                                              use_split, counts=scnt)
            def _vdata(j):
                # decimal variance inputs are UNSCALED ints; moments are
                # VALUE-unit doubles (same scaling contract as cpu_agg)
                d = vvs[j][0].data.astype(jnp.float64)
                cdt = agg_specs[j][1].child.data_type
                if isinstance(cdt, T.DecimalType):
                    d = d / jnp.float64(10 ** cdt.scale)
                return d

            vcols = [jnp.where(svs[j], _vdata(j), 0.0) for j in vplan_j]
            fsums_v = batched_segment_sum_f64(vcols, gid, gpad, capacity,
                                              use_split=False)
            fsums = {}
            for i, (j, _) in enumerate(splan):
                fsums[j] = fsums_s[:, i]
            for i, j in enumerate(vplan_j):
                fsums[j] = fsums_v[:, i]

            # second batched pass: centered moments (positive values, so the
            # split path's relative-error guard applies cleanly)
            ccols = []
            for j in vplan_j:
                mean = fsums[j] / jnp.maximum(nonnulls[j], 1)
                ccols.append(jnp.where(
                    svs[j], (_vdata(j) - mean[gid]) ** 2, 0.0))
            csums = batched_segment_sum_f64(ccols, gid, gpad, capacity,
                                            use_split)
            m2s = {j: csums[:, i2] for i2, j in enumerate(vplan_j)}

            fres = {}
            for j, kind in fplan:
                fnagg = agg_specs[j][1]
                nonnull = nonnulls[j]
                has_any = (nonnull > 0) & exists
                s = fsums[j]
                if kind == "sum":
                    fres[j] = (jnp.where(has_any, s, 0.0), has_any)
                elif kind == "avg":
                    fres[j] = (jnp.where(has_any, s / jnp.maximum(nonnull, 1), 0.0),
                               has_any)
                else:
                    if isinstance(fnagg, (agg.StddevPop, agg.VariancePop)):
                        denom = jnp.maximum(nonnull, 1)
                        validity = has_any
                    else:
                        denom = jnp.maximum(nonnull - 1, 1)
                        validity = (nonnull > 1) & exists
                    var = m2s[j] / denom
                    out = jnp.sqrt(var) if isinstance(
                        fnagg, (agg.StddevPop, agg.StddevSamp)) else var
                    fres[j] = (jnp.where(validity, out, 0.0), validity)

            for j, (_, fnagg) in enumerate(agg_specs):
                if j in fres:
                    data, validity = fres[j]
                elif isinstance(fnagg, agg.Count):
                    w = mcnt[:, 0] if fnagg.child is None else nonnulls[j]
                    data, validity = w.astype(jnp.int64), exists
                elif isinstance(fnagg, agg.MergeMoments):
                    data, validity = self._merge_moments(
                        vvs[j], live, gid, gpad, exists)
                else:
                    sd = vvs[j][0].data if vvs[j] else None
                    data, validity = self._agg_one(
                        fnagg, sd, svs[j], live, gid, gpad, exists,
                        capacity, use_split)
                pairs.append((data, validity))
            from spark_rapids_tpu.ops.scatter32 import compact_pairs
            outs, _ = compact_pairs([d for d, _ in pairs],
                                    [v for _, v in pairs], exists, gpad)
            return list(outs), ngroups

        return kernel

    # -- general path: sort-segment -----------------------------------------
    def _build_kernel(self, capacity: int, filter_preps, key_preps, val_preps,
                      grouping, agg_specs, filters):
        value_exprs = [list(fn.children) for _, fn in agg_specs]
        use_split = self.use_split

        def kernel(cols, aux, nrows, live_in):
            live = self._eval_live(filters, capacity, cols, aux, nrows,
                                   filter_preps, live_in)

            key_vals: List[DevVal] = []
            for g, preps in zip(grouping, key_preps):
                ctx = EvalCtx(cols, aux, nrows, capacity, live=live_in)
                ctx._prep_iter = iter(preps)
                key_vals.append(_walk_eval(g, ctx))
            val_vals = []
            for ves, per_child in zip(value_exprs, val_preps):
                vals = []
                for ve, preps in zip(ves, per_child):
                    ctx = EvalCtx(cols, aux, nrows, capacity, live=live_in)
                    ctx._prep_iter = iter(preps)
                    vals.append(_walk_eval(ve, ctx))
                val_vals.append(vals)

            # normalize float keys so grouping matches the CPU oracle
            norm = []
            for kv in key_vals:
                d = kv.data
                if jnp.issubdtype(d.dtype, jnp.floating):
                    d = jnp.where(d == 0.0, jnp.zeros_like(d), d)
                norm.append(DevVal(d, kv.validity))
            key_vals = norm

            if grouping:
                operands = [(~live).astype(jnp.int32)]  # dead rows last
                for kv in key_vals:
                    operands.extend(_sortable(kv.data, kv.validity))
                from spark_rapids_tpu.ops.ordering import lex_sort
                payload = jnp.arange(capacity, dtype=jnp.int32)
                sorted_all = lex_sort(operands, payload)
                perm = sorted_all[-1]
                s_live = live[perm]
                s_keys = [DevVal(kv.data[perm], kv.validity[perm])
                          for kv in key_vals]
                s_vals = [[DevVal(x.data[perm], x.validity[perm])
                           for x in vv] for vv in val_vals]

                # group boundaries on the CANONICAL operands (raw float
                # compares would split NaN groups: NaN != NaN); the sort
                # already emitted every operand in sorted order — compare
                # those directly instead of re-gathering by perm
                first = jnp.arange(capacity) == 0
                changed = jnp.zeros(capacity, dtype=jnp.bool_)
                for so in sorted_all[1:-1]:
                    changed = changed | (so != jnp.roll(so, 1))
                new_group = (first | changed) & s_live
                gid = jnp.cumsum(new_group.astype(jnp.int32)) - 1
                gid = jnp.where(s_live, gid, capacity - 1)  # park dead rows
                ngroups = jnp.sum(new_group.astype(jnp.int32))
            else:
                s_live = live
                s_keys = []
                s_vals = val_vals
                gid = jnp.zeros(capacity, dtype=jnp.int32)
                ngroups = jnp.asarray(1, dtype=jnp.int32)

            group_live = jnp.arange(capacity, dtype=jnp.int32) < ngroups

            outs = []
            # key columns: scatter first-occurrence values to gid slots
            from spark_rapids_tpu.ops.scatter32 import scatter_pair
            for kv in s_keys:
                tgt = jnp.where(s_live, gid, capacity)
                kd, kvv = scatter_pair(capacity, tgt, kv.data, kv.validity)
                outs.append((kd, kvv & group_live))

            for (name, fnagg), vv in zip(agg_specs, s_vals):
                if isinstance(fnagg, agg.MergeMoments):
                    outs.append(self._merge_moments(vv, s_live, gid,
                                                    capacity, group_live))
                    continue
                sd = vv[0].data if vv else None
                sv = (vv[0].validity & s_live) if vv else None
                outs.append(self._agg_one(fnagg, sd, sv, s_live, gid, capacity,
                                          group_live, capacity, use_split))
            return outs, ngroups

        return kernel

    @staticmethod
    def _merge_moments(vv3, live, gid, nseg, group_live):
        """Numerically stable merge of per-batch moment partials
        (n_i, s_i, m2_i) -> total m2, via Chan's combination
        m2 = sum(m2_i) + sum(n_i * (mean_i - mean_total)^2). All sums run
        exact emulated f64 — the merge table is partials-sized, tiny."""
        nvv, svv, mvv = vv3
        sv = nvv.validity & svv.validity & mvv.validity & live
        n = jnp.where(sv, nvv.data.astype(jnp.float64), 0.0)
        s = jnp.where(sv, svv.data.astype(jnp.float64), 0.0)
        m2 = jnp.where(sv, mvv.data.astype(jnp.float64), 0.0)
        N = jax.ops.segment_sum(n, gid, num_segments=nseg)
        S = jax.ops.segment_sum(s, gid, num_segments=nseg)
        mean_tot = S / jnp.maximum(N, 1.0)
        mean_i = s / jnp.maximum(n, 1.0)
        c = jnp.where(sv, m2 + n * (mean_i - mean_tot[gid]) ** 2, 0.0)
        M2 = jax.ops.segment_sum(c, gid, num_segments=nseg)
        has = (jax.ops.segment_sum(sv.astype(jnp.int32), gid,
                                   num_segments=nseg) > 0) & group_live
        return (jnp.where(has, M2, 0.0), has)

    @staticmethod
    def _agg_one(fnagg, sd, sv, live, gid, nseg, group_live, capacity, use_split):
        """One aggregate over segment ids. ``sd``/``sv``: value data and
        validity aligned with ``gid`` (``sv`` already excludes dead rows);
        ``live``: row liveness (COUNT(*)); ``nseg``: number of segments;
        ``group_live``: which segment slots are real groups."""
        seg = jax.ops
        if isinstance(fnagg, agg.Count):
            w = live if fnagg.child is None else sv
            # capacity < 2^31 always (power-of-two row buckets), so count
            # accumulates natively in i32 and widens to Spark's LONG after
            cnt = seg.segment_sum(w.astype(jnp.int32), gid,
                                  num_segments=nseg).astype(jnp.int64)
            return (cnt, group_live)

        nonnull = seg.segment_sum(sv.astype(jnp.int32), gid, num_segments=nseg)
        has_any = (nonnull > 0) & group_live

        if isinstance(fnagg, agg.Sum):
            if isinstance(fnagg.data_type, T.LongType):
                v = jnp.where(sv, sd.astype(jnp.int64), 0)
                s = seg.segment_sum(v, gid, num_segments=nseg)
                return (s, has_any)
            if isinstance(fnagg.data_type, T.DecimalType):
                return _dec_sum_segments(fnagg.data_type, sd, sv, gid,
                                         nseg, has_any)
            v = jnp.where(sv, sd.astype(jnp.float64), 0.0)
            s = segment_sum_f64(v, gid, nseg, capacity, use_split,
                                counts=nonnull)
            return (jnp.where(has_any, s, 0.0), has_any)

        if isinstance(fnagg, agg.Average):
            if isinstance(fnagg.child.data_type, T.DecimalType):
                # EXACT 128-bit unscaled sum (Spark computes avg(decimal)
                # from an exact decimal sum; riding the f64 split pass
                # would accumulate error per row), ONE sign-magnitude
                # rounding at the final f64 convert + divide. A 128-bit
                # overflow (t3 outside i32) nulls the result, mirroring
                # the Sum path's non-ANSI CheckOverflow semantics.
                hi128, lo128, t3 = _dec_wide_sum_segments(sd, sv, gid, nseg)
                ovf = (t3 > 0x7FFFFFFF) | (t3 < -0x80000000)
                tot = _dec_wide_to_f64(hi128, lo128)
                valid = has_any & ~ovf
                # unscaled exact sum -> VALUE-unit double result (one
                # rounding), matching Cast(decimal->double) semantics
                dscale = jnp.float64(10 ** fnagg.child.data_type.scale)
                return (jnp.where(
                    valid, tot / (jnp.maximum(nonnull, 1) * dscale),
                    0.0), valid)
            v = jnp.where(sv, sd.astype(jnp.float64), 0.0)
            s = segment_sum_f64(v, gid, nseg, capacity, use_split)
            return (jnp.where(has_any, s / jnp.maximum(nonnull, 1), 0.0), has_any)

        if isinstance(fnagg, (agg.StddevPop, agg.StddevSamp, agg.VariancePop, agg.VarianceSamp)):
            sdf = sd.astype(jnp.float64)
            cdt = fnagg.child.data_type
            if isinstance(cdt, T.DecimalType):
                # unscaled decimal ints -> VALUE-unit moments (same
                # scaling contract as cpu_agg / the batched f64 ride)
                sdf = sdf / jnp.float64(10 ** cdt.scale)
            v = jnp.where(sv, sdf, 0.0)
            # EXACT mean: a split-sum mean error d would inflate the
            # centered pass by n*d^2 (quadratic amplification)
            s = segment_sum_f64(v, gid, nseg, capacity, use_split=False)
            mean = s / jnp.maximum(nonnull, 1)
            centered = jnp.where(sv, (sdf - mean[gid]) ** 2, 0.0)
            m2 = segment_sum_f64(centered, gid, nseg, capacity, use_split)
            if isinstance(fnagg, (agg.StddevPop, agg.VariancePop)):
                denom = jnp.maximum(nonnull, 1)
                validity = has_any
            else:
                denom = jnp.maximum(nonnull - 1, 1)
                validity = (nonnull > 1) & group_live
            var = m2 / denom
            out = jnp.sqrt(var) if isinstance(fnagg, (agg.StddevPop, agg.StddevSamp)) else var
            return (jnp.where(validity, out, 0.0), validity)

        if isinstance(fnagg, (agg.Min, agg.Max)) \
                and getattr(sd, "ndim", 1) == 2:
            return _dec128_minmax_segments(
                isinstance(fnagg, agg.Min), sd, sv, gid, nseg, has_any)

        if isinstance(fnagg, (agg.Min, agg.Max)) \
                and use_split and sd.dtype in (jnp.float64, jnp.int64):
            # native-32-bit two-pass limb reduction (ops/segsum.py) — the
            # emulated-64 scatter compare-select it replaces dominates
            # whole queries at large segment counts
            from spark_rapids_tpu.ops.segsum import segment_minmax_64
            r = segment_minmax_64(isinstance(fnagg, agg.Min), sd, sv, gid, nseg)
            return (jnp.where(has_any, r, jnp.zeros_like(r)), has_any)

        if isinstance(fnagg, (agg.Min, agg.Max)):
            dt = sd.dtype
            if jnp.issubdtype(dt, jnp.floating):
                ident = jnp.asarray(jnp.inf if isinstance(fnagg, agg.Min) else -jnp.inf, dtype=dt)
            elif dt == jnp.bool_:
                sd = sd.astype(jnp.int32)
                dt = jnp.int32
                ident = jnp.asarray(1 if isinstance(fnagg, agg.Min) else 0, dtype=dt)
            else:
                info = jnp.iinfo(dt)
                ident = jnp.asarray(info.max if isinstance(fnagg, agg.Min) else info.min, dtype=dt)
            v = jnp.where(sv, sd, ident)
            if isinstance(fnagg, agg.Min):
                r = seg.segment_min(v, gid, num_segments=nseg)
            else:
                r = seg.segment_max(v, gid, num_segments=nseg)
            if isinstance(fnagg.data_type, T.BooleanType):
                r = r.astype(jnp.bool_)
            zero = jnp.zeros_like(r)
            return (jnp.where(has_any, r, zero), has_any)

        if isinstance(fnagg, (agg.CollectList, agg.CollectSet)):
            from spark_rapids_tpu.ops.ordering import comparable_operands
            keep = sv
            sdv = sd
            gidv = gid
            if isinstance(fnagg, agg.CollectSet):
                from spark_rapids_tpu.ops.ordering import lex_sort
                # distinct: re-sort by (gid, value) and keep group-local
                # first occurrences
                ops = comparable_operands(
                    jnp.where(sv, sd, jnp.zeros_like(sd)))
                res = lex_sort(
                    [gid, (~sv).astype(jnp.int32)] + ops,
                    jnp.arange(capacity, dtype=jnp.int32))
                gidv = res[0]
                sflag = res[1] == 0
                perm2 = res[-1]
                sdv = sd[perm2]
                same = gidv == jnp.roll(gidv, 1)
                for o in res[2:-1]:
                    same = same & (o == jnp.roll(o, 1))
                first = jnp.arange(capacity) == 0
                keep = sflag & (first | ~same)
            from spark_rapids_tpu.ops.scatter32 import scatter_set
            cpos = jnp.cumsum(keep.astype(jnp.int32)) - 1
            etgt = jnp.where(keep, cpos, capacity)
            elements = scatter_set(capacity, etgt, sdv, mode="drop")
            evalid = jnp.zeros(capacity, dtype=jnp.bool_).at[etgt].set(
                True, mode="drop")
            counts = seg.segment_sum(keep.astype(jnp.int32), gidv,
                                     num_segments=nseg)
            offsets = jnp.concatenate(
                [jnp.zeros(1, dtype=jnp.int32),
                 jnp.cumsum(counts).astype(jnp.int32)])
            # empty array (not null) for groups whose values were all null
            return ((offsets, elements, evalid), group_live)

        if isinstance(fnagg, agg.Percentile):
            from spark_rapids_tpu.ops.ordering import comparable_operands, lex_sort
            ops = comparable_operands(jnp.where(sv, sd, jnp.zeros_like(sd)))
            res = lex_sort(
                [gid, (~sv).astype(jnp.int32)] + ops,
                jnp.arange(capacity, dtype=jnp.int32))
            gidv = res[0]
            perm2 = res[-1]
            sdv = sd[perm2].astype(jnp.float64)
            svv = sv[perm2]
            nn2 = seg.segment_sum(svv.astype(jnp.int32), gidv,
                                  num_segments=nseg)
            start = seg.segment_min(jnp.arange(capacity, dtype=jnp.int32),
                                    gidv, num_segments=nseg)
            k = (nn2 - 1).astype(jnp.float64) * fnagg.percentage
            klo = jnp.floor(k).astype(jnp.int32)
            khi = jnp.ceil(k).astype(jnp.int32)
            safe_s = jnp.clip(start, 0, capacity - 1)
            vlo = sdv[jnp.clip(safe_s + klo, 0, capacity - 1)]
            vhi = sdv[jnp.clip(safe_s + khi, 0, capacity - 1)]
            out = vlo + (vhi - vlo) * (k - klo)
            validity = (nn2 > 0) & group_live
            return (jnp.where(validity, out, 0.0), validity)

        if isinstance(fnagg, (agg.First, agg.Last)):
            idx = jnp.arange(capacity, dtype=jnp.int32)
            pick_mask = sv if fnagg.ignore_nulls else live
            sentinel = capacity if isinstance(fnagg, agg.First) else -1
            pos = jnp.where(pick_mask, idx, sentinel)
            if isinstance(fnagg, agg.First):
                chosen = seg.segment_min(pos, gid, num_segments=nseg)
            else:
                chosen = seg.segment_max(pos, gid, num_segments=nseg)
            got = (chosen >= 0) & (chosen < capacity) & group_live
            safe = jnp.clip(chosen, 0, capacity - 1)
            data = sd[safe]
            # chosen rows are live by construction, so sv at them equals the
            # raw value validity — right for both ignore_nulls modes
            validity = got & sv[safe]
            return (jnp.where(validity, data, jnp.zeros_like(data)), validity)

        raise ColumnarProcessingError(f"device aggregate {type(fnagg).__name__}")

    def describe(self):
        fused = f", fusedFilters={len(self.filters)}" if self.filters else ""
        return (f"TpuHashAggregate[keys={self.grouping_names}, "
                f"aggs={[n for n, _ in self.agg_specs]}{fused}]")
