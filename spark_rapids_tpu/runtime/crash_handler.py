"""Fatal-error capture + debug dumps.

Reference (SURVEY.md §5): ``GpuCoreDumpHandler.scala`` — on a fatal CUDA
error the executor captures a GPU core dump via a named-pipe monitor and
streams it out, then ``RapidsExecutorPlugin.onTaskFailed`` exits the
process with code 20 so Spark reschedules on another node;
``DumpUtils.scala`` dumps cudf tables to parquet for debugging.

TPU mapping: fatal XLA/PJRT errors (non-OOM XlaRuntimeError: INTERNAL,
device halted, tunnel lost) trigger a crash-report capture — device
memory stats, buffer-catalog state, the failing plan, the exception, and
a faulthandler-style thread dump — written to the configured dump dir.
``FATAL_EXIT_CODE`` and ``exit_on_fatal`` implement the
reschedule-elsewhere protocol for executor deployments."""

from __future__ import annotations

import json
import os
import sys
import time
import traceback
from typing import Optional

from spark_rapids_tpu.conf import RapidsConf, bool_conf, str_conf

FATAL_EXIT_CODE = 20  # reference: RapidsExecutorPlugin exits 20

CRASH_DUMP_DIR = str_conf(
    "spark.rapids.memory.crashDump.dir", "/tmp/rapids_tpu_crash",
    "Directory for fatal-device-error crash reports (GpuCoreDumpHandler "
    "analog).")

EXIT_ON_FATAL = bool_conf(
    "spark.rapids.fatalError.exit", False,
    "Exit the process with code 20 on a fatal device error so the "
    "scheduler replaces this executor (reference Plugin.scala:669-694).")


def is_fatal_device_error(exc: BaseException) -> bool:
    """Fatal = device/runtime failure that is NOT a recoverable OOM.
    Distinct from the per-op KernelCrashError class the circuit breaker
    owns: a fatal error means the DEVICE (or its PJRT tunnel) is gone,
    so recovery is backend reinitialization (runtime/health.py), not
    operator demotion."""
    from spark_rapids_tpu.errors import DeviceLostError
    from spark_rapids_tpu.runtime.retry import is_device_oom
    if isinstance(exc, DeviceLostError):
        return True  # already classified (typed injection / re-raise)
    if is_device_oom(exc):
        return False
    name = type(exc).__name__
    msg = str(exc)
    return "XlaRuntimeError" in name and any(
        k in msg for k in ("INTERNAL", "UNAVAILABLE", "ABORTED",
                           "device halted", "DEADLINE_EXCEEDED"))


def write_crash_report(exc: BaseException, conf: RapidsConf,
                       plan_description: str = "") -> Optional[str]:
    """Capture a crash report; returns the report path (best effort — a
    crash handler must never raise)."""
    try:
        dump_dir = str(conf.get_entry(CRASH_DUMP_DIR))
        os.makedirs(dump_dir, exist_ok=True)
        path = os.path.join(dump_dir, f"crash_{int(time.time() * 1000)}.json")
        report = {
            "timestamp": time.time(),
            "exception_type": type(exc).__name__,
            "exception": str(exc),
            "traceback": traceback.format_exc(),
            "plan": plan_description,
        }
        try:
            import jax
            dev = jax.devices()[0]
            report["device"] = {"platform": dev.platform,
                                "kind": getattr(dev, "device_kind", "")}
            try:
                report["memory_stats"] = {
                    k: int(v) for k, v in dev.memory_stats().items()}
            except Exception:
                pass
        except Exception:
            pass
        try:
            from spark_rapids_tpu.runtime.spill import BufferCatalog
            cat = BufferCatalog.get()
            report["buffer_catalog"] = {
                "device_bytes": cat.device_bytes(),
                "host_bytes": cat.host_bytes(),
                "spill_device_count": cat.spill_device_count,
                "spill_disk_count": cat.spill_disk_count,
            }
        except Exception:
            pass
        try:
            import threading
            names = {t.ident: t.name for t in threading.enumerate()}
            dump = []
            for tid, frame in sys._current_frames().items():
                dump.append(f"Thread {names.get(tid, tid)}:\n"
                            + "".join(traceback.format_stack(frame)))
            report["thread_dump"] = "\n".join(dump)
        except Exception:
            pass
        with open(path, "w") as f:
            json.dump(report, f, indent=2)
        return path
    except Exception:
        return None


def handle_fatal(exc: BaseException, conf: RapidsConf,
                 plan_description: str = "") -> None:
    """Executor fatal-error protocol: capture a report, optionally exit 20
    (the caller re-raises when we return)."""
    path = write_crash_report(exc, conf, plan_description)
    if path:
        print(f"[spark-rapids-tpu] fatal device error; crash report at "
              f"{path}", file=sys.stderr)
    if bool(conf.get_entry(EXIT_ON_FATAL)):
        # os._exit skips atexit handlers, so the disk-tier spill files
        # must be swept HERE — the crash-exit path is exactly where
        # they used to leak (the catalog's shutdown() never ran)
        try:
            from spark_rapids_tpu.runtime.spill import _atexit_spill_sweep
            _atexit_spill_sweep()
        except Exception:
            pass
        # likewise the transactional writer's staging trees: a write
        # job in flight when the device dies must not leave
        # _temporary/ debris for the rescheduled executor's scans
        # (the committed destination is untouched — the replayed job
        # re-stages and re-promotes the same deterministic names)
        try:
            from spark_rapids_tpu.io.committer import sweep_active_jobs
            sweep_active_jobs()
        except Exception:
            pass
        sys.stderr.flush()
        os._exit(FATAL_EXIT_CODE)


def dump_table(table, path: str) -> str:
    """Dump a Host/Device table to parquet for debugging
    (DumpUtils.scala analog; LORE uses the same shape)."""
    from spark_rapids_tpu.io.arrow_convert import host_table_to_arrow
    import pyarrow.parquet as pq
    host = table.to_host() if hasattr(table, "to_host") else table
    pq.write_table(host_table_to_arrow(host), path)
    return path
