"""Deterministic scalable data-generation DSL.

Reference (SURVEY.md §2.11): the ``datagen/`` module —
``bigDataGen.scala`` (~3,200 LoC): per-column seeded generators with
configurable distributions (flat/normal/exponential/multi-modal),
null/special-value probabilities, correlated key groups for joins, and
the ScaleTest table suite (``ScaleTestDataGen.scala``) parameterized by
scale factor.

Design properties kept from the reference:
- **column-stable determinism**: each column's stream seeds from
  (seed, table, column), so adding/removing OTHER columns or changing
  row-chunking never changes a column's values;
- **distribution objects** compose with any value mapper;
- **key groups** generate join-consistent foreign keys (a child table's
  keys are drawn from the parent's key domain);
- **scale factor** drives row counts multiplicatively.
"""

from __future__ import annotations

import hashlib
import string as _string
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar import HostColumn, HostTable
from spark_rapids_tpu.errors import ColumnarProcessingError


def _column_seed(seed: int, table: str, column: str) -> int:
    h = hashlib.sha256(f"{seed}/{table}/{column}".encode()).digest()
    return int.from_bytes(h[:8], "little")


# ---------------------------------------------------------------------------
# distributions (bigDataGen distribution analog)
# ---------------------------------------------------------------------------

class Distribution:
    """Maps n uniform draws to positions in [0, 1)."""

    def sample(self, n: int, rng: np.random.Generator) -> np.ndarray:
        raise NotImplementedError


class Flat(Distribution):
    def sample(self, n, rng):
        return rng.random(n)


@dataclass
class Normal(Distribution):
    """Truncated normal centered at ``center`` (0..1)."""

    center: float = 0.5
    stddev: float = 0.15

    def sample(self, n, rng):
        return np.clip(rng.normal(self.center, self.stddev, n), 0.0,
                       np.nextafter(1.0, 0.0))


@dataclass
class Exponential(Distribution):
    """Skewed toward 0 (hot keys); rate controls the skew."""

    rate: float = 4.0

    def sample(self, n, rng):
        v = rng.exponential(1.0 / self.rate, n)
        return np.clip(v, 0.0, np.nextafter(1.0, 0.0))


@dataclass
class MultiModal(Distribution):
    """Mixture of normals at the given centers (multi-modal hot spots)."""

    centers: Sequence[float] = (0.2, 0.8)
    stddev: float = 0.05

    def sample(self, n, rng):
        which = rng.integers(0, len(self.centers), n)
        base = rng.normal(0.0, self.stddev, n)
        return np.clip(base + np.asarray(self.centers)[which], 0.0,
                       np.nextafter(1.0, 0.0))


# ---------------------------------------------------------------------------
# column generators
# ---------------------------------------------------------------------------

#: generation block size: row i's value depends only on (column seed,
#: i // BLOCK), so ANY chunking yields identical values (the reference's
#: scalable-determinism property)
GEN_BLOCK = 8192


@dataclass
class ColumnGen:
    dtype: T.DataType
    null_prob: float = 0.0
    distribution: Distribution = field(default_factory=Flat)

    def values(self, n: int, rng: np.random.Generator) -> np.ndarray:
        raise NotImplementedError

    def _block(self, block_index: int, seed: int, table: str, column: str):
        rng = np.random.default_rng(
            (_column_seed(seed, table, column), block_index))
        data = self.values(GEN_BLOCK, rng)
        if self.null_prob > 0:
            validity = rng.random(GEN_BLOCK) >= self.null_prob
        else:
            validity = np.ones(GEN_BLOCK, dtype=np.bool_)
        return data, validity

    def generate(self, n: int, seed: int, table: str,
                 column: str, row_offset: int = 0) -> HostColumn:
        datas = []
        valids = []
        pos = row_offset
        end = row_offset + n
        while pos < end:
            b = pos // GEN_BLOCK
            lo = pos - b * GEN_BLOCK
            hi = min(end - b * GEN_BLOCK, GEN_BLOCK)
            data, validity = self._block(b, seed, table, column)
            datas.append(np.asarray(data, dtype=object)[lo:hi]
                         if isinstance(self.dtype, T.StringType)
                         else np.asarray(data)[lo:hi])
            valids.append(validity[lo:hi])
            pos = b * GEN_BLOCK + hi
        data = np.concatenate(datas) if len(datas) > 1 else datas[0]
        validity = np.concatenate(valids) if len(valids) > 1 else valids[0]
        if isinstance(self.dtype, T.StringType):
            out = np.empty(n, dtype=object)
            out[:] = data
            out[~validity] = None
            return HostColumn(self.dtype, out, validity)
        zero = np.zeros((), dtype=self.dtype.np_dtype).item()
        return HostColumn(
            self.dtype,
            np.where(validity, data, zero).astype(self.dtype.np_dtype),
            validity)


@dataclass
class LongRange(ColumnGen):
    """Integers in [lo, hi] under the distribution."""

    dtype: T.DataType = T.LONG
    lo: int = 0
    hi: int = 1 << 31

    def values(self, n, rng):
        u = self.distribution.sample(n, rng)
        span = self.hi - self.lo + 1
        return (self.lo + (u * span).astype(np.int64)).astype(
            self.dtype.np_dtype)


@dataclass
class SequentialKey(ColumnGen):
    """Unique ascending key: row_offset + i (primary keys)."""

    dtype: T.DataType = T.LONG
    start: int = 0

    def generate(self, n, seed, table, column, row_offset=0):
        data = np.arange(self.start + row_offset,
                         self.start + row_offset + n, dtype=np.int64)
        return HostColumn(self.dtype, data.astype(self.dtype.np_dtype),
                          np.ones(n, dtype=np.bool_))

    def values(self, n, rng):  # pragma: no cover - generate() overrides
        raise AssertionError


@dataclass
class ForeignKey(ColumnGen):
    """Keys drawn from a parent key domain [0, parent_rows) under the
    distribution — join-consistent by construction (key-group analog)."""

    dtype: T.DataType = T.LONG
    parent_rows: int = 1000

    def values(self, n, rng):
        u = self.distribution.sample(n, rng)
        return (u * self.parent_rows).astype(np.int64)


@dataclass
class DoubleRange(ColumnGen):
    dtype: T.DataType = T.DOUBLE
    lo: float = 0.0
    hi: float = 1.0

    def values(self, n, rng):
        u = self.distribution.sample(n, rng)
        return (self.lo + u * (self.hi - self.lo)).astype(
            self.dtype.np_dtype)


@dataclass
class DecimalRange(ColumnGen):
    """decimal(p, s) uniform in [lo, hi] (values, not unscaled)."""

    dtype: T.DataType = field(default_factory=lambda: T.DecimalType(10, 2))
    lo: float = 0.0
    hi: float = 1000.0

    def values(self, n, rng):
        u = self.distribution.sample(n, rng)
        scale = 10 ** self.dtype.scale
        return np.round(
            (self.lo + u * (self.hi - self.lo)) * scale).astype(np.int64)


@dataclass
class Word(ColumnGen):
    """Strings from a bounded vocabulary (cardinality) with the
    distribution choosing the word — dictionary-friendly."""

    dtype: T.DataType = T.STRING
    cardinality: int = 1000
    prefix: str = "w"

    def values(self, n, rng):
        u = self.distribution.sample(n, rng)
        idx = (u * self.cardinality).astype(np.int64)
        return [f"{self.prefix}{i:08d}" for i in idx]


@dataclass
class RandomString(ColumnGen):
    dtype: T.DataType = T.STRING
    min_len: int = 0
    max_len: int = 16
    alphabet: str = _string.ascii_letters + _string.digits + " _"

    def values(self, n, rng):
        lens = rng.integers(self.min_len, self.max_len + 1, n)
        chars = np.array(list(self.alphabet))
        return ["".join(rng.choice(chars, size=l)) for l in lens]


@dataclass
class DateRange(ColumnGen):
    dtype: T.DataType = T.DATE
    lo_days: int = 8000   # ~1991
    hi_days: int = 11000  # ~2000

    def values(self, n, rng):
        u = self.distribution.sample(n, rng)
        span = self.hi_days - self.lo_days + 1
        return (self.lo_days + (u * span)).astype(np.int32)


@dataclass
class TimestampRange(ColumnGen):
    dtype: T.DataType = T.TIMESTAMP
    lo_micros: int = 0
    hi_micros: int = 2_000_000_000_000_000

    def values(self, n, rng):
        u = self.distribution.sample(n, rng)
        span = self.hi_micros - self.lo_micros
        return (self.lo_micros + u * span).astype(np.int64)


@dataclass
class BooleanGen(ColumnGen):
    dtype: T.DataType = T.BOOLEAN
    true_prob: float = 0.5

    def values(self, n, rng):
        return rng.random(n) < self.true_prob


@dataclass
class MappedGen(ColumnGen):
    """Arbitrary value mapper over the distribution (escape hatch)."""

    dtype: T.DataType = T.LONG
    fn: Callable[[np.ndarray], np.ndarray] = None

    def values(self, n, rng):
        return self.fn(self.distribution.sample(n, rng))


# ---------------------------------------------------------------------------
# table specs
# ---------------------------------------------------------------------------

class TableSpec:
    """DSL: TableSpec('orders', rows_per_sf=150_000)
    .col('o_orderkey', SequentialKey())
    .col('o_custkey', ForeignKey(parent_rows=..., distribution=Exponential()))
    """

    def __init__(self, name: str, rows_per_sf: int):
        self.name = name
        self.rows_per_sf = rows_per_sf
        self.columns: List[Tuple[str, ColumnGen]] = []

    def col(self, name: str, gen: ColumnGen) -> "TableSpec":
        self.columns.append((name, gen))
        return self

    def rows_at(self, scale_factor: float) -> int:
        return max(int(self.rows_per_sf * scale_factor), 1)

    def generate(self, scale_factor: float = 1.0, seed: int = 0,
                 chunk_rows: Optional[int] = None) -> List[HostTable]:
        """Chunked generation: values are identical regardless of
        chunking (row_offset re-seeds each chunk per column)."""
        total = self.rows_at(scale_factor)
        chunk = chunk_rows or total
        out = []
        off = 0
        while off < total:
            n = min(chunk, total - off)
            cols = [g.generate(n, seed, self.name, cname, row_offset=off)
                    for cname, g in self.columns]
            out.append(HostTable([c for c, _ in self.columns], cols))
            off += n
        return out

    def generate_table(self, scale_factor: float = 1.0,
                       seed: int = 0) -> HostTable:
        (t,) = self.generate(scale_factor, seed)
        return t


# ---------------------------------------------------------------------------
# ScaleTest suite (ScaleTestDataGen analog): a TPC-H-flavored trio whose
# key domains are join-consistent at any scale factor
# ---------------------------------------------------------------------------

def scale_test_specs(scale_factor: float = 1.0) -> Dict[str, TableSpec]:
    customers = int(25_000 * scale_factor) or 1
    orders = int(250_000 * scale_factor) or 1
    spec_c = (TableSpec("customer", 25_000)
              .col("c_custkey", SequentialKey())
              .col("c_name", Word(cardinality=1 << 20, prefix="Customer#"))
              .col("c_nationkey", LongRange(lo=0, hi=24))
              .col("c_acctbal", DecimalRange(
                  dtype=T.DecimalType(12, 2), lo=-999.99, hi=9999.99)))
    spec_o = (TableSpec("orders", 250_000)
              .col("o_orderkey", SequentialKey())
              .col("o_custkey", ForeignKey(parent_rows=customers,
                                           distribution=Exponential()))
              .col("o_orderdate", DateRange())
              .col("o_totalprice", DoubleRange(lo=100.0, hi=500_000.0,
                                               distribution=Normal())))
    spec_l = (TableSpec("lineitem", 1_000_000)
              .col("l_orderkey", ForeignKey(parent_rows=orders,
                                            distribution=Flat()))
              .col("l_quantity", LongRange(lo=1, hi=50))
              .col("l_extendedprice", DoubleRange(lo=900.0, hi=105_000.0))
              .col("l_discount", DoubleRange(lo=0.0, hi=0.1))
              .col("l_tax", DoubleRange(lo=0.0, hi=0.08))
              .col("l_returnflag", Word(cardinality=3, prefix="R"))
              .col("l_linestatus", Word(cardinality=2, prefix="S"))
              .col("l_shipdate", DateRange())
              .col("l_comment", RandomString(max_len=24, null_prob=0.02)))
    return {"customer": spec_c, "orders": spec_o, "lineitem": spec_l}
