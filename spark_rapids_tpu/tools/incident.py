"""``tools incident`` — render flight-recorder bundles offline.

The black-box reader: loads the incident bundles the flight recorder
(obs/telemetry.py) dumped under ``spark.rapids.obs.flightRecorder.dir``
and renders each one — the triggering fault point and ladder
rung/action, the health/mesh/cluster topology at the instant of the
incident, ladder + recovery counters, the telemetry tail, recent
event-record summaries, and any live query table captured. Stdlib-only
over the JSON bundles, like the rest of the tools."""

from __future__ import annotations

import json
import os
from typing import List


def load_bundles(path: str) -> List[dict]:
    """Load bundles from one .json file or a flight-recorder dir
    (oldest first — bundle filenames sort by millisecond timestamp).
    Unreadable bundles are skipped with a stub entry rather than
    failing the whole render (a truncated bundle from a dying process
    is exactly when you need the others)."""
    if os.path.isdir(path):
        files = [os.path.join(path, n) for n in sorted(os.listdir(path))
                 if n.startswith("incident-") and n.endswith(".json")]
    elif os.path.exists(path):
        files = [path]
    else:
        raise FileNotFoundError(f"no incident bundle(s) at {path}")
    if not files:
        raise FileNotFoundError(f"no incident bundles under {path}")
    out: List[dict] = []
    for f in files:
        try:
            with open(f) as fh:
                b = json.load(fh)
        except (OSError, ValueError) as exc:
            b = {"kind": "unreadable", "action": "",
                 "reason": f"{type(exc).__name__}: {exc}"}
        b["_path"] = f
        out.append(b)
    return out


def _counters_line(d: dict) -> str:
    return " ".join(f"{k}={v}" for k, v in sorted((d or {}).items())
                    if v) or "(none)"


def render_incident(bundles: List[dict], last: int = 0) -> str:
    """Human rendering; ``last`` > 0 renders only the newest N (the
    full count still heads the output)."""
    lines: List[str] = [f"Incident bundles: {len(bundles)}"]
    shown = bundles[-last:] if last > 0 else bundles
    for b in shown:
        lines.append("")
        lines.append(f"== {os.path.basename(b.get('_path', '?'))}")
        lines.append(
            f"   kind={b.get('kind')} action={b.get('action')}"
            + (f" seq={b['seq']}" if b.get("seq") else "")
            + (f" domain={b['faultDomain']}" if b.get("faultDomain")
               else "")
            + (f" faultPoint={b['faultPoint']}" if b.get("faultPoint")
               else ""))
        lines.append(f"   trigger: {b.get('reason')}")
        health = b.get("health") or {}
        if health:
            lines.append(
                f"   health: {health.get('state')}"
                + (f" (CPU-only: {health['cpuOnlyReason']})"
                   if health.get("cpuOnlyReason") else ""))
            lines.append("   ladder: backend "
                         + _counters_line(health.get("backend"))
                         + " | mesh "
                         + _counters_line(health.get("meshLadder"))
                         + " | host "
                         + _counters_line(health.get("hostLadder")))
        cluster = b.get("cluster") or {}
        if cluster.get("enabled"):
            lines.append(
                f"   cluster: {len(cluster.get('liveHosts') or [])}/"
                f"{cluster.get('declaredHosts')} live"
                + (f", lost {','.join(cluster['lostHosts'])}"
                   if cluster.get("lostHosts") else "")
                + (f", excluded {','.join(cluster['excludedHosts'])}"
                   if cluster.get("excludedHosts") else "")
                + (f", single-process: {cluster['singleProcessReason']}"
                   if cluster.get("singleProcessReason") else ""))
        mesh = b.get("mesh") or {}
        if mesh.get("shape"):
            lines.append(f"   mesh: {mesh.get('shape')}"
                         + (f", excluded devices "
                            f"{mesh.get('excludedDeviceIds')}"
                            if mesh.get("excludedDeviceIds") else ""))
        if b.get("demotions"):
            lines.append("   demotions: "
                         + ", ".join(sorted(b["demotions"])))
        if b.get("faultFires"):
            lines.append("   fault fires: "
                         + _counters_line(b["faultFires"]))
        if b.get("recovery"):
            lines.append("   recovery: " + _counters_line(b["recovery"]))
        quarantine = b.get("quarantine") or {}
        if quarantine.get("strikes"):
            lines.append(f"   quarantine: {quarantine['strikes']} "
                         f"strikes, {quarantine.get('quarantined', 0)} "
                         f"templates quarantined")
        tele = b.get("telemetry") or {}
        tail = tele.get("tail") or []
        sampler = tele.get("sampler") or {}
        lines.append(
            f"   telemetry tail: {len(tail)} samples "
            f"(sampler {'on' if sampler.get('enabled') else 'off'}, "
            f"{sampler.get('intervalMs', '?')}ms)")
        if tail:
            last_s = tail[-1]
            moved = {s: d for s, d in (last_s.get("deltas") or {}).items()}
            lines.append(
                f"     last: health={last_s.get('health')} "
                f"hosts={last_s.get('hostTopology')} "
                f"mesh={last_s.get('meshShape')}"
                + (f" deltas={json.dumps(moved, sort_keys=True)}"
                   if moved else ""))
        recent = b.get("recentEvents") or []
        if recent:
            lines.append(f"   recent queries ({len(recent)}):")
            for r in recent[-5:]:
                lines.append(
                    f"     #{r.get('queryIndex')} "
                    f"{r.get('queryTag') or '-'} wall="
                    f"{r.get('wallS')}s health={r.get('healthState')}"
                    + (f" demotions={r['demotions']}"
                       if r.get("demotions") else ""))
        for svc in b.get("activeQueries") or []:
            if svc.get("queries"):
                lines.append(f"   live queries: {len(svc['queries'])}")
                for q in svc["queries"][:8]:
                    lines.append(
                        f"     #{q.get('id')} {q.get('state')} "
                        f"{q.get('pool')}/{q.get('tenant')} "
                        f"tag={q.get('tag') or '-'}")
            elif not svc.get("available"):
                lines.append("   live queries: (service busy — table "
                             "unavailable at capture time)")
    return "\n".join(lines)
