"""Date/time expressions (reference: datetimeExpressions.scala +
GpuTimeZoneDB — SURVEY.md §2.3/§2.9; Appendix A datetime rules).

TPU-first: DATE is int32 days and TIMESTAMP is int64 UTC micros, so the
calendar functions are pure integer arithmetic on the VPU using the
days-from-civil / civil-from-days algorithms (Howard Hinnant's public
algorithms — branch-free and fully vectorizable). Timestamps are UTC-only
like the reference's default carve-out (non-UTC session timezones fall back
— the reference gates most of these on UTC too, GpuTimeZoneDB being the
exception it ships natively)."""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar import HostColumn, HostTable
from spark_rapids_tpu.errors import ColumnarProcessingError
from spark_rapids_tpu.ops.common import BinaryExpression, UnaryExpression, null_and
from spark_rapids_tpu.ops.expr import DevVal, Expression
from spark_rapids_tpu.ops.strings import DictStringToValue

MICROS_PER_DAY = 86_400_000_000
MICROS_PER_SECOND = 1_000_000


def civil_from_days(days):
    """(year, month, day) from days-since-epoch. Integer-only, vectorized;
    valid over the whole int32 day range."""
    z = days.astype(jnp.int64) + 719468
    era = jnp.floor_divide(z, 146097)
    doe = z - era * 146097                                   # [0, 146096]
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)          # [0, 365]
    mp = (5 * doy + 2) // 153                                # [0, 11]
    d = doy - (153 * mp + 2) // 5 + 1                        # [1, 31]
    m = jnp.where(mp < 10, mp + 3, mp - 9)                   # [1, 12]
    y = jnp.where(m <= 2, y + 1, y)
    return y.astype(jnp.int32), m.astype(jnp.int32), d.astype(jnp.int32)


def days_from_civil(y, m, d):
    """days-since-epoch from (year, month, day); inverse of civil_from_days."""
    y = y.astype(jnp.int64)
    m = m.astype(jnp.int64)
    d = d.astype(jnp.int64)
    y = jnp.where(m <= 2, y - 1, y)
    era = jnp.floor_divide(y, 400)
    yoe = y - era * 400
    mp = jnp.where(m > 2, m - 3, m + 9)
    doy = (153 * mp + 2) // 5 + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return (era * 146097 + doe - 719468).astype(jnp.int32)


def _np_civil(days: np.ndarray) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    z = days.astype(np.int64) + 719468
    era = np.floor_divide(z, 146097)
    doe = z - era * 146097
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    d = doy - (153 * mp + 2) // 5 + 1
    m = np.where(mp < 10, mp + 3, mp - 9)
    y = np.where(m <= 2, y + 1, y)
    return y.astype(np.int32), m.astype(np.int32), d.astype(np.int32)


def _np_days_from_civil(y, m, d):
    y = y.astype(np.int64)
    m = m.astype(np.int64)
    d = d.astype(np.int64)
    y = np.where(m <= 2, y - 1, y)
    era = np.floor_divide(y, 400)
    yoe = y - era * 400
    mp = np.where(m > 2, m - 3, m + 9)
    doy = (153 * mp + 2) // 5 + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return (era * 146097 + doe - 719468).astype(np.int32)


class _DateField(UnaryExpression):
    """Base: DATE -> INT field extraction."""

    @property
    def data_type(self):
        return T.INT

    def resolve(self, bound_children):
        c = bound_children[0]
        if not isinstance(c.data_type, T.DateType):
            raise ColumnarProcessingError(
                f"{self.name} requires a date input, got {c.data_type}")
        return self.with_children(bound_children)

    def _field_np(self, days: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def _field_dev(self, days):
        raise NotImplementedError

    def eval_cpu(self, table: HostTable) -> HostColumn:
        c = self.children[0].eval_cpu(table)
        return HostColumn(self.data_type,
                          self._field_np(c.data).astype(np.int32),
                          c.validity.copy())

    def eval_dev(self, ctx, child_vals, prep) -> DevVal:
        cv = child_vals[0]
        return DevVal(self._field_dev(cv.data).astype(jnp.int32), cv.validity)


class Year(_DateField):
    def _field_np(self, days):
        return _np_civil(days)[0]

    def _field_dev(self, days):
        return civil_from_days(days)[0]


class Month(_DateField):
    def _field_np(self, days):
        return _np_civil(days)[1]

    def _field_dev(self, days):
        return civil_from_days(days)[1]


class DayOfMonth(_DateField):
    def _field_np(self, days):
        return _np_civil(days)[2]

    def _field_dev(self, days):
        return civil_from_days(days)[2]


class Quarter(_DateField):
    def _field_np(self, days):
        return (_np_civil(days)[1] - 1) // 3 + 1

    def _field_dev(self, days):
        return (civil_from_days(days)[1] - 1) // 3 + 1


class DayOfWeek(_DateField):
    """Sunday = 1 .. Saturday = 7 (1970-01-01 was a Thursday = 5)."""

    def _field_np(self, days):
        return np.mod(days.astype(np.int64) + 4, 7).astype(np.int32) + 1

    def _field_dev(self, days):
        return jnp.mod(days.astype(jnp.int64) + 4, 7).astype(jnp.int32) + 1


class WeekDay(_DateField):
    """Monday = 0 .. Sunday = 6."""

    def _field_np(self, days):
        return np.mod(days.astype(np.int64) + 3, 7).astype(np.int32)

    def _field_dev(self, days):
        return jnp.mod(days.astype(jnp.int64) + 3, 7).astype(jnp.int32)


class DayOfYear(_DateField):
    def _field_np(self, days):
        y, _, _ = _np_civil(days)
        jan1 = _np_days_from_civil(y, np.full_like(y, 1), np.full_like(y, 1))
        return (days - jan1 + 1).astype(np.int32)

    def _field_dev(self, days):
        y, _, _ = civil_from_days(days)
        one = jnp.ones_like(y)
        return (days - days_from_civil(y, one, one) + 1).astype(jnp.int32)


class LastDay(_DateField):
    """Last day of the input date's month (returns DATE)."""

    @property
    def data_type(self):
        return T.DATE

    def _field_np(self, days):
        y, m, _ = _np_civil(days)
        ny = np.where(m == 12, y + 1, y)
        nm = np.where(m == 12, 1, m + 1)
        return (_np_days_from_civil(ny, nm, np.ones_like(ny)) - 1).astype(np.int32)

    def _field_dev(self, days):
        y, m, _ = civil_from_days(days)
        ny = jnp.where(m == 12, y + 1, y)
        nm = jnp.where(m == 12, 1, m + 1)
        return (days_from_civil(ny, nm, jnp.ones_like(ny)) - 1).astype(jnp.int32)


class DateAdd(BinaryExpression):
    """date + n days (n negative for DateSub)."""

    @property
    def data_type(self):
        return T.DATE

    def eval_cpu(self, table):
        d = self.children[0].eval_cpu(table)
        n = self.children[1].eval_cpu(table)
        validity = d.validity & n.validity
        return HostColumn(T.DATE,
                          (d.data.astype(np.int64) + n.data.astype(np.int64)
                           ).astype(np.int32),
                          validity)

    def eval_dev(self, ctx, child_vals, prep):
        d, n = child_vals
        out = (d.data.astype(jnp.int64) + n.data.astype(jnp.int64)).astype(jnp.int32)
        return DevVal(out, null_and(d.validity, n.validity))


class DateSub(BinaryExpression):
    @property
    def data_type(self):
        return T.DATE

    def eval_cpu(self, table):
        d = self.children[0].eval_cpu(table)
        n = self.children[1].eval_cpu(table)
        return HostColumn(T.DATE,
                          (d.data.astype(np.int64) - n.data.astype(np.int64)
                           ).astype(np.int32),
                          d.validity & n.validity)

    def eval_dev(self, ctx, child_vals, prep):
        d, n = child_vals
        out = (d.data.astype(jnp.int64) - n.data.astype(jnp.int64)).astype(jnp.int32)
        return DevVal(out, null_and(d.validity, n.validity))


class DateDiff(BinaryExpression):
    """datediff(end, start) = end - start in days."""

    @property
    def data_type(self):
        return T.INT

    def eval_cpu(self, table):
        e = self.children[0].eval_cpu(table)
        s = self.children[1].eval_cpu(table)
        return HostColumn(T.INT, (e.data - s.data).astype(np.int32),
                          e.validity & s.validity)

    def eval_dev(self, ctx, child_vals, prep):
        e, s = child_vals
        return DevVal((e.data - s.data).astype(jnp.int32),
                      null_and(e.validity, s.validity))


class AddMonths(BinaryExpression):
    """add_months(date, n): clamps the day to the target month's last day."""

    @property
    def data_type(self):
        return T.DATE

    @staticmethod
    def _add(y, m, d, n, np_mod):
        total = (m - 1) + n
        ny = y + np_mod.floor_divide(total, 12)
        nm = np_mod.mod(total, 12) + 1
        return ny, nm, d

    def eval_cpu(self, table):
        dcol = self.children[0].eval_cpu(table)
        ncol = self.children[1].eval_cpu(table)
        y, m, d = _np_civil(dcol.data)
        ny, nm, nd = self._add(y.astype(np.int64), m.astype(np.int64),
                               d.astype(np.int64),
                               ncol.data.astype(np.int64), np)
        # clamp to last day of target month
        last = _np_civil(_np_days_from_civil(
            np.where(nm == 12, ny + 1, ny), np.where(nm == 12, 1, nm + 1),
            np.ones_like(ny)) - 1)[2]
        nd = np.minimum(nd, last.astype(np.int64))
        out = _np_days_from_civil(ny, nm, nd)
        return HostColumn(T.DATE, out, dcol.validity & ncol.validity)

    def eval_dev(self, ctx, child_vals, prep):
        dv, nv = child_vals
        y, m, d = civil_from_days(dv.data)
        ny, nm, nd = self._add(y.astype(jnp.int64), m.astype(jnp.int64),
                               d.astype(jnp.int64),
                               nv.data.astype(jnp.int64), jnp)
        last = civil_from_days(days_from_civil(
            jnp.where(nm == 12, ny + 1, ny), jnp.where(nm == 12, 1, nm + 1),
            jnp.ones_like(ny)) - 1)[2]
        nd = jnp.minimum(nd, last.astype(jnp.int64))
        return DevVal(days_from_civil(ny, nm, nd),
                      null_and(dv.validity, nv.validity))


class _TimestampField(UnaryExpression):
    """TIMESTAMP (UTC micros) -> INT field."""

    divisor = 1
    modulus = 0

    @property
    def data_type(self):
        return T.INT

    def resolve(self, bound_children):
        c = bound_children[0]
        if not isinstance(c.data_type, T.TimestampType):
            raise ColumnarProcessingError(
                f"{self.name} requires a timestamp input, got {c.data_type}")
        return self.with_children(bound_children)

    def eval_cpu(self, table):
        c = self.children[0].eval_cpu(table)
        v = np.floor_divide(c.data, self.divisor)
        if self.modulus:
            v = np.mod(v, self.modulus)
        return HostColumn(T.INT, v.astype(np.int32), c.validity.copy())

    def eval_dev(self, ctx, child_vals, prep):
        cv = child_vals[0]
        v = jnp.floor_divide(cv.data, self.divisor)
        if self.modulus:
            v = jnp.mod(v, self.modulus)
        return DevVal(v.astype(jnp.int32), cv.validity)


class Hour(_TimestampField):
    divisor = 3_600_000_000
    modulus = 24


class Minute(_TimestampField):
    divisor = 60_000_000
    modulus = 60


class Second(_TimestampField):
    divisor = MICROS_PER_SECOND
    modulus = 60


class UnixTimestampFromTs(UnaryExpression):
    """to_unix_timestamp(ts): floor seconds since epoch as LONG."""

    @property
    def data_type(self):
        return T.LONG

    def eval_cpu(self, table):
        c = self.children[0].eval_cpu(table)
        return HostColumn(T.LONG, np.floor_divide(c.data, MICROS_PER_SECOND),
                          c.validity.copy())

    def eval_dev(self, ctx, child_vals, prep):
        cv = child_vals[0]
        return DevVal(jnp.floor_divide(cv.data, MICROS_PER_SECOND), cv.validity)


class SecondsToTimestamp(UnaryExpression):
    @property
    def data_type(self):
        return T.TIMESTAMP

    def eval_cpu(self, table):
        c = self.children[0].eval_cpu(table)
        return HostColumn(T.TIMESTAMP,
                          c.data.astype(np.int64) * MICROS_PER_SECOND,
                          c.validity.copy())

    def eval_dev(self, ctx, child_vals, prep):
        cv = child_vals[0]
        return DevVal(cv.data.astype(jnp.int64) * MICROS_PER_SECOND, cv.validity)


class MillisToTimestamp(UnaryExpression):
    @property
    def data_type(self):
        return T.TIMESTAMP

    def eval_cpu(self, table):
        c = self.children[0].eval_cpu(table)
        return HostColumn(T.TIMESTAMP, c.data.astype(np.int64) * 1000,
                          c.validity.copy())

    def eval_dev(self, ctx, child_vals, prep):
        cv = child_vals[0]
        return DevVal(cv.data.astype(jnp.int64) * 1000, cv.validity)


class MicrosToTimestamp(UnaryExpression):
    @property
    def data_type(self):
        return T.TIMESTAMP

    def eval_cpu(self, table):
        c = self.children[0].eval_cpu(table)
        return HostColumn(T.TIMESTAMP, c.data.astype(np.int64), c.validity.copy())

    def eval_dev(self, ctx, child_vals, prep):
        cv = child_vals[0]
        return DevVal(cv.data.astype(jnp.int64), cv.validity)


class TsToDate(UnaryExpression):
    """Cast-helper: timestamp -> date (UTC floor to day)."""

    @property
    def data_type(self):
        return T.DATE

    def eval_cpu(self, table):
        c = self.children[0].eval_cpu(table)
        return HostColumn(T.DATE,
                          np.floor_divide(c.data, MICROS_PER_DAY).astype(np.int32),
                          c.validity.copy())

    def eval_dev(self, ctx, child_vals, prep):
        cv = child_vals[0]
        return DevVal(jnp.floor_divide(cv.data, MICROS_PER_DAY).astype(jnp.int32),
                      cv.validity)


# -- string timestamp parsing (UnixTimestamp family) -------------------------

#: Java SimpleDateFormat token -> strptime directive (longest-first).
#: Patterns containing tokens outside this table are untranslatable:
#: the expression then RAISES instead of silently nulling (the reference
#: gates device parsing to a known-compatible subset the same way —
#: GpuToTimestamp supported formats).
_JAVA_TOKENS = [
    ("yyyy", "%Y"), ("yyy", "%Y"), ("yy", "%y"),
    ("MM", "%m"), ("dd", "%d"), ("HH", "%H"), ("hh", "%I"),
    ("mm", "%M"), ("ss", "%S"),
    ("M", "%m"), ("d", "%d"), ("H", "%H"), ("m", "%M"), ("s", "%S"),
]


def translate_java_format(fmt: str):
    """Java SimpleDateFormat -> strptime; None when a token has no
    faithful mapping (fractions, zones, am/pm, day names, quoted text)."""
    out = []
    i = 0
    while i < len(fmt):
        ch = fmt[i]
        if ch.isalpha():
            for tok, rep in _JAVA_TOKENS:
                if fmt.startswith(tok, i):
                    out.append(rep)
                    i += len(tok)
                    break
            else:
                return None  # unsupported pattern letter
        else:
            if ch == "%":
                out.append("%%")
            else:
                out.append(ch)
            i += 1
    return "".join(out)


class UnixTimestamp(DictStringToValue, BinaryExpression):
    """unix_timestamp(string, fmt): seconds since epoch as LONG; null on
    parse failure (Spark non-ANSI). fmt must be a literal in the
    supported subset; other formats tag CPU fallback."""

    out_type = T.LONG

    def __init__(self, child: Expression, fmt: Expression = None):
        from spark_rapids_tpu.ops.expr import Literal
        fmt = fmt if fmt is not None else Literal.of("yyyy-MM-dd HH:mm:ss")
        self.children = (child, fmt)

    def with_children(self, children):
        return type(self)(children[0], children[1])

    def key(self):
        from spark_rapids_tpu.ops.expr import Literal
        f = self.children[1]
        return (type(self).__name__.lower(),
                f.value if isinstance(f, Literal) else None,
                self.children[0].key())

    def _fmt(self):
        from spark_rapids_tpu.ops.expr import Literal
        f = self.children[1]
        if isinstance(f, Literal) and f.value is not None:
            return translate_java_format(str(f.value))
        return None

    @property
    def device_supported(self):
        return self._fmt() is not None

    def value_of(self, s: str):
        import datetime as _dt
        fmt = self._fmt()
        if fmt is None:
            # untranslatable format: the CPU path is the FINAL fallback —
            # raising loudly beats silently nulling every row
            from spark_rapids_tpu.ops.expr import Literal
            f = self.children[1]
            shown = f.value if isinstance(f, Literal) else f
            raise ColumnarProcessingError(
                f"unix_timestamp format {shown!r} is not supported "
                "(unsupported SimpleDateFormat tokens)")
        try:
            d = _dt.datetime.strptime(s.strip(), fmt)
        except ValueError:
            return None
        return int((d.replace(tzinfo=_dt.timezone.utc)
                    - _dt.datetime(1970, 1, 1,
                                   tzinfo=_dt.timezone.utc)).total_seconds())


class ToUnixTimestamp(UnixTimestamp):
    """to_unix_timestamp(string, fmt) — same semantics."""


class GetTimestamp(UnixTimestamp):
    """to_timestamp(string, fmt): TIMESTAMP (micros) instead of seconds."""

    out_type = T.TIMESTAMP

    def value_of(self, s: str):
        v = super().value_of(s)
        return None if v is None else v * 1_000_000


class TimeAdd(BinaryExpression):
    """timestamp + interval (literal micros — the reference requires a
    literal CalendarInterval without months too)."""

    @property
    def data_type(self):
        return T.TIMESTAMP

    def key(self):
        from spark_rapids_tpu.ops.expr import Literal
        i = self.children[1]
        return ("time_add", i.value if isinstance(i, Literal) else None,
                self.children[0].key())

    @property
    def device_supported(self):
        from spark_rapids_tpu.ops.expr import Literal
        return isinstance(self.children[1], Literal)

    def _micros(self):
        """Interval micros, or None for a null literal (null interval ->
        null column, Spark semantics)."""
        from spark_rapids_tpu.ops.expr import Literal
        i = self.children[1]
        if not isinstance(i, Literal):
            raise ColumnarProcessingError(
                "TimeAdd interval must be a literal")
        return None if i.value is None else int(i.value)

    def eval_cpu(self, table):
        c = self.children[0].eval_cpu(table)
        m = self._micros()
        if m is None:
            return HostColumn(T.TIMESTAMP, np.zeros_like(c.data),
                              np.zeros(len(c.data), dtype=np.bool_))
        return HostColumn(T.TIMESTAMP, c.data + m, c.validity.copy())

    def eval_dev(self, ctx, child_vals, prep):
        c = child_vals[0]
        m = self._micros()
        if m is None:
            return DevVal(jnp.zeros_like(c.data),
                          jnp.zeros_like(c.validity))
        return DevVal(c.data + jnp.int64(m), c.validity)


class PreciseTimestampConversion(UnaryExpression):
    """Exact long<->timestamp reinterpret at micros precision (Spark
    inserts it around window time functions)."""

    def __init__(self, child: Expression, to_timestamp: bool = True):
        super().__init__(child)
        self._to_ts = to_timestamp

    @property
    def data_type(self):
        return T.TIMESTAMP if self._to_ts else T.LONG

    def with_children(self, children):
        return PreciseTimestampConversion(children[0], self._to_ts)

    def key(self):
        return ("precise_ts_conv", self._to_ts, self.children[0].key())

    def eval_cpu(self, table):
        c = self.children[0].eval_cpu(table)
        return HostColumn(self.data_type, c.data.astype(np.int64),
                          c.validity.copy())

    def eval_dev(self, ctx, child_vals, prep):
        (c,) = child_vals
        return DevVal(c.data.astype(jnp.int64), c.validity)
