"""External-truth oracle tests (VERDICT r4 weak #5).

The engine's usual oracle compares the device path against its OWN CPU
path — self-referential by construction. These tests pin exec-level
semantics against values derived OUTSIDE the engine:

- hand-computed literals derived from the Spark SQL specification (each
  case documents the derivation — the analog of committing Spark-produced
  fixtures, which this environment cannot generate without a JVM;
  reference: integration_tests run real Spark as the truth side),
- pyarrow-written parquet fixtures read back through the engine (an
  independent writer exercising the scan path),
- pandas as an independent compute engine where its semantics provably
  match Spark's (inner-join matching, group sums over non-null ints).

If one of these fails while the self-oracle agrees on both paths, the
ENGINE pair is wrong together — exactly the failure class the
self-oracle cannot see.
"""

import numpy as np
import pytest

from spark_rapids_tpu import functions as F
from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.column import HostColumn
from spark_rapids_tpu.columnar.table import HostTable
from spark_rapids_tpu.ops.expr import col, lit
from spark_rapids_tpu.plan import from_host_table
from spark_rapids_tpu.session import TpuSession


@pytest.fixture
def s():
    return TpuSession()


def rows(df):
    return sorted(df.collect(), key=repr)


# -- join semantics ----------------------------------------------------------

def test_inner_join_drops_null_keys(s):
    """SQL spec: `=` is null-rejecting, so an inner join NEVER matches a
    NULL key to anything (not even another NULL). Truth: the single
    non-null key 1 matches once -> exactly one output row."""
    left = HostTable(["k", "l"], [
        HostColumn(T.LongType(), np.array([1, 0, 2]),
                   np.array([True, False, True])),
        HostColumn(T.LongType(), np.array([10, 20, 30]))])
    right = HostTable(["k", "r"], [
        HostColumn(T.LongType(), np.array([1, 0]),
                   np.array([True, False])),
        HostColumn(T.LongType(), np.array([100, 200]))])
    got = rows(from_host_table(left, s).join(from_host_table(right, s),
                                             on=["k"], how="inner"))
    # the engine surfaces BOTH key columns (no coalescing on join)
    assert got == [(1, 10, 1, 100)]


def test_left_join_null_keys_emit_unmatched(s):
    """Left outer: null-keyed left rows survive with a NULL right side."""
    left = HostTable(["k", "l"], [
        HostColumn(T.LongType(), np.array([1, 0]),
                   np.array([True, False])),
        HostColumn(T.LongType(), np.array([10, 20]))])
    right = HostTable(["k", "r"], [
        HostColumn(T.LongType(), np.array([1])),
        HostColumn(T.LongType(), np.array([100]))])
    got = rows(from_host_table(left, s).join(from_host_table(right, s),
                                             on=["k"], how="left"))
    assert got == [(1, 10, 1, 100), (None, 20, None, None)]


def test_join_matches_pandas_on_multiplicity(s):
    """Duplicate keys multiply: pandas merge implements the same inner-
    join relational semantics — an independent engine as truth."""
    import pandas as pd
    rng = np.random.default_rng(5)
    lk = rng.integers(0, 20, 300)
    rk = rng.integers(0, 20, 100)
    left = HostTable(["k", "l"], [
        HostColumn(T.LongType(), lk),
        HostColumn(T.LongType(), np.arange(300))])
    right = HostTable(["k", "r"], [
        HostColumn(T.LongType(), rk),
        HostColumn(T.LongType(), np.arange(100))])
    got = rows(from_host_table(left, s).join(from_host_table(right, s),
                                             on=["k"], how="inner")
               .select("k", "l", "r"))
    want = pd.merge(pd.DataFrame({"k": lk, "l": np.arange(300)}),
                    pd.DataFrame({"k": rk, "r": np.arange(100)}), on="k")
    assert len(got) == len(want)
    assert sorted(got) == sorted(
        map(tuple, want[["k", "l", "r"]].itertuples(index=False)))


# -- aggregation semantics ---------------------------------------------------

def test_global_agg_over_empty_input(s):
    """SQL spec: a global aggregate over zero rows yields EXACTLY ONE row
    with count=0 and null sum/min/max (not an empty result)."""
    ht = HostTable(["v"], [HostColumn(T.LongType(), np.array([], np.int64))])
    got = from_host_table(ht, s).agg(
        F.count("v").alias("c"), F.sum("v").alias("sv"),
        F.min("v").alias("mn")).collect()
    assert got == [(0, None, None)]


def test_grouped_agg_over_empty_input_is_empty(s):
    """...but a GROUPED aggregate over zero rows yields zero rows."""
    ht = HostTable(["k", "v"], [
        HostColumn(T.LongType(), np.array([], np.int64)),
        HostColumn(T.LongType(), np.array([], np.int64))])
    got = from_host_table(ht, s).group_by("k").agg(
        F.count("v").alias("c")).collect()
    assert got == []


def test_count_star_vs_count_col_and_avg_ignores_nulls(s):
    """count(*)=3 counts rows; count(v)=2 counts non-nulls; avg divides
    by the NON-NULL count: (10+30)/2 = 20.0 exactly."""
    ht = HostTable(["v"], [
        HostColumn(T.DoubleType(), np.array([10.0, 0.0, 30.0]),
                   np.array([True, False, True]))])
    got = from_host_table(ht, s).agg(
        F.count().alias("star"), F.count("v").alias("nonnull"),
        F.avg("v").alias("a")).collect()
    assert got == [(3, 2, 20.0)]


def test_sum_of_all_null_group_is_null(s):
    """sum over a group whose every value is NULL is NULL, count is 0."""
    ht = HostTable(["k", "v"], [
        HostColumn(T.LongType(), np.array([1, 1, 2])),
        HostColumn(T.LongType(), np.array([0, 0, 5]),
                   np.array([False, False, True]))])
    got = rows(from_host_table(ht, s).group_by("k").agg(
        F.sum("v").alias("sv"), F.count("v").alias("c")))
    assert got == [(1, None, 0), (2, 5, 1)]


def test_group_sums_match_pandas(s):
    """Independent-engine truth for exact integer group sums."""
    import pandas as pd
    rng = np.random.default_rng(9)
    k = rng.integers(0, 50, 5000)
    v = rng.integers(-1000, 1000, 5000)
    ht = HostTable(["k", "v"], [HostColumn(T.LongType(), k),
                                HostColumn(T.LongType(), v)])
    got = dict((r[0], r[1]) for r in
               from_host_table(ht, s).group_by("k")
               .agg(F.sum("v").alias("s")).collect())
    want = pd.DataFrame({"k": k, "v": v}).groupby("k")["v"].sum()
    assert got == {int(kk): int(vv) for kk, vv in want.items()}


# -- sort semantics ----------------------------------------------------------

def test_sort_null_placement_spark_defaults(s):
    """Spark: ASC -> NULLS FIRST, DESC -> NULLS LAST (the SQL standard
    leaves this implementation-defined; Spark's choice is what the
    reference implements in SortUtils)."""
    ht = HostTable(["v"], [
        HostColumn(T.LongType(), np.array([3, 0, 1]),
                   np.array([True, False, True]))])
    asc = [r[0] for r in from_host_table(ht, s).sort("v").collect()]
    assert asc == [None, 1, 3]
    desc = [r[0] for r in
            from_host_table(ht, s).sort("v", ascending=False).collect()]
    assert desc == [3, 1, None]


# -- window semantics --------------------------------------------------------

def test_default_window_frame_includes_peers(s):
    """Spark's DEFAULT frame with ORDER BY is RANGE UNBOUNDED PRECEDING
    TO CURRENT ROW: tied order keys are PEERS, so every tied row sees the
    sum INCLUDING all its peers. Input (one partition), ordered by o:
      o: 1, 2, 2, 3   v: 10, 20, 30, 40
    running sum per row: 10, 60, 60, 100  (both o=2 rows include each
    other — the classic Spark window gotcha a ROWS frame would not
    show)."""
    from spark_rapids_tpu.ops.window import Window as W
    ht = HostTable(["o", "v"], [
        HostColumn(T.LongType(), np.array([1, 2, 2, 3])),
        HostColumn(T.LongType(), np.array([10, 20, 30, 40]))])
    got = from_host_table(ht, s).with_windows(
        rs=F.sum(col("v")).over(W.order_by("o"))).collect()
    assert [r[2] for r in got] == [10, 60, 60, 100]


def test_rows_frame_at_partition_edges(s):
    """ROWS BETWEEN 1 PRECEDING AND 1 FOLLOWING clamps at partition
    edges: [10,20,30] -> 30, 60, 50."""
    from spark_rapids_tpu.ops.window import Window as W
    ht = HostTable(["o", "v"], [
        HostColumn(T.LongType(), np.array([1, 2, 3])),
        HostColumn(T.LongType(), np.array([10, 20, 30]))])
    got = from_host_table(ht, s).with_windows(
        rs=F.sum(col("v")).over(W.order_by("o").rows_between(-1, 1))
    ).collect()
    assert [r[2] for r in got] == [30, 60, 50]


def test_row_number_vs_rank_on_ties(s):
    """o = [5, 5, 7]: row_number = 1,2,3; rank = 1,1,3 (gap after tie)."""
    from spark_rapids_tpu.ops.window import Window as W
    from spark_rapids_tpu.functions import rank, row_number
    ht = HostTable(["o"], [HostColumn(T.LongType(), np.array([5, 5, 7]))])
    got = from_host_table(ht, s).with_windows(
        rn=row_number().over(W.order_by("o")),
        rk=rank().over(W.order_by("o"))).collect()
    assert [(r[1], r[2]) for r in got] == [(1, 1), (2, 1), (3, 3)]


# -- cast / expression semantics ---------------------------------------------

def test_double_to_long_cast_truncates_toward_zero(s):
    """Spark cast(double as long) truncates toward zero: -1.9 -> -1,
    1.9 -> 1 (NOT floor)."""
    ht = HostTable(["v"], [
        HostColumn(T.DoubleType(), np.array([-1.9, 1.9, -0.5]))])
    got = [r[0] for r in from_host_table(ht, s)
           .select(col("v").cast("bigint").alias("i")).collect()]
    assert got == [-1, 1, 0]


def test_integer_division_and_mod_signs(s):
    """Spark % follows the DIVIDEND's sign (Java semantics):
    -7 % 3 = -1, 7 % -3 = 1."""
    ht = HostTable(["a", "b"], [
        HostColumn(T.LongType(), np.array([-7, 7])),
        HostColumn(T.LongType(), np.array([3, -3]))])
    got = [r[0] for r in from_host_table(ht, s)
           .select((col("a") % col("b")).alias("m")).collect()]
    assert got == [-1, 1]


# -- independent-writer parquet fixture --------------------------------------

def test_parquet_written_by_pyarrow_reads_back(s, tmp_path):
    """pyarrow (an independent implementation) writes the fixture; the
    engine's scan must surface exactly pyarrow's values, incl. nulls,
    dictionary-encoded strings and out-of-order row groups."""
    pa = pytest.importorskip("pyarrow")
    import pyarrow.parquet as pq
    t = pa.table({
        "i": pa.array([1, None, 3, 4], type=pa.int64()),
        "s": pa.array(["a", "b", None, "a"]),
        "f": pa.array([0.5, -0.5, None, 2.25], type=pa.float64()),
    })
    path = str(tmp_path / "fx.parquet")
    pq.write_table(t, path, row_group_size=2)  # 2 row groups
    got = rows(s.read_parquet(path))
    assert got == sorted([(1, "a", 0.5), (None, "b", -0.5),
                          (3, None, None), (4, "a", 2.25)], key=repr)
