"""Executor-process entry point: ``python -m
spark_rapids_tpu.runtime.cluster_exec``.

A separate module on purpose: running ``runtime/cluster.py`` itself
with ``-m`` would execute it as ``__main__`` AND import it again as
``spark_rapids_tpu.runtime.cluster`` from the scan path — two module
instances, double-registered conf keys. This shim holds no state."""

from spark_rapids_tpu.runtime.cluster import executor_main

if __name__ == "__main__":
    raise SystemExit(executor_main())
