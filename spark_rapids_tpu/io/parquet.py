"""Parquet scan + writer.

Reference: GpuParquetScan.scala (2,897 LoC; three reader modes, footer parse
on CPU, predicate pushdown), GpuParquetFileFormat.scala writer — SURVEY.md
§2.4. Here the footer parse / row-group pruning is pyarrow metadata; the
COALESCING mode stitches at row-group granularity like
MultiFileParquetPartitionReader (GpuParquetScan.scala:1867)."""

from __future__ import annotations

from typing import Iterator, List, Optional, Sequence

import pyarrow.parquet as pq

from spark_rapids_tpu.columnar import HostTable
from spark_rapids_tpu.conf import PARQUET_READER_TYPE, RapidsConf
from spark_rapids_tpu.io.arrow_convert import arrow_schema_to_spark, decode_to_schema
from spark_rapids_tpu.io.common import FileScanNode
from spark_rapids_tpu.io.writer import write_partitioned
from spark_rapids_tpu.plan.nodes import Schema


class ParquetScanNode(FileScanNode):
    format_name = "parquet"

    def __init__(self, paths, conf: RapidsConf, columns=None, reader_type=None,
                 filters=None, **options):
        #: pyarrow-style predicate pushdown filters, e.g. [("x", ">", 3)]
        self.filters = filters

        super().__init__(paths, conf, columns=columns, reader_type=reader_type,
                         **options)

    def _conf_reader_type(self) -> str:
        return self.conf.get_entry(PARQUET_READER_TYPE)

    def _cache_key_extra(self) -> tuple:
        return (repr(self.filters),)

    def file_schema(self, path: str) -> Schema:
        return arrow_schema_to_spark(pq.read_schema(path))

    def _file_columns(self) -> Optional[List[str]]:
        if self.columns is None:
            return None
        data_names = {n for n, _ in self.data_schema}
        return [c for c in self.columns if c in data_names]

    def read_file(self, path: str) -> HostTable:
        cols = self._file_columns()
        if cols is not None and not cols:
            from spark_rapids_tpu.io.common import row_carrier_table
            return row_carrier_table(pq.ParquetFile(path).metadata.num_rows)
        t = pq.read_table(path, columns=cols, filters=self.filters)
        return decode_to_schema(t, self.data_schema)

    def _coalescing_chunks(self, paths=None) -> Iterator[HostTable]:
        """Row-group-granular chunks for the stitcher (one device upload per
        stitched group). With pushdown filters the row-group fast path is
        bypassed so filtering stays identical across reader modes."""
        if self.filters is not None:
            yield from self._perfile(paths)
            return
        for path in (self.paths if paths is None else paths):
            f = pq.ParquetFile(path)
            for rg in range(f.metadata.num_row_groups):
                t = f.read_row_group(rg, columns=self._file_columns())
                yield self._with_partition_columns(
                    decode_to_schema(t, self.data_schema), path)


def write_parquet(table: HostTable, path: str,
                  partition_by: Optional[Sequence[str]] = None,
                  compression: str = "snappy", row_group_rows: int = 1 << 20,
                  committer=None) -> List[str]:
    """Write a HostTable as parquet file(s); returns written paths.

    With ``partition_by``, writes Hive-style key=value directories via the
    dynamic-partitioning writer (GpuFileFormatDataWriter analog). All
    output stages through the transactional committer (io/committer.py);
    pass ``committer`` to run under a caller-owned WriteJob."""
    def _write_one(tbl: HostTable, file_path: str):
        from spark_rapids_tpu.io.arrow_convert import host_table_to_arrow
        pq.write_table(host_table_to_arrow(tbl), file_path,
                       compression=compression, row_group_size=row_group_rows)

    return write_partitioned(table, path, _write_one, "parquet",
                             partition_by, committer=committer)
