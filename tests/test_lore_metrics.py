"""LORE dump/replay + metrics levels (reference: lore/GpuLore.scala,
GpuExec metric levels)."""

import subprocess
import sys


def _key(row):
    return tuple((x is None, str(x)) for x in row)

from spark_rapids_tpu import functions as F
from spark_rapids_tpu.ops.expr import col, lit
from spark_rapids_tpu.plan import from_host_table

from tests.data_gen import IntGen, StringGen, gen_table


def _table():
    return gen_table({"k": StringGen(cardinality=5),
                      "v": IntGen(min_val=-50, max_val=50)}, 200, 9)


def test_lore_ids_and_metrics_tree(session):
    df = from_host_table(_table(), session).filter(col("v") > lit(0)) \
        .group_by("k").agg(F.count().alias("c"))
    df.collect_table()
    tree = session.last_metrics()
    assert "loreId=1" in tree
    assert "TpuHashAggregate" in tree
    assert "numOutputRows" in tree


def test_lore_dump_and_replay_same_process(tmp_path):
    from spark_rapids_tpu import lore
    from spark_rapids_tpu.session import TpuSession

    table = _table()
    probe = TpuSession()
    df = from_host_table(table, probe).group_by("k").agg(
        F.count().alias("c"), F.sum(col("v")).alias("sv"))
    expected = sorted(df.collect(), key=_key)

    # find the aggregate's lore id from a first run
    probe.execute(df.plan)
    agg_id = None
    for line in probe.last_metrics().splitlines():
        if "TpuHashAggregate" in line:
            agg_id = int(line.split("loreId=")[1].split("]")[0])
    assert agg_id is not None

    dump = TpuSession({"spark.rapids.sql.lore.idsToDump": str(agg_id),
                       "spark.rapids.sql.lore.dumpPath": str(tmp_path)})
    got = sorted(from_host_table(table, dump).group_by("k").agg(
        F.count().alias("c"), F.sum(col("v")).alias("sv")).collect(),
        key=_key)
    assert got == expected  # dumping must not change results

    replayed = lore.replay(str(tmp_path / f"lore-{agg_id}"))
    rows = sorted(
        (tuple(c.to_pylist()[i] for c in replayed.columns)
         for i in range(replayed.num_rows)), key=_key)
    assert rows == expected


def test_lore_replay_fresh_process(tmp_path):
    """Replay must work from a brand-new interpreter (the reference's
    whole point: reproduce one operator offline)."""
    from spark_rapids_tpu.session import TpuSession

    table = _table()
    dump = TpuSession({"spark.rapids.sql.lore.idsToDump": "2",
                       "spark.rapids.sql.lore.dumpPath": str(tmp_path)})
    df = from_host_table(table, dump).group_by("k").agg(F.count().alias("c"))
    expected = sorted(df.collect(), key=_key)

    code = f"""
import os, sys
os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
os.environ["JAX_PLATFORMS"] = "cpu"
import jax; jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, {repr(sys.path[0] or ".")})
from spark_rapids_tpu import lore
t = lore.replay({repr(str(tmp_path / "lore-2"))})
rows = sorted((tuple(c.to_pylist()[i] for c in t.columns) for i in range(t.num_rows)), key=lambda r: tuple((x is None, str(x)) for x in r))
print(repr(rows))
"""
    out = subprocess.run([sys.executable, "-c", code], capture_output=True,
                         text=True, cwd="/root/repo")
    assert out.returncode == 0, out.stderr[-2000:]
    assert repr(expected) in out.stdout


def test_metrics_level_gating(session):
    from spark_rapids_tpu.execs.base import TpuExec, set_metrics_level

    class Dummy(TpuExec):
        pass

    d = Dummy()
    set_metrics_level("ESSENTIAL")
    d.add_metric("debugOnly", 1, level="DEBUG")
    d.add_metric("moderate", 1, level="MODERATE")
    d.add_metric("essential", 1, level="ESSENTIAL")
    assert d.metrics == {"essential": 1}
    set_metrics_level("DEBUG")
    d.add_metric("debugOnly", 1, level="DEBUG")
    assert d.metrics == {"essential": 1, "debugOnly": 1}
    set_metrics_level("MODERATE")
