"""Hash expressions: Murmur3Hash and XxHash64, Spark-exact on device.

Reference: HashFunctions.scala + jni Hash kernels (SURVEY.md §2.9 —
"murmur3/xxhash64 Spark-exact"). Murmur3 reuses the shuffle layer's device
kernel (shuffle/hashing.py, validated against Spark's documented composite
vector). XxHash64 implements Spark's XXH64 variant with seed 42: fixed-width
types hash as single 8/4-byte "tail" rounds; strings run full XXH64 over
UTF-8 bytes via the dictionary byte-matrix gather."""

from __future__ import annotations

from typing import List

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.errors import ColumnarProcessingError
from spark_rapids_tpu.columnar import HostColumn, HostTable
from spark_rapids_tpu.ops.expr import (
    DevVal,
    EvalCtx,
    Expression,
    NodePrep,
    PrepCtx,
)
from spark_rapids_tpu.shuffle.hashing import (
    murmur3_hash_device,
    murmur3_hash_host,
    string_dict_bytes,
)

P1 = 0x9E3779B185EBCA87
P2 = 0xC2B2AE3D27D4EB4F
P3 = 0x165667B19E3779F9
P4 = 0x85EBCA77C2B2AE63
P5 = 0x27D4EB2F165667C5
M64 = (1 << 64) - 1
XX_SEED = 42


class _HashBase(Expression):
    """n-ary row hash; children hash in order, each output seeding the next."""

    def __init__(self, *children: Expression):
        self.children = tuple(children)

    def with_children(self, children):
        return type(self)(*children)

    @property
    def nullable(self):
        return False

    def key(self):
        return (type(self).__name__.lower(),
                tuple(c.key() for c in self.children))

    def prep(self, pctx: PrepCtx, child_preps) -> NodePrep:
        slots = []
        for c, p in zip(self.children, child_preps):
            if isinstance(c.data_type, T.StringType):
                mat, lens = string_dict_bytes(
                    p.out_dict if p.out_dict is not None
                    else np.array([], dtype=object))
                slots.append((pctx.add_aux(mat), pctx.add_aux(lens)))
            else:
                slots.append(None)
        flat = tuple(s for pair in slots if pair for s in pair)
        return NodePrep(aux_slots=flat,
                        extra={"string_ix": tuple(
                            i for i, s in enumerate(slots) if s)})

    def _string_bytes(self, ctx: EvalCtx, prep: NodePrep):
        out = {}
        it = iter(prep.aux_slots)
        for i in prep.extra["string_ix"]:
            out[i] = (ctx.aux[next(it)], ctx.aux[next(it)])
        return out


class Murmur3Hash(_HashBase):
    @property
    def data_type(self):
        return T.INT

    def eval_cpu(self, table: HostTable) -> HostColumn:
        cols = [c.eval_cpu(table) for c in self.children]
        n = table.num_rows
        out = np.empty(n, dtype=np.int32)
        for r in range(n):
            out[r] = murmur3_hash_host(
                [(cols[j].data[r], bool(cols[j].validity[r]),
                  self.children[j].data_type) for j in range(len(cols))])
        return HostColumn(T.INT, out, np.ones(n, dtype=np.bool_))

    def eval_dev(self, ctx: EvalCtx, child_vals, prep: NodePrep) -> DevVal:
        cols = [(v.data, v.validity, c.data_type)
                for c, v in zip(self.children, child_vals)]
        h = murmur3_hash_device(cols, string_bytes=self._string_bytes(ctx, prep))
        return DevVal(h, jnp.ones(ctx.capacity, dtype=jnp.bool_))


# -- xxhash64 ---------------------------------------------------------------

def _u64(x):
    return x.astype(jnp.uint64)


def _rotl64(x, r):
    r = jnp.uint64(r)
    return (x << r) | (x >> (jnp.uint64(64) - r))


def _xx_fmix(h):
    h = h ^ (h >> jnp.uint64(33))
    h = (h * jnp.uint64(P2)).astype(jnp.uint64)
    h = h ^ (h >> jnp.uint64(29))
    h = (h * jnp.uint64(P3)).astype(jnp.uint64)
    return h ^ (h >> jnp.uint64(32))


def _xx_process_long(value_u64, seed_u64):
    """Spark XXH64 hashLong: one 8-byte round + avalanche."""
    h = seed_u64 + jnp.uint64(P5) + jnp.uint64(8)
    k1 = (value_u64 * jnp.uint64(P2)).astype(jnp.uint64)
    k1 = _rotl64(k1, 31)
    k1 = (k1 * jnp.uint64(P1)).astype(jnp.uint64)
    h = h ^ k1
    h = (_rotl64(h, 27) * jnp.uint64(P1) + jnp.uint64(P4)).astype(jnp.uint64)
    return _xx_fmix(h)


def _xx_process_int(value_u32, seed_u64):
    """Spark XXH64 hashInt: one 4-byte round + avalanche."""
    h = seed_u64 + jnp.uint64(P5) + jnp.uint64(4)
    k1 = (value_u32.astype(jnp.uint64) * jnp.uint64(P1)).astype(jnp.uint64)
    h = h ^ k1
    h = (_rotl64(h, 23) * jnp.uint64(P2) + jnp.uint64(P3)).astype(jnp.uint64)
    return _xx_fmix(h)


def _xx_hash_bytes_device(byte_rows, lengths, seed_u64):
    """Full XXH64 over per-row byte sequences (dictionary byte matrix,
    leading dim padded; L static)."""
    n, L = byte_rows.shape
    lengths = lengths.astype(jnp.int32)

    def word64(base):
        b = byte_rows[:, base:base + 8].astype(jnp.uint64)
        out = jnp.zeros(n, dtype=jnp.uint64)
        for k in range(8):
            out = out | (b[:, k] << jnp.uint64(8 * k))
        return out

    # 32-byte stripes with 4 accumulators
    seed = seed_u64
    v1 = seed + jnp.uint64(P1) + jnp.uint64(P2)
    v2 = seed + jnp.uint64(P2)
    v3 = seed
    v4 = seed - jnp.uint64(P1)
    nstripes = lengths // 32
    has_stripes = nstripes > 0

    def stripe_round(v, w):
        v = (v + w * jnp.uint64(P2)).astype(jnp.uint64)
        v = _rotl64(v, 31)
        return (v * jnp.uint64(P1)).astype(jnp.uint64)

    for s in range(L // 32):
        base = s * 32
        active = (s < nstripes)
        nv1 = stripe_round(v1, word64(base))
        nv2 = stripe_round(v2, word64(base + 8))
        nv3 = stripe_round(v3, word64(base + 16))
        nv4 = stripe_round(v4, word64(base + 24))
        v1 = jnp.where(active, nv1, v1)
        v2 = jnp.where(active, nv2, v2)
        v3 = jnp.where(active, nv3, v3)
        v4 = jnp.where(active, nv4, v4)

    merged = (_rotl64(v1, 1) + _rotl64(v2, 7) + _rotl64(v3, 12)
              + _rotl64(v4, 18)).astype(jnp.uint64)

    def merge_round(h, v):
        v = stripe_round(jnp.zeros_like(v), v)  # mixK-style
        h = h ^ v
        return (h * jnp.uint64(P1) + jnp.uint64(P4)).astype(jnp.uint64)

    merged = merge_round(merged, v1)
    merged = merge_round(merged, v2)
    merged = merge_round(merged, v3)
    merged = merge_round(merged, v4)

    h = jnp.where(has_stripes, merged, seed + jnp.uint64(P5))
    h = (h + lengths.astype(jnp.uint64)).astype(jnp.uint64)

    # 8-byte tail words: walk gated 8-aligned positions after the stripes
    pos = nstripes * 32
    max_tail_words = 3  # < 32 bytes remain => at most 3 full 8-byte words
    for _ in range(max_tail_words):
        idx8 = jnp.clip(pos, 0, max(L - 8, 0))
        b = jnp.stack([jnp.take_along_axis(
            byte_rows, jnp.clip(idx8 + k, 0, L - 1)[:, None], axis=1)[:, 0]
            for k in range(8)], axis=1).astype(jnp.uint64)
        word = jnp.zeros(n, dtype=jnp.uint64)
        for k in range(8):
            word = word | (b[:, k] << jnp.uint64(8 * k))
        active = (pos + 8) <= lengths
        k1 = stripe_round(jnp.zeros(n, dtype=jnp.uint64), word)
        nh = ((_rotl64(h ^ k1, 27) * jnp.uint64(P1)) + jnp.uint64(P4)).astype(jnp.uint64)
        h = jnp.where(active, nh, h)
        pos = jnp.where(active, pos + 8, pos)

    # 4-byte tail
    idx4 = jnp.clip(pos, 0, max(L - 4, 0))
    b4 = jnp.stack([jnp.take_along_axis(
        byte_rows, jnp.clip(idx4 + k, 0, L - 1)[:, None], axis=1)[:, 0]
        for k in range(4)], axis=1).astype(jnp.uint64)
    word4 = jnp.zeros(n, dtype=jnp.uint64)
    for k in range(4):
        word4 = word4 | (b4[:, k] << jnp.uint64(8 * k))
    active4 = (pos + 4) <= lengths
    nh = h ^ ((word4 * jnp.uint64(P1)).astype(jnp.uint64))
    nh = ((_rotl64(nh, 23) * jnp.uint64(P2)) + jnp.uint64(P3)).astype(jnp.uint64)
    h = jnp.where(active4, nh, h)
    pos = jnp.where(active4, pos + 4, pos)

    # byte tail
    for _ in range(3):
        idxb = jnp.clip(pos, 0, L - 1)
        byte = jnp.take_along_axis(byte_rows, idxb[:, None], axis=1)[:, 0]
        active1 = pos < lengths
        nh = h ^ ((byte.astype(jnp.uint64) * jnp.uint64(P5)).astype(jnp.uint64))
        nh = ((_rotl64(nh, 11) * jnp.uint64(P1))).astype(jnp.uint64)
        h = jnp.where(active1, nh, h)
        pos = jnp.where(active1, pos + 1, pos)

    return _xx_fmix(h)


def _bitcast(x, dtype):
    return jax.lax.bitcast_convert_type(x, dtype)


def xxhash64_device(cols: List, seed: int = XX_SEED, string_bytes=None):
    n = cols[0][0].shape[0]
    h = jnp.full(n, np.uint64(seed), dtype=jnp.uint64)
    for i, (data, validity, dt) in enumerate(cols):
        if isinstance(dt, T.StringType):
            mat, lens = string_bytes[i]
            codes = jnp.clip(data, 0, mat.shape[0] - 1)
            nh = _xx_hash_bytes_device(mat[codes], lens[codes], h)
        elif isinstance(dt, (T.LongType, T.TimestampType, T.DecimalType)):
            nh = _xx_process_long(_bitcast(data.astype(jnp.int64), jnp.uint64), h)
        elif isinstance(dt, T.DoubleType):
            d = jnp.where(data == 0.0, jnp.zeros_like(data), data)
            nh = _xx_process_long(_bitcast(d, jnp.uint64), h)
        elif isinstance(dt, T.FloatType):
            d = jnp.where(data == 0.0, jnp.zeros_like(data), data)
            nh = _xx_process_int(_bitcast(d, jnp.uint32), h)
        elif isinstance(dt, T.BooleanType):
            nh = _xx_process_int(data.astype(jnp.uint32), h)
        else:
            nh = _xx_process_int(_bitcast(data.astype(jnp.int32), jnp.uint32), h)
        h = jnp.where(validity, nh, h)
    return _bitcast(h, jnp.int64)


# -- numpy mirror -----------------------------------------------------------

def _np_rotl64(x, r):
    x = int(x) & M64
    return ((x << r) | (x >> (64 - r))) & M64


def _np_xx_fmix(h):
    h = int(h) & M64
    h ^= h >> 33
    h = (h * P2) & M64
    h ^= h >> 29
    h = (h * P3) & M64
    h ^= h >> 32
    return h


def _np_xx_long(v, seed):
    v = int(np.int64(v)) & M64
    h = (seed + P5 + 8) & M64
    k1 = (v * P2) & M64
    k1 = _np_rotl64(k1, 31)
    k1 = (k1 * P1) & M64
    h ^= k1
    h = (_np_rotl64(h, 27) * P1 + P4) & M64
    return _np_xx_fmix(h)


def _np_xx_int(v, seed):
    v = int(np.uint32(np.int32(v)))
    h = (seed + P5 + 4) & M64
    h ^= (v * P1) & M64
    h = (_np_rotl64(h, 23) * P2 + P3) & M64
    return _np_xx_fmix(h)


def _np_xx_bytes(b: bytes, seed: int) -> int:
    length = len(b)
    if length >= 32:
        v1 = (seed + P1 + P2) & M64
        v2 = (seed + P2) & M64
        v3 = seed & M64
        v4 = (seed - P1) & M64
        i = 0
        while i + 32 <= length:
            for vi, off in ((1, 0), (2, 8), (3, 16), (4, 24)):
                w = int.from_bytes(b[i + off:i + off + 8], "little")
                v = {1: v1, 2: v2, 3: v3, 4: v4}[vi]
                v = (v + w * P2) & M64
                v = _np_rotl64(v, 31)
                v = (v * P1) & M64
                if vi == 1:
                    v1 = v
                elif vi == 2:
                    v2 = v
                elif vi == 3:
                    v3 = v
                else:
                    v4 = v
            i += 32
        h = (_np_rotl64(v1, 1) + _np_rotl64(v2, 7) + _np_rotl64(v3, 12)
             + _np_rotl64(v4, 18)) & M64
        for v in (v1, v2, v3, v4):
            k = (v * P2) & M64
            k = _np_rotl64(k, 31)
            k = (k * P1) & M64
            h ^= k
            h = (h * P1 + P4) & M64
        pos = i
    else:
        h = (seed + P5) & M64
        pos = 0
    h = (h + length) & M64
    while pos + 8 <= length:
        w = int.from_bytes(b[pos:pos + 8], "little")
        k1 = (w * P2) & M64
        k1 = _np_rotl64(k1, 31)
        k1 = (k1 * P1) & M64
        h ^= k1
        h = (_np_rotl64(h, 27) * P1 + P4) & M64
        pos += 8
    if pos + 4 <= length:
        w = int.from_bytes(b[pos:pos + 4], "little")
        h ^= (w * P1) & M64
        h = (_np_rotl64(h, 23) * P2 + P3) & M64
        pos += 4
    while pos < length:
        h ^= (b[pos] * P5) & M64
        h = (_np_rotl64(h, 11) * P1) & M64
        pos += 1
    return _np_xx_fmix(h)


def xxhash64_host(values, seed: int = XX_SEED) -> int:
    h = seed
    for v, valid, dt in values:
        if not valid:
            continue
        if isinstance(dt, T.StringType):
            h = _np_xx_bytes(str(v).encode("utf-8"), h)
        elif T.is_dec128(dt):
            # Spark-exact: bytes of the unscaled BigInteger (see
            # shuffle/hashing.py murmur3 dec128 note)
            from spark_rapids_tpu.shuffle.hashing import (
                _dec128_twos_complement_bytes,
            )
            h = _np_xx_bytes(_dec128_twos_complement_bytes(int(v)), h)
        elif isinstance(dt, (T.LongType, T.TimestampType, T.DecimalType)):
            h = _np_xx_long(v, h)
        elif isinstance(dt, T.DoubleType):
            d = 0.0 if v == 0.0 else float(v)
            h = _np_xx_long(np.float64(d).view(np.int64), h)
        elif isinstance(dt, T.FloatType):
            f = 0.0 if v == 0.0 else float(v)
            h = _np_xx_int(np.float32(f).view(np.int32), h)
        elif isinstance(dt, T.BooleanType):
            h = _np_xx_int(1 if v else 0, h)
        else:
            h = _np_xx_int(int(v), h)
    return int(np.uint64(h).view(np.int64))


class XxHash64(_HashBase):
    @property
    def data_type(self):
        return T.LONG

    def eval_cpu(self, table: HostTable) -> HostColumn:
        cols = [c.eval_cpu(table) for c in self.children]
        n = table.num_rows
        out = np.empty(n, dtype=np.int64)
        for r in range(n):
            out[r] = xxhash64_host(
                [(cols[j].data[r], bool(cols[j].validity[r]),
                  self.children[j].data_type) for j in range(len(cols))])
        return HostColumn(T.LONG, out, np.ones(n, dtype=np.bool_))

    def eval_dev(self, ctx: EvalCtx, child_vals, prep: NodePrep) -> DevVal:
        cols = [(v.data, v.validity, c.data_type)
                for c, v in zip(self.children, child_vals)]
        h = xxhash64_device(cols, string_bytes=self._string_bytes(ctx, prep))
        return DevVal(h, jnp.ones(ctx.capacity, dtype=jnp.bool_))


# -- hive hash ---------------------------------------------------------------

def _hive_string_hash(s: str) -> int:
    """Hive HiveHasher.hashUnsafeBytes: fold SIGNED UTF-8 bytes
    (31*h + byte), int32 wraparound. Matches String.hashCode only for
    ASCII — non-ASCII must use the byte fold or bucketing diverges."""
    h = 0
    for byte in s.encode("utf-8"):
        signed = byte - 256 if byte >= 128 else byte
        h = (h * 31 + signed) & 0xFFFFFFFF
    return h - (1 << 32) if h >= (1 << 31) else h


def _hive_timestamp_value(micros: int) -> int:
    """Hive TimestampWritable.hashCode layout: (seconds << 30) | nanos,
    before the standard long fold."""
    seconds, rem = divmod(int(micros), 1_000_000)
    return (seconds << 30) | (rem * 1000)


def _hive_field_host(value, valid: bool, dtype) -> int:
    if not valid:
        return 0
    if isinstance(dtype, T.BooleanType):
        return 1 if value else 0
    if isinstance(dtype, (T.ByteType, T.ShortType, T.IntegerType,
                          T.DateType)):
        return int(np.int32(value))
    if isinstance(dtype, T.LongType):
        v = int(np.int64(value))
        return int(np.int32((v ^ ((v >> 32) & 0xFFFFFFFF)) & 0xFFFFFFFF))
    if isinstance(dtype, T.FloatType):
        bits = np.float32(value).view(np.int32)
        return int(bits)
    if isinstance(dtype, (T.DoubleType, T.TimestampType)):
        if isinstance(dtype, T.TimestampType):
            v = _hive_timestamp_value(int(np.int64(value)))
        else:
            v = int(np.float64(value).view(np.int64))
        return int(np.int32((v ^ ((v >> 32) & 0xFFFFFFFF)) & 0xFFFFFFFF))
    if isinstance(dtype, T.StringType):
        return _hive_string_hash(value)
    raise ColumnarProcessingError(f"hive hash of {dtype} not supported")


class HiveHash(_HashBase):
    """Hive hash (reference: HashFunctions.scala hiveHash / JNI Hash):
    row hash = fold(31 * h + fieldHash), null fields hash to 0."""

    @property
    def data_type(self):
        return T.INT

    def eval_cpu(self, table: HostTable) -> HostColumn:
        cols = [c.eval_cpu(table) for c in self.children]
        n = table.num_rows
        out = np.empty(n, dtype=np.int32)
        for r in range(n):
            h = 0
            for j, c in enumerate(cols):
                f = _hive_field_host(c.data[r], bool(c.validity[r]),
                                     self.children[j].data_type)
                h = (h * 31 + f) & 0xFFFFFFFF
            out[r] = np.uint32(h).astype(np.int32).item() \
                if h < (1 << 31) else h - (1 << 32)
        return HostColumn(T.INT, out, np.ones(n, dtype=np.bool_))

    def prep(self, pctx: PrepCtx, child_preps) -> NodePrep:
        # per-string-child: precomputed Java hashCode per DICT entry
        slots = []
        for c, p in zip(self.children, child_preps):
            if isinstance(c.data_type, T.StringType):
                d = p.out_dict if p.out_dict is not None \
                    else np.array([], dtype=object)
                hashes = np.array(
                    [_hive_string_hash(s) for s in d] or [0],
                    dtype=np.int32)
                slots.append(pctx.add_aux(hashes))
            else:
                slots.append(None)
        flat = tuple(s for s in slots if s is not None)
        return NodePrep(aux_slots=flat,
                        extra={"string_ix": tuple(
                            i for i, s in enumerate(slots)
                            if s is not None)})

    def eval_dev(self, ctx: EvalCtx, child_vals, prep: NodePrep) -> DevVal:
        it = iter(prep.aux_slots)
        string_hash = {i: ctx.aux[next(it)]
                       for i in prep.extra["string_ix"]}
        h = jnp.zeros(ctx.capacity, dtype=jnp.int32)
        for j, (c, v) in enumerate(zip(self.children, child_vals)):
            dt = c.data_type
            if j in string_hash:
                tbl = string_hash[j]
                f = tbl[jnp.clip(v.data, 0, tbl.shape[0] - 1)]
            elif isinstance(dt, T.BooleanType):
                f = v.data.astype(jnp.int32)
            elif isinstance(dt, (T.ByteType, T.ShortType, T.IntegerType,
                                 T.DateType)):
                f = v.data.astype(jnp.int32)
            elif isinstance(dt, T.TimestampType):
                micros = v.data.astype(jnp.int64)
                seconds = jnp.floor_divide(micros, 1_000_000)
                nanos = (micros - seconds * 1_000_000) * 1000
                x = (seconds << 30) | nanos
                f = (x ^ ((x >> 32) & 0xFFFFFFFF)).astype(jnp.int32)
            elif isinstance(dt, T.LongType):
                x = v.data.astype(jnp.int64)
                f = (x ^ ((x >> 32) & 0xFFFFFFFF)).astype(jnp.int32)
            elif isinstance(dt, T.FloatType):
                f = jax.lax.bitcast_convert_type(
                    v.data.astype(jnp.float32), jnp.int32)
            elif isinstance(dt, T.DoubleType):
                x = jax.lax.bitcast_convert_type(
                    v.data.astype(jnp.float64), jnp.int64)
                f = (x ^ ((x >> 32) & 0xFFFFFFFF)).astype(jnp.int32)
            else:
                raise ColumnarProcessingError(
                    f"hive hash of {dt} not supported on device")
            f = jnp.where(v.validity, f, jnp.int32(0))
            h = h * jnp.int32(31) + f
        return DevVal(h, jnp.ones(ctx.capacity, dtype=jnp.bool_))
