"""Expression-breadth batch tests: get_json_object/json_tuple, hive
hash, conv, ceil/floor-at-scale, unix_timestamp parsing, time_add,
InSet (reference: Appendix A inventory — GetJsonObject/JSONUtils,
HashFunctions.hiveHash, Conv, RoundCeil/RoundFloor, GpuToTimestamp)."""

import datetime as dt

import numpy as np
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.ops.expr import col, lit
from tests.asserts import assert_runs_on_tpu


# -- get_json_object ---------------------------------------------------------

DOCS = [
    '{"a": 1, "b": {"c": "x"}, "arr": [10, 20, {"d": true}]}',
    '{"a": "str", "arr": []}',
    'not json',
    '{"a": null}',
    '{"b": {"c": "y"}, "arr": [1, 2, 3]}',
]


def _jdf(s):
    return s.create_dataframe({"j": np.array(DOCS, dtype=object)})


def test_get_json_object(session, cpu_session):
    from spark_rapids_tpu.ops.json_fns import GetJsonObject

    def q(s):
        return _jdf(s).select(
            GetJsonObject(col("j"), lit("$.a")).alias("a"),
            GetJsonObject(col("j"), lit("$.b.c")).alias("bc"),
            GetJsonObject(col("j"), lit("$.arr[1]")).alias("i1"),
            GetJsonObject(col("j"), lit("$.arr[2].d")).alias("d"),
            GetJsonObject(col("j"), lit("$.missing")).alias("m"))

    got = q(session).collect()
    assert got == q(cpu_session).collect()
    assert got[0] == ("1", "x", "20", "true", None)
    assert got[1][0] == "str"          # strings unquoted
    assert got[2] == (None,) * 5       # invalid json -> null
    assert got[3][0] is None           # json null -> null
    assert got[4][2] == "2"
    assert_runs_on_tpu(q, session)


def test_get_json_object_objects_and_wildcard(session, cpu_session):
    from spark_rapids_tpu.ops.json_fns import GetJsonObject

    def q(s):
        return _jdf(s).select(
            GetJsonObject(col("j"), lit("$.b")).alias("obj"),
            GetJsonObject(col("j"), lit("$.arr[*]")).alias("w"))

    got = q(session).collect()
    assert got == q(cpu_session).collect()
    assert got[0][0] == '{"c":"x"}'            # objects -> compact json
    assert got[4][1] == "[1,2,3]"              # wildcard collects


def test_json_tuple(session, cpu_session):
    from spark_rapids_tpu.ops.json_fns import json_tuple

    def q(s):
        return _jdf(s).select(*json_tuple(col("j"), "a", "b"))

    got = q(session).collect()
    assert got == q(cpu_session).collect()
    assert got[0][0] == "1" and got[1][0] == "str"


# -- hive hash ---------------------------------------------------------------

def test_hive_hash_known_vectors(session, cpu_session):
    """Java oracle: "Spark".hashCode() == 80085693 (hand-folded
    31*h + c over S,p,a,r,k); int passes through; long folds hi^lo;
    multi-column folds 31*h + f."""
    from spark_rapids_tpu.ops.hashfns import HiveHash, _hive_string_hash
    h = 0
    for ch in "Spark":
        h = (h * 31 + ord(ch)) & 0xFFFFFFFF
    assert _hive_string_hash("Spark") == h == 80085693
    assert _hive_string_hash("") == 0

    def q(s):
        df = s.create_dataframe({
            "s": np.array(["Spark", "", None], dtype=object),
            "i": np.array([42, -1, 7], dtype=np.int64)})
        return df.select(HiveHash(col("s")).alias("hs"),
                         HiveHash(col("i")).alias("hi"),
                         HiveHash(col("s"), col("i")).alias("hsi"))

    got = q(session).collect()
    assert got == q(cpu_session).collect()
    assert got[0][0] == 80085693
    assert got[0][1] == 42            # long 42: 42 ^ 0 = 42
    assert got[2][0] == 0             # null -> 0
    w = (31 * 80085693 + 42) & 0xFFFFFFFF
    w = w - (1 << 32) if w >= (1 << 31) else w
    assert got[0][2] == w  # int32 wraparound
    assert_runs_on_tpu(q, session)


# -- conv --------------------------------------------------------------------

def test_conv(session, cpu_session):
    from spark_rapids_tpu.ops.strings import Conv

    def q(s):
        df = s.create_dataframe({"x": np.array(
            ["100", "ff", "-10", "zz", "", "12junk"], dtype=object)})
        return df.select(
            Conv(col("x"), lit(2), lit(10)).alias("b2"),
            Conv(col("x"), lit(16), lit(10)).alias("b16"),
            Conv(col("x"), lit(10), lit(16)).alias("b10_16"))

    got = q(session).collect()
    assert got == q(cpu_session).collect()
    assert got[0] == ("4", "256", "64")      # "100" in bases 2/16/10
    assert got[1][1] == "255"                # ff hex
    assert got[3] == ("0", "0", "0")         # no valid digit -> "0" (Hive)
    assert got[4] == (None, None, None)      # empty -> null
    assert got[5][0] == "1"                  # truncates at first bad char
    # negative wraps to uint64 for positive toBase (Hive semantics)
    assert got[2][1] == str((1 << 64) - 16)


# -- ceil/floor at scale -----------------------------------------------------

def test_round_ceil_floor(session, cpu_session):
    from spark_rapids_tpu.ops.math import RoundCeil, RoundFloor

    def q(s):
        df = s.create_dataframe({"x": np.array([1.234, -1.234, 5.0])})
        return df.select(RoundCeil(col("x"), lit(1)).alias("c"),
                         RoundFloor(col("x"), lit(1)).alias("f"))

    got = q(session).collect()
    want = q(cpu_session).collect()
    for g, w in zip(got, want):
        for a, b in zip(g, w):
            assert abs(a - b) < 1e-9
    assert abs(got[0][0] - 1.3) < 1e-9 and abs(got[0][1] - 1.2) < 1e-9
    assert abs(got[1][0] - (-1.2)) < 1e-9 and abs(got[1][1] - (-1.3)) < 1e-9


# -- unix_timestamp parsing --------------------------------------------------

def test_unix_timestamp_parsing(session, cpu_session):
    from spark_rapids_tpu.ops.datetime import GetTimestamp, UnixTimestamp

    def q(s):
        df = s.create_dataframe({"t": np.array(
            ["2024-03-10 12:34:56", "1970-01-01 00:00:00", "oops", None],
            dtype=object)})
        return df.select(UnixTimestamp(col("t")).alias("u"),
                         GetTimestamp(col("t")).alias("ts"))

    got = q(session).collect()
    assert got == q(cpu_session).collect()
    want = int(dt.datetime(2024, 3, 10, 12, 34, 56,
                           tzinfo=dt.timezone.utc).timestamp())
    assert got[0][0] == want
    assert got[1][0] == 0
    assert got[2][0] is None and got[3][0] is None
    assert_runs_on_tpu(q, session)


def test_unix_timestamp_custom_format(session, cpu_session):
    from spark_rapids_tpu.ops.datetime import UnixTimestamp

    def q(s):
        df = s.create_dataframe({"t": np.array(["10/03/2024"], dtype=object)})
        return df.select(UnixTimestamp(col("t"), lit("dd/MM/yyyy")).alias("u"))

    got = q(session).collect()
    assert got == q(cpu_session).collect()
    assert got[0][0] == int(dt.datetime(2024, 3, 10,
                                        tzinfo=dt.timezone.utc).timestamp())


# -- time_add ----------------------------------------------------------------

def test_time_add(session, cpu_session):
    from spark_rapids_tpu.ops.datetime import TimeAdd
    base = dt.datetime(2024, 1, 1, 0, 0, 0)

    def q(s):
        df = s.create_dataframe({"t": [base]}, {"t": T.TIMESTAMP})
        return df.select(
            TimeAdd(col("t"), lit(3_600_000_000)).alias("plus_hour"))

    got = q(session).collect()
    assert got == q(cpu_session).collect()
    assert got[0][0] == base + dt.timedelta(hours=1)


# -- InSet -------------------------------------------------------------------

def test_inset(session, cpu_session):
    from spark_rapids_tpu.ops.predicates import InSet

    def q(s):
        df = s.create_dataframe({"x": np.array([1, 5, 9], dtype=np.int64)})
        return df.filter(InSet(col("x"), [lit(1), lit(9), lit(100)]))

    got = sorted(q(session).collect())
    assert got == sorted(q(cpu_session).collect())
    assert [r[0] for r in got] == [1, 9]
    assert_runs_on_tpu(q, session)


def test_conv_saturation_and_signed(cpu_session):
    """Hive NumberConverter corners (review fixes): unsigned-64
    saturation, signed output for negative toBase, '+' not a sign."""
    from spark_rapids_tpu.ops.strings import Conv
    c = Conv._convert
    assert c("99999999999999999999", 10, 16) == "FFFFFFFFFFFFFFFF"
    assert c("99999999999999999999", 10, -16) == "-1"
    assert c("+15", 10, 16) == "0"     # '+' stops parsing at value 0
    assert c(" 15", 10, 16) == "0"     # whitespace is not trimmed
    assert c("-10", 10, 10) == str((1 << 64) - 10)  # wraps unsigned
    assert c("-10", 10, -10) == "-10"  # signed output


def test_hive_hash_non_ascii_and_timestamp(session, cpu_session):
    """Review fixes: UTF-8 signed-byte fold + Hive timestamp layout."""
    from spark_rapids_tpu.ops.hashfns import (
        HiveHash,
        _hive_string_hash,
        _hive_timestamp_value,
    )
    # 'é' = UTF-8 C3 A9 -> (-61)*31 + (-87) = -1978
    assert _hive_string_hash("é") == -1978
    assert _hive_timestamp_value(1_000_000) == 1 << 30

    def q(s):
        df = s.create_dataframe(
            {"s": np.array(["café", "é"], dtype=object),
             "t": np.array([1_000_000, 1_500_000], dtype=np.int64)},
            dtypes={"s": T.STRING, "t": T.TIMESTAMP})
        return df.select(HiveHash(col("s")).alias("hs"),
                         HiveHash(col("t")).alias("ht"))

    got = q(session).collect()
    assert got == q(cpu_session).collect()
    assert got[1][0] == -1978
    assert got[0][1] == (1 << 30) ^ 0  # (1<<30) fits in low word


def test_unix_timestamp_translated_format(session, cpu_session):
    """yyyyMMdd translates generically now (review fix)."""
    from spark_rapids_tpu.ops.datetime import UnixTimestamp

    def q(s):
        df = s.create_dataframe({"t": np.array(["20200101"], dtype=object)})
        return df.select(UnixTimestamp(col("t"), lit("yyyyMMdd")).alias("u"))

    got = q(session).collect()
    assert got == q(cpu_session).collect()
    assert got[0][0] == 1577836800


def test_unix_timestamp_unsupported_format_raises(cpu_session):
    from spark_rapids_tpu.errors import ColumnarProcessingError
    from spark_rapids_tpu.ops.datetime import UnixTimestamp
    df = cpu_session.create_dataframe(
        {"t": np.array(["x"], dtype=object)})
    with pytest.raises(ColumnarProcessingError, match="not supported"):
        df.select(UnixTimestamp(col("t"),
                                lit("yyyy-MM-dd'T'HH:mm:ssZ")).alias("u")
                  ).collect()


def test_get_json_object_per_row_path(cpu_session):
    """Non-literal path evaluates per row on the CPU path (review fix)."""
    from spark_rapids_tpu.ops.json_fns import GetJsonObject
    df = cpu_session.create_dataframe({
        "j": np.array(['{"a":1,"b":2}', '{"a":3,"b":4}'], dtype=object),
        "p": np.array(["$.a", "$.b"], dtype=object)})
    rows = df.select(GetJsonObject(col("j"), col("p")).alias("v")).collect()
    assert rows == [("1",), ("4",)]
