"""Out-of-core execution under a hard HBM budget (ISSUE 15).

The memory fault domain end to end: the MemoryArbiter's hard budget
(runtime/memory.py) enforced at every device landing, spill/unspill
round trips staying bit-identical, chunked scans, the CRC footer on
disk-tier spill frames, the injected ``mem.*`` ladder walk (retry ->
split-and-retry -> chunked re-execution -> per-op CPU demotion) with
explain()/incident-bundle visibility, admission consulting the
arbiter's live occupancy, and arbiter accounting exactness under
concurrency.
"""

import glob
import json
import os
import threading

import numpy as np
import pytest

from spark_rapids_tpu import functions as F
from spark_rapids_tpu.errors import SpillCorruptionError
from spark_rapids_tpu.obs.metrics import scopes_snapshot
from spark_rapids_tpu.ops.expr import col
from spark_rapids_tpu.runtime.faults import CIRCUIT_BREAKER, FAULTS, RECOVERY
from spark_rapids_tpu.runtime.health import HEALTH
from spark_rapids_tpu.runtime.memory import (
    MEMORY,
    MemoryArbiter,
    estimate_device_nbytes,
    forced_chunking,
    scan_chunks,
)
from spark_rapids_tpu.runtime.spill import BufferCatalog, SpillableDeviceTable
from spark_rapids_tpu.columnar import DeviceTable, HostTable
from spark_rapids_tpu.session import TpuSession

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _clean_process_state():
    """Every test leaves the process-wide fault/health/arbiter state
    the way it found it (the file rides tier-1 between other suites)."""
    yield
    from spark_rapids_tpu.runtime.retry import RMM_TPU
    FAULTS.disarm()
    CIRCUIT_BREAKER.reset()
    HEALTH.reset()
    MEMORY.reset()
    RMM_TPU.clear()
    # tests squeeze the host tier to 4KB over a per-test tmp dir —
    # later suites must get the default catalog back, not a tier
    # pointed at a removed directory
    BufferCatalog.reset()


def _mem_scope():
    return dict(scopes_snapshot().get("memory", {}))


def _data(n=20000, seed=0):
    rng = np.random.default_rng(seed)
    return {"k": rng.integers(0, 50, n).astype(np.int64),
            "v": rng.random(n),
            "s": np.array(["a", "bb", "ccc"], dtype=object)[
                rng.integers(0, 3, n)]}


def _agg(s, data, nb=6):
    return sorted(s.create_dataframe(data, num_batches=nb)
                  .group_by("k")
                  .agg(F.sum(col("v")).alias("sv"),
                       F.count(col("s")).alias("c"))
                  .collect())


def _cpu():
    return TpuSession({"spark.rapids.sql.enabled": "false"})


#: the out-of-core JOIN workload: the build side stays resident across
#: streaming probe chunks, so a budget below (build + pipeline) forces
#: the build through spill/unspill cycles between batches — the
#: textbook out-of-core hash join. Grouping key is LOW cardinality so
#: the merge table fits any budget (a high-cardinality grouping's
#: merge is legitimately output-sized).
def _join_data(seed=0):
    rng = np.random.default_rng(seed)
    left = {"k": rng.integers(0, 3000, 20000).astype(np.int64),
            "g": rng.integers(0, 40, 20000).astype(np.int64),
            "v": rng.random(20000)}
    right = {"k": np.arange(3000).astype(np.int64),
             "w": rng.random(3000), "x": rng.random(3000),
             "y": rng.random(3000)}
    return left, right


def _join_q(s, left, right, nb=4):
    ldf = s.create_dataframe(left, num_batches=nb)
    rdf = s.create_dataframe(right)
    return sorted(ldf.join(rdf, on=["k"], how="inner").group_by("g")
                  .agg(F.sum(col("v")).alias("sv"),
                       F.sum(col("w")).alias("sw"))
                  .collect())


_BUDGET = 160 * 1024
_SHARE = int(_BUDGET * 0.1)


def _budget_conf(extra=None):
    conf = {"spark.rapids.memory.device.budgetBytes": str(_BUDGET),
            "spark.rapids.memory.device.scanChunkFraction": "0.1"}
    conf.update(extra or {})
    return conf


def _shape_baseline(left, right):
    """The same-shape baseline: a PLAIN session under forced_chunking
    at the budget's chunk share executes the exact batch structure the
    budgeted run takes — with zero enforcement — so the budgeted run
    must be BITWISE identical to it (spills/unspills/retries must not
    change one bit; the scale harness's contract)."""
    plain = TpuSession()
    with forced_chunking(_SHARE):
        return _join_q(plain, left, right)


# ---------------------------------------------------------------------------
# budget-enforced spill/unspill bit-identity
# ---------------------------------------------------------------------------


def test_budget_enforced_spill_unspill_bit_identity(tmp_path):
    """A 160KB budget below (build + pipeline): the join build side
    rides spill/unspill cycles between probe chunks (host tier
    squeezed to 4KB so the DISK tier and its CRC footers see traffic)
    — and the result is BITWISE identical to the same-shape baseline:
    enforcement's spills, unspills and evictions changed nothing."""
    left, right = _join_data()
    BufferCatalog.reset(host_limit_bytes=4096, disk_dir=str(tmp_path))
    before = _mem_scope()
    s = TpuSession(_budget_conf())
    got = _join_q(s, left, right)
    moved = {k: v - before.get(k, 0) for k, v in _mem_scope().items()}
    assert moved.get("spillBytes", 0) > 0, moved
    assert moved.get("unspills", 0) > 0, moved
    assert moved.get("scanChunks", 0) > 0, moved
    want = _shape_baseline(left, right)
    assert got == want  # bitwise: sorted rows of python-native values


def test_chunked_scan_identity_vs_unchunked():
    """Chunked landings compute the same ANSWER as one batch (row
    multiset; f64 merge order may move final ulps — the bitwise
    contract runs against the same-shape baseline above), and the
    chunked run reports its chunks."""
    data = _data(8000, seed=3)
    plain = TpuSession()
    want = _agg(plain, data, nb=1)
    before = _mem_scope()
    budgeted = TpuSession({
        # chunk share ~16KB: an 8k-row 3-column table must split
        "spark.rapids.memory.device.budgetBytes": str(256 * 1024),
        "spark.rapids.memory.device.scanChunkFraction": "0.0625",
    })
    got = _agg(budgeted, data, nb=1)
    assert len(got) == len(want)
    for g, w in zip(got, want):
        assert g[0] == w[0] and g[2] == w[2], (g, w)
        assert abs(g[1] - w[1]) <= 1e-9 * max(1.0, abs(w[1])), (g, w)
    moved = {k: v - before.get(k, 0) for k, v in _mem_scope().items()}
    assert moved.get("scanChunks", 0) > 1, moved
    # metric surfaced on the scan exec too
    assert "scanChunks" in budgeted.last_metrics()
    # and the bitwise contract against the SAME chunk structure
    share = int(256 * 1024 * 0.0625)
    with forced_chunking(share):
        same_shape = _agg(plain, data, nb=1)
    assert got == same_shape


def test_scan_chunks_respects_forced_override():
    h = HostTable.from_pydict(_data(4000, seed=4))
    MEMORY.reset()
    assert scan_chunks(h) == [h]  # HBM-sized default budget: no chunking
    est = estimate_device_nbytes(h)
    with forced_chunking(est // 4):
        chunks = scan_chunks(h)
    assert len(chunks) > 1
    assert sum(c.num_rows for c in chunks) == h.num_rows
    # each chunk fits the forced share (bucket-padded estimate)
    for c in chunks:
        assert estimate_device_nbytes(c) <= est // 2


# ---------------------------------------------------------------------------
# CRC footer on disk spill frames
# ---------------------------------------------------------------------------


def test_crc_corrupt_unspill_raises_typed(tmp_path):
    """Bit-rot on a disk-tier spill frame is CAUGHT by the CRC footer:
    unspill raises typed SpillCorruptionError (a KernelCrashError —
    the replay machinery re-lands from the scan cache), counts the
    corruption, and drops the frame instead of serving wrong bytes."""
    cat = BufferCatalog.reset(disk_dir=str(tmp_path))
    dt = DeviceTable.from_host(HostTable.from_pydict(_data(500, seed=5)))
    sb = SpillableDeviceTable(dt, cat)
    del dt
    sb.spill_to_host()
    sb.spill_to_disk()
    path = sb._disk_path
    raw = open(path, "rb").read()
    flipped = raw[:8] + bytes([raw[8] ^ 0xFF]) + raw[9:]
    open(path, "wb").write(flipped)
    before = _mem_scope()
    with pytest.raises(SpillCorruptionError):
        sb.get()
    assert not os.path.exists(path)  # corrupt frame dropped, not kept
    moved = {k: v - before.get(k, 0) for k, v in _mem_scope().items()}
    assert moved.get("spillCorruptions", 0) == 1
    sb.release()


def test_injected_unspill_corruption_replays_bit_identical(tmp_path):
    """End to end: a seeded ``mem.unspill`` corruption under a budget
    that forces disk-tier round trips — the query replays and
    completes bit-identical to the same-shape baseline (re-landed
    from the scan source), never serving the corrupt frame."""
    left, right = _join_data(seed=6)
    BufferCatalog.reset(host_limit_bytes=4096, disk_dir=str(tmp_path))
    replays_before = RECOVERY.snapshot()["query_replays"]
    before = _mem_scope()
    s = TpuSession(_budget_conf({
        "spark.rapids.sql.runtimeFallback.enabled": "true",
        "spark.rapids.test.faults": "mem.unspill:corrupt:1:11",
    }))
    got = _join_q(s, left, right)
    moved = {k: v - before.get(k, 0) for k, v in _mem_scope().items()}
    assert moved.get("spillCorruptions", 0) >= 1, moved
    assert FAULTS.counters().get("mem.unspill", 0) >= 1
    assert RECOVERY.snapshot()["query_replays"] > replays_before
    FAULTS.disarm()
    assert got == _shape_baseline(left, right)


# ---------------------------------------------------------------------------
# the memory degradation ladder
# ---------------------------------------------------------------------------


def test_memory_ladder_unit_walk(tmp_path):
    """on_memory_pressure rung by rung: retry -> chunk -> cpu_demote
    (attributed) / abort (unattributed), one incident bundle per
    action, and any completed query resets the ladder."""
    from spark_rapids_tpu.conf import RapidsConf
    from spark_rapids_tpu.errors import FatalDeviceOOM
    conf = RapidsConf({
        "spark.rapids.obs.flightRecorder.dir": str(tmp_path)})
    HEALTH.reset()
    exc = FatalDeviceOOM("device OOM persisted after 2 spill-retries")
    assert HEALTH.on_memory_pressure(exc, conf) == "retry"
    assert HEALTH.on_memory_pressure(exc, conf) == "chunk"
    # unattributed third escalation: nothing to demote -> abort
    assert HEALTH.on_memory_pressure(exc, conf) == "abort"
    exc.fault_op = "SomeOp"
    assert HEALTH.on_memory_pressure(exc, conf) == "cpu_demote"
    assert CIRCUIT_BREAKER.demotion_reason("SomeOp") is not None
    snap = HEALTH.memory_snapshot()
    assert snap["memoryPressureEvents"] == 4
    assert snap["memoryChunkedReexecutions"] == 1
    assert snap["memoryCpuDemotions"] == 1
    bundles = [json.load(open(p))
               for p in glob.glob(str(tmp_path / "incident-*.json"))]
    actions = sorted(b["action"] for b in bundles
                     if b["kind"] == "memory.ladder")
    assert actions == ["abort", "chunk", "cpu_demote", "retry"]
    # every bundle embeds the arbiter snapshot + memory ladder state
    assert all("memory" in b and "memoryLadder" in b["health"]
               for b in bundles)
    # ANY success resets the consecutive count
    HEALTH.note_success()
    assert HEALTH.memory_snapshot()["memoryConsecutive"] == 0


def test_memory_ladder_end_to_end_cpu_demotion(tmp_path):
    """A sustained budget squeeze (every reservation refused for 10
    grants) walks the full ladder end to end: spill-retry and
    split-and-retry inside the retry framework, then retry ->
    chunked re-execution -> per-op CPU demotion — and the query STILL
    completes with the right answer, the demotion visible in
    explain()-style surfaces (breaker reason + event record) and one
    incident bundle per ladder action."""
    data = {"k": [1, 2, 3] * 100, "v": [1.0] * 300}
    s = TpuSession({
        "spark.rapids.test.faults": "mem.reserve:oom:10:3",
        "spark.rapids.sql.runtimeFallback.enabled": "true",
        "spark.rapids.obs.flightRecorder.dir": str(tmp_path),
        "spark.rapids.sql.eventLog.enabled": "true",
        "spark.rapids.sql.eventLog.dir": str(tmp_path / "ev"),
    })
    got = sorted(s.create_dataframe(data).group_by("k")
                 .agg(F.sum(col("v")).alias("sv")).collect())
    assert got == [(1, 100.0), (2, 100.0), (3, 100.0)]
    demoted = CIRCUIT_BREAKER.demoted_ops()
    assert demoted, "the ladder never reached the CPU-demotion rung"
    assert any("OOM" in reason or "oom" in reason
               for reason in demoted.values())
    snap = HEALTH.memory_snapshot()
    assert snap["memoryChunkedReexecutions"] >= 1
    assert snap["memoryCpuDemotions"] >= 1
    # incident bundles: >= 1 per ladder action taken
    bundles = [json.load(open(p))
               for p in glob.glob(str(tmp_path / "incident-*.json"))]
    mem_bundles = [b for b in bundles if b["kind"] == "memory.ladder"]
    assert len(mem_bundles) >= snap["memoryPressureEvents"]
    assert {"retry", "chunk", "cpu_demote"} <= {
        b["action"] for b in mem_bundles}
    # the escalation's triggering fault point parses from the cause
    assert any(b.get("faultPoint") == "mem.reserve"
               for b in mem_bundles)
    # event record carries the demotion map (explain() convention)
    rec = s.last_event_record
    assert rec["schema"] == 11
    assert any(op in rec["demotions"] for op in demoted)
    assert rec["oomRetries"] > 0


def test_split_and_retry_under_budget():
    """splitRetries (schema v10) counts the split-and-retry rung: an
    injected SplitAndRetryOOM halves the input and both halves replay,
    the reassembled output bit-identical to the unsplit input."""
    from spark_rapids_tpu.runtime.retry import RMM_TPU, with_retry
    data = _data(2000, seed=7)
    host = HostTable.from_pydict(data)
    dt = DeviceTable.from_host(host)
    before = _mem_scope()
    RMM_TPU.force_split_and_retry_oom(1)
    outs = list(with_retry(dt, lambda d: d.to_host()))
    assert len(outs) == 2  # halved by rows, both halves replayed
    moved = {k: v - before.get(k, 0) for k, v in _mem_scope().items()}
    assert moved.get("splitRetries", 0) >= 1, moved
    merged = HostTable.concat(outs)
    assert merged.to_pydict() == host.to_pydict()


# ---------------------------------------------------------------------------
# admission + arbiter accounting
# ---------------------------------------------------------------------------


def test_admission_probe_consults_arbiter_occupancy():
    """The service's default memory probe reads the arbiter's LIVE
    ledger — bytes accounted outside the spill catalog (plain landed
    tables) gate admission too."""
    from spark_rapids_tpu.service.scheduler import _default_memory_probe
    MEMORY.reset()
    dt = DeviceTable.from_host(HostTable.from_pydict(_data(2000, seed=8)))
    occ = MEMORY.occupancy()
    assert occ > 0
    # nothing registered in the catalog, yet the probe sees the bytes
    assert _default_memory_probe() >= occ
    del dt
    assert MEMORY.occupancy() == 0


def test_admission_forward_progress_escape_pinned():
    """admission.maxDeviceBytes below live occupancy still admits when
    NOTHING is running — the existing forward-progress escape survives
    the arbiter-backed probe."""
    from spark_rapids_tpu.service.scheduler import QueryService
    MEMORY.reset()
    # pin real accounted occupancy far above the gate
    pinned = DeviceTable.from_host(
        HostTable.from_pydict(_data(4000, seed=9)))
    assert MEMORY.occupancy() > 64
    svc = QueryService({
        "spark.rapids.service.admission.maxDeviceBytes": "64",
        "spark.rapids.service.maxConcurrentQueries": "1",
    })
    try:
        df = svc.session.create_dataframe({"a": [1, 2, 3]})
        h = svc.submit(df)
        out = h.result(timeout=30)
        assert out.num_rows == 3
        assert svc.health()["state"] == "HEALTHY"
        assert "memory" in svc.health()
        assert svc.health()["memory"]["occupancyBytes"] >= 0
    finally:
        svc.shutdown()
        del pinned


def test_arbiter_accounting_exact_under_threads():
    """Reserve/account/release exactness under 4 threads: occupancy
    returns to zero, the peak never exceeds the budget when every
    grant goes through reserve, and no reservation leaks."""
    arb = MemoryArbiter()

    class _Conf:
        def get_entry(self, entry):
            return {"spark.rapids.memory.device.budgetBytes": 1 << 20,
                    "spark.rapids.memory.device.scanChunkFraction":
                        0.25}[entry.key]

    arb.configure(_Conf())
    errors = []

    def worker(seed):
        rng = np.random.default_rng(seed)
        try:
            for _ in range(200):
                n = int(rng.integers(1, 2048))
                r = arb.reserve(n)
                assert arb.occupancy() >= n
                r.release()
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    snap = arb.snapshot()
    assert snap["occupancyBytes"] == 0
    assert snap["reservedBytes"] == 0
    assert 0 < snap["peakBytes"] <= snap["budgetBytes"]
    assert snap["budgetViolations"] == 0


def test_reserve_refuses_when_spilling_cannot_make_room():
    """A reservation past the budget with nothing spillable raises
    RetryOOM (the retry framework's signal), not a silent grant."""
    from spark_rapids_tpu.errors import RetryOOM
    arb = MemoryArbiter()

    class _Conf:
        def get_entry(self, entry):
            return {"spark.rapids.memory.device.budgetBytes": 4096,
                    "spark.rapids.memory.device.scanChunkFraction":
                        0.25}[entry.key]

    arb.configure(_Conf())
    r = arb.reserve(4000)
    with pytest.raises(RetryOOM):
        arb.reserve(4000)
    r.release()
    arb.reserve(4000).release()  # room again once the first released


# ---------------------------------------------------------------------------
# event-log schema v10
# ---------------------------------------------------------------------------


def test_device_budget_flag_validation():
    """validate_flags rejects the --device-budget combinations the
    memory harness does not implement, naming the supported modes."""
    from types import SimpleNamespace

    import scale_test as st

    def args(**kw):
        base = dict(mesh=0, hosts=0, streaming=False, concurrency=0,
                    service_faults=False,
                    cpu_baseline=False, require_tpu=False, chaos=False,
                    device_budget=0)
        base.update(kw)
        return SimpleNamespace(**base)

    st.validate_flags(args(device_budget=4_000_000))  # supported
    st.validate_flags(args(device_budget=4_000_000, chaos=True))
    # planes COMPOSE now: budget x hosts / budget x concurrency route
    # to the fleet closure instead of being rejected
    st.validate_flags(args(device_budget=4_000_000, hosts=2))
    st.validate_flags(args(device_budget=4_000_000, concurrency=4))
    for bad in (args(device_budget=100),
                args(device_budget=4_000_000, mesh=8),
                args(device_budget=4_000_000, cpu_baseline=True),
                args(device_budget=4_000_000, require_tpu=True)):
        with pytest.raises(SystemExit) as ei:
            st.validate_flags(bad)
        assert "supported modes" in str(ei.value)


def test_event_log_v10_memory_fields(tmp_path):
    """spillBytes/unspills ride the record as per-query memory-scope
    deltas; budgetPeak reads the arbiter's peak."""
    left, right = _join_data(seed=10)
    BufferCatalog.reset(host_limit_bytes=4096, disk_dir=str(tmp_path))
    s = TpuSession(_budget_conf({
        "spark.rapids.sql.eventLog.enabled": "true",
        "spark.rapids.sql.eventLog.dir": str(tmp_path / "ev"),
    }))
    _join_q(s, left, right)
    rec = s.last_event_record
    assert rec["schema"] == 11
    assert rec["spillBytes"] > 0
    assert rec["unspills"] > 0
    assert rec["budgetPeak"] > 0
    # and the tools read them back (profile Memory line)
    from spark_rapids_tpu.tools.report import build_profile, render_profile
    prof = build_profile([rec])
    assert prof["memory"]["spillBytes"] > 0
    assert "Memory:" in render_profile(prof)
