"""Static-analysis layer tests (ISSUE 2 tentpole).

Four layers:
  * golden suite: the TPC-H q1-q22 corpus (DSL + SQL, AQE on/off)
    converts and verifies CLEAN in error mode — the regression pin that
    every future plan/overrides change runs under;
  * repo lint + registry audit exit clean on the repo itself, and the
    committed SUPPORTED_OPS.md / CONFIGS.md are byte-identical to their
    generators;
  * one NEGATIVE test per lint rule (every id in diagnostics.RULES):
    a deliberately broken plan/registry/source fragment produces exactly
    that rule id at the expected path;
  * pins for the real violations the tooling surfaced (decimal %
    unregistered, avg/stddev over decimal in unscaled units, dec128 ->
    double cast crash in the streaming average merge).
"""

import ast

import numpy as np
import pytest

from spark_rapids_tpu import functions as F
from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar import HostColumn, HostTable
from spark_rapids_tpu.conf import RapidsConf
from spark_rapids_tpu.lint.diagnostics import RULES
from spark_rapids_tpu.lint.plan_verifier import (
    verify_converted,
    verify_meta,
)
from spark_rapids_tpu.ops.expr import BoundReference, Expression, Literal, col
from spark_rapids_tpu.plan import from_host_table
from spark_rapids_tpu.plan import nodes as P
from spark_rapids_tpu.session import TpuSession


def _scan_exec(names=("a",), dtypes=(T.LONG,)):
    from spark_rapids_tpu.execs.basic import TpuScanExec
    cols = [HostColumn(dt, np.arange(3, dtype=np.int64).astype(
        dt.np_dtype if not isinstance(dt, T.StringType) else np.int64))
        for dt in dtypes]
    return TpuScanExec([HostTable(list(names), cols)])


def _wrap(exec_):
    from spark_rapids_tpu.execs.base import DeviceToHost
    return DeviceToHost(exec_)


def _ids(diags):
    return {d.rule_id for d in diags}


def _find(diags, rule_id):
    hits = [d for d in diags if d.rule_id == rule_id]
    assert hits, f"no {rule_id} diagnostic in {[str(d) for d in diags]}"
    return hits


# ---------------------------------------------------------------------------
# golden suite: q1-q22 x (dsl, sql) x (aqe on/off) verifies clean
# ---------------------------------------------------------------------------


def test_golden_suite_plans_verify_clean():
    """The whole TPC-H corpus converts with zero diagnostics — the
    regression pin for 'the suite lints clean' (satellite 1)."""
    from spark_rapids_tpu.lint.golden import verify_golden_plans
    diags = verify_golden_plans(scale_factor=0.002)
    assert diags == [], [str(d) for d in diags]


def test_golden_corpus_is_q1_to_q22_in_both_forms():
    from spark_rapids_tpu.lint.golden import _load_scale_test, golden_tables
    scale_test = _load_scale_test()  # repo root may not be on sys.path
    tables = golden_tables(0.002)
    s = TpuSession()
    dsl = scale_test.build_queries(s, tables)
    sql = scale_test.build_sql_queries(s, tables)
    want = {f"q{i}" for i in range(1, 23)}
    assert set(dsl) == want
    assert set(sql) == want


def test_repo_lints_clean():
    from spark_rapids_tpu.lint.repo_lint import lint_repo
    diags = lint_repo()
    assert diags == [], [str(d) for d in diags]


def test_registry_audit_clean():
    from spark_rapids_tpu.lint.registry_audit import audit_registry
    diags = audit_registry()
    assert diags == [], [str(d) for d in diags]


def test_committed_docs_are_byte_identical_to_generators():
    """Drift gate: SUPPORTED_OPS.md and CONFIGS.md must be regenerated
    (python -m spark_rapids_tpu.lint --write-docs) whenever a registry
    changes."""
    import os

    import spark_rapids_tpu
    from spark_rapids_tpu.conf import generate_docs
    from spark_rapids_tpu.lockorder import generate_locks_md
    from spark_rapids_tpu.overrides.docs import generate_supported_ops
    root = os.path.dirname(os.path.dirname(
        os.path.abspath(spark_rapids_tpu.__file__)))
    with open(os.path.join(root, "SUPPORTED_OPS.md")) as f:
        assert f.read() == generate_supported_ops()
    with open(os.path.join(root, "CONFIGS.md")) as f:
        assert f.read() == generate_docs()
    with open(os.path.join(root, "LOCKS.md")) as f:
        assert f.read() == generate_locks_md()


def test_cli_lists_every_rule(capsys):
    from spark_rapids_tpu.lint.__main__ import main
    assert main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rid in RULES:
        assert rid in out


def test_sessions_run_verifier_in_error_mode():
    """conftest injects planVerify.mode=error into every test session
    (the assert-on-fallback analog)."""
    from spark_rapids_tpu.conf import PLAN_VERIFY_MODE
    s = TpuSession()
    assert str(s.conf.get_entry(PLAN_VERIFY_MODE)).lower() == "error"
    # ...while the production default stays off
    assert RapidsConf().get_entry(PLAN_VERIFY_MODE) == "off"


# ---------------------------------------------------------------------------
# negative tests: plan verifier rules
# ---------------------------------------------------------------------------


def test_pv_schema_pass_through_divergence():
    from spark_rapids_tpu.execs.basic import TpuLimitExec
    ex = TpuLimitExec(_scan_exec(), 5)
    ex.output_schema = lambda: [("other", T.INT)]  # break the contract
    diags = _find(verify_converted(_wrap(ex)), "PV-SCHEMA")
    assert any("pass-through" in d.message and "Limit" in d.path
               for d in diags), [str(d) for d in diags]


def test_pv_schema_malformed_entry():
    from spark_rapids_tpu.execs.basic import TpuLimitExec
    ex = TpuLimitExec(_scan_exec(), 5)
    ex.output_schema = lambda: [("a", "not-a-datatype")]
    diags = _find(verify_converted(_wrap(ex)), "PV-SCHEMA")
    assert any("malformed" in d.message for d in diags)


def test_pv_transition_device_exec_over_host_node():
    from spark_rapids_tpu.execs.basic import TpuLimitExec
    host = P.RangeNode(0, 10)
    ex = TpuLimitExec(host, 5)  # raw PlanNode under a device exec
    diags = _find(verify_converted(_wrap(ex)), "PV-TRANSITION")
    d = diags[0]
    assert "without a HostToDevice transition" in d.message
    assert d.path == "DeviceToHost.Limit"


def test_pv_transition_host_node_over_device_exec():
    f = P.Filter(P.RangeNode(0, 10), col("id") > Literal(3))
    f.children = (_scan_exec(("id",), (T.LONG,)),)  # device exec, no adapter
    diags = _find(verify_converted(f), "PV-TRANSITION")
    assert "InputAdapter(DeviceToHost)" in diags[0].message
    assert diags[0].path == "Filter"  # reported at the consuming parent
    assert "Scan" in diags[0].message


def test_pv_exchange_hash_without_keys():
    from spark_rapids_tpu.execs.exchange import TpuShuffleExchangeExec
    ex = TpuShuffleExchangeExec(_scan_exec(), "hash", 4, [], RapidsConf())
    diags = _find(verify_converted(_wrap(ex)), "PV-EXCHANGE")
    assert "hash partitioning requires keys" in diags[0].message
    assert "ShuffleExchange" in diags[0].path


def test_pv_exchange_key_outside_child_output():
    from spark_rapids_tpu.execs.exchange import TpuShuffleExchangeExec
    ex = TpuShuffleExchangeExec(
        _scan_exec(), "hash", 4, [BoundReference(7, T.LONG)], RapidsConf())
    diags = _find(verify_converted(_wrap(ex)), "PV-EXCHANGE")
    assert any("ordinal 7" in d.message for d in diags)


def test_pv_boundref_ordinal_and_type():
    from spark_rapids_tpu.execs.basic import TpuProjectExec
    ex = TpuProjectExec(_scan_exec(("a",), (T.LONG,)),
                        [BoundReference(3, T.LONG)], ["x"])
    diags = _find(verify_converted(_wrap(ex)), "PV-BOUNDREF")
    assert "ordinal 3" in diags[0].message
    assert "Project" in diags[0].path

    ex2 = TpuProjectExec(_scan_exec(("a",), (T.LONG,)),
                         [BoundReference(0, T.STRING)], ["x"])
    diags2 = _find(verify_converted(_wrap(ex2)), "PV-BOUNDREF")
    assert "typed string" in diags2[0].message


class _UnregisteredExpr(Expression):
    def __init__(self, child):
        self.children = (child,)

    @property
    def data_type(self):
        return T.LONG


def test_pv_typesig_unregistered_expression_on_device():
    from spark_rapids_tpu.execs.basic import TpuProjectExec
    ex = TpuProjectExec(_scan_exec(("a",), (T.LONG,)),
                        [_UnregisteredExpr(BoundReference(0, T.LONG))],
                        ["x"])
    diags = _find(verify_converted(_wrap(ex)), "PV-TYPESIG")
    assert "_UnregisteredExpr" in diags[0].message
    assert "ran on device anyway" in diags[0].message


def test_pv_decimal_result_type_divergence():
    from spark_rapids_tpu.execs.basic import TpuProjectExec
    from spark_rapids_tpu.ops.decimal import DecimalAdd
    e = DecimalAdd(BoundReference(0, T.DecimalType(10, 2)),
                   BoundReference(1, T.DecimalType(10, 2)))
    e._result = T.DecimalType(7, 1)  # tamper: violates the promotion rule
    ex = TpuProjectExec(
        _scan_exec(("a", "b"), (T.DecimalType(10, 2), T.DecimalType(10, 2))),
        [e], ["x"])
    diags = _find(verify_converted(_wrap(ex)), "PV-DECIMAL")
    assert "promotion rule gives decimal(11,2)" in diags[0].message


class _BadNotNull(Expression):
    nullable = False  # plain class attr shadowing the derived property

    def __init__(self, child):
        self.children = (child,)

    @property
    def data_type(self):
        return T.LONG


def test_pv_nullable_plain_attr_over_nullable_child():
    from spark_rapids_tpu.execs.basic import TpuProjectExec
    ex = TpuProjectExec(_scan_exec(("a",), (T.LONG,)),
                        [_BadNotNull(BoundReference(0, T.LONG))], ["x"])
    diags = _find(verify_converted(_wrap(ex)), "PV-NULLABLE")
    assert "_BadNotNull" in diags[0].message
    assert "without overriding the nullable property" in diags[0].message


def test_pv_fallback_empty_reason_and_missing_rule():
    from spark_rapids_tpu.overrides.rules import PlanMeta

    meta = PlanMeta(P.RangeNode(0, 5), RapidsConf())
    meta.reasons = ["   "]
    diags = []
    verify_meta(meta, diags)
    assert any(d.rule_id == "PV-FALLBACK"
               and "empty reason" in d.message for d in diags)

    class _RuleLess(P.PlanNode):
        def output_schema(self):
            return [("x", T.LONG)]

    meta2 = PlanMeta(_RuleLess(), RapidsConf())  # untagged: no reasons
    diags2 = []
    verify_meta(meta2, diags2)
    assert any(d.rule_id == "PV-FALLBACK"
               and "no exec rule" in d.message for d in diags2)


def test_pv_agg_non_aggregate_spec():
    from spark_rapids_tpu.execs.aggregate import TpuHashAggregateExec
    ex = TpuHashAggregateExec(_scan_exec(("a",), (T.LONG,)),
                              [BoundReference(0, T.LONG)],
                              [("bad", Literal(1))], ["k"])
    diags = _find(verify_converted(_wrap(ex)), "PV-AGG")
    assert "not an AggregateFunction" in diags[0].message
    assert "HashAggregate" in diags[0].path


def test_pv_join_key_type_divergence():
    from spark_rapids_tpu.execs.join import TpuJoinExec
    ls = [("a", T.LONG)]
    rs = [("b", T.INT)]
    ex = TpuJoinExec(_scan_exec(("a",), (T.LONG,)),
                     _scan_exec(("b", ), (T.INT,)), "inner",
                     [BoundReference(0, T.LONG)],
                     [BoundReference(0, T.INT)], None, ls, rs)
    diags = _find(verify_converted(_wrap(ex)), "PV-JOIN")
    assert "types diverge: bigint vs int" in diags[0].message

    ex2 = TpuJoinExec(_scan_exec(("a",), (T.LONG,)),
                      _scan_exec(("b",), (T.LONG,)), "sideways",
                      [BoundReference(0, T.LONG)],
                      [BoundReference(0, T.LONG)], None,
                      [("a", T.LONG)], [("b", T.LONG)])
    diags2 = _find(verify_converted(_wrap(ex2)), "PV-JOIN")
    assert "unsupported join type" in diags2[0].message


# ---------------------------------------------------------------------------
# negative tests: registry auditor rules
# ---------------------------------------------------------------------------


def test_ra_conf_orphan_unread_key():
    """RA-CONF-ORPHAN: a declared key no engine source ever reads (by
    string or by its ConfEntry variable) is flagged; wired keys and the
    allowlist are not."""
    from spark_rapids_tpu import conf as C
    from spark_rapids_tpu.lint.registry_audit import (
        _audit_conf_referenced,
        _repo_root,
    )
    key = "spark.rapids.sql.test.orphanedProbeKey"
    C.str_conf(key, "", "negative-test probe: intentionally unread")
    try:
        diags = []
        _audit_conf_referenced(diags, _repo_root(None))
        hits = _find(diags, "RA-CONF-ORPHAN")
        assert any(d.path == key for d in hits)
        # a heavily-wired key is never flagged
        assert not any(d.path == "spark.rapids.sql.eventLog.enabled"
                       for d in hits)
    finally:
        C._REGISTRY.pop(key, None)


def test_ra_unregistered_device_expression():
    import spark_rapids_tpu.ops.math as math_mod
    from spark_rapids_tpu.lint.registry_audit import _audit_unregistered

    class FakeDevExpr(Expression):
        def eval_dev(self, ctx, child_vals, prep):  # device kernel
            raise AssertionError

    FakeDevExpr.__module__ = "spark_rapids_tpu.ops.math"
    FakeDevExpr.__name__ = "FakeDevExpr"
    math_mod.FakeDevExpr = FakeDevExpr
    try:
        diags = []
        _audit_unregistered(diags)
        hits = _find(diags, "RA-UNREGISTERED")
        assert any("FakeDevExpr" in d.path for d in hits)
    finally:
        del math_mod.FakeDevExpr


def test_ra_param_arity_overflow():
    from spark_rapids_tpu.lint.registry_audit import _audit_param_arity
    from spark_rapids_tpu.overrides import rules as R
    from spark_rapids_tpu.overrides.typesig import ExprChecks, TypeSig

    class OneArg(Expression):
        def __init__(self, child):
            self.children = (child,)

    sig = TypeSig(T.LongType)
    R._EXPR_CHECKS[OneArg] = ExprChecks((sig, sig, sig))
    try:
        diags = []
        _audit_param_arity(diags)
        hits = _find(diags, "RA-PARAM-ARITY")
        assert any("OneArg" in d.path and "3 parameter" in d.message
                   for d in hits)
    finally:
        del R._EXPR_CHECKS[OneArg]


def test_ra_kill_switch_orphan():
    from spark_rapids_tpu import conf as C
    from spark_rapids_tpu.lint.registry_audit import _audit_kill_switches
    key = "spark.rapids.sql.exec.NoSuchExecRule"
    C.register_op_kill_switch("exec", "NoSuchExecRule", True, "orphan")
    try:
        diags = []
        _audit_kill_switches(diags)
        hits = _find(diags, "RA-KILL-SWITCH")
        assert any(d.path == key for d in hits)
    finally:
        C._REGISTRY.pop(key, None)


def test_ra_sql_exposure_missing_aggregate(monkeypatch):
    from spark_rapids_tpu.lint import registry_audit as RA
    names = dict(RA._AGG_SQL_NAMES)
    del names["Sum"]
    monkeypatch.setattr(RA, "_AGG_SQL_NAMES", names)
    diags = []
    RA._audit_sql_exposure(diags)
    hits = _find(diags, "RA-SQL-EXPOSURE")
    assert any("Sum" in d.path for d in hits)


def test_ra_essential_metrics_missing():
    from spark_rapids_tpu.execs.base import TpuExec
    from spark_rapids_tpu.lint.registry_audit import audit_exec_metrics_tree

    class HalfMetered(TpuExec):
        pass

    e = HalfMetered()
    e.metrics.add("opTime", 0.1)  # ran, but never counted its output
    diags = []
    audit_exec_metrics_tree(e, diags)
    hits = _find(diags, "RA-ESSENTIAL-METRICS")
    assert any("HalfMetered" in d.path
               and "numOutputRows" in d.message for d in hits)
    # a metric-less ROOT means the observation boundary never installed
    bare = HalfMetered()
    diags2 = []
    audit_exec_metrics_tree(bare, diags2)
    assert any("never installed" in d.message
               for d in _find(diags2, "RA-ESSENTIAL-METRICS"))


def test_ra_doc_drift(tmp_path):
    from spark_rapids_tpu.lint.registry_audit import _audit_doc_drift
    (tmp_path / "SUPPORTED_OPS.md").write_text("stale\n")
    (tmp_path / "LOCKS.md").write_text("stale\n")
    # CONFIGS.md missing entirely
    diags = []
    _audit_doc_drift(diags, str(tmp_path))
    assert any(d.rule_id == "RA-DOC-DRIFT-OPS"
               and "differs from the generator" in d.message for d in diags)
    assert any(d.rule_id == "RA-DOC-DRIFT-CONFIGS"
               and "missing" in d.message for d in diags)
    assert any(d.rule_id == "RA-DOC-DRIFT-LOCKS"
               and "differs from the generator" in d.message for d in diags)


# ---------------------------------------------------------------------------
# negative tests: repo lint rules (synthetic sources)
# ---------------------------------------------------------------------------


def _run_rl(check, rel, src, *extra):
    diags = []
    check(rel, ast.parse(src), *extra, diags)
    return diags


def test_rl_host_sync():
    from spark_rapids_tpu.lint.repo_lint import _check_host_sync
    src = "import jax\nx = jax.device_get(y)\nz = arr.block_until_ready()\n"
    diags = _run_rl(_check_host_sync, "spark_rapids_tpu/execs/foo.py", src)
    hits = _find(diags, "RL-HOST-SYNC")
    assert len(hits) == 2
    assert {d.path.rsplit(":", 1)[1] for d in hits} == {"2", "3"}
    # the import form must not slip past the chain matcher
    imp = "from jax import device_get\nn = device_get(x)\n"
    ihits = _find(_run_rl(_check_host_sync,
                          "spark_rapids_tpu/ops/foo.py", imp),
                  "RL-HOST-SYNC")
    assert len(ihits) == 2  # the import AND the bare call
    # np.asarray/float/int over a provable jax expression sync too...
    dev = ("import jax.numpy as jnp\nimport numpy as np\n"
           "a = np.asarray(jnp.sum(x))\nn = int(jnp.max(y))\n")
    dhits = _find(_run_rl(_check_host_sync,
                          "spark_rapids_tpu/execs/foo.py", dev),
                  "RL-HOST-SYNC")
    assert len(dhits) == 2
    # ...but the sanctioned host_fetch funnel stays clean
    ok = ("from spark_rapids_tpu.dispatch import host_fetch\n"
          "import jax.numpy as jnp\n"
          "n = int(host_fetch(jnp.sum(x)))\n")
    assert _run_rl(_check_host_sync,
                   "spark_rapids_tpu/execs/foo.py", ok) == []
    # the same source OUTSIDE a hot path is fine
    assert _run_rl(_check_host_sync, "spark_rapids_tpu/io/foo.py", src) == []


def test_rl_jnp_scope():
    from spark_rapids_tpu.lint.repo_lint import _check_jnp_scope
    src = "import jax.numpy as jnp\n"
    diags = _run_rl(_check_jnp_scope, "spark_rapids_tpu/sql/analyzer.py", src)
    hits = _find(diags, "RL-JNP-SCOPE")
    assert "outside the device layers" in hits[0].message
    assert _run_rl(_check_jnp_scope,
                   "spark_rapids_tpu/execs/basic.py", src) == []
    # `import jax` + attribute access bypass of the import check
    attr = "import jax\nx = jax.numpy.asarray([1])\n"
    ahits = _find(_run_rl(_check_jnp_scope,
                          "spark_rapids_tpu/sql/analyzer.py", attr),
                  "RL-JNP-SCOPE")
    assert len(ahits) == 1 and "used" in ahits[0].message


def test_rl_conf_key():
    from spark_rapids_tpu.lint.repo_lint import _check_conf_keys
    src = 'k = conf.get("spark.rapids.sql.noSuchKey")\n'
    diags = _run_rl(_check_conf_keys, "spark_rapids_tpu/session.py", src,
                    {"spark.rapids.sql.enabled"})
    hits = _find(diags, "RL-CONF-KEY")
    assert "spark.rapids.sql.noSuchKey" in hits[0].message
    ok = 'k = conf.get("spark.rapids.sql.enabled")\n'
    assert _run_rl(_check_conf_keys, "spark_rapids_tpu/session.py", ok,
                   {"spark.rapids.sql.enabled"}) == []


def test_rl_nondeterminism():
    from spark_rapids_tpu.lint.repo_lint import _check_nondeterminism
    src = ("import time\nt = time.time()\n"
           "import numpy as np\nr = np.random.rand(3)\n"
           "g = np.random.default_rng(0)\n")
    diags = _run_rl(_check_nondeterminism,
                    "spark_rapids_tpu/ops/foo.py", src)
    hits = _find(diags, "RL-NONDETERMINISM")
    assert len(hits) == 2  # time.time + np.random.rand; default_rng is ok
    assert _run_rl(_check_nondeterminism,
                   "spark_rapids_tpu/io/foo.py", src) == []


def test_rl_dead_lambda():
    from spark_rapids_tpu.lint.repo_lint import _check_dead_lambdas
    src = "pn = lambda x: x\nused = lambda y: y\nprint(used(1))\n"
    diags = _run_rl(_check_dead_lambdas, "spark_rapids_tpu/delta/foo.py", src)
    hits = _find(diags, "RL-DEAD-LAMBDA")
    assert len(hits) == 1
    assert "'pn'" in hits[0].message
    assert hits[0].path.endswith(":1")


def test_rl_thread_shared():
    from spark_rapids_tpu.lint.repo_lint import _check_thread_shared
    src = (
        "import threading\n"
        "_CACHE = {}\n"
        "_ITEMS = []\n"
        "_LOCK = threading.Lock()\n"
        "class Mgr:\n"
        "    _instance = None\n"
        "    @classmethod\n"
        "    def get(cls):\n"
        "        cls._instance = Mgr()\n"         # unlocked class attr
        "        return cls._instance\n"
        "def bad(k, v):\n"
        "    _CACHE[k] = v\n"                     # unlocked subscript
        "    _ITEMS.append(v)\n"                  # unlocked mutator
        "def good(k, v):\n"
        "    with _LOCK:\n"
        "        _CACHE[k] = v\n"                 # guarded: clean
        "        _ITEMS.append(v)\n"
        "def rebind():\n"
        "    global _CACHE\n"
        "    _CACHE = {}\n"                       # unlocked global rebind
    )
    diags = _run_rl(_check_thread_shared,
                    "spark_rapids_tpu/runtime/foo.py", src)
    hits = _find(diags, "RL-THREAD-SHARED")
    assert len(hits) == 4, [str(d) for d in hits]
    msgs = " ".join(d.message for d in hits)
    assert "_CACHE[...]" in msgs and "_ITEMS.append" in msgs
    assert "cls._instance (class attribute)" in msgs
    # module-level (import-time) writes and non-scanned dirs are clean
    assert _run_rl(_check_thread_shared,
                   "spark_rapids_tpu/ops/foo.py", src) == []
    init_only = "_REG = {}\n_REG['x'] = 1\n"
    assert _run_rl(_check_thread_shared,
                   "spark_rapids_tpu/shuffle/foo.py", init_only) == []
    # the service package is scanned too
    assert _find(_run_rl(_check_thread_shared,
                         "spark_rapids_tpu/service/foo.py", src),
                 "RL-THREAD-SHARED")
    # the allowlist keys on the CONTAINER name (or the class-attr name),
    # suppressing every finding shape for that state and nothing else
    import spark_rapids_tpu.lint.repo_lint as RL
    saved = dict(RL._THREAD_SHARED_ALLOWLIST)
    try:
        RL._THREAD_SHARED_ALLOWLIST.update({
            "spark_rapids_tpu/runtime/foo.py:_CACHE": "test",
            "spark_rapids_tpu/runtime/foo.py:_instance": "test"})
        left = _find(_run_rl(_check_thread_shared,
                             "spark_rapids_tpu/runtime/foo.py", src),
                     "RL-THREAD-SHARED")
        assert len(left) == 1 and "_ITEMS.append" in left[0].message
    finally:
        RL._THREAD_SHARED_ALLOWLIST.clear()
        RL._THREAD_SHARED_ALLOWLIST.update(saved)


def test_rl_write_commit():
    from spark_rapids_tpu.lint.repo_lint import _check_write_commit
    src = (
        "import os\n"
        "import pyarrow.parquet as pq\n"
        "def write_stuff(t, path):\n"
        "    pq.write_table(t, path)\n"            # outside _write_one
        "    with open(path, 'w') as f:\n"         # write-mode open
        "        f.write('x')\n"
        "    os.replace(path + '.tmp', path)\n"    # promotion
        "def _write_one(tbl, file_path):\n"
        "    pq.write_table(tbl, file_path)\n"     # sanctioned callback
        "    with open(file_path, 'w') as f:\n"
        "        f.write('x')\n"
        "def read_stuff(path):\n"
        "    with open(path) as f:\n"              # default 'r': clean
        "        return f.read()\n"
        "    with open(path, 'rb') as f:\n"
        "        return f.read()\n"
    )
    diags = _run_rl(_check_write_commit, "spark_rapids_tpu/io/foo.py", src)
    hits = _find(diags, "RL-WRITE-COMMIT")
    assert len(hits) == 3, [str(d) for d in hits]
    msgs = " ".join(d.message for d in hits)
    assert "os.replace" in msgs and "committer" in msgs
    # the committer itself and the file cache are exempt, as is
    # anything outside io/
    assert _run_rl(_check_write_commit,
                   "spark_rapids_tpu/io/committer.py", src) == []
    assert _run_rl(_check_write_commit,
                   "spark_rapids_tpu/io/filecache.py", src) == []
    assert _run_rl(_check_write_commit,
                   "spark_rapids_tpu/delta/foo.py", src) == []


def test_rl_mesh_host():
    """RL-MESH-HOST: host materialization inside parallel/ (or the
    placement layer) outside a sanctioned gather point — the static
    guard for 'zero host round-trips between exchanges'."""
    from spark_rapids_tpu.lint.repo_lint import _check_mesh_host
    src = (
        "import jax\n"
        "import numpy as np\n"
        "from spark_rapids_tpu.dispatch import host_fetch\n"
        "def bad(x):\n"
        "    a = np.asarray(x)\n"            # host materialization
        "    b = jax.device_get(x)\n"        # raw device fetch
        "    c = host_fetch(x)\n"            # unsanctioned fetch helper
        "    d = x.block_until_ready()\n"    # device sync
        "    return list(x.addressable_shards)\n"  # per-shard host read
        "def mesh_gather(x):\n"              # allowlisted gather point
        "    return host_fetch(x)\n"
    )
    diags = _run_rl(_check_mesh_host, "spark_rapids_tpu/parallel/foo.py",
                    src)
    hits = _find(diags, "RL-MESH-HOST")
    # 5 in bad() plus foo.py's OWN mesh_gather (the allowlist keys on
    # rel:function, so only mesh.py's gather is sanctioned)
    assert len(hits) == 6, [str(d) for d in hits]
    msgs = " ".join(d.message for d in hits)
    assert "np.asarray" in msgs and "addressable_shards" in msgs
    # the allowlist hook keys on rel:function — mesh.py's mesh_gather
    # is sanctioned, foo.py's is not... and outside the mesh dirs the
    # rule does not apply at all
    allowed = _run_rl(_check_mesh_host,
                      "spark_rapids_tpu/parallel/mesh.py",
                      "from spark_rapids_tpu.dispatch import host_fetch\n"
                      "def mesh_gather(x):\n"
                      "    return host_fetch(x)\n")
    assert allowed == []
    # the allowlist keys on QUALIFIED names: a method merely NAMED
    # mesh_gather (qualname Foo.mesh_gather) is not the sanctioned
    # module-level gather point
    nested = _run_rl(_check_mesh_host,
                     "spark_rapids_tpu/parallel/mesh.py",
                     "from spark_rapids_tpu.dispatch import host_fetch\n"
                     "class Foo:\n"
                     "    def mesh_gather(self, x):\n"
                     "        return host_fetch(x)\n")
    assert len(_find(nested, "RL-MESH-HOST")) == 1
    assert _run_rl(_check_mesh_host, "spark_rapids_tpu/execs/foo.py",
                   src) == []
    # the placement layer is shard-dispatch code: covered
    placed = _run_rl(_check_mesh_host,
                     "spark_rapids_tpu/runtime/placement.py",
                     "import numpy as np\n"
                     "def f(x):\n    return np.asarray(x)\n")
    assert len(_find(placed, "RL-MESH-HOST")) == 1


def test_rl_kernel_host():
    """RL-KERNEL-HOST: numpy or host syncs inside kernels/ — the
    static guard for 'a Pallas primitive never stalls the program
    that embeds it' (ISSUE 11 satellite)."""
    from spark_rapids_tpu.lint.repo_lint import _check_kernel_host
    src = (
        "import jax\n"
        "import numpy as np\n"                      # numpy import
        "from spark_rapids_tpu.dispatch import host_fetch\n"
        "def bad(x):\n"
        "    a = np.asarray(x)\n"                   # np materialization
        "    b = jax.device_get(x)\n"               # raw device fetch
        "    c = host_fetch(x)\n"                   # sanctioned-elsewhere
        "    return x.block_until_ready()\n"        # device sync
    )
    diags = _run_rl(_check_kernel_host,
                    "spark_rapids_tpu/kernels/foo.py", src)
    hits = _find(diags, "RL-KERNEL-HOST")
    assert len(hits) == 5, [str(d) for d in hits]
    msgs = " ".join(d.message for d in hits)
    assert "numpy import" in msgs and "np.asarray" in msgs
    # jnp and pallas are the kernel layer's whole point — clean
    ok = ("import jax\nimport jax.numpy as jnp\n"
          "from jax.experimental import pallas as pl\n"
          "def k(r):\n    r[:] = jnp.cumsum(r[:])\n")
    assert _run_rl(_check_kernel_host,
                   "spark_rapids_tpu/kernels/foo.py", ok) == []
    # outside kernels/ the rule does not apply (other rules own those)
    assert _run_rl(_check_kernel_host,
                   "spark_rapids_tpu/ops/foo.py", src) == []
    # the allowlist hook keys on rel:qualified-function
    from spark_rapids_tpu.lint import repo_lint as RL
    RL._KERNEL_HOST_ALLOWLIST["spark_rapids_tpu/kernels/foo.py:ok_fn"] = \
        "negative-test probe"
    try:
        allowed = _run_rl(
            _check_kernel_host, "spark_rapids_tpu/kernels/foo.py",
            "from spark_rapids_tpu.dispatch import host_fetch\n"
            "def ok_fn(x):\n    return host_fetch(x)\n")
        assert allowed == []
    finally:
        del RL._KERNEL_HOST_ALLOWLIST[
            "spark_rapids_tpu/kernels/foo.py:ok_fn"]


def test_rl_fault_point():
    from spark_rapids_tpu.lint.repo_lint import (
        _check_fault_registry,
        _check_fault_sites,
    )

    # unregistered name + non-literal name at the site
    src = ("from spark_rapids_tpu.runtime.faults import fault_point\n"
           "fault_point('no.such.point')\n"
           "name = 'dispatch.kernel'\n"
           "fault_point(name)\n")
    calls = {}
    diags = _run_rl(_check_fault_sites, "spark_rapids_tpu/foo.py", src,
                    calls)
    hits = _find(diags, "RL-FAULT-POINT")
    assert len(hits) == 2
    assert "not registered" in hits[0].message
    assert "string literal" in hits[1].message

    # a registered point with NO call site anywhere -> registry-side hit
    diags2 = []
    _check_fault_registry({}, diags2)
    assert diags2 and all(d.rule_id == "RL-FAULT-POINT" for d in diags2)
    assert any("no fault_point" in d.message for d in diags2)

    # a site outside the registered module -> module-drift hit
    good_src = ("from spark_rapids_tpu.runtime.faults import fault_point\n"
                "fault_point('dispatch.kernel')\n")
    calls3 = {}
    assert _run_rl(_check_fault_sites, "spark_rapids_tpu/elsewhere.py",
                   good_src, calls3) == []
    from spark_rapids_tpu.runtime.faults import FAULT_POINTS
    full = {name: [f"{module}:1"]
            for name, (module, _) in FAULT_POINTS.items()}
    full["dispatch.kernel"] = ["spark_rapids_tpu/elsewhere.py:2"]
    diags3 = []
    _check_fault_registry(full, diags3)
    assert len(diags3) == 1
    assert "registered module" in diags3[0].message

    # the real repo is clean in both directions
    diags4 = []
    _check_fault_registry(
        {name: [f"{module}:1"]
         for name, (module, _) in FAULT_POINTS.items()}, diags4)
    assert diags4 == []


def test_rl_fault_point_mesh_domain():
    """The mesh fault domain rides the SAME two-direction audit as
    every other point class: an UNREGISTERED mesh point at a call site
    is flagged, and a registered ``mesh.*`` point whose call site
    disappears (the distributed path silently losing chaos coverage —
    exactly the pre-PR state this issue fixed) is flagged from the
    registry side."""
    from spark_rapids_tpu.lint.repo_lint import (
        _check_fault_registry,
        _check_fault_sites,
    )
    from spark_rapids_tpu.runtime.faults import FAULT_POINTS

    # direction 1: a mesh-looking point nobody registered
    src = ("from spark_rapids_tpu.runtime.faults import fault_point\n"
           "fault_point('mesh.reland.unregistered')\n")
    diags = _run_rl(_check_fault_sites, "spark_rapids_tpu/parallel/foo.py",
                    src, {})
    hits = _find(diags, "RL-FAULT-POINT")
    assert len(hits) == 1 and "not registered" in hits[0].message

    # direction 2: every registered mesh.* point with NO call site ->
    # one registry-side diagnostic each (the points exist)
    mesh_points = [n for n in FAULT_POINTS if n.startswith("mesh.")]
    assert len(mesh_points) == 4, mesh_points
    calls = {name: [f"{module}:1"]
             for name, (module, _) in FAULT_POINTS.items()
             if not name.startswith("mesh.")}
    diags2 = []
    _check_fault_registry(calls, diags2)
    uncalled = [d for d in diags2 if "no fault_point" in d.message]
    assert len(uncalled) == len(mesh_points)
    assert any("mesh.gather" in d.message for d in uncalled)


def test_rl_obs_passive():
    """RL-OBS-PASSIVE: the telemetry sampler may not call host_fetch /
    device syncs, touch jax, drive query execution, or take the
    query-path locks — sampling must never perturb execution (ISSUE 14
    satellite)."""
    from spark_rapids_tpu.lint.repo_lint import _check_obs_passive
    rel = "spark_rapids_tpu/obs/telemetry.py"
    src = (
        "import jax\n"                                     # device work
        "from spark_rapids_tpu.dispatch import host_fetch\n"
        "def bad_sample(session, svc, exe, table):\n"
        "    a = host_fetch(table)\n"                      # host sync
        "    b = jax.device_get(table)\n"                  # host sync
        "    finalize_observation(exe)\n"                  # device fetch
        "    session.execute(table)\n"                     # drives a query
        "    with session._obs_lock:\n"                    # query-path lock
        "        pass\n"
        "    svc._cond.acquire()\n"                        # query-path lock
    )
    diags = _run_rl(_check_obs_passive, rel, src)
    hits = _find(diags, "RL-OBS-PASSIVE")
    assert len(hits) == 7, [str(d) for d in hits]
    msgs = " ".join(d.message for d in hits)
    assert "host sync" in msgs and "query-path lock" in msgs
    assert "drives query execution" in msgs
    # the sampler's own bounded reads are clean: snapshot surfaces,
    # its private ring lock, plain time/json work
    ok = (
        "import threading, time\n"
        "from spark_rapids_tpu.obs.metrics import scopes_snapshot\n"
        "_lock = threading.Lock()\n"
        "def sample():\n"
        "    snap = scopes_snapshot()\n"
        "    with _lock:\n"
        "        return dict(snap)\n"
    )
    assert _run_rl(_check_obs_passive, rel, ok) == []
    # scoped to the telemetry module only
    assert _run_rl(_check_obs_passive,
                   "spark_rapids_tpu/obs/events.py", src) == []
    # and the REAL module is clean under the rule
    import os

    import spark_rapids_tpu
    root = os.path.dirname(os.path.dirname(
        os.path.abspath(spark_rapids_tpu.__file__)))
    real = open(os.path.join(root, rel)).read()
    assert _run_rl(_check_obs_passive, rel, real) == []


def test_rl_mem_account():
    """RL-MEM-ACCOUNT: raw jax.device_put inside execs//ops/ lands
    bytes the memory arbiter never accounts — the static guard for the
    hard device budget's zero-violation contract (ISSUE 15)."""
    from spark_rapids_tpu.lint.repo_lint import _check_mem_account
    src = (
        "import jax\n"
        "from jax import device_put\n"              # banned import form
        "def bad(a, dev):\n"
        "    x = jax.device_put(a, dev)\n"          # raw landing
        "    y = device_put(a, dev)\n"              # bare-name call
        "    return x, y\n"
    )
    for rel in ("spark_rapids_tpu/execs/foo.py",
                "spark_rapids_tpu/ops/foo.py"):
        hits = _find(_run_rl(_check_mem_account, rel, src),
                     "RL-MEM-ACCOUNT")
        assert len(hits) == 3, [str(d) for d in hits]
        assert "from_host" in hits[0].message
    # the accounted landing path itself is clean
    ok = ("from spark_rapids_tpu.columnar import DeviceTable\n"
          "def good(host):\n"
          "    return DeviceTable.from_host(host)\n")
    assert _run_rl(_check_mem_account,
                   "spark_rapids_tpu/execs/foo.py", ok) == []
    # outside execs//ops/ the rule does not apply (columnar/table.py
    # and parallel/mesh.py ARE the sanctioned landing layers)
    assert _run_rl(_check_mem_account,
                   "spark_rapids_tpu/columnar/table.py", src) == []
    # the allowlist hook keys on rel:qualified-function — the mesh
    # re-land's digest-scalar put stays sanctioned with justification
    from spark_rapids_tpu.lint.repo_lint import _MEM_ACCOUNT_ALLOWLIST
    key = ("spark_rapids_tpu/execs/mesh.py:"
           "TpuMeshRelandExec._reland")
    assert key in _MEM_ACCOUNT_ALLOWLIST
    allow = ("import jax\n"
             "class TpuMeshRelandExec:\n"
             "    def _reland(self, t):\n"
             "        return jax.device_put(t, None)\n")
    assert _run_rl(_check_mem_account,
                   "spark_rapids_tpu/execs/mesh.py", allow) == []


def test_rl_mv_epoch():
    """RL-MV-EPOCH: streaming/ (micro-batch + MV maintenance) may only
    reach the result cache through the invalidation-epoch API — a
    direct mutation there is a second write path into cache coherence
    (ISSUE 16)."""
    from spark_rapids_tpu.lint.repo_lint import _check_mv_epoch
    src = (
        "from spark_rapids_tpu.service.result_cache import ResultCache\n"
        "def bad(service, key, table):\n"
        "    service.result_cache.put(key, table)\n"       # mutator call
        "    service.result_cache._entries.clear()\n"      # raw entries
    )
    hits = _find(_run_rl(_check_mv_epoch,
                         "spark_rapids_tpu/streaming/mv.py", src),
                 "RL-MV-EPOCH")
    # ResultCache import + put() + _entries access (+ the clear() on
    # the _entries chain) each flag
    assert len(hits) >= 3, [str(d) for d in hits]
    assert any("epoch" in h.message for h in hits)
    # the epoch API itself is the sanctioned crossing
    ok = (
        "from spark_rapids_tpu.service.result_cache import (\n"
        "    bump_table_epoch,\n"
        "    register_epoch_listener,\n"
        ")\n"
        "def good(path):\n"
        "    bump_table_epoch('delta:' + path, 'refresh')\n"
    )
    assert _run_rl(_check_mv_epoch,
                   "spark_rapids_tpu/streaming/mv.py", ok) == []
    # outside streaming/ the rule does not apply (the scheduler OWNS
    # the cache and mutates it legitimately)
    assert _run_rl(_check_mv_epoch,
                   "spark_rapids_tpu/service/scheduler.py", src) == []


# ---------------------------------------------------------------------------
# concurrency contracts (ISSUE 17): RL-LOCK-DECL / RL-LOCK-ORDER /
# RL-LOCK-EFFECT negatives over synthetic registries, plus the runtime
# lock witness
# ---------------------------------------------------------------------------


def _cc_registry(*decls):
    """Synthetic LOCK_ORDER: (name, rank, site, kind) tuples."""
    from spark_rapids_tpu.lockorder import LockDecl
    return {name: LockDecl(name, rank, site, kind, "test lock")
            for name, rank, site, kind in decls}


def _run_cc(files, registry, order_allow=None, effect_allow=None):
    from spark_rapids_tpu.lint.concurrency import check_concurrency
    diags = []
    check_concurrency({rel: ast.parse(src) for rel, src in files.items()},
                      diags, registry=registry,
                      order_allow=order_allow or {},
                      effect_allow=effect_allow or {})
    return diags


#: one in-scope module + two declared locks, A(10) under B(20) — the
#: shared fixture most order/effect sub-cases build on
_CC_REL = "spark_rapids_tpu/runtime/cc_mod.py"
_CC_TWO = (("t.a", 10, f"{_CC_REL}:A", "Lock"),
           ("t.b", 20, f"{_CC_REL}:B", "Lock"))
_CC_HDR = ("from spark_rapids_tpu.lockorder import ordered_lock\n"
           'A = ordered_lock("t.a")\n'
           'B = ordered_lock("t.b")\n')


def test_rl_lock_decl_raw_construction():
    """An undeclared raw threading primitive in a concurrent package is
    the core RL-LOCK-DECL negative (and the witness smoke's seed)."""
    src = "import threading\nL = threading.Lock()\n"
    hits = _find(_run_cc({"spark_rapids_tpu/runtime/bad.py": src},
                         _cc_registry()), "RL-LOCK-DECL")
    assert len(hits) == 1 and "raw threading.Lock()" in hits[0].message
    assert hits[0].path == "spark_rapids_tpu/runtime/bad.py:2"
    # the from-import alias spelling cannot slip past
    alias = "from threading import RLock as RL\nx = RL()\n"
    ahits = _find(_run_cc({"spark_rapids_tpu/obs/bad.py": alias},
                          _cc_registry()), "RL-LOCK-DECL")
    assert len(ahits) == 1 and "raw RL()" in ahits[0].message
    # outside the concurrency-scoped packages the rule does not apply
    assert _run_cc({"spark_rapids_tpu/plan/fine.py": src},
                   _cc_registry()) == []


def test_rl_lock_decl_factory_contract():
    reg = _cc_registry(("t.a", 10, _CC_REL + ":A", "Lock"))
    head = "from spark_rapids_tpu.lockorder import ordered_lock\n"
    # undeclared name
    hits = _find(_run_cc({_CC_REL: head + 'X = ordered_lock("nope")\n'},
                         reg), "RL-LOCK-DECL")
    assert any("not declared" in d.message for d in hits)
    # non-literal name defeats the audit
    hits = _find(_run_cc({_CC_REL: head + "X = ordered_lock(name)\n"},
                         reg), "RL-LOCK-DECL")
    assert any("string literal" in d.message for d in hits)
    # declared site and construction site must agree
    hits = _find(_run_cc({_CC_REL: head + 'WRONG = ordered_lock("t.a")\n'},
                         reg), "RL-LOCK-DECL")
    assert any("one declared construction site" in d.message for d in hits)
    # declared kind and factory must agree
    rl = ("from spark_rapids_tpu.lockorder import ordered_rlock\n"
          'A = ordered_rlock("t.a")\n')
    hits = _find(_run_cc({_CC_REL: rl}, reg), "RL-LOCK-DECL")
    assert any("declared as Lock but constructed" in d.message
               for d in hits)
    # a declared lock never constructed at its site = stale entry
    hits = _find(_run_cc({_CC_REL: "x = 1\n"}, reg), "RL-LOCK-DECL")
    assert any("stale registry entry" in d.message for d in hits)
    # ...and the clean construction is exactly zero findings
    assert _run_cc({_CC_REL: head + 'A = ordered_lock("t.a")\n'},
                   reg) == []


def test_rl_lock_order_with_nesting():
    reg = _cc_registry(*_CC_TWO)
    # ascending ranks: clean
    ok = _CC_HDR + "def f():\n    with A:\n        with B:\n            pass\n"
    assert _run_cc({_CC_REL: ok}, reg) == []
    # descending ranks: the inversion finding
    bad = _CC_HDR + "def f():\n    with B:\n        with A:\n            pass\n"
    hits = _find(_run_cc({_CC_REL: bad}, reg), "RL-LOCK-ORDER")
    assert any("'t.a' (rank 10) while holding 't.b' (rank 20)"
               in d.message for d in hits)
    # try-acquire is the sanctioned out-of-order shape
    tryacq = (_CC_HDR + "def f():\n    with B:\n"
              "        if A.acquire(blocking=False):\n"
              "            A.release()\n")
    assert _run_cc({_CC_REL: tryacq}, reg) == []
    # the allowlist hook suppresses a justified site (RL-MESH-HOST shape)
    assert _run_cc({_CC_REL: bad}, reg,
                   order_allow={f"{_CC_REL}:f": "test justification"}) == []


def test_rl_lock_order_through_call_graph():
    """The inversion two frames deep: f holds B and calls g, which
    blocking-acquires A — the bounded call-graph closure reports it at
    f's call site with the `via` evidence."""
    reg = _cc_registry(*_CC_TWO)
    src = (_CC_HDR
           + "def g():\n    with A:\n        pass\n"
           + "def f():\n    with B:\n        g()\n")
    hits = _find(_run_cc({_CC_REL: src}, reg), "RL-LOCK-ORDER")
    assert any("via g()" in d.message for d in hits)


def test_rl_lock_order_cycle_defeats_allowlist():
    """f ascends A->B (clean); g's B->A inversion is allowlisted — but
    the two edges compose into a deadlock cycle, which is reported
    regardless of allowlisting."""
    reg = _cc_registry(*_CC_TWO)
    src = (_CC_HDR
           + "def f():\n    with A:\n        with B:\n            pass\n"
           + "def g():\n    with B:\n        with A:\n            pass\n")
    diags = _run_cc({_CC_REL: src}, reg,
                    order_allow={f"{_CC_REL}:g": "test justification"})
    hits = _find(diags, "RL-LOCK-ORDER")
    assert any(d.path == "lockorder:cycle"
               and "allowlisting cannot suppress" in d.message
               for d in hits)
    # the allowlisted LOCAL finding stayed suppressed: only the cycle
    assert len(hits) == 1


def test_rl_lock_effect():
    reg = _cc_registry(*_CC_TWO)
    src = (_CC_HDR
           + "import subprocess\n"
           + "from spark_rapids_tpu.runtime.faults import fault_point\n"
           + "def f():\n    with A:\n"
           + "        subprocess.run(['x'])\n"
           + "        fault_point('t.point')\n")
    hits = _find(_run_cc({_CC_REL: src}, reg), "RL-LOCK-EFFECT")
    msgs = " | ".join(d.message for d in hits)
    assert "subprocess.run()" in msgs
    assert "fault_point() raise site" in msgs
    assert all("holding lock 't.a'" in d.message for d in hits)
    # the allowlist hook keys on the HOLDER function
    assert _run_cc({_CC_REL: src}, reg,
                   effect_allow={f"{_CC_REL}:f": "test justification"}) == []


def test_rl_lock_effect_condition_wait():
    """Waiting on a Condition while holding a DIFFERENT lock is a
    finding; waiting on the condition you hold is how conditions
    work."""
    rel = _CC_REL
    reg = _cc_registry(("t.a", 10, f"{rel}:A", "Lock"),
                       ("t.cv", 20, f"{rel}:CV", "Condition"))
    head = ("from spark_rapids_tpu.lockorder import ordered_lock, "
            "ordered_condition\n"
            'A = ordered_lock("t.a")\n'
            'CV = ordered_condition("t.cv")\n')
    bad = head + ("def f():\n    with A:\n        with CV:\n"
                  "            CV.wait()\n")
    hits = _find(_run_cc({rel: bad}, reg), "RL-LOCK-EFFECT")
    assert any("wait on Condition 't.cv'" in d.message
               and "'t.a'" in d.message for d in hits)
    ok = head + "def f():\n    with CV:\n        CV.wait()\n"
    assert _run_cc({rel: ok}, reg) == []


def test_lock_witness_rank_inversion_raises():
    """The armed witness turns a would-be deadlock interleaving into a
    typed LockOrderViolation carrying the held chain."""
    from spark_rapids_tpu import lockorder
    lockorder.arm_witness()
    try:
        low = lockorder.ordered_lock("streaming.query")     # rank 100
        high = lockorder.ordered_lock("memory.arbiter")     # rank 740
        # ascending is silent, and the held snapshot tracks it
        with low:
            with high:
                assert lockorder.held_snapshot() == [
                    "streaming.query", "memory.arbiter"]
        assert lockorder.held_snapshot() == []
        # descending raises BEFORE touching the inner lock
        with high:
            with pytest.raises(lockorder.LockOrderViolation) as ei:
                low.acquire()
            assert "held chain" in str(ei.value)
            assert "memory.arbiter" in str(ei.value)
            # try-acquire stays exempt at runtime too
            assert low.acquire(blocking=False)
            low.release()
        assert lockorder.held_snapshot() == []
        # re-acquiring a held non-reentrant lock = self-deadlock
        with pytest.raises(lockorder.LockOrderViolation,
                           match="self-deadlock"):
            with low:
                low.acquire()
    finally:
        lockorder.disarm_witness()


def test_lock_witness_condition_wait_releases():
    from spark_rapids_tpu import lockorder
    lockorder.arm_witness()
    try:
        cv = lockorder.ordered_condition("service.scheduler.cond")
        with cv:
            assert lockorder.held_snapshot() == ["service.scheduler.cond"]
            cv.wait(timeout=0.01)
            # wait() re-acquired: the held stack is restored
            assert lockorder.held_snapshot() == ["service.scheduler.cond"]
        assert lockorder.held_snapshot() == []
    finally:
        lockorder.disarm_witness()


def test_lock_witness_construction_time_election():
    """configure() arms from conf; disarmed factories hand back RAW
    primitives (zero steady-state overhead), armed ones the witness
    wrappers — elected at construction, not per-acquire."""
    from spark_rapids_tpu import lockorder
    try:
        lockorder.configure(RapidsConf(
            {"spark.rapids.lint.lockWitness": "true"}))
        assert lockorder.witness_armed()
        wrapped = lockorder.ordered_lock("streaming.query")
        assert "witnessed" in repr(wrapped)
        lockorder.configure(RapidsConf())
        assert not lockorder.witness_armed()
        raw = lockorder.ordered_lock("streaming.query")
        assert not hasattr(raw, "_decl")
        # a pre-arming lock stays raw even while the witness is armed
        lockorder.arm_witness()
        with raw:
            pass
        # undeclared names fail fast regardless of arming
        with pytest.raises(lockorder.LockDeclError, match="not declared"):
            lockorder.ordered_lock("no.such.lock")
        with pytest.raises(lockorder.LockDeclError, match="declared as"):
            lockorder.ordered_rlock("streaming.query")
    finally:
        lockorder.disarm_witness()


def test_lock_registry_known_suspects_ranked():
    """ISSUE 17 satellite: the sites previous PRs fixed by hand are now
    pinned by rank so the ordering cannot silently regress."""
    from spark_rapids_tpu.lockorder import LOCK_ORDER, LOCK_WITNESS
    r = {n: d.rank for n, d in LOCK_ORDER.items()}
    # scheduler condition is acquired before the per-query handle lock
    assert r["service.scheduler.cond"] < r["service.handle"]
    # arbiter work happens UNDER a SpillableBatch lock (account/spill)
    assert r["spill.batch"] < r["memory.arbiter"]
    # catalog singleton access sits between batch and the catalog maps
    assert r["spill.batch"] < r["spill.catalog.instance"] \
        < r["spill.catalog.registry"]
    # telemetry/observability rings are leaf locks: above every
    # runtime/service lock they are reached from
    assert r["obs.telemetry.ring"] > r["memory.arbiter"]
    assert r["obs.telemetry.ring"] > r["service.scheduler.cond"]
    # fault registry is consulted from inside every subsystem
    assert r["faults.registry"] > r["memory.arbiter"]
    # ranks form a total order (no ties to hide behind)
    assert len(set(r.values())) == len(r)
    assert LOCK_WITNESS.key == "spark.rapids.lint.lockWitness"


@pytest.mark.chaos
def test_lock_witness_chaos_service_scenario():
    """Tier-1 chaos pin: a concurrent service run under memory pressure
    (admission, scheduler condition, result cache, arbiter, telemetry)
    completes with the witness armed — every blocking acquisition on
    every thread respected LOCK_ORDER, or this raises
    LockOrderViolation."""
    from spark_rapids_tpu import lockorder
    from spark_rapids_tpu.ops.expr import lit
    from spark_rapids_tpu.service import QueryService
    conf = {
        "spark.rapids.lint.lockWitness": "true",
        # a small device budget forces arbiter/spill traffic under load
        "spark.rapids.memory.device.budgetBytes": str(256 * 1024),
    }
    try:
        with QueryService(conf, max_concurrent=3) as svc:
            data = {"k": np.array(["a", "b", "c", "d"] * 60, dtype=object),
                    "v": np.arange(240, dtype=np.int64)}
            df = svc.session.create_dataframe(data, num_batches=6)
            handles = [
                svc.submit(df.filter(col("v") >= lit(i))
                           .group_by("k").agg(F.sum("v").alias("sv")))
                for i in range(8)]
            for h in handles:
                assert h.wait(timeout=60)
            assert all(h.result() is not None for h in handles)
        assert lockorder.held_snapshot() == []
    finally:
        lockorder.disarm_witness()


def test_cli_json_smoke(tmp_path):
    """Satellite: the --json contract in a real subprocess, within the
    5s budget — a clean all-skip run exits 0, and a tiny synthetic tree
    with an undeclared lock exits 1 with machine-readable diagnostics."""
    import json
    import subprocess
    import sys
    import time
    t0 = time.monotonic()
    clean = subprocess.run(
        [sys.executable, "-m", "spark_rapids_tpu.lint", "--json",
         "--skip-repo", "--skip-registry", "--skip-plans",
         "--skip-exec-metrics"],
        capture_output=True, text=True)
    assert clean.returncode == 0, clean.stderr
    out = json.loads(clean.stdout)
    assert out == {"phases": {}, "diagnostics": [], "ok": True}

    bad = tmp_path / "spark_rapids_tpu" / "runtime"
    bad.mkdir(parents=True)
    (bad / "bad.py").write_text("import threading\nL = threading.Lock()\n")
    failing = subprocess.run(
        [sys.executable, "-m", "spark_rapids_tpu.lint", "--json",
         "--repo-root", str(tmp_path), "--skip-registry",
         "--skip-plans", "--skip-exec-metrics"],
        capture_output=True, text=True)
    assert failing.returncode == 1, failing.stderr
    out = json.loads(failing.stdout)
    assert out["ok"] is False and out["phases"]["repo"] >= 1
    assert any(
        d["rule_id"] == "RL-LOCK-DECL"
        and d["path"] == "spark_rapids_tpu/runtime/bad.py:2"
        and d["severity"] == "error"
        for d in out["diagnostics"])
    assert time.monotonic() - t0 < 5.0, "CLI smoke blew the 5s budget"


def test_every_rule_has_a_negative_test():
    """Meta-pin: the rule surface and this module's negative coverage
    cannot drift apart (>= 12 rules required by the issue)."""
    module_src = open(__file__).read()
    assert len(RULES) >= 12
    for rid in RULES:
        assert rid in module_src, f"rule {rid} has no negative test"


# ---------------------------------------------------------------------------
# pins for the real violations the tooling surfaced (satellite 1)
# ---------------------------------------------------------------------------


def _dec_table(precision=4, scale=2):
    return HostTable(["d", "e", "g"], [
        HostColumn(T.DecimalType(precision, scale),
                   np.array([100, 200, 300, 400], dtype=np.int64)),
        HostColumn(T.DecimalType(precision, scale),
                   np.array([30, 30, 70, 70], dtype=np.int64)),
        HostColumn(T.LONG, np.array([0, 0, 1, 1], dtype=np.int64))])


def test_decimal_remainder_registered_and_on_device(session, cpu_session):
    """RA-UNREGISTERED catch: DecimalRemainder/DecimalPmod shipped device
    kernels but were never registered — decimal % silently fell back."""
    from spark_rapids_tpu.overrides import rules as R
    from spark_rapids_tpu.ops.decimal import DecimalPmod, DecimalRemainder
    R._build_expr_sigs()
    from spark_rapids_tpu.overrides.typesig import lookup_mro
    assert lookup_mro(R._EXPR_SIGS, DecimalRemainder) is not None
    assert lookup_mro(R._EXPR_SIGS, DecimalPmod) is not None

    t = _dec_table()
    expr = (col("d") % col("e")).alias("r")
    want = from_host_table(t, cpu_session).select(expr).collect()
    got = from_host_table(t, session).select(expr).collect()
    assert got == want
    from tests.asserts import assert_runs_on_tpu
    assert_runs_on_tpu(
        lambda s: from_host_table(t, s).select(expr), session)


def test_avg_decimal_returns_value_units(session, cpu_session):
    """PV/PROBE catch: avg(decimal(4,2)) of [1.00..4.00] must be in VALUE
    units (2.5), not unscaled units (250), on every path."""
    t = _dec_table()
    for s in (session, cpu_session):
        rows = from_host_table(t, s).agg(F.avg("d").alias("a")).collect()
        assert rows == [(2.5,)], (s, rows)
        by_g = sorted(from_host_table(t, s).group_by("g")
                      .agg(F.avg("d").alias("a")).collect())
        assert by_g == [(0, 1.5), (1, 3.5)], (s, by_g)


def test_avg_decimal_streaming_merge_path(session):
    """The streaming partial-merge path casts its dec128 partial sums to
    double — this crashed (two-limb broadcast) before the cast fix."""
    t = _dec_table()
    s = TpuSession({"spark.rapids.sql.batchSizeBytes": "1"})
    rows = sorted(from_host_table(t, s, 4).group_by("g")
                  .agg(F.avg("d").alias("a")).collect())
    assert rows == [(0, 1.5), (1, 3.5)], rows


def test_stddev_decimal_value_units(session, cpu_session):
    import math
    t = _dec_table()
    want = math.sqrt(np.var([1.0, 2.0, 3.0, 4.0], ddof=1))
    for s in (session, cpu_session):
        (got,), = from_host_table(t, s).agg(
            F.stddev(col("d")).alias("x")).collect()
        assert got == pytest.approx(want, rel=1e-9), (s, got)


def test_window_avg_decimal_value_units(session, cpu_session):
    from spark_rapids_tpu.ops.window import Window as W
    t = _dec_table()
    for s in (session, cpu_session):
        rows = sorted(from_host_table(t, s).with_windows(
            a=F.avg(col("d")).over(W.partition_by("g")))
            .select("g", "a").collect())
        assert rows == [(0, 1.5), (0, 1.5), (1, 3.5), (1, 3.5)], (s, rows)


def test_dec128_cast_to_double_on_device(session, cpu_session):
    """Cast(decimal(25,2) -> double) used to broadcast-crash on the
    two-limb device representation."""
    big = 10 ** 20  # needs 128-bit storage at precision 25
    vals = np.array([big * 100 + 25, -big * 100, 0], dtype=object)
    t = HostTable(["d"], [HostColumn(T.DecimalType(25, 2), vals)])
    expr = col("d").cast("double").alias("x")
    want = from_host_table(t, cpu_session).select(expr).collect()
    got = from_host_table(t, session).select(expr).collect()
    # two-limb f64 combine vs one exact division: allow ULP-level skew
    for (g,), (w,) in zip(got, want):
        assert g == pytest.approx(w, rel=1e-13), (g, w)
    assert got[0][0] == pytest.approx(float(big), rel=1e-13)
