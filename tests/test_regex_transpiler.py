"""Java->Python regex transpiler guard (reference analog: RegexParser.scala
+ RegularExpressionTranspilerSuite — transpile exactly or reject)."""

import re
import warnings

import pytest

from spark_rapids_tpu.ops.regex_transpiler import (
    RegexUnsupported,
    transpile_java_regex,
    try_transpile,
)


def _match(java_pattern, s):
    t = transpile_java_regex(java_pattern)
    return re.search(t, s, re.ASCII) is not None


# -- semantics the transpiler must PRESERVE (Java behavior) -----------------

def test_digit_class_is_ascii_only():
    assert _match(r"^\d+$", "123")
    assert not _match(r"^\d+$", "١٢")  # Arabic-Indic digits
    assert not _match(r"^\w+$", "café")     # é not in Java \w


def test_dot_excludes_all_java_line_terminators():
    assert _match("a.b", "axb")
    for terminator in ("\n", "\r", "", " ", " "):
        assert not _match("a.b", f"a{terminator}b"), repr(terminator)


def test_dollar_matches_before_final_terminator():
    # Java: 'abc$' finds a match in 'abc\n', 'abc\r\n', and 'abc\r'
    assert _match("abc$", "abc")
    assert _match("abc$", "abc\n")
    assert _match("abc$", "abc\r\n")
    assert _match("abc$", "abc\r")      # python's raw $ would miss this
    assert not _match("abc$", "abc\nx")


def test_quote_literal_block():
    assert _match(r"\Q1+1\E", "1+1")
    assert not _match(r"\Q1+1\E", "111")


def test_named_group_syntax_converts():
    t = transpile_java_regex("(?<year>[0-9]+)-x")
    m = re.search(t, "2024-x", re.ASCII)
    assert m and m.group("year") == "2024"


def test_char_class_expansions_inside_brackets():
    assert _match(r"^[\d_]+$", "12_3")
    assert not _match(r"^[\d_]+$", "١")


def test_escaped_specials_and_quantifiers_pass():
    assert _match(r"a\.b", "a.b")
    assert not _match(r"a\.b", "axb")
    assert _match(r"^a{2,3}$", "aaa")
    assert _match(r"(ab|cd)+", "abcd")
    assert _match(r"x(?=y)", "xy")
    assert not _match(r"x(?=y)", "xz")


def test_leading_dotall_flag():
    assert _match(r"(?s)a.b", "a\nb")


# -- constructs the guard must REJECT ---------------------------------------

@pytest.mark.parametrize("pattern", [
    "a*+",                 # possessive quantifier
    "[a-z&&[^bc]]",        # class intersection
    "[[:alpha:]]",         # POSIX class
    r"\p{Alpha}+",         # unicode property
    r"\bword\b",           # Java ASCII word boundary
    r"\x{0041}",           # Java hex syntax
    "(?i)abc",             # inline flags (non-(?s))
    r"a\0101",             # octal escape
    r"\Gabc",              # \G anchor
    r"[\W]",               # negated class inside brackets
    r"(?m)^a$",            # multiline changes anchors
])
def test_rejected_constructs(pattern):
    with pytest.raises(RegexUnsupported):
        transpile_java_regex(pattern)


def test_try_transpile_returns_reason():
    pat, reason = try_transpile("a*+")
    assert pat is None and "possessive" in reason


# -- plan integration -------------------------------------------------------

def test_untranspilable_rlike_falls_back(session):
    from spark_rapids_tpu import functions as F
    from spark_rapids_tpu.ops.expr import col
    from tests.asserts import assert_falls_back
    from tests.data_gen import StringGen, gen_table

    def build(s):
        from spark_rapids_tpu.plan import from_host_table
        df = from_host_table(gen_table({"s": StringGen(cardinality=5)}, 50, 3), s)
        return df.select(F.rlike(col("s"), r"\bword\b").alias("m"))

    assert_falls_back(build, session, "Project")


def test_transpilable_rlike_runs_on_device(session, cpu_session):
    from spark_rapids_tpu import functions as F
    from spark_rapids_tpu.ops.expr import col
    from tests.asserts import assert_runs_on_tpu, assert_tpu_and_cpu_are_equal
    from tests.data_gen import StringGen, gen_table

    def build(s):
        from spark_rapids_tpu.plan import from_host_table
        df = from_host_table(gen_table({"s": StringGen(cardinality=8)}, 80, 4), s)
        return df.select(col("s"), F.rlike(col("s"), r"^[A-M]\d*").alias("m"))

    assert_runs_on_tpu(build, session)
    assert_tpu_and_cpu_are_equal(build, session, cpu_session)


def test_fallback_emits_divergence_warning(cpu_session):
    from spark_rapids_tpu import functions as F
    from spark_rapids_tpu.ops.expr import col
    from spark_rapids_tpu.plan import from_host_table
    from tests.data_gen import StringGen, gen_table

    df = from_host_table(gen_table({"s": StringGen(cardinality=4)}, 20, 5),
                         cpu_session)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        df.select(F.rlike(col("s"), r"\bx\b").alias("m")).collect()
    assert any("diverge from Spark" in str(x.message) for x in w)
