"""Offline profiling / qualification tools over query event logs.

The spark-rapids-tools analog: ``python -m spark_rapids_tpu.tools
profile <eventlog>`` turns the JSONL event logs the engine writes
(``spark.rapids.sql.eventLog.enabled`` — obs/events.py) into a
machine-readable profiling report (top operators by self time, compute
vs transfer vs shuffle breakdown, per-exchange skew, spill/retry
summary, fallback inventory, span attribution), and ``... compare A B``
diffs two runs per-query/per-operator — the tool perf PRs cite instead
of hand-timing.

Operates purely on the JSON records — no session/runtime machinery is
touched, so the CLI runs anywhere the logs land (it shares only the
event-schema constant with obs/events.py).
"""

from spark_rapids_tpu.tools.report import (  # noqa: F401
    build_profile,
    load_events,
    render_profile,
)
from spark_rapids_tpu.tools.compare import (  # noqa: F401
    build_compare,
    render_compare,
)


def require_tpu_backend() -> str:
    """THE --require-tpu gate shared by bench.py and scale_test.py:
    resolve the JAX backend (initializes it — call only after any
    virtual-device/mesh environment setup) and exit 2 with a
    machine-readable error when it is 'cpu'. Returns the backend name.
    Exists because BENCH_r06 silently committed CPU-backend numbers: a
    perf run that meant to hit the TPU must fail loudly, with one
    error contract, not two hand-synced copies."""
    import json
    import sys

    import jax
    backend = jax.default_backend()
    if backend == "cpu":
        print(json.dumps({
            "error": "backend is 'cpu' but --require-tpu was given "
                     "(no TPU backend resolved)",
            "backend": backend}))
        sys.exit(2)
    return backend
