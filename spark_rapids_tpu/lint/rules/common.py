"""Shared plumbing for the per-rule lint modules.

Every rule module under ``lint/rules/`` walks the same parsed ASTs with
the same small vocabulary: repo-relative paths, dotted attribute
chains, and THE host-synchronization call set (the device-residency
rules walk different scopes but must agree on what a host sync IS — a
spelling added to one and not the other would silently diverge).
"""

from __future__ import annotations

import ast
import os
from typing import Optional


def _repo_root(repo_root: Optional[str]) -> str:
    if repo_root:
        return repo_root
    import spark_rapids_tpu
    return os.path.dirname(os.path.dirname(
        os.path.abspath(spark_rapids_tpu.__file__)))


def _iter_source_files(root: str):
    pkg = os.path.join(root, "spark_rapids_tpu")
    for dirpath, dirnames, filenames in os.walk(pkg):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for f in sorted(filenames):
            if f.endswith(".py"):
                yield os.path.join(dirpath, f)
    for f in ("bench.py", "scale_test.py"):
        p = os.path.join(root, f)
        if os.path.exists(p):
            yield p


def _rel(root: str, path: str) -> str:
    return os.path.relpath(path, root)


def _attr_chain(node: ast.AST) -> str:
    """Dotted name of an attribute/name chain ('' when not a plain chain)."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _host_sync_call(chain: str) -> bool:
    """THE host-synchronization call set shared by the device-residency
    rules (RL-MESH-HOST and RL-KERNEL-HOST walk different scopes but
    must agree on what a host sync IS — a spelling added to one and not
    the other would silently diverge)."""
    return ((chain.endswith("device_get") and chain.startswith(
                ("jax.", "jax")))
            or chain == "host_fetch" or chain.endswith(".host_fetch")
            or chain.endswith(".block_until_ready"))


def _is_device_expr(node: ast.AST) -> bool:
    """Is this expression PROVABLY a device value — a jnp./jax. call not
    already funneled through the sanctioned host_fetch wrapper (whose
    RESULT is host data, however device-y its argument)?"""
    if isinstance(node, ast.Call):
        chain = _attr_chain(node.func)
        if chain == "host_fetch" or chain.endswith(".host_fetch"):
            return False
        if chain.startswith(("jnp.", "jax.")):
            return True
    for child in ast.iter_child_nodes(node):
        if _is_device_expr(child):
            return True
    return False
