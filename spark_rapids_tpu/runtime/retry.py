"""OOM retry framework.

Reference (SURVEY.md §2.5): RmmRapidsRetryIterator.scala — withRetry /
withRetryNoSplit / withRestoreOnRetry catch GpuRetryOOM / GpuSplitAndRetryOOM
thrown by the RmmSpark per-thread state machine; on retry the thread spills
and replays; on split-and-retry the input halves and both halves replay.
OOM *injection* for tests = RmmSpark.forceRetryOOM.

TPU mapping: a device OOM surfaces as an XlaRuntimeError with
RESOURCE_EXHAUSTED from PJRT. The retry driver spills registered spillables
through the BufferCatalog and replays the jitted computation; escalation
splits the input batch in half by rows (sound for row-wise operators; ops
with cross-row semantics use with_retry_no_split)."""

from __future__ import annotations

import contextvars
import threading
from typing import Callable, Iterator, List, Optional, Sequence, Union

import jax.numpy as jnp

from spark_rapids_tpu.columnar import DeviceTable, bucket_for
from spark_rapids_tpu.errors import (
    CpuRetryOOM,
    FatalDeviceOOM,
    RetryOOM,
    SplitAndRetryOOM,
)
from spark_rapids_tpu.runtime.spill import BufferCatalog, SpillableBatch
from spark_rapids_tpu.lockorder import ordered_lock


def is_device_oom(exc: BaseException) -> bool:
    """True when an exception is a retryable allocation failure — device
    (XLA RESOURCE_EXHAUSTED / RetryOOM family) or host (CpuRetryOOM from
    the HostAlloc arbiter; the reference routes CpuRetryOOM through the
    same retry framework)."""
    if isinstance(exc, (RetryOOM, SplitAndRetryOOM, CpuRetryOOM)):
        return True
    name = type(exc).__name__
    msg = str(exc)
    return ("XlaRuntimeError" in name and
            ("RESOURCE_EXHAUSTED" in msg or "Out of memory" in msg
             or "out of memory" in msg))


class RetryStateMachine:
    """Per-thread injected-OOM bookkeeping (RmmSpark thread state analog).

    ``force_retry_oom(n)`` arms n RetryOOM throws at the next n retry
    blocks on the calling thread; ``force_split_and_retry_oom(n)``
    likewise for the escalation path."""

    def __init__(self):
        self._local = threading.local()

    def _state(self):
        st = getattr(self._local, "st", None)
        if st is None:
            st = {"retry": 0, "split": 0, "retry_count": 0, "split_count": 0}
            self._local.st = st
        return st

    def force_retry_oom(self, num_ooms: int = 1):
        self._state()["retry"] += num_ooms

    def force_split_and_retry_oom(self, num_ooms: int = 1):
        self._state()["split"] += num_ooms

    def maybe_inject(self):
        st = self._state()
        if st["retry"] > 0:
            st["retry"] -= 1
            raise RetryOOM("injected RetryOOM (test)")
        if st["split"] > 0:
            st["split"] -= 1
            raise SplitAndRetryOOM("injected SplitAndRetryOOM (test)")

    def note_retry(self):
        self._state()["retry_count"] += 1
        # the process-wide memory scope mirrors retry traffic so the
        # event log (schema v10) attributes oomRetries per query
        from spark_rapids_tpu.runtime.memory import MEM_SCOPE
        MEM_SCOPE.add("oomRetries", 1)

    def note_split(self):
        self._state()["split_count"] += 1
        from spark_rapids_tpu.runtime.memory import MEM_SCOPE
        MEM_SCOPE.add("splitRetries", 1)

    @property
    def retry_count(self) -> int:
        return self._state()["retry_count"]

    @property
    def split_count(self) -> int:
        return self._state()["split_count"]

    def clear(self):
        self._local.st = None


RMM_TPU = RetryStateMachine()

#: spark.rapids.memory.gpu.oomMaxRetries, set per-query by the session so
#: every retry site (execs have no conf handle) honors the user's setting.
MAX_RETRIES_VAR = contextvars.ContextVar("rapids_oom_max_retries", default=2)


def split_device_table_in_half(dt: DeviceTable) -> List[DeviceTable]:
    """Halve a batch by rows (splitSpillableInHalfByRows analog). Slicing
    device arrays re-buckets each half to the smaller capacity."""
    if any(getattr(c, "is_nested", False) for c in dt.columns):
        raise FatalDeviceOOM(
            "cannot row-split a batch with nested (array/struct/map) "
            "columns (rebuilding offsets under OOM is unsupported; reduce "
            "batch size instead)")
    dt = dt.compacted()  # masked batches: prefix order before row slicing
    n = dt.num_rows
    if n < 2:
        raise FatalDeviceOOM(
            f"cannot split a {n}-row batch further (GpuSplitAndRetryOOM at floor)")
    first = n // 2
    second = n - first
    outs = []
    for start, cnt in ((0, first), (first, second)):
        cap = bucket_for(cnt)
        cols = []
        for c in dt.columns:
            data = jnp.zeros(cap, dtype=c.data.dtype).at[:cnt].set(
                c.data[start:start + cnt])
            validity = jnp.zeros(cap, dtype=jnp.bool_).at[:cnt].set(
                c.validity[start:start + cnt])
            cols.append(c.with_arrays(data, validity))
        outs.append(DeviceTable(dt.names, cols, cnt, cap))
    return outs


SpillableOrTable = Union[SpillableBatch, DeviceTable]


def _as_spillable(x: SpillableOrTable, catalog: BufferCatalog) -> SpillableBatch:
    if isinstance(x, SpillableBatch):
        return x
    return SpillableBatch(x, catalog)



class DeviceMemoryEventHandler:
    """Allocator-failure callback (DeviceMemoryEventHandler.scala:108
    analog): on an allocation failure, spill synchronously and report
    whether the allocation should be retried. Retrying stops when a spill
    pass frees nothing twice in a row ON THE SAME CATALOG — the state the
    reference escalates to the OOM state machine. Thread-safe; the
    catalog is a call argument, never shared mutable state."""

    def __init__(self, catalog: Optional[BufferCatalog] = None):
        self._default_catalog = catalog
        self._lock = ordered_lock("memory.retry_handler")
        self.alloc_failure_count = 0
        self.spilled_bytes = 0
        self.spill_crashes = 0
        self._fruitless: dict = {}  # id(catalog) -> consecutive count

    def on_alloc_failure(self, catalog: Optional[BufferCatalog] = None
                         ) -> bool:
        from spark_rapids_tpu.columnar.table import evict_device_caches
        from spark_rapids_tpu.dispatch import clear_device_constants
        from spark_rapids_tpu.parallel.exchange import clear_mesh_caches
        catalog = catalog or self._default_catalog or BufferCatalog.get()
        evict_device_caches()
        clear_device_constants()  # interned aux/remap arrays re-upload lazily
        clear_mesh_caches()  # pinned replicated dict matrices re-intern lazily
        try:
            freed = catalog.synchronous_spill(1 << 62)
        except Exception:
            # the spill pass itself died mid-demotion (a real I/O
            # failure, or the mem.spill chaos point): OOM RECOVERY
            # MUST NOT DIE RECOVERING — whatever the pass freed before
            # failing stays freed, the crash is counted, and the
            # replay proceeds (bounded by the caller's max_retries)
            with self._lock:
                self.spill_crashes += 1
            freed = 0
        with self._lock:
            self.alloc_failure_count += 1
            self.spilled_bytes += freed
            key = id(catalog)
            if freed > 0:
                self._fruitless[key] = 0
                return True
            n = self._fruitless.get(key, 0) + 1
            self._fruitless[key] = n
            return n < 2

    def reset_fruitless(self, catalog: BufferCatalog):
        """Called at retry-block entry: a new operator's memory pressure is
        a fresh situation; stale fruitless counts must not pre-escalate."""
        with self._lock:
            self._fruitless.pop(id(catalog), None)


DEVICE_MEMORY_EVENT_HANDLER = DeviceMemoryEventHandler()


def _free_device_memory(catalog: BufferCatalog) -> bool:
    """Release everything releasable before a replay: cached scan images
    first (lowest priority), then registered spillables through the
    catalog tiers. Returns False when further same-size retries are
    pointless (two fruitless spill passes on this catalog)."""
    return DEVICE_MEMORY_EVENT_HANDLER.on_alloc_failure(catalog)


def _free_memory_for(exc: BaseException, catalog: BufferCatalog) -> bool:
    """Route the spill response to the EXHAUSTED tier: a host OOM
    (CpuRetryOOM from the HostAlloc arbiter) frees HOST memory by pushing
    the host tier to disk — spilling device buffers into host RAM would
    worsen it. Device OOMs take the device demotion chain."""
    if isinstance(exc, CpuRetryOOM):
        catalog.spill_host_to_disk()
        # a blocked-then-raised host alloc may succeed after other tasks
        # release grants, so a replay is always worthwhile
        return True
    return _free_device_memory(catalog)

def with_retry(
    inputs: Union[SpillableOrTable, Sequence[SpillableOrTable]],
    fn: Callable[[DeviceTable], object],
    *,
    splittable: bool = True,
    max_retries: Optional[int] = None,
    catalog: Optional[BufferCatalog] = None,
) -> Iterator[object]:
    """Run ``fn`` over input batch(es), surviving device OOM.

    Per attempt: injection hook fires first (tests), then fn runs; on OOM the
    catalog spills and the SAME input replays (up to max_retries), after
    which the input splits in half by rows and both halves replay
    recursively (when ``splittable``). Results stream out as an iterator —
    one result per final (possibly split) input batch.

    The reference contract this mirrors: withRetry(spillable)(fn) —
    RmmRapidsRetryIterator.scala:62; withRetryNoSplit :126."""
    catalog = catalog or BufferCatalog.get()
    if max_retries is None:
        max_retries = MAX_RETRIES_VAR.get()
    DEVICE_MEMORY_EVENT_HANDLER.reset_fruitless(catalog)
    stack: List[SpillableBatch] = []
    if isinstance(inputs, (SpillableBatch, DeviceTable)):
        inputs = [inputs]
    for x in reversed(list(inputs)):
        stack.append(_as_spillable(x, catalog))

    sb = None
    try:
        while stack:
            sb = stack.pop()
            attempts = 0
            while True:
                try:
                    from spark_rapids_tpu.runtime.speculation import guard_attempt
                    RMM_TPU.maybe_inject()
                    with sb.pinned_batch() as dt:
                        result = guard_attempt(lambda: fn(dt))
                    sb.release()
                    sb = None
                    yield result
                    break
                except Exception as exc:
                    oom = is_device_oom(exc)
                    escalate = isinstance(exc, SplitAndRetryOOM) or (
                        oom and attempts >= max_retries)
                    if oom and not escalate:
                        attempts += 1
                        RMM_TPU.note_retry()
                        # free everything we can, then replay the same
                        # input — unless spilling freed nothing twice on
                        # this catalog, in which case a same-size replay
                        # is pointless and we escalate straight to split
                        if _free_memory_for(exc, catalog):
                            continue
                        escalate = True
                    if escalate:
                        tier = ("host" if isinstance(exc, CpuRetryOOM)
                                else "device")
                        if not splittable:
                            raise FatalDeviceOOM(
                                f"{tier} OOM and operator cannot split "
                                "its input") from exc
                        RMM_TPU.note_split()
                        _free_memory_for(exc, catalog)
                        with sb.pinned_batch() as dt:
                            halves = split_device_table_in_half(dt)
                        sb.release()
                        sb = None
                        for h in reversed(halves):
                            stack.append(_as_spillable(h, catalog))
                        break
                    raise
    finally:
        # abandonment (limit upstream), FatalDeviceOOM, or any error: drop
        # every still-registered input so the catalog never leaks buffers
        if sb is not None:
            sb.release()
        for pending in stack:
            pending.release()


def with_retry_no_split(
    inputs: Union[SpillableOrTable, Sequence[SpillableOrTable]],
    fn: Callable[[DeviceTable], object],
    *,
    max_retries: Optional[int] = None,
    catalog: Optional[BufferCatalog] = None,
) -> Iterator[object]:
    return with_retry(inputs, fn, splittable=False, max_retries=max_retries,
                      catalog=catalog)


def retry_block(fn: Callable[[], object], *, max_retries: Optional[int] = None,
                catalog: Optional[BufferCatalog] = None) -> object:
    """Retry an arbitrary device computation that has no single input batch
    (joins, merges): spill-and-replay only, no split escalation."""
    catalog = catalog or BufferCatalog.get()
    if max_retries is None:
        max_retries = MAX_RETRIES_VAR.get()
    DEVICE_MEMORY_EVENT_HANDLER.reset_fruitless(catalog)
    from spark_rapids_tpu.runtime.speculation import guard_attempt
    attempts = 0
    while True:
        try:
            RMM_TPU.maybe_inject()
            return guard_attempt(fn)
        except Exception as exc:
            if is_device_oom(exc) and attempts < max_retries:
                attempts += 1
                RMM_TPU.note_retry()
                # replay even when the spill pass freed nothing: a
                # retry_block has no split escalation, the replay
                # budget is already bounded by max_retries, and a
                # blocked-then-raised budget reservation (or an
                # injected OOM) can succeed on replay without new
                # spillables appearing — the with_retry fruitless
                # check exists to stop SAME-SIZE replays when a split
                # is the better move, which has no analog here
                _free_memory_for(exc, catalog)
                continue
            if is_device_oom(exc):
                tier = "host" if isinstance(exc, CpuRetryOOM) else "device"
                raise FatalDeviceOOM(
                    f"{tier} OOM persisted after {attempts} "
                    "spill-retries") from exc
            raise
