"""Worker watchdog: hard wall limits + self-healing worker pool.

Reference: Spark's executor heartbeat + task reaper
(``spark.task.reaper.*``) — the driver kills tasks that blow their
wall budget and replaces executors that stop heartbeating. This
service's workers are threads over ONE shared session, so the analog
is in-process:

* **Hard wall limit** (``spark.rapids.service.hardTimeoutMs``) — the
  cooperative deadline (PR 5) fires at exec-boundary batch pulls; a
  worker wedged INSIDE one dispatch (a stuck tunnel round trip, the
  ``dispatch.wedge`` chaos fault) never reaches the next pull, so that
  deadline can never fire. The watchdog sweeps RUNNING queries against
  the hard limit and, past it, ABANDONS the worker: the handle fails
  with a typed :class:`~spark_rapids_tpu.errors.HardTimeoutError`, a
  replacement worker spawns so pool capacity holds, and the abandoned
  thread exits on its own when (if) the dispatch ever returns — Python
  threads cannot be killed, only disowned.
* **Liveness backstop** — a worker thread that died without running
  the scheduler's own death handling (it catches everything, so this
  means something catastrophic) is detected dead, its handle failed,
  and a replacement spawned.

Lifecycle counters (``workersLost`` / ``workersRespawned`` /
``hardTimeouts``) live in the ``health`` metric scope
(runtime/health.py) next to the device-loss counters.
"""

from __future__ import annotations

import threading
import time

from spark_rapids_tpu.conf import int_conf
from spark_rapids_tpu.errors import HardTimeoutError, WorkerLostError
from spark_rapids_tpu.service.query import QueryState

HARD_TIMEOUT_MS = int_conf(
    "spark.rapids.service.hardTimeoutMs", 0,
    "HARD per-query wall limit from the RUNNING transition, "
    "milliseconds — distinct from the cooperative "
    "defaultTimeoutMs/submit(timeout_ms=) deadline, which only fires "
    "between batches: past this limit the watchdog abandons the "
    "worker (it may be wedged inside a single dispatch), fails the "
    "handle with HardTimeoutError, and spawns a replacement worker. "
    "0 disables the hard limit; the liveness backstop still runs.")


class _Worker:
    """One pool worker's bookkeeping: the thread, the handle it is
    currently running (None between queries), and the ``lost`` flag the
    watchdog sets when it abandons the worker — the worker's own loop
    checks it under the scheduler lock and exits without touching the
    (already-corrected) running count."""

    __slots__ = ("thread", "handle", "lost", "name")

    def __init__(self, name: str):
        self.thread: threading.Thread = None
        self.handle = None
        self.lost = False
        self.name = name

    def __repr__(self):
        return (f"_Worker({self.name}, lost={self.lost}, "
                f"handle={self.handle})")


class WorkerWatchdog:
    """Sweeper thread over the service's worker pool. All pool state is
    read and corrected under the service's condition lock; handle
    transitions happen under each handle's own lock (no ordering cycle:
    handle locks never acquire the scheduler lock)."""

    def __init__(self, service):
        self.service = service
        self.hard_timeout_ms = int(
            service.conf.get_entry(HARD_TIMEOUT_MS))
        self._thread = threading.Thread(
            target=self._loop, name="rapids-svc-watchdog", daemon=True)
        self._thread.start()

    def join(self, timeout: float = 5.0) -> None:
        self._thread.join(timeout)

    def _loop(self):
        svc = self.service
        while True:
            with svc._cond:
                if svc._shutdown:
                    return
                self._sweep_locked()
                svc._cond.wait(timeout=svc._SWEEP_INTERVAL_S)

    def _sweep_locked(self):
        svc = self.service
        # executor heartbeat sweep (runtime/cluster.py): hosts that
        # missed spark.rapids.cluster.missedBeats beats are declared
        # lost here too — the service's watchdog is the cross-host
        # health authority when a cluster driver is attached (the
        # driver's own sweeper covers driverless harness runs).
        # Best-effort and lock-free on our side: the cluster never
        # takes the service lock, so no ordering cycle.
        try:
            from spark_rapids_tpu.runtime.cluster import (
                sweep_cluster_hosts,
            )
            sweep_cluster_hosts()
        except Exception:
            pass  # host health must never break worker health
        now = time.monotonic()
        for w in list(svc._workers):
            if w.lost:
                continue
            h = w.handle
            if not w.thread.is_alive():
                # backstop: the worker loop's own death handling catches
                # BaseException, so a dead thread with lost unset means
                # something catastrophic killed it outside that net.
                # The thread is gone regardless of any handle race —
                # always respawn
                self._abandon_locked(
                    w, h, WorkerLostError(
                        f"service worker {w.name} died unexpectedly"),
                    QueryState.FAILED, count="failed",
                    require_transition=False)
            elif (h is not None and self.hard_timeout_ms > 0
                    and h.start_t is not None
                    and h.state == QueryState.RUNNING
                    and (now - h.start_t) * 1000.0 > self.hard_timeout_ms):
                self._abandon_locked(
                    w, h, HardTimeoutError(
                        f"query {h.query_id} exceeded the hard wall "
                        f"limit ({self.hard_timeout_ms}ms) — worker "
                        f"{w.name} abandoned (wedged inside a "
                        "dispatch?)"),
                    QueryState.TIMED_OUT, count="timed_out",
                    require_transition=True)

    def _abandon_locked(self, w, handle, error, terminal, count: str,
                        require_transition: bool):
        """Fail ``handle`` with ``error`` and mark ``w`` lost (it exits
        its loop without decrementing the running count — corrected
        here); respawn a replacement. With ``require_transition`` the
        whole abandonment is gated on WINNING the handle's terminal
        transition: a query that completed between the sweep's state
        read and this call keeps its healthy worker — abandoning it
        would count a phantom hard timeout and discard a good thread.
        Caller holds the service condition lock."""
        svc = self.service
        transitioned = (handle._transition(terminal, error=error)
                        if handle is not None else False)
        if require_transition and not transitioned:
            return  # lost the race: the query finished; worker is fine
        if transitioned:
            svc.counters[count] += 1
            if count == "timed_out":
                svc._health_metrics.add("hardTimeouts", 1)
                svc.counters["hardTimeouts"] += 1
            # if the wedged dispatch ever returns, the next cooperative
            # boundary aborts the (already-failed) query immediately
            handle.scope.cancel()
            svc._strike_locked(handle, str(error))
        w.lost = True
        if handle is not None:
            # the abandoned worker no longer counts toward concurrency
            svc._running -= 1
        svc._note_worker_lost_locked(w)
        svc._cond.notify_all()
