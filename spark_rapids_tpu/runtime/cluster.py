"""Multi-host cluster runtime: the driver/executor protocol and the
HOST fault domain.

The paper's bar is TPC-DS SF1K on a v5e-256 pod — a multi-HOST job.
PR 9 made execution mesh-native but the mesh is all devices of ONE
process, and PR 10's degradation ladder only knows how to lose a
*device*. This module is the missing layer above both (SNIPPETS.md
[1]-[2]: "on multi-process platforms such as TPU pods, pjit can be
used to run computations across all available devices across
processes"):

* :class:`ClusterRuntime` (``CLUSTER``) — conf-driven host topology
  over the device mesh (``spark.rapids.cluster.*``): H executor hosts,
  each owning a contiguous device group (the ``dcn`` rows of the
  hierarchical mesh PR 9 models — with the cluster enabled the
  all-to-alls physically ride ICI within a host group and DCN across).
  Host identity folds into the plan fingerprint (the host topology
  token) and the executable cache's generation, like the mesh's.
* **Driver/executor protocol** — PR 9's driver/PlacementLayer split is
  the seam: :class:`ClusterDriver` is the driver half (socket listener,
  scan dispatch, heartbeat ledger), :func:`executor_main` the executor
  half (a separate PROCESS that scans only the source files assigned
  to its host and ships the decoded shards back over a framed TPAK
  wire, modeled on the P2P shuffle transport). File scans partition
  source files BY HOST before the mesh shards rows by device
  (io/common.py routes through :meth:`ClusterRuntime.scan_route`).
* **Host fault domain** — registered ``host.*`` fault points
  (executor heartbeat, host shard landing, DCN exchange, driver →
  executor dispatch); ``device_lost`` at any of them raises the typed
  :class:`~spark_rapids_tpu.errors.HostLostError` (a whole PROCESS
  died, not a device) that walks the HOST degradation ladder
  (runtime/health.py ``on_host_loss``: retry → re-land the dead
  host's shards onto survivors → shrink the dcn axis → single-process
  fallback → the whole-backend ladder), bounded by
  ``spark.rapids.cluster.maxHostLosses``.
* **Cross-host health** — executor heartbeats ride the PR 3
  :class:`~spark_rapids_tpu.shuffle.heartbeat.ShuffleHeartbeatManager`
  (the driver-mediated peer ledger): a host that misses
  ``spark.rapids.cluster.missedBeats`` beats is declared lost by the
  driver's sweep (the PR 7 watchdog calls :func:`sweep_cluster_hosts`
  too), and a killed-then-respawned executor REJOINS through the same
  re-register path — ``CLUSTER.restore_host`` returns the topology to
  full strength.

The cluster, like the mesh it sits above, is PROCESS state (one
ClusterRuntime, configured per query by the placement layer).
Single-process operation is byte-identical to cluster operation by
construction: executors return the same per-file batches, in the same
path order, that a local scan would decode — the simulation harness
(``scale_test.py --hosts N``) asserts exactly that, with chaos.
"""

from __future__ import annotations

import os
import socket
import struct
import subprocess
import sys
import threading
import time
from contextlib import contextmanager
from contextvars import ContextVar
from typing import Dict, List, Optional, Tuple

from spark_rapids_tpu.conf import RapidsConf, bool_conf, int_conf
from spark_rapids_tpu.obs.metrics import metric_scope, register_metric
from spark_rapids_tpu.lockorder import ordered_lock

CLUSTER_ENABLED = bool_conf(
    "spark.rapids.cluster.enabled", False,
    "Multi-host cluster execution: the session's file scans partition "
    "their source files BY HOST and dispatch each host's subset to its "
    "executor process over the driver/executor protocol "
    "(runtime/cluster.py), landing the returned shards locally in "
    "path order — bit-identical to a single-process scan. Requires an "
    "attached ClusterDriver with live executors (scale_test.py "
    "--hosts N, or a real pod deployment); without one, scans stay "
    "local. Host topology folds into the plan fingerprint and the "
    "executable cache's generation.", commonly_used=True)

CLUSTER_NUM_HOSTS = int_conf(
    "spark.rapids.cluster.hosts", 0,
    "Declared executor-host count of the cluster topology. 0 derives "
    "the count from the attached ClusterDriver's expected hosts. With "
    "the hierarchical mesh enabled, host i owns the i-th contiguous "
    "device group (the dcn rows): all-to-alls ride ICI within a host "
    "group and DCN across.")

CLUSTER_HEARTBEAT_MS = int_conf(
    "spark.rapids.cluster.heartbeatIntervalMs", 250,
    "Executor heartbeat period against the driver's ledger (the PR 3 "
    "ShuffleHeartbeatManager pattern over the cluster wire). The "
    "driver's sweep declares a host lost after missedBeats * this "
    "interval without a beat.")

CLUSTER_MISSED_BEATS = int_conf(
    "spark.rapids.cluster.missedBeats", 3,
    "Consecutive heartbeat intervals an executor may miss before the "
    "driver's sweep declares its host LOST: in-flight and subsequent "
    "scans re-land the host's shards onto survivors, and the host "
    "ladder (runtime/health.py on_host_loss) owns recovery. A host "
    "that rejoins (heartbeat re-register, or a respawned executor's "
    "fresh registration) is restored to the topology.")

CLUSTER_MAX_HOST_LOSSES = int_conf(
    "spark.rapids.cluster.maxHostLosses", 2,
    "Topology shrinks (a host evicted from the cluster, its device "
    "group excluded from the dcn axis) the host degradation ladder "
    "may perform after repeated host losses before latching "
    "single-process fallback — the driver then scans everything "
    "locally (still serving, minus the cluster) until a host rejoins "
    "and restore returns the topology to declared strength.")

CLUSTER_DISPATCH_TIMEOUT_MS = int_conf(
    "spark.rapids.cluster.dispatchTimeoutMs", 30000,
    "Socket timeout for one driver->executor round trip (scan "
    "dispatch and frame receive). A timeout is classified as a host "
    "loss — the executor process is presumed dead or wedged — and "
    "raises the typed HostLostError the host ladder recovers from.")

# -- the `cluster` metric scope ---------------------------------------------

register_metric("hostsLost", "count", "ESSENTIAL",
                "executor hosts declared lost (missed-beat sweep, "
                "dead dispatch socket, or the host ladder's re-land "
                "rung) — each one re-routes its shards to survivors")
register_metric("hostRelands", "count", "ESSENTIAL",
                "host shard re-landings: scans that re-assigned a "
                "lost host's source files onto surviving executors "
                "(one count per lost host per routed scan)")
register_metric("hostShrinks", "count", "ESSENTIAL",
                "topology shrinks: hosts evicted from the cluster by "
                "the degradation ladder, their device group excluded "
                "from the dcn axis (bounded by "
                "spark.rapids.cluster.maxHostLosses)")
register_metric("hostRestores", "count", "ESSENTIAL",
                "hosts restored to the topology after a rejoin "
                "(heartbeat re-register / respawned executor)")
register_metric("dcnExchanges", "count", "ESSENTIAL",
                "shuffle collectives whose mesh spanned more than one "
                "cluster host group — the all-to-all crossed the DCN "
                "axis, not just intra-host ICI")
register_metric("hostShardsLanded", "count", "MODERATE",
                "host shard batches landed by the driver from "
                "executor scan responses (one per file batch)")
register_metric("hostShardRetries", "count", "MODERATE",
                "host shard landings retried after a corrupt frame "
                "(TPAK CRC mismatch at the host.shard.land boundary)")
register_metric("executorBeatsDropped", "count", "MODERATE",
                "executor heartbeats dropped at the driver (injected "
                "host.heartbeat faults or ledger errors) — enough of "
                "them and the sweep declares the host lost")
register_metric("clusterScanFallbacks", "count", "MODERATE",
                "scans that requested cluster routing but ran locally "
                "(unsupported format, hive-partitioned paths, no live "
                "executors, or the single-process latch)")

CLUSTER_SCOPE = metric_scope("cluster")

#: CRC-failed host shard landings retried against the intact received
#: frame before the landing is classified as a host loss
SHARD_LAND_RETRIES = 2

#: scan formats the executor side can reconstruct from a wire spec
#: (everything else falls back to a local scan, counted). Parquet only:
#: its named constructor kwargs (columns, filters) all round-trip
#: through _scan_spec. CSV does NOT qualify — CsvScanNode consumes
#: sep/header/schema/quote/... as named kwargs that never reach
#: self.options, so a wire rebuild would silently parse with defaults
#: and break the bit-identity contract.
_EXECUTOR_SCAN_FORMATS = ("parquet",)


# -- per-query per-host scan attribution -------------------------------------
# Thread-local like the dispatch counters: the drain pulls cluster-
# routed scans on the executing thread, so per-host stats accumulated
# here belong to exactly one in-flight query. The session resets at
# top-level execute and folds the result into the v9 event record's
# ``hostScans`` field.

_TL_SCAN_STATS = threading.local()


def reset_host_scan_stats() -> None:
    _TL_SCAN_STATS.stats = {}


def host_scan_stats() -> Dict[str, dict]:
    """This thread's accumulated per-host scan attribution:
    {host: {scans, files, bytes, wallS, execWallS, crcRetries}}."""
    return {h: dict(v)
            for h, v in getattr(_TL_SCAN_STATS, "stats", {}).items()}


def _bump_host_stat(host_id: str, **deltas) -> None:
    stats = getattr(_TL_SCAN_STATS, "stats", None)
    if stats is None:
        stats = _TL_SCAN_STATS.stats = {}
    e = stats.setdefault(host_id, {"scans": 0, "files": 0, "bytes": 0,
                                   "wallS": 0.0, "execWallS": 0.0,
                                   "crcRetries": 0})
    for k, v in deltas.items():
        cur = e.get(k, 0)
        e[k] = (round(cur + v, 6) if isinstance(cur, float)
                else cur + int(v))


#: per-ATTEMPT cluster suppression (the session's replay machinery sets
#: this when an attempt must not touch the cluster at all); distinct
#: from the single-process LATCH, which is process state until a host
#: rejoins
_SUPPRESS: "ContextVar[Optional[str]]" = ContextVar(
    "cluster_suppress", default=None)


def cluster_suppression_reason() -> Optional[str]:
    return _SUPPRESS.get()


@contextmanager
def suppressed_cluster(reason: str):
    """Scope one execution attempt's cluster demotion (scans land
    locally for THIS thread's attempt only)."""
    tok = _SUPPRESS.set(reason)
    try:
        yield
    finally:
        _SUPPRESS.reset(tok)


class ClusterRuntime:
    """Process-wide cluster topology state (owned like MESH/HEALTH,
    configured per query by the placement layer). The fault-domain
    half: ``_lost`` holds hosts the sweep or the ladder's re-land rung
    declared lost (they rejoin via restore_host), ``_excluded`` holds
    hosts the shrink rung evicted (their device group leaves the dcn
    axis until restore), and ``_single_process_reason`` is the
    bottom-rung latch — the driver scans everything locally until a
    host rejoins."""

    def __init__(self):
        self._lock = ordered_lock("cluster.runtime")
        self._enabled = False
        self._declared_hosts = 0
        self._config_key = None
        self._generation = 0
        self._driver: Optional["ClusterDriver"] = None
        self._lost: set = set()
        self._excluded: set = set()
        self._single_process_reason: Optional[str] = None
        self._degraded_reason: Optional[str] = None
        #: (generation, {device id -> host index}) — the collective
        #: hot path's cached view of the host groups (rebuilt only on
        #: topology change, never per exchange)
        self._dev_host_map: Optional[tuple] = None

    # -- configuration -------------------------------------------------------
    def configure(self, conf: RapidsConf) -> None:
        """Apply the session's cluster conf (cheap when unchanged; a
        real change bumps the generation so cached trees fence)."""
        enabled = bool(conf.get_entry(CLUSTER_ENABLED))
        hosts = int(conf.get_entry(CLUSTER_NUM_HOSTS))
        with self._lock:
            if hosts <= 0 and self._driver is not None:
                hosts = self._driver.expected_hosts
            key = (enabled, hosts)
            if key == self._config_key:
                return
            self._config_key = key
            self._enabled = enabled
            self._declared_hosts = hosts
            self._generation += 1

    def attach_driver(self, driver: Optional["ClusterDriver"]) -> None:
        """Bind (or clear) the process's cluster driver — the harness /
        deployment entry point. Detaching also clears the fault-domain
        state: a fresh driver starts at full strength."""
        with self._lock:
            self._driver = driver
            self._config_key = None  # re-derive host count next configure
            if driver is None:
                self._lost = set()
                self._excluded = set()
                self._single_process_reason = None
                self._degraded_reason = None
            self._generation += 1

    def driver(self) -> Optional["ClusterDriver"]:
        with self._lock:
            return self._driver

    # -- state ---------------------------------------------------------------
    def active(self) -> bool:
        """Is cluster routing live for THIS thread right now? (enabled,
        driver attached, at least one usable host, no single-process
        latch, no per-attempt suppression)."""
        if _SUPPRESS.get() is not None:
            return False
        with self._lock:
            return (self._enabled and self._driver is not None
                    and self._single_process_reason is None
                    and len(self._usable_hosts_locked()) > 0)

    def _declared_ids_locked(self) -> List[str]:
        return [f"h{i}" for i in range(self._declared_hosts)]

    def _usable_hosts_locked(self) -> List[str]:
        return [h for h in self._declared_ids_locked()
                if h not in self._lost and h not in self._excluded]

    def usable_hosts(self) -> List[str]:
        with self._lock:
            return self._usable_hosts_locked()

    def declared_hosts(self) -> int:
        with self._lock:
            return self._declared_hosts

    def generation(self) -> int:
        with self._lock:
            return self._generation

    def identity_token(self) -> str:
        """Stable token of the current HOST topology — folded into the
        plan fingerprint next to the mesh identity token, so cached
        plans never cross cluster topologies."""
        if _SUPPRESS.get() is not None:
            return "cluster:suppressed"
        with self._lock:
            if not self._enabled or self._driver is None:
                return "cluster:off"
            if self._single_process_reason is not None:
                return "cluster:single-process"
            return (f"cluster:{self._declared_hosts}/"
                    f"lost={','.join(sorted(self._lost))}/"
                    f"excl={','.join(sorted(self._excluded))}")

    def topology_str(self) -> Optional[str]:
        """Human/event-log host topology ('2' at full strength,
        '1/2' degraded); None when cluster execution is off."""
        with self._lock:
            if not self._enabled or self._driver is None:
                return None
            if self._single_process_reason is not None:
                return f"0/{self._declared_hosts}"
            live = len(self._usable_hosts_locked())
            if live == self._declared_hosts:
                return str(self._declared_hosts)
            return f"{live}/{self._declared_hosts}"

    def host_device_ids(self, host_id: str) -> Tuple[int, ...]:
        """Device ids of ``host_id``'s contiguous group (the dcn row
        the host owns when the hierarchical mesh is enabled)."""
        with self._lock:
            n = self._declared_hosts
        if n <= 0:
            return ()
        try:
            idx = int(host_id.lstrip("h"))
        except ValueError:
            return ()
        import jax
        devices = jax.devices()
        per = max(1, len(devices) // n)
        # the LAST host owns any remainder: every device belongs to
        # exactly one host even when the count is not divisible, so a
        # shrink can never strand unowned devices in the mesh
        end = len(devices) if idx == n - 1 else (idx + 1) * per
        return tuple(d.id for d in devices[idx * per:end])

    def device_host_map(self) -> Dict[int, int]:
        """device id -> owning host index for the declared topology,
        cached per generation — the ICI exchange consults this on
        EVERY collective (dcn_exchange_point), so it must not re-walk
        jax.devices() per host per call."""
        with self._lock:
            gen = self._generation
            n = self._declared_hosts
            if (self._dev_host_map is not None
                    and self._dev_host_map[0] == gen):
                return self._dev_host_map[1]
        mapping: Dict[int, int] = {}
        if n > 0:
            import jax
            devices = jax.devices()
            per = max(1, len(devices) // n)
            for i in range(n):
                # last host owns the remainder (host_device_ids's rule)
                end = len(devices) if i == n - 1 else (i + 1) * per
                for d in devices[i * per:end]:
                    mapping[d.id] = i
        with self._lock:
            if self._generation == gen:
                self._dev_host_map = (gen, mapping)
        return mapping

    # -- the host degradation ladder's cluster half --------------------------
    def mark_host_lost(self, host_id: Optional[str], reason: str) -> Optional[str]:
        """Declare one host lost (the sweep's missed-beat verdict, a
        dead dispatch socket, or the ladder's re-land rung). With no
        host named (injected losses), the LAST usable host is the
        deterministic choice. Subsequent scans re-land the host's
        shards onto survivors; the host rejoins via restore_host.
        Returns the host id marked, or None when nothing usable is
        left to mark."""
        with self._lock:
            if host_id is not None and host_id in self._declared_ids_locked():
                if host_id in self._lost or host_id in self._excluded:
                    return host_id  # already marked; never pick a second victim
            else:
                usable = self._usable_hosts_locked()
                if not usable:
                    return None
                host_id = usable[-1]
            self._lost.add(host_id)
            self._degraded_reason = reason
            self._generation += 1
        CLUSTER_SCOPE.add("hostsLost", 1)
        return host_id

    def shrink_excluding(self, host_id: Optional[str], reason: str) -> bool:
        """The ladder's shrink rung: evict one host from the topology
        — its device group leaves the mesh's dcn axis (the generation
        bump fences every cached tree, exactly like a mesh shrink).
        Returns False when no second host remains (the ladder then
        latches single-process)."""
        with self._lock:
            if not self._enabled or self._declared_hosts <= 0:
                return False
            candidates = [h for h in self._declared_ids_locked()
                          if h not in self._excluded]
            if len(candidates) <= 1:
                return False
            if host_id is None or host_id in self._excluded:
                lost_first = [h for h in candidates if h in self._lost]
                host_id = (lost_first or candidates)[-1]
            self._excluded.add(host_id)
            self._lost.discard(host_id)
            self._degraded_reason = reason
            self._generation += 1
        CLUSTER_SCOPE.add("hostShrinks", 1)
        # the host's device group leaves the mesh: the declared
        # hierarchical shape no longer fits the survivors, so the mesh
        # collapses to a flat surviving-device axis (the PR 10 partial-
        # pod contract — correctness never depended on the declared
        # factorization)
        ids = self.host_device_ids(host_id)
        if ids:
            from spark_rapids_tpu.parallel.mesh import MESH
            MESH.exclude_devices(ids, reason)
        return True

    def latch_single_process(self, reason: str) -> None:
        """Bottom cluster rung: stop routing to executors entirely —
        every scan lands locally (still serving, minus the cluster)
        until a host rejoins and restore clears the latch."""
        with self._lock:
            self._single_process_reason = reason
            self._degraded_reason = reason
            self._generation += 1

    def restore_host(self, host_id: str) -> bool:
        """A host rejoined (heartbeat re-register / respawned
        executor's fresh registration): clear its lost/excluded state,
        the single-process latch, and the mesh exclusions its eviction
        caused. Returns whether anything was restored."""
        restore_mesh = False
        with self._lock:
            had = (host_id in self._lost or host_id in self._excluded
                   or self._single_process_reason is not None)
            restore_mesh = host_id in self._excluded
            self._lost.discard(host_id)
            self._excluded.discard(host_id)
            self._single_process_reason = None
            if not self._lost and not self._excluded:
                self._degraded_reason = None
            if had:
                self._generation += 1
        if had:
            CLUSTER_SCOPE.add("hostRestores", 1)
        if restore_mesh:
            from spark_rapids_tpu.parallel.mesh import MESH
            MESH.restore(f"cluster host {host_id} rejoined")
        return had

    def restore(self) -> bool:
        """Clear every host exclusion/latch (the end-of-chaos probe,
        or an operator-driven reset). A host that is genuinely still
        dead just re-walks the ladder."""
        with self._lock:
            had = bool(self._lost or self._excluded
                       or self._single_process_reason)
            lost_mesh = bool(self._excluded)
            self._lost = set()
            self._excluded = set()
            self._single_process_reason = None
            self._degraded_reason = None
            if had:
                self._generation += 1
        if lost_mesh:
            from spark_rapids_tpu.parallel.mesh import MESH
            MESH.restore("cluster topology restored")
        return had

    def degraded_reason(self) -> Optional[str]:
        with self._lock:
            return self._degraded_reason

    def health_snapshot(self) -> dict:
        """The host-topology state QueryService.health()['hosts']
        reports (mirroring the PR 10 mesh section)."""
        with self._lock:
            return self._health_snapshot_locked()

    def _health_snapshot_locked(self) -> dict:
        """Snapshot body for callers that already hold ``self._lock``
        — the shared-topology path (health.consistent_topology_snapshot)
        nests cluster→health→mesh→memory in declared rank order so one
        view can't tear across a mid-query shrink."""
        live = self._usable_hosts_locked() if self._enabled else []
        return {
            "enabled": self._enabled and self._driver is not None,
            "declaredHosts": self._declared_hosts,
            "liveHosts": live,
            "lostHosts": sorted(self._lost),
            "excludedHosts": sorted(self._excluded),
            "singleProcessReason": self._single_process_reason,
            "degradedReason": self._degraded_reason,
            "generation": self._generation,
        }

    # -- scan routing --------------------------------------------------------
    def scan_route(self, scan_node, paths: List[str]):
        """Route one file scan through the cluster, or return None for
        a local scan. Routing requires an active cluster, a format the
        executor side reconstructs, and no hive-partitioned path
        components (partition-value inference must see the FULL file
        list to be stable; a by-host subset could infer differently).
        Unroutable scans under an enabled cluster count
        clusterScanFallbacks."""
        if _SUPPRESS.get() is not None:
            return None
        with self._lock:
            driver = self._driver
            enabled = self._enabled
            routable = (enabled and driver is not None
                        and self._single_process_reason is None)
        if not routable:
            if enabled and driver is not None:
                CLUSTER_SCOPE.add("clusterScanFallbacks", 1)
            return None
        fmt = getattr(scan_node, "format_name", None)
        if fmt not in _EXECUTOR_SCAN_FORMATS or any(
                "=" in comp for p in paths
                for comp in os.path.dirname(p).split(os.sep)):
            CLUSTER_SCOPE.add("clusterScanFallbacks", 1)
            return None
        # the spec must survive the JSON wire: a date/np-typed filter
        # value pyarrow happily accepts locally would otherwise crash
        # the dispatch with an unclassified TypeError mid-query
        try:
            import json
            json.dumps(_scan_spec(scan_node, []))
        except TypeError:
            CLUSTER_SCOPE.add("clusterScanFallbacks", 1)
            return None
        return driver.scan(scan_node, paths)


#: THE process-wide cluster runtime (host topology is process state,
#: like the mesh and the device manager)
CLUSTER = ClusterRuntime()


def sweep_cluster_hosts() -> List[str]:
    """One heartbeat sweep over the attached driver's executor ledger
    (missed-beat threshold -> declare host lost). Called by the
    driver's own sweeper thread AND the query-service watchdog's
    sweep; a no-op without an attached driver."""
    driver = CLUSTER.driver()
    if driver is None:
        return []
    return driver.sweep_once()


def dcn_exchange_point(mesh) -> None:
    """THE cross-host collective marker: called by the ICI exchange
    before its all-to-all; when the exchange's mesh spans more than
    one cluster host group the collective crosses the DCN axis — the
    ``host.dcn.exchange`` fault point fires (device_lost there raises
    HostLostError into the host ladder) and dcnExchanges counts."""
    if not CLUSTER.active():
        return
    id_to_host = CLUSTER.device_host_map()
    if not id_to_host:
        return
    groups = set()
    for d in mesh.devices.flat:
        groups.add(id_to_host.get(d.id, -1))
        if len(groups) > 1:
            break
    if len(groups) <= 1:
        return
    from spark_rapids_tpu.runtime.faults import fault_point
    fault_point("host.dcn.exchange")
    CLUSTER_SCOPE.add("dcnExchanges", 1)


# ---------------------------------------------------------------------------
# Wire protocol (framed JSON header + optional binary payload, the P2P
# shuffle transport's framing pattern)
# ---------------------------------------------------------------------------


def _send_msg(sock: socket.socket, obj: dict, payload: bytes = b"") -> None:
    import json
    head = json.dumps(obj).encode("utf-8")
    sock.sendall(struct.pack("<II", len(head), len(payload)))
    sock.sendall(head)
    if payload:
        sock.sendall(payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("cluster peer closed the connection")
        buf.extend(chunk)
    return bytes(buf)


def _recv_msg(sock: socket.socket) -> Tuple[dict, bytes]:
    import json
    head_len, payload_len = struct.unpack("<II", _recv_exact(sock, 8))
    obj = json.loads(_recv_exact(sock, head_len).decode("utf-8"))
    payload = _recv_exact(sock, payload_len) if payload_len else b""
    return obj, payload


def _scan_spec(scan_node, paths: List[str]) -> dict:
    """The wire form of one host's scan assignment: enough for the
    executor to reconstruct the SAME scan node over its path subset
    (PERFILE mode pins one batch per file, so driver-side reassembly
    in path order is byte-identical to a local scan)."""
    spec = {
        "type": "scan",
        "format": scan_node.format_name,
        "paths": paths,
        "columns": scan_node.columns,
        "options": dict(scan_node.options),
        "file_info": bool(getattr(scan_node, "provide_file_info", False)),
    }
    filters = getattr(scan_node, "filters", None)
    if filters is not None:
        spec["filters"] = [list(f) for f in filters]
    return spec


def _build_scan_node(spec: dict):
    """Executor side of _scan_spec."""
    fmt = spec["format"]
    kwargs = dict(spec.get("options") or {})
    if fmt == "parquet":
        from spark_rapids_tpu.io.parquet import ParquetScanNode as cls
        filters = spec.get("filters")
        if filters is not None:
            kwargs["filters"] = [tuple(f) for f in filters]
    else:
        raise ValueError(f"unsupported cluster scan format {fmt!r}")
    node = cls(spec["paths"], RapidsConf({}), columns=spec.get("columns"),
               reader_type="PERFILE", **kwargs)
    if spec.get("file_info"):
        node.enable_file_info()
    return node


# ---------------------------------------------------------------------------
# Driver half
# ---------------------------------------------------------------------------


class _HostChannel:
    """One executor's data connection (driver->executor RPC). A lock
    serializes round trips; concurrent scans over one host queue."""

    __slots__ = ("host_id", "sock", "lock")

    def __init__(self, host_id: str, sock: socket.socket):
        self.host_id = host_id
        self.sock = sock
        self.lock = ordered_lock("cluster.channel")


class ClusterDriver:
    """The driver half of the cluster protocol: listens on a loopback
    socket, registers executor data/beat connections, dispatches scan
    work per host, sweeps heartbeats, and feeds host losses/rejoins
    into :data:`CLUSTER`. One instance per process (the harness or a
    real deployment attaches it via ``CLUSTER.attach_driver``)."""

    def __init__(self, expected_hosts: int,
                 conf: Optional[RapidsConf] = None):
        conf = conf or RapidsConf({})
        self.expected_hosts = int(expected_hosts)
        self.heartbeat_ms = int(conf.get_entry(CLUSTER_HEARTBEAT_MS))
        self.missed_beats = int(conf.get_entry(CLUSTER_MISSED_BEATS))
        self.dispatch_timeout_s = (
            int(conf.get_entry(CLUSTER_DISPATCH_TIMEOUT_MS)) / 1000.0)
        from spark_rapids_tpu.shuffle.heartbeat import (
            ShuffleHeartbeatManager,
        )
        self._hb = ShuffleHeartbeatManager(
            heartbeat_timeout_s=self.missed_beats * self.heartbeat_ms
            / 1000.0)
        self._lock = ordered_lock("cluster.driver")
        self._channels: Dict[str, _HostChannel] = {}
        self._registered: set = set()
        #: hosts with an OPEN beat connection right now — beat-conn EOF
        #: is the prompt, unambiguous death signal (a SIGKILLed process
        #: closes its sockets); the missed-beat sweep is the slower
        #: path for wedged-but-connected executors
        self._beat_alive: set = set()
        self._shutdown = False
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind(("127.0.0.1", 0))
        self._listener.listen(64)
        self.address = self._listener.getsockname()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="rapids-cluster-accept",
            daemon=True)
        self._accept_thread.start()
        self._sweep_thread = threading.Thread(
            target=self._sweep_loop, name="rapids-cluster-sweep",
            daemon=True)
        self._sweep_thread.start()

    # -- lifecycle -----------------------------------------------------------
    def shutdown(self) -> None:
        with self._lock:
            self._shutdown = True
            channels = list(self._channels.values())
            self._channels = {}
        for ch in channels:
            try:
                with ch.lock:
                    _send_msg(ch.sock, {"type": "shutdown"})
                    ch.sock.close()
            except OSError:
                pass
        try:
            self._listener.close()
        except OSError:
            pass

    # -- accept / registration ----------------------------------------------
    def _accept_loop(self) -> None:
        while True:
            try:
                conn, _addr = self._listener.accept()
            except OSError:
                return  # listener closed
            try:
                hello, _ = _recv_msg(conn)
            except (OSError, ValueError, ConnectionError):
                conn.close()
                continue
            host_id = str(hello.get("host", ""))
            role = hello.get("role")
            if role == "data":
                self._register_data(host_id, conn)
            elif role == "beat":
                threading.Thread(
                    target=self._beat_loop, args=(host_id, conn),
                    name=f"rapids-cluster-beat-{host_id}",
                    daemon=True).start()
            else:
                conn.close()

    def _register_data(self, host_id: str, conn: socket.socket) -> None:
        conn.settimeout(self.dispatch_timeout_s)
        rejoined = False
        with self._lock:
            if self._shutdown:
                conn.close()
                return
            old = self._channels.get(host_id)
            self._channels[host_id] = _HostChannel(host_id, conn)
            rejoined = host_id in self._registered
            self._registered.add(host_id)
        if old is not None:
            try:
                old.sock.close()
            except OSError:
                pass
        if rejoined:
            # a respawned executor's fresh registration: the host
            # rejoins the topology at full strength
            CLUSTER.restore_host(host_id)

    def _beat_loop(self, host_id: str, conn: socket.socket) -> None:
        """Driver side of one executor's heartbeat connection: the PR 3
        register/beat/evict/re-register protocol over the wire. An
        injected ``host.heartbeat`` fault DROPS the beat (counted) —
        enough dropped beats and the sweep declares the host lost, the
        exact missed-beat path a wedged executor takes."""
        from spark_rapids_tpu.errors import ColumnarProcessingError
        from spark_rapids_tpu.runtime.faults import fault_point
        from spark_rapids_tpu.shuffle.transport import PeerInfo
        me = PeerInfo(executor_id=host_id)
        self._hb.register_executor(me)
        with self._lock:
            self._beat_alive.add(host_id)
        try:
            _send_msg(conn, {"type": "registered"})
            while True:
                msg, _ = _recv_msg(conn)
                kind = msg.get("type")
                if kind == "beat":
                    try:
                        fault_point("host.heartbeat")
                        self._hb.heartbeat(host_id)
                        _send_msg(conn, {"type": "ok"})
                    except ColumnarProcessingError:
                        # the ledger evicted us between beats: tell the
                        # executor so it re-registers (rejoin path)
                        _send_msg(conn, {"type": "evicted"})
                    except Exception:
                        # injected beat fault: drop the beat, keep the
                        # connection — missing enough of them IS the
                        # failure mode under test
                        CLUSTER_SCOPE.add("executorBeatsDropped", 1)
                        _send_msg(conn, {"type": "dropped"})
                elif kind == "register":
                    self._hb.register_executor(me)
                    CLUSTER.restore_host(host_id)
                    _send_msg(conn, {"type": "registered"})
                else:
                    return
        except (OSError, ValueError, ConnectionError):
            # beat-connection EOF: the executor PROCESS is gone (a
            # SIGKILL closes its sockets) — declare the host lost
            # immediately instead of waiting out the beat window
            with self._lock:
                down = not self._shutdown
            if down:
                CLUSTER.mark_host_lost(
                    host_id,
                    f"host {host_id} heartbeat connection lost "
                    f"(executor process down)")
            return
        finally:
            with self._lock:
                self._beat_alive.discard(host_id)
            try:
                conn.close()
            except OSError:
                pass

    # -- health --------------------------------------------------------------
    def sweep_once(self) -> List[str]:
        """Evict executors that missed the beat window and declare
        their hosts lost (the watchdog's executor-heartbeat sweep) —
        and RESTORE lost hosts that are provably alive again (beating
        on an open connection, data channel usable): a ladder-marked
        host whose process never actually died — an injected transient
        loss — rejoins on evidence of health, the same outcome as the
        evicted->re-register path without waiting for an eviction."""
        dead = self._hb.evict_dead()
        for host_id in dead:
            CLUSTER.mark_host_lost(
                host_id,
                f"host {host_id} missed {self.missed_beats} heartbeats "
                f"({self.heartbeat_ms}ms interval)")
        snap = CLUSTER.health_snapshot()
        if snap["lostHosts"]:
            alive = set(self._hb.live_executors())
            with self._lock:
                beating = set(self._beat_alive)
                have = set(self._channels)
            for host_id in snap["lostHosts"]:
                if (host_id in alive and host_id in beating
                        and host_id in have):
                    CLUSTER.restore_host(host_id)
        return dead

    def _sweep_loop(self) -> None:
        interval = max(0.02, self.heartbeat_ms / 1000.0 / 2)
        while True:
            with self._lock:
                if self._shutdown:
                    return
            self.sweep_once()
            time.sleep(interval)

    def live_hosts(self) -> List[str]:
        """Hosts with a usable data channel, in declared order."""
        with self._lock:
            return sorted(self._channels)

    def wait_ready(self, n: Optional[int] = None,
                   timeout_s: float = 30.0) -> None:
        """Block until ``n`` (default: expected) executors have
        registered both channels."""
        want = n if n is not None else self.expected_hosts
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._lock:
                ready = len(self._channels)
            if ready >= want and len(self._hb.live_executors()) >= want:
                return
            time.sleep(0.02)
        raise TimeoutError(
            f"cluster driver: only "
            f"{len(self.live_hosts())}/{want} executors registered "
            f"within {timeout_s}s")

    # -- scan dispatch -------------------------------------------------------
    def _channel(self, host_id: str) -> _HostChannel:
        from spark_rapids_tpu.errors import HostLostError
        with self._lock:
            ch = self._channels.get(host_id)
        if ch is None:
            raise HostLostError(
                f"no data channel to executor host {host_id}",
                host_id=host_id)
        return ch

    def _drop_channel(self, host_id: str, ch: _HostChannel) -> None:
        with self._lock:
            if self._channels.get(host_id) is ch:
                del self._channels[host_id]
        try:
            ch.sock.close()
        except OSError:
            pass

    def scan_host(self, host_id: str, scan_node,
                  paths: List[str]) -> List[bytes]:
        """One driver->executor scan round trip: dispatch the host's
        path subset (the ``host.dispatch`` fault point), receive one
        TPAK frame per file. A socket failure/timeout mid-round-trip
        is a HOST loss (the process, not one request, is presumed
        gone) — typed HostLostError, channel dropped, ladder recovers.

        Cross-host trace propagation: when the driver's span tracer is
        live, the dispatch frame carries a ``trace`` flag — the
        executor runs its own SpanTracer around the scan and ships the
        span summaries (plus per-scan wall/bytes) back in the reply
        header, which merge into this query's trace on an
        ``executor-<host>`` lane and into the per-host ``hostScans``
        event-record attribution."""
        from spark_rapids_tpu.errors import HostLostError
        from spark_rapids_tpu.obs.spans import TRACER
        from spark_rapids_tpu.runtime.faults import fault_point
        ch = self._channel(host_id)
        fault_point("host.dispatch")
        spec = _scan_spec(scan_node, paths)
        if TRACER.enabled:
            spec["trace"] = True
        t0 = time.perf_counter()
        try:
            with ch.lock:
                _send_msg(ch.sock, spec)
                reply, _ = _recv_msg(ch.sock)
                if reply.get("type") == "error":
                    # a QUERY-scoped executor error (unreadable file,
                    # decode failure): the executor kept its loop and
                    # the channel stays usable — typed for the ladder,
                    # but never manufactured into a dead process
                    raise HostLostError(
                        f"executor host {host_id} failed its scan: "
                        f"{reply.get('error')}", host_id=host_id)
                frames = []
                for _ in range(int(reply.get("n", 0))):
                    _head, payload = _recv_msg(ch.sock)
                    frames.append(payload)
        except HostLostError:
            raise  # channel intact (error reply / injected fault)
        except (OSError, ValueError, ConnectionError) as exc:
            # the WIRE failed mid-round-trip: the process is presumed
            # gone — only here does the channel drop
            self._drop_channel(host_id, ch)
            raise HostLostError(
                f"executor host {host_id} lost mid-dispatch "
                f"({type(exc).__name__}: {exc})",
                host_id=host_id) from exc
        wall = time.perf_counter() - t0
        exec_scan = reply.get("scan") or {}
        _bump_host_stat(host_id, scans=1, files=len(frames),
                        bytes=sum(len(f) for f in frames), wallS=wall,
                        execWallS=float(exec_scan.get("wallS", 0.0)))
        spans = reply.get("spans")
        if spans:
            # anchor the executor's relative span clock at the dispatch
            # send: durations are exact, offsets shifted by the one-way
            # wire latency (different perf_counter domains)
            TRACER.add_remote_spans(host_id, spans, t0)
        return frames

    def scan(self, scan_node, paths: List[str]):
        """Partition ``paths`` BY HOST (contiguous slices over the
        usable hosts, so global path order — and therefore batch order
        and bit-identity — is preserved), dispatch each host's subset,
        and yield the landed batches in path order. A lost host's
        slice re-lands on survivors automatically: the assignment only
        ever covers usable hosts (hostRelands counts each lost host
        whose work was re-assigned)."""
        from spark_rapids_tpu.errors import CorruptFrameError, HostLostError
        from spark_rapids_tpu.obs.spans import TRACER
        from spark_rapids_tpu.runtime.faults import fault_point
        from spark_rapids_tpu.shuffle.serializer import unpack_table

        live = set(self.live_hosts())
        usable = [h for h in CLUSTER.usable_hosts() if h in live]
        if not usable:
            raise HostLostError(
                "no live executor hosts to scan against", host_id=None)
        # re-lands count LOST hosts only (their work is being routed
        # around, pending a rejoin); EXCLUDED hosts left the topology
        # deliberately via the shrink rung — steady-state scans on the
        # shrunk cluster are not degradation events
        relanded = len(CLUSTER.health_snapshot()["lostHosts"])
        if relanded > 0:
            CLUSTER_SCOPE.add("hostRelands", relanded)
        # contiguous slices in host order preserve global path order
        per = (len(paths) + len(usable) - 1) // len(usable)
        for i, host_id in enumerate(usable):
            sub = paths[i * per:(i + 1) * per]
            if not sub:
                continue
            # one driver-side span per dispatched host: the dispatch
            # round trip is attributed wall (executing thread), and the
            # executor's own spans nest under an executor-<host> lane
            sp = (TRACER.begin("cluster.scan", "cluster", host=host_id,
                               files=len(sub)) if TRACER.enabled else None)
            try:
                frames = self.scan_host(host_id, scan_node, sub)
            finally:
                TRACER.end(sp)
            for frame in frames:
                # THE host shard landing point: corrupt damages the
                # landed copy and the TPAK CRC catches it — the intact
                # received frame re-lands (hostShardRetries), modeling
                # a refetch from the executor's intact buffer; chronic
                # corruption classifies as a host loss
                for attempt in range(SHARD_LAND_RETRIES + 1):
                    data = fault_point("host.shard.land", data=frame)
                    try:
                        table, _ = unpack_table(data)
                        break
                    except CorruptFrameError as exc:
                        CLUSTER_SCOPE.add("hostShardRetries", 1)
                        _bump_host_stat(host_id, crcRetries=1)
                        if attempt >= SHARD_LAND_RETRIES:
                            raise HostLostError(
                                f"host {host_id} shard landing failed "
                                f"its CRC {attempt + 1} times "
                                f"({exc})", host_id=host_id) from exc
                CLUSTER_SCOPE.add("hostShardsLanded", 1)
                yield table


# ---------------------------------------------------------------------------
# Executor half
# ---------------------------------------------------------------------------


def _executor_scan(msg: dict, host_id: str):
    """Run one dispatched scan on the executor, optionally under the
    executor's OWN SpanTracer (the driver's dispatch frame carries a
    ``trace`` flag when its tracer is live): per-file decode + pack
    spans collect locally and ship back as compact summaries — t0
    relative to scan start, so the driver can merge them into ITS
    query trace on an executor lane. Returns (frames, scan_summary,
    span_payload)."""
    from spark_rapids_tpu.obs.spans import TRACER
    from spark_rapids_tpu.shuffle.serializer import pack_table
    want_trace = bool(msg.get("trace"))
    node = _build_scan_node(msg)
    t_q0 = time.perf_counter()
    frames: List[bytes] = []
    span_payload: List[dict] = []
    # the executor's scan is ALWAYS local: in thread mode (tests) this
    # process also hosts the driver, and an unsuppressed scan would
    # recurse through scan_route back to this very executor — deadlock
    # by construction
    with suppressed_cluster("executor-local scan"):
        if not want_trace:
            frames = [pack_table(t) for t in node.execute_cpu()]
        else:
            TRACER.begin_query(0)
            try:
                it = node.execute_cpu()
                i = 0
                while True:
                    t_f0 = time.perf_counter()
                    try:
                        table = next(it)
                    except StopIteration:
                        break
                    sp = TRACER.begin("executor.scan.file", "exec-scan",
                                      index=i)
                    if sp is not None:
                        sp.t0 = t_f0  # decode happened inside next()
                    TRACER.end(sp)
                    sp = TRACER.begin("executor.pack", "exec-scan",
                                      index=i)
                    frames.append(pack_table(table))
                    TRACER.end(sp)
                    i += 1
            finally:
                spans = TRACER.end_query()
            span_payload = [
                {"name": s.name, "cat": s.cat,
                 "t0": round(s.t0 - t_q0, 6), "dur": round(s.dur, 6),
                 "args": s.args}
                for s in spans][:256]
    scan_summary = {
        "wallS": round(time.perf_counter() - t_q0, 6),
        "files": len(frames),
        "bytes": sum(len(f) for f in frames),
        "host": host_id,
        "pid": os.getpid(),
    }
    return frames, scan_summary, span_payload


def _executor_serve_data(sock: socket.socket, host_id: str) -> None:
    """Executor data loop: serve driver scan requests until shutdown.
    One frame per file batch (PERFILE), TPAK-serialized — the same
    bytes the P2P shuffle moves."""
    while True:
        msg, _ = _recv_msg(sock)
        kind = msg.get("type")
        if kind == "scan":
            try:
                frames, scan_summary, span_payload = _executor_scan(
                    msg, host_id)
            except Exception as exc:  # noqa: BLE001 - report to driver
                _send_msg(sock, {"type": "error",
                                 "error": f"{type(exc).__name__}: {exc}"})
                continue
            reply = {"type": "scan_result", "n": len(frames),
                     "scan": scan_summary}
            if span_payload:
                reply["spans"] = span_payload
            _send_msg(sock, reply)
            for frame in frames:
                _send_msg(sock, {"type": "frame"}, payload=frame)
        elif kind == "ping":
            _send_msg(sock, {"type": "pong", "host": host_id,
                             "pid": os.getpid()})
        elif kind == "shutdown":
            return
        else:
            return


def _executor_beat_loop(host: str, port: int, host_id: str,
                        heartbeat_ms: int, stop: threading.Event) -> None:
    """Executor heartbeat loop: beat every interval; an ``evicted``
    reply re-registers (the PR 3 beat_or_recover rejoin path over the
    wire)."""
    try:
        sock = socket.create_connection((host, port), timeout=10.0)
        # block on replies: a driver wedged in a long GIL-holding
        # compile answers late, not never — timing out here would kill
        # the beat loop and read as a DEAD executor to the sweep
        sock.settimeout(None)
        _send_msg(sock, {"type": "hello", "role": "beat", "host": host_id})
        _recv_msg(sock)  # registered
        while not stop.wait(heartbeat_ms / 1000.0):
            _send_msg(sock, {"type": "beat"})
            reply, _ = _recv_msg(sock)
            if reply.get("type") == "evicted":
                _send_msg(sock, {"type": "register"})
                _recv_msg(sock)  # registered
    except (OSError, ValueError, ConnectionError):
        return  # driver gone; the data loop's failure ends the process


def _executor_run(host: str, port: int, host_id: str,
                  heartbeat_ms: int,
                  stop: Optional[threading.Event] = None) -> None:
    """One executor's lifetime: register both channels, beat on a
    background thread, serve scans until the driver closes."""
    stop = stop or threading.Event()
    beat = threading.Thread(
        target=_executor_beat_loop,
        args=(host, port, host_id, heartbeat_ms, stop),
        name=f"rapids-executor-beat-{host_id}", daemon=True)
    beat.start()
    try:
        sock = socket.create_connection((host, port), timeout=10.0)
        # connect timeout only: the data loop BLOCKS between requests
        # (an idle executor waiting for work is healthy, not dead —
        # liveness is the beat channel's job)
        sock.settimeout(None)
        _send_msg(sock, {"type": "hello", "role": "data", "host": host_id})
        _executor_serve_data(sock, host_id)
    except (OSError, ValueError, ConnectionError):
        pass
    finally:
        stop.set()


class ExecutorHandle:
    """Harness handle over one spawned executor (subprocess or
    in-process thread — the latter for cheap protocol tests)."""

    def __init__(self, host_id: str, mode: str, proc=None, thread=None,
                 stop: Optional[threading.Event] = None):
        self.host_id = host_id
        self.mode = mode
        self.proc = proc
        self.thread = thread
        self._stop = stop

    def alive(self) -> bool:
        if self.mode == "process":
            return self.proc is not None and self.proc.poll() is None
        return self.thread is not None and self.thread.is_alive()

    def terminate(self) -> None:
        """Kill the executor (the chaos harness's host kill)."""
        if self.mode == "process" and self.proc is not None:
            self.proc.kill()
            self.proc.wait(timeout=10)
        elif self._stop is not None:
            self._stop.set()


def spawn_executor(address: Tuple[str, int], host_id: str,
                   heartbeat_ms: int = 250,
                   mode: str = "process") -> ExecutorHandle:
    """Start one executor against a driver ``address``. ``process``
    spawns ``python -m spark_rapids_tpu.runtime.cluster_exec`` (the
    real multi-process harness; the shim module — running cluster.py
    itself under -m would double-import it); ``thread`` runs the same
    protocol loops in-process (fast protocol tests, no process
    isolation)."""
    host, port = address
    if mode == "thread":
        stop = threading.Event()
        t = threading.Thread(
            target=_executor_run,
            args=(host, port, host_id, heartbeat_ms, stop),
            name=f"rapids-executor-{host_id}", daemon=True)
        t.start()
        return ExecutorHandle(host_id, mode, thread=t, stop=stop)
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.Popen(
        [sys.executable, "-m", "spark_rapids_tpu.runtime.cluster_exec",
         "--host-id", host_id, "--driver-host", host,
         "--driver-port", str(port), "--heartbeat-ms", str(heartbeat_ms)],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    return ExecutorHandle(host_id, mode, proc=proc)


def executor_main(argv: Optional[List[str]] = None) -> int:
    """The executor process entry point of the multi-process
    simulation harness — launched as ``python -m
    spark_rapids_tpu.runtime.cluster_exec`` (a shim module: running
    THIS module under -m would import it twice and double-register
    its conf keys)."""
    import argparse
    ap = argparse.ArgumentParser(prog="spark_rapids_tpu.runtime.cluster")
    ap.add_argument("--host-id", required=True)
    ap.add_argument("--driver-host", default="127.0.0.1")
    ap.add_argument("--driver-port", type=int, required=True)
    ap.add_argument("--heartbeat-ms", type=int, default=250)
    args = ap.parse_args(argv)
    _executor_run(args.driver_host, args.driver_port, args.host_id,
                  args.heartbeat_ms)
    return 0
