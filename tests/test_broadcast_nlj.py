"""Broadcast exchange + conditioned nested-loop joins (reference analog:
GpuBroadcastExchangeExec / GpuBroadcastNestedLoopJoinExec)."""

import pytest

from spark_rapids_tpu.ops.expr import col, lit

from tests.asserts import assert_tpu_and_cpu_are_equal
from tests.data_gen import DoubleGen, IntGen, StringGen, gen_table


def _dfs(sess, n_left=300, n_right=40, nb=3, seed=53):
    from spark_rapids_tpu.plan import from_host_table
    lg = {"a": IntGen(min_val=0, max_val=60), "lv": DoubleGen(corner_prob=0.0)}
    rg = {"b": IntGen(min_val=0, max_val=60), "rv": IntGen(min_val=0, max_val=60)}
    left = from_host_table(gen_table(lg, n_left, seed), sess, nb)
    right = from_host_table(gen_table(rg, n_right, seed + 1), sess, 1)
    return left, right


@pytest.mark.parametrize("how", ["inner", "left", "right", "full",
                                 "leftsemi", "leftanti"])
def test_nlj_condition_join_types(session, cpu_session, how):
    def build(s):
        left, right = _dfs(s)
        return left.join(right, on=col("a") < col("rv"), how=how)
    assert_tpu_and_cpu_are_equal(build, session, cpu_session)


def test_nlj_range_band_condition(session, cpu_session):
    """Band join: a BETWEEN b-5 AND b+5 — the classic NLJ workload."""
    def build(s):
        left, right = _dfs(s)
        cond = (col("a") >= col("b") - lit(5)) & (col("a") <= col("b") + lit(5))
        return left.join(right, on=cond, how="inner")
    assert_tpu_and_cpu_are_equal(build, session, cpu_session)


def test_nlj_condition_with_nulls(session, cpu_session):
    def build(s):
        from spark_rapids_tpu.plan import from_host_table
        lg = {"a": IntGen(min_val=0, max_val=20, null_prob=0.3)}
        rg = {"b": IntGen(min_val=0, max_val=20, null_prob=0.3)}
        left = from_host_table(gen_table(lg, 120, 5), s, 2)
        right = from_host_table(gen_table(rg, 30, 6), s, 1)
        return left.join(right, on=col("a") == col("b") + lit(1), how="full")
    assert_tpu_and_cpu_are_equal(build, session, cpu_session)


def test_nlj_runs_on_device(session):
    from tests.asserts import assert_runs_on_tpu
    def build(s):
        left, right = _dfs(s)
        return left.join(right, on=col("a") < col("rv"), how="left")
    assert_runs_on_tpu(build, session)




def _collect_execs(root, cls):
    found = []

    def walk(e):
        if isinstance(e, cls):
            found.append(e)
        for c in getattr(e, "children", ()):
            walk(c)
        for attr in ("source", "tpu_exec", "cpu_node"):
            nxt = getattr(e, attr, None)
            if nxt is not None:
                walk(nxt)

    walk(root)
    return found


def test_broadcast_exchange_selected_for_small_build(session):
    """Small build sides (LocalScan size estimate) go through the broadcast
    exchange; the table materializes once and is reused."""
    from spark_rapids_tpu.overrides import apply_overrides
    from spark_rapids_tpu.execs.broadcast import TpuBroadcastExchangeExec

    from spark_rapids_tpu.plan import from_host_table
    l2 = {"k": IntGen(min_val=0, max_val=9), "x": IntGen()}
    r2 = {"k": IntGen(min_val=0, max_val=9), "y": IntGen()}
    left = from_host_table(gen_table(l2, 200, 1), session, 1)
    right = from_host_table(gen_table(r2, 50, 2), session, 1)
    j = left.join(right, on="k", how="inner")
    executable, _ = apply_overrides(j.plan, session.conf)

    found = _collect_execs(executable, TpuBroadcastExchangeExec)
    assert len(found) == 1, "build side should broadcast"
    list(executable.execute_cpu())
    assert found[0]._cached is not None
    cached = found[0]._cached
    list(executable.execute_cpu())
    assert found[0]._cached is cached  # reused, not rebuilt


def test_broadcast_disabled_by_threshold(session):
    from spark_rapids_tpu.session import TpuSession
    from spark_rapids_tpu.overrides import apply_overrides
    from spark_rapids_tpu.execs.broadcast import TpuBroadcastExchangeExec
    from spark_rapids_tpu.plan import from_host_table

    off = TpuSession({"spark.rapids.sql.broadcastSizeBytes": 0})
    l2 = {"k": IntGen(min_val=0, max_val=9)}
    left = from_host_table(gen_table(l2, 100, 1), off, 1)
    right = from_host_table(gen_table(l2, 20, 2), off, 1)
    executable, _ = apply_overrides(
        left.join(right, on="k", how="inner").plan, off.conf)

    found = _collect_execs(executable, TpuBroadcastExchangeExec)
    assert not found


# -- AQE runtime broadcast conversion ---------------------------------------

def _find_adaptive(e):
    """Locate the TpuAdaptiveBuildExec in a converted plan tree."""
    from spark_rapids_tpu.execs.broadcast import TpuAdaptiveBuildExec
    found = _collect_execs(e, TpuAdaptiveBuildExec)
    return found[0] if found else None


def test_aqe_runtime_broadcast_conversion(session, cpu_session):
    """A build side with NO static estimate converts to broadcast at
    runtime when measured under the threshold (DynamicJoinSelection
    analog); the decision is visible in the exec tree + metrics."""
    import numpy as np
    from spark_rapids_tpu import functions as F
    from spark_rapids_tpu.execs.broadcast import TpuAdaptiveBuildExec
    from spark_rapids_tpu.overrides.rules import apply_overrides
    from spark_rapids_tpu.plan import nodes as P
    from spark_rapids_tpu.plan import from_host_table
    from spark_rapids_tpu.columnar import HostTable
    from spark_rapids_tpu.ops.expr import col

    rng = np.random.default_rng(0)
    big = HostTable.from_pydict(
        {"k": rng.integers(0, 50, 5000).astype(np.int64),
         "v": rng.standard_normal(5000)})
    small = HostTable.from_pydict(
        {"k": np.arange(50, dtype=np.int64),
         "w": np.arange(50, dtype=np.int64) * 10})

    # hide the static estimate so the planner cannot prove broadcast
    scan = P.LocalScan([small])
    scan.estimate_bytes = lambda: None

    join = P.Join(P.LocalScan([big]), scan, "inner",
                  [col("k")], [col("k")])
    executable, _meta = apply_overrides(join, session.conf)

    ab = _find_adaptive(executable)
    assert ab is not None, "AQE adaptive build not planned"
    assert ab.converted is None  # undecided before execution

    rows = HostTable.concat(list(executable.execute_cpu()))
    assert rows.num_rows == 5000
    assert ab.converted is True  # runtime-measured small -> broadcast
    assert ab.metrics.get("aqeBroadcastConverted") == 1

    # oracle: result matches CPU join
    want = (from_host_table(big, cpu_session)
            .join(from_host_table(small, cpu_session), on=["k"])
            .count())
    assert rows.num_rows == want


def test_aqe_large_build_stays_shuffle(session):
    import numpy as np
    from spark_rapids_tpu.execs.broadcast import TpuAdaptiveBuildExec
    from spark_rapids_tpu.overrides.rules import apply_overrides
    from spark_rapids_tpu.plan import nodes as P
    from spark_rapids_tpu.columnar import HostTable
    from spark_rapids_tpu.ops.expr import col
    from spark_rapids_tpu.session import TpuSession

    s = TpuSession({"spark.rapids.sql.broadcastSizeBytes": "64"})
    rng = np.random.default_rng(1)
    left = HostTable.from_pydict(
        {"k": rng.integers(0, 20, 500).astype(np.int64)})
    right = HostTable.from_pydict(
        {"k": np.arange(20, dtype=np.int64),
         "w": np.arange(20, dtype=np.int64)})
    scan = P.LocalScan([right])
    scan.estimate_bytes = lambda: None
    join = P.Join(P.LocalScan([left]), scan, "inner", [col("k")], [col("k")])
    executable, _ = apply_overrides(join, s.conf)

    ab = _find_adaptive(executable)
    assert ab is not None
    out = list(executable.execute_cpu())
    assert sum(t.num_rows for t in out) == 500
    assert ab.converted is False  # 20-row build > 64-byte threshold
