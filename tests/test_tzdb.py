"""Timezone DB tests (reference: GpuTimeZoneDB + timezone matrix in CI —
SURVEY §2.9/§4): transition-table correctness vs zoneinfo, DST overlap/
gap resolution, device == host, engine integration for named zones."""

import datetime as dt
from zoneinfo import ZoneInfo

import numpy as np
import pytest

from spark_rapids_tpu.ops.tzdb import (
    TimeZoneDB,
    from_utc_micros_host,
    to_utc_micros_host,
)

EPOCH = dt.datetime(1970, 1, 1, tzinfo=dt.timezone.utc)
US = dt.timedelta(microseconds=1)


def _micros(d: dt.datetime) -> int:
    return int((d - EPOCH) / US)


@pytest.mark.parametrize("zone", ["America/New_York", "Europe/Berlin",
                                  "Asia/Kolkata", "Australia/Sydney"])
def test_from_utc_matches_zoneinfo(zone):
    z = ZoneInfo(zone)
    rng = np.random.default_rng(0)
    # random instants over 1975..2035, plus points near DST edges
    secs = rng.integers(157766400, 2051222400, 300)
    samples = [int(s) * 1_000_000 for s in secs]
    got = from_utc_micros_host(np.array(samples, dtype=np.int64), zone)
    for m, g in zip(samples, got):
        utc = EPOCH + m * US
        local = utc.astimezone(z)
        want = m + int(local.utcoffset() / US)
        assert g == want, (zone, utc, g, want)


def test_to_utc_gap_and_overlap_new_york():
    zone = "America/New_York"
    # 2024: spring forward Mar 10 02:00 EST -> 03:00 EDT; fall back
    # Nov 3 02:00 EDT -> 01:00 EST
    def wall(y, mo, d, h, mi=0):
        return _micros(dt.datetime(y, mo, d, h, mi,
                                   tzinfo=dt.timezone.utc))

    vals = np.array([
        wall(2024, 3, 10, 1, 30),    # before gap: EST (-5)
        wall(2024, 3, 10, 2, 30),    # IN the gap: resolves with EST
        wall(2024, 3, 10, 3, 30),    # after gap: EDT (-4)
        wall(2024, 11, 3, 1, 30),    # ambiguous: earlier offset (EDT)
        wall(2024, 11, 3, 3, 0),     # after overlap: EST
    ], dtype=np.int64)
    got = to_utc_micros_host(vals, zone)
    offs = (vals - got) // 3_600_000_000  # hours
    assert offs.tolist() == [-5, -5, -4, -4, -5]


def test_roundtrip_outside_transitions():
    zone = "Europe/Berlin"
    rng = np.random.default_rng(1)
    samples = np.array([int(s) * 1_000_000 for s in
                        rng.integers(0, 2 * 10**9, 500)], dtype=np.int64)
    local = from_utc_micros_host(samples, zone)
    back = to_utc_micros_host(local, zone)
    # ambiguous-hour wall times legitimately differ; all others roundtrip
    mismatch = (back != samples).sum()
    assert mismatch <= 2


def test_device_matches_host(session):
    import jax.numpy as jnp
    from spark_rapids_tpu.ops.tzdb import from_utc_micros_dev, to_utc_micros_dev
    zone = "Australia/Sydney"
    rng = np.random.default_rng(2)
    samples = np.array([int(s) * 1_000_000 for s in
                        rng.integers(0, 2 * 10**9, 200)], dtype=np.int64)
    assert np.array_equal(
        np.asarray(from_utc_micros_dev(jnp.asarray(samples), zone)),
        from_utc_micros_host(samples, zone))
    assert np.array_equal(
        np.asarray(to_utc_micros_dev(jnp.asarray(samples), zone)),
        to_utc_micros_host(samples, zone))


def test_engine_named_zone_on_device(session, cpu_session):
    """from/to_utc_timestamp with a DST zone now runs on DEVICE and
    matches the CPU oracle."""
    from spark_rapids_tpu import functions as F
    from spark_rapids_tpu import types as T
    from spark_rapids_tpu.ops.expr import col, lit
    from tests.asserts import assert_runs_on_tpu

    rng = np.random.default_rng(3)
    ts = (rng.integers(0, 2 * 10**9, 1000) * 1_000_000).astype(np.int64)

    def q(s):
        df = s.create_dataframe({"t": ts}, dtypes={"t": T.TIMESTAMP})
        return df.select(
            F.from_utc_timestamp(col("t"), lit("America/New_York"))
            .alias("l"),
            F.to_utc_timestamp(col("t"), lit("Europe/Berlin"))
            .alias("u"))

    got = q(session).collect()
    want = q(cpu_session).collect()
    assert got == want
    assert_runs_on_tpu(q, session)


def test_bogus_zone_falls_back():
    assert not TimeZoneDB.supported("Not/AZone")
