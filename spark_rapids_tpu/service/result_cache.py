"""Plan-fingerprint result cache.

Reference: Spark's ``CACHE TABLE`` / the reference plugin's
``GpuInMemoryTableScanExec`` cache the INPUT of a query; a serving
layer wants to cache the OUTPUT — the same SQL (or DSL plan) from
another tenant should not re-run q1 over an unchanged warehouse. The
cache keys on a CANONICAL STRUCTURAL FINGERPRINT of the submitted plan
(expression trees hash by their structural ``repr``; source tables by
identity token; file scans by path list) with the result-affecting conf
keys folded in, so two structurally identical queries hit regardless of
which tenant built them.

Correctness over hit rate, everywhere:

* anything the fingerprinter cannot PROVE structurally stable (a UDF
  closure, an unknown object with an address-y repr) marks the plan
  uncacheable — a miss, never a wrong hit;
* every catalog mutation or table write bumps the process-wide
  invalidation epoch (:func:`bump_invalidation_epoch`); entries
  remember the epoch they were filled under and a stale entry is
  evicted on lookup, never served;
* the LRU is bounded by ``spark.rapids.service.resultCache.maxBytes``
  of ``HostTable.nbytes()``.

Hit/miss/evict/invalidation counters live in the unified metric
registry's ``resultCache`` scope.
"""

from __future__ import annotations

import hashlib
import threading
import weakref
from collections import OrderedDict
from typing import Optional

from spark_rapids_tpu.obs.metrics import metric_scope, register_metric

register_metric("resultCacheHits", "count", "ESSENTIAL",
                "service queries served from the plan-fingerprint cache")
register_metric("resultCacheMisses", "count", "ESSENTIAL",
                "service queries that executed (fingerprint absent, "
                "stale, or plan uncacheable)")
register_metric("resultCacheEvictions", "count", "ESSENTIAL",
                "entries evicted by the LRU byte bound")
register_metric("resultCacheInvalidations", "count", "ESSENTIAL",
                "stale entries dropped on lookup after an epoch bump")
register_metric("resultCacheBytes", "bytes", "MODERATE",
                "bytes currently held by the result cache")


# ---------------------------------------------------------------------------
# Invalidation epoch
# ---------------------------------------------------------------------------

_EPOCH_LOCK = threading.Lock()
_EPOCH = [0]
_EPOCH_REASON = [""]


def invalidation_epoch() -> int:
    with _EPOCH_LOCK:
        return _EPOCH[0]


def bump_invalidation_epoch(reason: str = "") -> int:
    """Storage/catalog state changed (temp-view or table registration,
    WriteFiles, Delta/Iceberg commit): every currently cached result is
    stale. Called by the session's write detection, the SQL catalog's
    mutators, and the Delta log's commit path."""
    with _EPOCH_LOCK:
        _EPOCH[0] += 1
        _EPOCH_REASON[0] = reason
        return _EPOCH[0]


# ---------------------------------------------------------------------------
# Plan fingerprinting
# ---------------------------------------------------------------------------


class Unfingerprintable(Exception):
    """Internal: the plan holds state the fingerprinter cannot prove
    structurally stable. The query runs uncached."""


#: lazily resolved (datetime, np, T, HostTable, Expression, PlanNode) —
#: module-level import would pull the whole plan layer at package
#: import; resolving on first fingerprint keeps service importable
#: standalone while the hot path pays one tuple unpack per call
_FP_TYPES = None


#: conf key prefixes that cannot change a query's RESULT — observability
#: and service knobs are excluded from the fingerprint so flipping the
#: event log on does not cold the cache. Everything else folds in.
_RESULT_NEUTRAL_PREFIXES = (
    "spark.rapids.sql.eventLog.",
    "spark.rapids.trace.",
    "spark.rapids.profile.",
    "spark.rapids.sql.metrics.level",
    "spark.rapids.sql.lore.",
    "spark.rapids.sql.explain",
    "spark.rapids.sql.planVerify.mode",
    "spark.rapids.service.",
)

#: identity tokens for in-memory source tables: a HostTable object IS
#: its data (tables are immutable after construction), so identity is a
#: sound cache key — and the weak keying means a collected table can
#: never alias a new one's token
_TABLE_TOKENS: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
_TABLE_TOKEN_LOCK = threading.Lock()
_TABLE_TOKEN_SEQ = [0]


def _table_token(table) -> str:
    with _TABLE_TOKEN_LOCK:
        tok = _TABLE_TOKENS.get(table)
        if tok is None:
            _TABLE_TOKEN_SEQ[0] += 1
            tok = f"tbl#{_TABLE_TOKEN_SEQ[0]}"
            _TABLE_TOKENS[table] = tok
        return tok


def _fp_value(obj, depth: int = 0) -> str:
    """One value's canonical token. Raises Unfingerprintable for
    anything that cannot be proven stable."""
    # deferred-but-cached: fingerprinting runs on the service's submit
    # hot path, once per attribute of every plan node — resolve the
    # type anchors once per process, not per call
    global _FP_TYPES
    if _FP_TYPES is None:
        import datetime

        import numpy as np

        from spark_rapids_tpu import types as T
        from spark_rapids_tpu.columnar import HostTable
        from spark_rapids_tpu.ops.expr import Expression
        from spark_rapids_tpu.plan.nodes import PlanNode
        _FP_TYPES = (datetime, np, T, HostTable, Expression, PlanNode)
    datetime, np, T, HostTable, Expression, PlanNode = _FP_TYPES

    if depth > 64:
        raise Unfingerprintable("plan too deep to fingerprint")
    if obj is None or isinstance(obj, (bool, int, float, str, bytes)):
        return f"{type(obj).__name__}:{obj!r}"
    if isinstance(obj, (datetime.date, datetime.datetime)):
        return f"dt:{obj.isoformat()}"
    if isinstance(obj, T.DataType):
        return f"type:{obj}"
    if isinstance(obj, HostTable):
        return _fp_value_table(obj)
    if isinstance(obj, (Expression, PlanNode)) or \
            type(obj).__module__.startswith("spark_rapids_tpu."):
        # generic structural walk over instance state — plan nodes,
        # expressions, and plain engine data holders (SortOrder,
        # WindowSpec, ...). Unlike .key() (which drops string literal
        # VALUES because the compile cache doesn't need them) or
        # __repr__ (which some subclasses leave at the children-only
        # default), this captures EVERY non-child attribute, so two
        # nodes differing in any parameter can never collide; state the
        # walk cannot prove stable (closures, device arrays) raises
        # Unfingerprintable and the plan just never caches
        return _fp_node(obj, depth + 1)
    if isinstance(obj, np.generic):
        return f"np:{obj.dtype}:{obj!r}"
    if isinstance(obj, np.ndarray):
        if obj.dtype == object:
            raise Unfingerprintable("object ndarray in plan state")
        return (f"nd:{obj.dtype}:{obj.shape}:"
                f"{hashlib.sha1(np.ascontiguousarray(obj).tobytes()).hexdigest()}")
    if isinstance(obj, dict):
        items = sorted((str(k), _fp_value(v, depth + 1))
                       for k, v in obj.items())
        return "dict{" + ",".join(f"{k}={v}" for k, v in items) + "}"
    if isinstance(obj, (list, tuple)):
        return ("seq[" +
                ",".join(_fp_value(v, depth + 1) for v in obj) + "]")
    if isinstance(obj, (set, frozenset)):
        return ("set{" +
                ",".join(sorted(_fp_value(v, depth + 1) for v in obj)) +
                "}")
    raise Unfingerprintable(
        f"{type(obj).__name__} in plan state is not fingerprintable")


def _fp_value_table(table) -> str:
    return f"table:{_table_token(table)}"


#: per-node attributes that never affect results (caches, back-refs;
#: the session conf folds into the fingerprint separately)
_SKIP_ATTRS = {"_session", "_table", "conf", "_conf"}


def _fp_node(node, depth: int = 0) -> str:
    """Canonical token of one plan node or expression: class name +
    every non-child attribute's token (sorted by name) + children in
    order."""
    parts = [type(node).__name__]
    try:
        state = vars(node)
    except TypeError:  # __slots__ object; nothing generic to prove
        raise Unfingerprintable(
            f"{type(node).__name__} has no inspectable state")
    for name in sorted(state):
        if name in _SKIP_ATTRS or name == "children":
            continue
        value = state[name]
        if callable(value) and not isinstance(value, type):
            raise Unfingerprintable(
                f"{type(node).__name__}.{name} holds a callable")
        parts.append(f"{name}={_fp_value(value, depth + 1)}")
    kids = ",".join(_fp_node(c, depth + 1)
                    for c in getattr(node, "children", ()))
    return "(" + ";".join(parts) + ")[" + kids + "]"


def fingerprint(plan, conf) -> Optional[str]:
    """Canonical fingerprint of (bound plan, result-affecting conf), or
    None when the plan is uncacheable (side-effecting WriteFiles nodes,
    UDF closures, unfingerprintable state)."""
    from spark_rapids_tpu.plan.nodes import WriteFiles

    stack = [plan]
    while stack:
        n = stack.pop()
        if isinstance(n, WriteFiles):
            return None  # side effects never cache
        stack.extend(getattr(n, "children", ()))
    try:
        plan_tok = _fp_node(plan)
    except Unfingerprintable:
        return None
    conf_items = sorted(
        (k, str(v)) for k, v in conf.to_dict().items()
        if not any(k.startswith(p) or k == p.rstrip(".")
                   for p in _RESULT_NEUTRAL_PREFIXES))
    h = hashlib.sha1()
    h.update(plan_tok.encode())
    h.update(repr(conf_items).encode())
    return h.hexdigest()


# ---------------------------------------------------------------------------
# The LRU cache
# ---------------------------------------------------------------------------


class _Entry:
    __slots__ = ("table", "nbytes", "epoch", "event_record")

    def __init__(self, table, nbytes: int, epoch: int, event_record):
        self.table = table
        self.nbytes = nbytes
        self.epoch = epoch
        self.event_record = event_record


class ResultCache:
    """LRU HostTable cache bounded by bytes. Thread-safe; entries filled
    under an older invalidation epoch are dropped on lookup."""

    def __init__(self, max_bytes: int):
        self.max_bytes = int(max_bytes)
        self._lock = threading.Lock()
        self._entries: "OrderedDict[str, _Entry]" = OrderedDict()
        self._bytes = 0
        self._metrics = metric_scope("resultCache")
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.invalidations = 0

    def _account_miss(self):
        self.misses += 1
        self._metrics.add("resultCacheMisses", 1)

    def get(self, key: Optional[str]):
        """The cached (table, event_record) for ``key``, or None. A None
        key (uncacheable plan) counts a miss."""
        if key is None:
            with self._lock:
                self._account_miss()
            return None
        epoch = invalidation_epoch()
        with self._lock:
            e = self._entries.get(key)
            if e is not None and e.epoch != epoch:
                del self._entries[key]
                self._bytes -= e.nbytes
                self._metrics.add("resultCacheBytes", -e.nbytes)
                self.invalidations += 1
                self._metrics.add("resultCacheInvalidations", 1)
                e = None
            if e is None:
                self._account_miss()
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            self._metrics.add("resultCacheHits", 1)
            return e

    def put(self, key: Optional[str], table, event_record=None,
            epoch: Optional[int] = None) -> bool:
        """Insert a result. ``epoch`` is the invalidation epoch the
        result was COMPUTED under (captured by the caller before
        execution) — a write that landed mid-execution then stales the
        entry on its first lookup instead of the entry masquerading as
        post-write state. Defaults to the current epoch for callers
        with no execution window. Oversized results (> max_bytes) are
        not cached. Returns whether stored."""
        if key is None or table is None:
            return False
        nbytes = int(table.nbytes())
        if nbytes > self.max_bytes:
            return False
        if epoch is None:
            epoch = invalidation_epoch()
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old.nbytes
                self._metrics.add("resultCacheBytes", -old.nbytes)
            while self._bytes + nbytes > self.max_bytes and self._entries:
                _, victim = self._entries.popitem(last=False)
                self._bytes -= victim.nbytes
                self._metrics.add("resultCacheBytes", -victim.nbytes)
                self.evictions += 1
                self._metrics.add("resultCacheEvictions", 1)
            self._entries[key] = _Entry(table, nbytes, epoch, event_record)
            self._bytes += nbytes
            self._metrics.add("resultCacheBytes", nbytes)
        return True

    def clear(self) -> None:
        with self._lock:
            self._metrics.add("resultCacheBytes", -self._bytes)
            self._entries.clear()
            self._bytes = 0

    @property
    def bytes_used(self) -> int:
        with self._lock:
            return self._bytes

    @property
    def entry_count(self) -> int:
        with self._lock:
            return len(self._entries)

    def stats(self) -> dict:
        with self._lock:
            return {"hits": self.hits, "misses": self.misses,
                    "evictions": self.evictions,
                    "invalidations": self.invalidations,
                    "entries": len(self._entries), "bytes": self._bytes}
