"""Dynamic-partitioning columnar writer.

Reference: GpuFileFormatDataWriter.scala — the dynamic partition writer splits
each batch by the partition-key tuple and routes rows to per-partition files
under Hive-style key=value/ directories; single-partition writes emit
part-00000 files. SURVEY.md §2.3 (DataWritingCommandExec row)."""

from __future__ import annotations

import os
from typing import Callable, List, Optional, Sequence

import numpy as np

from spark_rapids_tpu.columnar import HostColumn, HostTable
from spark_rapids_tpu.errors import ColumnarProcessingError


def _escape_partition_value(v) -> str:
    if v is None:
        return "__HIVE_DEFAULT_PARTITION__"
    s = str(v)
    out = []
    for ch in s:
        if ch in '\\/:*?"<>|\x7f' or ord(ch) < 32 or ch in "%=":
            out.append("%{:02X}".format(ord(ch)))
        else:
            out.append(ch)
    return "".join(out)


def write_partitioned(table: HostTable, path: str,
                      write_one: Callable[[HostTable, str], None],
                      extension: str,
                      partition_by: Optional[Sequence[str]] = None,
                      ) -> List[str]:
    """Route rows to files; returns the list of files written."""
    from spark_rapids_tpu.runtime.faults import fault_point
    os.makedirs(path, exist_ok=True)
    written: List[str] = []
    if not partition_by:
        out = os.path.join(path, f"part-00000.{extension}")
        fault_point("io.write.file")
        write_one(table, out)
        return [out]

    for k in partition_by:
        if k not in table.names:
            raise ColumnarProcessingError(f"partition column {k!r} not in table")
    data_names = [n for n in table.names if n not in partition_by]
    key_cols = [table.column(k) for k in partition_by]
    n = table.num_rows

    # group rows by partition tuple (host-side; the device path partitions
    # on device then routes per-partition slices here)
    keys = []
    for i in range(n):
        keys.append(tuple(
            None if not c.validity[i] else
            (c.data[i].item() if isinstance(c.data[i], np.generic) else c.data[i])
            for c in key_cols))
    order = {}
    for i, k in enumerate(keys):
        order.setdefault(k, []).append(i)

    file_idx = 0
    for key_tuple, rows in order.items():
        idx = np.asarray(rows, dtype=np.int64)
        sub_cols = []
        for name in data_names:
            c = table.column(name)
            sub_cols.append(HostColumn(c.dtype, c.data[idx], c.validity[idx]))
        sub = HostTable(data_names, sub_cols)
        part_dir = os.path.join(path, *[
            f"{k}={_escape_partition_value(v)}"
            for k, v in zip(partition_by, key_tuple)])
        os.makedirs(part_dir, exist_ok=True)
        out = os.path.join(part_dir, f"part-{file_idx:05d}.{extension}")
        write_one(sub, out)
        written.append(out)
        file_idx += 1
    return written
