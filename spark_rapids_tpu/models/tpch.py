"""TPC-H-style flagship pipeline (q1: scan -> filter -> project -> group-by
aggregate) — the reference's headline workload shape (pricing summary
report). Used by bench.py and __graft_entry__.py.

Two forms:
* ``q1_dataframe``  — through the full engine (plan -> overrides -> execs);
* ``q1_kernel``     — the same computation as one explicit jittable XLA
  program (filter mask + segment reduction), the distilled hot path."""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar import HostColumn, HostTable


RETURNFLAGS = np.array(["A", "N", "R"], dtype=object)
LINESTATUS = np.array(["F", "O"], dtype=object)
Q1_CUTOFF_DAYS = 10471  # 1998-09-02 as days since epoch


def lineitem_table(num_rows: int, seed: int = 0) -> HostTable:
    """Deterministic lineitem-ish generator (datagen analog)."""
    rng = np.random.default_rng(seed)
    qty = rng.integers(1, 51, size=num_rows).astype(np.float64)
    price = (rng.random(num_rows) * 100000.0).round(2)
    disc = (rng.integers(0, 11, size=num_rows) / 100.0)
    tax = (rng.integers(0, 9, size=num_rows) / 100.0)
    rf = RETURNFLAGS[rng.integers(0, 3, size=num_rows)]
    ls = LINESTATUS[rng.integers(0, 2, size=num_rows)]
    ship = rng.integers(8766, 10957, size=num_rows).astype(np.int32)  # 1994..1999
    cols = {
        "l_quantity": HostColumn(T.DOUBLE, qty),
        "l_extendedprice": HostColumn(T.DOUBLE, price),
        "l_discount": HostColumn(T.DOUBLE, disc),
        "l_tax": HostColumn(T.DOUBLE, tax),
        "l_returnflag": HostColumn(T.STRING, rf),
        "l_linestatus": HostColumn(T.STRING, ls),
        "l_shipdate": HostColumn(T.DATE, ship),
    }
    return HostTable(list(cols.keys()), list(cols.values()))


def q1_dataframe(session, table: HostTable, num_batches: int = 1):
    """TPC-H q1 through the engine (reference:
    integration_tests qa_nightly-style SQL; the scan->filter->agg slice of
    SURVEY.md §7 phase 2)."""
    from spark_rapids_tpu import functions as F
    from spark_rapids_tpu.ops.expr import col, lit
    from spark_rapids_tpu.plan import from_host_table

    df = from_host_table(table, session, num_batches)
    return (
        df.filter(col("l_shipdate") <= lit(Q1_CUTOFF_DAYS, T.DATE))
        .select(
            col("l_returnflag"), col("l_linestatus"), col("l_quantity"),
            col("l_extendedprice"), col("l_discount"),
            (col("l_extendedprice") * (lit(1.0) - col("l_discount"))).alias("disc_price"),
            (col("l_extendedprice") * (lit(1.0) - col("l_discount"))
             * (lit(1.0) + col("l_tax"))).alias("charge"),
        )
        .group_by("l_returnflag", "l_linestatus")
        .agg(
            F.sum(F.col("l_quantity")).alias("sum_qty"),
            F.sum(F.col("l_extendedprice")).alias("sum_base_price"),
            F.sum(F.col("disc_price")).alias("sum_disc_price"),
            F.sum(F.col("charge")).alias("sum_charge"),
            F.avg(F.col("l_quantity")).alias("avg_qty"),
            F.avg(F.col("l_extendedprice")).alias("avg_price"),
            F.avg(F.col("l_discount")).alias("avg_disc"),
            F.count().alias("count_order"),
        )
        .sort("l_returnflag", "l_linestatus")
    )


#: q1 as SQL text (bench.py --sql): lowers onto the same plan shape as
#: q1_dataframe (Sort over Aggregate over Project over Filter)
Q1_SQL = """
SELECT l_returnflag, l_linestatus,
       SUM(l_quantity) AS sum_qty,
       SUM(l_extendedprice) AS sum_base_price,
       SUM(disc_price) AS sum_disc_price,
       SUM(charge) AS sum_charge,
       AVG(l_quantity) AS avg_qty,
       AVG(l_extendedprice) AS avg_price,
       AVG(l_discount) AS avg_disc,
       COUNT(*) AS count_order
FROM (SELECT l_returnflag, l_linestatus, l_quantity, l_extendedprice,
             l_discount,
             l_extendedprice * (1.0 - l_discount) AS disc_price,
             l_extendedprice * (1.0 - l_discount) * (1.0 + l_tax) AS charge
      FROM lineitem
      WHERE l_shipdate <= DATE '1998-09-02')
GROUP BY l_returnflag, l_linestatus
ORDER BY l_returnflag, l_linestatus
"""


def q1_sql(session, table: HostTable, num_batches: int = 1):
    """q1 from SQL text via session.sql() (the front door the reference's
    qa_nightly corpus uses); plans identically to q1_dataframe."""
    from spark_rapids_tpu.plan import from_host_table
    from_host_table(table, session, num_batches)\
        .create_or_replace_temp_view("lineitem")
    return session.sql(Q1_SQL)


NUM_Q1_GROUPS = 8  # 3 flags x 2 statuses padded to a static bound


def q1_kernel(qty, price, disc, tax, flag_code, status_code, shipdate, nrows):
    """The distilled q1 device program: one fused XLA computation.

    Group keys ride as small dictionary codes (the engine's string strategy)
    so gid = flag*2 + status is a direct index — segment reductions with a
    static group bound, no sort needed for low-cardinality keys (the engine's
    sort-segment aggregate generalizes to arbitrary keys)."""
    n = qty.shape[0]
    live = jnp.arange(n, dtype=jnp.int32) < nrows
    keep = live & (shipdate <= Q1_CUTOFF_DAYS)
    gid = flag_code * 2 + status_code
    w = keep.astype(jnp.float64)
    disc_price = price * (1.0 - disc)
    charge = disc_price * (1.0 + tax)

    def seg(v):
        return jax.ops.segment_sum(v * w, gid, num_segments=NUM_Q1_GROUPS)

    cnt = jax.ops.segment_sum(keep.astype(jnp.int64), gid, num_segments=NUM_Q1_GROUPS)
    sum_qty = seg(qty)
    sum_price = seg(price)
    sum_disc_price = seg(disc_price)
    sum_charge = seg(charge)
    sum_disc = seg(disc)
    denom = jnp.maximum(cnt, 1).astype(jnp.float64)
    return (sum_qty, sum_price, sum_disc_price, sum_charge,
            sum_qty / denom, sum_price / denom, sum_disc / denom, cnt)


def q1_kernel_example_args(num_rows: int = 1 << 16, seed: int = 0):
    table = lineitem_table(num_rows, seed)
    rf = np.searchsorted(np.sort(RETURNFLAGS.astype(str)), table.column("l_returnflag").data.astype(str))
    ls = np.searchsorted(np.sort(LINESTATUS.astype(str)), table.column("l_linestatus").data.astype(str))
    return (
        jnp.asarray(table.column("l_quantity").data),
        jnp.asarray(table.column("l_extendedprice").data),
        jnp.asarray(table.column("l_discount").data),
        jnp.asarray(table.column("l_tax").data),
        jnp.asarray(rf.astype(np.int32)),
        jnp.asarray(ls.astype(np.int32)),
        jnp.asarray(table.column("l_shipdate").data),
        jnp.asarray(np.int32(num_rows)),
    )


def q1_pandas(table: HostTable):
    """CPU baseline via pandas (the "Spark CPU" proxy for bench.py).
    Built from the raw internal arrays (dates stay int days) so the baseline
    measures compute, not python-object conversion."""
    import pandas as pd
    df = pd.DataFrame({n: c.data for n, c in zip(table.names, table.columns)})
    df = df[df.l_shipdate <= Q1_CUTOFF_DAYS].copy()
    df["disc_price"] = df.l_extendedprice * (1.0 - df.l_discount)
    df["charge"] = df.disc_price * (1.0 + df.l_tax)
    g = df.groupby(["l_returnflag", "l_linestatus"], sort=True)
    out = g.agg(
        sum_qty=("l_quantity", "sum"),
        sum_base_price=("l_extendedprice", "sum"),
        sum_disc_price=("disc_price", "sum"),
        sum_charge=("charge", "sum"),
        avg_qty=("l_quantity", "mean"),
        avg_price=("l_extendedprice", "mean"),
        avg_disc=("l_discount", "mean"),
        count_order=("l_quantity", "size"),
    ).reset_index()
    return out


# ---------------------------------------------------------------------------
# q3-style multi-join pipeline (customer JOIN orders JOIN lineitem with
# filters, aggregation and sort — the broadcast-join-heavy plan shape;
# reference: NDS/TPC-DS plans are broadcast-heavy per VERDICT r1)
# ---------------------------------------------------------------------------

SEGMENTS = np.array(["AUTOMOBILE", "BUILDING", "FURNITURE", "HOUSEHOLD",
                     "MACHINERY"], dtype=object)
Q3_DATE = 9204  # 1995-03-15


def q3_tables(num_rows: int, seed: int = 0):
    """lineitem (num_rows), orders (num_rows // 4), customer (num_rows // 40)."""
    rng = np.random.default_rng(seed)
    n_ord = max(num_rows // 4, 1)
    n_cust = max(num_rows // 40, 1)

    cust = HostTable(["c_custkey", "c_mktsegment"], [
        HostColumn(T.LONG, np.arange(n_cust, dtype=np.int64)),
        HostColumn(T.STRING, SEGMENTS[rng.integers(0, len(SEGMENTS), n_cust)]),
    ])
    orders = HostTable(["o_orderkey", "o_custkey", "o_orderdate"], [
        HostColumn(T.LONG, np.arange(n_ord, dtype=np.int64)),
        HostColumn(T.LONG, rng.integers(0, n_cust, n_ord)),
        HostColumn(T.DATE, rng.integers(8766, 9855, n_ord).astype(np.int32)),
    ])
    lineitem = HostTable(
        ["l_orderkey", "l_extendedprice", "l_discount", "l_shipdate"], [
            HostColumn(T.LONG, rng.integers(0, n_ord, num_rows)),
            HostColumn(T.DOUBLE, (rng.random(num_rows) * 100000.0).round(2)),
            HostColumn(T.DOUBLE, rng.integers(0, 11, num_rows) / 100.0),
            HostColumn(T.DATE, rng.integers(8766, 9855, num_rows).astype(np.int32)),
        ])
    return cust, orders, lineitem


def q3_dataframe(session, cust, orders, lineitem, segment: str = "BUILDING"):
    from spark_rapids_tpu import functions as F
    from spark_rapids_tpu.ops.expr import col, lit
    from spark_rapids_tpu.plan import from_host_table

    c = from_host_table(cust, session).filter(
        col("c_mktsegment") == lit(segment))
    o = from_host_table(orders, session).filter(
        col("o_orderdate") < lit(Q3_DATE, T.DATE))
    li = from_host_table(lineitem, session).filter(
        col("l_shipdate") > lit(Q3_DATE, T.DATE))
    joined = (li.join(o.with_column("l_orderkey", col("o_orderkey")),
                      on="l_orderkey", how="inner")
              .join(c.with_column("o_custkey", col("c_custkey")),
                    on="o_custkey", how="inner"))
    return (joined
            .select(col("l_orderkey"), col("o_orderdate"),
                    (col("l_extendedprice") * (lit(1.0) - col("l_discount")))
                    .alias("volume"))
            .group_by("l_orderkey")
            .agg(F.sum(col("volume")).alias("revenue"),
                 F.count().alias("n"))
            .sort(P_REV_DESC())
            .limit(10))


#: q3 as SQL text (bench.py --sql); nested selects mirror the
#: filter/with_column/join chain of q3_dataframe
Q3_SQL = """
SELECT l_orderkey, SUM(volume) AS revenue, COUNT(*) AS n FROM (
    SELECT l_orderkey, o_orderdate,
           l_extendedprice * (1.0 - l_discount) AS volume
    FROM (SELECT * FROM lineitem WHERE l_shipdate > DATE '1995-03-15')
    JOIN (SELECT *, o_orderkey AS l_orderkey
          FROM orders WHERE o_orderdate < DATE '1995-03-15')
      USING (l_orderkey)
    JOIN (SELECT *, c_custkey AS o_custkey
          FROM customer WHERE c_mktsegment = '{segment}')
      USING (o_custkey))
GROUP BY l_orderkey
ORDER BY revenue DESC LIMIT 10
"""


def q3_sql(session, cust, orders, lineitem, segment: str = "BUILDING"):
    from spark_rapids_tpu.plan import from_host_table
    from_host_table(cust, session).create_or_replace_temp_view("customer")
    from_host_table(orders, session).create_or_replace_temp_view("orders")
    from_host_table(lineitem, session)\
        .create_or_replace_temp_view("lineitem")
    return session.sql(Q3_SQL.format(segment=segment))


def P_REV_DESC():
    from spark_rapids_tpu.ops.expr import col
    from spark_rapids_tpu.plan.nodes import SortOrder
    return SortOrder(col("revenue"), ascending=False)


def q3_pandas(cust, orders, lineitem, segment: str = "BUILDING"):
    import pandas as pd
    c = pd.DataFrame({n: col.data for n, col in zip(cust.names, cust.columns)})
    o = pd.DataFrame({n: col.data for n, col in zip(orders.names, orders.columns)})
    li = pd.DataFrame({n: col.data for n, col in
                       zip(lineitem.names, lineitem.columns)})
    c = c[c.c_mktsegment == segment]
    o = o[o.o_orderdate < Q3_DATE]
    li = li[li.l_shipdate > Q3_DATE].copy()
    j = li.merge(o, left_on="l_orderkey", right_on="o_orderkey")
    j = j.merge(c, left_on="o_custkey", right_on="c_custkey")
    j["volume"] = j.l_extendedprice * (1.0 - j.l_discount)
    g = (j.groupby("l_orderkey")
         .agg(revenue=("volume", "sum"), n=("volume", "size"))
         .reset_index()
         .sort_values("revenue", ascending=False)
         .head(10))
    return g
