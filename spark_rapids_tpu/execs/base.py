"""Exec base + host<->device transitions (reference: GpuExec.scala,
GpuRowToColumnarExec / GpuColumnarToRowExec — SURVEY.md §2.2/§2.3)."""

from __future__ import annotations

import contextvars
import time
from typing import Iterator, List, Optional, Sequence, Tuple

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar import DeviceTable, HostTable
from spark_rapids_tpu.obs.metrics import (
    METRIC_LEVELS,  # noqa: F401  (re-export: historical import site)
    MetricSet,
    set_metrics_level,  # noqa: F401  (re-export: the session's setter)
)
from spark_rapids_tpu.plan.nodes import PlanNode, Schema

#: spark.rapids.tpu.maskedBatches.enabled, set per-query by the session
#: (execs have no conf handle — same pattern as retry.MAX_RETRIES_VAR)
MASKED_ENABLED = contextvars.ContextVar("rapids_masked_batches",
                                        default=True)


class TpuExec:
    """Base of device operators. ``execute`` yields DeviceTable batches.

    Two output protocols (columnar/table.py DeviceTable.live):
    ``execute()`` always yields PREFIX tables (live rows at [0, nrows));
    ``execute_masked()`` may yield MASKED tables (liveness as a device
    bool mask), letting mask-aware consumers skip the per-column
    compaction scatter. The default implementations tie them together so
    an exec only ever implements one of the two: mask-oblivious execs
    implement ``execute`` (and ``execute_masked`` forwards to it); mask-
    producing execs implement ``execute_masked`` (and ``execute`` compacts
    each batch)."""

    children: Tuple[object, ...] = ()  # TpuExec or HostToDevice

    #: set by mask-producing execs that implement execute_masked directly
    produces_masked = False

    def __init__(self):
        self.metrics = MetricSet()

    def output_schema(self) -> Schema:
        raise NotImplementedError

    def execute(self) -> Iterator[DeviceTable]:
        if not self.produces_masked:
            raise NotImplementedError
        for b in self.execute_masked():
            yield b.compacted()

    def execute_masked(self) -> Iterator[DeviceTable]:
        return self.execute()

    @property
    def name(self):
        return type(self).__name__

    def describe(self) -> str:
        return self.name

    def tree_string(self, indent: int = 0) -> str:
        s = "  " * indent + "* " + self.describe() + "\n"
        for c in self.children:
            s += c.tree_string(indent + 1)
        return s

    def add_metric(self, key: str, value, level: Optional[str] = None):
        """Record into the unified registry (obs/metrics.py). ``level``
        None resolves from the metric's registered spec (undeclared
        names default to MODERATE — the historical behavior)."""
        self.metrics.add(key, value, level)


class HostToDevice(TpuExec):
    """Transition: wraps a CPU PlanNode, uploading its host batches
    (GpuRowToColumnarExec analog; columnar host->HBM copy)."""

    def __init__(self, cpu_node: PlanNode):
        super().__init__()
        self.cpu_node = cpu_node

    def output_schema(self):
        return self.cpu_node.output_schema()

    def execute(self):
        from spark_rapids_tpu.runtime.memory import scan_chunks
        from spark_rapids_tpu.runtime.profiler import op_range
        from spark_rapids_tpu.runtime.retry import retry_block
        for batch in self.cpu_node.execute_cpu():
            # transitions are device landings like scans: batches over
            # their budget share land as bounded partitions, and a
            # budget squeeze (arbiter RetryOOM) spills and replays
            # instead of failing the query at the upload
            for ch in scan_chunks(batch):
                t0 = time.perf_counter()
                with op_range("HostToDevice", cat="transfer"):
                    dt = retry_block(
                        lambda c=ch: DeviceTable.from_host(c))
                self.add_metric("h2dTime", time.perf_counter() - t0)
                self.add_metric("h2dBatches", 1)
                yield dt

    def describe(self):
        return f"HostToDevice[{self.cpu_node.describe()}]"

    def tree_string(self, indent: int = 0):
        s = "  " * indent + "* " + "HostToDevice\n"
        return s + self.cpu_node.tree_string(indent + 1)


class DeviceToHost:
    """Transition: device exec -> host batches (GpuColumnarToRowExec analog).

    When the session arms ``_async_fetch`` (root transition only,
    ``spark.rapids.sql.asyncResultFetch``), batches yield as
    :class:`~spark_rapids_tpu.columnar.table.PendingHostTable` — the
    packed d2h kernel is ENQUEUED here (still under the device
    semaphore) and the session completes the round trip after releasing
    it, so the tunnel latency stops blocking the next admitted query.
    Mid-plan transitions feeding CPU fallback nodes never arm it."""

    def __init__(self, tpu_exec: TpuExec):
        self.tpu_exec = tpu_exec
        self.metrics = MetricSet()
        #: set per query by the session on the ROOT transition
        self._async_fetch = False

    def output_schema(self):
        return self.tpu_exec.output_schema()

    def add_metric(self, key: str, value, level: Optional[str] = None):
        """Same level-honoring path as TpuExec.add_metric, so
        spark.rapids.sql.metrics.level applies to transitions too."""
        self.metrics.add(key, value, level)

    def execute_cpu(self) -> Iterator[HostTable]:
        from spark_rapids_tpu.columnar.table import PendingHostTable
        from spark_rapids_tpu.runtime.profiler import op_range
        for dt in self.tpu_exec.execute():
            t0 = time.perf_counter()
            with op_range("DeviceToHost", cat="transfer"):
                out = dt.to_host_pending() if self._async_fetch \
                    else dt.to_host()
            # incremental so an early-terminating consumer (limit) still
            # leaves accurate numbers; measures ONLY the d2h conversion
            # (under async fetch: only the ENQUEUE — the fetch itself is
            # recorded as resultFetchTime by the session's resolver)
            self.add_metric("d2hTime", time.perf_counter() - t0)
            self.add_metric("numOutputBatches", 1)
            if isinstance(out, PendingHostTable):
                self.add_metric("asyncFetchBatches", 1)
            else:
                self.add_metric("numOutputRows", out.num_rows)
            yield out

    def describe(self):
        return "DeviceToHost"

    def tree_string(self, indent: int = 0):
        return "  " * indent + "DeviceToHost\n" + self.tpu_exec.tree_string(indent + 1)


class InputAdapter(PlanNode):
    """CPU plan node that sources batches from an arbitrary executable
    (used when a CPU fallback node sits above converted children)."""

    def __init__(self, source, schema: Schema):
        self.source = source
        self._schema = schema

    def output_schema(self):
        return self._schema

    def execute_cpu(self):
        return self.source.execute_cpu()

    def describe(self):
        return "InputAdapter"

    def tree_string(self, indent: int = 0):
        return "  " * indent + "InputAdapter\n" + self.source.tree_string(indent + 1)
