"""Exception hierarchy, mirroring the reference's OOM/retry protocol.

Reference: spark-rapids-jni exception types (SURVEY.md §2.9) --
GpuRetryOOM / GpuSplitAndRetryOOM / CpuRetryOOM / CpuSplitAndRetryOOM /
GpuOOM -- thrown by the RmmSpark per-thread state machine and caught by
RmmRapidsRetryIterator.withRetry (RmmRapidsRetryIterator.scala:33-757).

On TPU the analogs are raised when a PJRT/XLA device allocation fails (or
when the runtime's HBM budget tracker decides a batch will not fit), and by
the test-only OOM injection hooks.
"""

from __future__ import annotations


class RapidsTpuError(Exception):
    """Base for all engine errors."""


class RetryOOM(RapidsTpuError):
    """Device allocation failed; caller should spill and replay the same
    input (reference: GpuRetryOOM)."""


class SplitAndRetryOOM(RapidsTpuError):
    """Device allocation failed and replay alone will not help; caller should
    split the input (halve rows) and replay (reference: GpuSplitAndRetryOOM)."""


class CpuRetryOOM(RapidsTpuError):
    """Host allocation failed; spill host buffers and replay."""


class CpuSplitAndRetryOOM(RapidsTpuError):
    """Host allocation failed; split input and replay."""


class FatalDeviceOOM(RapidsTpuError):
    """Unrecoverable device OOM after retries exhausted (reference: GpuOOM)."""


class ColumnarProcessingError(RapidsTpuError):
    """An operator failed on device in a way that is not an OOM."""


class UnsupportedOnTpu(RapidsTpuError):
    """Raised when an operator/expression is asked to run on device but was
    tagged unsupported; indicates a bug in the plan-rewrite layer (normal
    operation converts such nodes back to CPU)."""


class PlanVerificationError(RapidsTpuError):
    """A converted plan violated a structural invariant
    (spark.rapids.sql.planVerify.mode=error). Carries the structured
    diagnostics in ``.diagnostics``; the message lists rule id + plan
    path per finding."""

    def __init__(self, diagnostics):
        self.diagnostics = list(diagnostics)
        super().__init__(
            "plan verification failed:\n" +
            "\n".join(f"  {d}" for d in self.diagnostics))


class AnsiViolation(RapidsTpuError, ArithmeticError):
    """ANSI mode (spark.sql.ansi.enabled) runtime error: overflow, divide
    by zero, invalid cast, or array index out of bounds — the engine's
    SparkArithmeticException. Device kernels record the violation as a
    device flag that rides the collect fetch (like speculation flags);
    the CPU oracle raises at evaluation."""
