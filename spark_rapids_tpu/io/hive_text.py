"""Hive text (LazySimpleSerDe delimited) scan + writer.

Reference: org.apache.spark.sql.hive.rapids (GpuHiveTextFileFormat /
GpuHiveTableScanExec) — Hive's default text layout: \\x01 field delimiter,
no header, '\\N' as the null marker, no quoting/escaping of delimiters.
Rides the CSV machinery with Hive defaults pinned (the reference routes it
through the same text-reader base)."""

from __future__ import annotations

from typing import List, Optional, Sequence

from spark_rapids_tpu.columnar import HostTable
from spark_rapids_tpu.conf import RapidsConf, str_conf
from spark_rapids_tpu.io.csv import CsvScanNode
from spark_rapids_tpu.io.writer import write_partitioned
from spark_rapids_tpu.plan.nodes import Schema

HIVE_TEXT_READER_TYPE = str_conf(
    "spark.rapids.sql.format.hiveText.reader.type", "AUTO",
    "PERFILE, COALESCING, MULTITHREADED or AUTO.")

HIVE_DELIM = "\x01"
HIVE_NULL = "\\N"


class HiveTextScanNode(CsvScanNode):
    """Supports the LazySimpleSerDe property surface the reference's
    GpuHiveTableScanExec reads from table properties: ``field.delim``
    (-> delimiter), ``serialization.null.format`` (-> null_value), and
    ``escape.delim`` (-> escape). Partitioned hive tables (key=value
    directory layout) recover partition columns through the shared
    FileScanNode machinery (io/common.py)."""

    format_name = "hiveText"

    def __init__(self, paths, conf: RapidsConf, schema: Schema,
                 columns=None, reader_type=None,
                 delimiter: str = HIVE_DELIM, null_value: str = HIVE_NULL,
                 escape: Optional[str] = None, **options):
        if schema is None:
            raise ValueError("Hive text tables require an explicit schema "
                             "(the format carries no header)")
        super().__init__(paths, conf, columns=columns,
                         reader_type=reader_type, schema=schema,
                         header=False, sep=delimiter, null_value=null_value,
                         quote="", escape=escape, **options)

    def _conf_reader_type(self) -> str:
        return self.conf.get_entry(HIVE_TEXT_READER_TYPE)

    def _newlines_in_values(self) -> bool:
        # with escape.delim set, an ESCAPED literal newline is data
        # (LazySimpleSerDe), not a row terminator
        return self.escape is not None


def _hive_cell(v, null_value: str, delimiter: str,
               escape: Optional[str]) -> str:
    """Hive LazySimpleSerDe value rendering: lowercase booleans, ``\\N``
    nulls, ISO dates/timestamps; with escape.delim set, delimiter/
    newline/escape bytes in the RENDERED text escape (a LONG of -5
    under delimiter='-' needs escaping just like a string) — an escaped
    literal newline reads back via newlines_in_values."""
    if v is None:
        return null_value
    if isinstance(v, bool):
        return "true" if v else "false"
    s = str(v)
    if escape:
        s = (s.replace(escape, escape + escape)
             .replace(delimiter, escape + delimiter)
             .replace("\n", escape + "\n"))
    return s


def write_hive_text(table: HostTable, path: str,
                    partition_by: Optional[Sequence[str]] = None,
                    delimiter: str = HIVE_DELIM,
                    null_value: str = HIVE_NULL,
                    escape: Optional[str] = None,
                    committer=None) -> List[str]:
    def _write_one(tbl: HostTable, file_path: str):
        cols = [c.to_pylist() for c in tbl.columns]
        with open(file_path, "w") as f:
            for i in range(tbl.num_rows):
                f.write(delimiter.join(
                    _hive_cell(cols[j][i], null_value, delimiter, escape)
                    for j in range(len(cols))) + "\n")

    return write_partitioned(table, path, _write_one, "txt", partition_by,
                             committer=committer)
