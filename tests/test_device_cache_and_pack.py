"""Scan device-residency cache (GpuInMemoryTableScanExec analog) and the
packed single-fetch to_host path."""

import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu import functions as F
from spark_rapids_tpu.columnar import HostColumn, HostTable
from spark_rapids_tpu.columnar.table import DeviceTable, evict_device_caches
from spark_rapids_tpu.ops.expr import col, lit


def _table():
    n = 300
    rng = np.random.default_rng(5)
    sv = np.array([["x", "yy", "zzz"][i] for i in rng.integers(0, 3, n)],
                  dtype=object)
    cols = {
        "i8": HostColumn(T.BYTE, rng.integers(-100, 100, n).astype(np.int8)),
        "i16": HostColumn(T.SHORT, rng.integers(-30000, 30000, n).astype(np.int16)),
        "i32": HostColumn(T.INT, rng.integers(-2**31, 2**31 - 1, n).astype(np.int32)),
        "i64": HostColumn(T.LONG, rng.integers(-2**62, 2**62, n).astype(np.int64)),
        "f32": HostColumn(T.FLOAT, rng.standard_normal(n).astype(np.float32)),
        "f64": HostColumn(T.DOUBLE, rng.standard_normal(n) * 1e8,
                          rng.random(n) > 0.2),
        "b": HostColumn(T.BOOLEAN, rng.integers(0, 2, n).astype(np.bool_)),
        "s": HostColumn(T.STRING, sv),
        "dt": HostColumn(T.DATE, rng.integers(0, 20000, n).astype(np.int32)),
        "ts": HostColumn(T.TIMESTAMP, rng.integers(0, 2**50, n).astype(np.int64)),
    }
    return HostTable(list(cols.keys()), list(cols.values()))


def test_packed_to_host_roundtrip_all_dtypes():
    host = _table()
    back = DeviceTable.from_host(host).to_host()
    assert back.names == host.names
    for name, orig, got in zip(host.names, host.columns, back.columns):
        np.testing.assert_array_equal(orig.validity, got.validity, err_msg=name)
        if isinstance(orig.dtype, T.StringType):
            for o, g, v in zip(orig.data, got.data, orig.validity):
                if v:
                    assert o == g, name
        else:
            ov = orig.data[orig.validity]
            gv = got.data[got.validity]
            np.testing.assert_array_equal(ov, gv, err_msg=name)


def test_packed_to_host_corner_doubles():
    vals = np.array([0.0, -0.0, np.inf, -np.inf, np.nan, 1e308, -1e308,
                     5e-324, 1.5, -2.75])
    host = HostTable(["d"], [HostColumn(T.DOUBLE, vals)])
    got = DeviceTable.from_host(host).to_host().columns[0].data
    # NaN compares unequal; compare bit patterns where the backend kept them
    for o, g in zip(vals, got):
        if np.isnan(o):
            assert np.isnan(g)
        else:
            assert o == g, (o, g)


def test_scan_device_cache_hit_and_eviction(session):
    from spark_rapids_tpu.plan import from_host_table

    table = _table()
    df = lambda: from_host_table(table, session)  # noqa: E731
    r1 = df().group_by("s").agg(F.count().alias("c")).collect()
    assert "device" in table._cache
    cached = table._cache["device"]
    r2 = df().group_by("s").agg(F.count().alias("c")).collect()
    assert table._cache["device"] is cached  # reused, not re-uploaded
    assert sorted(r1) == sorted(r2)

    assert evict_device_caches() >= 1
    assert "device" not in table._cache
    r3 = df().group_by("s").agg(F.count().alias("c")).collect()
    assert sorted(r1) == sorted(r3)


def test_scan_device_cache_disabled(session):
    from spark_rapids_tpu.session import TpuSession
    from spark_rapids_tpu.plan import from_host_table

    off = TpuSession({"spark.rapids.tpu.scan.deviceCache": "false"})
    table = _table()
    from_host_table(table, off).filter(col("i32") > lit(0)).collect()
    assert "device" not in table._cache


def test_oom_retry_evicts_scan_cache(session):
    """Injected OOM must drop cached device images before replay."""
    from spark_rapids_tpu.session import TpuSession
    from spark_rapids_tpu.plan import from_host_table

    table = _table()
    s = TpuSession()
    from_host_table(table, s).filter(col("i32") > lit(0)).collect()
    assert "device" in table._cache

    inj = TpuSession({"spark.rapids.sql.test.injectRetryOOM": "retry:1"})
    out = from_host_table(table, inj).filter(col("i32") > lit(0)).collect()
    # the retry's spill pass evicted the cached image; the replay either
    # reuploaded (cache repopulated) or ran uncached — results must hold
    n_pos = int((np.asarray(table.column("i32").data) > 0).sum())
    assert len(out) == n_pos
