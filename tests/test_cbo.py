"""Cost-based optimizer (CostBasedOptimizer.scala analog): small plans
revert to CPU when the device doesn't pay for its overhead."""

from spark_rapids_tpu import functions as F
from spark_rapids_tpu.ops.expr import col, lit
from spark_rapids_tpu.plan import from_host_table

from tests.data_gen import IntGen, StringGen, gen_table


def _session(extra=None):
    from spark_rapids_tpu.session import TpuSession
    conf = {"spark.rapids.sql.optimizer.enabled": "true"}
    conf.update(extra or {})
    return TpuSession(conf)


def _plan_on_device(session, df) -> bool:
    from spark_rapids_tpu.overrides import wrap_plan
    from spark_rapids_tpu.overrides.optimizer import apply_cbo
    meta = wrap_plan(df.plan, session.conf)
    apply_cbo(meta, session.conf)
    return meta.can_run_on_tpu


def test_tiny_plan_reverts_to_cpu(cpu_session):
    s = _session()
    df = from_host_table(gen_table({"x": IntGen()}, 50, 1), s) \
        .filter(col("x") > lit(0))
    assert not _plan_on_device(s, df)
    # the reason names CBO, and results still come out right
    from spark_rapids_tpu.overrides import wrap_plan
    from spark_rapids_tpu.overrides.optimizer import apply_cbo
    meta = wrap_plan(df.plan, s.conf)
    apply_cbo(meta, s.conf)
    assert any("CBO" in r for r in meta.reasons)
    assert df.count() == sum(
        1 for v in gen_table({"x": IntGen()}, 50, 1)
        .columns[0].to_pylist() if v is not None and v > 0)


def test_large_plan_stays_on_device():
    s = _session()
    df = from_host_table(gen_table({"x": IntGen()}, 2_000_000, 1), s) \
        .filter(col("x") > lit(0))
    assert _plan_on_device(s, df)


def test_disabled_by_default(session):
    df = from_host_table(gen_table({"x": IntGen()}, 50, 1), session) \
        .filter(col("x") > lit(0))
    from spark_rapids_tpu.overrides import wrap_plan
    meta = wrap_plan(df.plan, session.conf)
    from spark_rapids_tpu.overrides.optimizer import apply_cbo
    apply_cbo(meta, session.conf)
    assert meta.can_run_on_tpu


def test_unknown_stats_left_alone():
    s = _session()
    # joins have no row estimate -> CBO must not touch the plan
    left = from_host_table(gen_table({"k": IntGen(min_val=0, max_val=5)}, 40, 1), s)
    right = from_host_table(gen_table({"k": IntGen(min_val=0, max_val=5)}, 20, 2), s)
    df = left.join(right, on="k", how="inner")
    assert _plan_on_device(s, df)
