"""Tier-1 streaming + materialized-view pipeline tests (ISSUE 16).

Covers the streaming subsystem's load-bearing contracts on a small,
seeded corpus:

* exactly-once across a mid-micro-batch kill — a resumed stream re-runs
  the pending batch and the sink's txn watermark dedupes, so the sink
  row set is bit-identical to a fault-free run;
* MV incremental refresh (append + re-aggregate strategies) bit-identical
  to a from-scratch recompute at every epoch;
* the full-recompute fallback and its reason surfaced in explain();
* per-table invalidation epochs: a commit to table B does not evict a
  cached result over table A;
* event-log schema v11 fields (microBatches … sinkReplays, mvEpoch).
"""

import json
import os

import pytest

from spark_rapids_tpu.columnar.table import HostTable
from spark_rapids_tpu.ops.expr import col, lit


def _rows(t):
    return sorted(zip(*[c.to_pylist() for c in t.columns]))


def _svc(tmp_path, **conf):
    from spark_rapids_tpu.service.scheduler import QueryService
    base = {"spark.rapids.service.maxConcurrentQueries": 2}
    base.update(conf)
    return QueryService(base)


def _make_delta(session, path, data, cdf=True):
    from spark_rapids_tpu.delta.commands import DeltaTable
    from spark_rapids_tpu.delta.table import write_delta
    from spark_rapids_tpu.plan.dataframe import from_host_table
    write_delta(from_host_table(HostTable.from_pydict(data), session).plan,
                session, path, mode="error")
    if cdf:
        DeltaTable(session, path).set_properties(
            {"delta.enableChangeDataFeed": "true"})
    return DeltaTable(session, path)


def _append(session, path, data):
    from spark_rapids_tpu.delta.table import write_delta
    from spark_rapids_tpu.plan.dataframe import from_host_table
    write_delta(from_host_table(HostTable.from_pydict(data), session).plan,
                session, path, mode="append")


# ---------------------------------------------------------------------------
# offset log protocol
# ---------------------------------------------------------------------------


def test_offset_log_pending_protocol(tmp_path):
    from spark_rapids_tpu.streaming import OffsetLog
    log = OffsetLog(str(tmp_path / "ck"))
    assert log.latest_batch_id() == -1
    assert log.pending_batch() is None
    log.write_offsets(0, {"start": 0, "end": 10})
    # offsets without a commit = the batch to re-run on resume
    assert log.pending_batch() == (0, {"start": 0, "end": 10})
    log.write_commit(0, {"outcome": "committed"})
    assert log.pending_batch() is None
    assert log.last_end_offset() == 10
    # planning out of order is a protocol violation, not silent data loss
    from spark_rapids_tpu.errors import ColumnarProcessingError
    with pytest.raises(ColumnarProcessingError):
        log.write_offsets(5, {"start": 10, "end": 20})


# ---------------------------------------------------------------------------
# exactly-once across a mid-micro-batch kill
# ---------------------------------------------------------------------------


@pytest.mark.chaos
def test_stream_exactly_once_after_kill(tmp_path):
    """Kill a stream mid-micro-batch (after its offsets are logged,
    before the sink commit), resume from the checkpoint, and require the
    sink row set to be bit-identical to a fault-free run — no lost and
    no duplicated rows."""
    from spark_rapids_tpu.delta.commands import DeltaTable
    from spark_rapids_tpu.delta.log import DeltaLog
    from spark_rapids_tpu.errors import KernelCrashError
    from spark_rapids_tpu.runtime.faults import FAULTS
    from spark_rapids_tpu.streaming import (
        DeltaStreamSink,
        OffsetLog,
        RateSource,
        StreamingQuery,
    )
    svc = _svc(tmp_path)
    try:
        s = svc.session
        # fault-free baseline: same seeded source into its own sink
        base_sink = str(tmp_path / "baseline_sink")
        q0 = StreamingQuery(
            svc, RateSource(rows_per_batch=20, seed=7, total_rows=60),
            DeltaStreamSink(base_sink, "base"), str(tmp_path / "ck0"),
            name="base")
        assert q0.process_available() == 3
        expected = _rows(s.execute(DeltaTable(s, base_sink).to_df().plan))

        # chaos run: second micro-batch dies between offset log and sink
        sink = str(tmp_path / "sink")
        ck = str(tmp_path / "ck")

        def fresh_query():
            return StreamingQuery(
                svc, RateSource(rows_per_batch=20, seed=7, total_rows=60),
                DeltaStreamSink(sink, "s1"), ck, name="s1")

        q = fresh_query()
        assert q.run_one_batch()
        FAULTS.arm("stream.batch:crash:1")
        try:
            with pytest.raises(KernelCrashError):
                q.run_one_batch()
        finally:
            FAULTS.disarm()
        # the killed batch is pending: offsets logged, no commit marker
        olog = OffsetLog(ck)
        assert olog.pending_batch() is not None
        # a fresh stream over the same checkpoint resumes exactly-once
        assert fresh_query().process_available() == 2
        got = _rows(s.execute(DeltaTable(s, sink).to_df().plan))
        assert got == expected

        # harder window: sink commit landed but the commit marker did
        # not — replay must dedupe via the txn watermark, not re-append
        last = olog.latest_committed_id()
        os.remove(os.path.join(olog.commits_dir, f"{last}.json"))
        assert fresh_query().process_available() == 1  # the replay
        got2 = _rows(s.execute(DeltaTable(s, sink).to_df().plan))
        assert got2 == expected
        assert DeltaLog(sink).last_txn_version("s1") == 2
    finally:
        svc.shutdown()


# ---------------------------------------------------------------------------
# MV incremental maintenance
# ---------------------------------------------------------------------------


def test_mv_incremental_bit_identity_every_epoch(tmp_path):
    """Aggregate (re-aggregate strategy) and projection (append strategy)
    MVs must serve tables bit-identical to a from-scratch recompute of
    the registered plan at the same epoch, after EVERY commit — with at
    least one refresh actually served incrementally."""
    import spark_rapids_tpu.functions as F
    svc = _svc(tmp_path)
    try:
        s = svc.session
        base = str(tmp_path / "base")
        dt = _make_delta(s, base, {"k": [1, 2, 3, 1], "v": [10, 20, 30, 40]})
        reg = svc.mv_registry()
        df = dt.to_df()
        mv_agg = reg.register(
            "agg", df.group_by(col("k")).agg(F.sum(col("v")).alias("sv"),
                                             F.count(col("v")).alias("c")))
        mv_proj = reg.register(
            "proj", df.filter(col("v") > lit(12)).select(col("k"), col("v")))
        assert mv_agg.strategy == "reaggregate"
        assert mv_proj.strategy == "append"

        commits = [
            {"k": [2, 4], "v": [5, 100]},
            {"k": [4, 1], "v": [7, 3]},
            {"k": [3], "v": [1000]},
        ]
        for data in commits:
            _append(s, base, data)
            assert mv_agg.stale and mv_proj.stale
            for mv in (mv_agg, mv_proj):
                served = mv.read()
                assert _rows(served) == _rows(mv.recompute_at_epoch()), \
                    f"{mv.name} diverged at epoch {mv.epoch()}"
        assert mv_agg.incremental_refreshes >= 1
        assert mv_proj.incremental_refreshes >= 1
        assert mv_agg.last_refresh_mode == "incremental-reaggregate"
        assert mv_proj.last_refresh_mode == "incremental-append"
    finally:
        svc.shutdown()


def test_mv_full_recompute_fallback_surfaced(tmp_path):
    """Non-whitelisted plans (joins) register with strategy=full, and an
    append-strategy view hit by non-insert changes falls back to a full
    recompute — both with the reason in explain()."""
    svc = _svc(tmp_path)
    try:
        from spark_rapids_tpu.delta.commands import DeltaTable
        s = svc.session
        a, b = str(tmp_path / "a"), str(tmp_path / "b")
        _make_delta(s, a, {"k": [1, 2], "x": [10, 20]})
        _make_delta(s, b, {"k": [1, 2], "y": [7, 8]}, cdf=False)
        reg = svc.mv_registry()
        joined = DeltaTable(s, a).to_df().join(
            DeltaTable(s, b).to_df(), on=["k"])
        mv_join = reg.register("j", joined)
        assert mv_join.strategy == "full"
        text = mv_join.explain()
        assert "strategy=full" in text and "fallback:" in text
        # join MV still refreshes correctly (full recompute) on commit
        _append(s, a, {"k": [2], "x": [100]})
        served = mv_join.read()
        assert mv_join.last_refresh_mode == "full-recompute"
        assert _rows(served) == _rows(mv_join.recompute_at_epoch())

        # append-strategy view + an UPDATE delta -> full fallback, with
        # the non-insert reason surfaced
        mv_p = reg.register(
            "p", DeltaTable(s, a).to_df().select(col("k"), col("x")))
        DeltaTable(s, a).update(col("k") == lit(1), {"x": lit(0)})
        mv_p.read()
        assert mv_p.last_refresh_mode == "full-recompute"
        assert "non-insert" in mv_p.explain()
    finally:
        svc.shutdown()


# ---------------------------------------------------------------------------
# per-table invalidation epochs
# ---------------------------------------------------------------------------


def test_per_table_epoch_scoping(tmp_path):
    """A Delta commit bumps only ITS table's epoch: cached results over
    other tables keep serving, same-table entries drop, and a global
    bump (catalog-wide) still evicts everything."""
    from spark_rapids_tpu.delta.commands import DeltaTable
    from spark_rapids_tpu.plan.fingerprint import bump_invalidation_epoch
    svc = _svc(tmp_path)
    try:
        s = svc.session
        a, b = str(tmp_path / "a"), str(tmp_path / "b")
        _make_delta(s, a, {"x": [1, 2, 3]}, cdf=False)
        _make_delta(s, b, {"y": [4, 5]}, cdf=False)

        def hit_count():
            return svc.result_cache.stats()["hits"]

        def run_over_a():
            h = svc.submit(DeltaTable(s, a).to_df().select(col("x")))
            h.result(timeout=60)

        run_over_a()               # fill
        run_over_a()               # hit
        assert hit_count() == 1
        _append(s, b, {"y": [6]})  # unrelated commit: table B only
        run_over_a()
        assert hit_count() == 2, "commit to B evicted a result over A"
        _append(s, a, {"x": [9]})  # same-table commit: must invalidate
        run_over_a()
        assert hit_count() == 2
        run_over_a()               # refilled at the new epoch
        assert hit_count() == 3
        bump_invalidation_epoch("catalog-wide test bump")
        run_over_a()
        assert hit_count() == 3, "global bump must evict everything"
    finally:
        svc.shutdown()


# ---------------------------------------------------------------------------
# scale_test flag validation
# ---------------------------------------------------------------------------


def test_streaming_flag_validation():
    """validate_flags rejects the --streaming combinations the harness
    does not implement, naming the supported modes."""
    from types import SimpleNamespace

    import scale_test as st

    def args(**kw):
        base = dict(mesh=0, hosts=0, streaming=False, concurrency=0,
                    service_faults=False, cpu_baseline=False,
                    require_tpu=False, chaos=False, device_budget=0)
        base.update(kw)
        return SimpleNamespace(**base)

    st.validate_flags(args(streaming=True))  # supported
    st.validate_flags(args(streaming=True, chaos=True))  # supported
    for bad in (args(streaming=True, mesh=4),
                args(streaming=True, hosts=2),
                args(streaming=True, device_budget=4_000_000),
                args(streaming=True, concurrency=2),
                args(streaming=True, chaos=True, service_faults=True),
                args(streaming=True, cpu_baseline=True)):
        with pytest.raises(SystemExit) as ei:
            st.validate_flags(bad)
        assert "supported modes" in str(ei.value)


# ---------------------------------------------------------------------------
# schema v11 + introspection surfaces
# ---------------------------------------------------------------------------


def test_schema_v11_streaming_fields(tmp_path):
    """Every v11 record carries the six streaming deltas and mvEpoch;
    an MV serve stamps its epoch; stream work shows up in the log's
    totals; /top and `tools top` show the recurring stream."""
    import spark_rapids_tpu.functions as F
    from spark_rapids_tpu.delta.commands import DeltaTable
    from spark_rapids_tpu.service.introspect import _routes
    from spark_rapids_tpu.streaming import (
        DeltaStreamSink,
        RateSource,
        StreamingQuery,
    )
    from spark_rapids_tpu.tools.top import render_top
    svc = _svc(
        tmp_path,
        **{"spark.rapids.sql.eventLog.enabled": True,
           "spark.rapids.sql.eventLog.dir": str(tmp_path / "ev")})
    try:
        s = svc.session
        base = str(tmp_path / "base")
        dt = _make_delta(s, base, {"k": [1, 2, 1], "v": [10, 20, 30]})
        mv = svc.mv_registry().register(
            "agg", dt.to_df().group_by(col("k")).agg(
                F.sum(col("v")).alias("sv")))
        _append(s, base, {"k": [2], "v": [5]})
        mv.read()
        rec = s.last_event_record
        assert rec["schema"] == 11
        assert rec["mvEpoch"] == mv.epoch()
        assert rec["queryTag"] == f"mv:agg@v{mv.epoch()}"

        q = StreamingQuery(
            svc, RateSource(rows_per_batch=25, seed=3, total_rows=50),
            DeltaStreamSink(str(tmp_path / "sink"), "s1"),
            str(tmp_path / "ck"), name="s1")
        svc.register_stream(q)
        assert q.process_available() == 2
        # one more trivial envelope so the trailing scope deltas land
        svc.submit(dt.to_df().select(col("k"))).result(timeout=60)

        records = [json.loads(line)
                   for line in open(s.last_event_path)
                   if line.strip()]
        for r in records:
            for f in ("microBatches", "mvRefreshes",
                      "mvIncrementalRefreshes", "mvFullRecomputes",
                      "sinkCommits", "sinkReplays"):
                assert f in r, f"record missing v11 field {f}"
            assert "mvEpoch" in r
        assert sum(r["microBatches"] for r in records) == 2
        assert sum(r["sinkCommits"] for r in records) == 2
        assert sum(r["mvRefreshes"] for r in records) >= 2

        # the recurring tenant is on the introspection surfaces
        doc = _routes(svc, "/top", {})
        names = [st["name"] for st in doc["streams"]]
        assert "s1" in names
        rendered = render_top(doc)
        assert "Streams: 1 recurring" in rendered and "s1" in rendered
    finally:
        svc.shutdown()
