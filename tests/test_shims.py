"""Shim layer (reference: ShimLoader.scala + build/shimplify.py —
SURVEY.md §2.12): version-range registry resolution, override hooks, and
the engine call sites that ride the shim."""

import numpy as np
import pytest

from spark_rapids_tpu import shims
from spark_rapids_tpu.shims.base import BaseShim
from spark_rapids_tpu.shims.jax_current import JaxCurrentShim
from spark_rapids_tpu.shims.jax_legacy import JaxLegacyShim


def test_parse_version_tolerant():
    assert shims.parse_version("0.4.35") == (0, 4, 35)
    assert shims.parse_version("0.9.0rc1") == (0, 9, 0)
    assert shims.parse_version("0.9") == (0, 9, 0)
    # vendor-suffixed strings resolve like ShimLoader tolerates
    # '3.4.1-databricks'
    assert shims.parse_version("0.5.3+cuda12") == (0, 5, 3)


def test_ranges_disjoint_and_ordered():
    """The shimplify invariant: providers own disjoint version ranges."""
    spans = sorted((c.MIN_VERSION, c.MAX_VERSION, c.__name__)
                   for c in shims.SHIM_PROVIDERS)
    for (lo1, hi1, n1), (lo2, hi2, n2) in zip(spans, spans[1:]):
        assert hi1 <= lo2, f"{n1} overlaps {n2}"
    for lo, hi, n in spans:
        assert lo < hi, n


def test_resolution_picks_range():
    assert shims.resolve_provider((0, 4, 35)) is JaxLegacyShim
    assert shims.resolve_provider((0, 5, 3)) is JaxLegacyShim
    assert shims.resolve_provider((0, 6, 0)) is JaxCurrentShim
    assert shims.resolve_provider((0, 9, 0)) is JaxCurrentShim


def test_unsupported_version_names_ranges():
    with pytest.raises(RuntimeError) as ei:
        shims.resolve_provider((0, 3, 0))
    msg = str(ei.value)
    assert "JaxLegacyShim" in msg and "JaxCurrentShim" in msg
    assert "SPARK_RAPIDS_TPU_JAX_SHIM_OVERRIDE" in msg


def test_running_version_resolves_and_caches():
    shims._reset_for_tests()
    s1 = shims.get_shim()
    assert isinstance(s1, BaseShim)
    assert shims.get_shim() is s1  # cached, ShimLoader-style


def test_env_override(monkeypatch):
    shims._reset_for_tests()
    monkeypatch.setenv("SPARK_RAPIDS_TPU_JAX_SHIM_OVERRIDE", "0.5.1")
    try:
        assert isinstance(shims.get_shim(), JaxLegacyShim)
    finally:
        shims._reset_for_tests()


def test_no_session_conf_override_exists():
    """The override is deliberately an ENV VAR, not a session conf: shims
    resolve at module import (pytree registration in columnar/nested.py),
    before any session can exist — a conf would be silently ignored.
    This pin keeps someone from adding one back."""
    from spark_rapids_tpu.conf import registry
    assert not any("shims" in k for k in registry())


def test_both_providers_apis_work():
    """Every provider's full API surface runs against the INSTALLED jax
    (the legacy provider's fallbacks degrade to current spellings)."""
    import jax
    for cls in shims.SHIM_PROVIDERS:
        shim = cls()
        assert callable(shim.shard_map())
        assert shim.tree_leaves({"a": 1, "b": (2, 3)}) == [1, 2, 3]
        doubled = shim.tree_map(lambda x: x * 2, {"a": 1, "b": 2})
        assert doubled == {"a": 2, "b": 4}
        assert isinstance(shim.default_backend(), str)
        assert shim.local_device_count() >= 1
        n = min(shim.local_device_count(), 8)
        mesh = shim.make_mesh((n,), ("x",))
        assert mesh.shape["x"] == n
        assert int(shim.jit(lambda a: a + 1)(np.int32(1))) == 2


def test_engine_ici_exchange_rides_shim():
    """The ICI all-to-all (the engine's shard_map call site) still runs
    through the shim indirection."""
    from spark_rapids_tpu.parallel.exchange import _shard_map
    assert callable(_shard_map())


def test_device_manager_discovery_and_selection():
    """Resource discovery + device selection (GpuDeviceManager analog):
    topology facts recorded, explicit ordinal honored, bad ordinal
    rejected with a clear error."""
    from spark_rapids_tpu.conf import RapidsConf
    from spark_rapids_tpu.errors import ColumnarProcessingError
    from spark_rapids_tpu.runtime.device_manager import TpuDeviceManager
    m = TpuDeviceManager(RapidsConf())
    m.initialize()
    topo = m.topology()
    assert topo["local_devices"] >= 1
    assert 0 <= topo["device_ordinal"] < topo["local_devices"]
    assert topo["hbm_limit_bytes"] > 0
    assert topo["num_processes"] >= 1

    m2 = TpuDeviceManager(RapidsConf(
        {"spark.rapids.tpu.deviceOrdinal": topo["local_devices"] - 1}))
    m2.initialize()
    assert m2.topology()["device_ordinal"] == topo["local_devices"] - 1

    bad = TpuDeviceManager(RapidsConf(
        {"spark.rapids.tpu.deviceOrdinal": 4096}))
    with pytest.raises(ColumnarProcessingError):
        bad.initialize()
