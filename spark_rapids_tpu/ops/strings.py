"""String expressions (reference: stringFunctions.scala + cudf strings —
SURVEY.md §2.3 "Misc exprs by family", Appendix A).

TPU-first design: device strings are order-preserving DICTIONARY CODES
(columnar/column.py), so every elementwise string function evaluates by
transforming the dictionary ON HOST (O(cardinality), not O(rows)) and
remapping codes on device with one gather. String->value functions
(length/ascii/instr/predicates) become an aux lookup table per dictionary
entry. This is the idiomatic mapping of cuDF's per-row string kernels onto
an accelerator whose strength is dense integer gathers: the dictionary IS
the compressed representation.

Functions whose result depends on MULTIPLE string columns per row (e.g.
concat of two columns) cannot use the dictionary transform and fall back
(device_supported=False) until a byte-matrix kernel lands.

Regex semantics note: Like is Spark-exact (translated to a Python regex
with escaped specials). RLike / RegExpExtract / RegExpReplace run ONLY
patterns the Java->Python transpiler (ops/regex_transpiler.py) can prove
semantics-exact; anything else tags the expression unsupported so the
plan falls back with the transpiler's reason — the same
guard-or-translate contract as the reference's RegexParser.scala. The CPU
fallback evaluates the raw pattern with a RuntimeWarning noting possible
Java/Python divergence (there is no JVM here to be exactly right)."""

from __future__ import annotations

import re
import warnings
from typing import Optional

import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar import HostColumn, HostTable
from spark_rapids_tpu.ops.common import UnaryExpression
from spark_rapids_tpu.ops.expr import (
    DevVal,
    EvalCtx,
    Expression,
    Literal,
    NodePrep,
    PrepCtx,
)


# ---------------------------------------------------------------------------
# Dictionary-transform machinery
# ---------------------------------------------------------------------------

class DictStringToString(Expression):
    """str -> str via host dictionary transform + device code remap.
    Subclasses implement ``transform(s) -> Optional[str]`` (None = null)."""

    _is_expr_base = True  # excluded from the rules registry

    @property
    def data_type(self):
        return T.STRING

    def transform(self, s: str) -> Optional[str]:
        raise NotImplementedError

    def _child_string(self):
        return self.children[0]

    def eval_cpu(self, table: HostTable) -> HostColumn:
        c = self._child_string().eval_cpu(table)
        n = len(c)
        out = np.empty(n, dtype=object)
        validity = c.validity.copy()
        for i in range(n):
            if validity[i]:
                r = self.transform(c.data[i])
                if r is None:
                    validity[i] = False
                    out[i] = None
                else:
                    out[i] = r
            else:
                out[i] = None
        return HostColumn(T.STRING, out, validity)

    def prep(self, pctx: PrepCtx, child_preps) -> NodePrep:
        d = child_preps[0].out_dict
        if d is None:
            d = np.array([], dtype=object)
        transformed = [self.transform(s) for s in d]
        # nulls in the transform become an invalid-marker remap of -1
        non_null = [t for t in transformed if t is not None]
        out_dict = np.unique(np.array(non_null, dtype=object)) if non_null \
            else np.array([], dtype=object)
        remap = np.array(
            [np.searchsorted(out_dict, t) if t is not None else -1
             for t in transformed], dtype=np.int32)
        slot = pctx.add_aux(remap if len(remap) else np.zeros(1, np.int32))
        return NodePrep(out_dict=out_dict, dict_sorted=True, aux_slots=(slot,))

    def eval_dev(self, ctx: EvalCtx, child_vals, prep: NodePrep) -> DevVal:
        remap = ctx.aux[prep.aux_slots[0]]
        cv = child_vals[0]
        codes = remap[jnp.clip(cv.data, 0, remap.shape[0] - 1)]
        validity = cv.validity & (codes >= 0)
        return DevVal(jnp.maximum(codes, 0), validity)


class DictStringToValue(Expression):
    """str -> fixed-width value via host lookup table + device gather.
    Subclasses implement ``value_of(s)`` and set ``out_type``."""

    _is_expr_base = True  # excluded from the rules registry

    out_type: T.DataType = T.INT

    @property
    def data_type(self):
        return self.out_type

    def value_of(self, s: str):
        raise NotImplementedError

    def eval_cpu(self, table: HostTable) -> HostColumn:
        c = self.children[0].eval_cpu(table)
        n = len(c)
        np_dt = self.out_type.np_dtype
        out = np.zeros(n, dtype=np_dt)
        validity = c.validity.copy()
        for i in range(n):
            if validity[i]:
                v = self.value_of(c.data[i])
                if v is None:
                    validity[i] = False
                else:
                    out[i] = v
        return HostColumn(self.out_type, out, validity)

    def prep(self, pctx: PrepCtx, child_preps) -> NodePrep:
        d = child_preps[0].out_dict
        if d is None:
            d = np.array([], dtype=object)
        np_dt = self.out_type.np_dtype
        vals = np.zeros(max(len(d), 1), dtype=np_dt)
        ok = np.ones(max(len(d), 1), dtype=np.bool_)
        for i, s in enumerate(d):
            v = self.value_of(s)
            if v is None:
                ok[i] = False
            else:
                vals[i] = v
        vslot = pctx.add_aux(vals)
        oslot = pctx.add_aux(ok)
        return NodePrep(aux_slots=(vslot, oslot))

    def eval_dev(self, ctx: EvalCtx, child_vals, prep: NodePrep) -> DevVal:
        vals = ctx.aux[prep.aux_slots[0]]
        ok = ctx.aux[prep.aux_slots[1]]
        cv = child_vals[0]
        idx = jnp.clip(cv.data, 0, vals.shape[0] - 1)
        return DevVal(vals[idx], cv.validity & ok[idx])


class _LiteralParams:
    """Mixin: every child after the first must be a literal (the dictionary
    transform folds parameters at prep time)."""

    @property
    def device_supported(self):
        return all(isinstance(c, Literal) for c in self.children[1:])


# ---------------------------------------------------------------------------
# str -> str
# ---------------------------------------------------------------------------

class Upper(DictStringToString, UnaryExpression):
    def transform(self, s):
        return s.upper()


class Lower(DictStringToString, UnaryExpression):
    def transform(self, s):
        return s.lower()


class Reverse(DictStringToString, UnaryExpression):
    def transform(self, s):
        return s[::-1]


class InitCap(DictStringToString, UnaryExpression):
    def transform(self, s):
        # Spark initcap: first letter of each whitespace-separated word
        return " ".join(w.capitalize() for w in s.split(" "))


class StringTrim(DictStringToString, UnaryExpression):
    def transform(self, s):
        return s.strip(" ")


class StringTrimLeft(DictStringToString, UnaryExpression):
    def transform(self, s):
        return s.lstrip(" ")


class StringTrimRight(DictStringToString, UnaryExpression):
    def transform(self, s):
        return s.rstrip(" ")


class Substring(_LiteralParams, DictStringToString):
    """Spark substring: 1-based pos; pos 0 treated as 1; negative from end."""

    def __init__(self, child: Expression, pos: Expression, length: Expression):
        self.children = (child, pos, length)

    def with_children(self, children):
        return Substring(*children)

    def key(self):
        return ("substring", self.children[0].key(),
                _lit_str_key(self.children[1]), _lit_str_key(self.children[2]))

    def transform(self, s):
        pos = self.children[1].value
        ln = self.children[2].value
        if ln < 0:
            return ""
        # Spark substringSQL: end is computed BEFORE clamping a negative
        # start, so substring('abcd', -5, 3) = 'ab' (start -1, end 2)
        if pos > 0:
            start = pos - 1
        elif pos == 0:
            start = 0
        else:
            start = len(s) + pos
        end = start + ln
        return s[max(start, 0):max(end, 0)]


class StringRepeat(_LiteralParams, DictStringToString):
    def __init__(self, child: Expression, times: Expression):
        self.children = (child, times)

    def with_children(self, children):
        return StringRepeat(*children)

    def key(self):
        return ("repeat", self.children[0].key(), _lit_str_key(self.children[1]))

    def transform(self, s):
        return s * max(int(self.children[1].value), 0)


class StringReplace(_LiteralParams, DictStringToString):
    def __init__(self, child: Expression, search: Expression, replace: Expression):
        self.children = (child, search, replace)

    def with_children(self, children):
        return StringReplace(*children)

    def key(self):
        return ("replace", self.children[0].key(),
                _lit_str_key(self.children[1]), _lit_str_key(self.children[2]))

    def transform(self, s):
        search = self.children[1].value
        if search == "":
            return s
        return s.replace(search, self.children[2].value or "")


class StringLPad(_LiteralParams, DictStringToString):
    def __init__(self, child: Expression, length: Expression, pad: Expression):
        self.children = (child, length, pad)

    def with_children(self, children):
        return StringLPad(*children)

    def key(self):
        return ("lpad", self.children[0].key(),
                _lit_str_key(self.children[1]), _lit_str_key(self.children[2]))

    def transform(self, s):
        ln = int(self.children[1].value)
        if ln <= 0:
            return ""  # Spark: non-positive target length yields empty
        pad = self.children[2].value
        if len(s) >= ln:
            return s[:ln]
        if not pad:
            return s
        fill = (pad * ln)[: ln - len(s)]
        return fill + s


class StringRPad(_LiteralParams, DictStringToString):
    def __init__(self, child: Expression, length: Expression, pad: Expression):
        self.children = (child, length, pad)

    def with_children(self, children):
        return StringRPad(*children)

    def key(self):
        return ("rpad", self.children[0].key(),
                _lit_str_key(self.children[1]), _lit_str_key(self.children[2]))

    def transform(self, s):
        ln = int(self.children[1].value)
        if ln <= 0:
            return ""  # Spark: non-positive target length yields empty
        pad = self.children[2].value
        if len(s) >= ln:
            return s[:ln]
        if not pad:
            return s
        fill = (pad * ln)[: ln - len(s)]
        return s + fill


class SubstringIndex(_LiteralParams, DictStringToString):
    def __init__(self, child: Expression, delim: Expression, count: Expression):
        self.children = (child, delim, count)

    def with_children(self, children):
        return SubstringIndex(*children)

    def key(self):
        return ("substring_index", self.children[0].key(),
                _lit_str_key(self.children[1]), _lit_str_key(self.children[2]))

    def transform(self, s):
        delim = self.children[1].value
        cnt = int(self.children[2].value)
        if not delim or cnt == 0:
            return ""
        parts = s.split(delim)
        if cnt > 0:
            return delim.join(parts[:cnt])
        return delim.join(parts[cnt:])


class StringTranslate(_LiteralParams, DictStringToString):
    def __init__(self, child: Expression, matching: Expression, replace: Expression):
        self.children = (child, matching, replace)

    def with_children(self, children):
        return StringTranslate(*children)

    def key(self):
        return ("translate", self.children[0].key(),
                _lit_str_key(self.children[1]), _lit_str_key(self.children[2]))

    def transform(self, s):
        matching = self.children[1].value
        replace = self.children[2].value or ""
        table = {}
        for i, ch in enumerate(matching):
            if ord(ch) not in table:  # Spark: FIRST mapping of a char wins
                table[ord(ch)] = replace[i] if i < len(replace) else None
        return s.translate(table)


import functools


@functools.lru_cache(maxsize=1024)
def _guarded_regex_cached(pattern: str):
    from spark_rapids_tpu.ops.regex_transpiler import try_transpile
    transpiled, reason = try_transpile(pattern)
    if transpiled is not None:
        return re.compile(transpiled, re.ASCII), True, None
    return re.compile(pattern), False, reason


def _guarded_regex(pattern: str):
    """(compiled python regex, device_ok, reason). Transpiled patterns
    compile with re.ASCII (Java default char classes); rejected patterns
    compile raw with a divergence warning and force CPU fallback. Cached —
    dictionary transforms call this once per dict ENTRY."""
    rx, ok, reason = _guarded_regex_cached(pattern)
    if not ok:
        warnings.warn(
            f"regex {pattern!r} is outside the transpilable subset "
            f"({reason}); evaluating with Python re — results may diverge "
            "from Spark", RuntimeWarning, stacklevel=3)
    return rx, ok, reason


class RegExpReplace(_LiteralParams, DictStringToString):
    def __init__(self, child: Expression, pattern: Expression, replacement: Expression):
        self.children = (child, pattern, replacement)

    def with_children(self, children):
        return RegExpReplace(*children)

    def key(self):
        return ("regexp_replace", self.children[0].key(),
                _lit_str_key(self.children[1]), _lit_str_key(self.children[2]))

    @staticmethod
    def _java_replacement_to_python(rep: str) -> str:
        """Java replacement semantics: $N = group ref (incl $0 = whole
        match), backslash escapes the next char; everything else literal."""
        out = []
        i = 0
        while i < len(rep):
            ch = rep[i]
            if ch == "\\" and i + 1 < len(rep):
                nxt = rep[i + 1]
                out.append("\\\\" if nxt == "\\" else nxt)
                i += 2
                continue
            if ch == "$" and i + 1 < len(rep) and rep[i + 1].isdigit():
                j = i + 1
                while j < len(rep) and rep[j].isdigit():
                    j += 1
                out.append(f"\\g<{rep[i + 1:j]}>")
                i = j
                continue
            out.append("\\\\" if ch == "\\" else ch)
            i += 1
        return "".join(out)

    @property
    def device_supported(self):
        from spark_rapids_tpu.ops.expr import Literal
        from spark_rapids_tpu.ops.regex_transpiler import try_transpile
        if not all(isinstance(c, Literal) for c in self.children[1:]):
            return False  # _LiteralParams contract: params must be literals
        return try_transpile(self.children[1].value)[1] is None

    def transform(self, s):
        rx, _, _ = _guarded_regex(self.children[1].value)
        rep = self._java_replacement_to_python(self.children[2].value or "")
        return rx.sub(rep, s)


class RegExpExtract(_LiteralParams, DictStringToString):
    def __init__(self, child: Expression, pattern: Expression, idx: Expression):
        self.children = (child, pattern, idx)

    def with_children(self, children):
        return RegExpExtract(*children)

    def key(self):
        return ("regexp_extract", self.children[0].key(),
                _lit_str_key(self.children[1]), _lit_str_key(self.children[2]))

    @property
    def device_supported(self):
        from spark_rapids_tpu.ops.expr import Literal
        from spark_rapids_tpu.ops.regex_transpiler import try_transpile
        if not all(isinstance(c, Literal) for c in self.children[1:]):
            return False  # _LiteralParams contract: params must be literals
        return try_transpile(self.children[1].value)[1] is None

    def transform(self, s):
        rx, _, _ = _guarded_regex(self.children[1].value)
        m = rx.search(s)
        if m is None:
            return ""
        g = int(self.children[2].value)
        return m.group(g) or ""


class Concat(DictStringToString):
    """concat of strings: dictionary transform when at most ONE child is a
    non-literal column; multi-column concat falls back."""

    def __init__(self, *children: Expression):
        self.children = tuple(children)

    def with_children(self, children):
        return Concat(*children)

    def key(self):
        return ("concat",) + tuple(
            c.key() if not isinstance(c, Literal) else ("lit", c.value)
            for c in self.children)

    @property
    def device_supported(self):
        non_lit = [c for c in self.children if not isinstance(c, Literal)]
        return len(non_lit) <= 1

    def _child_string(self):
        for c in self.children:
            if not isinstance(c, Literal):
                return c
        return self.children[0]

    def transform(self, s):
        parts = []
        for c in self.children:
            if isinstance(c, Literal):
                if c.value is None:
                    return None  # concat with null -> null
                parts.append(str(c.value))
            else:
                parts.append(s)
        return "".join(parts)

    def eval_cpu(self, table: HostTable) -> HostColumn:
        cols = [c.eval_cpu(table) for c in self.children]
        n = table.num_rows
        out = np.empty(n, dtype=object)
        validity = np.ones(n, dtype=np.bool_)
        for i in range(n):
            parts = []
            for c in cols:
                if not c.validity[i]:
                    validity[i] = False
                    break
                parts.append(str(c.data[i]))
            out[i] = "".join(parts) if validity[i] else None
        return HostColumn(T.STRING, out, validity)

    def prep(self, pctx, child_preps):
        # the non-literal child's prep is the one with the dictionary
        for c, p in zip(self.children, child_preps):
            if not isinstance(c, Literal):
                return DictStringToString.prep(self, pctx, [p])
        return DictStringToString.prep(self, pctx, [child_preps[0]])

    def eval_dev(self, ctx, child_vals, prep):
        for c, v in zip(self.children, child_vals):
            if not isinstance(c, Literal):
                return DictStringToString.eval_dev(self, ctx, [v], prep)
        return DictStringToString.eval_dev(self, ctx, [child_vals[0]], prep)


# ---------------------------------------------------------------------------
# str -> int / bool
# ---------------------------------------------------------------------------

class Length(DictStringToValue, UnaryExpression):
    out_type = T.INT

    def value_of(self, s):
        return len(s)


class BitLength(DictStringToValue, UnaryExpression):
    out_type = T.INT

    def value_of(self, s):
        return len(s.encode("utf-8")) * 8


class OctetLength(DictStringToValue, UnaryExpression):
    out_type = T.INT

    def value_of(self, s):
        return len(s.encode("utf-8"))


class Ascii(DictStringToValue, UnaryExpression):
    out_type = T.INT

    def value_of(self, s):
        return ord(s[0]) if s else 0


class _StringPredicate(_LiteralParams, DictStringToValue):
    out_type = T.BOOLEAN

    def __init__(self, child: Expression, param: Expression):
        self.children = (child, param)

    def with_children(self, children):
        return type(self)(*children)

    def key(self):
        return (type(self).__name__.lower(), self.children[0].key(),
                _lit_str_key(self.children[1]))

    @property
    def param(self) -> str:
        return self.children[1].value


class Contains(_StringPredicate):
    def value_of(self, s):
        return self.param in s


class StartsWith(_StringPredicate):
    def value_of(self, s):
        return s.startswith(self.param)


class EndsWith(_StringPredicate):
    def value_of(self, s):
        return s.endswith(self.param)


def like_to_regex(pattern: str, escape: str = "\\") -> str:
    """Spark-exact LIKE -> regex translation (% = .*, _ = ., escape char)."""
    out = ["^"]
    i = 0
    while i < len(pattern):
        ch = pattern[i]
        if ch == escape and i + 1 < len(pattern):
            out.append(re.escape(pattern[i + 1]))
            i += 2
            continue
        if ch == "%":
            out.append(".*")
        elif ch == "_":
            out.append(".")
        else:
            out.append(re.escape(ch))
        i += 1
    out.append("$")
    return "".join(out)


class Like(_StringPredicate):
    def value_of(self, s):
        return re.match(like_to_regex(self.param), s, re.DOTALL) is not None


class RLike(_StringPredicate):
    @property
    def device_supported(self):
        from spark_rapids_tpu.ops.expr import Literal
        from spark_rapids_tpu.ops.regex_transpiler import try_transpile
        if not all(isinstance(c, Literal) for c in self.children[1:]):
            return False
        return try_transpile(self.param)[1] is None

    def value_of(self, s):
        rx, _, _ = _guarded_regex(self.param)
        return rx.search(s) is not None


class StringInstr(_LiteralParams, DictStringToValue):
    """instr: 1-based position of first occurrence, 0 if absent."""

    out_type = T.INT

    def __init__(self, child: Expression, substr: Expression):
        self.children = (child, substr)

    def with_children(self, children):
        return StringInstr(*children)

    def key(self):
        return ("instr", self.children[0].key(), _lit_str_key(self.children[1]))

    def value_of(self, s):
        return s.find(self.children[1].value) + 1


class StringLocate(_LiteralParams, DictStringToValue):
    """locate(substr, str, start): 1-based, start 1-based."""

    out_type = T.INT

    def __init__(self, substr: Expression, child: Expression, start: Expression):
        self.children = (child, substr, start)

    def with_children(self, children):
        return StringLocate(children[1], children[0], children[2])

    def key(self):
        return ("locate", self.children[0].key(),
                _lit_str_key(self.children[1]), _lit_str_key(self.children[2]))

    def value_of(self, s):
        start = int(self.children[2].value)
        if start <= 0:
            return 0
        return s.find(self.children[1].value, start - 1) + 1


def _lit_str_key(e: Expression):
    if isinstance(e, Literal):
        return ("lit", e.value)
    return e.key()


class Conv(DictStringToString):
    """conv(numStr, fromBase, toBase): base conversion with Spark/Hive
    semantics (bases 2..36, literal bases; invalid digits truncate at the
    first bad char; empty -> null; toBase<0 -> signed output)."""

    def __init__(self, child, from_base, to_base):
        self.children = (child, from_base, to_base)

    def with_children(self, children):
        return Conv(children[0], children[1], children[2])

    def key(self):
        return ("conv", self._bases(), self.children[0].key())

    def _bases(self):
        from spark_rapids_tpu.ops.expr import Literal
        fb, tb = self.children[1], self.children[2]
        if isinstance(fb, Literal) and isinstance(tb, Literal) \
                and fb.value is not None and tb.value is not None:
            return int(fb.value), int(tb.value)
        return None

    @property
    def device_supported(self):
        b = self._bases()
        return b is not None and 2 <= b[0] <= 36 and 2 <= abs(b[1]) <= 36

    @staticmethod
    def _convert(s: str, from_base: int, to_base: int):
        """Hive NumberConverter semantics: empty -> null; '-' optional
        sign; digits stop at the FIRST invalid char ('+'/whitespace are
        invalid -> value 0 -> "0"); unsigned-64 accumulation SATURATES at
        2^64-1; positive toBase prints unsigned, negative prints signed."""
        if not (2 <= from_base <= 36 and 2 <= abs(to_base) <= 36):
            return None
        if not s:
            return None
        neg = s.startswith("-")
        t = s[1:] if neg else s
        digits = "0123456789abcdefghijklmnopqrstuvwxyz"[:from_base]
        u64_max = (1 << 64) - 1
        v = 0
        for ch in t.lower():
            d = digits.find(ch)
            if d < 0:
                break
            v = v * from_base + d
            if v > u64_max:
                v = u64_max  # saturate (Hive overflow behavior)
        if neg:
            v = (-v) & u64_max  # two's-complement wrap of the negation
        if to_base < 0 and v > (1 << 63) - 1:
            signed = v - (1 << 64)
            out_neg, v, base = True, -signed, -to_base
        else:
            out_neg, base = False, abs(to_base)
        alphabet = "0123456789ABCDEFGHIJKLMNOPQRSTUVWXYZ"
        out = ""
        while True:
            out = alphabet[v % base] + out
            v //= base
            if v == 0:
                break
        return ("-" if out_neg else "") + out

    def transform(self, s):
        b = self._bases()
        if b is None:
            return None
        return self._convert(s, b[0], b[1])

    def eval_cpu(self, table):
        if self._bases() is not None:
            return super().eval_cpu(table)
        # non-literal bases: CPU fallback evaluates them per row
        doc = self.children[0].eval_cpu(table)
        fb = self.children[1].eval_cpu(table)
        tb = self.children[2].eval_cpu(table)
        n = len(doc)
        out = np.empty(n, dtype=object)
        validity = (doc.validity & fb.validity & tb.validity).copy()
        for i in range(n):
            r = None
            if validity[i]:
                r = self._convert(doc.data[i], int(fb.data[i]),
                                  int(tb.data[i]))
            out[i] = r
            validity[i] = r is not None
        return HostColumn(T.STRING, out, validity)
