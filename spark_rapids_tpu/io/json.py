"""JSON scan + writer (reference: GpuJsonScan.scala /
GpuTextBasedPartitionReader — SURVEY.md §2.4).

Spark options honored: multiLine (whole-file JSON array/object parsed via
the stdlib and rebuilt as lines for arrow), primitivesAsString, and
mode = PERMISSIVE (malformed lines -> all-null row) | DROPMALFORMED |
FAILFAST, matching the reference's tagging-or-support contract instead of
silently ignoring options."""

from __future__ import annotations

import json as _json
from typing import List, Optional, Sequence

import pyarrow as pa
import pyarrow.json as pjson

from spark_rapids_tpu.columnar import HostTable
from spark_rapids_tpu.conf import RapidsConf, str_conf
from spark_rapids_tpu.io.arrow_convert import (
    arrow_schema_to_spark,
    decode_to_schema,
    spark_type_to_arrow,
)
from spark_rapids_tpu.io.common import FileScanNode
from spark_rapids_tpu.io.writer import write_partitioned
from spark_rapids_tpu.plan.nodes import Schema

JSON_READER_TYPE = str_conf(
    "spark.rapids.sql.format.json.reader.type", "AUTO",
    "PERFILE, COALESCING, MULTITHREADED or AUTO.")


class JsonScanNode(FileScanNode):
    format_name = "json"

    def __init__(self, paths, conf: RapidsConf, columns=None, reader_type=None,
                 schema: Optional[Schema] = None, multi_line: bool = False,
                 primitives_as_string: bool = False,
                 mode: str = "PERMISSIVE", **options):
        self.user_schema = schema
        self.multi_line = multi_line
        self.primitives_as_string = primitives_as_string
        self.mode = str(mode).upper()
        if self.mode not in ("PERMISSIVE", "DROPMALFORMED", "FAILFAST"):
            raise ValueError(f"unknown JSON mode {mode!r}")
        super().__init__(paths, conf, columns=columns, reader_type=reader_type,
                         **options)

    def _conf_reader_type(self) -> str:
        return self.conf.get_entry(JSON_READER_TYPE)

    def _cache_key_extra(self) -> tuple:
        return (tuple(self.user_schema or ()), self.multi_line,
                self.primitives_as_string, self.mode)

    def _parse_opts(self):
        if self.primitives_as_string and self.user_schema is None:
            return None  # schema inference happens post-stringify
        if not self.user_schema:
            return None
        from spark_rapids_tpu import types as T
        schema = []
        for n, dt in self.user_schema:
            nested = isinstance(dt, (T.ArrayType, T.StructType, T.MapType))
            at = (pa.string() if self.primitives_as_string and not nested
                  else spark_type_to_arrow(dt))
            schema.append((n, at))
        return pjson.ParseOptions(explicit_schema=pa.schema(schema))

    def _normalized_lines(self, path: str) -> bytes:
        """Apply multiLine + mode to produce clean JSON-lines bytes."""
        with open(path, "rb") as f:
            raw = f.read()
        if self.multi_line:
            try:
                doc = _json.loads(raw)
            except _json.JSONDecodeError:
                if self.mode == "FAILFAST":
                    raise
                # PERMISSIVE: one all-null row; DROPMALFORMED: empty
                return b"{}" if self.mode == "PERMISSIVE" else b""
            rows = doc if isinstance(doc, list) else [doc]
            return ("\n".join(_json.dumps(r) for r in rows)).encode()
        if self.mode == "FAILFAST":
            for ln in raw.splitlines():
                if ln.strip():
                    _json.loads(ln)  # raises on malformed
            return raw

        def _reject_const(_):
            raise _json.JSONDecodeError("non-standard constant", "", 0)

        out = []
        for ln in raw.splitlines():
            s = ln.strip()
            if not s:
                continue
            try:
                # parse_constant: Python json accepts NaN/Infinity that
                # Arrow rejects — treat them as malformed consistently
                _json.loads(s, parse_constant=_reject_const)
                out.append(ln)
            except _json.JSONDecodeError:
                if self.mode == "PERMISSIVE":
                    out.append(b"{}")  # all-null row (Spark permissive)
                # DROPMALFORMED: skip
        return b"\n".join(out)

    def _read_arrow(self, path: str) -> pa.Table:
        import io as _io
        if not self.multi_line:
            # fast path: stream straight through arrow; the per-line
            # salvage pass only runs if arrow rejects the file
            try:
                return pjson.read_json(path,
                                       parse_options=self._parse_opts())
            except pa.ArrowInvalid:
                if self.mode == "FAILFAST":
                    raise
        data = self._normalized_lines(path)
        if not data.strip():
            # every row dropped (DROPMALFORMED): an empty typed table
            if self.user_schema:
                return pa.table({n: pa.array([], spark_type_to_arrow(dt))
                                 for n, dt in self.user_schema})
            return pa.table({})
        return pjson.read_json(_io.BytesIO(data),
                               parse_options=self._parse_opts())

    def file_schema(self, path: str) -> Schema:
        if self.user_schema:
            return list(self.user_schema)
        schema = arrow_schema_to_spark(self._read_arrow(path).schema)
        if self.primitives_as_string:
            # Spark stringifies only PRIMITIVE leaves; nested stay as-is
            from spark_rapids_tpu import types as T
            schema = [(n, T.STRING if not isinstance(
                dt, (T.ArrayType, T.StructType, T.MapType)) else dt)
                for n, dt in schema]
        return schema

    def read_file(self, path: str) -> HostTable:
        tbl = self._read_arrow(path)
        if self.primitives_as_string and self.user_schema is None:
            cols = []
            for i in range(tbl.num_columns):
                c = tbl.column(i)
                if pa.types.is_nested(c.type):
                    cols.append(c)  # Spark leaves nested types intact
                else:
                    cols.append(c.cast(pa.string()))
            tbl = pa.table(dict(zip(tbl.column_names, cols)))
        return decode_to_schema(tbl, self.data_schema)


def write_json(table: HostTable, path: str,
               partition_by: Optional[Sequence[str]] = None,
               committer=None) -> List[str]:
    """JSON-lines writer (Arrow has no JSON writer; rows serialize via the
    host columns directly)."""
    def _write_one(tbl: HostTable, file_path: str):
        cols = [c.to_pylist() for c in tbl.columns]
        with open(file_path, "w") as f:
            for i in range(tbl.num_rows):
                row = {n: cols[j][i] for j, n in enumerate(tbl.names)
                       if cols[j][i] is not None}
                f.write(_json.dumps(row, default=str) + "\n")
    return write_partitioned(table, path, _write_one, "json", partition_by,
                             committer=committer)
