"""Hash-aggregate oracle tests (reference analog: hash_aggregate_test.py)."""

import pytest

from spark_rapids_tpu import functions as F
from spark_rapids_tpu.ops.expr import col

from tests.asserts import assert_tpu_and_cpu_are_equal, assert_runs_on_tpu
from tests.data_gen import (
    BooleanGen, DoubleGen, IntGen, LongGen, StringGen, gen_table,
)


def _df(sess, gens, n=800, seed=11, num_batches=1):
    from spark_rapids_tpu.plan import from_host_table
    return from_host_table(gen_table(gens, n, seed), sess, num_batches)


KEYED = {"k": IntGen(min_val=0, max_val=20), "v": LongGen(min_val=-1000, max_val=1000),
         "d": DoubleGen(), "s": StringGen(cardinality=10)}


def test_groupby_count_sum_min_max(session, cpu_session):
    assert_tpu_and_cpu_are_equal(
        lambda s: _df(s, KEYED).group_by("k").agg(
            F.count().alias("cnt"),
            F.count(col("v")).alias("cntv"),
            F.sum(col("v")).alias("sumv"),
            F.min(col("v")).alias("minv"),
            F.max(col("v")).alias("maxv"),
        ),
        session, cpu_session)


def test_groupby_avg(session, cpu_session):
    assert_tpu_and_cpu_are_equal(
        lambda s: _df(s, KEYED).group_by("k").agg(
            F.avg(col("v")).alias("avgv"),
            F.avg(col("d")).alias("avgd"),
            F.sum(col("d")).alias("sumd"),
        ),
        session, cpu_session, approximate_float=True)


def test_groupby_string_key(session, cpu_session):
    assert_tpu_and_cpu_are_equal(
        lambda s: _df(s, KEYED).group_by("s").agg(
            F.count().alias("cnt"),
            F.sum(col("v")).alias("sumv"),
        ),
        session, cpu_session)


def test_groupby_multi_key(session, cpu_session):
    assert_tpu_and_cpu_are_equal(
        lambda s: _df(s, KEYED).group_by("k", "s").agg(
            F.count().alias("cnt"),
            F.max(col("d")).alias("maxd"),
        ),
        session, cpu_session, approximate_float=True)


def test_groupby_string_minmax(session, cpu_session):
    assert_tpu_and_cpu_are_equal(
        lambda s: _df(s, KEYED).group_by("k").agg(
            F.min(col("s")).alias("mins"),
            F.max(col("s")).alias("maxs"),
        ),
        session, cpu_session)


def test_global_agg(session, cpu_session):
    assert_tpu_and_cpu_are_equal(
        lambda s: _df(s, KEYED).agg(
            F.count().alias("cnt"),
            F.sum(col("v")).alias("sumv"),
            F.min(col("k")).alias("mink"),
            F.max(col("s")).alias("maxs"),
        ),
        session, cpu_session)


def test_agg_with_expr_keys_and_values(session, cpu_session):
    assert_tpu_and_cpu_are_equal(
        lambda s: _df(s, KEYED).group_by((col("k") % 5).alias("k5")).agg(
            F.sum(col("v") * 2).alias("s2"),
            F.count(col("d")).alias("cd"),
        ),
        session, cpu_session)


def test_stddev_variance(session, cpu_session):
    assert_tpu_and_cpu_are_equal(
        lambda s: _df(s, KEYED).group_by("k").agg(
            F.stddev(col("d")).alias("sd"),
            F.var_pop(col("d")).alias("vp"),
        ),
        session, cpu_session, approximate_float=True)


def test_first_last(session, cpu_session):
    # first/last are order-dependent; with a single batch and stable device
    # sort they must agree with the CPU path
    assert_tpu_and_cpu_are_equal(
        lambda s: _df(s, KEYED).group_by("k").agg(
            F.first(col("v")).alias("fv"),
            F.last(col("v")).alias("lv"),
            F.first(col("v"), ignore_nulls=True).alias("fvn"),
        ),
        session, cpu_session)


def test_agg_multi_batch_input(session, cpu_session):
    assert_tpu_and_cpu_are_equal(
        lambda s: _df(s, KEYED, n=2000, num_batches=5).group_by("k").agg(
            F.count().alias("cnt"), F.sum(col("v")).alias("sv")),
        session, cpu_session)


def test_agg_runs_on_tpu(session):
    assert_runs_on_tpu(
        lambda s: _df(s, KEYED).group_by("k").agg(F.sum(col("v")).alias("sv")),
        session)


def test_boolean_minmax(session, cpu_session):
    assert_tpu_and_cpu_are_equal(
        lambda s: _df(s, {"k": IntGen(min_val=0, max_val=5), "b": BooleanGen()})
        .group_by("k").agg(F.min(col("b")).alias("minb"), F.max(col("b")).alias("maxb")),
        session, cpu_session)


def test_collect_list_set_percentile(session, cpu_session):
    """collect_list / collect_set / exact percentile on device (sort-
    segment path; reference: GpuCollectList/Set, GpuPercentile)."""
    from tests.asserts import assert_runs_on_tpu
    gens = {"k": StringGen(cardinality=5),
            "v": IntGen(min_val=-30, max_val=30, null_prob=0.2),
            "d": DoubleGen(corner_prob=0.0)}

    def build(s):
        return _df(s, gens, n=300).group_by("k").agg(
            F.collect_list(col("v")).alias("cl"),
            F.collect_set(col("v")).alias("cs"),
            F.percentile(col("d"), 0.5).alias("med"),
            F.percentile(col("d"), 0.9).alias("p90"),
        )

    assert_runs_on_tpu(build, session)
    tpu = build(session).collect_table().to_pydict()
    cpu = build(cpu_session).collect_table().to_pydict()
    tkey = sorted(range(len(tpu["k"])), key=lambda i: str(tpu["k"][i]))
    ckey = sorted(range(len(cpu["k"])), key=lambda i: str(cpu["k"][i]))
    for ti, ci in zip(tkey, ckey):
        assert tpu["k"][ti] == cpu["k"][ci]
        # list preserves input order; set is value-sorted on both paths
        assert tpu["cl"][ti] == cpu["cl"][ci]
        assert tpu["cs"][ti] == sorted(set(cpu["cs"][ci]))
        for name in ("med", "p90"):
            a, b = tpu[name][ti], cpu[name][ci]
            assert (a is None) == (b is None)
            if a is not None:
                assert abs(a - b) <= 1e-9 * max(1.0, abs(b)), (name, a, b)


def test_collect_list_empty_groups(session, cpu_session):
    """All-null value groups produce EMPTY arrays, not null."""
    gens = {"k": StringGen(cardinality=3),
            "v": IntGen(null_prob=1.0)}  # every value null
    tpu = _df(session, gens, n=60).group_by("k").agg(
        F.collect_list(col("v")).alias("cl")).collect_table().to_pydict()
    assert all(x == [] for x in tpu["cl"])
