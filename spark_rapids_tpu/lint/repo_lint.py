"""Python-AST repo lint: project invariants the type system can't hold.

The TPU-first rule this codebase lives by (dispatch.py header): NOTHING
transfers host<->device on a warm query outside the sanctioned sites.
The type checker cannot see a stray ``jax.device_get`` in a kernel or a
conf key referenced by a typo'd string — this lint can.

The rules themselves live in per-rule modules under ``lint/rules/``
(see each module's docstring for its contract) plus the concurrency
pass in ``lint/concurrency.py``; this module is the driver —
``lint_repo()`` parses every source file once and runs the shared rule
registry (``lint.rules.REGISTRY``) over the trees — and the stable
import surface: every ``_check_*`` checker and allowlist keeps its
historical name HERE (same objects, re-exported), so callers and tests
are unaffected by the package split.

Rules (RL-*): RL-HOST-SYNC, RL-JNP-SCOPE, RL-CONF-KEY,
RL-NONDETERMINISM, RL-DEAD-LAMBDA, RL-FAULT-POINT, RL-THREAD-SHARED,
RL-MESH-HOST, RL-WRITE-COMMIT, RL-KERNEL-HOST, RL-OBS-PASSIVE,
RL-MEM-ACCOUNT, RL-MV-EPOCH, and the concurrency contract
(RL-LOCK-DECL, RL-LOCK-ORDER, RL-LOCK-EFFECT — see
``lint/concurrency.py``).
"""

from __future__ import annotations

import ast
from typing import List, Optional

from spark_rapids_tpu.lint.diagnostics import Diagnostic
from spark_rapids_tpu.lint.rules import REGISTRY, LintContext
# re-exports: the stable import surface (tests and callers patch the
# allowlist DICTS in place — these must stay the same objects the rule
# modules read)
from spark_rapids_tpu.lint.rules.common import (  # noqa: F401
    _attr_chain, _host_sync_call, _is_device_expr, _iter_source_files,
    _rel, _repo_root)
from spark_rapids_tpu.lint.rules.conf_keys import (  # noqa: F401
    _CONF_KEY_RE, _check_conf_keys)
from spark_rapids_tpu.lint.rules.determinism import (  # noqa: F401
    _SEEDED_RANDOM_OK, _check_dead_lambdas, _check_nondeterminism)
from spark_rapids_tpu.lint.rules.device_residency import (  # noqa: F401
    _DEVICE_DIRS, _DEVICE_FILES, _KERNEL_HOST_ALLOWLIST,
    _MEM_ACCOUNT_ALLOWLIST, _MESH_HOST_ALLOWLIST, _check_host_sync,
    _check_jnp_scope, _check_kernel_host, _check_mem_account,
    _check_mesh_host)
from spark_rapids_tpu.lint.rules.fault_points import (  # noqa: F401
    _check_fault_registry, _check_fault_sites, _is_fault_point_call)
from spark_rapids_tpu.lint.rules.io_write import (  # noqa: F401
    _WRITE_COMMIT_EXEMPT, _WRITE_ONE, _check_write_commit,
    _open_mode_writes)
from spark_rapids_tpu.lint.rules.obs_passive import (  # noqa: F401
    _OBS_PASSIVE_ALLOWLIST, _OBS_PASSIVE_MODULE, _check_obs_passive)
from spark_rapids_tpu.lint.rules.streaming_epoch import (  # noqa: F401
    _MV_EPOCH_ALLOWED_IMPORTS, _check_mv_epoch)
from spark_rapids_tpu.lint.rules.thread_shared import (  # noqa: F401
    _THREAD_SHARED_ALLOWLIST, _THREAD_SHARED_DIRS, _check_thread_shared,
    _is_lock_guard, _is_mutable_container)


def lint_repo(repo_root: Optional[str] = None) -> List[Diagnostic]:
    root = _repo_root(repo_root)
    from spark_rapids_tpu.lint.registry_audit import _import_full_package
    _import_full_package()
    from spark_rapids_tpu import conf as C
    ctx = LintContext(declared=set(C.registry()))
    diags: List[Diagnostic] = []
    for path in _iter_source_files(root):
        rel = _rel(root, path)
        if rel.startswith("spark_rapids_tpu/lint/"):
            continue  # the lint's own rule tables name forbidden patterns
        with open(path) as f:
            src = f.read()
        tree = ast.parse(src, filename=rel)  # unparseable repo = hard error
        ctx.trees[rel] = tree
        for rule in REGISTRY:
            if rule.file_check is not None:
                rule.file_check(ctx, rel, tree, diags)
    for rule in REGISTRY:
        if rule.finalizer is not None:
            rule.finalizer(ctx, diags)
    return diags
