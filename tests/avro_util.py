"""Minimal Avro container-file WRITER for test data generation (the image
has no Avro library; the reference generates avro test data with
spark-avro in its integration suite). Supports what the scan supports:
records of primitives, ["null", T] unions, date/timestamp logical types,
codecs null/deflate/zstandard."""

import io
import json
import struct
import zlib


def _zigzag(n: int) -> bytes:
    u = (n << 1) ^ (n >> 63)
    out = bytearray()
    while True:
        b = u & 0x7F
        u >>= 7
        if u:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _encode_value(field_schema, v, out: io.BytesIO):
    if isinstance(field_schema, list):
        null_index = field_schema.index("null")
        if v is None:
            out.write(_zigzag(null_index))
            return
        branch = [b for b in field_schema if b != "null"][0]
        out.write(_zigzag(1 - null_index))
        _encode_value(branch, v, out)
        return
    if isinstance(field_schema, dict):
        t = field_schema.get("type")
        if t == "record":
            for fld in field_schema["fields"]:
                _encode_value(fld["type"], v[fld["name"]], out)
            return
        if t == "array":
            if v:
                out.write(_zigzag(len(v)))
                for item in v:
                    _encode_value(field_schema["items"], item, out)
            out.write(_zigzag(0))
            return
        if t == "map":
            if v:
                out.write(_zigzag(len(v)))
                for k, item in v.items():
                    kb = k.encode("utf-8")
                    out.write(_zigzag(len(kb)) + kb)
                    _encode_value(field_schema["values"], item, out)
            out.write(_zigzag(0))
            return
        logical = field_schema.get("logicalType")
        if logical == "timestamp-millis":
            out.write(_zigzag(int(v)))
            return
        _encode_value(field_schema["type"], v, out)
        return
    if field_schema in ("int", "long"):
        out.write(_zigzag(int(v)))
    elif field_schema == "boolean":
        out.write(b"\x01" if v else b"\x00")
    elif field_schema == "float":
        out.write(struct.pack("<f", v))
    elif field_schema == "double":
        out.write(struct.pack("<d", v))
    elif field_schema == "string":
        b = v.encode("utf-8")
        out.write(_zigzag(len(b)) + b)
    else:
        raise ValueError(f"unsupported avro type {field_schema!r}")


def write_avro(path, schema: dict, rows, codec="null", rows_per_block=1000,
               sync=b"0123456789abcdef"):
    """rows: list of dicts keyed by field name."""
    fields = schema["fields"]
    meta = {"avro.schema": json.dumps(schema).encode(),
            "avro.codec": codec.encode()}
    with open(path, "wb") as f:
        f.write(b"Obj\x01")
        f.write(_zigzag(len(meta)))
        for k, v in meta.items():
            kb = k.encode()
            f.write(_zigzag(len(kb)) + kb)
            f.write(_zigzag(len(v)) + v)
        f.write(_zigzag(0))
        f.write(sync)
        for start in range(0, len(rows), rows_per_block):
            chunk = rows[start:start + rows_per_block]
            body = io.BytesIO()
            for row in chunk:
                for fld in fields:
                    _encode_value(fld["type"], row[fld["name"]], body)
            data = body.getvalue()
            if codec == "deflate":
                c = zlib.compressobj(wbits=-15)
                data = c.compress(data) + c.flush()
            elif codec == "zstandard":
                import zstandard
                data = zstandard.ZstdCompressor().compress(data)
            elif codec != "null":
                raise ValueError(f"unsupported codec {codec}")
            f.write(_zigzag(len(chunk)))
            f.write(_zigzag(len(data)))
            f.write(data)
            f.write(sync)
