"""Delta Lake connector (reference: delta-lake/ module family, 35k LoC —
SURVEY.md §2.8). Native implementation of the Delta protocol (JSON log +
parquet checkpoints + deletion vectors) over this engine's scan/write
paths: snapshot reads with time travel, append/overwrite writes with
per-file stats, DELETE (deletion-vector path), UPDATE, MERGE, OPTIMIZE
(+Z-ORDER), VACUUM, DESCRIBE HISTORY."""

from spark_rapids_tpu.delta.commands import DeltaTable, MergeBuilder
from spark_rapids_tpu.delta.log import (
    DeltaConcurrentModificationException,
    DeltaLog,
    Snapshot,
)
from spark_rapids_tpu.delta.table import DeltaScanNode, write_delta

__all__ = [
    "DeltaTable", "MergeBuilder", "DeltaLog", "Snapshot",
    "DeltaConcurrentModificationException", "DeltaScanNode", "write_delta",
]

# register the scan with the overrides engine (kill switch:
# spark.rapids.sql.exec.DeltaScanNode)
from spark_rapids_tpu.overrides.rules import register_file_scan

register_file_scan(DeltaScanNode)
