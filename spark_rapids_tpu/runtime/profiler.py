"""Profiler / tracing subsystem.

Reference (SURVEY.md §5): (a) NVTX ranges everywhere
(``NvtxWithMetrics.scala``) for Nsight timelines; (b) the built-in async
profiler — ``profiler.scala`` ProfilerOnExecutor/OnDriver: JNI CUPTI
trace collection to a ProfileWriter, with driver-coordinated enable
windows keyed by job/time ranges (``spark.rapids.profile.*`` confs).

TPU mapping: XLA's profiler (Xprof) plays CUPTI's role —
``jax.profiler.start_trace/stop_trace`` writes a TensorBoard/Xprof trace
directory; ``jax.profiler.TraceAnnotation`` is the NVTX-range analog and
shows engine operators on the device timeline. Enable windows: every
query, or a query-index range (``spark.rapids.profile.queryRanges`` e.g.
"2-5,8" — RangeConfMatcher semantics)."""

from __future__ import annotations

import contextlib
import os
import threading
from typing import Optional, Set

from spark_rapids_tpu.conf import RapidsConf, bool_conf, str_conf

PROFILE_ENABLED = bool_conf(
    "spark.rapids.profile.enabled", False,
    "Collect XLA (Xprof) device traces for queries (profiler.scala "
    "analog).")

PROFILE_PATH = str_conf(
    "spark.rapids.profile.pathPrefix", "/tmp/rapids_tpu_profile",
    "Directory prefix for collected trace sessions.")

PROFILE_QUERY_RANGES = str_conf(
    "spark.rapids.profile.queryRanges", "",
    "Query-index ranges to profile, e.g. \"0-2,5\" (empty = all queries "
    "when profiling is enabled). RangeConfMatcher syntax.")


def parse_ranges(spec: str) -> Optional[Set[int]]:
    """\"1-3,8\" -> {1,2,3,8}; empty/blank -> None (match all)
    (RangeConfMatcher.scala analog)."""
    spec = (spec or "").strip()
    if not spec:
        return None
    out: Set[int] = set()
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "-" in part:
            lo, _, hi = part.partition("-")
            out.update(range(int(lo), int(hi) + 1))
        else:
            out.add(int(part))
    return out


class TpuProfiler:
    """Per-session profiler driver (ProfilerOnExecutor analog)."""

    def __init__(self, conf: RapidsConf):
        self.enabled = bool(conf.get_entry(PROFILE_ENABLED))
        self.path_prefix = str(conf.get_entry(PROFILE_PATH))
        self.ranges = parse_ranges(str(conf.get_entry(PROFILE_QUERY_RANGES)))
        self._query_index = 0
        self._lock = threading.Lock()
        self._active_path: Optional[str] = None
        self.sessions_written = 0

    def should_profile(self, query_index: int) -> bool:
        return self.enabled and (self.ranges is None
                                 or query_index in self.ranges)

    @contextlib.contextmanager
    def profile_query(self):
        """Wrap one query execution in a trace session; traces land under
        <prefix>/query_<N>/."""
        with self._lock:
            idx = self._query_index
            self._query_index += 1
        if not self.should_profile(idx):
            yield None
            return
        import jax
        path = os.path.join(self.path_prefix, f"query_{idx}")
        with self._lock:
            if self._active_path is not None:
                claimed = False
            else:
                self._active_path = path
                claimed = True
        if not claimed:
            # XLA allows one trace session per process; nested/concurrent
            # queries (cached-relation materialization) ride the outer
            # session — and run OUTSIDE the lock
            yield None
            return
        os.makedirs(path, exist_ok=True)
        try:
            jax.profiler.start_trace(path)
            try:
                yield path
            finally:
                jax.profiler.stop_trace()
                self.sessions_written += 1
        finally:
            with self._lock:
                self._active_path = None


def op_range(name: str):
    """Operator range on the device timeline (NvtxRange analog). Usable
    whether or not a trace session is active — zero-cost when inactive."""
    import jax
    return jax.profiler.TraceAnnotation(name)
