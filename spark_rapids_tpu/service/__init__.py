"""Multi-tenant concurrent query service.

Reference: the serving layer the plugin assumes Spark provides —
concurrent tasks sharing one device through ``GpuSemaphore``
(``spark.rapids.sql.concurrentGpuTasks``), scheduler pools, and the
driver's kill/timeout plumbing. This engine owns its sessions, so it
owns the serving layer too:

* :mod:`spark_rapids_tpu.service.scheduler` — ``QueryService``: a
  worker pool in front of one ``TpuSession``, with named scheduling
  pools, per-tenant weighted fair queueing, bounded queue depth with
  typed rejection (``QueryRejectedError`` + retry-after), per-query
  deadlines, and memory-pressure-aware admission consulting the spill
  catalog. Knobs under ``spark.rapids.service.*``.
* :mod:`spark_rapids_tpu.service.query` — ``QueryHandle``: the
  QUEUED -> ADMITTED -> RUNNING -> {FINISHED, FAILED, CANCELLED,
  TIMED_OUT} state machine, plus the cooperative-cancellation exec
  boundary (third per-query wrapper in the
  ``install_fault_boundaries`` / ``install_observation`` family).
* :mod:`spark_rapids_tpu.service.result_cache` — plan-fingerprint LRU
  result cache over ``HostTable`` results, invalidated on catalog
  mutation and table writes.
"""

from spark_rapids_tpu.service.query import (  # noqa: F401
    QueryHandle,
    QueryState,
    install_cancellation,
)
from spark_rapids_tpu.service.result_cache import ResultCache  # noqa: F401
from spark_rapids_tpu.service.scheduler import QueryService  # noqa: F401
