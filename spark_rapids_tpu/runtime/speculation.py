"""Speculative sizing — deferred validation of data-dependent decisions.

The reference sizes every join's output exactly by syncing the gather-map
row count to the host (GpuHashJoin.scala:104-420 joinGatherer row counts,
JoinGatherer.scala) — on a discrete GPU that sync is microseconds. On a
tunneled TPU every host sync is a ~0.1s round trip (PERF.md), so an exact
sync per operator puts a hard latency floor under multi-operator plans
(the round-2 q3 regression: 10 syncs = 1s).

The TPU-first answer: operators SPECULATE a static output capacity (e.g. a
hash join's output fits the probe side's bucket — true for every
foreign-key join), keep the real row count as a device scalar, and record
a device boolean "speculation failed" flag. Nothing syncs mid-plan; the
flags ride along and are validated by the ONE packed device fetch the
query already pays at collect time (columnar/table.py to_host). If any
flag is set the collect raises SpeculationFailed, the failing sites go on
a process-wide blocklist, and the session replays the query — the replay
takes the exact (sync-per-operator) path at those sites, so results are
always exact. Warm queries therefore run fully async: N dispatched
kernels, one round trip.
"""

from __future__ import annotations

import contextvars
import threading
from typing import List, Optional, Tuple

import jax
from spark_rapids_tpu.lockorder import ordered_lock


class SpeculationFailed(Exception):
    """A speculative capacity/layout guess was wrong; replay exactly."""

    def __init__(self, sites: List[str]):
        super().__init__(f"speculation failed at sites: {sites}")
        self.sites = list(sites)


class SpecContext:
    """Per-query-execution collection of pending speculation flags.

    A flag is a device bool scalar that is True when the speculation it
    guards FAILED. Flags are consumed (embedded into a packed fetch) by
    DeviceTable.to_host; any left over are validated with one extra fetch
    at the end of session.execute."""

    def __init__(self):
        self.pending: List[Tuple[str, jax.Array]] = []

    def add_flag(self, site_key: str, flag) -> None:
        self.pending.append((site_key, flag))

    def take_pending(self) -> List[Tuple[str, jax.Array]]:
        out = self.pending
        self.pending = []
        return out

    def validate_remaining(self) -> None:
        """Fetch + check any flags no packed fetch consumed (one sync)."""
        pending = self.take_pending()
        if not pending:
            return
        import jax.numpy as jnp
        vals = jax.device_get(jnp.stack([f for _, f in pending]))
        check_flag_values([s for s, _ in pending], vals)


def check_flag_values(sites: List[str], values) -> None:
    failed = [s for s, v in zip(sites, values) if bool(v)]
    if not failed:
        return
    sizing = [s for s in failed if not s.startswith("ansi:")]
    if sizing:
        # a sizing miss means downstream data (and any ANSI flags computed
        # from it) is untrustworthy — replay first; the exact replay
        # re-evaluates ANSI flags over correct intermediates
        raise SpeculationFailed(sizing)
    ansi = [s[len("ansi:"):] for s in failed]
    # an ANSI violation is a USER-FACING error, not a sizing miss:
    # raise it directly — replaying could not change the data
    from spark_rapids_tpu.errors import AnsiViolation
    raise AnsiViolation("[ANSI] " + "; ".join(sorted(set(ansi))))


_CTX: contextvars.ContextVar[Optional[SpecContext]] = contextvars.ContextVar(
    "rapids_spec_ctx", default=None)

#: sites whose speculation failed once — they take the exact path forever
#: after (per process), so a repeated query shape never replays twice.
_BLOCKLIST = set()
#: guards _BLOCKLIST writes: failed attempts on CONCURRENT query
#: workers blocklist sites at the same time (membership reads stay
#: lock-free — set containment is atomic under the GIL, and a stale
#: read only costs one extra speculative attempt)
_BLOCKLIST_LOCK = ordered_lock("speculation.blocklist")


def current() -> Optional[SpecContext]:
    return _CTX.get()


def activate() -> "contextvars.Token":
    return _CTX.set(SpecContext())


def deactivate(token) -> None:
    _CTX.reset(token)


def allowed(site_key: str) -> Optional[SpecContext]:
    """The active context, iff speculation is enabled for this site."""
    ctx = _CTX.get()
    if ctx is None or site_key in _BLOCKLIST:
        return None
    return ctx


def blocklist(sites) -> None:
    with _BLOCKLIST_LOCK:
        _BLOCKLIST.update(sites)


def guard_attempt(fn):
    """Run ``fn`` dropping any speculation flags it added if it raises —
    an OOM-aborted attempt's pending flags would otherwise be validated
    (and can spuriously blocklist the site) even though the attempt's
    results were discarded and replayed (ADVICE r3, execs/join.py).

    take_pending() REPLACES the pending list (a mid-attempt collect
    consumes flags), so the snapshot tracks the list identity: if the list
    changed, everything now pending was added by this attempt."""
    ctx = _CTX.get()
    snap_list = ctx.pending if ctx is not None else None
    snap_len = len(snap_list) if snap_list is not None else 0
    try:
        return fn()
    except BaseException:
        if ctx is not None:
            if ctx.pending is snap_list:
                del ctx.pending[snap_len:]
            else:
                ctx.pending.clear()
        raise
