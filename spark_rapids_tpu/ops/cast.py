"""Cast (reference: GpuCast.scala, 1,809 LoC + JNI CastStrings; SURVEY.md
§2.3/§2.9). This round covers the numeric/boolean/temporal core with Java
narrowing semantics; string<->numeric and string<->temporal casts follow the
reference's staged approach (some off by default) and are added as they gain
CPU-exact implementations.

Java narrowing rules implemented:
* int -> smaller int: wrap (low bits);
* float/double -> integral: truncate toward zero, saturate at MIN/MAX,
  NaN -> 0;
* numeric -> boolean: v != 0; boolean -> numeric: 1/0;
* date -> timestamp: midnight UTC micros; timestamp -> date: floor to day.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar import HostColumn, HostTable
from spark_rapids_tpu.ops.common import UnaryExpression
from spark_rapids_tpu.ops.expr import DevVal, Expression, NodePrep

_INT_BOUNDS = {
    np.dtype(np.int8): (-(1 << 7), (1 << 7) - 1),
    np.dtype(np.int16): (-(1 << 15), (1 << 15) - 1),
    np.dtype(np.int32): (-(1 << 31), (1 << 31) - 1),
    np.dtype(np.int64): (-(1 << 63), (1 << 63) - 1),
}

MICROS_PER_DAY = 86_400_000_000


def _cast_data_np(data: np.ndarray, src: T.DataType, dst: T.DataType) -> np.ndarray:
    sd, dd = src.np_dtype, dst.np_dtype
    if isinstance(dst, T.BooleanType):
        return data != 0
    if isinstance(src, T.BooleanType):
        return data.astype(dd)
    if isinstance(src, (T.FloatType, T.DoubleType)) and isinstance(dst, T.IntegralType):
        lo, hi = _INT_BOUNDS[dd]
        with np.errstate(invalid="ignore"):
            t = np.trunc(data)
            t = np.where(np.isnan(data), 0.0, t)
            t = np.clip(t, float(lo), float(hi))
        # float64 cannot represent 2^63-1 exactly; rely on clip + cast with
        # saturation applied before conversion.
        out = np.empty(data.shape, dtype=dd)
        big = t >= float(hi)
        small = t <= float(lo)
        mid = ~(big | small)
        out[big] = hi
        out[small] = lo
        out[mid] = t[mid].astype(dd)
        return out
    if isinstance(src, T.DateType) and isinstance(dst, T.TimestampType):
        return data.astype(np.int64) * MICROS_PER_DAY
    if isinstance(src, T.TimestampType) and isinstance(dst, T.DateType):
        return np.floor_divide(data, MICROS_PER_DAY).astype(np.int32)
    with np.errstate(over="ignore", invalid="ignore"):
        return data.astype(dd)


def _cast_data_jnp(data, src: T.DataType, dst: T.DataType):
    dd = dst.np_dtype
    if isinstance(dst, T.BooleanType):
        return data != 0
    if isinstance(src, T.BooleanType):
        return data.astype(dd)
    if isinstance(src, (T.FloatType, T.DoubleType)) and isinstance(dst, T.IntegralType):
        lo, hi = _INT_BOUNDS[np.dtype(dd)]
        t = jnp.trunc(data)
        t = jnp.where(jnp.isnan(data), 0.0, t)
        t = jnp.clip(t, float(lo), float(hi))
        out = t.astype(dd)
        out = jnp.where(t >= float(hi), hi, out)
        out = jnp.where(t <= float(lo), lo, out)
        return out
    if isinstance(src, T.DateType) and isinstance(dst, T.TimestampType):
        return data.astype(jnp.int64) * MICROS_PER_DAY
    if isinstance(src, T.TimestampType) and isinstance(dst, T.DateType):
        return jnp.floor_divide(data, MICROS_PER_DAY).astype(jnp.int32)
    return data.astype(dd)


_SUPPORTED_SIMPLE = (T.BooleanType, T.ByteType, T.ShortType, T.IntegerType,
                     T.LongType, T.FloatType, T.DoubleType, T.DateType,
                     T.TimestampType)


def cast_supported(src: T.DataType, dst: T.DataType) -> bool:
    if src == dst:
        return True
    if isinstance(src, _SUPPORTED_SIMPLE) and isinstance(dst, _SUPPORTED_SIMPLE):
        # temporal <-> non-temporal numeric casts not yet implemented except
        # the date/timestamp pair handled above.
        temporal = (T.DateType, T.TimestampType)
        s_t, d_t = isinstance(src, temporal), isinstance(dst, temporal)
        if s_t != d_t:
            return False
        return True
    return False


class Cast(UnaryExpression):
    def __init__(self, child: Expression, dtype: T.DataType):
        super().__init__(child)
        self._dtype = dtype

    @property
    def data_type(self):
        return self._dtype

    def with_children(self, children):
        return Cast(children[0], self._dtype)

    def key(self):
        return ("cast", str(self._dtype), self.children[0].key())

    @property
    def device_supported(self):
        return cast_supported(self.child.data_type, self._dtype)

    def eval_cpu(self, table: HostTable) -> HostColumn:
        c = self.child.eval_cpu(table)
        if c.dtype == self._dtype:
            return c
        data = _cast_data_np(c.data, c.dtype, self._dtype)
        zero = np.zeros((), dtype=self._dtype.np_dtype).item()
        return HostColumn(self._dtype, np.where(c.validity, data, zero).astype(self._dtype.np_dtype),
                          c.validity.copy())

    def prep(self, pctx, child_preps):
        return NodePrep()

    def eval_dev(self, ctx, child_vals, prep):
        (c,) = child_vals
        if self.child.data_type == self._dtype:
            return c
        data = _cast_data_jnp(c.data, self.child.data_type, self._dtype)
        return DevVal(jnp.where(c.validity, data, jnp.zeros_like(data)), c.validity)

    def __repr__(self):
        return f"cast({self.children[0]!r} as {self._dtype})"
