"""Concurrency contract analyzer: lock declarations, the static
acquisition-order graph, and held-lock effects.

The runtime's deadlock history (PRs 7/13/14 — see
lockorder.py) all reduced to the same two mistakes: acquiring
locks in an undeclared order, and doing something blocking while a
lock was held.  Three rules make both mechanical:

* **RL-LOCK-DECL** — every ``threading.Lock/RLock/Condition/
  Semaphore`` constructed in the concurrent packages
  (:data:`_LOCK_SCOPE_DIRS`) must go through the
  ``lockorder.py`` ``ordered_*`` factories with a
  string-literal name declared in ``LOCK_ORDER``, constructed at
  exactly the declared site; and every ``LOCK_ORDER`` entry must have
  a live construction site (both directions, like RL-FAULT-POINT).

* **RL-LOCK-ORDER** — an AST + call-graph pass tracks which declared
  locks are held at each ``with``/``.acquire()`` site, follows calls
  to a bounded depth (:data:`_CALL_DEPTH`), and builds the
  held→acquired edge set.  An edge whose acquired rank is <= a held
  rank violates the hierarchy; the full blocking-edge graph is also
  checked for cycles (an allowlisted edge can silence the local
  finding, but a CLOSED cycle is reported regardless — a justified
  exception must still not compose into a deadlock).
  ``acquire(blocking=False)`` try-acquires are exempt: they cannot
  deadlock, and the spill paths rely on exactly that escape.

* **RL-LOCK-EFFECT** — forbidden while any declared lock is held:
  host syncs (the shared ``_host_sync_call`` set), socket
  send/recv/connect/accept, ``subprocess.*``, ``fault_point()``
  raising sites, ``record_incident()``, and ``.wait()`` on a
  Condition other than the one held.  Exceptions go in
  :data:`_LOCK_EFFECT_ALLOWLIST` with a justification (the
  RL-MESH-HOST hook shape).

The pass is deliberately BOUNDED: lock expressions it cannot resolve
to a declaration and calls it cannot resolve to a scanned function are
skipped, never guessed — resolution covers ``self``/``cls``
attributes, module globals, unique class names, module-level
singletons (``MEMORY = MemoryArbiter()``) and globally-unique
attribute/method basenames.  The runtime lock witness
(``spark.rapids.lint.lockWitness``) covers the dynamic remainder.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from spark_rapids_tpu.lint.diagnostics import Diagnostic, make
from spark_rapids_tpu.lint.rules.common import (_attr_chain,
                                                _host_sync_call)

#: directories whose lock constructions fall under the contract
_LOCK_SCOPE_DIRS = ("spark_rapids_tpu/runtime/",
                    "spark_rapids_tpu/service/",
                    "spark_rapids_tpu/parallel/",
                    "spark_rapids_tpu/obs/",
                    "spark_rapids_tpu/io/",
                    "spark_rapids_tpu/columnar/",
                    "spark_rapids_tpu/streaming/")

#: the registry/factory module itself — the one place allowed to touch
#: raw threading primitives (inside the ordered_* factories)
_LOCKORDER_MODULE = "spark_rapids_tpu/lockorder.py"

_FACTORY_KINDS = {"ordered_lock": "Lock", "ordered_rlock": "RLock",
                  "ordered_condition": "Condition",
                  "ordered_semaphore": "Semaphore"}

_RAW_CTORS = ("Lock", "RLock", "Condition", "Semaphore",
              "BoundedSemaphore")

#: call-graph depth followed from a held region (order + effect).
#: Deliberate bound: deeper chains trade precision for noise; the
#: runtime witness covers what the static pass cannot see.
_CALL_DEPTH = 3

#: sanctioned order-edge exceptions: "<rel>:<qualified function>" (the
#: function where the violating acquisition happens) -> justification.
#: The hook for reviewed exceptions — add an entry HERE with a reason,
#: never a bare suppression.  NOTE: a cycle in the blocking-edge graph
#: is reported even when every edge in it is allowlisted.
_LOCK_ORDER_ALLOWLIST: Dict[str, str] = {}

#: sanctioned held-lock effects: "<rel>:<qualified function>" ->
#: justification (same shape as RL-MESH-HOST).
_LOCK_EFFECT_ALLOWLIST: Dict[str, str] = {
    "spark_rapids_tpu/runtime/cluster.py:ClusterDriver.scan_host":
        "the channel lock EXISTS to serialize one wire request/reply "
        "round trip per host socket — send/recv under it IS the "
        "protected operation; the lock is per-host and leaf-ranked "
        "within the cluster band (nothing is acquired under it), so a "
        "wedged executor stalls only its own channel's queue, never "
        "extends a deadlock chain",
    "spark_rapids_tpu/runtime/cluster.py:ClusterDriver.shutdown":
        "the farewell message rides the same serialized-round-trip "
        "channel contract as scan_host; the socket is closed inside "
        "the same hold so no later request can interleave with the "
        "shutdown frame",
    "spark_rapids_tpu/runtime/spill.py:SpillableBatch.get":
        "fault_point('mem.unspill') fires under the batch RLock on "
        "purpose (via _ensure_host_locked): an injected unspill "
        "failure must unwind through the exact locked region the real "
        "TPU restore uses, or the chaos tier would test an unlocked "
        "path production never takes; fault_point itself never blocks "
        "(raise-or-return)",
    "spark_rapids_tpu/runtime/spill.py:SpillableBatch.get_host":
        "same mem.unspill contract as SpillableBatch.get — the "
        "host-side materialization shares _ensure_host_locked",
    "spark_rapids_tpu/runtime/spill.py:"
        "SpillableBatch._spill_to_host_locked":
        "fault_point('mem.spill') under the batch RLock — same "
        "contract as mem.unspill: the injected spill failure must "
        "exercise the locked spill path; raise-or-return, no blocking",
    "spark_rapids_tpu/runtime/spill.py:"
        "SpillableBatch._spill_to_disk_locked":
        "fault_point('mem.spill.disk') under the batch RLock — the "
        "disk demotion variant of the mem.spill contract above",
    "spark_rapids_tpu/service/scheduler.py:QueryService._run":
        "the mesh gate EXISTS to serialize the whole device-launch "
        "window — execute (and the worker_crash fault point on its "
        "path) under it IS the protected operation: two concurrent "
        "multi-device launches interleave their collective rendezvous "
        "per-device and deadlock. The gate is taken holding nothing "
        "and ranks below the service band, so a wedged holder stalls "
        "only the launch queue (booked as queue wait, not hard-wall "
        "time), never extends a deadlock chain",
}

_SOCKET_CALL_SUFFIXES = (".sendall", ".recv", ".recv_into", ".accept",
                         ".connect", ".recvfrom")

#: method names the builtin container/str/bytes/file protocol claims —
#: the unique-basename call-resolution fallback must never fire for
#: these (an ``x.update(...)`` is almost always a dict/set, not the one
#: repo class that defines an ``update`` method)
_BUILTIN_METHOD_NAMES = frozenset(
    n for t in (dict, set, frozenset, list, tuple, str, bytes)
    for n in dir(t) if not n.startswith("_")) | frozenset(
    ("read", "write", "close", "flush", "seek", "tell", "readline",
     "readlines", "writelines", "fileno", "truncate"))


@dataclass(frozen=True)
class _LockRef:
    """A resolved reference to a declared lock."""
    name: str
    rank: int
    kind: str


@dataclass
class _Event:
    """One thing a function body may do that the contract cares
    about.  kind: 'acquire' (lock, blocking) or 'effect' (desc, and
    waited= the Condition for wait effects)."""
    kind: str
    lock: Optional[_LockRef] = None
    blocking: bool = True
    desc: str = ""
    waited: Optional[_LockRef] = None
    line: int = 0


@dataclass
class _Func:
    rel: str
    qual: str
    cls: Optional[str]
    #: every event in the body (closure ingredient)
    events: List[_Event] = field(default_factory=list)
    #: every resolved call in the body: (callee key, line)
    calls: List[Tuple[Tuple[str, str], int]] = field(default_factory=list)
    #: direct events while holding locks IN this function:
    #: (held snapshot, event)
    held_events: List[Tuple[Tuple[_LockRef, ...], _Event]] = \
        field(default_factory=list)
    #: resolved calls while holding locks IN this function:
    #: (held snapshot, callee key, line)
    held_calls: List[Tuple[Tuple[_LockRef, ...], Tuple[str, str], int]] \
        = field(default_factory=list)


def _in_scope(rel: str) -> bool:
    return rel.startswith(_LOCK_SCOPE_DIRS)


def _module_to_rel(dotted: str) -> Optional[str]:
    if dotted and dotted.startswith("spark_rapids_tpu"):
        return dotted.replace(".", "/") + ".py"
    return None


# ---------------------------------------------------------------------------
# RL-LOCK-DECL
# ---------------------------------------------------------------------------


def _check_lock_decl(trees: Dict[str, ast.AST],
                     diags: List[Diagnostic],
                     registry) -> None:
    """Both directions of the declaration audit (the RL-FAULT-POINT
    shape): raw constructions in scope are findings, every factory
    call must name a declared lock at its declared site, and every
    declared lock must be constructed at that site."""
    constructed: Dict[str, List[str]] = {}
    for rel, tree in sorted(trees.items()):
        if rel == _LOCKORDER_MODULE:
            continue
        threading_names: Set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom) \
                    and node.module == "threading":
                threading_names.update(
                    a.asname or a.name for a in node.names
                    if a.name in _RAW_CTORS)

        def visit(node, cls: Optional[str]):
            if isinstance(node, ast.ClassDef):
                cls = node.name
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                # keep the ENCLOSING class for self.attr assigns
                pass
            if isinstance(node, (ast.Assign, ast.AnnAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                value = node.value
                if isinstance(value, ast.Call):
                    fn = _attr_chain(value.func).split(".")[-1]
                    if fn in _FACTORY_KINDS and len(targets) == 1:
                        qual = _target_qual(targets[0], cls)
                        _factory_site(rel, value, qual, constructed,
                                      diags, registry)
                        return  # the call is consumed; don't re-flag
            if isinstance(node, ast.Call):
                chain = _attr_chain(node.func)
                fn = chain.split(".")[-1]
                raw = (chain.startswith("threading.")
                       and chain.split(".", 1)[1] in _RAW_CTORS) \
                    or chain in threading_names
                if raw and _in_scope(rel):
                    diags.append(make(
                        "RL-LOCK-DECL", f"{rel}:{node.lineno}",
                        f"raw {chain}() constructed in a concurrent "
                        "package — declare the lock in "
                        "lockorder.LOCK_ORDER and construct it via "
                        "the ordered_* factories so it carries a rank"))
                    return
                if fn in _FACTORY_KINDS:
                    # a factory call NOT in a simple assignment — the
                    # site cannot match any declared Class.attr/global
                    _factory_site(rel, node, None, constructed,
                                  diags, registry)
                    return
            for child in ast.iter_child_nodes(node):
                visit(child, cls)

        visit(tree, None)
    for name, decl in sorted(registry.items(),
                             key=lambda kv: kv[1].rank):
        if constructed.get(name):
            continue
        if decl.module in trees:
            diags.append(make(
                "RL-LOCK-DECL", f"lockorder.LOCK_ORDER[{name!r}]",
                f"declared lock has no ordered_* construction at its "
                f"site {decl.site} — stale registry entry (rank "
                f"{decl.rank} ordering nothing)"))


def _target_qual(target: ast.AST, cls: Optional[str]) -> Optional[str]:
    """Qualified name a construction is bound to: ``Class.attr`` for
    ``self.attr``/``cls.attr``/class-body assigns, the bare global
    name at module level, None for any other binding shape."""
    if isinstance(target, ast.Name):
        return f"{cls}.{target.id}" if cls else target.id
    if isinstance(target, ast.Attribute) \
            and isinstance(target.value, ast.Name) \
            and target.value.id in ("self", "cls") and cls:
        return f"{cls}.{target.attr}"
    return None


def _factory_site(rel, call, qual, constructed, diags, registry):
    fn = _attr_chain(call.func).split(".")[-1]
    kind = _FACTORY_KINDS[fn]
    arg = call.args[0] if call.args else None
    if not (isinstance(arg, ast.Constant) and isinstance(arg.value, str)):
        diags.append(make(
            "RL-LOCK-DECL", f"{rel}:{call.lineno}",
            f"{fn}() name must be a string literal so the registry "
            "audit can see it"))
        return
    name = arg.value
    decl = registry.get(name)
    if decl is None:
        diags.append(make(
            "RL-LOCK-DECL", f"{rel}:{call.lineno}",
            f"{fn}({name!r}) is not declared in "
            "lockorder.LOCK_ORDER"))
        return
    if decl.kind != kind:
        diags.append(make(
            "RL-LOCK-DECL", f"{rel}:{call.lineno}",
            f"lock {name!r} declared as {decl.kind} but constructed "
            f"via {fn}()"))
        return
    site = f"{rel}:{qual}" if qual else None
    if site != decl.site:
        diags.append(make(
            "RL-LOCK-DECL", f"{rel}:{call.lineno}",
            f"{fn}({name!r}) constructed at "
            f"{site or f'{rel}:<unbound>'} but declared at "
            f"{decl.site} — one lock, one declared construction site"))
        return
    constructed.setdefault(name, []).append(f"{rel}:{call.lineno}")


# ---------------------------------------------------------------------------
# resolution indexes
# ---------------------------------------------------------------------------


class _Indexes:
    """Whole-repo name resolution for locks and calls — each map only
    answers when the answer is UNIQUE; ambiguity means 'unresolved',
    never a guess."""

    def __init__(self, trees: Dict[str, ast.AST], registry):
        self.registry = registry
        #: exact decl site -> LockRef
        self.by_site: Dict[str, _LockRef] = {}
        #: attr basename -> LockRef (globally unique only)
        self.by_attr: Dict[str, Optional[_LockRef]] = {}
        #: (rel, attr basename) -> LockRef (unique in module only)
        self.by_mod_attr: Dict[Tuple[str, str], Optional[_LockRef]] = {}
        for d in registry.values():
            ref = _LockRef(d.name, d.rank, d.kind)
            self.by_site[d.site] = ref
            a = d.attr
            self.by_attr[a] = None if a in self.by_attr else ref
            k = (d.module, a)
            self.by_mod_attr[k] = None if k in self.by_mod_attr else ref

        #: (rel, qualname) -> _Func (every def, methods as Class.name)
        self.funcs: Dict[Tuple[str, str], _Func] = {}
        #: class name -> rel (globally unique only)
        self.classes: Dict[str, Optional[str]] = {}
        #: method basename -> (rel, qual) (globally unique only)
        self.methods: Dict[str, Optional[Tuple[str, str]]] = {}
        #: singleton global name -> (rel, class name) (unique only)
        self.singletons: Dict[str, Optional[Tuple[str, str]]] = {}
        #: per-file from-imports: rel -> {local name: (rel2, name)}
        self.imports: Dict[str, Dict[str, Tuple[str, str]]] = {}
        #: per-file module aliases: rel -> {alias: rel2}
        self.mod_aliases: Dict[str, Dict[str, str]] = {}

        for rel, tree in trees.items():
            self._index_file(rel, tree)

    def _index_file(self, rel: str, tree: ast.AST):
        imports: Dict[str, Tuple[str, str]] = {}
        aliases: Dict[str, str] = {}
        self.imports[rel] = imports
        self.mod_aliases[rel] = aliases
        local_classes: Set[str] = set()

        def note_func(qual: str, cls: Optional[str], node):
            self.funcs[(rel, qual)] = _Func(rel, qual, cls)
            base = qual.rsplit(".", 1)[-1]
            if "." in qual:  # methods/nested only for unique-name map
                self.methods[base] = (None if base in self.methods
                                      else (rel, qual))

        def walk(node, prefix: str, cls: Optional[str]):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    q = f"{prefix}.{child.name}" if prefix else child.name
                    local_classes.add(child.name)
                    self.classes[child.name] = (
                        None if child.name in self.classes else rel)
                    walk(child, q, child.name)
                elif isinstance(child, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                    q = f"{prefix}.{child.name}" if prefix else child.name
                    note_func(q, cls, child)
                    walk(child, q, cls)
                else:
                    walk(child, prefix, cls)

        walk(tree, "", None)

        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom) and node.module:
                rel2 = _module_to_rel(node.module)
                if rel2:
                    for a in node.names:
                        imports[a.asname or a.name] = (rel2, a.name)
            elif isinstance(node, ast.Import):
                for a in node.names:
                    rel2 = _module_to_rel(a.name)
                    if rel2:
                        aliases[a.asname or a.name.split(".")[-1]] = rel2
        # module-level singletons: NAME = ClassName(...)
        for node in tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and isinstance(node.value, ast.Call) \
                    and isinstance(node.value.func, ast.Name):
                cname = node.value.func.id
                if cname in local_classes:
                    n = node.targets[0].id
                    self.singletons[n] = (
                        None if n in self.singletons else (rel, cname))

    # -- lock resolution --------------------------------------------

    def resolve_lock(self, node: ast.AST, rel: str,
                     cls: Optional[str]) -> Optional[_LockRef]:
        chain = _attr_chain(node)
        if not chain:
            return None
        parts = chain.split(".")
        if parts[0] in ("self", "cls") and cls and len(parts) == 2:
            return self.by_site.get(f"{rel}:{cls}.{parts[1]}")
        if len(parts) == 1:
            return self.by_site.get(f"{rel}:{parts[0]}")
        if len(parts) == 2:
            # ClassName.attr
            crel = self.classes.get(parts[0])
            if crel:
                ref = self.by_site.get(f"{crel}:{parts[0]}.{parts[1]}")
                if ref:
                    return ref
            # SINGLETON.attr
            s = self.singletons.get(parts[0])
            if s:
                ref = self.by_site.get(f"{s[0]}:{s[1]}.{parts[1]}")
                if ref:
                    return ref
            # imported global: from mod import _LOCK
            imp = self.imports.get(rel, {}).get(parts[0])
            if imp:
                ref = self.by_site.get(f"{imp[0]}:{imp[1]}.{parts[1]}")
                if ref:
                    return ref
        # unique attribute basename — module first, then global
        ref = self.by_mod_attr.get((rel, parts[-1]))
        if ref:
            return ref
        if (rel, parts[-1]) not in self.by_mod_attr:
            return self.by_attr.get(parts[-1])
        return None

    # -- call resolution --------------------------------------------

    def resolve_call(self, call: ast.Call, rel: str, cls: Optional[str],
                     qual: str) -> Optional[Tuple[str, str]]:
        chain = _attr_chain(call.func)
        if not chain:
            return None
        parts = chain.split(".")
        if parts[0] in ("self", "cls") and cls and len(parts) == 2:
            key = (rel, f"{cls}.{parts[1]}")
            if key in self.funcs:
                return key
            m = self.methods.get(parts[1])
            return m if m and m[0] == rel else None
        if len(parts) == 1:
            name = parts[0]
            # sibling nested function first, then module-level, then
            # a from-import
            prefix = qual.rsplit(".", 1)[0] if "." in qual else None
            if prefix and (rel, f"{prefix}.{name}") in self.funcs:
                return (rel, f"{prefix}.{name}")
            if (rel, name) in self.funcs:
                return (rel, name)
            imp = self.imports.get(rel, {}).get(name)
            if imp and imp in self.funcs:
                return imp
            return None
        if len(parts) == 2:
            head, meth = parts
            crel = self.classes.get(head)
            if crel and (crel, f"{head}.{meth}") in self.funcs:
                return (crel, f"{head}.{meth}")
            s = self.singletons.get(head)
            if s and (s[0], f"{s[1]}.{meth}") in self.funcs:
                return (s[0], f"{s[1]}.{meth}")
            arel = self.mod_aliases.get(rel, {}).get(head)
            if arel and (arel, meth) in self.funcs:
                return (arel, meth)
            imp = self.imports.get(rel, {}).get(head)
            if imp:
                # from pkg import module  /  from mod import SINGLETON
                rel2 = _module_to_rel(
                    imp[0][:-3].replace("/", ".") + "." + imp[1]) \
                    if imp[0].endswith("__init__.py") else None
                if rel2 and (rel2, meth) in self.funcs:
                    return (rel2, meth)
                if (imp[0], f"{imp[1]}.{meth}") in self.funcs:
                    return (imp[0], f"{imp[1]}.{meth}")
                s2 = self.singletons.get(imp[1])
                if s2 and (s2[0], f"{s2[1]}.{meth}") in self.funcs:
                    return (s2[0], f"{s2[1]}.{meth}")
        # unique method basename anywhere — except names shared with
        # the builtin container/str/file protocol, where the receiver
        # is far more likely a dict/set/list/file than the one class
        # that happens to define the method (``_BLOCKLIST.update(...)``
        # must not resolve to some unrelated ``Foo.update``)
        if parts[-1] in _BUILTIN_METHOD_NAMES:
            return None
        return self.methods.get(parts[-1])


# ---------------------------------------------------------------------------
# per-function event extraction
# ---------------------------------------------------------------------------


def _acquire_blocking(call: ast.Call) -> bool:
    """blocking flag of a ``.acquire(...)`` call; non-literal ->
    treated as blocking (conservative)."""
    for kw in call.keywords:
        if kw.arg == "blocking":
            return not (isinstance(kw.value, ast.Constant)
                        and kw.value.value is False)
    if call.args:
        a0 = call.args[0]
        if isinstance(a0, ast.Constant) and a0.value is False:
            return False
    return True


def _effect_of(call: ast.Call, chain: str,
               idx: _Indexes, rel: str,
               cls: Optional[str]) -> Optional[_Event]:
    parts = chain.split(".")
    if _host_sync_call(chain):
        return _Event("effect", desc=f"host sync {chain}()",
                      line=call.lineno)
    if chain.endswith(_SOCKET_CALL_SUFFIXES) \
            or chain == "socket.create_connection":
        return _Event("effect", desc=f"socket {chain}()",
                      line=call.lineno)
    if chain.startswith("subprocess."):
        return _Event("effect", desc=f"{chain}()", line=call.lineno)
    if parts[-1] == "fault_point":
        return _Event("effect", desc="fault_point() raise site",
                      line=call.lineno)
    if parts[-1] == "record_incident":
        return _Event("effect", desc="record_incident() (flight-"
                      "recorder dump walks every snapshot surface)",
                      line=call.lineno)
    if parts[-1] in ("wait", "wait_for") and len(parts) >= 2 \
            and isinstance(call.func, ast.Attribute):
        ref = idx.resolve_lock(call.func.value, rel, cls)
        if ref is not None and ref.kind == "Condition":
            return _Event("effect",
                          desc=f"wait on Condition {ref.name!r}",
                          waited=ref, line=call.lineno)
    return None


def _extract_events(trees: Dict[str, ast.AST], idx: _Indexes) -> None:
    """Fill every _Func with its direct events, calls, and
    held-region snapshots."""
    for rel, tree in sorted(trees.items()):
        if rel == _LOCKORDER_MODULE:
            continue

        def do_func(fnode, key: Tuple[str, str]):
            fn = idx.funcs[key]

            def walk(node, held: Tuple[_LockRef, ...]):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef,
                                     ast.Lambda)):
                    # nested defs run later, not under these locks
                    return
                acquired_here: List[_LockRef] = []
                if isinstance(node, ast.With):
                    for item in node.items:
                        ref = idx.resolve_lock(item.context_expr, rel,
                                               fn.cls)
                        if ref is not None:
                            ev = _Event("acquire", lock=ref,
                                        blocking=True,
                                        line=node.lineno)
                            fn.events.append(ev)
                            if held:
                                fn.held_events.append((held, ev))
                            acquired_here.append(ref)
                elif isinstance(node, ast.Call):
                    chain = _attr_chain(node.func)
                    if chain.split(".")[-1] == "acquire" \
                            and isinstance(node.func, ast.Attribute):
                        ref = idx.resolve_lock(node.func.value, rel,
                                               fn.cls)
                        if ref is not None:
                            ev = _Event("acquire", lock=ref,
                                        blocking=_acquire_blocking(node),
                                        line=node.lineno)
                            fn.events.append(ev)
                            if held:
                                fn.held_events.append((held, ev))
                    else:
                        ev = _effect_of(node, chain, idx, rel, fn.cls)
                        if ev is not None:
                            fn.events.append(ev)
                            if held:
                                fn.held_events.append((held, ev))
                        else:
                            callee = idx.resolve_call(node, rel, fn.cls,
                                                      fn.qual)
                            if callee is not None and callee != key:
                                fn.calls.append((callee, node.lineno))
                                if held:
                                    fn.held_calls.append(
                                        (held, callee, node.lineno))
                if acquired_here:
                    inner = held + tuple(acquired_here)
                    for child in ast.iter_child_nodes(node):
                        walk(child, inner)
                else:
                    for child in ast.iter_child_nodes(node):
                        walk(child, held)

            for child in ast.iter_child_nodes(fnode):
                walk(child, ())

        def find_funcs(node, prefix: str):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    q = f"{prefix}.{child.name}" if prefix else child.name
                    find_funcs(child, q)
                elif isinstance(child, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                    q = f"{prefix}.{child.name}" if prefix else child.name
                    if (rel, q) in idx.funcs:
                        do_func(child, (rel, q))
                    find_funcs(child, q)
                else:
                    find_funcs(child, prefix)

        find_funcs(tree, "")


# ---------------------------------------------------------------------------
# transitive closure + findings
# ---------------------------------------------------------------------------


def _closure(idx: _Indexes, key: Tuple[str, str], depth: int,
             memo: Dict[Tuple[Tuple[str, str], int], List[_Event]],
             stack: Set[Tuple[str, str]]) -> List[_Event]:
    """Every acquire/effect event reachable from ``key`` within
    ``depth`` call hops (cycle-safe, memoized)."""
    mk = (key, depth)
    if mk in memo:
        return memo[mk]
    if key in stack:
        return []
    fn = idx.funcs.get(key)
    if fn is None:
        return []
    out = list(fn.events)
    if depth > 0:
        stack.add(key)
        seen: Set[Tuple[str, str]] = set()
        for callee, _line in fn.calls:
            if callee in seen:
                continue
            seen.add(callee)
            out.extend(_closure(idx, callee, depth - 1, memo, stack))
        stack.discard(key)
    memo[mk] = out
    return out


def check_concurrency(trees: Dict[str, ast.AST],
                      diags: List[Diagnostic],
                      *,
                      registry=None,
                      order_allow: Optional[Dict[str, str]] = None,
                      effect_allow: Optional[Dict[str, str]] = None,
                      call_depth: int = _CALL_DEPTH) -> None:
    """Run all three concurrency rules over the parsed repo.

    ``trees`` maps repo-relative paths to parsed ASTs (the whole
    package in real runs; tests pass synthetic subsets with a custom
    ``registry`` of LockDecls)."""
    if registry is None:
        from spark_rapids_tpu.lockorder import LOCK_ORDER
        registry = LOCK_ORDER
    if order_allow is None:
        order_allow = _LOCK_ORDER_ALLOWLIST
    if effect_allow is None:
        effect_allow = _LOCK_EFFECT_ALLOWLIST

    _check_lock_decl(trees, diags, registry)

    idx = _Indexes(trees, registry)
    _extract_events(trees, idx)

    memo: Dict[Tuple[Tuple[str, str], int], List[_Event]] = {}
    #: blocking held->acquired edges for the cycle pass:
    #: (held name, acquired name) -> first "rel:line via" evidence
    edges: Dict[Tuple[str, str], str] = {}
    seen_findings: Set[Tuple[str, str, str, str]] = set()

    def order_finding(fn: _Func, held: _LockRef, acq: _LockRef,
                      line: int, via: str):
        fkey = f"{fn.rel}:{fn.qual}"
        dedup = ("order", fkey, held.name, acq.name)
        if dedup in seen_findings:
            return
        seen_findings.add(dedup)
        if fkey in order_allow:
            return
        diags.append(make(
            "RL-LOCK-ORDER", f"{fn.rel}:{line}",
            f"blocking acquire of {acq.name!r} (rank {acq.rank}) "
            f"while holding {held.name!r} (rank {held.rank})"
            + (f" via {via}" if via else "")
            + " — acquisition must strictly ascend LOCK_ORDER ranks; "
            "use acquire(blocking=False), reorder, or allowlist "
            f"{fkey} in _LOCK_ORDER_ALLOWLIST with a justification"))

    def effect_finding(fn: _Func, held: _LockRef, ev: _Event,
                       line: int, via: str):
        fkey = f"{fn.rel}:{fn.qual}"
        dedup = ("effect", fkey, held.name, ev.desc)
        if dedup in seen_findings:
            return
        seen_findings.add(dedup)
        if fkey in effect_allow:
            return
        diags.append(make(
            "RL-LOCK-EFFECT", f"{fn.rel}:{line}",
            f"{ev.desc} while holding lock {held.name!r}"
            + (f" via {via}" if via else "")
            + " — blocking work under a lock turns one slow/wedged "
            "operation into a pile-up; move it outside the critical "
            f"section or allowlist {fkey} in _LOCK_EFFECT_ALLOWLIST "
            "with a justification"))

    def consider(fn: _Func, held: Tuple[_LockRef, ...], ev: _Event,
                 line: int, via: str):
        if ev.kind == "acquire":
            for h in held:
                if h.name == ev.lock.name:
                    continue  # reentrant/same-decl: instance ordering
                if ev.blocking:
                    edges.setdefault((h.name, ev.lock.name),
                                     f"{fn.rel}:{line}"
                                     + (f" via {via}" if via else ""))
                    if ev.lock.rank <= h.rank:
                        order_finding(fn, h, ev.lock, line, via)
        else:
            for h in held:
                if ev.waited is not None and ev.waited.name == h.name:
                    continue  # waiting on the condition you hold: fine
                effect_finding(fn, h, ev, line, via)

    for key in sorted(idx.funcs):
        fn = idx.funcs[key]
        for held, ev in fn.held_events:
            consider(fn, held, ev, ev.line, "")
        for held, callee, line in fn.held_calls:
            sub = _closure(idx, callee, call_depth - 1, memo, set())
            via = f"{callee[1]}()"
            for ev in sub:
                consider(fn, held, ev, line, via)

    # cycle pass over ALL blocking edges (allowlisted included): a
    # rank-clean graph cannot cycle, so any cycle here means an
    # allowlisted/violating edge composed into a real deadlock shape
    cyc = _find_cycle(edges)
    if cyc:
        path = " -> ".join(cyc + [cyc[0]])
        evidence = "; ".join(
            f"{a}->{b} at {edges[(a, b)]}"
            for a, b in zip(cyc, cyc[1:] + [cyc[0]])
            if (a, b) in edges)
        diags.append(make(
            "RL-LOCK-ORDER", "lockorder:cycle",
            f"lock acquisition graph contains a cycle: {path} "
            f"({evidence}) — a deadlock is one unlucky interleaving "
            "away; break the cycle, allowlisting cannot suppress it"))


def _find_cycle(edges: Dict[Tuple[str, str], str]) -> List[str]:
    """First cycle in the directed edge set (DFS), [] when acyclic."""
    graph: Dict[str, List[str]] = {}
    for a, b in sorted(edges):
        graph.setdefault(a, []).append(b)
    WHITE, GREY, BLACK = 0, 1, 2
    color: Dict[str, int] = {}
    parent: Dict[str, str] = {}

    def dfs(u: str) -> Optional[List[str]]:
        color[u] = GREY
        for v in graph.get(u, ()):
            c = color.get(v, WHITE)
            if c == GREY:
                cyc = [v]
                w = u
                while w != v:
                    cyc.append(w)
                    w = parent[w]
                cyc.reverse()
                return cyc
            if c == WHITE:
                parent[v] = u
                found = dfs(v)
                if found:
                    return found
        color[u] = BLACK
        return None

    for u in sorted(graph):
        if color.get(u, WHITE) == WHITE:
            found = dfs(u)
            if found:
                return found
    return []
