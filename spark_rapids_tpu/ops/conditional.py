"""Conditional expressions (reference: If CaseWhen Coalesce Least Greatest
NaNvl — conditionalExpressions.scala; SURVEY.md Appendix A).

String results are handled by merging branch dictionaries host-side and
remapping branch codes on device (see ops/common.py)."""

from __future__ import annotations

from typing import Sequence

import numpy as np
import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar import HostColumn, HostTable
from spark_rapids_tpu.ops.common import (
    align_string_dicts_many,
    dev_remap_codes,
)
from spark_rapids_tpu.ops.expr import DevVal, Expression, NodePrep


def _is_string(e: Expression) -> bool:
    return isinstance(e.data_type, T.StringType)


class If(Expression):
    def __init__(self, pred: Expression, if_true: Expression, if_false: Expression):
        self.children = (pred, if_true, if_false)

    @property
    def data_type(self):
        return self.children[1].data_type

    def with_children(self, children):
        return If(*children)

    def eval_cpu(self, table: HostTable) -> HostColumn:
        from spark_rapids_tpu.dispatch import ANSI_MODE
        p = self.children[0].eval_cpu(table)
        take_a = p.validity & p.data.astype(np.bool_)
        if ANSI_MODE.get():
            # Spark evaluates branches lazily: only selected rows may
            # raise — evaluate each branch on its row subset
            a = _eval_branch_cpu(self.children[1], table, take_a,
                                 self.data_type)
            b = _eval_branch_cpu(self.children[2], table, ~take_a,
                                 self.data_type)
        else:
            a = self.children[1].eval_cpu(table)
            b = self.children[2].eval_cpu(table)
        data = np.where(take_a, a.data, b.data)
        validity = np.where(take_a, a.validity, b.validity)
        return HostColumn(self.data_type, data, validity)

    def eval_walk(self, ctx):
        """Custom device walk: branch values evaluate under an ANSI guard
        so unselected rows cannot raise (ops/expr._walk_eval hook)."""
        from spark_rapids_tpu.ops.expr import _walk_eval
        p = _walk_eval(self.children[0], ctx)
        take_a = p.validity & p.data
        if ctx.ansi:
            with ctx.guarded(take_a):
                a = _walk_eval(self.children[1], ctx)
            with ctx.guarded(~take_a):
                b = _walk_eval(self.children[2], ctx)
        else:
            a = _walk_eval(self.children[1], ctx)
            b = _walk_eval(self.children[2], ctx)
        prep = ctx.next_prep()
        return self.eval_dev_branches(ctx, p, a, b, prep, take_a)

    def prep(self, pctx, child_preps):
        if child_preps[1].out_dict is not None:
            return align_string_dicts_many(pctx, child_preps[1:3])
        return NodePrep()

    def eval_dev(self, ctx, child_vals, prep):
        p, a, b = child_vals
        return self.eval_dev_branches(ctx, p, a, b, prep,
                                      p.validity & p.data)

    def eval_dev_branches(self, ctx, p, a, b, prep, take_a):
        ad, bd = a.data, b.data
        if prep.aux_slots:
            ad = dev_remap_codes(ctx, prep.aux_slots[0], ad)
            bd = dev_remap_codes(ctx, prep.aux_slots[1], bd)
        return DevVal(jnp.where(take_a, ad, bd), jnp.where(take_a, a.validity, b.validity))


class CaseWhen(Expression):
    """children = [cond0, val0, cond1, val1, ..., (else)]. An odd child count
    means the last child is the else branch; otherwise else is NULL."""

    def __init__(self, *children: Expression):
        self.children = tuple(children)

    @property
    def has_else(self) -> bool:
        return len(self.children) % 2 == 1

    @property
    def data_type(self):
        return self.children[1].data_type

    def with_children(self, children):
        return CaseWhen(*children)

    def _branches(self):
        n = len(self.children) - (1 if self.has_else else 0)
        return [(self.children[i], self.children[i + 1]) for i in range(0, n, 2)]

    def _value_child_indices(self):
        n = len(self.children) - (1 if self.has_else else 0)
        idx = list(range(1, n, 2))
        if self.has_else:
            idx.append(len(self.children) - 1)
        return idx

    def eval_walk(self, ctx):
        """Device walk with branch guards: each value expression (and the
        else) evaluates only-raising-for rows its predicate selects."""
        from spark_rapids_tpu.ops.expr import _walk_eval
        if not ctx.ansi:
            vals = [_walk_eval(c, ctx) for c in self.children]
            return self.eval_dev(ctx, vals, ctx.next_prep())
        vals = []
        decided = None
        n_branch = len(self.children) - (1 if self.has_else else 0)
        for i in range(0, n_branch, 2):
            c = _walk_eval(self.children[i], ctx)
            vals.append(c)
            take = c.validity & c.data
            if decided is not None:
                take = take & ~decided
            with ctx.guarded(take):
                vals.append(_walk_eval(self.children[i + 1], ctx))
            decided = take if decided is None else (decided | take)
        if self.has_else:
            with ctx.guarded(~decided if decided is not None
                             else jnp.ones(ctx.capacity, jnp.bool_)):
                vals.append(_walk_eval(self.children[-1], ctx))
        return self.eval_dev(ctx, vals, ctx.next_prep())

    def _eval_cpu_ansi(self, table):
        """Lazy-branch CPU evaluation: each value expression runs only on
        the rows its predicate (first-match) selects."""
        n = table.num_rows
        decided = np.zeros(n, dtype=np.bool_)
        dtype = self.data_type
        npdt = np.int32 if False else None
        data = None
        validity = np.zeros(n, dtype=np.bool_)
        for cond, val in self._branches():
            c = cond.eval_cpu(table)
            take = ~decided & c.validity & c.data.astype(np.bool_)
            part = _eval_branch_cpu(val, table, take, dtype)
            if data is None:
                data = part.data.copy()
            else:
                data = np.where(take, part.data, data)
            validity = np.where(take, part.validity, validity)
            decided |= take
        if self.has_else:
            part = _eval_branch_cpu(self.children[-1], table, ~decided,
                                    dtype)
            if data is None:
                data = part.data.copy()
            else:
                data = np.where(~decided, part.data, data)
            validity = np.where(~decided, part.validity, validity)
        return HostColumn(dtype, data, validity)

    def eval_cpu(self, table):
        from spark_rapids_tpu.dispatch import ANSI_MODE
        if ANSI_MODE.get():
            return self._eval_cpu_ansi(table)
        n = table.num_rows
        dtype = self.data_type
        if isinstance(dtype, T.StringType):
            data = np.full(n, "", dtype=object)
        else:
            data = np.zeros(n, dtype=dtype.np_dtype)
        validity = np.zeros(n, dtype=np.bool_)
        decided = np.zeros(n, dtype=np.bool_)
        for cond, val in self._branches():
            c = cond.eval_cpu(table)
            v = val.eval_cpu(table)
            take = ~decided & c.validity & c.data.astype(np.bool_)
            data = np.where(take, v.data, data)
            validity = np.where(take, v.validity, validity)
            decided |= take
        if self.has_else:
            v = self.children[-1].eval_cpu(table)
            data = np.where(~decided, v.data, data)
            validity = np.where(~decided, v.validity, validity)
        return HostColumn(dtype, data, validity)

    def prep(self, pctx, child_preps):
        vidx = self._value_child_indices()
        if child_preps[vidx[0]].out_dict is not None:
            return align_string_dicts_many(pctx, [child_preps[i] for i in vidx])
        return NodePrep()

    def eval_dev(self, ctx, child_vals, prep):
        vidx = self._value_child_indices()
        remapped = {}
        if prep.aux_slots:
            for slot, i in zip(prep.aux_slots, vidx):
                remapped[i] = dev_remap_codes(ctx, slot, child_vals[i].data)
        cap = ctx.capacity
        dtype = self.data_type
        data = jnp.zeros(cap, dtype=jnp.int32 if isinstance(dtype, T.StringType) else dtype.np_dtype)
        validity = jnp.zeros(cap, dtype=jnp.bool_)
        decided = jnp.zeros(cap, dtype=jnp.bool_)
        n_branch = len(self.children) - (1 if self.has_else else 0)
        for i in range(0, n_branch, 2):
            c = child_vals[i]
            v = child_vals[i + 1]
            vd = remapped.get(i + 1, v.data)
            take = ~decided & c.validity & c.data
            data = jnp.where(take, vd, data)
            validity = jnp.where(take, v.validity, validity)
            decided = decided | take
        if self.has_else:
            i = len(self.children) - 1
            v = child_vals[i]
            vd = remapped.get(i, v.data)
            data = jnp.where(decided, data, vd)
            validity = jnp.where(decided, validity, v.validity)
        return DevVal(data, validity)


def _eval_branch_cpu(expr, table, mask, dtype):
    """Evaluate ``expr`` over only the mask-selected rows (ANSI lazy-branch
    semantics), scattering results back to full length."""
    from spark_rapids_tpu.columnar import HostTable as _HT
    idx = np.nonzero(mask)[0]
    sub = _HT(table.names,
              [HostColumn(c.dtype, c.data[idx], c.validity[idx])
               for c in table.columns])
    part = expr.eval_cpu(sub)
    n = table.num_rows
    data = np.zeros(n, dtype=part.data.dtype) \
        if part.data.dtype != object else np.full(n, None, dtype=object)
    validity = np.zeros(n, dtype=np.bool_)
    data[idx] = part.data
    validity[idx] = part.validity
    return HostColumn(part.dtype, data, validity)


class Coalesce(Expression):
    def __init__(self, *children: Expression):
        self.children = tuple(children)

    @property
    def data_type(self):
        return self.children[0].data_type

    def with_children(self, children):
        return Coalesce(*children)

    def eval_cpu(self, table):
        cols = [c.eval_cpu(table) for c in self.children]
        data = cols[0].data.copy()
        validity = cols[0].validity.copy()
        for c in cols[1:]:
            take = ~validity & c.validity
            data = np.where(take, c.data, data)
            validity |= c.validity
        return HostColumn(self.data_type, data, validity)

    def prep(self, pctx, child_preps):
        if child_preps[0].out_dict is not None:
            return align_string_dicts_many(pctx, child_preps)
        return NodePrep()

    def eval_dev(self, ctx, child_vals, prep):
        datas = [v.data for v in child_vals]
        if prep.aux_slots:
            datas = [dev_remap_codes(ctx, s, d) for s, d in zip(prep.aux_slots, datas)]
        data = datas[0]
        validity = child_vals[0].validity
        for v, d in zip(child_vals[1:], datas[1:]):
            take = ~validity & v.validity
            data = jnp.where(take, d, data)
            validity = validity | v.validity
        return DevVal(data, validity)


class _MinMaxN(Expression):
    """Least/Greatest: skip nulls; null only when every input is null."""

    _pick_cpu = None
    _pick_dev = None

    def __init__(self, *children: Expression):
        self.children = tuple(children)

    @property
    def data_type(self):
        return self.children[0].data_type

    def with_children(self, children):
        return type(self)(*children)

    def prep(self, pctx, child_preps):
        if child_preps[0].out_dict is not None:
            return align_string_dicts_many(pctx, child_preps)
        return NodePrep()

    def eval_cpu(self, table):
        cols = [c.eval_cpu(table) for c in self.children]
        string = isinstance(self.data_type, T.StringType)
        data = cols[0].data.copy()
        if string:
            data = np.where(cols[0].validity, data, "")
        validity = cols[0].validity.copy()
        for c in cols[1:]:
            cd = np.where(c.validity, c.data, "") if string else c.data
            better = c.validity & (~validity | type(self)._pick_cpu(cd, data))
            data = np.where(better, cd, data)
            validity |= c.validity
        if string:
            data = data.astype(object)
            out = np.empty(len(data), dtype=object)
            out[:] = data
            out[~validity] = None
            data = out
        return HostColumn(self.data_type, data, validity)

    def eval_dev(self, ctx, child_vals, prep):
        datas = [v.data for v in child_vals]
        if prep.aux_slots:
            datas = [dev_remap_codes(ctx, s, d) for s, d in zip(prep.aux_slots, datas)]
        data = datas[0]
        validity = child_vals[0].validity
        for v, d in zip(child_vals[1:], datas[1:]):
            better = v.validity & (~validity | type(self)._pick_dev(d, data))
            data = jnp.where(better, d, data)
            validity = validity | v.validity
        return DevVal(jnp.where(validity, data, jnp.zeros_like(data)), validity)


class Least(_MinMaxN):
    _pick_cpu = staticmethod(lambda new, cur: new < cur)
    _pick_dev = staticmethod(lambda new, cur: new < cur)


class Greatest(_MinMaxN):
    _pick_cpu = staticmethod(lambda new, cur: new > cur)
    _pick_dev = staticmethod(lambda new, cur: new > cur)


class NaNvl(Expression):
    """NaNvl(a, b): a if a is not NaN else b (types already double/float)."""

    def __init__(self, left: Expression, right: Expression):
        self.children = (left, right)

    @property
    def data_type(self):
        return self.children[0].data_type

    def with_children(self, children):
        return NaNvl(*children)

    def eval_cpu(self, table):
        a = self.children[0].eval_cpu(table)
        b = self.children[1].eval_cpu(table)
        take_b = a.validity & np.isnan(a.data)
        data = np.where(take_b, b.data, a.data)
        validity = np.where(take_b, b.validity, a.validity)
        return HostColumn(self.data_type, data, validity)

    def eval_dev(self, ctx, child_vals, prep):
        a, b = child_vals
        take_b = a.validity & jnp.isnan(a.data)
        return DevVal(jnp.where(take_b, b.data, a.data),
                      jnp.where(take_b, b.validity, a.validity))
