"""Out-of-core sort (spilled-run range merge) + partition-less running
window streaming (reference: GpuSortExec.scala:281 merge of spilled runs;
window/GpuWindowExec.scala GpuRunningWindowExec). VERDICT r3 weak #5/#6:
these paths used to either materialize the whole table or raise
"requires a single batch"."""

import numpy as np
import pytest

from spark_rapids_tpu import functions as F
from spark_rapids_tpu.ops.expr import col, lit
from spark_rapids_tpu.session import TpuSession


def _tpu_ooc():
    # 1-byte threshold: every multi-batch sort goes out of core
    return TpuSession({"spark.rapids.sql.sort.outOfCoreThresholdBytes": "1"})


def _cpu():
    return TpuSession({"spark.rapids.sql.enabled": "false"})


def _data(n=5000, seed=0):
    rng = np.random.default_rng(seed)
    return {"k": rng.integers(-1000, 1000, n).astype(np.int64),
            "v": rng.random(n),
            "s": np.array(["a", "bb", "c"], dtype=object)[
                rng.integers(0, 3, n)]}


# -- out-of-core sort --------------------------------------------------------

@pytest.mark.parametrize("ascending", [True, False])
def test_ooc_sort_matches_in_core_and_oracle(ascending):
    data = _data()
    ooc, cpu = _tpu_ooc(), _cpu()
    q = lambda s: [r[0] for r in
                   s.create_dataframe(data, num_batches=5)
                   .sort("k", ascending=ascending).select(col("k"))
                   .collect()]
    got, want = q(ooc), q(cpu)
    assert got == want
    # the out-of-core path actually ran
    m = ooc.last_metrics()
    assert "sortOutOfCore" in m, m


def test_ooc_sort_multi_key_with_ties():
    rng = np.random.default_rng(1)
    n = 4000
    data = {"k": rng.integers(0, 20, n).astype(np.int64),  # heavy ties
            "u": rng.integers(0, 10**6, n).astype(np.int64)}
    ooc, cpu = _tpu_ooc(), _cpu()
    q = lambda s: (s.create_dataframe(data, num_batches=4)
                   .sort("k", "u").collect())
    assert q(ooc) == q(cpu)


def test_ooc_sort_with_nulls_first_and_last():
    from spark_rapids_tpu import types as T
    from spark_rapids_tpu.plan.nodes import SortOrder
    vals = [5, None, 3, None, 8, 1, None, 2] * 50
    ooc, cpu = _tpu_ooc(), _cpu()
    for nulls_first in (True, False):
        q = lambda s: [r[0] for r in s.create_dataframe(
            {"k": vals}, dtypes={"k": T.LONG}, num_batches=4)
            .sort(SortOrder(col("k"), ascending=True,
                            nulls_first=nulls_first)).collect()]
        assert q(ooc) == q(cpu)


def test_ooc_sort_string_keys():
    data = _data(3000, seed=2)
    ooc, cpu = _tpu_ooc(), _cpu()
    q = lambda s: [r[0] for r in
                   s.create_dataframe(data, num_batches=3)
                   .sort("s", "k").select(col("s")).collect()]
    assert q(ooc) == q(cpu)


def test_ooc_sort_emits_multiple_batches():
    """Peak-HBM bound: the out-of-core stream yields range batches, not
    one concatenated table."""
    from spark_rapids_tpu.execs.sort import sorted_run_stream
    from spark_rapids_tpu.plan.nodes import SortOrder
    from spark_rapids_tpu.columnar import HostTable, HostColumn
    from spark_rapids_tpu import types as T
    rng = np.random.default_rng(3)
    runs = []
    for i in range(3):
        k = np.sort(rng.integers(0, 10**6, 1000)).astype(np.int64)
        runs.append(HostTable(["k"], [HostColumn(T.LONG, k)]))
    out = list(sorted_run_stream(runs, [SortOrder(
        __import__("spark_rapids_tpu.ops.expr", fromlist=["BoundReference"]
                   ).BoundReference(0, T.LONG))], target_rows=1000))
    assert len(out) >= 3
    collected = []
    for dt in out:
        collected.extend(dt.to_host().to_pydict()["k"])
    assert collected == sorted(collected)
    assert len(collected) == 3000


# -- streaming running windows ----------------------------------------------

def _win_q(s, fn_name, num_batches=4):
    from spark_rapids_tpu.functions import (
        dense_rank,
        rank,
        row_number,
    )
    from spark_rapids_tpu.ops.window import Window as W
    data = _data(3000, seed=4)
    spec = W.order_by("k")
    fns = {
        "row_number": row_number(),
        "rank": rank(),
        "dense_rank": dense_rank(),
        "sum": F.sum(col("v")),
        "count": F.count(col("v")),
        "min": F.min(col("v")),
        "max": F.max(col("v")),
        "avg": F.avg(col("v")),
    }
    df = s.create_dataframe(data, num_batches=num_batches)
    return sorted(df.with_windows(w=fns[fn_name].over(spec))
                  .select(col("k"), col("w")).collect())


@pytest.mark.parametrize("fn_name", [
    "row_number", "rank", "dense_rank", "sum", "count", "min", "max",
    "avg"])
def test_streaming_running_window_matches_oracle(fn_name):
    # tiny batch target: the coalesce below the window keeps batches
    # separate, forcing the cross-batch streaming path
    tpu = TpuSession({"spark.rapids.sql.batchSizeBytes": "1"})
    cpu = _cpu()
    got, want = _win_q(tpu, fn_name), _win_q(cpu, fn_name)
    assert len(got) == len(want)
    for g, w in zip(got, want):
        assert g[0] == w[0]
        if isinstance(g[1], float):
            assert abs(g[1] - w[1]) <= 1e-6 * max(1.0, abs(w[1])), (g, w)
        else:
            assert g[1] == w[1], (g, w)


def test_streaming_window_used_not_concat():
    """The running-window streaming path must actually fire."""
    from spark_rapids_tpu.functions import row_number
    from spark_rapids_tpu.ops.window import Window as W
    s = TpuSession({"spark.rapids.sql.batchSizeBytes": "1"})
    df = s.create_dataframe(_data(2000, seed=5), num_batches=3)
    _ = df.with_windows(rn=row_number().over(W.order_by("k"))).collect()
    assert "runningWindowBatches" in s.last_metrics()


def test_non_running_partitionless_window_no_longer_raises():
    """lag over a partition-less multi-batch input takes the concat
    fallback (used to raise 'requires a single batch')."""
    from spark_rapids_tpu.functions import lag
    from spark_rapids_tpu.ops.window import Window as W
    tpu = TpuSession({"spark.rapids.sql.batchSizeBytes": "1"})
    cpu = _cpu()
    data = _data(1500, seed=6)
    q = lambda s: sorted(
        s.create_dataframe(data, num_batches=3)
        .with_windows(p=lag(col("v"), 1).over(W.order_by("k", "v")))
        .select(col("k"), col("p")).collect(), key=repr)
    got, want = q(tpu), q(cpu)
    assert len(got) == len(want)


def test_ooc_sort_with_injected_oom():
    """Out-of-core sort survives injected device OOM (spill + replay)."""
    data = _data(3000, seed=7)
    ooc = TpuSession({
        "spark.rapids.sql.sort.outOfCoreThresholdBytes": "1",
        "spark.rapids.sql.test.injectRetryOOM": "retry:2"})
    cpu = _cpu()
    q = lambda s: [r[0] for r in
                   s.create_dataframe(data, num_batches=3)
                   .sort("k").select(col("k")).collect()]
    assert q(ooc) == q(cpu)


def test_streaming_window_with_injected_oom():
    from spark_rapids_tpu.functions import row_number
    from spark_rapids_tpu.ops.window import Window as W
    tpu = TpuSession({"spark.rapids.sql.batchSizeBytes": "1",
                      "spark.rapids.sql.test.injectRetryOOM": "retry:1"})
    cpu = _cpu()
    data = _data(1200, seed=8)
    q = lambda s: sorted(
        s.create_dataframe(data, num_batches=3)
        .with_windows(rn=row_number().over(W.order_by("k", "v")))
        .select(col("k"), col("rn")).collect())
    assert q(tpu) == q(cpu)
