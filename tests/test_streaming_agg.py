"""Streaming multi-batch aggregation: partial-per-batch + merge
(reference analog: GpuAggregateExec partial/merge modes,
HashAggregateRetrySuite). A tiny batchSizeBytes forces the coalesce to
stream batches so the merge path actually runs."""

import pytest

from spark_rapids_tpu import functions as F
from spark_rapids_tpu.ops.expr import col, lit

from tests.asserts import assert_tpu_and_cpu_are_equal
from tests.data_gen import (
    BooleanGen, DoubleGen, IntGen, LongGen, StringGen, gen_table,
)


@pytest.fixture(scope="module")
def stream_session():
    """Batch target of 1 byte => every input batch streams separately."""
    from spark_rapids_tpu.session import TpuSession
    return TpuSession({"spark.rapids.sql.batchSizeBytes": 1})


def _df(sess, gens, n=900, seed=23, num_batches=4):
    from spark_rapids_tpu.plan import from_host_table
    return from_host_table(gen_table(gens, n, seed), sess, num_batches)


# corner_prob=0: +/-1e30 corner values make f64 sums ORDER-DEPENDENT (a
# small running sum absorbs into 1e30 and is lost when the pair cancels), so
# partial-per-batch order legitimately differs from the oracle's sequential
# order — the exact variance the reference gates with variableFloatAgg.
GENS = {"k": StringGen(cardinality=6), "b": BooleanGen(),
        "i": IntGen(min_val=-100, max_val=100),
        "v": LongGen(min_val=-1000, max_val=1000),
        "d": DoubleGen(corner_prob=0.0)}


def test_streaming_all_aggs(stream_session, cpu_session):
    assert_tpu_and_cpu_are_equal(
        lambda s: _df(s, GENS).group_by("k").agg(
            F.count().alias("cnt"), F.count(col("v")).alias("cntv"),
            F.sum(col("v")).alias("sv"), F.sum(col("d")).alias("sd"),
            F.min(col("d")).alias("mn"), F.max(col("v")).alias("mx"),
            F.first(col("v")).alias("fv"), F.last(col("d")).alias("ld"),
        ),
        stream_session, cpu_session, approximate_float=True)


def test_streaming_order_insensitive_aggs_corner_doubles(
        stream_session, cpu_session):
    """Corner-heavy doubles (inf/1e30/-0.0): count/min/max/first/last are
    order-insensitive and must match bit-for-bit even when streamed."""
    gens = {"k": StringGen(cardinality=5), "d": DoubleGen()}
    assert_tpu_and_cpu_are_equal(
        lambda s: _df(s, gens, num_batches=5).group_by("k").agg(
            F.count(col("d")).alias("c"), F.min(col("d")).alias("mn"),
            F.max(col("d")).alias("mx"), F.first(col("d")).alias("f"),
            F.last(col("d")).alias("l")),
        stream_session, cpu_session)


def test_streaming_avg(stream_session, cpu_session):
    assert_tpu_and_cpu_are_equal(
        lambda s: _df(s, GENS).group_by("k", "b").agg(
            F.avg(col("d")).alias("ad"), F.avg(col("i")).alias("ai")),
        stream_session, cpu_session, approximate_float=True)


def test_streaming_stddev_variance(stream_session, cpu_session):
    assert_tpu_and_cpu_are_equal(
        lambda s: _df(s, GENS).group_by("k").agg(
            F.stddev(col("d")).alias("sd"),
            F.stddev_pop(col("d")).alias("sp"),
            F.variance(col("d")).alias("vr"),
            F.var_pop(col("d")).alias("vp"),
        ),
        stream_session, cpu_session, approximate_float=True)


def test_streaming_global_agg(stream_session, cpu_session):
    assert_tpu_and_cpu_are_equal(
        lambda s: _df(s, GENS).agg(
            F.count().alias("c"), F.sum(col("v")).alias("sv"),
            F.min(col("i")).alias("mn"), F.avg(col("d")).alias("ad")),
        stream_session, cpu_session, approximate_float=True)


def test_streaming_with_fused_filter(stream_session, cpu_session):
    assert_tpu_and_cpu_are_equal(
        lambda s: _df(s, GENS)
        .filter(col("v") > lit(-500))
        .select(col("k"), (col("d") * lit(3.0)).alias("d3"), col("v"))
        .group_by("k")
        .agg(F.sum(col("d3")).alias("s3"), F.count().alias("c")),
        stream_session, cpu_session, approximate_float=True)


def test_streaming_sorted_path_int_keys(stream_session, cpu_session):
    """Int keys take the sort-segment path per batch; merge still works."""
    assert_tpu_and_cpu_are_equal(
        lambda s: _df(s, GENS).group_by("i").agg(
            F.count().alias("c"), F.sum(col("d")).alias("sd"),
            F.max(col("v")).alias("mx")),
        stream_session, cpu_session, approximate_float=True)


def test_streaming_with_injected_oom(cpu_session):
    """Partials replay after injected OOM (HashAggregateRetrySuite analog)."""
    from spark_rapids_tpu.session import TpuSession
    inj = TpuSession({"spark.rapids.sql.batchSizeBytes": 1,
                      "spark.rapids.sql.test.injectRetryOOM": "retry:2"})
    assert_tpu_and_cpu_are_equal(
        lambda s: _df(s, GENS).group_by("k").agg(
            F.count().alias("c"), F.sum(col("v")).alias("sv")),
        inj, cpu_session)


def test_streaming_nulls_in_keys_and_values(stream_session, cpu_session):
    gens = {"k": StringGen(cardinality=4),
            "v": IntGen(min_val=-50, max_val=50, null_prob=0.4),
            "d": DoubleGen(corner_prob=0.0)}
    assert_tpu_and_cpu_are_equal(
        lambda s: _df(s, gens, num_batches=6).group_by("k").agg(
            F.count(col("v")).alias("cv"), F.sum(col("v")).alias("sv"),
            F.avg(col("v")).alias("av"), F.first(col("v")).alias("fv")),
        stream_session, cpu_session, approximate_float=True)


def test_variance_large_mean_stability(stream_session, cpu_session):
    """|mean| >> stddev is the catastrophic case for naive moment merging;
    the MergeMoments Chan combination and exact variance means must hold
    (code-review r2 finding: M + Q - S^2/N cancelled to garbage)."""
    import numpy as np
    from spark_rapids_tpu.plan import from_host_table
    from spark_rapids_tpu.columnar import HostColumn, HostTable
    from spark_rapids_tpu import types as T

    n = 2000
    vals = 1e9 + np.arange(n) * 1e-6
    true_std = float(np.std(vals, ddof=1))
    t = HostTable(["k", "d"],
                  [HostColumn(T.STRING, np.array(["g"] * n, dtype=object)),
                   HostColumn(T.DOUBLE, vals)])
    for nb in (1, 4):
        got = from_host_table(t, stream_session, nb).group_by("k").agg(
            F.stddev(col("d")).alias("sd")).collect()[0][1]
        assert abs(got - true_std) <= 1e-3 * true_std, (nb, got, true_std)
