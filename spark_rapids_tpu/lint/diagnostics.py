"""Structured diagnostics + the rule registry.

Every lint finding is a ``Diagnostic`` carrying a stable rule id, a
location path (a plan path like ``Join.left.Project`` for the verifier, a
``file:line`` for the repo lint, a registry coordinate for the auditor)
and a human message.  Rule ids are registered here so the CLI can list
them and tests can assert the id surface is complete."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List


@dataclass(frozen=True)
class Diagnostic:
    rule_id: str
    path: str
    message: str
    severity: str = "error"

    def __str__(self) -> str:
        return f"[{self.rule_id}] {self.path}: {self.message}"


#: rule id -> one-line description (the CLI's --list-rules output; the
#: lint tests assert every id here has at least one negative test)
RULES: Dict[str, str] = {
    # -- plan verifier ------------------------------------------------------
    "PV-SCHEMA": "node output schema malformed or pass-through schema "
                 "diverges from its child",
    "PV-TRANSITION": "device/host boundary crossed without a "
                     "HostToDevice / DeviceToHost / InputAdapter node",
    "PV-EXCHANGE": "exchange partitioning inconsistent (mode, keys, "
                   "partition count)",
    "PV-BOUNDREF": "bound reference ordinal/type disagrees with the "
                   "child's output schema",
    "PV-TYPESIG": "device exec carries an expression outside its "
                  "declared TypeSig",
    "PV-DECIMAL": "decimal precision/scale invalid or arithmetic result "
                  "type diverges from the Spark promotion rules",
    "PV-NULLABLE": "expression nullability contract violated "
                   "(non-nullable claim over nullable inputs)",
    "PV-FALLBACK": "fallback bookkeeping broken (empty reason, reason "
                   "missing from explain(), or convertible node without "
                   "a rule)",
    "PV-AGG": "aggregate contract violated (spec arity, non-aggregate "
              "spec, unsupported device aggregate)",
    "PV-JOIN": "join contract violated (key arity/type mismatch, "
               "unsupported join type)",
    # -- registry auditor ---------------------------------------------------
    "RA-UNREGISTERED": "ops/* expression has a device kernel but no "
                       "overrides registration (silently CPU)",
    "RA-PARAM-ARITY": "ExprChecks parameter signature count exceeds the "
                      "expression's constructor arity",
    "RA-KILL-SWITCH": "per-op kill-switch conf key matches no registered "
                      "exec rule or expression",
    "RA-SQL-EXPOSURE": "device-supported operator not exposed through "
                       "the SQL function registry",
    "RA-DOC-DRIFT-OPS": "committed SUPPORTED_OPS.md differs from the "
                        "generator output",
    "RA-DOC-DRIFT-CONFIGS": "committed CONFIGS.md differs from the "
                            "generator output",
    "RA-CONF-ORPHAN": "conf key declared in the registry but never "
                      "read by the engine or its harnesses",
    "RA-DOC-DRIFT-LOCKS": "committed LOCKS.md differs from the "
                          "lockorder registry generator output",
    "RA-ESSENTIAL-METRICS": "an executed exec failed to emit the "
                            "ESSENTIAL opTime/numOutputRows/"
                            "numOutputBatches metrics after a "
                            "golden-corpus run (observation boundary "
                            "not installed or bypassed)",
    # -- repo lint ----------------------------------------------------------
    "RL-HOST-SYNC": "host synchronization in an execs/ or ops/ hot path "
                    "outside the sanctioned dispatch helpers",
    "RL-JNP-SCOPE": "jax.numpy imported outside the device layers",
    "RL-CONF-KEY": "conf key referenced via string literal but not "
                   "declared in the conf registry",
    "RL-NONDETERMINISM": "wall-clock or unseeded randomness inside a "
                         "kernel module",
    "RL-DEAD-LAMBDA": "lambda bound to a name that is never used",
    "RL-FAULT-POINT": "fault-point registry and fault_point() call sites "
                      "out of sync (unregistered name, non-literal name, "
                      "registered point with no site, or site outside "
                      "its registered module)",
    "RL-THREAD-SHARED": "module-global or class-level mutable state in "
                        "runtime/, shuffle/ or service/ written outside "
                        "a lock guard (concurrent query workers share "
                        "these modules)",
    "RL-WRITE-COMMIT": "io/ writer opens an output file or promotes a "
                       "path outside the transactional committer (all "
                       "table output must stage through io/committer.py "
                       "so a crash can never leave a torn final file)",
    "RL-MESH-HOST": "host materialization (np.asarray / jax.device_get "
                    "/ host_fetch / .block_until_ready / "
                    ".addressable_shards) inside parallel/ or the "
                    "shard-dispatch placement layer outside a "
                    "sanctioned gather point (device shards must stay "
                    "resident between exchanges)",
    "RL-KERNEL-HOST": "numpy import/materialization or host sync "
                      "(jax.device_get / host_fetch / "
                      ".block_until_ready) inside the Pallas kernel "
                      "layer (kernels/) outside the sanctioned "
                      "allowlist — kernels are pure device code "
                      "traced into other programs",
    "RL-OBS-PASSIVE": "the passive telemetry module (obs/telemetry.py) "
                      "touches the device (jax/jnp/host syncs/"
                      "finalize_observation), drives query execution, "
                      "or takes a query-path lock — sampling must "
                      "never perturb the execution it observes",
    "RL-MEM-ACCOUNT": "raw jax.device_put inside execs//ops/ outside "
                      "the sanctioned allowlist — device landings must "
                      "route through the memory-arbiter-accounted "
                      "DeviceTable.from_host path or the hard device "
                      "budget silently leaks",
    "RL-MV-EPOCH": "streaming/ touches the service result cache "
                   "directly (mutator call, _entries access, or a "
                   "non-epoch import from service/result_cache) — MV "
                   "and stream maintenance must go through the "
                   "invalidation-epoch API (bump_table_epoch/"
                   "epoch listeners) so cache coherence has exactly "
                   "one write path",
    "RL-LOCK-DECL": "threading.Lock/RLock/Condition/Semaphore "
                    "constructed in a concurrent package outside the "
                    "lockorder.py ordered_* factories, a "
                    "factory called with a non-literal/undeclared "
                    "name or at a site other than the declared one, "
                    "or a LOCK_ORDER entry with no construction site "
                    "(the rank hierarchy must cover every lock)",
    "RL-LOCK-ORDER": "a code path blocking-acquires a declared lock "
                     "while holding one of equal or higher rank (or "
                     "the acquisition graph closes a cycle) — "
                     "acquisition must strictly ascend the LOCK_ORDER "
                     "ranks; try-acquires (blocking=False) are exempt",
    "RL-LOCK-EFFECT": "a blocking operation (host sync, socket "
                      "send/recv, subprocess, fault_point raise site, "
                      "record_incident, wait on a different "
                      "Condition) runs while a declared lock is held "
                      "— move the effect outside the critical "
                      "section or allowlist it with a justification",
}


def rule_ids() -> List[str]:
    return sorted(RULES)


def make(rule_id: str, path: str, message: str,
         severity: str = "error") -> Diagnostic:
    if rule_id not in RULES:  # not an assert: must survive python -O
        raise ValueError(f"unknown lint rule id {rule_id}")
    return Diagnostic(rule_id, path, message, severity)
