"""TPC-H-style flagship pipeline (q1: scan -> filter -> project -> group-by
aggregate) — the reference's headline workload shape (pricing summary
report). Used by bench.py and __graft_entry__.py.

Two forms:
* ``q1_dataframe``  — through the full engine (plan -> overrides -> execs);
* ``q1_kernel``     — the same computation as one explicit jittable XLA
  program (filter mask + segment reduction), the distilled hot path."""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar import HostColumn, HostTable


RETURNFLAGS = np.array(["A", "N", "R"], dtype=object)
LINESTATUS = np.array(["F", "O"], dtype=object)
Q1_CUTOFF_DAYS = 10471  # 1998-09-02 as days since epoch


def lineitem_table(num_rows: int, seed: int = 0) -> HostTable:
    """Deterministic lineitem-ish generator (datagen analog)."""
    rng = np.random.default_rng(seed)
    qty = rng.integers(1, 51, size=num_rows).astype(np.float64)
    price = (rng.random(num_rows) * 100000.0).round(2)
    disc = (rng.integers(0, 11, size=num_rows) / 100.0)
    tax = (rng.integers(0, 9, size=num_rows) / 100.0)
    rf = RETURNFLAGS[rng.integers(0, 3, size=num_rows)]
    ls = LINESTATUS[rng.integers(0, 2, size=num_rows)]
    ship = rng.integers(8766, 10957, size=num_rows).astype(np.int32)  # 1994..1999
    cols = {
        "l_quantity": HostColumn(T.DOUBLE, qty),
        "l_extendedprice": HostColumn(T.DOUBLE, price),
        "l_discount": HostColumn(T.DOUBLE, disc),
        "l_tax": HostColumn(T.DOUBLE, tax),
        "l_returnflag": HostColumn(T.STRING, rf),
        "l_linestatus": HostColumn(T.STRING, ls),
        "l_shipdate": HostColumn(T.DATE, ship),
    }
    return HostTable(list(cols.keys()), list(cols.values()))


def q1_dataframe(session, table: HostTable, num_batches: int = 1):
    """TPC-H q1 through the engine (reference:
    integration_tests qa_nightly-style SQL; the scan->filter->agg slice of
    SURVEY.md §7 phase 2)."""
    from spark_rapids_tpu import functions as F
    from spark_rapids_tpu.ops.expr import col, lit
    from spark_rapids_tpu.plan import from_host_table

    df = from_host_table(table, session, num_batches)
    return (
        df.filter(col("l_shipdate") <= lit(Q1_CUTOFF_DAYS, T.DATE))
        .select(
            col("l_returnflag"), col("l_linestatus"), col("l_quantity"),
            col("l_extendedprice"), col("l_discount"),
            (col("l_extendedprice") * (lit(1.0) - col("l_discount"))).alias("disc_price"),
            (col("l_extendedprice") * (lit(1.0) - col("l_discount"))
             * (lit(1.0) + col("l_tax"))).alias("charge"),
        )
        .group_by("l_returnflag", "l_linestatus")
        .agg(
            F.sum(F.col("l_quantity")).alias("sum_qty"),
            F.sum(F.col("l_extendedprice")).alias("sum_base_price"),
            F.sum(F.col("disc_price")).alias("sum_disc_price"),
            F.sum(F.col("charge")).alias("sum_charge"),
            F.avg(F.col("l_quantity")).alias("avg_qty"),
            F.avg(F.col("l_extendedprice")).alias("avg_price"),
            F.avg(F.col("l_discount")).alias("avg_disc"),
            F.count().alias("count_order"),
        )
        .sort("l_returnflag", "l_linestatus")
    )


NUM_Q1_GROUPS = 8  # 3 flags x 2 statuses padded to a static bound


def q1_kernel(qty, price, disc, tax, flag_code, status_code, shipdate, nrows):
    """The distilled q1 device program: one fused XLA computation.

    Group keys ride as small dictionary codes (the engine's string strategy)
    so gid = flag*2 + status is a direct index — segment reductions with a
    static group bound, no sort needed for low-cardinality keys (the engine's
    sort-segment aggregate generalizes to arbitrary keys)."""
    n = qty.shape[0]
    live = jnp.arange(n, dtype=jnp.int32) < nrows
    keep = live & (shipdate <= Q1_CUTOFF_DAYS)
    gid = flag_code * 2 + status_code
    w = keep.astype(jnp.float64)
    disc_price = price * (1.0 - disc)
    charge = disc_price * (1.0 + tax)

    def seg(v):
        return jax.ops.segment_sum(v * w, gid, num_segments=NUM_Q1_GROUPS)

    cnt = jax.ops.segment_sum(keep.astype(jnp.int64), gid, num_segments=NUM_Q1_GROUPS)
    sum_qty = seg(qty)
    sum_price = seg(price)
    sum_disc_price = seg(disc_price)
    sum_charge = seg(charge)
    sum_disc = seg(disc)
    denom = jnp.maximum(cnt, 1).astype(jnp.float64)
    return (sum_qty, sum_price, sum_disc_price, sum_charge,
            sum_qty / denom, sum_price / denom, sum_disc / denom, cnt)


def q1_kernel_example_args(num_rows: int = 1 << 16, seed: int = 0):
    table = lineitem_table(num_rows, seed)
    rf = np.searchsorted(np.sort(RETURNFLAGS.astype(str)), table.column("l_returnflag").data.astype(str))
    ls = np.searchsorted(np.sort(LINESTATUS.astype(str)), table.column("l_linestatus").data.astype(str))
    return (
        jnp.asarray(table.column("l_quantity").data),
        jnp.asarray(table.column("l_extendedprice").data),
        jnp.asarray(table.column("l_discount").data),
        jnp.asarray(table.column("l_tax").data),
        jnp.asarray(rf.astype(np.int32)),
        jnp.asarray(ls.astype(np.int32)),
        jnp.asarray(table.column("l_shipdate").data),
        jnp.asarray(np.int32(num_rows)),
    )


def q1_pandas(table: HostTable):
    """CPU baseline via pandas (the "Spark CPU" proxy for bench.py).
    Built from the raw internal arrays (dates stay int days) so the baseline
    measures compute, not python-object conversion."""
    import pandas as pd
    df = pd.DataFrame({n: c.data for n, c in zip(table.names, table.columns)})
    df = df[df.l_shipdate <= Q1_CUTOFF_DAYS].copy()
    df["disc_price"] = df.l_extendedprice * (1.0 - df.l_discount)
    df["charge"] = df.disc_price * (1.0 + df.l_tax)
    g = df.groupby(["l_returnflag", "l_linestatus"], sort=True)
    out = g.agg(
        sum_qty=("l_quantity", "sum"),
        sum_base_price=("l_extendedprice", "sum"),
        sum_disc_price=("disc_price", "sum"),
        sum_charge=("charge", "sum"),
        avg_qty=("l_quantity", "mean"),
        avg_price=("l_extendedprice", "mean"),
        avg_disc=("l_discount", "mean"),
        count_order=("l_quantity", "size"),
    ).reset_index()
    return out
