"""Lock registry + rank hierarchy + opt-in runtime lock witness.

PRs 7, 13 and 14 each shipped a hand-diagnosed deadlock fix (the
BufferCatalog ``_instance_lock`` self-deadlock, the quarantine-strike
recording self-deadlock on the scheduler's condition, the
SpillableBatch<->arbiter ABBA cycle) — every one found only AFTER the
bug was written, because the ordering contract between the runtime's
~45 locks lived in tribal knowledge and CHANGES.md prose.  This module
makes the contract a machine-checked artifact:

* :data:`LOCK_ORDER` — THE single ordered hierarchy.  Every
  ``threading.Lock/RLock/Condition/Semaphore`` constructed in the
  concurrent packages (``runtime/``, ``service/``, ``parallel/``,
  ``obs/``, ``io/``, ``columnar/``, ``streaming/``) must be declared
  here with a NAME, a RANK and its construction SITE, and must be
  constructed through the :func:`ordered_lock` family so the
  declaration can never drift from the object it describes
  (lint rule RL-LOCK-DECL audits both directions).

* **The ordering contract**: a thread that blocking-acquires lock B
  while holding lock A must have ``rank(A) < rank(B)`` — acquisition
  order strictly ascends the hierarchy.  Non-blocking acquires
  (``acquire(blocking=False)``) are exempt: a try-acquire can never
  deadlock, and the spill/arbiter paths use exactly that escape (the
  PR-14 ABBA fix).  The static half (``lint/concurrency.py``,
  RL-LOCK-ORDER) builds the held->acquired edge graph over a bounded
  call graph; the runtime half is the WITNESS below.

* **Lock witness** (``spark.rapids.lint.lockWitness``, default off):
  when armed, the factories return thin instrumented wrappers that
  record per-thread acquisition sequences and raise typed
  :class:`LockOrderViolation` on any rank inversion — or on a
  blocking re-acquire of a non-reentrant lock this thread already
  holds (the self-deadlock class) — cross-validating the declared
  hierarchy against real executions where the static pass's bounded
  call graph cannot see (dynamic dispatch, callbacks).  Arming is a
  CONSTRUCTION-TIME election: locks built while the witness is armed
  are instrumented, locks built before stay raw — so the disarmed
  production process pays zero overhead on every hot-path acquire.
  The chaos tier arms it, then constructs the service/arbiter objects
  under test.

``LOCKS.md`` is generated from this registry (``python -m
spark_rapids_tpu.lint --write-docs``) and drift-checked by the lint.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from spark_rapids_tpu.conf import bool_conf

LOCK_WITNESS = bool_conf(
    "spark.rapids.lint.lockWitness", False,
    "Arm the runtime lock witness: locks constructed through the "
    "lockorder.py factories while armed are wrapped so every "
    "blocking acquisition is checked against the declared LOCK_ORDER "
    "rank hierarchy, raising typed LockOrderViolation on an inversion "
    "the static RL-LOCK-ORDER pass's bounded call graph missed. "
    "Construction-time election (locks built before arming stay raw); "
    "off by default — enabled under the tier-1 chaos tests.")


class LockOrderViolation(RuntimeError):
    """A thread blocking-acquired a declared lock out of rank order
    (or re-acquired a non-reentrant lock it already holds).  Raised by
    the armed witness INSTEAD of deadlocking; carries the held chain
    so the inversion is diagnosable from the message alone."""


class LockDeclError(RuntimeError):
    """A lock factory was called with an undeclared name, or the
    declared kind does not match the requested primitive."""


@dataclass(frozen=True)
class LockDecl:
    """One declared lock: its place in the single total order.

    ``site`` is ``<repo-relative module>:<qualified attribute>`` — the
    one construction site RL-LOCK-DECL pins the declaration to
    (``Class._attr`` for instance/class locks, the bare global name
    for module-level locks).  ``guards`` documents the state the lock
    protects (LOCKS.md column)."""

    name: str
    rank: int
    site: str
    kind: str  # Lock | RLock | Condition | Semaphore
    guards: str

    @property
    def module(self) -> str:
        return self.site.rsplit(":", 1)[0]

    @property
    def attr(self) -> str:
        """The attribute basename at the construction site."""
        return self.site.rsplit(":", 1)[1].rsplit(".", 1)[-1]


#: THE ordered lock hierarchy.  Ranks ascend from orchestrators (held
#: longest, acquired first) down to leaf bookkeeping locks (held for a
#: dict update, acquired under everything).  Bands of 100 group the
#: layers; gaps leave room to insert without renumbering.  A thread
#: holding rank R may blocking-acquire ranks > R only.
_DECLS: Tuple[LockDecl, ...] = (
    # -- streaming drivers (outermost: they submit queries + commits) --
    LockDecl("streaming.query", 100,
             "spark_rapids_tpu/streaming/query.py:StreamingQuery._lock",
             "Lock", "stream lifecycle: status, trigger thread, last "
                     "batch/offset bookkeeping"),
    LockDecl("streaming.mv.registry", 110,
             "spark_rapids_tpu/streaming/mv.py:"
             "MaterializedViewRegistry._lock",
             "Lock", "registered views + per-table staleness marks"),
    LockDecl("streaming.mv.refresh", 120,
             "spark_rapids_tpu/streaming/mv.py:"
             "MaterializedView._refresh_lock",
             "Lock", "one refresh (incremental or full recompute) at a "
                     "time per view"),
    LockDecl("service.mesh_gate", 150,
             "spark_rapids_tpu/service/scheduler.py:"
             "QueryService._mesh_gate",
             "Lock", "exclusive mesh occupancy: one multi-device "
                     "computation launch at a time when the service "
                     "drives a mesh/cluster topology (two concurrent "
                     "launches interleave their collective rendezvous "
                     "per-device and deadlock); single-chip services "
                     "never construct it. Ranks BELOW the service band "
                     "because it is held across the whole launch "
                     "window, inside which ladder incident capture "
                     "legitimately reads scheduler/handle state"),
    # -- query service -------------------------------------------------
    LockDecl("service.scheduler.cond", 200,
             "spark_rapids_tpu/service/scheduler.py:QueryService._cond",
             "Condition", "queues, WFQ clocks, worker pool, lifecycle "
                          "counters, SLO window, degradation latch — "
                          "ALL scheduler state"),
    LockDecl("service.scheduler.streams", 210,
             "spark_rapids_tpu/service/scheduler.py:"
             "QueryService._streams_lock",
             "Lock", "registered streaming tenants (name -> stream)"),
    LockDecl("service.handle", 220,
             "spark_rapids_tpu/service/query.py:QueryHandle._lock",
             "Lock", "per-handle state machine + result/error slot "
                     "(the watchdog's _cond -> handle order is the "
                     "canonical ranked pair)"),
    LockDecl("service.handle.seq", 230,
             "spark_rapids_tpu/service/query.py:QueryHandle._seq_lock",
             "Lock", "process-wide query id sequence"),
    LockDecl("service.result_cache", 240,
             "spark_rapids_tpu/service/result_cache.py:ResultCache._lock",
             "Lock", "fingerprint -> cached result entries + byte "
                     "accounting"),
    # -- cluster runtime ----------------------------------------------
    LockDecl("cluster.runtime", 300,
             "spark_rapids_tpu/runtime/cluster.py:ClusterRuntime._lock",
             "Lock", "host topology: declared/live/lost/excluded hosts, "
                     "generation"),
    LockDecl("cluster.driver", 310,
             "spark_rapids_tpu/runtime/cluster.py:ClusterDriver._lock",
             "Lock", "executor registry, beat ledger, data channels"),
    LockDecl("cluster.channel", 320,
             "spark_rapids_tpu/runtime/cluster.py:_HostChannel.lock",
             "Lock", "one in-flight wire request per host data channel "
                     "(socket send/recv serialized under it BY DESIGN — "
                     "allowlisted in the effect lint)"),
    # -- health / recovery --------------------------------------------
    LockDecl("health.monitor", 400,
             "spark_rapids_tpu/runtime/health.py:DeviceHealthMonitor._lock",
             "Lock", "loss streaks, reinit/ladder slot reservation, "
                     "backend generation"),
    LockDecl("health.quarantine", 410,
             "spark_rapids_tpu/runtime/health.py:QuarantineRegistry._lock",
             "Lock", "per-template strike history + quarantine set"),
    LockDecl("memory.retry_handler", 420,
             "spark_rapids_tpu/runtime/retry.py:"
             "DeviceMemoryEventHandler._lock",
             "Lock", "OOM-retry state: spill attempt counters per "
                     "allocation failure"),
    # -- device managers ----------------------------------------------
    LockDecl("device.manager.instance", 500,
             "spark_rapids_tpu/runtime/device_manager.py:"
             "TpuDeviceManager._instance_lock",
             "Lock", "singleton construction of the device manager"),
    LockDecl("semaphore.instance", 510,
             "spark_rapids_tpu/runtime/semaphore.py:"
             "TpuSemaphore._instance_lock",
             "Lock", "singleton construction / live resize of the task "
                     "semaphore"),
    LockDecl("semaphore.cond", 520,
             "spark_rapids_tpu/runtime/semaphore.py:TpuSemaphore._lock",
             "Condition", "device concurrency slots: holder map + "
                          "waiter wakeups"),
    LockDecl("mesh.runtime", 530,
             "spark_rapids_tpu/parallel/mesh.py:MeshRuntime._lock",
             "Lock", "mesh topology config, generation, identity token"),
    LockDecl("mesh.dict_intern", 540,
             "spark_rapids_tpu/parallel/exchange.py:_DICT_INTERN_LOCK",
             "Lock", "replicated-dictionary intern table + MeshExchange "
                     "cache (epoch-guarded late-publish rejection)"),
    LockDecl("profiler", 550,
             "spark_rapids_tpu/runtime/profiler.py:TpuProfiler._lock",
             "Lock", "profiler session state + sample buffers"),
    # -- host memory ---------------------------------------------------
    LockDecl("host_alloc.instance", 600,
             "spark_rapids_tpu/runtime/host_alloc.py:"
             "HostMemoryArbiter._instance_lock",
             "Lock", "singleton construction of the host arbiter"),
    LockDecl("host_alloc.cv", 610,
             "spark_rapids_tpu/runtime/host_alloc.py:HostMemoryArbiter._cv",
             "Condition", "host memory budget waits/wakeups"),
    LockDecl("pinned_pool.instance", 620,
             "spark_rapids_tpu/runtime/host_alloc.py:"
             "PinnedMemoryPool._instance_lock",
             "Lock", "singleton construction of the pinned pool"),
    LockDecl("pinned_pool", 630,
             "spark_rapids_tpu/runtime/host_alloc.py:PinnedMemoryPool._lock",
             "Lock", "pinned-buffer freelist"),
    # -- device memory / spill ----------------------------------------
    LockDecl("spill.batch", 710,
             "spark_rapids_tpu/runtime/spill.py:SpillableBatch._lock",
             "RLock", "one batch's tier payloads + pin count.  BELOW "
                      "the catalog and arbiter: get()/spill hold it "
                      "while registering bytes; the reverse direction "
                      "(catalog spill walk -> batch) is non-blocking "
                      "by contract (the PR-14 ABBA fix)"),
    LockDecl("spill.catalog", 720,
             "spark_rapids_tpu/runtime/spill.py:BufferCatalog._lock",
             "RLock", "spillable registry, disk-file tracking, spill "
                      "counters"),
    LockDecl("spill.catalog.instance", 725,
             "spark_rapids_tpu/runtime/spill.py:"
             "BufferCatalog._instance_lock",
             "Lock", "singleton construction/reset of the catalog.  "
                     "ABOVE spill.batch: a batch unspill's device "
                     "landing accounts through the arbiter, whose "
                     "spill pass reaches BufferCatalog.get() with the "
                     "batch RLock still held.  __init__ must NOT "
                     "re-take it (the PR-7 self-deadlock)"),
    LockDecl("spill.catalog.registry", 730,
             "spark_rapids_tpu/runtime/spill.py:"
             "BufferCatalog._all_catalogs_lock",
             "Lock", "weak set of every catalog (atexit sweep)"),
    LockDecl("memory.arbiter", 740,
             "spark_rapids_tpu/runtime/memory.py:MemoryArbiter._lock",
             "Lock", "device budget ledger: reservations, per-table "
                     "bytes, peak.  Never held across a spill pass "
                     "(_spill_for runs outside it)"),
    # -- io ------------------------------------------------------------
    LockDecl("io.committer.jobs", 800,
             "spark_rapids_tpu/io/committer.py:_ACTIVE_LOCK",
             "Lock", "process-wide in-flight WriteJob registry (crash "
                     "sweep reads it)"),
    LockDecl("io.filecache", 810,
             "spark_rapids_tpu/io/filecache.py:_FileCache._lock",
             "Lock", "scan file-cache entries + byte accounting"),
    # -- fault injection / speculation (taken deep inside anything) ----
    LockDecl("faults.registry", 900,
             "spark_rapids_tpu/runtime/faults.py:FaultRegistry._lock",
             "Lock", "armed fault schedule + fire counters (fault_point "
                     "runs under locks across the engine, so this must "
                     "rank ABOVE every subsystem lock — acquired "
                     "last)"),
    LockDecl("faults.recovery", 910,
             "spark_rapids_tpu/runtime/faults.py:RecoveryStats._lock",
             "Lock", "recovery action counters"),
    LockDecl("faults.breaker", 920,
             "spark_rapids_tpu/runtime/faults.py:CircuitBreaker._lock",
             "Lock", "per-op failure counts + demotion reasons"),
    LockDecl("speculation.blocklist", 930,
             "spark_rapids_tpu/runtime/speculation.py:_BLOCKLIST_LOCK",
             "Lock", "process-wide speculation blocklist"),
    # -- observability (leaf: every layer records into these) ----------
    LockDecl("obs.events.writer", 1000,
             "spark_rapids_tpu/obs/events.py:QueryEventWriter._lock",
             "Lock", "event-log file append + record sequence"),
    LockDecl("obs.events.recent", 1010,
             "spark_rapids_tpu/obs/events.py:_RECENT_LOCK",
             "Lock", "bounded recent-record ring (flight-recorder "
                     "summaries)"),
    LockDecl("obs.spans", 1020,
             "spark_rapids_tpu/obs/spans.py:SpanTracer._lock",
             "Lock", "span buffer + lane bookkeeping"),
    LockDecl("obs.telemetry.services", 1030,
             "spark_rapids_tpu/obs/telemetry.py:_SERVICES_LOCK",
             "Lock", "weak registry of live query services"),
    LockDecl("obs.telemetry.ring", 1040,
             "spark_rapids_tpu/obs/telemetry.py:TelemetryRing._lock",
             "Lock", "sampler config + bounded sample ring"),
    LockDecl("obs.flightrec", 1050,
             "spark_rapids_tpu/obs/telemetry.py:_FR_LOCK",
             "Lock", "incident bundle sequence + prune bookkeeping "
                     "(recording reads live surfaces only through "
                     "non-blocking/snapshot APIs)"),
    LockDecl("obs.metrics.spec", 1060,
             "spark_rapids_tpu/obs/metrics.py:_SPEC_LOCK",
             "Lock", "metric spec registry"),
    LockDecl("obs.metrics.scopes", 1070,
             "spark_rapids_tpu/obs/metrics.py:_SCOPE_LOCK",
             "Lock", "scope-name -> LockedMetricSet registry"),
    LockDecl("obs.metrics.scope", 1080,
             "spark_rapids_tpu/obs/metrics.py:LockedMetricSet._lock",
             "Lock", "one metric scope's counters — THE leaf lock: "
                     "metric adds happen under everything above"),
)

#: name -> declaration (THE registry; insertion order == rank order)
LOCK_ORDER: Dict[str, LockDecl] = {d.name: d for d in _DECLS}


def _validate_registry() -> None:
    ranks: Dict[int, str] = {}
    sites: Dict[str, str] = {}
    prev = None
    for d in _DECLS:
        if d.rank in ranks:
            raise LockDeclError(
                f"locks {ranks[d.rank]!r} and {d.name!r} share rank "
                f"{d.rank} — the hierarchy must be a total order")
        if d.site in sites:
            raise LockDeclError(
                f"locks {sites[d.site]!r} and {d.name!r} share site "
                f"{d.site}")
        if prev is not None and d.rank <= prev:
            raise LockDeclError(
                f"LOCK_ORDER entries out of rank order at {d.name!r}")
        ranks[d.rank] = d.name
        sites[d.site] = d.name
        prev = d.rank
    if len(LOCK_ORDER) != len(_DECLS):
        raise LockDeclError("duplicate lock name in LOCK_ORDER")


_validate_registry()


# ---------------------------------------------------------------------------
# runtime witness
# ---------------------------------------------------------------------------

#: construction-time election flag (see module docstring).  Reads are
#: a plain attribute load; writes happen in arm/disarm only.
_WITNESS_ARMED = False

#: process-monotonic count of witness violations DETECTED (each one also
#: raises LockOrderViolation at the acquire site).  Chaos closures record
#: the delta in-band — a committed artifact carries
#: ``lockWitnessViolations: 0`` as evidence, not as a vibe.
_WITNESS_VIOLATIONS = [0]
_WITNESS_VIOLATIONS_LOCK = threading.Lock()

_held_local = threading.local()


def _held() -> List[Tuple[int, LockDecl, bool]]:
    """This thread's live acquisitions: (lock object id, decl,
    underlying-is-reentrant)."""
    stack = getattr(_held_local, "stack", None)
    if stack is None:
        stack = _held_local.stack = []
    return stack


def arm_witness() -> None:
    """Arm the witness for locks constructed FROM NOW ON."""
    global _WITNESS_ARMED
    _WITNESS_ARMED = True


def disarm_witness() -> None:
    global _WITNESS_ARMED
    _WITNESS_ARMED = False


def witness_armed() -> bool:
    return _WITNESS_ARMED


def configure(conf) -> None:
    """Arm/disarm from conf (arm()-cheap; the session and the query
    service both call it before constructing their lock-owning
    objects, so a conf-armed witness covers every per-instance lock
    those builds create)."""
    if bool(conf.get_entry(LOCK_WITNESS)):
        arm_witness()
    else:
        disarm_witness()


def held_snapshot() -> List[str]:
    """Names of the declared locks THIS thread currently holds (test
    and diagnostic surface)."""
    return [d.name for _oid, d, _r in _held()]


def witness_violations() -> int:
    """Process-monotonic count of detected lock-order violations.
    Closures sample it before/after and assert the delta is zero."""
    with _WITNESS_VIOLATIONS_LOCK:
        return _WITNESS_VIOLATIONS[0]


def reset_witness_violations() -> None:
    """Test hook: zero the counter and drop the evidence records. A
    test that PROVOKES violations on purpose must reset afterwards or
    every later in-process closure reads its deliberate inversions as
    real ones."""
    with _WITNESS_VIOLATIONS_LOCK:
        _WITNESS_VIOLATIONS[0] = 0
        _WITNESS_RECORDS.clear()


#: evidence for the counter: the first N violations' (lock, held
#: chain, acquiring call site) — a raised LockOrderViolation often
#: lands in a best-effort except (telemetry, flight recorder) and
#: vanishes, so the count alone is undebuggable
_WITNESS_RECORDS: List[dict] = []
_WITNESS_RECORDS_MAX = 20


def witness_violation_records() -> List[dict]:
    """The recorded evidence behind :func:`witness_violations` (first
    ``_WITNESS_RECORDS_MAX`` only) — what a failing closure dumps."""
    with _WITNESS_VIOLATIONS_LOCK:
        return [dict(r) for r in _WITNESS_RECORDS]


def _count_violation(lock_name: str, chain: str) -> None:
    import traceback
    site = "".join(traceback.format_stack(limit=8)[:-2])
    with _WITNESS_VIOLATIONS_LOCK:
        _WITNESS_VIOLATIONS[0] += 1
        if len(_WITNESS_RECORDS) < _WITNESS_RECORDS_MAX:
            _WITNESS_RECORDS.append(
                {"lock": lock_name, "heldChain": chain, "site": site})


def _check_blocking_acquire(decl: LockDecl, oid: int,
                            reentrant: bool) -> None:
    for hoid, hdecl, hreent in _held():
        if hoid == oid:
            if reentrant:
                continue
            _count_violation(decl.name, decl.name)
            raise LockOrderViolation(
                f"witness: thread re-acquiring non-reentrant lock "
                f"{decl.name!r} (rank {decl.rank}) it already holds — "
                "guaranteed self-deadlock")
        if hdecl.rank >= decl.rank:
            chain = " -> ".join(
                f"{d.name}({d.rank})" for _o, d, _r in _held())
            _count_violation(decl.name, chain)
            raise LockOrderViolation(
                f"witness: blocking acquire of {decl.name!r} (rank "
                f"{decl.rank}) while holding {hdecl.name!r} (rank "
                f"{hdecl.rank}) inverts the declared order; held "
                f"chain: {chain}.  Either acquire in ascending rank, "
                "use acquire(blocking=False), or fix LOCK_ORDER")


def _note_acquired(decl: LockDecl, oid: int, reentrant: bool) -> None:
    _held().append((oid, decl, reentrant))


def _note_released(oid: int) -> None:
    stack = _held()
    for i in range(len(stack) - 1, -1, -1):
        if stack[i][0] == oid:
            del stack[i]
            return


class _WitnessedLock:
    """Rank-checking proxy over one threading primitive.  Only exists
    while the witness is armed at construction; delegates everything
    after bookkeeping, so lock SEMANTICS are unchanged — the witness
    raises instead of deadlocking, never the reverse."""

    _reentrant = False

    def __init__(self, inner, decl: LockDecl):
        self._inner = inner
        self._decl = decl

    def acquire(self, blocking: bool = True, timeout: float = -1):
        oid = id(self)
        if blocking:
            _check_blocking_acquire(self._decl, oid, self._reentrant)
            got = (self._inner.acquire(timeout=timeout)
                   if timeout is not None and timeout >= 0
                   else self._inner.acquire())
        else:
            got = self._inner.acquire(blocking=False)
        if got:
            _note_acquired(self._decl, oid, self._reentrant)
        return got

    def release(self):
        self._inner.release()
        _note_released(id(self))

    def locked(self):
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return (f"<witnessed {self._decl.kind} {self._decl.name!r} "
                f"rank={self._decl.rank}>")


class _WitnessedRLock(_WitnessedLock):
    _reentrant = True


class _WitnessedSemaphore(_WitnessedLock):
    # a semaphore with multiple permits can be "re-acquired" by one
    # thread legitimately; the rank check still applies against OTHER
    # held locks
    _reentrant = True

    def locked(self):  # semaphores have no locked()
        raise AttributeError("locked")


class _WitnessedCondition(_WitnessedLock):
    # threading.Condition's default lock is an RLock
    _reentrant = True

    def wait(self, timeout: Optional[float] = None):
        # wait() RELEASES the condition lock for its duration: the
        # witness must not count it as held, or a wakeup path that
        # correctly re-acquires in rank order would be flagged
        oid = id(self)
        stack = _held()
        depth = sum(1 for e in stack if e[0] == oid)
        for _ in range(depth):
            _note_released(oid)
        try:
            return self._inner.wait(timeout)
        finally:
            for _ in range(depth):
                _note_acquired(self._decl, oid, self._reentrant)

    def wait_for(self, predicate, timeout: Optional[float] = None):
        oid = id(self)
        stack = _held()
        depth = sum(1 for e in stack if e[0] == oid)
        for _ in range(depth):
            _note_released(oid)
        try:
            return self._inner.wait_for(predicate, timeout)
        finally:
            for _ in range(depth):
                _note_acquired(self._decl, oid, self._reentrant)

    def notify(self, n: int = 1):
        return self._inner.notify(n)

    def notify_all(self):
        return self._inner.notify_all()


def _resolve(name: str, kind: str) -> LockDecl:
    decl = LOCK_ORDER.get(name)
    if decl is None:
        raise LockDeclError(
            f"lock {name!r} is not declared in "
            "lockorder.LOCK_ORDER — add a LockDecl with a rank "
            "and the construction site (RL-LOCK-DECL)")
    if decl.kind != kind:
        raise LockDeclError(
            f"lock {name!r} declared as {decl.kind} but constructed as "
            f"{kind}")
    return decl


def ordered_lock(name: str) -> threading.Lock:
    """A declared, rank-ordered ``threading.Lock`` (witnessed when the
    witness is armed at construction time)."""
    decl = _resolve(name, "Lock")
    inner = threading.Lock()
    return _WitnessedLock(inner, decl) if _WITNESS_ARMED else inner


def ordered_rlock(name: str) -> threading.RLock:
    decl = _resolve(name, "RLock")
    inner = threading.RLock()
    return _WitnessedRLock(inner, decl) if _WITNESS_ARMED else inner


def ordered_condition(name: str) -> threading.Condition:
    decl = _resolve(name, "Condition")
    inner = threading.Condition()
    return _WitnessedCondition(inner, decl) if _WITNESS_ARMED else inner


def ordered_semaphore(name: str, value: int = 1) -> threading.Semaphore:
    decl = _resolve(name, "Semaphore")
    inner = threading.Semaphore(value)
    return _WitnessedSemaphore(inner, decl) if _WITNESS_ARMED else inner


# ---------------------------------------------------------------------------
# LOCKS.md generator
# ---------------------------------------------------------------------------


def generate_locks_md() -> str:
    """The committed LOCKS.md: the hierarchy as a reviewable table
    (CONFIGS.md convention — regenerated by ``--write-docs``,
    drift-checked by RA-DOC-DRIFT-LOCKS)."""
    lines = [
        "# Lock order registry",
        "",
        "Generated from `spark_rapids_tpu/lockorder.py` "
        "(`python -m spark_rapids_tpu.lint --write-docs`). "
        "Do not edit by hand.",
        "",
        "The concurrency contract: a thread blocking-acquires locks in "
        "strictly ASCENDING rank only; non-blocking "
        "(`acquire(blocking=False)`) try-acquires are exempt (they "
        "cannot deadlock). `lint/concurrency.py` enforces the contract "
        "statically (RL-LOCK-DECL / RL-LOCK-ORDER / RL-LOCK-EFFECT); "
        "the runtime lock witness (`spark.rapids.lint.lockWitness`) "
        "cross-validates it under the chaos tiers.",
        "",
        "| Rank | Name | Kind | Owning module | Guarded state |",
        "|---:|---|---|---|---|",
    ]
    for d in _DECLS:
        site = d.site.replace("spark_rapids_tpu/", "")
        lines.append(
            f"| {d.rank} | `{d.name}` | {d.kind} | `{site}` | "
            f"{d.guards} |")
    lines.append("")
    return "\n".join(lines)
