"""Pallas kernel layer — native two-limb 64-bit primitives.

PERF.md's measured cost model: dispatches pipeline for free and warm
uploads are zero, so the remaining per-row cost on the budget queries
is 64-bit EMULATION around scatters/gathers/sorts — i64/f64 split into
2-3 32-bit passes plus recombine chains. The HLO workarounds (masked
batches, split-f64 segment sums, segment_minmax_64) each shaved passes;
this layer removes them at the source: each hot primitive handles the
two-limb layout (ops/limbs.py — f64 as (f32, f32), i64 as hi/lo u32)
natively in ONE fused Pallas program:

  * ``sort``      — bitonic multi-column sort over packed key limbs +
                    payload permutation (kernels/sort.py), behind
                    ops/ordering.lex_sort;
  * ``segreduce`` — fused segmented min/max with the hi-limb-native /
                    lo-limb-tiebreak trick, and VMEM-built one-hot
                    split-sum partials (kernels/segreduce.py), behind
                    ops/segsum.py;
  * ``hashprobe`` — bounded-attempt hash-table probe for the join
                    (kernels/hashprobe.py), behind execs/join.py;
  * ``compact``   — one-kernel mask->gather row compaction over every
                    column of a table (kernels/compact.py), behind
                    the filter/join/table compaction sites.

Contract, enforced per primitive:

  * gated by ``spark.rapids.tpu.kernels.<name>.enabled`` ('auto' =
    non-CPU backends; the CPU backend runs Pallas in INTERPRET mode —
    bit-identical, which is how tier-1 pins identity without TPU
    hardware — but slower than XLA:CPU, so auto keeps it off there);
  * the HLO path remains the fallback for every ineligible shape
    (``KernelIneligible``) and is BIT-IDENTICAL by construction —
    pinned by tests/test_kernels.py;
  * a crash (including a Mosaic lowering failure on a backend that
    cannot compile the kernel) demotes that primitive to HLO for the
    ENGINE PROCESS — the PR-3 circuit-breaker pattern — with the
    reason surfaced in explain() and the event log;
  * the enablement set + demotions fold into every trace cache key
    (``trace_token``) and the plan fingerprint (``demotion_token``),
    so cached trees never cross paths;
  * ``pallasKernels`` / ``hloFallbacks`` counters in the ``compile``
    metric scope record which path each primitive resolved to AT
    TRACE TIME (warm dispatches replay the already-traced choice).
"""

from __future__ import annotations

import contextvars
import threading
from typing import Callable, Dict, Optional

from spark_rapids_tpu.conf import (
    KERNELS_COMPACT_ENABLED,
    KERNELS_HASHPROBE_ATTEMPTS,
    KERNELS_HASHPROBE_ENABLED,
    KERNELS_SEGREDUCE_ENABLED,
    KERNELS_SEGREDUCE_MAX_SEGMENTS,
    KERNELS_SORT_ENABLED,
    KERNELS_VMEM_BUDGET,
)

PRIMITIVES = ("sort", "segreduce", "hashprobe", "compact")

_ENABLE_ENTRIES = {
    "sort": KERNELS_SORT_ENABLED,
    "segreduce": KERNELS_SEGREDUCE_ENABLED,
    "hashprobe": KERNELS_HASHPROBE_ENABLED,
    "compact": KERNELS_COMPACT_ENABLED,
}


class KernelsConfig:
    """Resolved per-query kernel configuration (immutable snapshot)."""

    __slots__ = ("enabled", "vmem_budget", "max_segments", "attempts")

    def __init__(self, enabled=frozenset(), vmem_budget=64 << 20,
                 max_segments=8192, attempts=4):
        self.enabled = frozenset(enabled)
        self.vmem_budget = int(vmem_budget)
        self.max_segments = int(max_segments)
        self.attempts = int(attempts)


#: per-query resolved config, set by the placement layer at drain (the
#: MASKED_ENABLED / DIRECT_TABLE_MULT contextvar pattern: execs and ops
#: hold no conf handle). Default: everything off — a kernel must be
#: asked for.
KERNELS_ENABLED = contextvars.ContextVar("rapids_pallas_kernels",
                                         default=KernelsConfig())


def resolve_enabled(conf) -> KernelsConfig:
    """Resolve the spark.rapids.tpu.kernels.* keys for one query.
    'auto' means on for non-CPU backends (where 64-bit emulation is
    the tax) and off on CPU (native 64-bit; Pallas would run in
    interpret mode)."""
    import jax
    on_device = jax.default_backend() != "cpu"
    names = []
    for name, entry in _ENABLE_ENTRIES.items():
        mode = str(conf.get_entry(entry)).strip().lower()
        if mode in ("true", "1", "on"):
            names.append(name)
        elif mode in ("false", "0", "off"):
            pass
        elif on_device:  # auto
            names.append(name)
    return KernelsConfig(
        enabled=names,
        vmem_budget=conf.get_entry(KERNELS_VMEM_BUDGET),
        max_segments=conf.get_entry(KERNELS_SEGREDUCE_MAX_SEGMENTS),
        attempts=conf.get_entry(KERNELS_HASHPROBE_ATTEMPTS))


# -- per-primitive circuit breaker ------------------------------------------

_LOCK = threading.Lock()
#: primitive -> demotion reason, PROCESS-WIDE like the PR-3 circuit
#: breaker: a kernel that crashed (or cannot lower on this backend) is
#: broken for every session sharing the device
_DEMOTED: Dict[str, str] = {}


def demote(name: str, exc: BaseException) -> None:
    """Demote one primitive to the HLO path for the rest of the engine
    process; the reason feeds explain()/event-log demotions."""
    first_line = str(exc).splitlines()[0] if str(exc) else type(exc).__name__
    with _LOCK:
        if name in _DEMOTED:
            return
        reason = (f"pallas kernel '{name}' demoted to HLO: "
                  f"{type(exc).__name__}: {first_line}")
        _DEMOTED[name] = reason
    from spark_rapids_tpu.runtime.faults import RECOVERY
    RECOVERY.bump("demotions")
    # flight-recorder hook (obs/telemetry.py): a kernel demotion is an
    # incident like a ladder action — best-effort, outside _LOCK
    try:
        from spark_rapids_tpu.obs.telemetry import record_incident
        record_incident("kernel.demotion", name, reason, error=exc)
    except Exception:
        pass


def demotion_reason(name: str) -> Optional[str]:
    with _LOCK:
        return _DEMOTED.get(name)


def demoted_ops() -> Dict[str, str]:
    """{'pallas:<name>': reason} — merged into the event record's
    demotions map next to the exec circuit breaker's entries."""
    with _LOCK:
        return {f"pallas:{n}": r for n, r in _DEMOTED.items()}


def reset() -> None:
    """Test support: forget demotions."""
    with _LOCK:
        _DEMOTED.clear()


def demotion_token() -> str:
    """Folds into the plan fingerprint (plan/fingerprint.py) so cached
    executables/results never cross a demotion boundary — the
    MESH.identity_token() pattern for runtime state the conf cannot
    see."""
    with _LOCK:
        return "kdem:" + ",".join(sorted(_DEMOTED))


# -- gating -----------------------------------------------------------------


def config() -> KernelsConfig:
    return KERNELS_ENABLED.get()


def enabled(name: str) -> bool:
    """Is this primitive live for the current query (enabled by conf
    and not demoted)? Read at TRACE time — callers fold trace_token()
    into their jit cache keys so a flipped answer re-traces."""
    if name not in KERNELS_ENABLED.get().enabled:
        return False
    with _LOCK:
        return name not in _DEMOTED


def trace_token() -> tuple:
    """Everything that changes which path a traced kernel embeds: the
    resolved enablement set minus demotions, plus the shape-affecting
    tuning values. Any jit cache key built around a kernels decision
    must include this."""
    cfg = KERNELS_ENABLED.get()
    with _LOCK:
        live = tuple(sorted(n for n in cfg.enabled if n not in _DEMOTED))
    return (live, cfg.vmem_budget, cfg.max_segments, cfg.attempts)


# -- dispatch helpers -------------------------------------------------------


class KernelIneligible(Exception):
    """A kernel module declining one call (shape/size outside its
    envelope) — the caller takes the HLO path for that call, with no
    demotion recorded."""


class _TraceCapture(threading.local):
    """Per-thread stack of 'primitives embedded while tracing this
    program' sets. dispatch.tpu_jit pushes one frame around each
    outermost jitted call: a kernel that traces fine but fails at
    BACKEND COMPILE / first execution (Mosaic lowering happens when the
    enclosing jit first runs, not at trace time) raises outside
    guarded(), and the frame tells tpu_jit which primitives to demote
    before re-raising as a replayable KernelCrashError."""

    def __init__(self):
        self.stack = []


_TRACE_CAPTURE = _TraceCapture()


def begin_trace_capture() -> set:
    frame: set = set()
    _TRACE_CAPTURE.stack.append(frame)
    return frame


def end_trace_capture(frame: set) -> None:
    if _TRACE_CAPTURE.stack and _TRACE_CAPTURE.stack[-1] is frame:
        _TRACE_CAPTURE.stack.pop()
    elif frame in _TRACE_CAPTURE.stack:  # defensive: unwind past it
        while _TRACE_CAPTURE.stack and _TRACE_CAPTURE.stack[-1] is not frame:
            _TRACE_CAPTURE.stack.pop()
        if _TRACE_CAPTURE.stack:
            _TRACE_CAPTURE.stack.pop()


def note_used(name: str) -> None:
    """Record a primitive embedded in the program currently TRACING on
    this thread (no-op outside a capture frame). guarded() calls it on
    success; kernel modules dispatched outside guarded() (the join's
    hashprobe) call it directly."""
    if _TRACE_CAPTURE.stack:
        _TRACE_CAPTURE.stack[-1].add(name)


def count_fallback(name: str, fallback: Callable):
    """Run (and count) the HLO path for a primitive that is disabled
    or ineligible. Counting happens at trace time — see module doc."""
    from spark_rapids_tpu.dispatch import COMPILE_SCOPE
    COMPILE_SCOPE.add("hloFallbacks", 1)
    return fallback()


def guarded(name: str, kernel_fn: Callable, fallback: Callable):
    """Run ``kernel_fn`` with the per-primitive circuit breaker:
    ``KernelIneligible`` falls back silently (counted); any other
    non-OOM failure — an injected ``kernels.<name>`` crash, a Pallas
    abstract-eval/trace failure — DEMOTES the primitive process-wide
    and falls back. Device OOMs re-raise: the retry framework owns
    those. Failures that only surface when the ENCLOSING jit first
    executes (Mosaic lowering / backend compile) are outside this
    wrapper — the trace-capture frames + dispatch.tpu_jit handle
    those."""
    from spark_rapids_tpu.dispatch import COMPILE_SCOPE
    try:
        out = kernel_fn()
    except KernelIneligible:
        COMPILE_SCOPE.add("hloFallbacks", 1)
        return fallback()
    except Exception as exc:
        from spark_rapids_tpu.runtime.crash_handler import (
            is_fatal_device_error,
        )
        from spark_rapids_tpu.runtime.retry import is_device_oom
        if is_device_oom(exc) or is_fatal_device_error(exc):
            # OOMs belong to the retry framework; a dead device/tunnel
            # is the health monitor's to recover — demoting the kernel
            # for either would outlive the recovery (demotions are
            # process-permanent by design, for actual kernel faults)
            raise
        demote(name, exc)
        COMPILE_SCOPE.add("hloFallbacks", 1)
        return fallback()
    COMPILE_SCOPE.add("pallasKernels", 1)
    note_used(name)
    return out


def dispatch(name: str, kernel_fn: Callable, fallback: Callable):
    """THE standard primitive dispatch tail, shared by every router
    site (lex_sort, compact_pairs, the segsum routes): disabled ->
    counted HLO fallback; enabled -> guarded kernel with per-call
    ineligibility fallback and crash demotion."""
    if not enabled(name):
        return count_fallback(name, fallback)
    return guarded(name, kernel_fn, fallback)


def interpret_mode() -> bool:
    """Pallas interpret mode: on for the CPU backend (no Mosaic there;
    interpret is also what makes the bit-identity tests runnable in
    tier-1 without TPU hardware)."""
    import jax
    return jax.default_backend() == "cpu"
