"""UDF compiler: Python lambdas -> engine expressions (reference analog:
udf-compiler/CatalystExpressionBuilder + its opcode suite)."""

import warnings

import numpy as np

import pytest

from spark_rapids_tpu import functions as F
from spark_rapids_tpu import types as T
from spark_rapids_tpu.ops.expr import col
from spark_rapids_tpu.plan import from_host_table

from tests.asserts import assert_runs_on_tpu, assert_tpu_and_cpu_are_equal
from tests.data_gen import DoubleGen, IntGen, StringGen, gen_table


def _df(sess, n=400, seed=3):
    gens = {"x": IntGen(min_val=-100, max_val=100),
            "y": IntGen(min_val=1, max_val=50),
            "d": DoubleGen(corner_prob=0.0),
            "s": StringGen(cardinality=8)}
    return from_host_table(gen_table(gens, n, seed), sess)


def test_arithmetic_udf_compiles_and_runs_on_device(session, cpu_session):
    f = F.udf(lambda x, y: x * 2 + y - 1)
    assert f.compiled
    assert_runs_on_tpu(
        lambda s: _df(s).select("x", f(col("x"), col("y")).alias("u")),
        session)
    assert_tpu_and_cpu_are_equal(
        lambda s: _df(s).select("x", f(col("x"), col("y")).alias("u")),
        session, cpu_session)


def test_udf_matches_rowwise_python(session):
    fn = lambda x, y: (x % y) + abs(x) if x > 0 else y * 3  # noqa: E731
    f = F.udf(fn)
    assert f.compiled
    out = _df(session).select("x", "y", f(col("x"), col("y")).alias("u")) \
        .collect()
    for x, y, u in out:
        # null inputs follow SQL semantics (null condition -> else branch),
        # not Python (which would crash on None) — documented divergence
        if x is not None and y is not None:
            assert u == fn(x, y), (x, y, u)


def test_conditional_and_comparison_chain(session, cpu_session):
    f = F.udf(lambda x: 1 if 0 < x <= 50 else 0)
    assert f.compiled
    assert_tpu_and_cpu_are_equal(
        lambda s: _df(s).select(f(col("x")).alias("u")),
        session, cpu_session)


def test_string_method_udf(session, cpu_session):
    f = F.udf(lambda s: s.upper().strip())
    assert f.compiled
    assert_tpu_and_cpu_are_equal(
        lambda s: _df(s).select(f(col("s")).alias("u")),
        session, cpu_session)


def test_def_function_compiles():
    def my_udf(a, b):
        return (a + b) * 2 - abs(a - b)

    f = F.udf(my_udf)
    assert f.compiled


def test_min_max_rejected_for_null_semantics(session):
    """min()/max() would compile to null-SKIPPING Least/Greatest while the
    row-wise path null-propagates — the compiler must refuse."""
    import warnings
    from spark_rapids_tpu import types as T
    f = F.udf(lambda a, b: min(a, b), return_type=T.LONG)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        expr = f(col("x"), col("y"))
    assert any("row-wise" in str(x.message) for x in w)
    out = _df(session).select("x", "y", expr.alias("u")).collect()
    for x, y, u in out:
        if x is not None and y is not None:
            assert u == min(x, y)


def test_uncompilable_falls_back_with_warning(session):
    def loopy(x):
        t = 0
        for i in range(3):
            t += x
        return t

    f = F.udf(loopy, return_type=T.LONG)
    assert not f.compiled
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        expr = f(col("x"))
    assert any("row-wise" in str(x.message) for x in w)
    out = _df(session).select("x", expr.alias("u")).collect()
    for x, u in out:
        if x is not None:
            assert u == 3 * x


def test_uncompilable_without_return_type_raises():
    from spark_rapids_tpu.udf import UdfCompileError

    def loopy(x):
        t = 0
        for i in range(2):
            t += x
        return t

    f = F.udf(loopy)
    with pytest.raises(UdfCompileError):
        f(col("x"))


def test_closure_falls_back(session):
    k = 7
    f = F.udf(lambda x: x + k, return_type=T.LONG)
    with warnings.catch_warnings(record=True):
        warnings.simplefilter("always")
        expr = f(col("x"))
    out = _df(session).select("x", expr.alias("u")).collect()
    for x, u in out:
        if x is not None:
            assert u == x + 7


# -- columnar device UDF (RapidsUDF analog) ----------------------------------

def test_columnar_device_udf(session, cpu_session):
    import jax.numpy as jnp
    from spark_rapids_tpu.udf import columnar_udf
    from tests.asserts import assert_runs_on_tpu

    def clamped_product(args, valids):
        (x, y), (xv, yv) = args, valids
        return jnp.clip(x * y, -10.0, 10.0), xv & yv

    rng = np.random.default_rng(0)
    data = {"a": rng.standard_normal(500) * 5,
            "b": rng.standard_normal(500) * 5}

    def q(s):
        df = s.create_dataframe(dict(data))
        return df.select(
            columnar_udf(clamped_product, T.DOUBLE, "a", "b").alias("c"))

    got = q(session).collect()
    want = q(cpu_session).collect()
    for g, w in zip(got, want):
        assert abs(g[0] - w[0]) <= 1e-12 * max(1.0, abs(w[0]))
    assert max(abs(g[0]) for g in got) <= 10.0
    assert_runs_on_tpu(q, session)  # fused on device like a built-in


def test_columnar_udf_string_return_rejected(session):
    from spark_rapids_tpu.udf import UdfCompileError, columnar_udf
    with pytest.raises(UdfCompileError, match="fixed-width"):
        columnar_udf(lambda a, v: (a[0], v[0]), T.STRING, "a")


def test_to_device_arrays_export(session):
    """ColumnarRdd analog: results stay on device (jax arrays)."""
    import jax
    import numpy as np
    from spark_rapids_tpu.ops.expr import col

    df = (session.create_dataframe(
        {"x": np.arange(1000, dtype=np.int64),
         "s": np.array([f"v{i%5}" for i in range(1000)], dtype=object)})
        .filter(col("x") >= 500))
    arrays, n = df.to_device_arrays()
    assert n == 500
    assert isinstance(arrays["x"][0], jax.Array)       # no host round trip
    data, validity = arrays["x"]
    assert int(np.asarray(data[:n]).min()) == 500
    codes, v2, dictionary = arrays["s"]                # strings: dict-coded
    assert isinstance(codes, jax.Array) and len(dictionary) == 5


def test_columnar_udf_string_input_rejected(session):
    from spark_rapids_tpu.udf import UdfCompileError, columnar_udf
    df = session.create_dataframe(
        {"s": np.array(["a", "b"], dtype=object)})
    with pytest.raises(UdfCompileError, match="string arguments"):
        df.select(columnar_udf(lambda a, v: (a[0], v[0]),
                               T.DOUBLE, "s").alias("x"))


def test_columnar_udf_key_stable_across_lambda_recreation():
    """Recreated lambdas with identical code share one compile key."""
    from spark_rapids_tpu.udf import columnar_udf

    def make():
        return columnar_udf(lambda a, v: (a[0] + 1.0, v[0]), T.DOUBLE, "x")

    assert make().key() == make().key()


def test_to_device_arrays_sessionless():
    from spark_rapids_tpu.plan import range_df
    arrays, n = range_df(10).to_device_arrays()
    assert n == 10
