"""Math expressions (reference rules: Acos Acosh Asin Asinh Atan Atanh Cbrt
Ceil Cos Cosh Cot Exp Expm1 Floor Hypot Log Log10 Log1p Log2 Logarithm Pow
Rint Round BRound Signum Sin Sinh Sqrt Tan Tanh ToDegrees ToRadians
ShiftLeft ShiftRight ShiftRightUnsigned BitwiseAnd BitwiseOr BitwiseXor
BitwiseNot — mathExpressions.scala / arithmetic.scala; SURVEY.md Appendix A).

Spark-exact corners: log-family returns NULL for non-positive inputs;
ceil/floor of double return LongType (saturating at long bounds like Java);
round is HALF_UP, bround HALF_EVEN; shifts mask the count like Java
(& 31 / & 63)."""

from __future__ import annotations

import math

import numpy as np
import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar import HostColumn, HostTable
from spark_rapids_tpu.ops.common import BinaryExpression, UnaryExpression, coerce_numeric_pair
from spark_rapids_tpu.ops.expr import DevVal, Expression, Literal


class UnaryMath(UnaryExpression):
    """double -> double elementwise math. ``null_when`` makes the result NULL
    on a domain violation (Spark's log family)."""

    np_fn = None
    jnp_fn = None
    null_when = None  # fn(data) -> bool mask of inputs producing NULL

    @property
    def data_type(self):
        return T.DOUBLE

    def resolve(self, bound):
        from spark_rapids_tpu.ops.cast import Cast
        (c,) = bound
        if c.data_type != T.DOUBLE:
            c = Cast(c, T.DOUBLE)
        return type(self)(c)

    def eval_cpu(self, table: HostTable) -> HostColumn:
        c = self.child.eval_cpu(table)
        validity = c.validity.copy()
        with np.errstate(all="ignore"):
            if type(self).null_when is not None:
                validity &= ~type(self).null_when(c.data)
            data = type(self).np_fn(np.where(validity, c.data, 1.0))
        return HostColumn(T.DOUBLE, np.where(validity, data, 0.0), validity)

    def eval_dev(self, ctx, child_vals, prep):
        (c,) = child_vals
        validity = c.validity
        if type(self).null_when is not None:
            validity = validity & ~type(self).null_when(c.data)
        data = type(self).jnp_fn(jnp.where(validity, c.data, 1.0))
        return DevVal(jnp.where(validity, data, 0.0), validity)


def _mk_unary(name, np_fn, jnp_fn, null_when_np=None, null_when_jnp=None):
    cls = type(name, (UnaryMath,), {
        "np_fn": staticmethod(np_fn),
        "jnp_fn": staticmethod(jnp_fn),
    })
    if null_when_np is not None:
        # the mask lambdas below are pure comparisons, valid for both numpy
        # and traced jnp arrays
        cls.null_when = staticmethod(null_when_jnp or null_when_np)
    return cls


Sqrt = _mk_unary("Sqrt", np.sqrt, jnp.sqrt)
Cbrt = _mk_unary("Cbrt", np.cbrt, jnp.cbrt)
Exp = _mk_unary("Exp", np.exp, jnp.exp)
Expm1 = _mk_unary("Expm1", np.expm1, jnp.expm1)
Sin = _mk_unary("Sin", np.sin, jnp.sin)
Cos = _mk_unary("Cos", np.cos, jnp.cos)
Tan = _mk_unary("Tan", np.tan, jnp.tan)
Cot = _mk_unary("Cot", lambda x: 1.0 / np.tan(x), lambda x: 1.0 / jnp.tan(x))
Asin = _mk_unary("Asin", np.arcsin, jnp.arcsin)
Acos = _mk_unary("Acos", np.arccos, jnp.arccos)
Atan = _mk_unary("Atan", np.arctan, jnp.arctan)
Sinh = _mk_unary("Sinh", np.sinh, jnp.sinh)
Cosh = _mk_unary("Cosh", np.cosh, jnp.cosh)
Tanh = _mk_unary("Tanh", np.tanh, jnp.tanh)
Asinh = _mk_unary("Asinh", np.arcsinh, jnp.arcsinh)
Acosh = _mk_unary("Acosh", np.arccosh, jnp.arccosh)
Atanh = _mk_unary("Atanh", np.arctanh, jnp.arctanh)
Rint = _mk_unary("Rint", np.rint, jnp.round)
Signum = _mk_unary("Signum", np.sign, jnp.sign)
ToDegrees = _mk_unary("ToDegrees", np.degrees, lambda x: x * (180.0 / math.pi))
ToRadians = _mk_unary("ToRadians", np.radians, lambda x: x * (math.pi / 180.0))

# Spark's log family returns NULL for non-positive input (non-ANSI).
Log = _mk_unary("Log", np.log, jnp.log, lambda x: x <= 0.0)
Log10 = _mk_unary("Log10", np.log10, jnp.log10, lambda x: x <= 0.0)
Log2 = _mk_unary("Log2", np.log2, jnp.log2, lambda x: x <= 0.0)
Log1p = _mk_unary("Log1p", np.log1p, jnp.log1p, lambda x: x <= -1.0)


_LONG_MIN, _LONG_MAX = -(1 << 63), (1 << 63) - 1


class _CeilFloorBase(UnaryExpression):
    """ceil/floor of double -> LongType with Java-style saturation."""

    _np_fn = None
    _jnp_fn = None

    @property
    def data_type(self):
        if isinstance(self.child.data_type, (T.FloatType, T.DoubleType)):
            return T.LONG
        return self.child.data_type

    def resolve(self, bound):
        (c,) = bound
        if isinstance(c.data_type, T.IntegralType):
            return c  # no-op on integers (Spark keeps the value)
        from spark_rapids_tpu.ops.cast import Cast
        if c.data_type == T.FLOAT:
            c = Cast(c, T.DOUBLE)
        return type(self)(c)

    def eval_cpu(self, table):
        c = self.child.eval_cpu(table)
        with np.errstate(invalid="ignore"):
            r = type(self)._np_fn(c.data)
            r = np.where(np.isnan(c.data), 0.0, r)
            r = np.clip(r, float(_LONG_MIN), float(_LONG_MAX))
        out = np.empty(len(c), dtype=np.int64)
        big = r >= float(_LONG_MAX)
        small = r <= float(_LONG_MIN)
        mid = ~(big | small)
        out[big] = _LONG_MAX
        out[small] = _LONG_MIN
        out[mid] = r[mid].astype(np.int64)
        return HostColumn(T.LONG, np.where(c.validity, out, 0), c.validity.copy())

    def eval_dev(self, ctx, child_vals, prep):
        (c,) = child_vals
        r = type(self)._jnp_fn(c.data)
        r = jnp.where(jnp.isnan(c.data), 0.0, r)
        r = jnp.clip(r, float(_LONG_MIN), float(_LONG_MAX))
        out = r.astype(jnp.int64)
        out = jnp.where(r >= float(_LONG_MAX), _LONG_MAX, out)
        out = jnp.where(r <= float(_LONG_MIN), _LONG_MIN, out)
        return DevVal(jnp.where(c.validity, out, 0), c.validity)


class Ceil(_CeilFloorBase):
    _np_fn = staticmethod(np.ceil)
    _jnp_fn = staticmethod(jnp.ceil)


class Floor(_CeilFloorBase):
    _np_fn = staticmethod(np.floor)
    _jnp_fn = staticmethod(jnp.floor)


class _RoundBase(Expression):
    """Round(child, scale): HALF_UP (Round) / HALF_EVEN (BRound) at decimal
    scale d. Scale must be a literal (same restriction as the reference)."""

    half_even = False

    def __init__(self, child: Expression, scale: Expression = None):
        scale = scale if scale is not None else Literal.of(0)
        self.children = (child, scale)

    @property
    def data_type(self):
        return self.children[0].data_type

    def with_children(self, children):
        return type(self)(children[0], children[1])

    def key(self):
        s = self.children[1]
        sv = s.value if isinstance(s, Literal) else None
        return (self.name, sv, self.children[0].key())

    def _scale(self) -> int:
        s = self.children[1]
        if not isinstance(s, Literal):
            raise ValueError("round scale must be a literal")
        return int(s.value)

    def eval_cpu(self, table):
        c = self.children[0].eval_cpu(table)
        d = self._scale()
        factor = 10.0 ** d
        with np.errstate(all="ignore"):
            x = c.data * factor
            if self.half_even:
                r = np.rint(x)
            else:
                r = np.where(x >= 0, np.floor(x + 0.5), np.ceil(x - 0.5))
            data = r / factor
        if isinstance(c.dtype, T.IntegralType):
            data = data.astype(c.dtype.np_dtype)
        data = np.where(c.validity, data, np.zeros((), dtype=data.dtype))
        return HostColumn(self.data_type, data.astype(c.data.dtype), c.validity.copy())

    def eval_dev(self, ctx, child_vals, prep):
        c = child_vals[0]
        d = self._scale()
        factor = 10.0 ** d
        x = c.data * factor
        if self.half_even:
            r = jnp.round(x)
        else:
            r = jnp.where(x >= 0, jnp.floor(x + 0.5), jnp.ceil(x - 0.5))
        data = (r / factor).astype(c.data.dtype)
        return DevVal(jnp.where(c.validity, data, jnp.zeros_like(data)), c.validity)


class Round(_RoundBase):
    half_even = False


class BRound(_RoundBase):
    half_even = True


class Pow(BinaryExpression):
    @property
    def data_type(self):
        return T.DOUBLE

    def resolve(self, bound):
        from spark_rapids_tpu.ops.cast import Cast
        l, r = bound
        if l.data_type != T.DOUBLE:
            l = Cast(l, T.DOUBLE)
        if r.data_type != T.DOUBLE:
            r = Cast(r, T.DOUBLE)
        return Pow(l, r)

    def eval_cpu(self, table):
        l = self.left.eval_cpu(table)
        r = self.right.eval_cpu(table)
        validity = l.validity & r.validity
        with np.errstate(all="ignore"):
            data = np.power(np.where(validity, l.data, 1.0), np.where(validity, r.data, 1.0))
        return HostColumn(T.DOUBLE, np.where(validity, data, 0.0), validity)

    def eval_dev(self, ctx, child_vals, prep):
        l, r = child_vals
        validity = l.validity & r.validity
        data = jnp.power(jnp.where(validity, l.data, 1.0), jnp.where(validity, r.data, 1.0))
        return DevVal(jnp.where(validity, data, 0.0), validity)


class Hypot(BinaryExpression):
    @property
    def data_type(self):
        return T.DOUBLE

    def resolve(self, bound):
        from spark_rapids_tpu.ops.cast import Cast
        l, r = bound
        if l.data_type != T.DOUBLE:
            l = Cast(l, T.DOUBLE)
        if r.data_type != T.DOUBLE:
            r = Cast(r, T.DOUBLE)
        return Hypot(l, r)

    def eval_cpu(self, table):
        l = self.left.eval_cpu(table)
        r = self.right.eval_cpu(table)
        validity = l.validity & r.validity
        with np.errstate(all="ignore"):
            data = np.hypot(l.data, r.data)
        return HostColumn(T.DOUBLE, np.where(validity, data, 0.0), validity)

    def eval_dev(self, ctx, child_vals, prep):
        l, r = child_vals
        validity = l.validity & r.validity
        data = jnp.hypot(l.data, r.data)
        return DevVal(jnp.where(validity, data, 0.0), validity)


class Logarithm(BinaryExpression):
    """log(base, x): NULL when x <= 0 or base <= 0."""

    @property
    def data_type(self):
        return T.DOUBLE

    def resolve(self, bound):
        from spark_rapids_tpu.ops.cast import Cast
        l, r = bound
        if l.data_type != T.DOUBLE:
            l = Cast(l, T.DOUBLE)
        if r.data_type != T.DOUBLE:
            r = Cast(r, T.DOUBLE)
        return Logarithm(l, r)

    def eval_cpu(self, table):
        base = self.left.eval_cpu(table)
        x = self.right.eval_cpu(table)
        validity = base.validity & x.validity & (x.data > 0) & (base.data > 0)
        with np.errstate(all="ignore"):
            data = np.log(np.where(validity, x.data, 1.0)) / np.log(np.where(validity, base.data, 2.0))
        return HostColumn(T.DOUBLE, np.where(validity, data, 0.0), validity)

    def eval_dev(self, ctx, child_vals, prep):
        base, x = child_vals
        validity = base.validity & x.validity & (x.data > 0) & (base.data > 0)
        data = jnp.log(jnp.where(validity, x.data, 1.0)) / jnp.log(jnp.where(validity, base.data, 2.0))
        return DevVal(jnp.where(validity, data, 0.0), validity)


# ---------------------------------------------------------------------------
# Bitwise / shifts
# ---------------------------------------------------------------------------

class _BitwiseBinary(BinaryExpression):
    _np_op = None

    @property
    def data_type(self):
        return self.left.data_type

    def resolve(self, bound):
        left, right, _ = coerce_numeric_pair(*bound)
        return type(self)(left, right)

    def eval_cpu(self, table):
        l = self.left.eval_cpu(table)
        r = self.right.eval_cpu(table)
        validity = l.validity & r.validity
        data = type(self)._np_op(l.data, r.data)
        return HostColumn(self.data_type, np.where(validity, data, 0).astype(l.data.dtype), validity)

    def eval_dev(self, ctx, child_vals, prep):
        l, r = child_vals
        validity = l.validity & r.validity
        data = type(self)._np_op(l.data, r.data)
        return DevVal(jnp.where(validity, data, 0), validity)


class BitwiseAnd(_BitwiseBinary):
    _np_op = staticmethod(lambda a, b: a & b)


class BitwiseOr(_BitwiseBinary):
    _np_op = staticmethod(lambda a, b: a | b)


class BitwiseXor(_BitwiseBinary):
    _np_op = staticmethod(lambda a, b: a ^ b)


class BitwiseNot(UnaryExpression):
    @property
    def data_type(self):
        return self.child.data_type

    def eval_cpu(self, table):
        c = self.child.eval_cpu(table)
        return HostColumn(self.data_type, np.where(c.validity, ~c.data, 0).astype(c.data.dtype),
                          c.validity.copy())

    def eval_dev(self, ctx, child_vals, prep):
        (c,) = child_vals
        return DevVal(jnp.where(c.validity, ~c.data, 0), c.validity)


class _ShiftBase(BinaryExpression):
    """Java shift semantics: count is masked (&31 for int, &63 for long)."""

    @property
    def data_type(self):
        return self.left.data_type

    def _mask(self):
        return 63 if self.left.data_type == T.LONG else 31

    def _shift_np(self, a, cnt):
        raise NotImplementedError

    def eval_cpu(self, table):
        l = self.left.eval_cpu(table)
        r = self.right.eval_cpu(table)
        validity = l.validity & r.validity
        cnt = (r.data & self._mask()).astype(np.int64)
        with np.errstate(over="ignore"):
            data = self._shift_np(l.data, cnt, np)
        return HostColumn(self.data_type, np.where(validity, data, 0).astype(l.data.dtype), validity)

    def eval_dev(self, ctx, child_vals, prep):
        l, r = child_vals
        validity = l.validity & r.validity
        cnt = (r.data & self._mask()).astype(l.data.dtype)
        data = self._shift_np(l.data, cnt, jnp)
        return DevVal(jnp.where(validity, data, 0), validity)


class ShiftLeft(_ShiftBase):
    def _shift_np(self, a, cnt, xp):
        return xp.left_shift(a, cnt.astype(a.dtype))


class ShiftRight(_ShiftBase):
    def _shift_np(self, a, cnt, xp):
        return xp.right_shift(a, cnt.astype(a.dtype))


class ShiftRightUnsigned(_ShiftBase):
    def _shift_np(self, a, cnt, xp):
        unsigned = a.astype(np.uint64 if a.dtype == np.int64 else np.uint32) \
            if xp is np else a.astype(jnp.uint64 if a.dtype == jnp.int64 else jnp.uint32)
        shifted = xp.right_shift(unsigned, cnt.astype(unsigned.dtype))
        return shifted.astype(a.dtype)


class _RoundDirBase(_RoundBase):
    """ceil/floor at decimal scale (shim rules RoundCeil/RoundFloor).

    Integral inputs with scale <= 0 are EXACT Spark operations (ceil/floor
    to a power of ten): computed in integer arithmetic — the float64 path
    would perturb LONG values above 2^53 (ADVICE r2, ops/math.py)."""

    _np_fn = None
    _jnp_fn = None

    #: +1 for ceil (round quotient up on remainder), 0 for floor
    _adjust_up = 0

    def _int_exact_applicable(self, np_dtype) -> bool:
        """Exact path only when 10^-scale is representable in the column
        dtype — otherwise wider powers wrap (int16 at scale -5) and the
        float path's semantics apply."""
        return 10 ** (-self._scale()) <= int(np.iinfo(np_dtype).max)

    def _int_exact(self, data, xp):
        """floor/ceil of integral ``data`` at 10^scale, scale <= 0, exact."""
        pow10 = 10 ** (-self._scale())
        p = xp.asarray(np.asarray(pow10, dtype=data.dtype))
        q = data // p  # floor division (toward -inf) — floor case directly
        if self._adjust_up:
            q = q + ((data % p) != 0).astype(data.dtype)
        return q * p

    def eval_cpu(self, table):
        c = self.children[0].eval_cpu(table)
        if (isinstance(c.dtype, T.IntegralType) and self._scale() <= 0
                and self._int_exact_applicable(c.dtype.np_dtype)):
            return HostColumn(c.dtype, self._int_exact(c.data, np),
                              c.validity.copy())
        factor = 10.0 ** self._scale()
        with np.errstate(all="ignore"):
            data = type(self)._np_fn(c.data * factor) / factor
        if isinstance(c.dtype, T.IntegralType):
            data = data.astype(c.dtype.np_dtype)
        return HostColumn(c.dtype, data, c.validity.copy())

    def eval_dev(self, ctx, child_vals, prep):
        c = child_vals[0]
        dt = self.children[0].data_type
        if (isinstance(dt, T.IntegralType) and self._scale() <= 0
                and self._int_exact_applicable(dt.np_dtype)):
            return DevVal(self._int_exact(c.data, jnp), c.validity)
        factor = 10.0 ** self._scale()
        data = type(self)._jnp_fn(c.data * factor) / factor
        if isinstance(dt, T.IntegralType):
            data = data.astype(dt.np_dtype)
        return DevVal(data, c.validity)


class RoundCeil(_RoundDirBase):
    _np_fn = staticmethod(np.ceil)
    _jnp_fn = staticmethod(jnp.ceil)
    _adjust_up = 1


class RoundFloor(_RoundDirBase):
    _np_fn = staticmethod(np.floor)
    _jnp_fn = staticmethod(jnp.floor)
