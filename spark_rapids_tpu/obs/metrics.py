"""Unified metric registry (reference: GpuMetric, GpuExec.scala:52-342).

One process-wide table of TYPED metric specs — a name maps to a kind
(``timing`` seconds / ``count`` / ``bytes``) and a collection level
(ESSENTIAL < MODERATE < DEBUG) — plus the :class:`MetricSet` container
every metric producer holds. The set keeps the historical ``dict`` shape
(execs exposed ``self.metrics`` as a plain dict since the seed; tests,
``session.last_metrics`` and the lore pickler all index it), so it IS a
dict: ``add()`` is the level-honoring write path, raw ``[]`` writes stay
possible for bookkeeping values (``dispatches``) that bypass levels.

The active level comes from ``spark.rapids.sql.metrics.level`` and is
set per query by the session; subsystems that are not operators (spill
catalog, recovery counters, shuffle manager) record into named
process-wide scopes fetched via :func:`metric_scope`, so the event log
and crash reports read one registry instead of N ad-hoc counters.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, Optional
from spark_rapids_tpu.lockorder import ordered_lock

#: collection levels, ordered (reference: GpuMetric ESSENTIAL/MODERATE/
#: DEBUG). The session sets the active level from
#: spark.rapids.sql.metrics.level; MetricSet.add drops records above it.
METRIC_LEVELS = {"ESSENTIAL": 0, "MODERATE": 1, "DEBUG": 2}

METRIC_KINDS = ("timing", "count", "bytes")

_ACTIVE_LEVEL = [METRIC_LEVELS["MODERATE"]]


def set_metrics_level(name: str) -> None:
    _ACTIVE_LEVEL[0] = METRIC_LEVELS.get(
        str(name).upper(), METRIC_LEVELS["MODERATE"])


def active_level() -> int:
    return _ACTIVE_LEVEL[0]


@dataclass(frozen=True)
class MetricSpec:
    name: str
    kind: str          # timing | count | bytes
    level: str         # ESSENTIAL | MODERATE | DEBUG
    doc: str = ""


_SPECS: Dict[str, MetricSpec] = {}
_SPEC_LOCK = ordered_lock("obs.metrics.spec")


def register_metric(name: str, kind: str = "count",
                    level: str = "MODERATE", doc: str = "") -> MetricSpec:
    """Declare a typed metric. Re-registering an identical spec is a
    no-op; a CONFLICTING re-registration raises — two subsystems must
    not disagree about what a metric name means."""
    if kind not in METRIC_KINDS:
        raise ValueError(f"unknown metric kind {kind!r} for {name!r} "
                         f"(known: {', '.join(METRIC_KINDS)})")
    if level not in METRIC_LEVELS:
        raise ValueError(f"unknown metric level {level!r} for {name!r} "
                         f"(known: {', '.join(METRIC_LEVELS)})")
    spec = MetricSpec(name, kind, level, doc)
    with _SPEC_LOCK:
        old = _SPECS.get(name)
        if old is not None:
            if (old.kind, old.level) != (kind, level):
                raise ValueError(
                    f"metric {name!r} re-registered as "
                    f"({kind}, {level}) but is already "
                    f"({old.kind}, {old.level})")
            return old
        _SPECS[name] = spec
    return spec


def spec_for(name: str) -> MetricSpec:
    """Spec for a metric name; undeclared names get an inferred spec
    (``*Time`` -> timing, ``*Bytes*`` -> bytes, else count) at MODERATE
    — the historical default of ``add_metric``."""
    spec = _SPECS.get(name)
    if spec is not None:
        return spec
    if name.endswith("Time") or name.endswith("TimeS"):
        kind = "timing"
    elif "Bytes" in name or name.endswith("bytes"):
        kind = "bytes"
    else:
        kind = "count"
    return MetricSpec(name, kind, "MODERATE")


def registered_specs() -> Dict[str, MetricSpec]:
    with _SPEC_LOCK:
        return dict(_SPECS)


class MetricSet(dict):
    """A producer's metrics: a plain dict (name -> value) whose ``add``
    honors the level machinery. Raw ``[]`` assignment bypasses levels —
    reserved for bookkeeping the session always records (dispatches,
    replay counts)."""

    def add(self, key: str, value, level: Optional[str] = None) -> None:
        lvl = level if level is not None else spec_for(key).level
        if METRIC_LEVELS.get(lvl, 1) > _ACTIVE_LEVEL[0]:
            return
        self[key] = self.get(key, 0) + value

    def typed(self) -> Dict[str, dict]:
        """{name: {value, kind, level}} — the event-log rendering."""
        return {k: {"value": v, "kind": spec_for(k).kind,
                    "level": spec_for(k).level}
                for k, v in sorted(self.items())}


# ---------------------------------------------------------------------------
# Process-wide subsystem scopes
# ---------------------------------------------------------------------------


class LockedMetricSet(MetricSet):
    """A MetricSet whose ``add`` is atomic. Process-wide scopes are
    written from many threads at once (shuffle pool workers, concurrent
    query-service workers); the plain read-modify-write ``add`` would
    lose increments under that interleaving. Per-EXEC metric sets stay
    unlocked — an exec instance is drained by one thread."""

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self._lock = ordered_lock("obs.metrics.scope")

    def add(self, key: str, value, level: Optional[str] = None) -> None:
        with self._lock:
            super().add(key, value, level)


_SCOPES: Dict[str, LockedMetricSet] = {}
_SCOPE_LOCK = ordered_lock("obs.metrics.scopes")


def metric_scope(name: str) -> LockedMetricSet:
    """The named process-wide MetricSet for a non-operator subsystem
    (``spill``, ``recovery``, ``shuffle``, ``semaphore``, ``service``).
    Created on first use; the event log snapshots/diffs these per
    query. Thread-safe: ``add`` is atomic."""
    with _SCOPE_LOCK:
        s = _SCOPES.get(name)
        if s is None:
            s = _SCOPES[name] = LockedMetricSet()
        return s


def scopes_snapshot() -> Dict[str, Dict[str, object]]:
    with _SCOPE_LOCK:
        return {name: dict(s) for name, s in _SCOPES.items()}


# ---------------------------------------------------------------------------
# Core metric specs. ESSENTIAL is the set every exec must emit
# (RA-ESSENTIAL-METRICS audits this after a golden-corpus run); the
# subsystem scopes declare theirs where they record them.
# ---------------------------------------------------------------------------

#: the per-operator metrics the exec-boundary instrumentation
#: (obs.spans.install_observation) guarantees on every executed exec
ESSENTIAL_EXEC_METRICS = ("opTime", "numOutputRows", "numOutputBatches")

register_metric("opTime", "timing", "ESSENTIAL",
                "wall time spent inside this operator's execute "
                "boundary (includes children; self time = opTime minus "
                "the children's)")
register_metric("numOutputRows", "count", "ESSENTIAL",
                "rows this operator produced")
register_metric("numOutputBatches", "count", "ESSENTIAL",
                "batches this operator produced")
register_metric("d2hTime", "timing", "ESSENTIAL",
                "device->host conversion time at the DeviceToHost "
                "transition (under async result fetch: the kernel "
                "ENQUEUE only — the fetch is resultFetchTime)")
register_metric("resultFetchTime", "timing", "ESSENTIAL",
                "async d2h completion time for the root transition's "
                "packed result buffers, paid AFTER the device "
                "semaphore released")
register_metric("asyncFetchBatches", "count", "MODERATE",
                "result batches whose download was enqueued under the "
                "semaphore and completed asynchronously after release")
register_metric("h2dTime", "timing", "ESSENTIAL",
                "host->device upload time at the HostToDevice "
                "transition")
register_metric("h2dBatches", "count", "MODERATE",
                "batches uploaded at the HostToDevice transition")
register_metric("scanUploadTime", "timing", "MODERATE",
                "host->device upload time at file scans")
register_metric("shuffleWriteTime", "timing", "MODERATE",
                "shuffle partition split + write time")
register_metric("shuffleReadTime", "timing", "MODERATE",
                "shuffle partition read + upload time")
register_metric("shuffleBytesWritten", "bytes", "ESSENTIAL",
                "serialized bytes this exchange wrote")
register_metric("shuffleBytesRead", "bytes", "ESSENTIAL",
                "serialized bytes this exchange read")
register_metric("spillTime", "timing", "MODERATE",
                "time spent demoting buffers between tiers")
register_metric("spillDeviceCount", "count", "ESSENTIAL",
                "device->host spill demotions")
register_metric("spillDiskCount", "count", "ESSENTIAL",
                "host->disk spill demotions")
register_metric("spillDeviceBytes", "bytes", "ESSENTIAL",
                "device bytes freed by spilling")
register_metric("spillDiskBytes", "bytes", "ESSENTIAL",
                "host bytes demoted to disk")
register_metric("serializeTime", "timing", "MODERATE",
                "shuffle batch pack/compress wall time (recorded from "
                "the writing thread)")
