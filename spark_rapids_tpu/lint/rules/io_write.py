"""RL-WRITE-COMMIT — the exactly-once write contract holds only if
every byte of table output stages through the transactional committer
(io/committer.py): in ``io/`` modules, file-creating calls (write-mode
``open``, ``*.write_table``, ``*.write_csv``) may appear only inside
the ``_write_one`` staged-path callbacks, and
``os.replace``/``os.rename`` promotion belongs to the committer alone.
``committer.py`` itself and ``filecache.py`` (cache files are not
table output) are exempt."""

from __future__ import annotations

import ast
from typing import List

from spark_rapids_tpu.lint.diagnostics import Diagnostic, make
from spark_rapids_tpu.lint.rules.common import _attr_chain

#: io/ modules exempt from RL-WRITE-COMMIT: the committer IS the
#: sanctioned writer, and the file cache's files are not table output
_WRITE_COMMIT_EXEMPT = ("spark_rapids_tpu/io/committer.py",
                        "spark_rapids_tpu/io/filecache.py")

#: the sanctioned callback name: write_partitioned hands these a
#: committer staging path, never a final destination
_WRITE_ONE = "_write_one"


def _open_mode_writes(node: ast.Call) -> bool:
    """Is this an ``open()`` call with a write/append/exclusive mode?
    A non-literal mode is treated as writing (it would dodge the
    audit)."""
    mode = None
    if len(node.args) >= 2:
        mode = node.args[1]
    for kw in node.keywords:
        if kw.arg == "mode":
            mode = kw.value
    if mode is None:
        return False  # default 'r'
    if isinstance(mode, ast.Constant) and isinstance(mode.value, str):
        return any(c in mode.value for c in "wxa")
    return True


def _check_write_commit(rel: str, tree: ast.AST,
                        diags: List[Diagnostic]):
    if not rel.startswith("spark_rapids_tpu/io/") \
            or rel in _WRITE_COMMIT_EXEMPT:
        return

    def walk(node, in_write_one: bool):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            in_write_one = in_write_one or node.name == _WRITE_ONE
        if isinstance(node, ast.Call):
            chain = _attr_chain(node.func)
            if chain in ("os.replace", "os.rename") \
                    or chain.endswith((".replace", ".rename")) \
                    and chain.startswith("os."):
                diags.append(make(
                    "RL-WRITE-COMMIT", f"{rel}:{node.lineno}",
                    f"{chain}() in an io/ writer module — promotion "
                    "into final destinations is the committer's job "
                    "(io/committer.py WriteJob.commit_task)"))
            elif not in_write_one and (
                    chain.endswith((".write_table", ".write_csv"))
                    or (chain == "open" and _open_mode_writes(node))):
                diags.append(make(
                    "RL-WRITE-COMMIT", f"{rel}:{node.lineno}",
                    f"{chain}() creates an output file outside a "
                    f"{_WRITE_ONE} staged-path callback — table "
                    "output must stage through the transactional "
                    "committer, never open a final destination"))
        for child in ast.iter_child_nodes(node):
            walk(child, in_write_one)

    walk(tree, False)
