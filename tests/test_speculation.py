"""Speculative sizing machinery (runtime/speculation.py + the join/agg
speculation sites) — VERDICT r3 #2: the fail -> replay -> blocklist state
machine needs dedicated coverage, not incidental exercise.

Pattern reference: the reference unit-tests its retry state machine
exhaustively (tests/.../WithRetrySuite.scala)."""

import numpy as np
import pytest

from spark_rapids_tpu import functions as F
from spark_rapids_tpu.ops.expr import col, lit
from spark_rapids_tpu.runtime import speculation as spec
from spark_rapids_tpu.session import TpuSession


@pytest.fixture(autouse=True)
def _clean_blocklist():
    saved = set(spec._BLOCKLIST)
    spec._BLOCKLIST.clear()
    yield
    spec._BLOCKLIST.clear()
    spec._BLOCKLIST.update(saved)


def _cpu():
    return TpuSession({"spark.rapids.sql.enabled": "false"})


def _fk_tables(n=20_000, nkeys=500, seed=0):
    rng = np.random.default_rng(seed)
    fact = {"k": rng.integers(0, nkeys, n).astype(np.int64),
            "v": rng.random(n)}
    dim = {"k": np.arange(nkeys, dtype=np.int64),
           "w": (np.arange(nkeys) % 7).astype(np.int64)}
    return fact, dim


def _join_q(s, fact, dim, how="inner"):
    return sorted(
        s.create_dataframe(fact).join(s.create_dataframe(dim), on="k",
                                      how=how)
        .group_by("w").agg(F.count().alias("c"),
                           F.sum(col("v")).alias("sv")).collect())


def _rows_close(a, b):
    assert len(a) == len(b)
    for x, y in zip(a, b):
        assert x[0] == y[0] and x[1] == y[1]
        assert abs(x[2] - y[2]) <= 1e-6 * max(1.0, abs(y[2]))


# -- core state machine ------------------------------------------------------

def test_flags_validated_and_cleared_on_success():
    fact, dim = _fk_tables()
    s = TpuSession()
    _rows_close(_join_q(s, fact, dim), _join_q(_cpu(), fact, dim))
    # nothing blocklisted, no flags leaked into a stale context
    assert spec.current() is None
    assert not spec._BLOCKLIST


def test_duplicate_build_keys_fail_replay_blocklist_exact():
    """Duplicate build-side keys break the direct join's uniqueness
    speculation: the flag must fire, the query must REPLAY to an exact
    result, and the site must be blocklisted so the second run never
    replays."""
    rng = np.random.default_rng(1)
    n = 8000
    fact = {"k": rng.integers(0, 50, n).astype(np.int64),
            "v": rng.random(n)}
    dup = {"k": np.concatenate([np.arange(50), np.arange(50)]).astype(
        np.int64), "w": np.arange(100, dtype=np.int64)}
    s = TpuSession()
    got = sorted(
        s.create_dataframe(fact).join(s.create_dataframe(dup), on="k",
                                      how="inner")
        .group_by("k").agg(F.count().alias("c")).collect())
    want = sorted(
        _cpu().create_dataframe(fact).join(
            _cpu().create_dataframe(dup), on="k", how="inner")
        .group_by("k").agg(F.count().alias("c")).collect())
    assert got == want
    assert any(":direct" in site for site in spec._BLOCKLIST), \
        spec._BLOCKLIST
    blocked = set(spec._BLOCKLIST)
    # second run: the blocklisted site takes the sort-based path directly
    got2 = sorted(
        s.create_dataframe(fact).join(s.create_dataframe(dup), on="k",
                                      how="inner")
        .group_by("k").agg(F.count().alias("c")).collect())
    assert got2 == want
    assert set(spec._BLOCKLIST) == blocked  # no new failures


def test_sparse_key_range_falls_back_exact():
    """Build keys spread over a range far wider than the direct table
    capacity: the range-fits flag fires and the replay is exact."""
    rng = np.random.default_rng(2)
    n = 4000
    sparse_keys = rng.choice(10**9, size=200, replace=False).astype(np.int64)
    fact = {"k": sparse_keys[rng.integers(0, 200, n)],
            "v": rng.random(n)}
    dim = {"k": sparse_keys, "w": np.arange(200, dtype=np.int64)}
    s = TpuSession()
    got = _join_q(s, fact, dim)
    _rows_close(got, _join_q(_cpu(), fact, dim))
    assert any(":direct" in site for site in spec._BLOCKLIST)


def test_blocklist_is_per_operator_site():
    """Two same-shaped joins at different plan positions blocklist
    independently (ADVICE r3: _site_key shares look-alike operators)."""
    from spark_rapids_tpu.execs.join import TpuJoinExec
    from spark_rapids_tpu.ops.expr import BoundReference
    from spark_rapids_tpu import types as T
    mk = lambda: TpuJoinExec.__new__(TpuJoinExec)
    a, b = mk(), mk()
    for j, lid in ((a, 3), (b, 9)):
        j.join_type = "inner"
        j.left_keys = [BoundReference(0, T.LONG)]
        j.right_keys = [BoundReference(0, T.LONG)]
        j.left_names = ["k"]
        j.right_names = ["k"]
        j._site_base = "join:shape"
        j._lore_id = lid
    assert a._site_key != b._site_key


def test_conf_off_takes_exact_path():
    fact, dim = _fk_tables(seed=3)
    s = TpuSession({"spark.rapids.tpu.speculativeSizing.enabled": "false"})
    _rows_close(_join_q(s, fact, dim), _join_q(_cpu(), fact, dim))
    assert not spec._BLOCKLIST


# -- flag delivery -----------------------------------------------------------

def test_flags_ride_packed_fetch():
    """Small collect: the pending flags embed in the packed d2h fetch
    (to_host consumes ctx.take_pending) and validate there."""
    fact, dim = _fk_tables(n=5000, seed=4)
    s = TpuSession()
    df = (s.create_dataframe(fact)
          .join(s.create_dataframe(dim), on="k", how="inner"))
    out = df.group_by("w").agg(F.count().alias("c"))
    got = sorted(out.collect())
    want = sorted(
        _cpu().create_dataframe(fact).join(
            _cpu().create_dataframe(dim), on="k", how="inner")
        .group_by("w").agg(F.count().alias("c")).collect())
    assert got == want


def test_validate_remaining_catches_unfetched_flags():
    """Flags not consumed by any packed fetch raise at validate_remaining."""
    import jax.numpy as jnp
    tok = spec.activate()
    try:
        ctx = spec.current()
        ctx.add_flag("site-a", jnp.asarray(False))
        ctx.add_flag("site-b", jnp.asarray(True))
        with pytest.raises(spec.SpeculationFailed) as ei:
            ctx.validate_remaining()
        assert ei.value.sites == ["site-b"]
        assert not ctx.pending  # consumed
    finally:
        spec.deactivate(tok)


def test_guard_attempt_drops_flags_from_aborted_attempt():
    import jax.numpy as jnp
    tok = spec.activate()
    try:
        ctx = spec.current()
        ctx.add_flag("kept", jnp.asarray(False))

        def boom():
            ctx.add_flag("aborted", jnp.asarray(True))
            raise RuntimeError("attempt failed")

        with pytest.raises(RuntimeError):
            spec.guard_attempt(boom)
        assert [s for s, _ in ctx.pending] == ["kept"]
    finally:
        spec.deactivate(tok)


# -- interplay ---------------------------------------------------------------

def test_speculation_with_oom_injection():
    fact, dim = _fk_tables(seed=5)
    s = TpuSession({"spark.rapids.sql.test.injectRetryOOM": "retry:2"})
    _rows_close(_join_q(s, fact, dim), _join_q(_cpu(), fact, dim))
    assert not spec._BLOCKLIST  # aborted attempts must not blocklist


def test_speculation_with_multibatch_streaming():
    """Multi-batch probe side: each batch adds its own flags; all validate."""
    rng = np.random.default_rng(6)
    n = 30_000
    fact = {"k": rng.integers(0, 300, n).astype(np.int64),
            "v": rng.random(n)}
    dim = {"k": np.arange(300, dtype=np.int64),
           "w": (np.arange(300) % 5).astype(np.int64)}
    s = TpuSession()
    got = sorted(
        s.create_dataframe(fact, num_batches=4)
        .join(s.create_dataframe(dim), on="k", how="inner")
        .group_by("w").agg(F.count().alias("c")).collect())
    want = sorted(
        _cpu().create_dataframe(fact)
        .join(_cpu().create_dataframe(dim), on="k", how="inner")
        .group_by("w").agg(F.count().alias("c")).collect())
    assert got == want


def test_agg_speculative_shrink_site_blocklists_once():
    """All-distinct-keys aggregate: the shrink speculation misses, the
    site blocklists, and the immediate re-run does not replay again."""
    n = 150_000
    data = {"k": np.arange(n, dtype=np.int64)}
    # force the sort-segment path: dense int keys would otherwise take the
    # domain fast path, which emits a domain-sized output with no shrink
    # speculation at all
    s = TpuSession({"spark.rapids.tpu.agg.maxKeyDomainGroups": 0})
    q = lambda: s.create_dataframe(data).group_by("k").agg(
        F.count().alias("c"))
    r1 = q().collect()
    assert len(r1) == n
    shrink_sites = {x for x in spec._BLOCKLIST if x.endswith(":shrink")}
    assert shrink_sites
    r2 = q().collect()
    assert len(r2) == n
    assert {x for x in spec._BLOCKLIST if x.endswith(":shrink")} == \
        shrink_sites


def test_replay_metric_recorded():
    rng = np.random.default_rng(7)
    n = 8000
    fact = {"k": rng.integers(0, 50, n).astype(np.int64)}
    dup = {"k": np.concatenate([np.arange(50), np.arange(50)]).astype(
        np.int64), "w": np.arange(100, dtype=np.int64)}
    s = TpuSession()
    _ = (s.create_dataframe(fact).join(s.create_dataframe(dup), on="k",
                                       how="inner")
         .group_by("k").agg(F.count().alias("c")).collect())
    m = s.last_metrics()
    assert "speculationReplays" in m, m
