"""Shim provider for jax 0.4.30 - 0.5.x: shard_map lives under
jax.experimental, jax.tree.* may be absent (tree_util spelling), and
jax.make_mesh appears only late in the 0.4 line."""

from __future__ import annotations

from spark_rapids_tpu.shims.base import BaseShim


class JaxLegacyShim(BaseShim):
    MIN_VERSION = (0, 4, 30)
    MAX_VERSION = (0, 6, 0)

    def shard_map(self):
        import jax
        sm = getattr(jax, "shard_map", None)
        if sm is None:
            from jax.experimental.shard_map import shard_map as sm
        return sm

    def tree_map(self, f, tree, *rest):
        import jax
        tree_mod = getattr(jax, "tree", None)
        if tree_mod is not None and hasattr(tree_mod, "map"):
            return tree_mod.map(f, tree, *rest)
        return jax.tree_util.tree_map(f, tree, *rest)

    def tree_leaves(self, tree):
        import jax
        tree_mod = getattr(jax, "tree", None)
        if tree_mod is not None and hasattr(tree_mod, "leaves"):
            return tree_mod.leaves(tree)
        return jax.tree_util.tree_leaves(tree)

    def make_mesh(self, axis_shapes, axis_names):
        import jax
        mk = getattr(jax, "make_mesh", None)
        if mk is not None:
            return mk(axis_shapes, axis_names)
        import numpy as np
        from jax.sharding import Mesh
        devs = np.asarray(jax.devices()[:int(np.prod(axis_shapes))])
        return Mesh(devs.reshape(axis_shapes), axis_names)
