"""CPU (oracle/fallback) equi-join with Spark-exact semantics.

Gather-map design mirrors the reference's GpuHashJoin (SURVEY.md §2.3:
join -> GatherMap -> chunked gather): we compute left/right row-index arrays
then gather. Spark corners: NULL keys never match (but leftanti keeps
null-keyed left rows); semi/anti return only left columns; condition is
applied to candidate pairs before match bookkeeping for outer joins."""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar import HostColumn, HostTable
from spark_rapids_tpu.ops.expr import Expression


def _key_codes(left_cols: List[HostColumn], right_cols: List[HostColumn],
               nl: int, nr: int) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Densify join keys into a shared integer code space.

    Returns (left_codes, right_codes, left_has_null, right_has_null)."""
    l_null = np.zeros(nl, dtype=np.bool_)
    r_null = np.zeros(nr, dtype=np.bool_)
    combined_l = None
    combined_r = None
    for lc, rc in zip(left_cols, right_cols):
        l_null |= ~lc.validity
        r_null |= ~rc.validity
        if isinstance(lc.dtype, T.StringType):
            lv = np.where(lc.validity, lc.data, "")
            rv = np.where(rc.validity, rc.data, "")
            allv = np.concatenate([lv.astype(object), rv.astype(object)])
        else:
            lv, rv = lc.data, rc.data
            allv = np.concatenate([lv, rv])
        uniq, codes = np.unique(allv, return_inverse=True)
        codes = codes.astype(np.int64)
        lcode, rcode = codes[:nl], codes[nl:]
        if combined_l is None:
            combined_l, combined_r = lcode, rcode
        else:
            card = len(uniq)
            combined_l = combined_l * card + lcode
            combined_r = combined_r * card + rcode
            both = np.concatenate([combined_l, combined_r])
            _, dense = np.unique(both, return_inverse=True)
            dense = dense.astype(np.int64)
            combined_l, combined_r = dense[:nl], dense[nl:]
    return combined_l, combined_r, l_null, r_null


def _gather_map(l_codes, r_codes, l_null, r_null) -> Tuple[np.ndarray, np.ndarray]:
    """All matching (left_idx, right_idx) candidate pairs; null keys excluded."""
    nl = len(l_codes)
    valid_r = np.nonzero(~r_null)[0]
    rs = valid_r[np.argsort(r_codes[valid_r], kind="stable")]
    rs_codes = r_codes[rs]
    lo = np.searchsorted(rs_codes, l_codes, side="left")
    hi = np.searchsorted(rs_codes, l_codes, side="right")
    counts = np.where(l_null, 0, hi - lo)
    total = int(counts.sum())
    if total == 0:
        return np.array([], dtype=np.int64), np.array([], dtype=np.int64)
    left_idx = np.repeat(np.arange(nl, dtype=np.int64), counts)
    # positions within each row's [lo, hi) range
    csum = np.zeros(nl + 1, dtype=np.int64)
    np.cumsum(counts, out=csum[1:])
    offset_in_row = np.arange(total, dtype=np.int64) - csum[:-1][left_idx]
    right_pos = lo[left_idx] + offset_in_row
    right_idx = rs[right_pos]
    return left_idx, right_idx


def _gather_cols(table: HostTable, idx: np.ndarray, null_mask: Optional[np.ndarray] = None
                 ) -> List[HostColumn]:
    """Gather rows; where null_mask is True (or idx < 0) the output row is
    all-null (outer-join padding)."""
    n = len(idx)
    safe = np.clip(idx, 0, max(table.num_rows - 1, 0))
    cols = []
    for c in table.columns:
        if table.num_rows == 0:
            data = (np.full(n, None, dtype=object) if isinstance(c.dtype, T.StringType)
                    else np.zeros(n, dtype=c.dtype.np_dtype))
            validity = np.zeros(n, dtype=np.bool_)
            cols.append(HostColumn(c.dtype, data, validity))
            continue
        data = c.data[safe]
        validity = c.validity[safe]
        if null_mask is not None:
            validity = validity & ~null_mask
            if isinstance(c.dtype, T.StringType):
                data = data.copy()
                data[null_mask] = None
        cols.append(HostColumn(c.dtype, np.array(data), np.array(validity)))
    return cols


def join_cpu(left: HostTable, right: HostTable, join_type: str,
             left_keys: Sequence[Expression], right_keys: Sequence[Expression],
             condition: Optional[Expression]) -> HostTable:
    nl, nr = left.num_rows, right.num_rows
    jt = join_type.lower().replace("_", "")

    if jt == "cross" or not left_keys:
        # keyless non-cross join = nested loop: all pairs are candidates and
        # the condition decides matches (BroadcastNestedLoopJoin analog)
        li = np.repeat(np.arange(nl, dtype=np.int64), nr)
        ri = np.tile(np.arange(nr, dtype=np.int64), nl)
    else:
        lk = [k.eval_cpu(left) for k in left_keys]
        rk = [k.eval_cpu(right) for k in right_keys]
        l_codes, r_codes, l_null, r_null = _key_codes(lk, rk, nl, nr)
        li, ri = _gather_map(l_codes, r_codes, l_null, r_null)

    # apply the residual (non-equi) condition to candidate pairs
    if condition is not None and len(li):
        pair_cols = _gather_cols(left, li) + _gather_cols(right, ri)
        pair = HostTable(list(left.names) + list(right.names), pair_cols)
        pred = condition.eval_cpu(pair)
        keep = pred.validity & pred.data.astype(np.bool_)
        li, ri = li[keep], ri[keep]

    names_both = list(left.names) + list(right.names)

    if jt == "inner" or jt == "cross":
        cols = _gather_cols(left, li) + _gather_cols(right, ri)
        return HostTable(names_both, cols)

    l_matched = np.zeros(nl, dtype=np.bool_)
    l_matched[li] = True
    r_matched = np.zeros(nr, dtype=np.bool_)
    r_matched[ri] = True

    if jt == "leftsemi":
        idx = np.nonzero(l_matched)[0]
        return HostTable(left.names, _gather_cols(left, idx))
    if jt == "leftanti":
        idx = np.nonzero(~l_matched)[0]
        return HostTable(left.names, _gather_cols(left, idx))

    if jt in ("left", "leftouter"):
        extra_l = np.nonzero(~l_matched)[0]
        li2 = np.concatenate([li, extra_l])
        ri2 = np.concatenate([ri, np.full(len(extra_l), -1, dtype=np.int64)])
        null_r = ri2 < 0
        cols = _gather_cols(left, li2) + _gather_cols(right, ri2, null_r)
        return HostTable(names_both, cols)
    if jt in ("right", "rightouter"):
        extra_r = np.nonzero(~r_matched)[0]
        li2 = np.concatenate([li, np.full(len(extra_r), -1, dtype=np.int64)])
        ri2 = np.concatenate([ri, extra_r])
        null_l = li2 < 0
        cols = _gather_cols(left, li2, null_l) + _gather_cols(right, ri2)
        return HostTable(names_both, cols)
    if jt in ("full", "fullouter", "outer"):
        extra_l = np.nonzero(~l_matched)[0]
        extra_r = np.nonzero(~r_matched)[0]
        li2 = np.concatenate([li, extra_l, np.full(len(extra_r), -1, dtype=np.int64)])
        ri2 = np.concatenate([ri, np.full(len(extra_l), -1, dtype=np.int64), extra_r])
        cols = _gather_cols(left, li2, li2 < 0) + _gather_cols(right, ri2, ri2 < 0)
        return HostTable(names_both, cols)

    raise ValueError(f"unsupported join type {join_type}")
