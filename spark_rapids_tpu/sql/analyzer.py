"""Analyzer/binder: untyped SQL AST -> the existing DataFrame/plan layer.

Every lowered query flows through the SAME plan nodes the DataFrame API
builds (Project/Filter/Aggregate/Join/WindowNode/...), so the overrides
engine tags, falls back, and converts SQL queries exactly as it does DSL
queries — there is no parallel execution path. The analyzer's jobs:

  * resolve table names against the session catalog (temp views, file
    tables via the sources SPI) and CTEs;
  * resolve column identifiers (optionally alias-qualified) against the
    in-scope relation schemas;
  * resolve function names through sql.registry (builtins from
    functions.py, registered Python UDFs, Hive UDFs);
  * lower SELECT semantics in Spark's phase order — FROM, WHERE,
    GROUP BY/HAVING, window functions, projection, DISTINCT, set ops,
    ORDER BY, LIMIT — while ELIDING identity projections so a SQL query
    and its DSL form produce the same plan shape (and hence the same
    device dispatch count);
  * rewrite IN (subquery) to a left-semi/anti join and uncorrelated
    scalar subqueries to a cross join + hidden column (Spark's own
    rewrites), because the plan layer has no subquery nodes.

Unsupported constructs raise SqlAnalysisError with the query position
and an overrides-style per-construct reason."""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from spark_rapids_tpu import types as T
from spark_rapids_tpu.ops import aggregates as agg
from spark_rapids_tpu.ops.expr import (
    Alias,
    AttributeReference,
    Expression,
    Literal,
    col,
    lit,
    output_name,
)
from spark_rapids_tpu.plan import nodes as P
from spark_rapids_tpu.sql import ast as A
from spark_rapids_tpu.sql import registry
from spark_rapids_tpu.sql.errors import SqlAnalysisError, unsupported


class Scope:
    """In-scope relation: the lowered DataFrame plus per-relation-alias
    {logical name -> physical plan column} maps for qualified-name
    resolution. The plan layer binds AttributeReferences BY NAME over
    the concatenated join schema, so when both join sides carry a
    column `x` the right copy is renamed to a fresh physical name; the
    alias map and ``display`` keep the SQL-level names addressable."""

    def __init__(self, df,
                 aliases: Optional[Dict[str, Dict[str, str]]] = None,
                 visible: Optional[List[str]] = None,
                 display: Optional[Dict[str, str]] = None):
        self.df = df
        self.aliases = aliases or {}
        #: columns star-expansion may see (hides scalar-subquery helpers)
        self.visible = visible if visible is not None else self.columns
        #: physical -> SQL-level name for star expansion of renamed
        #: right-side join duplicates
        self.display = display or {}

    @property
    def columns(self) -> List[str]:
        return [n for n, _ in self.df.plan.output_schema()]

    def with_df(self, df) -> "Scope":
        return Scope(df, self.aliases, self.visible, self.display)


class Analyzer:
    def __init__(self, session, sql_text: str):
        self.session = session
        self.sql = sql_text
        self.ctes: Dict[str, object] = {}   # name -> plan (lowered CTEs)
        self._fresh = 0

    # -- errors --------------------------------------------------------------
    def err(self, msg: str, node: Optional[A.Node] = None) -> SqlAnalysisError:
        line = getattr(node, "line", 0) or 0
        colno = getattr(node, "col", 0) or 0
        return SqlAnalysisError(msg, self.sql, line, colno)

    def unsup(self, construct: str, reason: str,
              node: Optional[A.Node] = None) -> SqlAnalysisError:
        line = getattr(node, "line", 0) or 0
        colno = getattr(node, "col", 0) or 0
        return unsupported(construct, reason, self.sql, line, colno)

    def fresh_name(self, prefix: str) -> str:
        self._fresh += 1
        return f"__{prefix}{self._fresh}"

    # -- statements ----------------------------------------------------------
    def lower_statement(self, stmt: A.Node):
        from spark_rapids_tpu.plan import DataFrame, from_host_table

        if isinstance(stmt, A.Query):
            return self.lower_query(stmt)
        if isinstance(stmt, A.CreateView):
            cat = self.session.catalog
            if not stmt.replace and stmt.name.lower() in [
                    t.lower() for t in cat.list_tables()]:
                raise self.err(f"view {stmt.name!r} already exists "
                               "(use CREATE OR REPLACE)", stmt)
            if stmt.using is not None:
                path = stmt.options.get("path")
                if path is None:
                    raise self.err(
                        "CREATE TEMP VIEW ... USING requires a "
                        "path option: OPTIONS (path '...')", stmt)
                opts = {k: v for k, v in stmt.options.items()
                        if k != "path"}
                cat.register_table(stmt.name, stmt.using, path, **opts)
                return cat.table(stmt.name)
            df = self.lower_query(stmt.query)
            cat.create_or_replace_temp_view(stmt.name, df)
            return df
        if isinstance(stmt, A.DropView):
            cat = self.session.catalog
            dropped_view = cat.drop_temp_view(stmt.name)
            dropped = cat.drop_table(stmt.name) or dropped_view
            if not dropped and not stmt.if_exists:
                raise self.err(f"view {stmt.name!r} not found", stmt)
            from spark_rapids_tpu.columnar import HostTable
            return from_host_table(
                HostTable.from_pydict({"dropped": [stmt.name]}),
                self.session)
        raise self.err(f"unsupported statement {type(stmt).__name__}", stmt)

    # -- query / set ops -----------------------------------------------------
    def lower_query(self, q: A.Query):
        saved = dict(self.ctes)
        try:
            for name, sub in q.ctes:
                self.ctes[name.lower()] = self.lower_query(sub).plan
            if isinstance(q.body, A.Select):
                # plain selects take ORDER BY with them so sort keys may
                # reference input columns the projection drops (Spark
                # plans Project over Sort for that case)
                return self.lower_select(q.body, order_by=q.order_by,
                                         limit=q.limit)
            df = self._lower_set(q.body)
            if q.order_by:
                df = self._apply_order(df, q.order_by)
            if q.limit is not None:
                df = df.limit(q.limit)
            return df
        finally:
            self.ctes = saved

    def _lower_set(self, body: A.Node):
        if isinstance(body, A.Select):
            return self.lower_select(body)
        if isinstance(body, A.Query):
            return self.lower_query(body)
        if isinstance(body, A.SetOp):
            left = self._lower_set(body.left)
            right = self._lower_set(body.right)
            if len(left.columns) != len(right.columns):
                raise self.err(
                    f"UNION arms have {len(left.columns)} vs "
                    f"{len(right.columns)} columns", body)
            out = left.union(right)
            if body.op == "union":      # UNION DISTINCT
                out = self._distinct(out)
            return out
        raise self.err(f"unsupported query body {type(body).__name__}", body)

    def _distinct(self, df):
        # Spark plans DISTINCT as Aggregate(all output columns, no aggs)
        return df.group_by(*[col(n) for n in df.columns]).agg()

    # -- relations -----------------------------------------------------------
    def lower_relation(self, rel: A.Node) -> Scope:
        if isinstance(rel, A.TableRef):
            from spark_rapids_tpu.plan import DataFrame
            plan = self.ctes.get(rel.name.lower())
            if plan is not None:
                df = DataFrame(plan, self.session)
            else:
                df = self.session.catalog.lookup_relation(rel.name)
                if df is None:
                    raise self.err(
                        f"table or view {rel.name!r} not found (known: "
                        f"{self.session.catalog.list_tables()})", rel)
            names = [n for n, _ in df.plan.output_schema()]
            key = (rel.alias or rel.name).lower()
            return Scope(df, {key: {n: n for n in names}})
        if isinstance(rel, A.SubqueryRef):
            df = self.lower_query(rel.query)
            names = [n for n, _ in df.plan.output_schema()]
            aliases = {rel.alias.lower(): {n: n for n in names}} \
                if rel.alias else {}
            return Scope(df, aliases)
        if isinstance(rel, A.JoinRel):
            return self._lower_join(rel)
        raise self.err(f"unsupported relation {type(rel).__name__}", rel)

    def _disambiguate_right(self, ls: Scope, rs: Scope,
                            keep: Sequence[str] = ()):
        """Rename right-side columns whose names collide with the left
        side to fresh physical names (the plan layer binds references by
        NAME over the concatenated join schema, so duplicates would
        silently bind left). Returns (new_rs, old physical -> new
        physical map); ``keep`` columns (USING keys joined by name)
        stay. No collisions -> rs unchanged, no extra Project."""
        dup = [n for n in rs.columns if n in ls.columns and n not in keep]
        pmap = {n: n for n in rs.columns}
        if not dup:
            return rs, pmap
        exprs: List[Expression] = []
        for n in rs.columns:
            if n in dup:
                pmap[n] = self.fresh_name("r")
                exprs.append(Alias(col(n), pmap[n]))
            else:
                exprs.append(col(n))
        rdf = rs.df.select(*exprs)
        aliases = {a: {ln: pmap.get(pn, pn) for ln, pn in m.items()}
                   for a, m in rs.aliases.items()}
        display = {pmap.get(p, p): l for p, l in rs.display.items()}
        display.update({pmap[n]: n for n in dup})
        visible = [pmap.get(n, n) for n in rs.visible]
        return Scope(rdf, aliases, visible, display), pmap

    def _lower_join(self, rel: A.JoinRel) -> Scope:
        from spark_rapids_tpu.plan import DataFrame
        ls = self.lower_relation(rel.left)
        rs = self.lower_relation(rel.right)
        how = rel.how
        if how == "cross":
            rs, _ = self._disambiguate_right(ls, rs)
            df = ls.df.join(rs.df, on=None)
            return Scope(df, {**ls.aliases, **rs.aliases},
                         display={**ls.display, **rs.display})
        if rel.using:
            for c in rel.using:
                if c not in ls.columns or c not in rs.columns:
                    raise self.err(
                        f"USING column {c!r} must exist on both sides "
                        f"(left: {ls.columns}, right: {rs.columns})", rel)
            if how in ("right", "full"):
                return self._lower_outer_using(ls, rs, rel, how)
            rs, _ = self._disambiguate_right(ls, rs, keep=rel.using)
            df = ls.df.join(rs.df, on=list(rel.using), how=how)
            # USING hides the right-side duplicate of each join column
            # from star expansion (SQL natural-join output shape);
            # semi/anti output is the left side only
            if how in ("leftsemi", "leftanti"):
                visible = list(ls.visible)
            else:
                visible = ls.visible + [c for c in rs.visible
                                        if c not in rel.using]
            return Scope(df, {**ls.aliases, **rs.aliases}, visible,
                         display={**ls.display, **rs.display})
        # ON condition: extract equi key pairs (ExtractEquiJoinKeys
        # analog) so hash-join-able conditions take the equi path the
        # DSL's on=["k"] form takes
        rs, _ = self._disambiguate_right(ls, rs)
        merged_aliases = {**ls.aliases, **rs.aliases}
        merged_display = {**ls.display, **rs.display}
        combined = Scope(
            DataFrame(P.Join(ls.df.plan, rs.df.plan, "cross", [], []),
                      self.session), merged_aliases)
        conjuncts = _split_conjuncts(rel.on)
        lkeys: List[Expression] = []
        rkeys: List[Expression] = []
        residual: List[A.Node] = []
        for c in conjuncts:
            pair = self._equi_pair(c, ls, rs)
            if pair is None:
                residual.append(c)
            else:
                lkeys.append(pair[0])
                rkeys.append(pair[1])
        cond = None
        if residual:
            rest = residual[0]
            for nxt in residual[1:]:
                rest = A.BinOp(op="AND", left=rest, right=nxt,
                               line=nxt.line, col=nxt.col)
            cond = self.lower_expr(rest, combined)
        join = P.Join(ls.df.plan, rs.df.plan, how, lkeys, rkeys,
                      condition=cond)
        if how in ("leftsemi", "leftanti"):
            return Scope(DataFrame(join, self.session), merged_aliases,
                         list(ls.visible), display=dict(ls.display))
        return Scope(DataFrame(join, self.session), merged_aliases,
                     ls.visible + rs.visible, display=merged_display)

    def _lower_outer_using(self, ls: Scope, rs: Scope, rel: A.JoinRel,
                           how: str) -> Scope:
        """RIGHT/FULL JOIN ... USING: the merged key column is
        COALESCE(left, right) (right join: the right copy), NOT the
        left copy — an unmatched right row must surface its key, not
        NULL. Joins on explicit key pairs over a renamed right side,
        then projects the merged key back under the USING name."""
        from spark_rapids_tpu import functions as F
        from spark_rapids_tpu.plan import DataFrame
        rs, pmap = self._disambiguate_right(ls, rs)
        lk = [col(c) for c in rel.using]
        rk = [col(pmap[c]) for c in rel.using]
        df = DataFrame(P.Join(ls.df.plan, rs.df.plan, how, lk, rk),
                       self.session)
        right_key_phys = {pmap[c] for c in rel.using}
        exprs: List[Expression] = []
        for n in ls.columns:
            if n in rel.using:
                rc = col(pmap[n])
                e = rc if how == "right" else F.coalesce(col(n), rc)
                exprs.append(Alias(e, n))
            else:
                exprs.append(col(n))
        exprs += [col(n) for n in rs.columns if n not in right_key_phys]
        df = df.select(*exprs)
        # both sides' qualified key references resolve to the merged key
        aliases = {**ls.aliases,
                   **{a: {ln: (ln if ln in rel.using else pn)
                          for ln, pn in m.items()}
                      for a, m in rs.aliases.items()}}
        visible = ls.visible + [n for n in rs.visible
                                if n not in right_key_phys]
        display = {**ls.display,
                   **{p: l for p, l in rs.display.items()
                      if p not in right_key_phys}}
        return Scope(df, aliases, visible, display)

    def _equi_pair(self, c: A.Node, ls: Scope, rs: Scope):
        """(left_key, right_key) when ``c`` is `<left-only> = <right-only>`
        (either orientation), else None."""
        if not (isinstance(c, A.BinOp) and c.op == "="):
            return None
        s1 = self._ref_sides(c.left, ls, rs)
        s2 = self._ref_sides(c.right, ls, rs)
        if s1 == {"L"} and s2 == {"R"}:
            return (self.lower_expr(c.left, ls),
                    self.lower_expr(c.right, rs))
        if s1 == {"R"} and s2 == {"L"}:
            return (self.lower_expr(c.right, ls),
                    self.lower_expr(c.left, rs))
        return None

    def _ref_sides(self, node: A.Node, ls: Scope, rs: Scope) -> set:
        """Which join side(s) the column references in ``node`` touch."""
        sides: set = set()

        def walk(x):
            if isinstance(x, A.Ident):
                if len(x.parts) == 2:
                    q = x.parts[0].lower()
                    if q in ls.aliases:
                        sides.add("L")
                    elif q in rs.aliases:
                        sides.add("R")
                    else:
                        sides.add("?")
                else:
                    name = x.parts[0]
                    inl = name in ls.columns
                    inr = name in rs.columns
                    if inl and inr:
                        sides.update({"L", "R"})
                    elif inl:
                        sides.add("L")
                    elif inr:
                        sides.add("R")
                    else:
                        sides.add("?")
                return
            for f in ("left", "right", "operand", "low", "high", "pattern"):
                sub = getattr(x, f, None)
                if isinstance(sub, A.Node):
                    walk(sub)
            for seq in (getattr(x, "args", ()) or (),
                        getattr(x, "items", ()) or ()):
                for sub in seq:
                    if isinstance(sub, A.Node):
                        walk(sub)
        walk(node)
        return sides

    # -- SELECT --------------------------------------------------------------
    def lower_select(self, sel: A.Select, order_by=None, limit=None):
        from spark_rapids_tpu.plan import DataFrame

        # FROM (a FROM-less select evaluates over one synthetic row)
        if sel.from_ is not None:
            scope = self.lower_relation(sel.from_)
        else:
            scope = Scope(DataFrame(P.RangeNode(0, 1, 1), self.session),
                          {}, visible=[])

        # hints (the DSL's .repartition escape hatch)
        for hname, hargs in sel.hints:
            if hname == "REPARTITION":
                if not hargs or not hargs[0].isdigit():
                    raise self.err(
                        "REPARTITION hint needs (numPartitions[, cols...])",
                        sel)
                n = int(hargs[0])
                scope = scope.with_df(
                    scope.df.repartition(n, *hargs[1:]))
            elif hname == "COALESCE":
                if not hargs or not hargs[0].isdigit():
                    raise self.err("COALESCE hint needs (numPartitions)",
                                   sel)
                scope = scope.with_df(
                    scope.df.repartition(int(hargs[0])))
            else:
                raise self.unsup(f"hint {hname}",
                                 "supported hints: REPARTITION, COALESCE",
                                 sel)

        # WHERE (subquery rewrites first, then one Filter preserving the
        # original predicate tree so SQL text and DSL build equal plans)
        if sel.where is not None:
            scope = self._apply_where(scope, sel.where)

        # expand stars / assign positions
        items = self._expand_items(sel.items, scope)

        has_group = bool(sel.group_by) or sel.having is not None
        has_agg = has_group or any(
            self._contains_agg_call(it.expr) for it in items)

        if has_agg:
            df, names = self._lower_aggregate(scope, items, sel)
        else:
            df, names, pre_sorted = self._lower_plain_select(
                scope, items, sel, order_by)
            if pre_sorted:
                order_by = None

        if sel.distinct:
            df = self._distinct(df)
        if order_by:
            df = self._apply_order(df, order_by)
        if limit is not None:
            df = df.limit(limit)
        return df

    # -- WHERE ---------------------------------------------------------------
    def _apply_where(self, scope: Scope, where: A.Node) -> Scope:
        if not self._contains_subquery(where):
            return scope.with_df(
                scope.df.filter(self.lower_expr(where, scope)))
        conjuncts = _split_conjuncts(where)
        plain: List[A.Node] = []
        from spark_rapids_tpu.plan import DataFrame
        df = scope.df
        hidden: List[str] = []
        for c in conjuncts:
            if isinstance(c, A.InSubquery):
                sub = self.lower_query(c.query)
                sub_cols = sub.columns
                if len(sub_cols) != 1:
                    raise self.err(
                        "IN subquery must produce exactly one column, "
                        f"got {sub_cols}", c)
                key = self.lower_expr(c.operand, scope.with_df(df))
                if c.negated:
                    # NOT IN is null-aware (Spark's NullAwareAntiJoin):
                    # a NULL key or any NULL in the subquery makes the
                    # predicate UNKNOWN, which WHERE drops — a plain
                    # anti join would keep those rows. leftanti keeps
                    # rows with NO matching right row, so matching on
                    # (key = y OR key IS NULL OR y IS NULL) drops them;
                    # an empty subquery keeps everything (NOT IN over
                    # the empty set is TRUE, NULL key included).
                    name = self.fresh_name("notin")
                    sub = sub.select(col(sub_cols[0]).alias(name))
                    rkey = col(name)
                    cond = (key == rkey) | key.isnull() | rkey.isnull()
                    df = DataFrame(
                        P.Join(df.plan, sub.plan, "leftanti", [], [],
                               condition=cond), self.session)
                    continue
                df = DataFrame(
                    P.Join(df.plan, sub.plan, "leftsemi", [key],
                           [col(sub_cols[0])]), self.session)
                continue
            if self._contains_subquery(c):
                c, df, new_hidden = self._rewrite_scalar_subqueries(
                    c, df, scope)
                hidden.extend(new_hidden)
            plain.append(c)
        if plain:
            merged = plain[0]
            for nxt in plain[1:]:
                merged = A.BinOp(op="AND", left=merged, right=nxt,
                                 line=nxt.line, col=nxt.col)
            df = df.filter(self.lower_expr(
                merged, Scope(df, scope.aliases, scope.visible, scope.display)))
        if hidden:
            # project the helper columns back out
            keep = [col(n) for n in scope.visible]
            df = df.select(*keep)
        return Scope(df, scope.aliases, scope.visible, scope.display)

    def _rewrite_scalar_subqueries(self, node: A.Node, df, scope: Scope):
        """Uncorrelated scalar subqueries -> cross join + hidden column
        (RewriteCorrelatedScalarSubquery's uncorrelated slice)."""
        from spark_rapids_tpu.plan import DataFrame
        hidden: List[str] = []

        def walk(x):
            nonlocal df
            if isinstance(x, A.ScalarSubquery):
                sub = self.lower_query(x.query)
                if len(sub.columns) != 1:
                    raise self.err(
                        "scalar subquery must produce exactly one "
                        f"column, got {sub.columns}", x)
                name = self.fresh_name("scalar_sq")
                sub = sub.select(col(sub.columns[0]).alias(name))
                df = DataFrame(
                    P.Join(df.plan, sub.plan, "cross", [], []),
                    self.session)
                hidden.append(name)
                return A.Ident(parts=(name,), line=x.line, col=x.col)
            if isinstance(x, A.InSubquery):
                raise self.unsup(
                    "IN subquery", "only supported as a top-level WHERE "
                    "conjunct (it rewrites to a semi join)", x)
            for f in ("left", "right", "operand", "low", "high",
                      "pattern"):
                sub = getattr(x, f, None)
                if isinstance(sub, A.Node):
                    setattr(x, f, walk(sub))
            if getattr(x, "args", None):
                x.args = [walk(a) if isinstance(a, A.Node) else a
                          for a in x.args]
            if getattr(x, "items", None) and not isinstance(x, A.Select):
                x.items = [walk(a) if isinstance(a, A.Node) else a
                           for a in x.items]
            return x

        node = walk(node)
        return node, df, hidden

    # -- select items --------------------------------------------------------
    def _expand_items(self, items: Sequence[A.Node],
                      scope: Scope) -> List[A.SelectItem]:
        out: List[A.SelectItem] = []
        for it in items:
            if isinstance(it, A.Star):
                if it.qualifier is not None:
                    m = scope.aliases.get(it.qualifier.lower())
                    if m is None:
                        raise self.err(
                            f"unknown relation alias {it.qualifier!r} "
                            f"in {it.qualifier}.* (known: "
                            f"{sorted(scope.aliases)})", it)
                    pairs = list(m.items())     # logical -> physical
                else:
                    pairs = [(scope.display.get(n, n), n)
                             for n in scope.visible]
                for logical, physical in pairs:
                    out.append(A.SelectItem(
                        expr=A.Ident(parts=(physical,), line=it.line,
                                     col=it.col),
                        alias=logical if logical != physical else None,
                        line=it.line, col=it.col))
            else:
                out.append(it)
        return out

    def _contains_agg_call(self, node: A.Node) -> bool:
        if isinstance(node, A.FuncCall) and node.window is None:
            if node.name.lower() in _AGG_NAMES:
                return True
        for ch in _ast_children(node):
            if self._contains_agg_call(ch):
                return True
        return False

    def _contains_subquery(self, node: A.Node) -> bool:
        if isinstance(node, (A.ScalarSubquery, A.InSubquery)):
            return True
        return any(self._contains_subquery(c) for c in _ast_children(node))

    def _contains_window(self, node: A.Node) -> bool:
        if isinstance(node, A.FuncCall) and node.window is not None:
            return True
        return any(self._contains_window(c) for c in _ast_children(node))

    # -- plain (non-aggregate) select ---------------------------------------
    def _lower_plain_select(self, scope: Scope,
                            items: List[A.SelectItem], sel: A.Select,
                            order_by=None):
        df = scope.df
        win_items = [it for it in items if self._contains_window(it.expr)]
        if win_items:
            df, items = self._apply_windows(scope, items, sel)
            scope = Scope(df, scope.aliases, scope.visible, scope.display)
        exprs: List[Expression] = []
        names: List[str] = []
        for i, it in enumerate(items):
            e = self.lower_expr(it.expr, scope.with_df(df))
            if it.alias is not None:
                name = it.alias
                exprs.append(Alias(e, name))
            elif isinstance(it.expr, A.Ident):
                # qualified refs over renamed join duplicates output the
                # SQL-level name, not the internal physical one
                name = it.expr.parts[-1]
                exprs.append(e if output_name(e, name) == name
                             else Alias(e, name))
            else:
                name = output_name(e, f"col{i}")
                exprs.append(e)
            names.append(name)
        pre_sorted = False
        if order_by and not sel.distinct and \
                not self._order_uses_output_only(order_by, names):
            # sort keys reference input columns the projection drops:
            # sort first, then project (Spark's Project-over-Sort)
            in_scope = scope.with_df(df)
            orders = [
                P.SortOrder(
                    self._presort_expr(s, exprs, names, in_scope),
                    s.ascending, s.nulls_first)
                for s in order_by]
            df = df.sort(*orders)
            pre_sorted = True
        if _is_identity(exprs, names, df):
            return df, names, pre_sorted
        return df.select(*exprs), names, pre_sorted

    def _order_uses_output_only(self, order_by, names: List[str]) -> bool:
        """True when every sort key resolves against the select output
        (ordinals, select aliases, or idents that survive projection)."""
        def idents(x):
            if isinstance(x, A.Ident):
                yield x
            for ch in _ast_children(x):
                yield from idents(ch)
        for s in order_by:
            if isinstance(s.expr, A.Literal) and isinstance(
                    s.expr.value, int):
                continue
            for ident in idents(s.expr):
                # qualified refs only resolve against the INPUT scope
                # (the projected output loses relation aliases)
                if len(ident.parts) > 1 or ident.parts[-1] not in names:
                    return False
        return True

    def _presort_expr(self, s: A.SortItem, exprs, names: List[str],
                      scope: Scope) -> Expression:
        """Sort key for a pre-projection sort: ordinals and select
        aliases map to the projected expression over the input."""
        def unalias(e):
            return e.children[0] if isinstance(e, Alias) else e
        if isinstance(s.expr, A.Literal) and isinstance(s.expr.value, int):
            pos = s.expr.value
            if not (1 <= pos <= len(exprs)):
                raise self.err(
                    f"ORDER BY position {pos} is out of range", s.expr)
            return unalias(exprs[pos - 1])
        if isinstance(s.expr, A.Ident) and len(s.expr.parts) == 1 \
                and s.expr.parts[0] in names \
                and s.expr.parts[0] not in scope.columns:
            return unalias(exprs[names.index(s.expr.parts[0])])
        return self.lower_expr(s.expr, scope)

    def _apply_windows(self, scope: Scope, items: List[A.SelectItem],
                       sel: A.Select):
        """Append window columns via WindowNode, rewriting the items to
        reference them (window exprs must be top-level select items)."""
        df = scope.df
        pairs: List[Tuple[str, Expression]] = []
        new_items: List[A.SelectItem] = []
        for i, it in enumerate(items):
            if not self._contains_window(it.expr):
                new_items.append(it)
                continue
            if not (isinstance(it.expr, A.FuncCall)
                    and it.expr.window is not None):
                raise self.unsup(
                    "window expression",
                    "window functions must be top-level select items "
                    "(wrap arithmetic over them in an outer SELECT)",
                    it)
            wexpr = self._lower_window_call(
                it.expr, scope.with_df(df))
            name = it.alias or f"col{i}"
            pairs.append((name, wexpr))
            new_items.append(A.SelectItem(
                expr=A.Ident(parts=(name,), line=it.line, col=it.col),
                alias=None, line=it.line, col=it.col))
        df = df._wrap(P.WindowNode(df.plan, pairs))
        return df, new_items

    def _lower_window_call(self, call: A.FuncCall, scope: Scope):
        from spark_rapids_tpu.ops.window import (
            WindowExpression,
            WindowFunction,
            WindowSpec,
        )
        fn = self._lower_func(call, scope, allow_window_fn=True)
        if not isinstance(fn, (WindowFunction, agg.AggregateFunction)):
            raise self.unsup(
                f"window function {call.name}",
                "only ranking/offset functions and aggregates may be "
                "used with OVER", call)
        w = call.window
        partition = [self.lower_expr(p, scope) for p in w.partition_by]
        orders = [self._sort_order(s, scope) for s in w.order_by]
        spec = WindowSpec(partition, orders, w.frame)
        return WindowExpression(fn, spec)

    def _sort_order(self, s: A.SortItem, scope: Scope) -> P.SortOrder:
        return P.SortOrder(self.lower_expr(s.expr, scope), s.ascending,
                           s.nulls_first)

    # -- aggregate select ----------------------------------------------------
    def _lower_aggregate(self, scope: Scope, items: List[A.SelectItem],
                         sel: A.Select):
        # 1. grouping expressions (support ordinals and select aliases)
        key_asts: List[A.Node] = []
        for g in sel.group_by:
            if isinstance(g, A.Literal) and isinstance(g.value, int) \
                    and not isinstance(g.value, bool):
                if not (1 <= g.value <= len(items)):
                    raise self.err(
                        f"GROUP BY position {g.value} is out of range "
                        f"(select list has {len(items)} items)", g)
                key_asts.append(items[g.value - 1].expr)
                continue
            if isinstance(g, A.Ident) and len(g.parts) == 1 \
                    and g.parts[0] not in scope.columns:
                match = [it for it in items if it.alias == g.parts[0]]
                if match:
                    key_asts.append(match[0].expr)
                    continue
            key_asts.append(g)
        keys = [self.lower_expr(k, scope) for k in key_asts]
        key_lookup = {k.key(): i for i, k in enumerate(keys)}
        key_names = [output_name(k, f"k{i}") for i, k in enumerate(keys)]

        # 2. classify select items; collect agg specs in select order
        agg_specs: List[Tuple[str, agg.AggregateFunction]] = []
        plan_items: List[Tuple[str, str, object]] = []
        need_project = False
        for i, it in enumerate(items):
            if self._contains_window(it.expr):
                raise self.unsup(
                    "window function in an aggregate query",
                    "compute the aggregate in a subquery, then apply "
                    "the window in an outer SELECT", it)
            e = self.lower_expr(it.expr, scope)
            k = _safe_key(e)
            if k is not None and k in key_lookup:
                kn = key_names[key_lookup[k]]
                name = it.alias or (
                    it.expr.parts[-1] if isinstance(it.expr, A.Ident)
                    else kn)
                plan_items.append(("key", kn, name))
                if name != kn:
                    need_project = True
                continue
            if isinstance(e, agg.AggregateFunction):
                name = it.alias or f"col{i}"
                agg_specs.append((name, e))
                plan_items.append(("agg", name, name))
                continue
            # composite: expression over aggregates / keys
            rewritten = self._rewrite_over_agg(
                e, key_lookup, key_names, agg_specs, it)
            name = it.alias or f"col{i}"
            plan_items.append(("expr", rewritten, name))
            need_project = True

        # 3. HAVING may add hidden aggregates
        having_pred = None
        n_visible_aggs = len(agg_specs)
        if sel.having is not None:
            he = self.lower_expr(
                self._subst_select_aliases(sel.having, items, scope), scope)
            having_pred = self._rewrite_over_agg(
                he, key_lookup, key_names, agg_specs, sel.having,
                hidden=True, select_items=plan_items)
        if len(agg_specs) > n_visible_aggs:
            need_project = True

        # 4. build Aggregate through the DSL path
        aliased = [Alias(fn, name) for name, fn in agg_specs]
        df = scope.df.group_by(*keys).agg(*aliased)
        if having_pred is not None:
            df = df.filter(having_pred)

        # 5. natural-output check: SELECT keys..., aggs... in plan order
        # needs no projection (the shape every DSL group_by().agg() has)
        natural = [("key", kn, kn) for kn in key_names] + \
            [("agg", n, n) for n, _ in agg_specs]
        if not need_project and plan_items == natural:
            return df, [p[2] for p in plan_items]
        out_exprs: List[Expression] = []
        names: List[str] = []
        for kind, payload, name in plan_items:
            base = col(payload) if kind in ("key", "agg") else payload
            out_exprs.append(Alias(base, name))
            names.append(name)
        return df.select(*out_exprs), names

    def _subst_select_aliases(self, node: A.Node, items, scope: Scope):
        """HAVING may reference select-list aliases (Spark resolves them
        after aggregation); substitute the aliased expression AST.  Real
        input columns win on a name clash (Spark's resolution order), and
        subqueries keep their own scope."""
        import dataclasses
        if isinstance(node, A.Ident) and len(node.parts) == 1 \
                and node.parts[0] not in scope.columns:
            for it in items:
                if isinstance(it, A.SelectItem) \
                        and it.alias == node.parts[0]:
                    return it.expr
        if not dataclasses.is_dataclass(node) or isinstance(node, A.Query):
            return node

        def walk(v):
            if isinstance(v, A.Query):
                return v
            if isinstance(v, A.Node):
                return self._subst_select_aliases(v, items, scope)
            if isinstance(v, (list, tuple)):
                return type(v)(walk(x) for x in v)
            return v

        changes = {}
        for f in dataclasses.fields(node):
            v = getattr(node, f.name)
            nv = walk(v)
            if nv is not v and nv != v:
                changes[f.name] = nv
        return dataclasses.replace(node, **changes) if changes else node

    def _rewrite_over_agg(self, e: Expression, key_lookup, key_names,
                          agg_specs, node: A.Node, hidden: bool = False,
                          select_items=None) -> Expression:
        """Replace grouping-expr / aggregate subtrees with references to
        the Aggregate's output columns; anything else referencing input
        columns is an error (Spark's 'neither grouped nor aggregated')."""
        k = _safe_key(e)
        if k is not None and k in key_lookup:
            return col(key_names[key_lookup[k]])
        if isinstance(e, agg.AggregateFunction):
            for name, fn in agg_specs:
                if fn.key() == e.key():
                    return col(name)
            name = self.fresh_name("hav") if hidden else \
                self.fresh_name("agg")
            agg_specs.append((name, e))
            return col(name)
        if isinstance(e, AttributeReference):
            # HAVING may reference select aliases of aggregates
            if select_items is not None:
                for kind, payload, name in select_items:
                    if name == e.col_name and kind in ("key", "agg"):
                        return col(payload)
                    if name == e.col_name:
                        return payload
            raise self.err(
                f"column {e.col_name!r} must appear in GROUP BY or be "
                "inside an aggregate function", node)
        if not e.children:
            return e
        return e.with_children([
            self._rewrite_over_agg(c, key_lookup, key_names, agg_specs,
                                   node, hidden, select_items)
            for c in e.children])

    # -- ORDER BY / LIMIT ----------------------------------------------------
    def _apply_order(self, df, order_by: Sequence[A.SortItem]):
        out_cols = df.columns
        orders: List[P.SortOrder] = []
        scope = Scope(df, {})
        for s in order_by:
            if isinstance(s.expr, A.Literal) and isinstance(s.expr.value,
                                                            int):
                pos = s.expr.value
                if not (1 <= pos <= len(out_cols)):
                    raise self.err(
                        f"ORDER BY position {pos} is out of range", s.expr)
                e: Expression = col(out_cols[pos - 1])
            else:
                if self._contains_agg_call(s.expr):
                    raise self.unsup(
                        "aggregate in ORDER BY",
                        "alias the aggregate in the select list and "
                        "order by the alias", s.expr)
                e = self.lower_expr(s.expr, scope)
            orders.append(P.SortOrder(e, s.ascending, s.nulls_first))
        return df.sort(*orders)

    # -- expressions ---------------------------------------------------------
    def lower_expr(self, node: A.Node, scope: Scope) -> Expression:
        if isinstance(node, A.Literal):
            return self._literal(node)
        if isinstance(node, A.TypedLiteral):
            return self._typed_literal(node)
        if isinstance(node, A.IntervalLiteral):
            raise self.unsup(
                "standalone INTERVAL value",
                "intervals are only supported in date +/- INTERVAL "
                "arithmetic", node)
        if isinstance(node, A.Ident):
            return self._ident(node, scope)
        if isinstance(node, A.BinOp):
            return self._binop(node, scope)
        if isinstance(node, A.UnOp):
            if node.op == "NOT":
                return ~self.lower_expr(node.operand, scope)
            inner = node.operand
            if isinstance(inner, A.Literal) and isinstance(
                    inner.value, (int, float)) and not isinstance(
                    inner.value, bool):
                return lit(-inner.value)
            return -self.lower_expr(inner, scope)
        if isinstance(node, A.IsNull):
            e = self.lower_expr(node.operand, scope)
            return e.isnotnull() if node.negated else e.isnull()
        if isinstance(node, A.InList):
            from spark_rapids_tpu.ops.predicates import In
            e = In(self.lower_expr(node.operand, scope),
                   [self.lower_expr(i, scope) for i in node.items])
            return ~e if node.negated else e
        if isinstance(node, A.InSubquery):
            raise self.unsup(
                "IN subquery", "only supported as a top-level WHERE "
                "conjunct (it rewrites to a semi join)", node)
        if isinstance(node, A.ScalarSubquery):
            raise self.unsup(
                "scalar subquery", "only supported inside WHERE (it "
                "rewrites to a cross join)", node)
        if isinstance(node, A.Between):
            e = self.lower_expr(node.operand, scope)
            lo = self.lower_expr(node.low, scope)
            hi = self.lower_expr(node.high, scope)
            out = (e >= lo) & (e <= hi)
            return ~out if node.negated else out
        if isinstance(node, A.LikeOp):
            from spark_rapids_tpu.ops.strings import Like, RLike
            e = self.lower_expr(node.operand, scope)
            pat = self.lower_expr(node.pattern, scope)
            out = Like(e, pat) if node.kind == "like" else RLike(e, pat)
            return ~out if node.negated else out
        if isinstance(node, A.Cast):
            try:
                dt = T.parse_type(node.type_name)
            except TypeError as exc:
                raise self.err(str(exc), node)
            return self.lower_expr(node.operand, scope).cast(dt)
        if isinstance(node, A.Case):
            return self._case(node, scope)
        if isinstance(node, A.FuncCall):
            if node.window is not None:
                return self._lower_window_call(node, scope)
            return self._lower_func(node, scope)
        if isinstance(node, A.Star):
            raise self.err("'*' is only valid in the select list or "
                           "count(*)", node)
        raise self.err(
            f"unsupported expression {type(node).__name__}", node)

    def _literal(self, node: A.Literal) -> Expression:
        import decimal
        v = node.value
        if isinstance(v, decimal.Decimal):
            tup = v.as_tuple()
            scale = max(-tup.exponent, 0)
            # positive exponents widen the integer part: 1E2BD is 100 =
            # decimal(3,0), not decimal(1,0) (code-review fix — the old
            # precision left CheckOverflow nulling 1E2BD + 1BD)
            digits = len(tup.digits) + max(tup.exponent, 0)
            precision = max(digits, scale)
            unscaled = int(v.scaleb(scale))
            return Literal(unscaled, T.DecimalType(precision, scale))
        return lit(v)

    def _typed_literal(self, node: A.TypedLiteral) -> Expression:
        import datetime as _dt
        try:
            if node.kind == "date":
                return lit(_dt.date.fromisoformat(node.text))
            v = _dt.datetime.fromisoformat(node.text)
            return lit(v)
        except ValueError as exc:
            raise self.err(
                f"cannot parse {node.kind.upper()} literal "
                f"{node.text!r}: {exc}", node)

    def _ident(self, node: A.Ident, scope: Scope) -> Expression:
        if len(node.parts) == 1:
            name = node.parts[0]
            if name not in scope.columns:
                raise self.err(
                    f"cannot resolve column {name!r} "
                    f"(in scope: {scope.columns})", node)
            return col(name)
        if len(node.parts) == 2:
            qual, name = node.parts
            cols = scope.aliases.get(qual.lower())
            if cols is None:
                raise self.err(
                    f"unknown relation alias {qual!r} (known: "
                    f"{sorted(scope.aliases)})", node)
            if name not in cols:
                raise self.err(
                    f"column {name!r} not found in {qual!r} "
                    f"(columns: {list(cols)})", node)
            return col(cols[name])
        raise self.unsup(
            ".".join(node.parts),
            "only col and alias.col references are supported", node)

    def _binop(self, node: A.BinOp, scope: Scope) -> Expression:
        op = node.op
        if op == "AND":
            return self.lower_expr(node.left, scope) & \
                self.lower_expr(node.right, scope)
        if op == "OR":
            return self.lower_expr(node.left, scope) | \
                self.lower_expr(node.right, scope)
        # date +/- INTERVAL folds onto DateAdd/DateSub/AddMonths
        if op in ("+", "-") and isinstance(node.right, A.IntervalLiteral):
            return self._date_interval(node, scope)
        if op == "+" and isinstance(node.left, A.IntervalLiteral):
            flipped = A.BinOp(op="+", left=node.right, right=node.left,
                              line=node.line, col=node.col)
            return self._date_interval(flipped, scope)
        left = self.lower_expr(node.left, scope)
        right = self.lower_expr(node.right, scope)
        if op == "+":
            return left + right
        if op == "-":
            return left - right
        if op == "*":
            return left * right
        if op == "/":
            return left / right
        if op == "%":
            return left % right
        if op == "||":
            from spark_rapids_tpu.ops.strings import Concat
            return Concat(left, right)
        if op == "=":
            return left == right
        if op == "<=>":
            # null-safe equal: NEVER null (code-review fix: the previous
            # (isnull&isnull)|(==) lowering returned NULL when exactly
            # one side was null, so NOT(a <=> b) dropped rows)
            from spark_rapids_tpu.ops.predicates import EqualNullSafe
            return EqualNullSafe(left, right)
        if op == "<>":
            return left != right
        if op == "<":
            return left < right
        if op == "<=":
            return left <= right
        if op == ">":
            return left > right
        if op == ">=":
            return left >= right
        raise self.err(f"unsupported operator {op!r}", node)

    def _date_interval(self, node: A.BinOp, scope: Scope) -> Expression:
        from spark_rapids_tpu.ops.datetime import AddMonths, DateAdd, DateSub
        iv: A.IntervalLiteral = node.right
        e = self.lower_expr(node.left, scope)
        sign = 1 if node.op == "+" else -1
        if iv.months:
            e = AddMonths(e, lit(sign * iv.months))
        if iv.days:
            if node.op == "+":
                e = DateAdd(e, lit(iv.days))
            else:
                e = DateSub(e, lit(iv.days))
        return e

    def _case(self, node: A.Case, scope: Scope) -> Expression:
        from spark_rapids_tpu.ops.conditional import CaseWhen
        flat: List[Expression] = []
        operand = (self.lower_expr(node.operand, scope)
                   if node.operand is not None else None)
        for c, v in node.branches:
            ce = self.lower_expr(c, scope)
            if operand is not None:
                ce = operand == ce
            flat.append(ce)
            flat.append(self.lower_expr(v, scope))
        if node.else_value is not None:
            flat.append(self.lower_expr(node.else_value, scope))
        return CaseWhen(*flat)

    def _lower_func(self, node: A.FuncCall, scope: Scope,
                    allow_window_fn: bool = False) -> Expression:
        name = node.name
        if node.distinct:
            raise self.unsup(
                f"{name}(DISTINCT ...)",
                "distinct aggregates are not supported; use a "
                "subquery with GROUP BY", node)
        # count(*) / count(1) count rows
        if name.lower() == "count" and (
                (len(node.args) == 1 and isinstance(node.args[0], A.Star))
                or (len(node.args) == 1
                    and isinstance(node.args[0], A.Literal)
                    and node.args[0].value == 1)
                or not node.args):
            return agg.Count()
        builder = registry.lookup(name, self.session)
        if builder is None:
            raise self.err(
                f"undefined function {name!r} (not a builtin, "
                "registered UDF, or Hive UDF)", node)
        args = []
        for a in node.args:
            if isinstance(a, A.Star):
                raise self.err(
                    f"'*' argument is only valid in count(*)", a)
            args.append(self.lower_expr(a, scope))
        try:
            return builder(args)
        except SqlAnalysisError as exc:
            raise self.err(exc.raw_msg, node)
        except (TypeError, ValueError) as exc:
            raise self.err(f"function {name}: {exc}", node)


#: function names that produce AggregateFunction expressions — used to
#: decide whether a select needs the aggregate lowering path
_AGG_NAMES = {
    "sum", "min", "max", "avg", "mean", "count", "first", "last",
    "collect_list", "collect_set", "percentile", "approx_percentile",
    "stddev", "stddev_samp", "std", "stddev_pop", "variance",
    "var_samp", "var_pop",
}


def _ast_children(node: A.Node):
    for f in ("left", "right", "operand", "low", "high", "pattern",
              "else_value", "expr"):
        sub = getattr(node, f, None)
        if isinstance(sub, A.Node):
            yield sub
    for a in getattr(node, "args", ()) or ():
        if isinstance(a, A.Node):
            yield a
    for a in getattr(node, "items", ()) or ():
        if isinstance(a, A.Node):
            yield a
        elif isinstance(a, A.SelectItem):
            yield a.expr
    for c, v in getattr(node, "branches", ()) or ():
        yield c
        yield v


def _split_conjuncts(node: A.Node) -> List[A.Node]:
    if isinstance(node, A.BinOp) and node.op == "AND":
        return _split_conjuncts(node.left) + _split_conjuncts(node.right)
    return [node]


def _safe_key(e: Expression):
    try:
        return e.key()
    except Exception:
        return None


def _is_identity(exprs: List[Expression], names: List[str], df) -> bool:
    """SELECT of exactly the child's columns in order -> elide Project
    (keeps SQL plan shapes equal to their DSL forms)."""
    cols = df.columns
    if len(exprs) != len(cols):
        return False
    for e, n, c in zip(exprs, names, cols):
        if not isinstance(e, AttributeReference):
            return False
        if e.col_name != c or n != c:
            return False
    return True


def lower_statement(session, sql_text: str):
    """Parse + analyze one SQL statement into a DataFrame."""
    from spark_rapids_tpu.sql.parser import parse_statement
    stmt = parse_statement(sql_text)
    return Analyzer(session, sql_text).lower_statement(stmt)
