"""Untyped SQL AST (parser output, analyzer input).

Plain dataclasses: no engine types appear here — the analyzer owns the
mapping onto ops/ expressions and plan/ nodes. Every node carries the
1-based (line, col) of its first token so analysis errors can point into
the query text."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple


@dataclass
class Node:
    line: int = 0
    col: int = 0


# -- expressions -------------------------------------------------------------

@dataclass
class Ident(Node):
    """Possibly-qualified column reference: parts = [col] or [tbl, col]."""
    parts: Tuple[str, ...] = ()


@dataclass
class Star(Node):
    """`*` or `tbl.*` (select list / count(*))."""
    qualifier: Optional[str] = None


@dataclass
class Literal(Node):
    value: object = None           # int/float/Decimal/str/bool/None


@dataclass
class TypedLiteral(Node):
    """DATE '...' / TIMESTAMP '...'."""
    kind: str = ""                 # "date" | "timestamp"
    text: str = ""


@dataclass
class IntervalLiteral(Node):
    """INTERVAL <n> <unit> [<n> <unit>...] folded to (months, days).
    Only consumed by date +/- interval (the engine has no standalone
    interval columns)."""
    months: int = 0
    days: int = 0


@dataclass
class BinOp(Node):
    op: str = ""                   # +,-,*,/,%,=,<>,<,<=,>,>=,AND,OR,||
    left: "Node" = None
    right: "Node" = None


@dataclass
class UnOp(Node):
    op: str = ""                   # -, NOT
    operand: "Node" = None


@dataclass
class IsNull(Node):
    operand: "Node" = None
    negated: bool = False


@dataclass
class InList(Node):
    operand: "Node" = None
    items: Sequence["Node"] = ()
    negated: bool = False


@dataclass
class InSubquery(Node):
    operand: "Node" = None
    query: "Query" = None
    negated: bool = False


@dataclass
class Between(Node):
    operand: "Node" = None
    low: "Node" = None
    high: "Node" = None
    negated: bool = False


@dataclass
class LikeOp(Node):
    kind: str = "like"             # like | rlike
    operand: "Node" = None
    pattern: "Node" = None
    negated: bool = False


@dataclass
class Cast(Node):
    operand: "Node" = None
    type_name: str = ""


@dataclass
class Case(Node):
    """CASE [operand] WHEN c THEN v ... [ELSE e] END."""
    operand: Optional["Node"] = None
    branches: Sequence[Tuple["Node", "Node"]] = ()
    else_value: Optional["Node"] = None


@dataclass
class FrameBound:
    """None = UNBOUNDED, 0 = CURRENT ROW, +/-n = FOLLOWING/PRECEDING."""
    value: Optional[int] = None


@dataclass
class WindowDef(Node):
    partition_by: Sequence["Node"] = ()
    order_by: Sequence["SortItem"] = ()
    frame: Optional[Tuple[str, Optional[int], Optional[int]]] = None


@dataclass
class FuncCall(Node):
    name: str = ""
    args: Sequence["Node"] = ()
    distinct: bool = False
    window: Optional[WindowDef] = None


@dataclass
class ScalarSubquery(Node):
    query: "Query" = None


# -- relations ---------------------------------------------------------------

@dataclass
class TableRef(Node):
    name: str = ""
    alias: Optional[str] = None


@dataclass
class SubqueryRef(Node):
    query: "Query" = None
    alias: Optional[str] = None


@dataclass
class JoinRel(Node):
    left: "Node" = None
    right: "Node" = None
    how: str = "inner"             # inner|left|right|full|cross
    on: Optional["Node"] = None
    using: Sequence[str] = ()


# -- query structure ---------------------------------------------------------

@dataclass
class SelectItem(Node):
    expr: "Node" = None
    alias: Optional[str] = None


@dataclass
class SortItem(Node):
    expr: "Node" = None
    ascending: bool = True
    nulls_first: Optional[int] = None   # None = Spark default


@dataclass
class Select(Node):
    distinct: bool = False
    hints: Sequence[Tuple[str, Sequence[str]]] = ()
    items: Sequence["Node"] = ()        # SelectItem | Star
    from_: Optional["Node"] = None      # TableRef | SubqueryRef | JoinRel
    where: Optional["Node"] = None
    group_by: Sequence["Node"] = ()
    having: Optional["Node"] = None


@dataclass
class SetOp(Node):
    op: str = "unionall"                # unionall | union
    left: "Node" = None
    right: "Node" = None


@dataclass
class Query(Node):
    ctes: Sequence[Tuple[str, "Query"]] = ()
    body: "Node" = None                 # Select | SetOp
    order_by: Sequence[SortItem] = ()
    limit: Optional[int] = None


@dataclass
class CreateView(Node):
    name: str = ""
    replace: bool = False
    query: Optional[Query] = None
    using: Optional[str] = None         # file format for USING variant
    options: dict = field(default_factory=dict)


@dataclass
class DropView(Node):
    name: str = ""
    if_exists: bool = False
