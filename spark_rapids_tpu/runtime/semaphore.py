"""Task semaphore limiting concurrent device users.

Reference: GpuSemaphore.scala (SURVEY.md §2.5) — bounds how many tasks hold
device residency at once (spark.rapids.sql.concurrentGpuTasks), tracks wait
time, and can dump stacks when acquisition stalls. Here a "task" is a query
thread; the semaphore gates entry to device execution so concurrent queries
do not blow HBM."""

from __future__ import annotations

import sys
import threading
import time
import traceback
from typing import Dict, Optional

from spark_rapids_tpu.errors import SemaphoreTimeoutError
from spark_rapids_tpu.obs.metrics import metric_scope, register_metric
from spark_rapids_tpu.lockorder import ordered_condition, ordered_lock

# acquisition accounting lives in the unified registry's ``semaphore``
# scope (obs/metrics.py) so the event log diffs it per query like the
# spill/recovery/shuffle scopes
register_metric("acquireWaitTime", "timing", "ESSENTIAL",
                "wall time queries spent waiting for a device "
                "concurrency slot (TpuSemaphore)")
register_metric("acquires", "count", "ESSENTIAL",
                "TpuSemaphore slot acquisitions (first acquisition per "
                "holder; reentrant re-entries not counted)")
register_metric("acquireTimeouts", "count", "ESSENTIAL",
                "TpuSemaphore acquisitions abandoned on timeout "
                "(SemaphoreTimeoutError)")


class TpuSemaphore:
    _instance: Optional["TpuSemaphore"] = None
    _instance_lock = ordered_lock("semaphore.instance")

    def __init__(self, max_tasks: int, stall_dump_seconds: float = 60.0):
        self.max_tasks = max_tasks
        self.stall_dump_seconds = stall_dump_seconds
        self._lock = ordered_condition("semaphore.cond")
        self._holders: Dict[int, int] = {}  # thread id -> reentrant depth
        self._metrics = metric_scope("semaphore")
        self.total_wait_seconds = 0.0
        self.acquire_count = 0
        self.timeout_count = 0

    @classmethod
    def initialize(cls, max_tasks: int) -> "TpuSemaphore":
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = TpuSemaphore(max_tasks)
            elif cls._instance.max_tasks != max_tasks:
                # adjust the LIVE semaphore in place: holders/waiters carry
                # over so the concurrency cap is never bypassed
                inst = cls._instance
                with inst._lock:
                    inst.max_tasks = max_tasks
                    inst._lock.notify_all()
            return cls._instance

    @classmethod
    def current(cls) -> Optional["TpuSemaphore"]:
        return cls._instance

    def acquire_if_necessary(self, timeout: Optional[float] = None):
        """Reentrant per thread (a task that already holds it proceeds)."""
        tid = threading.get_ident()
        t0 = time.perf_counter()
        deadline = None if timeout is None else t0 + timeout
        with self._lock:
            if tid in self._holders:
                self._holders[tid] += 1
                return
            dumped = False
            while len(self._holders) >= self.max_tasks:
                remaining = None if deadline is None else deadline - time.perf_counter()
                if remaining is not None and remaining <= 0:
                    self.timeout_count += 1
                    self._metrics.add("acquireTimeouts", 1)
                    self._metrics.add("acquireWaitTime",
                                      time.perf_counter() - t0)
                    raise SemaphoreTimeoutError(
                        f"TpuSemaphore: {self.max_tasks} tasks already on "
                        f"device after waiting "
                        f"{time.perf_counter() - t0:.3f}s")
                waited = time.perf_counter() - t0
                if not dumped and waited > self.stall_dump_seconds:
                    self._dump_stacks()
                    dumped = True
                self._lock.wait(timeout=min(remaining or 1.0, 1.0))
            self._holders[tid] = 1
            self.acquire_count += 1
            waited = time.perf_counter() - t0
            self.total_wait_seconds += waited
            self._metrics.add("acquires", 1)
            self._metrics.add("acquireWaitTime", waited)

    def release_if_held(self):
        tid = threading.get_ident()
        with self._lock:
            depth = self._holders.get(tid)
            if depth is None:
                return
            if depth > 1:
                self._holders[tid] = depth - 1
            else:
                del self._holders[tid]
                self._lock.notify_all()

    def _dump_stacks(self):
        """Deadlock diagnostics (reference: dumpStackTracesOnFailureToAcquire)."""
        frames = sys._current_frames()
        print("TpuSemaphore: stalled acquisition; holder stacks:", file=sys.stderr)
        for tid in self._holders:
            frame = frames.get(tid)
            if frame:
                traceback.print_stack(frame, file=sys.stderr)

    @property
    def holders(self) -> int:
        with self._lock:
            return len(self._holders)


class acquired:
    """Context manager: with acquired(sem): ... (no-op when sem is None)."""

    def __init__(self, sem: Optional[TpuSemaphore]):
        self.sem = sem

    def __enter__(self):
        if self.sem is not None:
            self.sem.acquire_if_necessary()
        return self.sem

    def __exit__(self, *exc):
        if self.sem is not None:
            self.sem.release_if_held()
        return False
