"""DECIMAL128 column storage (reference: TypeChecks.scala:613 DECIMAL_128
tier; SURVEY.md §2.9): two-limb (hi i64, lo u64) device columns flowing
through scan/filter/compare/sort/group/join/collect, with per-op fallback
for the still-unimplemented arithmetic/agg-value kernels."""

import decimal as pydec

import numpy as np
import pytest

from spark_rapids_tpu import functions as F
from spark_rapids_tpu import types as T
from spark_rapids_tpu.ops.expr import col
from tests.asserts import assert_runs_on_tpu

P38 = T.DecimalType(38, 2)
MAX38 = 10**38 - 1


def _vals(n=400, seed=0, with_bounds=True):
    rng = np.random.default_rng(seed)
    out = []
    for i in range(n):
        r = rng.random()
        if r < 0.05:
            out.append(None)
        elif r < 0.15:
            # beyond int64: exercise both limbs
            out.append(int(rng.integers(-10**6, 10**6)) * 10**22 + 7)
        else:
            out.append(int(rng.integers(-10**9, 10**9)))
    if with_bounds:
        out[0] = MAX38
        out[1] = -MAX38
        out[2] = (1 << 64) + 1     # lo-limb carry boundary
        out[3] = -(1 << 64) - 1
        out[4] = (1 << 63)         # lo limb sign boundary
    return out


def _df(s, vals=None, name="d", extra=None):
    data = {name: _vals() if vals is None else vals}
    dtypes = {name: P38}
    if extra:
        for k, v in extra.items():
            data[k] = v
    return s.create_dataframe(data, dtypes=dtypes)


# -- storage roundtrip -------------------------------------------------------

def test_roundtrip_p38(session):
    vals = _vals()
    got = [r[0] for r in _df(session, vals).collect()]
    assert got == vals  # decimals are BIT-exact (unscaled ints)


def test_roundtrip_boundaries(session):
    vals = [MAX38, -MAX38, 0, None, 1, -1, (1 << 64), -(1 << 64),
            (1 << 63) - 1, (1 << 63), -(1 << 63), 10**19, -(10**19)]
    got = [r[0] for r in _df(session, vals).collect()]
    assert got == vals


def test_scan_runs_on_tpu(session):
    assert_runs_on_tpu(lambda s: _df(s).select("d"), session)


# -- compare / filter --------------------------------------------------------

def test_compare_two_columns(session, cpu_session):
    a = _vals(300, seed=1)
    b = _vals(300, seed=2)

    def q(s):
        df = s.create_dataframe({"a": a, "b": b},
                                dtypes={"a": P38, "b": P38})
        return df.select((col("a") < col("b")).alias("lt"),
                         (col("a") == col("b")).alias("eq"),
                         (col("a") >= col("b")).alias("ge"))

    assert q(session).collect() == q(cpu_session).collect()
    assert_runs_on_tpu(q, session)


def test_filter_by_comparison(session, cpu_session):
    a = _vals(300, seed=3)
    b = _vals(300, seed=4)

    def q(s):
        df = s.create_dataframe({"a": a, "b": b},
                                dtypes={"a": P38, "b": P38})
        return df.filter(col("a") > col("b"))

    got = sorted(q(session).collect(), key=repr)
    want = sorted(q(cpu_session).collect(), key=repr)
    assert got == want and len(got) > 0


# -- sort --------------------------------------------------------------------

def test_sort_by_p38_key(session, cpu_session):
    vals = _vals(500, seed=5)

    def q(s):
        return _df(s, vals).sort("d")

    got = [r[0] for r in q(session).collect()]
    want = [r[0] for r in q(cpu_session).collect()]
    assert got == want
    assert_runs_on_tpu(q, session)


def test_sort_descending(session, cpu_session):
    vals = _vals(200, seed=6)
    got = [r[0] for r in _df(session, vals)
           .sort("d", ascending=False).collect()]
    want = [r[0] for r in _df(cpu_session, vals)
            .sort("d", ascending=False).collect()]
    assert got == want


# -- group-by key / join key -------------------------------------------------

def test_group_by_p38_key(session, cpu_session):
    keys = [MAX38, -MAX38, (1 << 64) + 5, None]
    rng = np.random.default_rng(7)
    n = 300
    kcol = [keys[i] for i in rng.integers(0, len(keys), n)]
    vcol = rng.integers(0, 100, n).astype(np.int64)

    def q(s):
        df = s.create_dataframe({"k": kcol, "v": vcol}, dtypes={"k": P38})
        return df.group_by("k").agg(F.count("v").alias("c"),
                                    F.sum("v").alias("sv"))

    got = sorted(q(session).collect(), key=repr)
    want = sorted(q(cpu_session).collect(), key=repr)
    assert got == want and len(got) == 4


def test_join_on_p38_key(session, cpu_session):
    keys = [MAX38 - i for i in range(20)] + [-(1 << 64) - i
                                             for i in range(20)]
    rng = np.random.default_rng(8)
    lk = [keys[i] for i in rng.integers(0, 40, 200)]
    rk = keys[::2]

    def q(s):
        left = s.create_dataframe(
            {"k": lk, "v": np.arange(200, dtype=np.int64)},
            dtypes={"k": P38})
        right = s.create_dataframe(
            {"k": rk, "w": np.arange(20, dtype=np.int64)},
            dtypes={"k": P38})
        return left.join(right, on=["k"], how="inner")

    got = sorted(q(session).collect(), key=repr)
    want = sorted(q(cpu_session).collect(), key=repr)
    assert got == want and len(got) > 0


# -- multi-batch / masked flow ----------------------------------------------

def test_multibatch_concat_and_filter(session, cpu_session):
    vals = _vals(600, seed=9)

    def q(s):
        from spark_rapids_tpu.ops.predicates import IsNotNull
        df = s.create_dataframe({"d": vals}, dtypes={"d": P38},
                                num_batches=3)
        return df.filter(IsNotNull(col("d"))).sort("d")

    got = [r[0] for r in q(session).collect()]
    want = [r[0] for r in q(cpu_session).collect()]
    assert got == want


# -- honest fallback for unimplemented kernels -------------------------------

def test_avg_over_p38_falls_back_with_reason(session, cpu_session):
    vals = [10**20, 2 * 10**20, None, 5]

    def q(s):
        return _df(s, vals).agg(F.count("d").alias("c"))

    # count (and sum/min/max — see the agg kernel tests) run on device
    assert q(session).collect() == q(cpu_session).collect() == [(3,)]

    avg_df = _df(session, vals).agg(F.avg("d").alias("a"))
    plan = avg_df.explain()
    assert "decimal(>18)" in plan, plan
    # and the fallback answers exactly what the CPU oracle answers
    assert avg_df.collect() == \
        _df(cpu_session, vals).agg(F.avg("d").alias("a")).collect()


def test_matrix_reports_dec128_storage(session):
    from spark_rapids_tpu.overrides.docs import generate_supported_ops
    md = generate_supported_ops()
    row = next(ln for ln in md.splitlines()
               if ln.startswith("| BoundReference"))
    cells = [c.strip() for c in row.split("|")]
    assert cells[13] == "S", row  # DECIMAL128 column (see _TYPE_COLUMNS)


def test_shuffle_serializer_roundtrip():
    from spark_rapids_tpu.columnar import HostTable
    from spark_rapids_tpu.shuffle.serializer import pack_table, unpack_table
    vals = _vals(100, seed=10)
    t = HostTable.from_pydict({"d": vals, "x": list(range(100))},
                              dtypes={"d": P38})
    back, _ = unpack_table(pack_table(t))
    assert back.to_pydict()["d"] == vals
    assert back.columns[0].dtype == P38


def test_repartition_with_p38_payload(session, cpu_session):
    """Repartition by an INT key with a dec128 payload column rides the
    shuffle; hash-partitioning BY a dec128 key falls back with a
    reason."""
    vals = _vals(300, seed=11)
    rng = np.random.default_rng(12)
    k = rng.integers(0, 5, 300).astype(np.int64)

    def q(s):
        df = s.create_dataframe({"k": k, "d": vals}, dtypes={"d": P38})
        return df.repartition(4, "k")

    got = sorted(q(session).collect(), key=repr)
    want = sorted(q(cpu_session).collect(), key=repr)
    assert got == want

    # hash-partitioning BY a dec128 key: two-limb long-pair murmur3,
    # device and host partitioners agree
    by_dec = _df(session, vals).repartition(4, "d")
    assert "decimal(>18)" not in by_dec.explain()
    assert sorted(r[0] for r in by_dec.collect() if r[0] is not None) \
        == sorted(v for v in vals if v is not None)
    cpu_rows = sorted(
        r[0] for r in _df(cpu_session, vals).repartition(4, "d").collect()
        if r[0] is not None)
    assert cpu_rows == sorted(v for v in vals if v is not None)


def test_null_safe_equality(session, cpu_session):
    """<=> over p38 columns (two-limb device equality; review fix)."""
    from spark_rapids_tpu.ops.predicates import EqualNullSafe
    a = [MAX38, None, 5, None, (1 << 64) + 1]
    b = [MAX38, None, 6, 7, (1 << 64) + 1]

    def q(s):
        df = s.create_dataframe({"a": a, "b": b},
                                dtypes={"a": P38, "b": P38})
        return df.select(EqualNullSafe(col("a"), col("b")).alias("e"))

    got = [r[0] for r in q(session).collect()]
    assert got == [r[0] for r in q(cpu_session).collect()]
    assert got == [True, True, False, False, True]
    assert_runs_on_tpu(q, session)


def test_ici_mode_with_p38_payload_rides_the_collective(cpu_session):
    """ICI shuffle mode + dec128 payload: the mesh-native exchange
    scatters trailing dims along for the ride, so the two-limb layout
    now RIDES the collective instead of demoting to the host shuffle
    (the pre-mesh 1-D-only limitation is gone — results must still be
    exact)."""
    from spark_rapids_tpu.session import TpuSession
    vals = _vals(200, seed=13)
    rng = np.random.default_rng(14)
    k = rng.integers(0, 4, 200).astype(np.int64)
    ici = TpuSession({"spark.rapids.shuffle.mode": "ICI"})

    def q(s):
        df = s.create_dataframe({"k": k, "d": vals}, dtypes={"d": P38})
        return df.repartition(4, "k")

    got = sorted(q(ici).collect(), key=repr)
    want = sorted(q(cpu_session).collect(), key=repr)
    assert got == want
    assert "iciPartitions=4" in ici.last_metrics()


def test_parquet_scan_p38(session, cpu_session, tmp_path):
    """Arrow ingestion of decimal(>18) parquet produces object-int host
    columns and two-limb device columns (review fix — used to raise at
    scan time)."""
    import pyarrow as pa
    import pyarrow.parquet as pq
    with pydec.localcontext() as ctx:
        ctx.prec = 50  # default 28 silently rounds 38-digit decimals
        vals = [pydec.Decimal(v).scaleb(-2) if v is not None else None
                for v in _vals(120, seed=15)]
    pq.write_table(
        pa.table({"d": pa.array(vals, type=pa.decimal128(38, 2))}),
        tmp_path / "t.parquet")

    def q(s):
        return s.read_parquet(str(tmp_path / "t.parquet")).sort("d")

    got = [r[0] for r in q(session).collect()]
    want = [r[0] for r in q(cpu_session).collect()]
    assert got == want
    # unscaled int equality against the source values
    with pydec.localcontext() as ctx:
        ctx.prec = 50
        src = sorted(int(v.scaleb(2)) for v in vals if v is not None)
    assert [g for g in got if g is not None] == src


def test_outer_join_null_side_p38(session, cpu_session):
    """Outer-join null sides build (cap, 2) limb columns (review fix —
    1-D zeros used to corrupt/crash the dec128 payload)."""
    lk = np.array([0, 1, 2, 3], dtype=np.int64)
    rk = np.array([2, 3, 4, 5], dtype=np.int64)
    dvals = [MAX38, -(1 << 64) - 3, 7, None]

    def q(s, how):
        left = s.create_dataframe({"k": lk, "v": np.arange(4, dtype=np.int64)})
        right = s.create_dataframe({"k": rk, "d": dvals}, dtypes={"d": P38})
        return left.join(right, on=["k"], how=how)

    for how in ("left", "full"):
        got = sorted(q(session, how).collect(), key=repr)
        want = sorted(q(cpu_session, how).collect(), key=repr)
        assert got == want, how


def test_window_partition_by_p38_key(session, cpu_session):
    """rank() over PARTITION BY dec128 / ORDER BY dec128 (review fix —
    the window kernels' key zeroing was 1-D-only)."""
    keys = [MAX38, -(1 << 64), 5]
    rng = np.random.default_rng(16)
    n = 90
    k = [keys[i] for i in rng.integers(0, 3, n)]
    o = [int(x) * 10**20 for x in rng.integers(-50, 50, n)]

    def q(s):
        df = s.create_dataframe(
            {"k": k, "o": o, "v": np.arange(n, dtype=np.int64)},
            dtypes={"k": P38, "o": P38})
        return df.with_windows(
            rn=F.row_number().over(
                __import__("spark_rapids_tpu.ops.window",
                           fromlist=["Window"]).Window
                .partition_by("k").order_by("o")))

    got = sorted(q(session).collect(), key=repr)
    want = sorted(q(cpu_session).collect(), key=repr)
    assert got == want


# -- dec128 aggregate kernels (exact limb sums, two-limb min/max) ------------

def test_sum_p38_exact_on_device(session, cpu_session):
    """sum(decimal) is EXACT (limb sums, not an f64 ride) for both the
    dec128 and decimal64 storage tiers."""
    keys = np.array([0, 1, 0, 1, 0], dtype=np.int64)
    vals = [10**30 + 1, -(10**25), 10**30 + 2, 5, 7]

    def q(s):
        df = s.create_dataframe({"k": keys, "d": vals}, dtypes={"d": P38})
        return df.group_by("k").agg(F.sum("d").alias("s"),
                                    F.min("d").alias("mn"),
                                    F.max("d").alias("mx"))

    got = sorted(q(session).collect())
    want = sorted(q(cpu_session).collect())
    assert got == want
    by_k = {r[0]: r[1:] for r in got}
    assert by_k[0] == (2 * 10**30 + 10, 7, 10**30 + 2)
    assert by_k[1] == (-(10**25) + 5, -(10**25), 5)
    assert_runs_on_tpu(
        lambda s: s.create_dataframe({"k": keys, "d": vals},
                                     dtypes={"d": P38})
        .group_by("k").agg(F.sum("d").alias("s")), session)


def test_sum_decimal64_exact_beyond_f53(session, cpu_session):
    """decimal64 sums beyond 2^53 must stay exact (an f64 ride would
    round): 1e15-scale unscaled values x 2000 rows."""
    P15 = T.DecimalType(15, 2)
    vals = np.full(2000, 10**14 + 3, dtype=np.int64)

    def q(s):
        df = s.create_dataframe({"d": vals}, dtypes={"d": P15})
        return df.agg(F.sum("d").alias("s"))

    got = q(session).collect()
    assert got == q(cpu_session).collect()
    assert got == [(2000 * (10**14 + 3),)]  # exact integer


def test_sum_p38_overflow_nulls(session, cpu_session):
    """A sum beyond the result precision (p=38 already maxed) nulls
    (non-ANSI CheckOverflow semantics)."""
    vals = [MAX38, MAX38, MAX38]

    def q(s):
        return _df(s, vals).agg(F.sum("d").alias("s"))

    got = q(session).collect()
    assert got == q(cpu_session).collect() == [(None,)]


def test_minmax_p38_two_limb_tiebreak(session, cpu_session):
    """Values sharing a high limb order by the UNSIGNED low limb."""
    base = 5 << 64
    vals = [base + 1, base + (1 << 63), base + 2, None, -(1 << 64) - 9]

    def q(s):
        return _df(s, vals).agg(F.min("d").alias("mn"),
                                F.max("d").alias("mx"))

    got = q(session).collect()
    assert got == q(cpu_session).collect()
    assert got == [(-(1 << 64) - 9, base + (1 << 63))]


def test_sum_p38_multibatch_merge(session, cpu_session):
    """Partial/merge streaming path sums dec128 exactly across batches."""
    rng = np.random.default_rng(21)
    vals = [int(v) * 10**20 + int(w) for v, w in
            zip(rng.integers(-10**6, 10**6, 900),
                rng.integers(0, 1000, 900))]
    keys = rng.integers(0, 7, 900).astype(np.int64)

    def q(s):
        df = s.create_dataframe({"k": keys, "d": vals},
                                dtypes={"d": P38}, num_batches=4)
        return df.group_by("k").agg(F.sum("d").alias("s"))

    got = sorted(q(session).collect())
    want = sorted(q(cpu_session).collect())
    assert got == want
    import collections
    truth = collections.defaultdict(int)
    for k, v in zip(keys, vals):
        truth[int(k)] += v
    assert {r[0]: r[1] for r in got} == dict(truth)


def test_sum_p38_overflow_in_one_batch_nulls_final(session, cpu_session):
    """A single BATCH overflowing must null the FINAL merged sum, not
    silently drop that batch's rows (review fix)."""
    # batch 1 alone overflows p=38; batch 2 is tiny
    vals = [MAX38, MAX38, 5, 7]

    def q(s):
        df = s.create_dataframe({"d": vals}, dtypes={"d": P38},
                                num_batches=2)
        return df.agg(F.sum("d").alias("s"))

    got = q(session).collect()
    assert got == q(cpu_session).collect() == [(None,)]


def test_hash_expression_over_p38_is_spark_exact(session):
    """F.hash()/xxhash64 over dec128 fall back to the Spark-exact
    byte-array hash (review fix — the device limb hash serves only
    partitioning)."""
    from spark_rapids_tpu.ops.hashfns import Murmur3Hash, XxHash64

    vals = [MAX38, -(1 << 64) - 3, 0, None]
    df = _df(session, vals).select(
        Murmur3Hash(col("d")).alias("h"), XxHash64(col("d")).alias("x"))
    assert "unsupported type" in df.explain()
    got = {v: (h, x) for v, (h, x) in
           zip(vals, df.collect())}

    # independent Spark-truth: murmur3/xxhash over BigInteger.toByteArray
    import numpy as np
    from spark_rapids_tpu.shuffle.hashing import (
        _dec128_twos_complement_bytes,
        _np_hash_bytes,
    )
    from spark_rapids_tpu.ops.hashfns import XX_SEED, _np_xx_bytes
    for v in vals:
        if v is None:
            continue
        want_h = int(np.int32(_np_hash_bytes(
            _dec128_twos_complement_bytes(v), np.uint32(42))))
        assert got[v][0] == want_h, v


def test_csv_escape_newline_semantics_consistent(session, cpu_session,
                                                 tmp_path):
    """newlines_in_values stays False for plain CSV (review fix: it is
    hive-text-only — it governs pyarrow's multithreaded block
    splitting). NOTE: pyarrow's parser inherently treats an escaped
    newline as data with escape_char set (a documented divergence from
    Spark's unquoted multiLine=false split); both engine paths agree."""
    from spark_rapids_tpu import types as T
    p = tmp_path / "c.csv"
    p.write_text("a~\nb,1\nplain,2\n")

    def q(s):
        return s.read_csv(str(p), escape="~", header=False,
                          schema=[("s", T.STRING), ("x", T.LONG)],
                          mode="PERMISSIVE").collect()

    assert sorted(q(session), key=repr) == sorted(q(cpu_session), key=repr)
