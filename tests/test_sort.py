"""Sort oracle tests (reference analog: sort_test.py)."""

import pytest

from spark_rapids_tpu.ops.expr import col
from spark_rapids_tpu.plan.nodes import SortOrder

from tests.asserts import assert_tpu_and_cpu_are_equal
from tests.data_gen import DoubleGen, IntGen, LongGen, StringGen, TimestampGen, gen_table


def _df(sess, gens, n=600, seed=3):
    from spark_rapids_tpu.plan import from_host_table
    return from_host_table(gen_table(gens, n, seed), sess)


@pytest.mark.parametrize("gen", [IntGen(), LongGen(), DoubleGen(no_nans=True),
                                 StringGen(cardinality=15), TimestampGen()],
                         ids=lambda g: g.dtype.simple_string())
@pytest.mark.parametrize("ascending", [True, False], ids=["asc", "desc"])
def test_sort_single_key(session, cpu_session, gen, ascending):
    assert_tpu_and_cpu_are_equal(
        lambda s: _df(s, {"a": gen, "payload": IntGen(nullable=False)})
        .sort(SortOrder(col("a"), ascending)),
        session, cpu_session, ignore_order=False)


def test_sort_multi_key(session, cpu_session):
    assert_tpu_and_cpu_are_equal(
        lambda s: _df(s, {"a": IntGen(min_val=0, max_val=5), "b": StringGen(cardinality=6),
                          "p": LongGen()})
        .sort(SortOrder(col("a"), True), SortOrder(col("b"), False)),
        session, cpu_session, ignore_order=False)


@pytest.mark.parametrize("nulls_first", [True, False])
def test_sort_null_placement(session, cpu_session, nulls_first):
    assert_tpu_and_cpu_are_equal(
        lambda s: _df(s, {"a": IntGen(null_prob=0.3)})
        .sort(SortOrder(col("a"), True, nulls_first)),
        session, cpu_session, ignore_order=False)
