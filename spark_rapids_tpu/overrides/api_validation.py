"""API-drift validation.

Reference: ``api_validation/.../ApiValidation.scala:26`` — the reference
walks every Gpu* exec and compares its constructor signature against the
Spark exec it replaces, printing drift so a Spark upgrade can't silently
orphan a GPU operator.

TPU mapping: the plan layer and the exec layer evolve independently
here too (plan nodes in ``plan/``, device execs in ``execs/``, glued by
the convert functions in ``overrides/rules.py``). ``validate_api()``
audits, for every registered rule, the things that actually drift:

* the plan node exposes the required PlanNode surface
  (``output_schema``, ``children``) and the exec the required TpuExec
  surface (``execute``, ``output_schema``);
* the rule's convert function signature accepts (node, children, conf);
* expression rules expose the Expression contract
  (``with_children``, ``key``, ``eval_cpu``, ``data_type``) so plan
  rewrites and trace caching can rely on them.

Returns a list of human-readable drift findings (empty = in sync); the
test suite asserts emptiness, the CLI prints them."""

from __future__ import annotations

import inspect
from typing import List


def _check_signature(fn, name: str, findings: List[str]) -> None:
    try:
        sig = inspect.signature(fn)
    except (TypeError, ValueError):
        return
    params = [p for p in sig.parameters.values()
              if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)]
    accepts_var = any(p.kind == p.VAR_POSITIONAL
                      for p in sig.parameters.values())
    if not accepts_var and len(params) < 3:
        findings.append(
            f"{name}: convert function takes {len(params)} positional "
            "params, needs (node, children, conf)")


def validate_api() -> List[str]:
    from spark_rapids_tpu.execs.base import TpuExec
    from spark_rapids_tpu.ops.expr import Expression
    from spark_rapids_tpu.overrides import rules as R
    from spark_rapids_tpu.plan.nodes import PlanNode

    R._build_expr_sigs()
    findings: List[str] = []

    for node_cls, rule in R._EXEC_RULES.items():
        where = f"exec rule {node_cls.__name__}"
        if not issubclass(node_cls, PlanNode):
            findings.append(f"{where}: key is not a PlanNode subclass")
            continue
        for attr in ("output_schema",):
            if not callable(getattr(node_cls, attr, None)):
                findings.append(f"{where}: plan node lacks {attr}()")
        _check_signature(rule.convert_fn, where, findings)

    for cls in R._EXPR_SIGS:
        where = f"expression rule {cls.__name__}"
        if not issubclass(cls, Expression):
            findings.append(f"{where}: not an Expression subclass")
            continue
        for attr in ("with_children", "key", "eval_cpu"):
            impl = getattr(cls, attr, None)
            base = getattr(Expression, attr, None)
            if impl is None:
                findings.append(f"{where}: lacks {attr}")
            elif impl is base and attr in ("with_children", "key"):
                # leaf expressions legitimately inherit; only flag
                # multi-child classes that never override with_children
                init = inspect.signature(cls.__init__)
                n_params = len(init.parameters) - 1
                if attr == "with_children" and n_params >= 1 \
                        and getattr(base, "__isabstractmethod__", False):
                    findings.append(f"{where}: inherits abstract {attr}")
        if "data_type" not in dir(cls):
            findings.append(f"{where}: lacks data_type")

    # every TpuExec subclass reachable from the registry implements the
    # exec surface
    seen = set()

    def audit_exec_cls(ecls):
        if not (isinstance(ecls, type) and issubclass(ecls, TpuExec)) \
                or ecls in seen:
            return
        seen.add(ecls)
        if not callable(getattr(ecls, "execute", None)):
            findings.append(f"exec {ecls.__name__}: lacks execute()")
        if not callable(getattr(ecls, "output_schema", None)):
            findings.append(f"exec {ecls.__name__}: lacks output_schema()")

    import spark_rapids_tpu.execs as execs_pkg
    for attr in dir(execs_pkg):
        audit_exec_cls(getattr(execs_pkg, attr))
    return findings


def main() -> int:
    findings = validate_api()
    if not findings:
        print("api_validation: no drift")
        return 0
    for f in findings:
        print("DRIFT:", f)
    return 1


if __name__ == "__main__":
    raise SystemExit(main())
