"""WriteFiles commit protocol, Hive text scan, FileCache
(reference analogs: GpuDataWritingCommandExec, GpuHiveText, FileCache)."""

import os

import pytest

from spark_rapids_tpu import functions as F
from spark_rapids_tpu import types as T
from spark_rapids_tpu.ops.expr import col, lit
from spark_rapids_tpu.plan import from_host_table

from tests.data_gen import IntGen, StringGen, gen_table


def _df(sess, n=200, seed=4):
    return from_host_table(
        gen_table({"k": StringGen(cardinality=4, nullable=False),
                   "v": IntGen(nullable=False)}, n, seed), sess)


def test_write_parquet_commit_protocol(session, tmp_path):
    out = str(tmp_path / "t")
    stats = _df(session).filter(col("v") > lit(0)).write_parquet(out)
    assert os.path.exists(os.path.join(out, "_SUCCESS"))
    assert not any(d.startswith("_temporary") for d in os.listdir(out))
    row = stats.to_pydict()
    assert row["numFiles"][0] >= 1 and row["numBytes"][0] > 0
    back = session.read_parquet(out + "/part-00000.parquet").count()
    assert back == row["numRows"][0]


def test_write_partitioned_commit(session, tmp_path):
    out = str(tmp_path / "p")
    stats = _df(session).write_parquet(out, partition_by=["k"])
    assert os.path.exists(os.path.join(out, "_SUCCESS"))
    parts = [d for d in os.listdir(out) if d.startswith("k=")]
    assert len(parts) >= 2
    assert stats.to_pydict()["numRows"][0] == 200


def test_hive_text_roundtrip(session, tmp_path):
    out = str(tmp_path / "h")
    _df(session).write_hive_text(out)
    schema = [("k", T.STRING), ("v", T.INT)]
    files = [os.path.join(out, f) for f in os.listdir(out)
             if f.endswith(".txt")]
    back = session.read_hive_text(*files, schema=schema)
    a = sorted(back.collect())
    b = sorted(_df(session).collect())
    assert a == b


def test_hive_text_null_marker(session, tmp_path):
    p = str(tmp_path / "n.txt")
    with open(p, "w") as f:
        f.write("a\x015\n\\N\x017\nb\x01\\N\n")
    df = session.read_hive_text(p, schema=[("s", T.STRING), ("i", T.INT)])
    assert df.collect() == [("a", 5), (None, 7), ("b", None)]


def test_filecache_hits(tmp_path):
    from spark_rapids_tpu.io.filecache import FILE_CACHE
    from spark_rapids_tpu.session import TpuSession

    s = TpuSession({"spark.rapids.filecache.enabled": "true"})
    out = str(tmp_path / "c")
    _df(s).write_parquet(out)
    f = os.path.join(out, "part-00000.parquet")
    FILE_CACHE.clear()
    h0, m0 = FILE_CACHE.hits, FILE_CACHE.misses
    s.read_parquet(f).count()
    s.read_parquet(f).count()
    assert FILE_CACHE.misses == m0 + 1
    assert FILE_CACHE.hits >= h0 + 1


def test_filecache_disabled_by_default(session, tmp_path):
    from spark_rapids_tpu.io.filecache import FILE_CACHE
    out = str(tmp_path / "d")
    _df(session).write_parquet(out)
    FILE_CACHE.clear()
    m0 = FILE_CACHE.misses
    session.read_parquet(os.path.join(out, "part-00000.parquet")).count()
    assert FILE_CACHE.misses == m0  # cache never consulted
