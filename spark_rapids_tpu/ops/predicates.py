"""Predicates & boolean logic (reference rules: EqualTo, EqualNullSafe,
LessThan, LessThanOrEqual, GreaterThan, GreaterThanOrEqual, And, Or, Not,
IsNull, IsNotNull, IsNaN, In, InSet — GpuOverrides.scala expression registry,
SURVEY.md Appendix A)."""

from __future__ import annotations

import operator
from typing import Sequence

import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar import HostColumn, HostTable
from spark_rapids_tpu.ops.common import (
    BinaryExpression,
    UnaryExpression,
    align_string_dicts,
    coerce_numeric_pair,
    dev_aligned_codes,
    is_string_pair,
    null_and,
)
from spark_rapids_tpu.ops.expr import DevVal, EvalCtx, Expression, NodePrep, PrepCtx


def _spark_float_cmp(op, ld, rd, xp):
    """Spark total-order float comparison: NaN == NaN is TRUE and NaN is
    greater than every other value (SQL ref 'NaN semantics'); raw IEEE
    compares would return false for all NaN comparisons."""
    nl, nr = xp.isnan(ld), xp.isnan(rd)
    if op is operator.eq:
        return (ld == rd) | (nl & nr)
    if op is operator.lt:
        return (~nl & nr) | (ld < rd)
    if op is operator.le:
        return (~nl & nr) | (nl & nr) | (ld <= rd)
    if op is operator.gt:
        return (nl & ~nr) | (ld > rd)
    if op is operator.ge:
        return (nl & ~nr) | (nl & nr) | (ld >= rd)
    return op(ld, rd)


def _cpu_cmp_data(left: HostColumn, right: HostColumn, op):
    ld, rd = left.data, right.data
    if isinstance(left.dtype, T.StringType):
        # Invalid slots may hold None; substitute "" so object comparison
        # (Python str, code-point order == Spark UTF-8 byte order) is safe.
        ld = np.where(left.validity, ld, "")
        rd = np.where(right.validity, rd, "")
    elif np.issubdtype(np.asarray(ld).dtype, np.floating):
        return _spark_float_cmp(op, ld, rd, np)
    return op(ld, rd)


def _dec128_sign(l, r):
    """Three-way compare of (n, 2) int64 two-limb decimals: -1/0/+1 as
    i32. High limbs compare signed; low limbs compare as unsigned via a
    top-bit flip (no u64 bitcasts — the axon x64 rewrite lacks them)."""
    top = jnp.int64(-0x8000000000000000)
    lhi, llo = l[:, 0], l[:, 1] ^ top
    rhi, rlo = r[:, 0], r[:, 1] ^ top
    hi_cmp = jnp.where(lhi < rhi, -1, jnp.where(lhi > rhi, 1, 0)
                       ).astype(jnp.int32)
    lo_cmp = jnp.where(llo < rlo, -1, jnp.where(llo > rlo, 1, 0)
                       ).astype(jnp.int32)
    return jnp.where(hi_cmp != 0, hi_cmp, lo_cmp)


class BinaryComparison(BinaryExpression):
    op = None  # numpy/python operator
    jop = None  # jnp operator (same symbol works)

    @property
    def data_type(self):
        return T.BOOLEAN

    def resolve(self, bound):
        left, right = bound
        if is_string_pair(left, right) or left.data_type == right.data_type:
            return type(self)(left, right)
        left, right, _ = coerce_numeric_pair(left, right)
        return type(self)(left, right)

    def eval_cpu(self, table: HostTable) -> HostColumn:
        l = self.left.eval_cpu(table)
        r = self.right.eval_cpu(table)
        data = _cpu_cmp_data(l, r, type(self).op).astype(np.bool_)
        validity = l.validity & r.validity
        return HostColumn(T.BOOLEAN, np.where(validity, data, False), validity)

    def prep(self, pctx: PrepCtx, child_preps) -> NodePrep:
        lp, rp = child_preps
        if lp.out_dict is not None and rp.out_dict is not None:
            p = align_string_dicts(pctx, lp, rp)
            return NodePrep(aux_slots=p.aux_slots, extra={"string": True})
        return NodePrep()

    def eval_dev(self, ctx: EvalCtx, child_vals, prep) -> DevVal:
        lval, rval = child_vals
        if prep.extra.get("string"):
            ld, rd = dev_aligned_codes(ctx, prep, lval, rval)
        else:
            ld, rd = lval.data, rval.data
        validity = null_and(lval.validity, rval.validity)
        if jnp.issubdtype(ld.dtype, jnp.floating):
            data = _spark_float_cmp(type(self).op, ld, rd, jnp)
        elif getattr(ld, "ndim", 1) == 2:
            # DECIMAL128 two-limb storage: compare the three-way sign
            data = type(self).op(_dec128_sign(ld, rd),
                                 jnp.zeros(ld.shape[0], jnp.int32))
        else:
            data = type(self).op(ld, rd)
        return DevVal(jnp.where(validity, data, False), validity)


class EqualTo(BinaryComparison):
    op = staticmethod(operator.eq)


class LessThan(BinaryComparison):
    op = staticmethod(operator.lt)


class LessThanOrEqual(BinaryComparison):
    op = staticmethod(operator.le)


class GreaterThan(BinaryComparison):
    op = staticmethod(operator.gt)


class GreaterThanOrEqual(BinaryComparison):
    op = staticmethod(operator.ge)


class EqualNullSafe(BinaryComparison):
    """<=> : never null; null <=> null is true."""

    op = staticmethod(operator.eq)

    @property
    def nullable(self):
        return False

    def eval_cpu(self, table):
        l = self.left.eval_cpu(table)
        r = self.right.eval_cpu(table)
        both_valid = l.validity & r.validity
        both_null = ~l.validity & ~r.validity
        eq = _cpu_cmp_data(l, r, operator.eq).astype(np.bool_)
        data = np.where(both_valid, eq, both_null)
        return HostColumn(T.BOOLEAN, data, np.ones(len(l), dtype=np.bool_))

    def eval_dev(self, ctx, child_vals, prep):
        lval, rval = child_vals
        if prep.extra.get("string"):
            ld, rd = dev_aligned_codes(ctx, prep, lval, rval)
        else:
            ld, rd = lval.data, rval.data
        if jnp.issubdtype(ld.dtype, jnp.floating):
            eq_data = _spark_float_cmp(operator.eq, ld, rd, jnp)
        elif getattr(ld, "ndim", 1) == 2:  # DECIMAL128 two-limb
            eq_data = _dec128_sign(ld, rd) == 0
        else:
            eq_data = ld == rd
        both_valid = lval.validity & rval.validity
        both_null = ~lval.validity & ~rval.validity
        data = jnp.where(both_valid, eq_data, both_null)
        return DevVal(data, jnp.ones_like(data, dtype=jnp.bool_))


class And(BinaryExpression):
    """Kleene logic: false AND null = false."""

    @property
    def data_type(self):
        return T.BOOLEAN

    def eval_cpu(self, table):
        l = self.left.eval_cpu(table)
        r = self.right.eval_cpu(table)
        lv, rv = l.validity, r.validity
        ld = l.data.astype(np.bool_) & lv
        rd = r.data.astype(np.bool_) & rv
        data = ld & rd
        # valid iff: both valid, or either side is a definite false
        validity = (lv & rv) | (lv & ~l.data.astype(np.bool_)) | (rv & ~r.data.astype(np.bool_))
        return HostColumn(T.BOOLEAN, np.where(validity, data, False), validity)

    def eval_dev(self, ctx, child_vals, prep):
        lval, rval = child_vals
        ld = lval.data & lval.validity
        rd = rval.data & rval.validity
        data = ld & rd
        validity = (lval.validity & rval.validity) | (lval.validity & ~lval.data) | (rval.validity & ~rval.data)
        return DevVal(jnp.where(validity, data, False), validity)


class Or(BinaryExpression):
    """Kleene logic: true OR null = true."""

    @property
    def data_type(self):
        return T.BOOLEAN

    def eval_cpu(self, table):
        l = self.left.eval_cpu(table)
        r = self.right.eval_cpu(table)
        lv, rv = l.validity, r.validity
        ld = l.data.astype(np.bool_) & lv
        rd = r.data.astype(np.bool_) & rv
        data = ld | rd
        validity = (lv & rv) | ld | rd
        return HostColumn(T.BOOLEAN, np.where(validity, data, False), validity)

    def eval_dev(self, ctx, child_vals, prep):
        lval, rval = child_vals
        ld = lval.data & lval.validity
        rd = rval.data & rval.validity
        data = ld | rd
        validity = (lval.validity & rval.validity) | ld | rd
        return DevVal(jnp.where(validity, data, False), validity)


class Not(UnaryExpression):
    @property
    def data_type(self):
        return T.BOOLEAN

    def eval_cpu(self, table):
        c = self.child.eval_cpu(table)
        data = ~c.data.astype(np.bool_)
        return HostColumn(T.BOOLEAN, np.where(c.validity, data, False), c.validity.copy())

    def eval_dev(self, ctx, child_vals, prep):
        (c,) = child_vals
        return DevVal(jnp.where(c.validity, ~c.data, False), c.validity)


class IsNull(UnaryExpression):
    @property
    def data_type(self):
        return T.BOOLEAN

    @property
    def nullable(self):
        return False

    def eval_cpu(self, table):
        c = self.child.eval_cpu(table)
        return HostColumn(T.BOOLEAN, ~c.validity, np.ones(len(c), dtype=np.bool_))

    def eval_dev(self, ctx, child_vals, prep):
        (c,) = child_vals
        # Padding rows carry validity False; mask with live-row mask so the
        # result is deterministic there (consumers mask anyway).
        return DevVal(~c.validity, jnp.ones_like(c.validity))


class IsNotNull(UnaryExpression):
    @property
    def data_type(self):
        return T.BOOLEAN

    @property
    def nullable(self):
        return False

    def eval_cpu(self, table):
        c = self.child.eval_cpu(table)
        return HostColumn(T.BOOLEAN, c.validity.copy(), np.ones(len(c), dtype=np.bool_))

    def eval_dev(self, ctx, child_vals, prep):
        (c,) = child_vals
        return DevVal(c.validity, jnp.ones_like(c.validity))


class IsNaN(UnaryExpression):
    @property
    def data_type(self):
        return T.BOOLEAN

    @property
    def nullable(self):
        return False

    def eval_cpu(self, table):
        c = self.child.eval_cpu(table)
        data = np.isnan(c.data) & c.validity
        return HostColumn(T.BOOLEAN, data, np.ones(len(c), dtype=np.bool_))

    def eval_dev(self, ctx, child_vals, prep):
        (c,) = child_vals
        return DevVal(jnp.isnan(c.data) & c.validity, jnp.ones_like(c.validity))


class In(Expression):
    """value IN (literals...). Spark semantics: true if match; null if no
    match and (value is null or any list element is null); else false."""

    def __init__(self, value: Expression, items: Sequence[Expression]):
        self.children = (value,) + tuple(items)

    @property
    def value(self):
        return self.children[0]

    @property
    def items(self):
        return self.children[1:]

    @property
    def data_type(self):
        return T.BOOLEAN

    def with_children(self, children):
        return In(children[0], children[1:])

    def key(self):
        return ("in", tuple(c.key() for c in self.children))

    def eval_cpu(self, table):
        from spark_rapids_tpu.ops.expr import Literal
        v = self.value.eval_cpu(table)
        n = len(v)
        has_null_item = any(isinstance(i, Literal) and i.value is None for i in self.items)
        match = np.zeros(n, dtype=np.bool_)
        vd = v.data
        if isinstance(v.dtype, T.StringType):
            vd = np.where(v.validity, vd, "")
        for item in self.items:
            i = item.eval_cpu(table)
            idata = i.data
            if isinstance(v.dtype, T.StringType):
                idata = np.where(i.validity, idata, "")
            match |= (vd == idata) & i.validity
        validity = v.validity & (match | ~np.full(n, has_null_item))
        return HostColumn(T.BOOLEAN, np.where(validity, match, False), validity)

    def prep(self, pctx, child_preps):
        vp = child_preps[0]
        slots = []
        if vp.out_dict is not None:
            for ip in child_preps[1:]:
                p = align_string_dicts(pctx, vp, ip)
                slots.extend(p.aux_slots)
            return NodePrep(aux_slots=tuple(slots), extra={"string": True})
        return NodePrep()

    def eval_dev(self, ctx, child_vals, prep):
        from spark_rapids_tpu.ops.expr import Literal
        v = child_vals[0]
        has_null_item = any(isinstance(i, Literal) and i.value is None for i in self.items)
        match = jnp.zeros_like(v.validity)
        for idx, iv in enumerate(child_vals[1:]):
            if prep.extra.get("string"):
                lmap = ctx.aux[prep.aux_slots[2 * idx]]
                rmap = ctx.aux[prep.aux_slots[2 * idx + 1]]
                ld = lmap[jnp.clip(v.data, 0, lmap.shape[0] - 1)]
                rd = rmap[jnp.clip(iv.data, 0, rmap.shape[0] - 1)]
            else:
                ld, rd = v.data, iv.data
            match = match | ((ld == rd) & iv.validity)
        validity = v.validity & (match | (not has_null_item))
        return DevVal(jnp.where(validity, match, False), validity)


class InSet(In):
    """Optimized IN over a large literal set (Spark converts In -> InSet
    past spark.sql.optimizer.inSetConversionThreshold). Identical
    semantics; the device evaluation inherits In's chain, which XLA
    fuses into one vectorized membership test."""
