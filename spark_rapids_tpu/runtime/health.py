"""Device health monitor: device-loss recovery + poison-query quarantine.

Reference (SURVEY.md §5): on a fatal CUDA error the reference captures a
core dump and exits the executor with code 20, trusting Spark's driver
to reschedule the work on a healthy node. ``runtime/crash_handler.py``
implements that capture-and-exit half; this module is the RESCHEDULER
the exit protocol assumes exists — the single-process query service
(service/scheduler.py) has no Spark driver above it, so recovery from a
dead device has to happen in-process:

* **Device-loss recovery** — a fatal non-OOM device error
  (:func:`~spark_rapids_tpu.runtime.crash_handler.is_fatal_device_error`
  — classified DISTINCTLY from the per-op
  :class:`~spark_rapids_tpu.errors.KernelCrashError` the PR-3 circuit
  breaker owns) reinitializes the backend and invalidates every cache
  that references dead device state: the plan→executable cache (cached
  trees hold device-resident constants), the structural kernel-trace
  caches, the interned device const/scalar pools, cached scan device
  images, and jax's own jit caches. The failing query surfaces a typed
  RETRYABLE :class:`~spark_rapids_tpu.errors.DeviceLostError`; the
  query service requeues it against the recovered backend.
* **CPU-only latch** — after
  ``spark.rapids.service.deviceLoss.maxReinits`` CONSECUTIVE device
  losses (no successful query between them) the engine stops trusting
  the device entirely and latches CPU-only degraded mode: the overrides
  layer (PlanMeta.tag) falls every operator back with the latch reason,
  exactly like a circuit-breaker demotion but for the whole device.
  Serving survives at reduced speed instead of crash-looping.
* **Poison-query quarantine** — a template fingerprint
  (plan/fingerprint.py) that kills workers or the device
  ``spark.rapids.service.quarantine.maxStrikes`` times is quarantined:
  subsequent submissions are rejected with a typed
  :class:`~spark_rapids_tpu.errors.QueryQuarantinedError` carrying the
  strike history, and ``explain()`` flags the template.

Counters live in the unified registry's ``health`` scope so the event
log diffs them per query like spill/recovery/shuffle.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

from spark_rapids_tpu.conf import int_conf
from spark_rapids_tpu.obs.metrics import metric_scope, register_metric
from spark_rapids_tpu.lockorder import ordered_lock

DEVICE_LOSS_MAX_REINITS = int_conf(
    "spark.rapids.service.deviceLoss.maxReinits", 3,
    "Consecutive device losses (fatal non-OOM device errors with no "
    "successful query between them) tolerated before the engine stops "
    "reinitializing the backend and latches CPU-only degraded mode for "
    "the rest of the process (whole-device analog of the per-op "
    "runtime circuit breaker).")

QUARANTINE_MAX_STRIKES = int_conf(
    "spark.rapids.service.quarantine.maxStrikes", 3,
    "Times one query template (literal-stripped structural "
    "fingerprint) may kill a service worker or the device before it is "
    "quarantined: further submissions of the template are rejected "
    "with QueryQuarantinedError carrying the strike history.")

register_metric("deviceLost", "count", "ESSENTIAL",
                "fatal device errors observed (each triggers a "
                "backend reinitialization or the CPU-only latch)")
register_metric("deviceReinits", "count", "ESSENTIAL",
                "backend reinitializations after device loss "
                "(caches invalidated, device re-discovered)")
register_metric("workersLost", "count", "ESSENTIAL",
                "service workers that died or were abandoned by the "
                "watchdog (hard wall-limit breach)")
register_metric("workersRespawned", "count", "ESSENTIAL",
                "replacement service workers spawned so pool capacity "
                "holds through worker loss")
register_metric("hardTimeouts", "count", "ESSENTIAL",
                "queries failed by the watchdog's hard wall limit "
                "(spark.rapids.service.hardTimeoutMs)")
register_metric("quarantineStrikes", "count", "MODERATE",
                "worker/device kills recorded against query templates")
register_metric("quarantinedTemplates", "count", "ESSENTIAL",
                "query templates currently quarantined")
register_metric("meshDeviceLost", "count", "ESSENTIAL",
                "PARTIAL device losses observed (one mesh device dead, "
                "backend otherwise alive — each walks one rung of the "
                "mesh degradation ladder)")
register_metric("meshDegradations", "count", "ESSENTIAL",
                "times the degradation ladder demoted mesh execution "
                "(single-device re-land of an attempt, or a mesh "
                "shrink onto surviving devices)")
register_metric("meshShrinks", "count", "ESSENTIAL",
                "mesh reconfigurations onto surviving devices after "
                "partial device loss (bounded by "
                "spark.rapids.mesh.degrade.maxShrinks)")
register_metric("memoryPressure", "count", "ESSENTIAL",
                "FatalDeviceOOM escalations the memory degradation "
                "ladder handled (each walks one rung: full-spill "
                "retry, chunked re-execution, per-op CPU demotion)")
register_metric("memoryChunkedReexecutions", "count", "ESSENTIAL",
                "query replays forced onto chunked scans by the "
                "memory ladder's 'chunk' rung")
register_metric("memoryCpuDemotions", "count", "ESSENTIAL",
                "operators demoted to the CPU path by the memory "
                "ladder after chunked re-execution still could not "
                "fit the device budget")


def _record_ladder_incident(kind: str, action: str, exc: BaseException,
                            conf) -> None:
    """Flight-recorder hook for every degradation-ladder action
    (obs/telemetry.py). Called AFTER the monitor's lock is released —
    the bundle re-reads the health snapshots — and strictly
    best-effort: the black box must never mask the recovery it
    documents."""
    try:
        from spark_rapids_tpu.obs.telemetry import record_incident
        first = (str(exc).splitlines()[0] if str(exc)
                 else type(exc).__name__)
        reason = f"{type(exc).__name__}: {first}"
        cause = exc.__cause__
        if cause is not None and str(cause):
            # a wrapped escalation (FatalDeviceOOM from a RetryOOM)
            # names the triggering fault point only in its cause — ride
            # it along so the bundle's faultPoint parse still works
            reason += f" (cause: {str(cause).splitlines()[0]})"
        record_incident(kind, action, reason, conf=conf, error=exc)
    except Exception:
        pass


class DeviceHealthMonitor:
    """Process-wide device health state (the device is shared by every
    session in the process, like the circuit breaker and the kernel
    caches). Writes go through the instance lock; the hot-path reads
    (``cpu_only_reason`` in PlanMeta.tag, ``generation`` in the
    executable-cache token) are single attribute loads."""

    def __init__(self):
        self._lock = ordered_lock("health.monitor")
        self._metrics = metric_scope("health")
        self._consecutive_losses = 0
        self._reinits = 0
        self._losses = 0
        #: read LOCK-FREE on the per-node tag() hot path — a plain
        #: attribute load of an immutable str/None (latch is one-way
        #: until reset(), so a torn read cannot un-latch)
        self._cpu_only_reason: Optional[str] = None
        #: coherency generation for the executable cache: bumped per
        #: recovery so a tree checked out across a reinit can neither
        #: re-park into the fresh pool nor corrupt its busy count
        self._generation = 0
        # -- the mesh fault domain (partial device loss) ------------------
        #: consecutive PARTIAL mesh-device losses with no mesh-NATIVE
        #: success between them — drives the degradation ladder. A
        #: success achieved under single-device suppression does NOT
        #: reset it (the mesh was not exercised, so there is no
        #: evidence it recovered)
        self._mesh_consecutive = 0
        self._mesh_losses = 0
        self._mesh_shrinks = 0
        self._mesh_degradations = 0
        # -- the host fault domain (a dead executor PROCESS) --------------
        #: consecutive HOST losses with no cluster-NATIVE success
        #: between them — drives the host degradation ladder. A success
        #: achieved with the cluster inactive (suppressed / latched
        #: single-process) does NOT reset it.
        self._host_consecutive = 0
        self._host_losses = 0
        self._host_shrinks = 0
        # -- the memory fault domain (device budget exhaustion) ------------
        #: consecutive FatalDeviceOOMs with no success between them —
        #: drives the memory degradation ladder (retry-after-full-
        #: spill -> chunked re-execution -> per-op CPU demotion). Any
        #: completed query resets it (memory pressure is workload
        #: pressure, not broken hardware).
        self._mem_consecutive = 0
        self._mem_events = 0
        self._mem_chunked = 0
        self._mem_cpu_demotions = 0

    # -- hot-path reads ------------------------------------------------------
    def cpu_only_reason(self) -> Optional[str]:
        return self._cpu_only_reason

    def generation(self) -> int:
        return self._generation

    def state(self) -> str:
        """HEALTHY / DEGRADED / CPU_ONLY from the device's view alone
        (the query service folds its own worker-loss recency in)."""
        if self._cpu_only_reason is not None:
            return "CPU_ONLY"
        if self._consecutive_losses > 0:
            return "DEGRADED"
        return "HEALTHY"

    # -- the recovery protocol -----------------------------------------------
    def on_device_loss(self, exc: BaseException, conf) -> str:
        """One observed fatal device error: count it, reinitialize the
        backend (invalidating every device-referencing cache), and latch
        CPU-only mode once the consecutive-loss budget is spent. Returns
        the resulting health state. Serialized — two workers observing
        the same dead device recover one at a time, and the second
        recovery is a cheap re-clear of already-empty caches."""
        state = self._on_device_loss_inner(exc, conf)
        _record_ladder_incident("backend.ladder", state, exc, conf)
        return state

    def _on_device_loss_inner(self, exc: BaseException, conf) -> str:
        max_reinits = int(conf.get_entry(DEVICE_LOSS_MAX_REINITS))
        with self._lock:
            self._losses += 1
            self._consecutive_losses += 1
            self._generation += 1
            self._metrics.add("deviceLost", 1)
            if self._cpu_only_reason is not None:
                return "CPU_ONLY"
            if self._consecutive_losses >= max_reinits:
                self._cpu_only_reason = (
                    f"device health: CPU-only mode latched after "
                    f"{self._consecutive_losses} consecutive device "
                    f"losses (last: {type(exc).__name__}: "
                    f"{str(exc).splitlines()[0] if str(exc) else ''})")
                # the dead device's caches still need to go — CPU-only
                # queries must not resolve stale device constants
                self._invalidate_device_caches_locked()
                return "CPU_ONLY"
            self._reinits += 1
            self._metrics.add("deviceReinits", 1)
            self._reinitialize_backend_locked(conf)
            return "DEGRADED"

    def note_success(self, mesh_native: bool = False,
                     cluster_native: bool = False) -> None:
        """A query completed: the device (or the CPU-only path) works,
        so the consecutive-loss budget refills. The MESH ladder only
        resets on a mesh-NATIVE success (``mesh_native``): a query
        that converged under single-device suppression proves nothing
        about the mesh, and resetting on it would ping-pong a truly
        dead device between retry and single-device forever instead of
        walking down to the shrink rung. The HOST ladder resets only
        on a cluster-NATIVE success for the same reason."""
        if (self._consecutive_losses
                or self._mem_consecutive
                or (mesh_native and self._mesh_consecutive)
                or (cluster_native and self._host_consecutive)):
            with self._lock:
                self._consecutive_losses = 0
                # ANY success resets the memory ladder: the budget
                # squeeze was this workload's, not the hardware's
                self._mem_consecutive = 0
                if mesh_native:
                    self._mesh_consecutive = 0
                if cluster_native:
                    self._host_consecutive = 0

    def on_mesh_device_loss(self, exc: BaseException, conf) -> str:
        """One observed PARTIAL device loss (a ``mesh.*`` fault point's
        device_lost, or a real per-device failure classified as
        MeshDeviceLostError): walk the degradation ladder one rung and
        return the recovery action the session should take —

        * ``"retry"`` — first consecutive loss: replay the query on the
          unchanged mesh (transient ICI hiccups are routine on a pod);
        * ``"single_device"`` — second loss: replay THIS query with
          mesh landing suppressed (parallel/mesh.suppressed_mesh), the
          demotion reason riding the hostShuffleFallbacks/explain()
          machinery — the query converges while the mesh is suspect;
        * ``"shrink"`` — third loss on: reconfigure the mesh onto the
          surviving devices (MESH.shrink_excluding — the generation
          bump fences every stale cached tree/dictionary), bounded by
          spark.rapids.mesh.degrade.maxShrinks;
        * ``"DEGRADED"`` / ``"CPU_ONLY"`` — shrink budget spent (or
          nothing left to shrink): escalate to the whole-backend
          ladder (:meth:`on_device_loss` — backend reinit, then the
          CPU-only latch).
        """
        action = self._on_mesh_device_loss_inner(exc, conf)
        _record_ladder_incident("mesh.ladder", action, exc, conf)
        return action

    def _on_mesh_device_loss_inner(self, exc: BaseException, conf) -> str:
        from spark_rapids_tpu.parallel.mesh import (
            MESH,
            MESH_DEGRADE_MAX_SHRINKS,
        )
        max_shrinks = int(conf.get_entry(MESH_DEGRADE_MAX_SHRINKS))
        first = str(exc).splitlines()[0] if str(exc) else type(exc).__name__
        with self._lock:
            if self._cpu_only_reason is not None:
                return "CPU_ONLY"
            self._mesh_losses += 1
            self._mesh_consecutive += 1
            n = self._mesh_consecutive
            self._metrics.add("meshDeviceLost", 1)
            if n == 1:
                return "retry"
            if n == 2:
                self._mesh_degradations += 1
                self._metrics.add("meshDegradations", 1)
                return "single_device"
            # RESERVE the shrink slot while still holding the lock:
            # two workers observing losses concurrently must not both
            # pass a read-only budget check and shrink maxShrinks+1
            # times between them
            budget = self._mesh_shrinks < max(0, max_shrinks)
            if budget:
                self._mesh_shrinks += 1
        shrunk = False
        if budget:
            reason = (f"mesh degraded after {n} consecutive mesh-device "
                      f"losses (last: {type(exc).__name__}: {first})")
            shrunk = MESH.shrink_excluding(
                getattr(exc, "device_id", None), reason)
            if not shrunk:
                with self._lock:
                    self._mesh_shrinks -= 1  # nothing to shrink: return it
        if shrunk:
            with self._lock:
                self._mesh_degradations += 1
                # a fresh ladder for the smaller mesh: its first loss
                # is a retry again, not an instant escalation
                self._mesh_consecutive = 0
                self._metrics.add("meshShrinks", 1)
                self._metrics.add("meshDegradations", 1)
            return "shrink"
        # nothing left to shrink (or budget spent): the whole-backend
        # ladder owns it from here — reinit, then the CPU-only latch
        return self.on_device_loss(exc, conf)

    def mesh_demotion_note(self) -> str:
        """The reason string a single-device-suppressed attempt carries
        (surfaced by ici_demotion_reason / explain())."""
        with self._lock:
            return (f"mesh degraded to single-device landing after "
                    f"{self._mesh_consecutive} consecutive mesh-device "
                    f"losses")

    def mesh_snapshot(self) -> Dict[str, int]:
        with self._lock:
            return self._mesh_snapshot_locked()

    def _mesh_snapshot_locked(self) -> Dict[str, int]:
        return {
            "meshDeviceLost": self._mesh_losses,
            "meshConsecutiveLosses": self._mesh_consecutive,
            "meshShrinks": self._mesh_shrinks,
            "meshDegradations": self._mesh_degradations,
        }

    def on_host_loss(self, exc: BaseException, conf) -> str:
        """One observed HOST loss (a dead executor process — a
        ``host.*`` fault point's device_lost, a dead dispatch socket,
        or the missed-beat sweep's verdict surfacing as a typed
        HostLostError): walk the HOST degradation ladder one rung and
        return the recovery action the session should take —

        * ``"retry"`` — first consecutive loss: replay the query
          against the unchanged topology (a dropped message or a
          transient DCN hiccup is routine across hosts);
        * ``"reland"`` — second loss: declare the host LOST
          (CLUSTER.mark_host_lost) and replay — the replay's scans
          re-land the dead host's shards onto the survivors, and the
          host rejoins later via the heartbeat re-register path;
        * ``"shrink"`` — third loss on: evict the host from the
          topology (CLUSTER.shrink_excluding — its device group
          leaves the mesh's dcn axis, the generation bump fences
          every cached tree), bounded by
          spark.rapids.cluster.maxHostLosses;
        * ``"single_process"`` — shrink budget spent (or one host
          left): latch single-process fallback — every scan lands
          locally, still serving, until a host rejoins;
        * ``"DEGRADED"`` / ``"CPU_ONLY"`` — host losses keep coming
          even under the single-process latch: escalate to the
          whole-backend ladder (:meth:`on_device_loss`).
        """
        action = self._on_host_loss_inner(exc, conf)
        _record_ladder_incident("host.ladder", action, exc, conf)
        return action

    def _on_host_loss_inner(self, exc: BaseException, conf) -> str:
        from spark_rapids_tpu.runtime.cluster import (
            CLUSTER,
            CLUSTER_MAX_HOST_LOSSES,
        )
        max_losses = int(conf.get_entry(CLUSTER_MAX_HOST_LOSSES))
        first = str(exc).splitlines()[0] if str(exc) else type(exc).__name__
        host_id = getattr(exc, "host_id", None)
        already_latched = (
            CLUSTER.health_snapshot()["singleProcessReason"] is not None)
        budget = False
        with self._lock:
            if self._cpu_only_reason is not None:
                return "CPU_ONLY"
            self._host_losses += 1
            self._host_consecutive += 1
            n = self._host_consecutive
            if not already_latched and n >= 3:
                # RESERVE the shrink slot under the lock (the mesh
                # ladder's two-worker argument applies here too)
                budget = self._host_shrinks < max(0, max_losses)
                if budget:
                    self._host_shrinks += 1
        if already_latched:
            # the cluster is already out of the picture and hosts are
            # STILL being lost (injected schedules can do this): the
            # whole-backend ladder owns it from here
            return self.on_device_loss(exc, conf)
        reason = (f"cluster degraded after {n} consecutive host losses "
                  f"(last: {type(exc).__name__}: {first})")
        if n == 1:
            return "retry"
        if n == 2:
            CLUSTER.mark_host_lost(host_id, reason)
            return "reland"
        if budget:
            shrunk = CLUSTER.shrink_excluding(host_id, reason)
            if shrunk:
                with self._lock:
                    # a fresh ladder for the smaller topology
                    self._host_consecutive = 0
                return "shrink"
            with self._lock:
                self._host_shrinks -= 1  # nothing to shrink: return it
        CLUSTER.latch_single_process(
            f"cluster latched single-process after {n} consecutive "
            f"host losses (last: {type(exc).__name__}: {first})")
        return "single_process"

    def host_demotion_note(self) -> str:
        """The reason string a host-ladder replay carries (surfaced in
        explain()/event log alongside the mesh demotion notes)."""
        with self._lock:
            return (f"cluster degraded after {self._host_consecutive} "
                    f"consecutive host losses")

    def host_snapshot(self) -> Dict[str, int]:
        with self._lock:
            return self._host_snapshot_locked()

    def _host_snapshot_locked(self) -> Dict[str, int]:
        return {
            "hostsLost": self._host_losses,
            "hostConsecutiveLosses": self._host_consecutive,
            "hostShrinks": self._host_shrinks,
        }

    def on_memory_pressure(self, exc: BaseException, conf) -> str:
        """One FatalDeviceOOM that escaped the retry framework (spill
        replays AND split-and-retry both exhausted — the working set
        truly does not fit the device budget at this execution shape):
        walk the MEMORY degradation ladder one rung and return the
        recovery action the session should take —

        * ``"retry"`` — first escalation: spill EVERYTHING spillable
          (whole device tier + cached scan images) and replay at the
          same shape — transient co-resident pressure (a concurrent
          query's working set) may have passed;
        * ``"chunk"`` — second escalation: replay with scans FORCED
          onto smaller chunks (runtime/memory.forced_chunking at half
          the normal chunk share) — bounded partitions stream where
          one batch could not fit;
        * ``"cpu_demote"`` — third escalation on: demote the
          attributed operator (``exc.fault_op``) to the CPU path via
          the runtime circuit breaker — the replay re-plans with that
          op off-device, the reason surfaced in explain()/event log
          like every other demotion;
        * ``"abort"`` — no operator attribution to demote (or the
          ladder is exhausted): the session re-raises the typed OOM.

        Each action records a flight-recorder incident bundle
        (``memory.ladder``), like every other domain's ladder."""
        action = self._on_memory_pressure_inner(exc, conf)
        _record_ladder_incident("memory.ladder", action, exc, conf)
        return action

    def _on_memory_pressure_inner(self, exc: BaseException, conf) -> str:
        with self._lock:
            self._mem_events += 1
            self._mem_consecutive += 1
            n = self._mem_consecutive
            self._metrics.add("memoryPressure", 1)
        if n == 1:
            # make maximum room before the same-shape replay
            try:
                from spark_rapids_tpu.columnar.table import (
                    evict_device_caches,
                )
                from spark_rapids_tpu.runtime.spill import BufferCatalog
                evict_device_caches()
                BufferCatalog.get().spill_all_device()
            except Exception:
                pass  # recovery must never raise
            return "retry"
        if n == 2:
            with self._lock:
                self._mem_chunked += 1
                self._metrics.add("memoryChunkedReexecutions", 1)
            try:
                from spark_rapids_tpu.columnar.table import (
                    evict_device_caches,
                )
                evict_device_caches()  # a cached unchunked image would
                # serve the replay the very batch that did not fit
            except Exception:
                pass
            return "chunk"
        op = getattr(exc, "fault_op", None)
        if op is None:
            return "abort"
        from spark_rapids_tpu.runtime.faults import CIRCUIT_BREAKER
        # force-demote: one recorded failure at threshold 1 trips the
        # breaker, and the replay's re-plan falls the op back to CPU
        CIRCUIT_BREAKER.record_failure(op, exc, max_failures=1)
        with self._lock:
            self._mem_cpu_demotions += 1
            self._metrics.add("memoryCpuDemotions", 1)
        return "cpu_demote"

    def memory_snapshot(self) -> Dict[str, int]:
        with self._lock:
            return self._memory_snapshot_locked()

    def _memory_snapshot_locked(self) -> Dict[str, int]:
        return {
            "memoryPressureEvents": self._mem_events,
            "memoryConsecutive": self._mem_consecutive,
            "memoryChunkedReexecutions": self._mem_chunked,
            "memoryCpuDemotions": self._mem_cpu_demotions,
        }

    def _invalidate_device_caches_locked(self) -> None:
        """Drop every cache that references device state — cached
        executables hold device-resident interned constants, kernel
        traces point at compiled programs on the dead backend, and
        cached scan images ARE device arrays. Today (pre-PR) these
        would all be served stale after a reinit."""
        from spark_rapids_tpu.columnar.table import evict_device_caches
        from spark_rapids_tpu.dispatch import (
            clear_device_constants,
            clear_pallas_programs,
        )
        from spark_rapids_tpu.ops.expr import clear_kernel_caches
        from spark_rapids_tpu.parallel.exchange import clear_mesh_caches
        from spark_rapids_tpu.plan.executable_cache import EXEC_CACHE
        EXEC_CACHE.invalidate_all()
        clear_kernel_caches()
        clear_pallas_programs()
        clear_device_constants()
        evict_device_caches()
        # mesh-exchange caches key on device IDS, which survive a reinit
        # unchanged — they'd serve the dead backend's buffers without this
        clear_mesh_caches()
        try:
            import jax
            jax.clear_caches()
        except Exception:
            pass  # recovery must never raise

    def _reinitialize_backend_locked(self, conf) -> None:
        """Re-run device discovery on the live manager (new PJRT client
        state picks up here). Best-effort: a reinit that itself fails
        leaves the next query to fail, bump the consecutive count, and
        drive toward the CPU-only latch."""
        self._invalidate_device_caches_locked()
        try:
            from spark_rapids_tpu.runtime.device_manager import (
                TpuDeviceManager,
            )
            mgr = TpuDeviceManager.current()
            if mgr is not None:
                mgr.initialized = False
                mgr.initialize()
        except Exception:
            pass

    # -- introspection -------------------------------------------------------
    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return self._snapshot_locked()

    def _snapshot_locked(self) -> Dict[str, int]:
        return {
            "deviceLost": self._losses,
            "deviceReinits": self._reinits,
            "consecutiveLosses": self._consecutive_losses,
        }

    def reset(self) -> None:
        with self._lock:
            self._consecutive_losses = 0
            self._reinits = 0
            self._losses = 0
            self._cpu_only_reason = None
            self._generation += 1
            self._mesh_consecutive = 0
            self._mesh_losses = 0
            self._mesh_shrinks = 0
            self._mesh_degradations = 0
            self._host_consecutive = 0
            self._host_losses = 0
            self._host_shrinks = 0
            self._mem_consecutive = 0
            self._mem_events = 0
            self._mem_chunked = 0
            self._mem_cpu_demotions = 0


HEALTH = DeviceHealthMonitor()


class QuarantineRegistry:
    """Strike ledger per query TEMPLATE (literal-stripped structural
    fingerprint): a template that repeatedly kills workers or the
    device is the prime poison suspect, whatever its literals. Plans
    too dynamic to fingerprint (UDF closures) cannot be quarantined —
    they also cannot hit any cache, so each run is independent."""

    def __init__(self):
        self._lock = ordered_lock("health.quarantine")
        self._metrics = metric_scope("health")
        #: template_fp -> ordered strike reasons
        self._strikes: Dict[str, List[str]] = {}
        self._quarantined: Dict[str, List[str]] = {}

    def strike(self, template_fp: Optional[str], reason: str,
               max_strikes: int) -> bool:
        """Record one kill against ``template_fp``; returns True when
        this strike quarantined the template."""
        if template_fp is None:
            return False
        with self._lock:
            history = self._strikes.setdefault(template_fp, [])
            history.append(reason)
            strikes = len(history)
            self._metrics.add("quarantineStrikes", 1)
            already = template_fp in self._quarantined
            quarantined = (not already
                           and strikes >= max(1, int(max_strikes)))
            if quarantined:
                self._quarantined[template_fp] = list(history)
                self._metrics.add("quarantinedTemplates", 1)
        # flight-recorder hook OUTSIDE the registry lock (the bundle
        # re-reads this registry's snapshot) and ASYNC: callers hold
        # the scheduler's condition lock here (worker-loss handling),
        # and a bundle write to a slow dir must not stall the
        # service's submit/pick/finish paths for its duration
        try:
            from spark_rapids_tpu.obs.telemetry import (
                record_incident_async,
            )
            record_incident_async(
                "quarantine", "quarantined" if quarantined else "strike",
                reason, extra={"template": template_fp,
                               "strikes": strikes})
        except Exception:
            pass
        return quarantined

    def is_quarantined(self, template_fp: Optional[str]) -> Optional[List[str]]:
        """The strike history when quarantined, else None."""
        if template_fp is None:
            return None
        with self._lock:
            history = self._quarantined.get(template_fp)
            return list(history) if history is not None else None

    def strike_count(self, template_fp: Optional[str]) -> int:
        if template_fp is None:
            return 0
        with self._lock:
            return len(self._strikes.get(template_fp, ()))

    def history(self, template_fp: Optional[str]) -> List[str]:
        if template_fp is None:
            return []
        with self._lock:
            return list(self._strikes.get(template_fp, ()))

    def snapshot(self) -> dict:
        with self._lock:
            return self._snapshot_locked()

    def _snapshot_locked(self) -> dict:
        return {
            "templatesWithStrikes": len(self._strikes),
            "strikes": sum(len(v) for v in self._strikes.values()),
            "quarantined": len(self._quarantined),
        }

    def reset(self) -> None:
        with self._lock:
            n = len(self._quarantined)
            self._strikes = {}
            self._quarantined = {}
            if n:
                self._metrics.add("quarantinedTemplates", -n)


QUARANTINE = QuarantineRegistry()


def consistent_topology_snapshot() -> dict:
    """ONE coherent view of the whole fleet topology — host cluster,
    device health ladders, quarantine ledger, mesh, memory arbiter —
    taken with every owning lock held simultaneously, so the sections
    cannot tear against each other across a mid-query shrink (a host
    loss updates the cluster under its own lock, releases it, and only
    THEN excludes the host's devices from the mesh; independent
    section reads can observe the half-applied shrink).

    This is the shared-topology path: ``QueryService.health()``, the
    ``/topology`` introspection route, and the fleet closure all read
    it, so admission control and the degradation ladders argue about
    the same topology. Locks nest in declared ascending rank —
    cluster.runtime(300) → health.monitor(400) → health.quarantine(410)
    → mesh.runtime(530) → memory.arbiter(740) — and every body under
    the nest is a pure dict read (RL-LOCK-EFFECT clean). The memory
    budget is resolved BEFORE the nest: budget_bytes() self-acquires
    the arbiter lock, which is non-reentrant by contract."""
    from spark_rapids_tpu.parallel.mesh import MESH
    from spark_rapids_tpu.runtime.cluster import CLUSTER
    from spark_rapids_tpu.runtime.memory import MEMORY
    mem_budget = MEMORY.budget_bytes()
    with CLUSTER._lock:
        with HEALTH._lock:
            with QUARANTINE._lock:
                with MESH._lock:
                    with MEMORY._lock:
                        return {
                            "generation": HEALTH._generation,
                            "state": HEALTH.state(),
                            "cpuOnlyReason": HEALTH.cpu_only_reason(),
                            "backend": HEALTH._snapshot_locked(),
                            "hosts": {
                                **CLUSTER._health_snapshot_locked(),
                                **HEALTH._host_snapshot_locked(),
                            },
                            "mesh": {
                                **MESH._health_snapshot_locked(),
                                **HEALTH._mesh_snapshot_locked(),
                            },
                            "memory": {
                                **MEMORY._snapshot_locked(mem_budget),
                                **HEALTH._memory_snapshot_locked(),
                            },
                            "quarantine": QUARANTINE._snapshot_locked(),
                        }
