"""Pandas/Arrow Python UDF exec tests (reference: udf_test.py +
execution/python/ execs — SURVEY.md §2.3/§3.5)."""

import numpy as np
import pandas as pd
import pytest

from spark_rapids_tpu import functions as F
from spark_rapids_tpu.errors import ColumnarProcessingError
from spark_rapids_tpu.ops.expr import col
from spark_rapids_tpu import types as T


def _df(s, n=600, batches=3, seed=0):
    rng = np.random.default_rng(seed)
    return s.create_dataframe(
        {"k": rng.integers(0, 8, n).astype(np.int64),
         "v": rng.standard_normal(n),
         "w": rng.integers(-50, 50, n).astype(np.int64)},
        num_batches=batches)


# -- map_in_pandas -----------------------------------------------------------

def test_map_in_pandas(session, cpu_session):
    def fn(pdfs):
        for pdf in pdfs:
            out = pdf[pdf.v > 0][["k", "v"]].copy()
            out["v2"] = out.v * 2
            yield out

    def q(s):
        return _df(s).map_in_pandas(
            fn, [("k", T.LONG), ("v", T.DOUBLE), ("v2", T.DOUBLE)])

    got = sorted(q(session).collect())
    want = sorted(q(cpu_session).collect())
    assert got == want
    assert len(got) > 0


def test_map_in_pandas_runs_on_tpu(session):
    df = _df(session).map_in_pandas(
        lambda it: (pdf[["k"]] for pdf in it), [("k", T.LONG)])
    plan = df.explain()
    assert "TpuMapInPandasExec" in plan or "MapInPandas" in plan
    assert df.count() == 600


def test_map_in_pandas_schema_mismatch_raises(session):
    df = _df(session).map_in_pandas(
        lambda it: (pdf[["k"]] for pdf in it),
        [("missing", T.STRING)])
    with pytest.raises(ColumnarProcessingError, match="declared schema"):
        df.collect()


# -- apply_in_pandas (FlatMapGroupsInPandas) --------------------------------

def test_apply_in_pandas(session, cpu_session):
    def center(pdf):
        out = pdf.copy()
        out["v"] = out.v - out.v.mean()
        return out[["k", "v"]]

    def q(s):
        return (_df(s).group_by("k")
                .apply_in_pandas(center, [("k", T.LONG), ("v", T.DOUBLE)]))

    got = sorted(q(session).collect())
    want = sorted(q(cpu_session).collect())
    assert len(got) == len(want) == 600
    for g, w in zip(got, want):
        assert g[0] == w[0]
        assert abs(g[1] - w[1]) <= 1e-9 * max(1.0, abs(w[1]))


def test_apply_in_pandas_shrinking_groups(session):
    # fn returning one row per group (top-1 by v)
    def top1(pdf):
        return pdf.nlargest(1, "v")[["k", "v"]]

    df = (_df(session).group_by("k")
          .apply_in_pandas(top1, [("k", T.LONG), ("v", T.DOUBLE)]))
    rows = df.collect()
    assert len(rows) == 8  # one per key


# -- grouped-agg pandas UDFs (AggregateInPandas) ----------------------------

def test_aggregate_in_pandas(session, cpu_session):
    @F.pandas_udf("double", "grouped_agg")
    def mean_udf(v: pd.Series) -> float:
        return float(v.mean())

    @F.pandas_udf("long", "grouped_agg")
    def span_udf(w: pd.Series) -> int:
        return int(w.max() - w.min())

    def q(s):
        return (_df(s).group_by("k")
                .agg(mean_udf("v").alias("m"), span_udf("w").alias("s")))

    got = sorted(q(session).collect())
    want = sorted(q(cpu_session).collect())
    assert len(got) == len(want) == 8
    for g, w in zip(got, want):
        assert g[0] == w[0] and g[2] == w[2]
        assert abs(g[1] - w[1]) <= 1e-9 * max(1.0, abs(w[1]))


def test_mixing_pandas_and_builtin_aggs_rejected(session):
    @F.pandas_udf("double", "grouped_agg")
    def m(v):
        return float(v.mean())

    with pytest.raises(ValueError, match="cannot mix"):
        _df(session).group_by("k").agg(m("v"), F.sum("v").alias("s"))


# -- scalar pandas UDFs (ArrowEvalPython) -----------------------------------

def test_scalar_pandas_udf_in_select(session, cpu_session):
    @F.pandas_udf("double")
    def plus_one(v: pd.Series) -> pd.Series:
        return v + 1.0

    @F.pandas_udf("string")
    def fmt(k: pd.Series, w: pd.Series) -> pd.Series:
        return k.astype(str) + ":" + w.astype(str)

    def q(s):
        return _df(s).select("k", plus_one("v").alias("v1"),
                             fmt("k", "w").alias("t"))

    got = sorted(q(session).collect())
    want = sorted(q(cpu_session).collect())
    assert got == want
    assert isinstance(got[0][2], str) and ":" in got[0][2]


def test_scalar_udf_over_expression_args(session, cpu_session):
    @F.pandas_udf("double")
    def square(x: pd.Series) -> pd.Series:
        return x * x

    def q(s):
        return _df(s).select(square(col("v") + col("w")).alias("sq"))

    got = sorted(q(session).collect())
    want = sorted(q(cpu_session).collect())
    assert len(got) == len(want)
    for g, w in zip(got, want):
        assert abs(g[0] - w[0]) <= 1e-9 * max(1.0, abs(w[0]))


def test_nested_scalar_udf_rejected(session):
    @F.pandas_udf("double")
    def p1(v):
        return v + 1

    with pytest.raises(ColumnarProcessingError, match="top-level"):
        _df(session).select((p1("v") + col("w")).alias("x"))


def test_wrong_length_result_raises(session):
    @F.pandas_udf("double")
    def bad(v: pd.Series) -> pd.Series:
        return v.head(3)

    df = _df(session).select(bad("v").alias("x"))
    with pytest.raises(ColumnarProcessingError, match="rows"):
        df.collect()


# -- worker semaphore --------------------------------------------------------

def test_python_worker_semaphore_bounds_concurrency(session):
    import threading
    from spark_rapids_tpu.session import TpuSession

    live = [0]
    peak = [0]
    lock = threading.Lock()

    def probe(pdf):
        with lock:
            live[0] += 1
            peak[0] = max(peak[0], live[0])
        import time
        time.sleep(0.02)
        with lock:
            live[0] -= 1
        return pdf[["k", "v"]]

    s = TpuSession({"spark.rapids.python.concurrentPythonWorkers": "1"})
    df = (_df(s).group_by("k")
          .apply_in_pandas(probe, [("k", T.LONG), ("v", T.DOUBLE)]))
    assert df.count() == 600
    assert peak[0] == 1


def test_nested_udf_execs_do_not_deadlock():
    """map_in_pandas over a child scalar-UDF exec with ONE worker permit:
    the semaphore must be thread-reentrant (review fix)."""
    from spark_rapids_tpu.session import TpuSession
    s = TpuSession({"spark.rapids.python.concurrentPythonWorkers": "1"})

    @F.pandas_udf("double")
    def plus_one(v):
        return v + 1.0

    inner = _df(s).select("k", plus_one("v").alias("v1"))
    out = inner.map_in_pandas(
        lambda it: (pdf[pdf.v1 > 1.0] for pdf in it),
        [("k", T.LONG), ("v1", T.DOUBLE)])
    assert out.count() > 0


# -- map_in_arrow (MapInArrow / GpuMapInArrowExec) ---------------------------

def test_map_in_arrow(session, cpu_session):
    import pyarrow as pa

    def fn(rbs):
        for rb in rbs:
            t = pa.Table.from_batches([rb])
            yield t.append_column(
                "v2", pa.compute.multiply(t.column("v"), 2.0))

    def q(s):
        return _df(s).map_in_arrow(
            fn, [("k", T.LONG), ("v", T.DOUBLE), ("w", T.LONG),
                 ("v2", T.DOUBLE)])

    got = sorted(q(session).collect())
    want = sorted(q(cpu_session).collect())
    assert got == want
    assert len(got) == 600


def test_map_in_arrow_runs_on_tpu(session):
    df = _df(session).map_in_arrow(
        lambda it: it, [("k", T.LONG), ("v", T.DOUBLE), ("w", T.LONG)])
    assert "MapInArrow" in df.explain()
    assert df.count() == 600


def test_map_in_arrow_schema_mismatch_raises(session):
    df = _df(session).map_in_arrow(
        lambda it: it, [("missing", T.STRING)])
    with pytest.raises(ColumnarProcessingError, match="declared schema"):
        df.collect()


# -- cogroup (FlatMapCoGroupsInPandas) ---------------------------------------

def _cogroup_dfs(s):
    left = s.create_dataframe(
        {"k": np.array([0, 0, 1, 2, 2, 5], dtype=np.int64),
         "v": np.array([1.0, 2.0, 3.0, 4.0, 5.0, 6.0])}, num_batches=2)
    right = s.create_dataframe(
        {"kk": np.array([0, 2, 2, 3], dtype=np.int64),
         "w": np.array([10.0, 20.0, 30.0, 40.0])})
    return left, right


def test_cogroup_apply_in_pandas(session, cpu_session):
    def merge(l, r):
        return pd.DataFrame({
            "k": [l.k.iloc[0] if len(l) else r.kk.iloc[0]],
            "lsum": [float(l.v.sum())],
            "rsum": [float(r.w.sum())]})

    def q(s):
        left, right = _cogroup_dfs(s)
        return (left.group_by("k").cogroup(right.group_by("kk"))
                .apply_in_pandas(
                    merge, [("k", T.LONG), ("lsum", T.DOUBLE),
                            ("rsum", T.DOUBLE)]))

    got = sorted(q(session).collect())
    want = sorted(q(cpu_session).collect())
    assert got == want
    # keys on either side: 0,1,2 from left, 3 only on right, 5 only left
    assert [r[0] for r in got] == [0, 1, 2, 3, 5]
    # key 3 sees an empty left frame, key 5 an empty right frame
    by_k = {r[0]: r for r in got}
    assert by_k[3][1] == 0.0 and by_k[3][2] == 40.0
    assert by_k[5][1] == 6.0 and by_k[5][2] == 0.0


def test_cogroup_key_arity_mismatch_raises(session):
    left, right = _cogroup_dfs(session)
    with pytest.raises(ColumnarProcessingError, match="arity"):
        (left.group_by("k").cogroup(right.group_by("kk", "w"))
         .apply_in_pandas(lambda l, r: l, [("k", T.LONG)]))


def test_cogroup_runs_on_tpu(session):
    left, right = _cogroup_dfs(session)
    df = (left.group_by("k").cogroup(right.group_by("kk"))
          .apply_in_pandas(
              lambda l, r: pd.DataFrame({"n": [len(l) + len(r)]}),
              [("n", T.LONG)]))
    assert "FlatMapCoGroupsInPandas" in df.explain()
    assert sum(r[0] for r in df.collect()) == 10


# -- window pandas UDFs (WindowInPandas) -------------------------------------

def test_window_in_pandas_unbounded(session, cpu_session):
    from spark_rapids_tpu.ops.window import Window as W

    @F.pandas_udf("double", "grouped_agg")
    def gmean(v):
        return float(v.mean())

    def q(s):
        return _df(s, n=200, batches=2).with_windows(
            m=gmean("v").over(W.partition_by("k")))

    got = sorted(q(session).collect())
    want = sorted(q(cpu_session).collect())
    assert len(got) == 200
    for g, w in zip(got, want):
        assert g[:3] == w[:3]
        assert abs(g[3] - w[3]) < 1e-9


def test_window_in_pandas_bounded_rows(session, cpu_session):
    from spark_rapids_tpu.ops.window import Window as W

    @F.pandas_udf("double", "grouped_agg")
    def gsum(v):
        return float(v.sum())

    spec = (W.partition_by("k").order_by("w")
            .rows_between(-1, 1))  # sliding 3-row frame

    def q(s):
        return _df(s, n=60, batches=1).with_windows(m=gsum("v").over(spec))

    got = sorted(q(session).collect())
    want = sorted(q(cpu_session).collect())
    for g, w in zip(got, want):
        assert abs(g[3] - w[3]) < 1e-9


def test_window_in_pandas_mixed_with_builtin(session):
    from spark_rapids_tpu.ops.window import Window as W

    @F.pandas_udf("double", "grouped_agg")
    def gmax(v):
        return float(v.max())

    df = _df(session, n=100, batches=1).with_windows(
        rn=F.row_number().over(W.partition_by("k").order_by("v")),
        m=gmax("v").over(W.partition_by("k")))
    rows = df.collect()
    assert len(rows) == 100
    # per-k max column must equal the true group max
    import collections
    gm = collections.defaultdict(lambda: -1e18)
    for r in rows:
        gm[r[0]] = max(gm[r[0]], r[1])
    for r in rows:
        assert abs(r[4] - gm[r[0]]) < 1e-12


def test_window_in_pandas_scalar_udf_over_raises(session):
    from spark_rapids_tpu.ops.window import Window as W

    @F.pandas_udf("double")
    def sc(v):
        return v

    with pytest.raises(ColumnarProcessingError, match="grouped_agg"):
        sc("v").over(W.partition_by("k"))


def test_window_in_pandas_running_frame(session, cpu_session):
    """Default ORDER BY frame = RANGE UNBOUNDED PRECEDING..CURRENT ROW:
    a running aggregate whose frame ends at the last PEER (review fix)."""
    from spark_rapids_tpu.ops.window import Window as W

    @F.pandas_udf("double", "grouped_agg")
    def gsum(v):
        return float(v.sum())

    def q(s):
        df = s.create_dataframe(
            {"k": np.array([0, 0, 0, 0, 0, 1, 1], dtype=np.int64),
             "t": np.array([1, 2, 2, 3, 4, 1, 1], dtype=np.int64),
             "v": np.array([1.0, 2.0, 3.0, 4.0, 5.0, 6.0, 7.0])})
        return df.with_windows(
            rs=gsum("v").over(W.partition_by("k").order_by("t")))

    got = sorted(q(session).collect())
    want = sorted(q(cpu_session).collect())
    assert got == want
    by = {(r[0], r[1], r[2]): r[3] for r in got}
    # k=0: t=1 -> 1; t=2 peers (2,3) both see 1+2+3=6; t=3 -> 10; t=4 -> 15
    assert by[(0, 1, 1.0)] == 1.0
    assert by[(0, 2, 2.0)] == 6.0 and by[(0, 2, 3.0)] == 6.0
    assert by[(0, 3, 4.0)] == 10.0 and by[(0, 4, 5.0)] == 15.0
    # k=1: both rows are peers at t=1 -> 13
    assert by[(1, 1, 6.0)] == 13.0 and by[(1, 1, 7.0)] == 13.0


def test_window_in_pandas_negative_frame_is_empty(session):
    """rows_between(-3, -2) near the partition start must yield an EMPTY
    frame, not wrap around (review fix)."""
    from spark_rapids_tpu.ops.window import Window as W

    @F.pandas_udf("double", "grouped_agg")
    def gsum(v):
        return float(v.sum())

    df = session.create_dataframe(
        {"k": np.zeros(5, dtype=np.int64),
         "t": np.arange(5, dtype=np.int64),
         "v": np.array([1.0, 2.0, 4.0, 8.0, 16.0])})
    rows = sorted(df.with_windows(
        m=gsum("v").over(W.partition_by("k").order_by("t")
                         .rows_between(-3, -2))).collect())
    got = [r[3] for r in rows]
    # frames: [], [], [1], [1+2], [2+4]
    assert got == [0.0, 0.0, 1.0, 3.0, 6.0]


def test_window_in_pandas_expr_partition_key_raises(session):
    from spark_rapids_tpu.ops.window import Window as W

    @F.pandas_udf("double", "grouped_agg")
    def gmean(v):
        return float(v.mean())

    with pytest.raises(ValueError, match="plain columns"):
        _df(session).with_windows(
            m=gmean("v").over(W.partition_by(col("k") + col("w"))))


def test_cogroup_null_keys_align(session, cpu_session):
    """Null keys present on BOTH sides cogroup into ONE pair (review
    fix: NaN != NaN must not split the null group)."""
    def q(s):
        left = s.create_dataframe(
            {"k": np.array([1.0, np.nan, np.nan]),
             "v": np.array([10.0, 20.0, 30.0])})
        right = s.create_dataframe(
            {"kk": np.array([np.nan, 2.0]),
             "u": np.array([5.0, 7.0])})
        return (left.group_by("k").cogroup(right.group_by("kk"))
                .apply_in_pandas(
                    lambda l, r: pd.DataFrame(
                        {"nl": [len(l)], "nr": [len(r)]}),
                    [("nl", T.LONG), ("nr", T.LONG)]))

    got = sorted(q(session).collect())
    assert got == sorted(q(cpu_session).collect())
    # pairs: k=1 (1,0), k=2 (0,1), k=null (2,1) — exactly 3 pairs
    assert got == [[0, 1], [1, 0], [2, 1]] or \
        [tuple(r) for r in got] == [(0, 1), (1, 0), (2, 1)]


def test_window_in_pandas_unknown_column_raises_at_plan(session):
    from spark_rapids_tpu.ops.window import Window as W

    @F.pandas_udf("double", "grouped_agg")
    def gmean(v):
        return float(v.mean())

    with pytest.raises(ColumnarProcessingError, match="nope"):
        _df(session).with_windows(
            m=gmean("nope").over(W.partition_by("k")))


def test_window_in_pandas_range_fully_unbounded(session):
    """range_between(None, None) = whole partition, NOT a running frame
    (review fix)."""
    from spark_rapids_tpu.ops.window import Window as W

    @F.pandas_udf("double", "grouped_agg")
    def gsum(v):
        return float(v.sum())

    df = session.create_dataframe(
        {"k": np.zeros(3, dtype=np.int64),
         "t": np.arange(3, dtype=np.int64),
         "v": np.array([1.0, 2.0, 4.0])})
    rows = df.with_windows(m=gsum("v").over(
        W.partition_by("k").order_by("t").range_between(None, None)))
    assert [r[3] for r in sorted(rows.collect())] == [7.0, 7.0, 7.0]


def test_window_in_pandas_empty_input(session):
    """Zero-row child with a running frame must not crash (review fix)."""
    from spark_rapids_tpu.ops.expr import lit
    from spark_rapids_tpu.ops.window import Window as W

    @F.pandas_udf("double", "grouped_agg")
    def gsum(v):
        return float(v.sum())

    df = (_df(session, n=50, batches=1)
          .filter(col("w") > lit(10**9))
          .with_windows(m=gsum("v").over(W.order_by("w"))))
    assert df.collect() == []
