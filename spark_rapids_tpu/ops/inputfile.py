"""input_file_name / input_file_block_start / input_file_block_length.

Reference: InputFileBlockRule.scala + GpuInputFileName/GpuInputFileBlock*
(org/apache/spark/sql/rapids) — the reference constrains plan chains so
the expressions stay in the same stage as the file scan (issue #3333).
This engine's analog (overrides/input_file.py) REWRITES the plan instead:
the scan attaches per-row provenance columns (file name as a 1-entry
dictionary per batch, block start/length as constants per batch) and the
expressions become bound references to them. Granularity note: the
engine's readers split at file / row-group level and report per-FILE
blocks (start 0, length = file size).

An expression that survives rewrite (no file scan below it, or a
shuffle/aggregate boundary in between) evaluates to Spark's
"no file info available" values: empty string / -1.
"""

from __future__ import annotations

import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.column import HostColumn
from spark_rapids_tpu.ops.expr import DevVal, Expression, NodePrep

#: hidden provenance column names the scan attaches
FILE_NAME_COL = "__input_file_name__"
FILE_START_COL = "__input_file_block_start__"
FILE_LENGTH_COL = "__input_file_block_length__"
FILE_INFO_COLS = (FILE_NAME_COL, FILE_START_COL, FILE_LENGTH_COL)


class _InputFileExpr(Expression):
    """Base: binds to itself; evaluates to the NO-INFO constant unless the
    plan rewrite substituted a provenance column reference."""

    children = ()

    def bind(self, schema):
        return self

    def with_children(self, children):
        return self

    def key(self):
        return (self.name.lower(),)

    @property
    def nullable(self):
        return False

    def _no_info(self):  # (numpy fill value,)
        raise NotImplementedError

    def eval_cpu(self, table):
        n = table.num_rows
        return self._host_const(n)

    def prep(self, pctx, child_preps) -> NodePrep:
        return self._dev_prep(pctx)

    def eval_dev(self, ctx, child_vals, prep) -> DevVal:
        import jax.numpy as jnp
        data = jnp.zeros(ctx.capacity, dtype=self._dev_dtype())
        valid = jnp.ones(ctx.capacity, dtype=jnp.bool_)
        return DevVal(data + self._dev_fill(), valid)


class InputFileName(_InputFileExpr):
    name = "InputFileName"

    @property
    def data_type(self):
        return T.STRING

    def _host_const(self, n):
        data = np.empty(n, dtype=object)
        data[:] = ""
        return HostColumn(T.STRING, data)

    def _dev_prep(self, pctx):
        return NodePrep(out_dict=np.array([""], dtype=object))

    def _dev_dtype(self):
        import jax.numpy as jnp
        return jnp.int32  # dictionary code 0 -> ""

    def _dev_fill(self):
        return 0


class InputFileBlockStart(_InputFileExpr):
    name = "InputFileBlockStart"

    @property
    def data_type(self):
        return T.LONG

    def _host_const(self, n):
        return HostColumn(T.LONG, np.full(n, -1, dtype=np.int64))

    def _dev_prep(self, pctx):
        return NodePrep()

    def _dev_dtype(self):
        import jax.numpy as jnp
        return jnp.int64

    def _dev_fill(self):
        return -1


class InputFileBlockLength(InputFileBlockStart):
    name = "InputFileBlockLength"


def contains_input_file_expr(expr: Expression) -> bool:
    if isinstance(expr, _InputFileExpr):
        return True
    return any(contains_input_file_expr(c) for c in expr.children)


def substitute(expr: Expression, schema) -> Expression:
    """Replace input_file_* nodes with bound references to the hidden
    provenance columns present in ``schema``."""
    from spark_rapids_tpu.ops.expr import BoundReference
    names = [n for n, _ in schema]
    if isinstance(expr, _InputFileExpr):
        col = {InputFileName: FILE_NAME_COL,
               InputFileBlockStart: FILE_START_COL,
               InputFileBlockLength: FILE_LENGTH_COL}[type(expr)]
        i = names.index(col)
        return BoundReference(i, schema[i][1], name_hint=col)
    kids = [substitute(c, schema) for c in expr.children]
    return expr.with_children(kids)
