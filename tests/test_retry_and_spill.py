"""Memory runtime tests: spill tiers, OOM retry/split, semaphore.

Mirrors the reference's retry suites (SURVEY.md §4: WithRetrySuite,
HashAggregateRetrySuite, GpuSortRetrySuite, GpuCoalesceBatchesRetrySuite,
RapidsBufferCatalogSuite, RapidsHostMemoryStoreSuite, RapidsDiskStoreSuite)
with injected OOMs instead of real allocator pressure."""

import threading

import numpy as np
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar import DeviceTable, HostTable
from spark_rapids_tpu.errors import FatalDeviceOOM, RetryOOM
from spark_rapids_tpu.runtime.retry import (
    RMM_TPU,
    retry_block,
    split_device_table_in_half,
    with_retry,
    with_retry_no_split,
)
from spark_rapids_tpu.runtime.semaphore import TpuSemaphore, acquired
from spark_rapids_tpu.runtime.spill import (
    TIER_DEVICE,
    TIER_DISK,
    TIER_HOST,
    BufferCatalog,
    SpillableBatch,
)
from tests.data_gen import IntGen, LongGen, StringGen, gen_table


@pytest.fixture(autouse=True)
def clean_state():
    RMM_TPU.clear()
    yield
    RMM_TPU.clear()


@pytest.fixture()
def catalog():
    return BufferCatalog(host_limit_bytes=1 << 20)


def _dev_table(n=500, seed=1):
    host = gen_table({"a": IntGen(), "b": LongGen(), "s": StringGen()}, n, seed=seed)
    return DeviceTable.from_host(host), host


# -- spill framework --------------------------------------------------------

def test_spill_device_host_disk_roundtrip(catalog):
    dt, host = _dev_table()
    sb = SpillableBatch(dt, catalog)
    del dt
    assert sb.tier == TIER_DEVICE
    assert catalog.device_bytes() > 0

    freed = sb.spill_to_host()
    assert freed > 0 and sb.tier == TIER_HOST
    assert catalog.device_bytes() == 0

    freed2 = sb.spill_to_disk()
    assert freed2 > 0 and sb.tier == TIER_DISK
    assert catalog.host_bytes() == 0

    back = sb.get()  # disk -> device
    assert sb.tier == TIER_DEVICE
    assert back.to_host().to_pydict() == host.to_pydict()
    sb.release()


def test_synchronous_spill_frees_by_priority(catalog):
    tables = [_dev_table(200, seed=i)[0] for i in range(4)]
    sbs = [SpillableBatch(t, catalog, priority=i) for i, t in enumerate(tables)]
    del tables
    target = sbs[0].device_bytes + 1
    catalog.synchronous_spill(target)
    # lowest priority spilled first
    assert sbs[0].tier == TIER_HOST
    assert sbs[3].tier == TIER_DEVICE
    for sb in sbs:
        sb.release()


def test_pinned_batches_do_not_spill(catalog):
    dt, _ = _dev_table(100)
    sb = SpillableBatch(dt, catalog)
    sb.pin()
    assert catalog.synchronous_spill(1 << 62) == 0
    assert sb.tier == TIER_DEVICE
    sb.unpin()
    assert catalog.synchronous_spill(1 << 62) > 0
    assert sb.tier == TIER_HOST
    sb.release()


def test_host_limit_overflows_to_disk():
    catalog = BufferCatalog(host_limit_bytes=1)  # everything overflows
    dt, _ = _dev_table(300)
    sb = SpillableBatch(dt, catalog)
    del dt
    catalog.synchronous_spill(1 << 62)
    assert sb.tier == TIER_DISK
    assert catalog.spill_disk_count == 1
    sb.release()


# -- split ------------------------------------------------------------------

def test_split_in_half_preserves_rows():
    dt, host = _dev_table(333)
    a, b = split_device_table_in_half(dt)
    assert a.num_rows + b.num_rows == 333
    merged = HostTable.concat([a.to_host(), b.to_host()])
    assert merged.to_pydict() == host.to_pydict()


def test_split_single_row_raises():
    dt, _ = _dev_table(1)
    with pytest.raises(FatalDeviceOOM):
        split_device_table_in_half(dt)


# -- with_retry -------------------------------------------------------------

def test_with_retry_replays_same_input(catalog):
    dt, host = _dev_table(100)
    RMM_TPU.force_retry_oom(2)
    calls = []

    def fn(t):
        calls.append(t.num_rows)
        return t.to_host().to_pydict()

    outs = list(with_retry(dt, fn, catalog=catalog))
    assert len(outs) == 1 and outs[0] == host.to_pydict()
    assert RMM_TPU.retry_count == 2


def test_with_retry_split_escalation(catalog):
    dt, host = _dev_table(100)
    RMM_TPU.force_split_and_retry_oom(1)
    outs = list(with_retry(dt, lambda t: t.to_host(), catalog=catalog))
    assert len(outs) == 2  # halves
    assert HostTable.concat(outs).to_pydict() == host.to_pydict()
    assert RMM_TPU.split_count == 1


def test_with_retry_exhaustion_splits_after_max_retries(catalog):
    dt, _ = _dev_table(64)

    class FakeOOM(Exception):
        pass

    FakeOOM.__name__ = "XlaRuntimeError"
    fails = {"n": 3}

    def fn(t):
        if fails["n"] > 0:
            fails["n"] -= 1
            raise FakeOOM("RESOURCE_EXHAUSTED: out of memory")
        return t.num_rows

    outs = list(with_retry(dt, fn, max_retries=2, catalog=catalog))
    assert sum(outs) == 64 and len(outs) == 2  # split happened once


def test_with_retry_no_split_raises_fatal(catalog):
    dt, _ = _dev_table(64)
    RMM_TPU.force_split_and_retry_oom(1)
    with pytest.raises(FatalDeviceOOM):
        list(with_retry_no_split(dt, lambda t: t, catalog=catalog))


def test_with_retry_passes_through_other_errors(catalog):
    dt, _ = _dev_table(16)
    with pytest.raises(ValueError):
        list(with_retry(dt, lambda t: (_ for _ in ()).throw(ValueError("x")),
                        catalog=catalog))


def test_retry_block_spills_then_succeeds(catalog):
    other, _ = _dev_table(512, seed=9)
    sb = SpillableBatch(other, catalog)
    del other
    RMM_TPU.force_retry_oom(1)
    out = retry_block(lambda: 42, catalog=catalog)
    assert out == 42
    assert sb.tier == TIER_HOST  # the retry spilled registered buffers
    sb.release()


# -- operator integration (injection through the conf) ----------------------

@pytest.mark.parametrize("inject", ["retry:2", "split:1"])
def test_query_survives_injected_oom(session, cpu_session, inject):
    from spark_rapids_tpu import functions as F
    from spark_rapids_tpu.ops.expr import col
    from spark_rapids_tpu.session import TpuSession

    host = gen_table({"k": IntGen(min_val=0, max_val=9), "v": LongGen()}, 2000, seed=5)
    inj_session = TpuSession({"spark.rapids.sql.test.injectRetryOOM": inject})

    def build(s):
        return (s.create_dataframe(host, num_batches=3)
                .filter(col("v").isnotnull())
                .group_by("k").agg(F.sum("v").alias("sv"),
                                   F.count("v").alias("c")))

    got = sorted(map(str, build(inj_session).collect()))
    want = sorted(map(str, build(cpu_session).collect()))
    assert got == want


def test_join_survives_injected_oom(cpu_session):
    from spark_rapids_tpu.session import TpuSession
    host_l = gen_table({"k": IntGen(min_val=0, max_val=20), "lv": LongGen()}, 300, seed=1)
    host_r = gen_table({"k": IntGen(min_val=0, max_val=20), "rv": LongGen()}, 200, seed=2)
    inj = TpuSession({"spark.rapids.sql.test.injectRetryOOM": "retry:1"})

    def build(s):
        return s.create_dataframe(host_l).join(s.create_dataframe(host_r),
                                               on="k", how="inner")
    got = sorted(map(str, build(inj).collect()))
    want = sorted(map(str, build(cpu_session).collect()))
    assert got == want


# -- semaphore --------------------------------------------------------------

def test_semaphore_limits_concurrency():
    sem = TpuSemaphore(2)
    active = []
    peak = []
    lock = threading.Lock()

    def work(i):
        with acquired(sem):
            with lock:
                active.append(i)
                peak.append(len(active))
            import time
            time.sleep(0.02)
            with lock:
                active.remove(i)

    threads = [threading.Thread(target=work, args=(i,)) for i in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert max(peak) <= 2
    assert sem.acquire_count == 6


def test_semaphore_reentrant():
    sem = TpuSemaphore(1)
    with acquired(sem):
        with acquired(sem):  # same thread re-enters
            assert sem.holders == 1
    assert sem.holders == 0


def test_semaphore_live_downsizing_wakes_waiters_as_holders_release():
    """Regression pin for shrinking max_tasks below the CURRENT holder
    count via initialize() on the live singleton: a waiter must stay
    blocked until holders drop BELOW the new cap, then wake promptly —
    release's notify_all plus the waiter's len(holders) >= max_tasks
    recheck cover the shrink correctly."""
    import time
    saved = TpuSemaphore._instance
    TpuSemaphore._instance = None
    try:
        sem = TpuSemaphore.initialize(2)
        hold = [threading.Event() for _ in range(2)]
        started = [threading.Event() for _ in range(2)]

        def holder(i):
            sem.acquire_if_necessary()
            started[i].set()
            hold[i].wait(10)
            sem.release_if_held()

        holders = [threading.Thread(target=holder, args=(i,))
                   for i in range(2)]
        for t in holders:
            t.start()
        for s in started:
            assert s.wait(5)
        assert sem.holders == 2

        shrunk = TpuSemaphore.initialize(1)  # live downsize, holders carry
        assert shrunk is sem and sem.max_tasks == 1

        got = threading.Event()

        def waiter():
            sem.acquire_if_necessary(timeout=8)
            got.set()
            sem.release_if_held()

        w = threading.Thread(target=waiter)
        w.start()
        time.sleep(0.1)
        assert not got.is_set()  # 2 holders >= cap 1: must block
        hold[0].set()            # 2 -> 1 holders: still AT the cap
        time.sleep(0.2)
        assert not got.is_set()
        hold[1].set()            # 1 -> 0: below cap, waiter must wake
        w.join(8)
        assert got.is_set()
        for t in holders:
            t.join(5)
        assert sem.holders == 0
    finally:
        TpuSemaphore._instance = saved


def test_fruitless_counters_are_per_catalog():
    """Satellite pin: DeviceMemoryEventHandler keys its consecutive
    fruitless-spill counts by id(catalog) — two threads OOM-ing on
    DIFFERENT catalogs must not share counters (a shared count would
    pre-escalate the second thread to split on its FIRST fruitless
    spill), and reset_fruitless must clear only its own catalog."""
    from spark_rapids_tpu.runtime.retry import DeviceMemoryEventHandler
    handler = DeviceMemoryEventHandler()
    cat_a = BufferCatalog(host_limit_bytes=1 << 20)  # empty: spills free 0
    cat_b = BufferCatalog(host_limit_bytes=1 << 20)

    results = {}

    def oom_twice(name, cat, barrier):
        out = []
        for _ in range(2):
            barrier.wait(timeout=5)
            out.append(handler.on_alloc_failure(cat))
        results[name] = out

    barrier = threading.Barrier(2)
    ta = threading.Thread(target=oom_twice, args=("a", cat_a, barrier))
    tb = threading.Thread(target=oom_twice, args=("b", cat_b, barrier))
    ta.start(); tb.start()
    ta.join(10); tb.join(10)
    # each catalog gets its OWN first-fruitless grace (True), then its
    # own second-fruitless escalation (False) — no cross-talk
    assert results == {"a": [True, False], "b": [True, False]}

    # reset clears exactly one catalog's count
    handler.reset_fruitless(cat_a)
    assert handler.on_alloc_failure(cat_a) is True   # fresh grace for a
    assert handler.on_alloc_failure(cat_b) is False  # b still escalated


def test_semaphore_timeout():
    sem = TpuSemaphore(1)
    sem.acquire_if_necessary()
    err = []

    def blocked():
        try:
            sem.acquire_if_necessary(timeout=0.05)
        except TimeoutError as e:
            err.append(e)

    t = threading.Thread(target=blocked)
    t.start()
    t.join()
    assert err
    sem.release_if_held()
