"""Tier-1 slice of the cluster flight recorder (ISSUE 14).

The full closure is ``python scale_test.py --hosts 2 --chaos`` (q1-q22
with executor-span/trace, per-host profile and incident-bundle
assertions); this slice keeps every mechanism exercised in the tier-1
gate without the corpus cost:

* telemetry ring: sampler delta correctness, bounded ring, JSONL
  export, the background sampler thread;
* flight recorder: one bundle per host-ladder action (with the
  triggering fault point, rung and telemetry tail), kernel-demotion
  and quarantine-strike bundles through the conf-less default path,
  bundle pruning to maxBundles;
* cross-host trace propagation: a 2-host THREAD-mode cluster scan
  merges executor-lane spans into the driver's Chrome trace and
  attributes per-host scans bit-exactly in the v9 event record
  (hostScans), CRC retries attributed to the corrupted host;
* live introspection: `tools top` over a real QueryService's loopback
  endpoint (subprocess smoke) + the rolling SLO surface;
* `tools incident` subprocess smoke over recorded bundles;
* `tools compare`/`profile` accept OLDER event schemas with one
  warning instead of crashing on mixed-version dirs.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from spark_rapids_tpu.conf import RapidsConf

pytestmark = [pytest.mark.chaos]

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_obs_state():
    """Telemetry/flight-recorder/ladder state is PROCESS state —
    restore all of it so the rest of the suite sees defaults (the
    test_hosts hygiene pattern)."""
    from spark_rapids_tpu import kernels
    from spark_rapids_tpu.obs.telemetry import TELEMETRY
    from spark_rapids_tpu.runtime.cluster import CLUSTER
    from spark_rapids_tpu.runtime.faults import CIRCUIT_BREAKER, FAULTS
    from spark_rapids_tpu.runtime.health import HEALTH, QUARANTINE
    from spark_rapids_tpu.session import TpuSession

    def reset():
        FAULTS.disarm()
        CIRCUIT_BREAKER.reset()
        HEALTH.reset()
        QUARANTINE.reset()
        CLUSTER.restore()
        kernels.reset()
        TELEMETRY.configure(RapidsConf({}))  # recorder defaults too
        TELEMETRY.reset()

    reset()
    yield
    reset()
    # leave the process-wide cluster (and mesh) OFF for the suite
    TpuSession().placement.prepare()


# ---------------------------------------------------------------------------
# telemetry ring
# ---------------------------------------------------------------------------


def test_sampler_delta_correctness():
    """Each sample carries the per-scope DELTAS since the previous
    sample plus the health/topology view; an idle interval records no
    phantom movement."""
    from spark_rapids_tpu.obs.metrics import metric_scope
    from spark_rapids_tpu.obs.telemetry import TELEMETRY
    scope = metric_scope("ttestScope")
    base = TELEMETRY.sample_once()
    assert base is not None
    scope.add("ttestCounter", 5)
    s1 = TELEMETRY.sample_once()
    assert s1["deltas"]["ttestScope"]["ttestCounter"] == 5
    assert s1["health"] in ("HEALTHY", "DEGRADED", "CPU_ONLY")
    assert "meshShape" in s1 and "hostTopology" in s1
    assert isinstance(s1["t"], float)
    s2 = TELEMETRY.sample_once()
    assert "ttestScope" not in s2["deltas"]  # nothing moved


def test_ring_bounded_export_and_background_thread(tmp_path):
    """The ring drops oldest past ringSize, exports as JSONL, and the
    conf-driven background thread actually samples."""
    from spark_rapids_tpu.obs.telemetry import TELEMETRY
    TELEMETRY.configure(RapidsConf({
        "spark.rapids.obs.telemetry.ringSize": "5"}))
    for _ in range(9):
        TELEMETRY.sample_once()
    tail = TELEMETRY.tail()
    assert len(tail) == 5
    assert TELEMETRY.tail(2) == tail[-2:]
    path = TELEMETRY.export_jsonl(str(tmp_path / "tele.jsonl"))
    lines = open(path).read().strip().splitlines()
    assert len(lines) == 5
    assert all("deltas" in json.loads(ln) for ln in lines)
    # background sampler: enabled -> samples accrue without any query
    TELEMETRY.configure(RapidsConf({
        "spark.rapids.obs.telemetry.enabled": "true",
        "spark.rapids.obs.telemetry.intervalMs": "20",
        "spark.rapids.obs.telemetry.ringSize": "5"}))
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline:
        if TELEMETRY.stats()["samples"] >= 3:
            break
        time.sleep(0.02)
    assert TELEMETRY.stats()["samples"] >= 3
    assert TELEMETRY.stats()["errors"] == 0
    TELEMETRY.configure(RapidsConf({}))  # thread stops


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------


def test_flight_recorder_bundle_per_host_ladder_action(tmp_path):
    """Every on_host_loss invocation dumps one bundle carrying the
    triggering fault point, the ladder rung taken, topology, and the
    telemetry tail."""
    from spark_rapids_tpu.errors import HostLostError
    from spark_rapids_tpu.obs.telemetry import TELEMETRY
    from spark_rapids_tpu.runtime.cluster import CLUSTER
    from spark_rapids_tpu.runtime.health import HEALTH
    CLUSTER.configure(RapidsConf({
        "spark.rapids.cluster.enabled": "true",
        "spark.rapids.cluster.hosts": "2"}))
    conf = RapidsConf({
        "spark.rapids.obs.flightRecorder.dir": str(tmp_path)})
    TELEMETRY.sample_once()  # something for the tail
    exc = HostLostError("injected host loss at host.dispatch",
                        host_id="h1")
    assert HEALTH.on_host_loss(exc, conf) == "retry"
    assert HEALTH.on_host_loss(exc, conf) == "reland"
    from spark_rapids_tpu.tools.incident import load_bundles
    bundles = [b for b in load_bundles(str(tmp_path))
               if b["kind"] == "host.ladder"]
    assert [b["action"] for b in bundles] == ["retry", "reland"]
    b = bundles[-1]
    assert b["faultPoint"] == "host.dispatch"
    assert b["errorType"] == "HostLostError"
    assert b["health"]["hostLadder"]["hostsLost"] == 2
    assert "h1" in b["cluster"]["lostHosts"]
    assert isinstance(b["telemetry"]["tail"], list)
    assert b["telemetry"]["tail"], "telemetry tail missing"
    assert "host.ladder" in os.path.basename(b["_path"])


def test_flight_recorder_kernel_demotion_and_quarantine(tmp_path):
    """Conf-less trigger sites (kernels.demote, QUARANTINE.strike) land
    bundles in the PROCESS-configured recorder dir (the one the last
    TELEMETRY.configure saw)."""
    from spark_rapids_tpu import kernels
    from spark_rapids_tpu.obs.telemetry import TELEMETRY
    from spark_rapids_tpu.runtime.health import QUARANTINE
    TELEMETRY.configure(RapidsConf({
        "spark.rapids.obs.flightRecorder.dir": str(tmp_path)}))
    kernels.demote("compact",
                   RuntimeError("injected kernel crash at "
                                "kernels.compact"))
    assert QUARANTINE.strike("fp-ttest", "killed a worker", 2) is False
    assert QUARANTINE.strike("fp-ttest", "killed another", 2) is True
    from spark_rapids_tpu.tools.incident import load_bundles
    # strike bundles dump ASYNC (the strike site runs under the
    # scheduler's condition lock) — wait for all three
    deadline = time.monotonic() + 20.0
    while time.monotonic() < deadline:
        if len(os.listdir(tmp_path)) >= 3:
            break
        time.sleep(0.02)
    bundles = load_bundles(str(tmp_path))
    kinds = [(b["kind"], b["action"]) for b in bundles]
    assert ("kernel.demotion", "compact") in kinds
    assert ("quarantine", "strike") in kinds
    assert ("quarantine", "quarantined") in kinds
    kb = [b for b in bundles if b["kind"] == "kernel.demotion"][0]
    assert kb["faultPoint"] == "kernels.compact"
    assert "pallas:compact" in kb["demotions"]


def test_flight_recorder_prunes_to_max_bundles(tmp_path):
    from spark_rapids_tpu.obs.telemetry import record_incident
    conf = RapidsConf({
        "spark.rapids.obs.flightRecorder.dir": str(tmp_path),
        "spark.rapids.obs.flightRecorder.maxBundles": "3"})
    paths = [record_incident("ttest", f"a{i}", f"r{i}", conf=conf)
             for i in range(5)]
    assert all(paths)
    left = sorted(os.listdir(tmp_path))
    assert len(left) == 3
    # newest survive
    assert os.path.basename(paths[-1]) in left
    assert os.path.basename(paths[0]) not in left


def test_flight_recorder_disabled_records_nothing(tmp_path):
    from spark_rapids_tpu.obs.telemetry import record_incident
    conf = RapidsConf({
        "spark.rapids.obs.flightRecorder.enabled": "false",
        "spark.rapids.obs.flightRecorder.dir": str(tmp_path)})
    assert record_incident("ttest", "a", "r", conf=conf) is None
    assert list(tmp_path.iterdir()) == []


# ---------------------------------------------------------------------------
# cross-host trace propagation (2-host THREAD-mode cluster)
# ---------------------------------------------------------------------------


@pytest.fixture()
def thread_cluster(tmp_path_factory):
    """Driver + 2 thread-mode executors (the cheap protocol harness)
    over a 4-file parquet corpus."""
    from spark_rapids_tpu.columnar import HostTable
    from spark_rapids_tpu.io.parquet import write_parquet
    from spark_rapids_tpu.runtime.cluster import (
        CLUSTER,
        ClusterDriver,
        spawn_executor,
    )
    base = tmp_path_factory.mktemp("tele_corpus")
    n = 400
    t = HostTable.from_pydict({
        "k": [f"k{i % 5}" for i in range(n)],
        "v": np.arange(n, dtype=np.int64)})
    for i in range(4):
        write_parquet(t.slice(i * 100, 100), str(base / f"c{i:03d}"))
    driver = ClusterDriver(2, RapidsConf({}))
    executors = [spawn_executor(driver.address, f"h{i}", mode="thread")
                 for i in range(2)]
    driver.wait_ready(2, timeout_s=30.0)
    CLUSTER.attach_driver(driver)
    yield str(base)
    CLUSTER.attach_driver(None)
    driver.shutdown()
    for h in executors:
        h.terminate()


def _cluster_session(tmp_path, extra=None):
    from spark_rapids_tpu.session import TpuSession
    conf = {"spark.rapids.cluster.enabled": "true",
            "spark.rapids.cluster.hosts": "2",
            "spark.rapids.sql.eventLog.enabled": "true",
            "spark.rapids.sql.eventLog.dir": str(tmp_path / "ev"),
            "spark.rapids.trace.enabled": "true",
            "spark.rapids.trace.dir": str(tmp_path / "tr")}
    conf.update(extra or {})
    return TpuSession(conf)


def test_cross_host_span_merge_and_host_scan_attribution(
        thread_cluster, tmp_path):
    """The core propagation contract: a cluster-routed scan's event
    record attributes every dispatch/frame/byte to its executor host
    BIT-EXACTLY (2 hosts x 2 files each, bytes = the landed TPAK
    frames), and the Chrome trace carries the driver's per-host
    cluster.scan spans plus the executor-lane spans merged from the
    replies."""
    from spark_rapids_tpu.obs.metrics import scopes_snapshot
    s = _cluster_session(tmp_path)
    before = dict(scopes_snapshot().get("cluster", {}))
    out = s.read_parquet(thread_cluster).collect_table()
    assert out.num_rows == 400
    after = dict(scopes_snapshot().get("cluster", {}))
    assert after.get("hostShardsLanded", 0) - before.get(
        "hostShardsLanded", 0) == 4

    rec = s.last_event_record
    scans = rec["hostScans"]
    assert sorted(scans) == ["h0", "h1"]
    for host in ("h0", "h1"):
        st = scans[host]
        assert st["scans"] == 1
        assert st["files"] == 2  # 4 files split contiguously over 2
        assert st["bytes"] > 0
        assert st["wallS"] >= st["execWallS"] > 0
        assert st["crcRetries"] == 0
    # bit-exact: the frames landed ARE the frames attributed
    assert sum(st["files"] for st in scans.values()) == 4

    trace = json.loads(open(os.path.join(
        str(tmp_path / "tr"),
        f"query_{rec['queryIndex']}.trace.json")).read())
    events = trace["traceEvents"]
    cluster_spans = [e for e in events if e["name"] == "cluster.scan"]
    assert {e["args"]["host"] for e in cluster_spans} == {"h0", "h1"}
    lanes = {e["args"]["name"] for e in events if e["ph"] == "M"
             and str(e["args"].get("name", "")).startswith("executor-")}
    assert lanes == {"executor-h0", "executor-h1"}
    # per file: one decode span + one pack span, per executor
    exec_spans = [e for e in events if e.get("cat") == "exec-scan"]
    assert len(exec_spans) == 8
    assert {e["name"] for e in exec_spans} == {"executor.scan.file",
                                               "executor.pack"}
    # remote spans stay OFF the attribution thread: coverage intact
    assert rec["spans"]["attributedS"] / rec["wallS"] >= 0.5


def test_crc_retry_attributed_to_the_corrupt_host(thread_cluster,
                                                  tmp_path):
    """A corrupt shard landing's CRC retry shows up against the host
    whose frame was damaged."""
    s = _cluster_session(tmp_path, {
        "spark.rapids.test.faults": "host.shard.land:corrupt:1:3"})
    s.read_parquet(thread_cluster).collect_table()
    rec = s.last_event_record
    retries = {h: st["crcRetries"] for h, st in rec["hostScans"].items()}
    assert sum(retries.values()) == 1, retries


# ---------------------------------------------------------------------------
# live introspection + tools smokes
# ---------------------------------------------------------------------------


def _svc_query(svc):
    from spark_rapids_tpu import functions as F
    from spark_rapids_tpu.ops.expr import col, lit
    df = svc.session.create_dataframe({
        "k": np.array(["a", "b"] * 40, dtype=object),
        "v": np.arange(80, dtype=np.int64)})
    return (df.filter(col("v") > lit(3))
            .group_by("k").agg(F.sum("v").alias("sv")))


def test_tools_top_over_live_service(tmp_path):
    """Subprocess smoke: `tools top` polls a real service's loopback
    endpoint and renders health + SLOs + telemetry."""
    from spark_rapids_tpu.service import QueryService
    with QueryService({
            "spark.rapids.service.introspect.enabled": "true",
            "spark.rapids.obs.telemetry.enabled": "true",
            "spark.rapids.obs.telemetry.intervalMs": "50"}) as svc:
        assert svc.introspect_port
        q = _svc_query(svc)
        for tenant in ("alice", "bob"):
            svc.submit(q, tenant=tenant).result(timeout=120)
        slo = svc.slo_snapshot()
        assert slo["pools"]["default"]["count"] == 2
        assert set(slo["tenants"]) == {"default/alice", "default/bob"}
        assert slo["pools"]["default"]["latency"]["p95S"] >= \
            slo["pools"]["default"]["latency"]["p50S"] >= 0
        assert svc.query_table() == []  # nothing live between queries
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        out = subprocess.run(
            [sys.executable, "-m", "spark_rapids_tpu.tools", "top",
             "--port", str(svc.introspect_port)],
            capture_output=True, text=True, env=env, cwd=_REPO_ROOT,
            timeout=120)
        assert out.returncode == 0, out.stderr
        assert "Service: HEALTHY" in out.stdout
        assert "pool   default" in out.stdout
        assert "Telemetry: on" in out.stdout
        out_json = subprocess.run(
            [sys.executable, "-m", "spark_rapids_tpu.tools", "top",
             "--port", str(svc.introspect_port), "--json"],
            capture_output=True, text=True, env=env, cwd=_REPO_ROOT,
            timeout=120)
        doc = json.loads(out_json.stdout)
        assert doc["stats"]["finished"] == 2
        assert doc["slo"]["pools"]["default"]["count"] == 2
    # unreachable endpoint -> exit 1 with a pointer, not a traceback
    gone = subprocess.run(
        [sys.executable, "-m", "spark_rapids_tpu.tools", "top",
         "--port", str(svc.introspect_port)],
        capture_output=True, text=True, env=env, cwd=_REPO_ROOT,
        timeout=120)
    assert gone.returncode == 1
    assert "introspect" in gone.stderr


def test_tools_incident_subprocess_smoke(tmp_path):
    from spark_rapids_tpu.obs.telemetry import record_incident
    conf = RapidsConf({
        "spark.rapids.obs.flightRecorder.dir": str(tmp_path)})
    p = record_incident(
        "host.ladder", "reland",
        "HostLostError: injected host loss at host.dispatch",
        conf=conf)
    assert p
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, "-m", "spark_rapids_tpu.tools", "incident",
         str(tmp_path)],
        capture_output=True, text=True, env=env, cwd=_REPO_ROOT,
        timeout=120)
    assert out.returncode == 0, out.stderr
    assert "Incident bundles: 1" in out.stdout
    assert "kind=host.ladder action=reland" in out.stdout
    assert "faultPoint=host.dispatch" in out.stdout
    assert "trigger: HostLostError" in out.stdout
    assert "telemetry tail:" in out.stdout
    out_json = subprocess.run(
        [sys.executable, "-m", "spark_rapids_tpu.tools", "incident",
         "--json", str(tmp_path)],
        capture_output=True, text=True, env=env, cwd=_REPO_ROOT,
        timeout=120)
    bundles = json.loads(out_json.stdout)
    assert len(bundles) == 1 and bundles[0]["action"] == "reland"
    # a missing dir is a clean exit 1, not a stack trace
    missing = subprocess.run(
        [sys.executable, "-m", "spark_rapids_tpu.tools", "incident",
         str(tmp_path / "nope")],
        capture_output=True, text=True, env=env, cwd=_REPO_ROOT,
        timeout=120)
    assert missing.returncode == 1


def test_tools_accept_older_schemas_with_one_warning(tmp_path, capsys):
    """Satellite: mixed-version event-log dirs load with a single
    warning — per-version fields default to 0/absent — instead of a
    KeyError/ValueError crash (`tools compare` over logs written
    before an engine upgrade)."""
    from spark_rapids_tpu import functions as F
    from spark_rapids_tpu.ops.expr import col, lit
    from spark_rapids_tpu.session import TpuSession
    from spark_rapids_tpu.tools import build_compare
    from spark_rapids_tpu.tools.report import build_profile, load_events

    def run(d):
        s = TpuSession({"spark.rapids.sql.eventLog.enabled": "true",
                        "spark.rapids.sql.eventLog.dir": str(d)})
        s.next_query_tag = "q"
        df = s.create_dataframe({"k": np.array(["a", "b"] * 20,
                                               dtype=object),
                                 "v": np.arange(40, dtype=np.int64)})
        (df.filter(col("v") > lit(1)).group_by("k")
         .agg(F.sum("v").alias("s"))).collect_table()
        return s.last_event_record

    rec = run(tmp_path / "b")
    # an OLD (v8-era) record: no hostScans field, schema 8
    old = {k: v for k, v in rec.items() if k != "hostScans"}
    old["schema"] = 8
    os.makedirs(tmp_path / "a")
    with open(tmp_path / "a" / "events-old.jsonl", "w") as f:
        f.write(json.dumps(old) + "\n")
    capsys.readouterr()
    records = load_events(str(tmp_path / "a"))
    assert len(records) == 1
    err = capsys.readouterr().err
    assert err.count("older event schema") == 1
    # both tools run over the mixed pair without crashing
    cmp = build_compare(str(tmp_path / "a"), str(tmp_path / "b"))
    assert cmp["matchedQueries"] == 1
    prof = build_profile(load_events(str(tmp_path / "a")))
    assert prof["queryCount"] == 1
    assert prof["hostResilience"]["perHost"] == {}
    # a FUTURE schema still refuses loudly
    with open(tmp_path / "a" / "events-future.jsonl", "w") as f:
        f.write(json.dumps({**old, "schema": 99}) + "\n")
    with pytest.raises(ValueError, match="schema"):
        load_events(str(tmp_path / "a"))
