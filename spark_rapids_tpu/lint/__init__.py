"""Static-analysis layer: plan verifier, registry auditor, repo lint.

Reference analogs: the spark-rapids ``spark.rapids.sql.test.enabled``
assert-on-fallback harness and Catalyst's plan-integrity validation
(``QueryExecution.assertAnalyzed`` / structural ``validatePlan`` checks —
Armbrust et al.).  The reproduction's tagging layer (overrides/typesig.py,
overrides/rules.py) decides what runs on device, but until this package
nothing *checked* the resulting physical plan, the op registries, or the
codebase itself.  Three tools, one diagnostic format:

* ``plan_verifier.verify_converted`` — walks a converted physical plan
  (post-overrides, including the AQE-deferred build nodes) and asserts
  cross-layer invariants: schema contracts, device/host transition
  correctness, exchange partitioning, decimal precision/scale
  propagation, TypeSig conformance, fallback-reason hygiene.
* ``registry_audit.audit_registry`` — cross-checks ops/* expression
  classes against the overrides registries, ExprChecks signatures, SQL
  exposure and the committed SUPPORTED_OPS.md / CONFIGS.md.
* ``repo_lint.lint_repo`` — a Python-AST lint enforcing project
  invariants the type system can't (host syncs in hot paths, jnp outside
  device layers, undeclared conf keys, nondeterminism in kernels, dead
  lambdas).

All three run from one CLI (``python -m spark_rapids_tpu.lint``) and as a
pytest module in tier-1 (tests/test_lint.py).  The plan verifier also runs
inline on every ``TpuSession.execute`` under
``spark.rapids.sql.planVerify.mode = off|warn|error``.
"""

from spark_rapids_tpu.lint.diagnostics import Diagnostic, RULES, rule_ids

__all__ = [
    "Diagnostic",
    "RULES",
    "rule_ids",
    "verify_converted",
    "verify_plan",
    "audit_registry",
    "lint_repo",
    "run_all",
]


def verify_converted(executable, meta=None, conf=None):
    from spark_rapids_tpu.lint.plan_verifier import verify_converted as _v
    return _v(executable, meta, conf)


def verify_plan(plan, conf=None):
    from spark_rapids_tpu.lint.plan_verifier import verify_plan as _v
    return _v(plan, conf)


def audit_registry(repo_root=None):
    from spark_rapids_tpu.lint.registry_audit import audit_registry as _a
    return _a(repo_root)


def lint_repo(repo_root=None):
    from spark_rapids_tpu.lint.repo_lint import lint_repo as _l
    return _l(repo_root)


def run_all(repo_root=None, scale_factor: float = 0.01,
            include_plans: bool = True):
    """Run repo lint + registry audit (+ the golden-suite plan
    verification) and return every diagnostic."""
    diags = list(lint_repo(repo_root))
    diags += list(audit_registry(repo_root))
    if include_plans:
        from spark_rapids_tpu.lint.golden import verify_golden_plans
        diags += list(verify_golden_plans(scale_factor=scale_factor))
    return diags
