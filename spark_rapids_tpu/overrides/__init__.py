"""Plan-rewrite engine (reference: GpuOverrides.scala 4,755 LoC +
RapidsMeta.scala + TypeChecks.scala + GpuTransitionOverrides.scala —
SURVEY.md §2.2, the heart of the product).

Same architecture: wrap every plan node in a Meta, tag unsupported nodes
with human-readable reasons (never fail — fall back per operator), convert
the supported subtree to TPU execs, then insert host<->device transitions
and coalesce nodes."""

from spark_rapids_tpu.overrides.typesig import TypeSig  # noqa: F401
from spark_rapids_tpu.overrides.rules import (  # noqa: F401
    PlanMeta,
    wrap_plan,
    convert_plan,
    apply_overrides,
    explain_plan,
)
