"""CSV scan + writer (reference: GpuCSVScan.scala over
GpuTextBasedPartitionReader — SURVEY.md §2.4: CPU line splitting + parse).

The reference splits lines on CPU and parses on device; for the TPU build
the Arrow CSV parser is the host decode and the parsed columns upload as one
batch. Schema may be supplied (Spark-style) or inferred by Arrow."""

from __future__ import annotations

from typing import List, Optional, Sequence

import pyarrow as pa
import pyarrow.csv as pcsv

from spark_rapids_tpu.columnar import HostTable
from spark_rapids_tpu.conf import RapidsConf, str_conf
from spark_rapids_tpu.io.arrow_convert import (
    arrow_schema_to_spark,
    decode_to_schema,
    host_table_to_arrow,
    spark_type_to_arrow,
)
from spark_rapids_tpu.io.common import FileScanNode
from spark_rapids_tpu.io.writer import write_partitioned
from spark_rapids_tpu.plan.nodes import Schema

CSV_READER_TYPE = str_conf(
    "spark.rapids.sql.format.csv.reader.type", "AUTO",
    "PERFILE, COALESCING, MULTITHREADED or AUTO.")


class CsvScanNode(FileScanNode):
    format_name = "csv"

    def __init__(self, paths, conf: RapidsConf, columns=None, reader_type=None,
                 schema: Optional[Schema] = None, header: bool = True,
                 delimiter: str = ",", **options):
        self.user_schema = schema
        self.header = header
        self.delimiter = delimiter
        super().__init__(paths, conf, columns=columns, reader_type=reader_type,
                         **options)

    def _conf_reader_type(self) -> str:
        return self.conf.get_entry(CSV_READER_TYPE)

    def _read_opts(self):
        read_opts = pcsv.ReadOptions()
        if not self.header:
            if not self.user_schema:
                raise ValueError("headerless CSV requires an explicit schema")
            read_opts = pcsv.ReadOptions(
                column_names=[n for n, _ in self.user_schema])
        parse_opts = pcsv.ParseOptions(delimiter=self.delimiter)
        convert = None
        if self.user_schema:
            convert = pcsv.ConvertOptions(column_types={
                n: spark_type_to_arrow(dt) for n, dt in self.user_schema})
        return read_opts, parse_opts, convert

    def file_schema(self, path: str) -> Schema:
        if self.user_schema:
            return list(self.user_schema)
        return arrow_schema_to_spark(self._read_arrow(path).schema)

    def _read_arrow(self, path: str) -> pa.Table:
        read_opts, parse_opts, convert = self._read_opts()
        return pcsv.read_csv(path, read_options=read_opts,
                             parse_options=parse_opts, convert_options=convert)

    def read_file(self, path: str) -> HostTable:
        return decode_to_schema(self._read_arrow(path), self.data_schema)


def write_csv(table: HostTable, path: str,
              partition_by: Optional[Sequence[str]] = None,
              header: bool = True) -> List[str]:
    def _write_one(tbl: HostTable, file_path: str):
        opts = pcsv.WriteOptions(include_header=header)
        pcsv.write_csv(host_table_to_arrow(tbl), file_path, opts)
    return write_partitioned(table, path, _write_one, "csv", partition_by)
