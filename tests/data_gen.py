"""Seeded data generators per Spark type (reference: integration_tests
data_gen.py — SURVEY.md §4). Deterministic, nullable, corner-value-heavy."""

from __future__ import annotations

import string
from typing import Dict, Optional

import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar import HostColumn, HostTable

DEFAULT_SEED = 42

_INT_CORNERS = {
    T.BYTE: [0, 1, -1, 127, -128],
    T.SHORT: [0, 1, -1, 32767, -32768],
    T.INT: [0, 1, -1, 2147483647, -2147483648],
    T.LONG: [0, 1, -1, (1 << 63) - 1, -(1 << 63)],
}
_FLOAT_CORNERS = [0.0, -0.0, 1.0, -1.0, 1e30, -1e30, 1e-30]


class Gen:
    def __init__(self, dtype: T.DataType, nullable: bool = True, null_prob: float = 0.1):
        self.dtype = dtype
        self.nullable = nullable
        self.null_prob = null_prob

    def generate(self, n: int, rng: np.random.Generator) -> HostColumn:
        data = self._values(n, rng)
        if self.nullable:
            validity = rng.random(n) >= self.null_prob
        else:
            validity = np.ones(n, dtype=np.bool_)
        if isinstance(self.dtype, T.StringType):
            out = np.empty(n, dtype=object)
            out[:] = data
            out[~validity] = None
            return HostColumn(self.dtype, out, validity)
        zero = np.zeros((), dtype=self.dtype.np_dtype).item()
        data = np.where(validity, data, zero).astype(self.dtype.np_dtype)
        return HostColumn(self.dtype, data, validity)

    def _values(self, n, rng):
        raise NotImplementedError


class IntGen(Gen):
    def __init__(self, dtype=T.INT, nullable=True, min_val=None, max_val=None,
                 corner_prob: float = 0.05, null_prob: float = 0.1):
        super().__init__(dtype, nullable, null_prob)
        info = np.iinfo(dtype.np_dtype)
        self.min_val = info.min if min_val is None else min_val
        self.max_val = info.max if max_val is None else max_val
        self.corner_prob = corner_prob

    def _values(self, n, rng):
        vals = rng.integers(self.min_val, self.max_val, size=n, dtype=np.int64,
                            endpoint=True).astype(self.dtype.np_dtype)
        corners = _INT_CORNERS.get(self.dtype)
        if corners and self.corner_prob > 0 and self.min_val <= corners[0] <= self.max_val:
            usable = [c for c in corners if self.min_val <= c <= self.max_val]
            mask = rng.random(n) < self.corner_prob
            vals[mask] = rng.choice(np.array(usable, dtype=self.dtype.np_dtype),
                                    size=int(mask.sum()))
        return vals


class LongGen(IntGen):
    def __init__(self, nullable=True, **kw):
        super().__init__(T.LONG, nullable, **kw)


class ByteGen(IntGen):
    def __init__(self, nullable=True, **kw):
        super().__init__(T.BYTE, nullable, **kw)


class ShortGen(IntGen):
    def __init__(self, nullable=True, **kw):
        super().__init__(T.SHORT, nullable, **kw)


class BooleanGen(Gen):
    def __init__(self, nullable=True):
        super().__init__(T.BOOLEAN, nullable)

    def _values(self, n, rng):
        return rng.integers(0, 2, size=n).astype(np.bool_)


class FloatGen(Gen):
    def __init__(self, dtype=T.DOUBLE, nullable=True, no_nans=True, corner_prob=0.05):
        super().__init__(dtype, nullable)
        self.no_nans = no_nans
        self.corner_prob = corner_prob

    def _values(self, n, rng):
        vals = (rng.standard_normal(n) * 1e6).astype(self.dtype.np_dtype)
        mask = rng.random(n) < self.corner_prob
        corners = np.array(_FLOAT_CORNERS, dtype=self.dtype.np_dtype)
        vals[mask] = rng.choice(corners, size=int(mask.sum()))
        return vals


class DoubleGen(FloatGen):
    def __init__(self, nullable=True, **kw):
        super().__init__(T.DOUBLE, nullable, **kw)


class StringGen(Gen):
    def __init__(self, nullable=True, max_len: int = 12, alphabet: Optional[str] = None,
                 cardinality: Optional[int] = None):
        super().__init__(T.STRING, nullable)
        self.max_len = max_len
        self.alphabet = alphabet or (string.ascii_letters + string.digits + " _")
        self.cardinality = cardinality

    def _values(self, n, rng):
        if self.cardinality:
            pool = self._make(self.cardinality, rng)
            idx = rng.integers(0, len(pool), size=n)
            return [pool[i] for i in idx]
        return self._make(n, rng)

    def _make(self, n, rng):
        lens = rng.integers(0, self.max_len + 1, size=n)
        chars = np.array(list(self.alphabet))
        return ["".join(rng.choice(chars, size=l)) for l in lens]


class DateGen(Gen):
    def __init__(self, nullable=True):
        super().__init__(T.DATE, nullable)

    def _values(self, n, rng):
        return rng.integers(-25000, 25000, size=n).astype(np.int32)


class TimestampGen(Gen):
    def __init__(self, nullable=True):
        super().__init__(T.TIMESTAMP, nullable)

    def _values(self, n, rng):
        return rng.integers(-2_000_000_000_000_000, 4_000_000_000_000_000,
                            size=n).astype(np.int64)


def gen_table(gens: Dict[str, Gen], n: int, seed: int = DEFAULT_SEED) -> HostTable:
    rng = np.random.default_rng(seed)
    names, cols = [], []
    for name, g in gens.items():
        names.append(name)
        cols.append(g.generate(n, rng))
    return HostTable(names, cols)


def gen_for_type(dt: T.DataType) -> Gen:
    """Default generator for a Spark type."""
    if isinstance(dt, T.BooleanType):
        return BooleanGen()
    if isinstance(dt, T.ByteType):
        return ByteGen()
    if isinstance(dt, T.ShortType):
        return ShortGen()
    if isinstance(dt, T.IntegerType):
        return IntGen()
    if isinstance(dt, T.LongType):
        return LongGen()
    if isinstance(dt, T.FloatType):
        return FloatGen(T.FLOAT)
    if isinstance(dt, T.DoubleType):
        return DoubleGen()
    if isinstance(dt, T.StringType):
        return StringGen()
    if isinstance(dt, T.DateType):
        return DateGen()
    if isinstance(dt, T.TimestampType):
        return TimestampGen()
    raise TypeError(f"no default generator for {dt}")


def table_gen(schema: Dict[str, T.DataType], n: int,
              seed: int = DEFAULT_SEED) -> HostTable:
    """Generate a table from a {name: DataType} schema with default gens."""
    return gen_table({name: gen_for_type(dt) for name, dt in schema.items()},
                     n, seed=seed)


#: the standard per-type matrix used across test files
numeric_gens = [ByteGen(), ShortGen(), IntGen(), LongGen(), FloatGen(T.FLOAT), DoubleGen()]
all_basic_gens = numeric_gens + [BooleanGen(), StringGen(), DateGen(), TimestampGen()]
