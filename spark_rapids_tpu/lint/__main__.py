"""CLI: ``python -m spark_rapids_tpu.lint``.

Runs the repo lint, the registry auditor and the golden-suite plan
verification (TPC-H q1-q22, DSL + SQL, AQE on/off) and exits non-zero on
any diagnostic — the correctness gate every PR runs under.

Exit status: 0 when every phase ran clean, 1 when ANY diagnostic was
produced (CI gates on it).  ``--json`` swaps the human output for one
machine-readable JSON object on stdout::

    {"phases": {"repo": 0, ...},
     "diagnostics": [{"rule_id": ..., "path": ..., "message": ...,
                      "severity": ...}, ...],
     "ok": true/false}

with the same exit-status contract."""

from __future__ import annotations

import argparse
import json
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m spark_rapids_tpu.lint",
        description="plan verifier + registry auditor + repo lint")
    ap.add_argument("--skip-repo", action="store_true",
                    help="skip the Python-AST repo lint")
    ap.add_argument("--skip-registry", action="store_true",
                    help="skip the registry/doc-drift audit")
    ap.add_argument("--skip-plans", action="store_true",
                    help="skip golden-suite (TPC-H q1-q22) plan "
                         "verification")
    ap.add_argument("--skip-exec-metrics", action="store_true",
                    help="skip the RA-ESSENTIAL-METRICS executed-corpus "
                         "audit (runs a golden-corpus slice)")
    ap.add_argument("--sf", type=float, default=0.01,
                    help="scale factor for golden-suite table generation")
    ap.add_argument("--list-rules", action="store_true",
                    help="print every rule id and exit")
    ap.add_argument("--json", action="store_true",
                    help="emit one JSON object (phases, diagnostics, "
                         "ok) instead of human-readable lines; exit "
                         "status stays 1 on any diagnostic")
    ap.add_argument("--repo-root", default=None, metavar="DIR",
                    help="root directory the repo lint scans (default: "
                         "the installed checkout; the smoke tests "
                         "point it at tiny synthetic trees)")
    ap.add_argument("--write-docs", action="store_true",
                    help="regenerate SUPPORTED_OPS.md, CONFIGS.md and "
                         "LOCKS.md from the registries, then exit")
    args = ap.parse_args(argv)

    from spark_rapids_tpu.lint.diagnostics import RULES
    if args.list_rules:
        for rid in sorted(RULES):
            print(f"{rid:22s} {RULES[rid]}")
        return 0
    if args.write_docs:
        from spark_rapids_tpu.lint.registry_audit import regenerate_docs
        for path in regenerate_docs():
            print(f"wrote {path}")
        return 0

    quiet = args.json
    diags = []
    ran = []
    phases = {}

    def phase(name: str, label: str, found):
        if not quiet:
            print(f"{label}: {len(found)} diagnostic(s)")
        diags.extend(found)
        ran.append(label)
        phases[name] = len(found)

    if not args.skip_repo:
        from spark_rapids_tpu.lint.repo_lint import lint_repo
        phase("repo", "repo lint", lint_repo(repo_root=args.repo_root))
    if not args.skip_registry:
        from spark_rapids_tpu.lint.registry_audit import audit_registry
        phase("registry", "registry audit", audit_registry())
    if not args.skip_plans:
        from spark_rapids_tpu.lint.golden import verify_golden_plans
        phase("plans", "golden-suite plan verify",
              verify_golden_plans(scale_factor=args.sf))
    if not args.skip_exec_metrics:
        from spark_rapids_tpu.lint.registry_audit import audit_exec_metrics
        phase("exec_metrics", "exec-metrics audit", audit_exec_metrics())

    if args.json:
        print(json.dumps({
            "phases": phases,
            "diagnostics": [
                {"rule_id": d.rule_id, "path": d.path,
                 "message": d.message, "severity": d.severity}
                for d in diags],
            "ok": not diags,
        }, indent=2, sort_keys=True))
        return 1 if diags else 0

    for d in diags:
        print(str(d))
    if diags:
        print(f"FAILED: {len(diags)} diagnostic(s)")
        return 1
    print(f"OK: {', '.join(ran) if ran else 'nothing checked'} clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
