"""External source provider SPI.

Reference: ``org/apache/spark/sql/rapids/ExternalSource.scala:1-233`` —
connectors (Delta, Iceberg, Avro, Hive) are NOT hard-wired into the
override rules; each ships a *provider* that the plugin discovers lazily,
probes for availability (the spark-avro jar may simply not be on the
classpath), and consults for scan/write support by capability.

TPU mapping: providers register themselves in this module's registry at
import; availability probes check importability of the modules a provider
needs (the pip-package analog of jar probing), and ``TpuSession.read`` /
``read_format`` route every connector lookup through the registry, so a
new format plugs in with one ``register_provider`` call and no engine
edits."""

from __future__ import annotations

import importlib.util
from typing import Dict, Optional, Sequence

from spark_rapids_tpu.errors import ColumnarProcessingError


class ExternalSourceProvider:
    """One connector's contract (DeltaProvider/IcebergProvider/
    AvroProvider analog). Subclasses override ``create_scan_node`` and
    declare formats + capabilities."""

    #: provider name for diagnostics
    name: str = "?"
    #: format strings this provider serves (session.read.format(...))
    formats: Sequence[str] = ()
    #: what the provider can do: subset of {"read", "write", "time-travel",
    #: "snapshot-id", "table-api"}
    capabilities: frozenset = frozenset({"read"})
    #: python modules that must be importable for the provider to load
    #: (ExternalSource.hasSparkAvroJar analog)
    required_modules: Sequence[str] = ()

    def is_available(self) -> bool:
        try:
            return all(importlib.util.find_spec(m) is not None
                       for m in self.required_modules)
        except (ImportError, ModuleNotFoundError, ValueError):
            return False

    def create_scan_node(self, paths, conf, **options):
        raise NotImplementedError

    def create_table_api(self, session, path):
        """Optional richer table handle (DeltaTable analog)."""
        raise ColumnarProcessingError(
            f"provider {self.name} has no table API")


_PROVIDERS: Dict[str, ExternalSourceProvider] = {}


def register_provider(provider: ExternalSourceProvider) -> None:
    """Make a connector discoverable (ExternalSource registration)."""
    for fmt in provider.formats:
        _PROVIDERS[fmt.lower()] = provider


def provider_for(fmt: str) -> Optional[ExternalSourceProvider]:
    """The available provider serving ``fmt``, or None (absent or its
    required modules are missing — graceful absence, the reference logs
    and continues without the connector)."""
    p = _PROVIDERS.get(fmt.lower())
    if p is not None and not p.is_available():
        return None
    return p


def supported_formats() -> Sequence[str]:
    return sorted(f for f, p in _PROVIDERS.items() if p.is_available())


def create_scan(fmt: str, paths, conf, **options):
    p = provider_for(fmt)
    if p is None:
        raise ColumnarProcessingError(
            f"no available source provider for format {fmt!r} "
            f"(available: {list(supported_formats())})")
    if "read" not in p.capabilities:
        raise ColumnarProcessingError(
            f"source provider {p.name} does not support reads")
    return p.create_scan_node(paths, conf, **options)


# ---------------------------------------------------------------------------
# Built-in providers (each defers its connector import to call time, so a
# broken/absent connector never breaks the registry itself)
# ---------------------------------------------------------------------------

def _single_path(paths, fmt: str) -> str:
    if isinstance(paths, str):
        return paths
    if len(paths) != 1:
        raise ColumnarProcessingError(
            f"{fmt} reads take exactly ONE table path, got {len(paths)}")
    return paths[0]

class _ParquetProvider(ExternalSourceProvider):
    name = "parquet"
    formats = ("parquet",)
    capabilities = frozenset({"read", "write"})
    required_modules = ("pyarrow.parquet",)

    def create_scan_node(self, paths, conf, **options):
        from spark_rapids_tpu.io.parquet import ParquetScanNode
        return ParquetScanNode(list(paths), conf, **options)


class _OrcProvider(ExternalSourceProvider):
    name = "orc"
    formats = ("orc",)
    capabilities = frozenset({"read", "write"})
    required_modules = ("pyarrow.orc",)

    def create_scan_node(self, paths, conf, **options):
        from spark_rapids_tpu.io.orc import OrcScanNode
        return OrcScanNode(list(paths), conf, **options)


class _CsvProvider(ExternalSourceProvider):
    name = "csv"
    formats = ("csv",)
    capabilities = frozenset({"read", "write"})

    def create_scan_node(self, paths, conf, **options):
        from spark_rapids_tpu.io.csv import CsvScanNode
        return CsvScanNode(list(paths), conf, **options)


class _JsonProvider(ExternalSourceProvider):
    name = "json"
    formats = ("json",)
    capabilities = frozenset({"read", "write"})

    def create_scan_node(self, paths, conf, **options):
        from spark_rapids_tpu.io.json import JsonScanNode
        return JsonScanNode(list(paths), conf, **options)


class _AvroProvider(ExternalSourceProvider):
    """AvroProvider analog — the reference probes for the spark-avro jar
    (ExternalSource.scala:44-57); here the in-repo reader is self-contained
    so the probe is trivially true, but the path is the same."""

    name = "avro"
    formats = ("avro",)
    capabilities = frozenset({"read", "write"})

    def create_scan_node(self, paths, conf, **options):
        from spark_rapids_tpu.io.avro import AvroScanNode
        return AvroScanNode(list(paths), conf, **options)


class _DeltaProvider(ExternalSourceProvider):
    name = "delta"
    formats = ("delta",)
    capabilities = frozenset({"read", "write", "time-travel", "table-api"})

    def create_scan_node(self, paths, conf, **options):
        from spark_rapids_tpu.delta import DeltaScanNode
        return DeltaScanNode(_single_path(paths, "delta"), conf, **options)

    def create_table_api(self, session, path):
        from spark_rapids_tpu.delta import DeltaTable
        return DeltaTable(session, path)


class _IcebergProvider(ExternalSourceProvider):
    name = "iceberg"
    formats = ("iceberg",)
    capabilities = frozenset({"read", "snapshot-id"})

    def create_scan_node(self, paths, conf, **options):
        from spark_rapids_tpu.iceberg import IcebergScanNode
        return IcebergScanNode(_single_path(paths, "iceberg"), conf,
                               **options)


class _HiveTextProvider(ExternalSourceProvider):
    name = "hive-text"
    formats = ("hive", "hive-text", "hivetext")
    capabilities = frozenset({"read", "write"})

    def create_scan_node(self, paths, conf, **options):
        from spark_rapids_tpu.io.hive_text import HiveTextScanNode
        return HiveTextScanNode(list(paths), conf, **options)


for _p in (_ParquetProvider(), _OrcProvider(), _CsvProvider(),
           _JsonProvider(), _AvroProvider(), _DeltaProvider(),
           _IcebergProvider(), _HiveTextProvider()):
    register_provider(_p)


class DataFrameReader:
    """session.read.format("delta").option(...).load(path) — the
    pyspark reader surface routed through the provider SPI."""

    def __init__(self, session):
        self._session = session
        self._format = "parquet"
        self._options: Dict[str, object] = {}

    def format(self, fmt: str) -> "DataFrameReader":
        self._format = fmt
        return self

    def option(self, key: str, value) -> "DataFrameReader":
        self._options[key] = value
        return self

    def options(self, **opts) -> "DataFrameReader":
        self._options.update(opts)
        return self

    def load(self, *paths):
        from spark_rapids_tpu.plan import DataFrame
        node = create_scan(self._format, list(paths), self._session.conf,
                           **self._options)
        return DataFrame(node, self._session)

    def parquet(self, *paths):
        return self.format("parquet").load(*paths)

    def csv(self, *paths, **opts):
        return self.format("csv").options(**opts).load(*paths)

    def json(self, *paths, **opts):
        return self.format("json").options(**opts).load(*paths)

    def orc(self, *paths):
        return self.format("orc").load(*paths)
