"""Window exec tests vs the CPU oracle (reference: window_function_test.py
matrix — SURVEY.md §4)."""

import pytest

from spark_rapids_tpu import functions as F
from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar import HostTable
from spark_rapids_tpu.ops.window import Window
from tests.asserts import assert_runs_on_tpu, assert_tpu_and_cpu_are_equal
from tests.data_gen import DoubleGen, IntGen, LongGen, StringGen, gen_table


def _t(n=400, seed=0):
    return gen_table({"k": IntGen(min_val=0, max_val=8, null_prob=0.05),
                      "o": LongGen(min_val=-100, max_val=100),
                      "v": LongGen(),
                      "d": DoubleGen(),
                      "s": StringGen(cardinality=12)}, n, seed=seed)


W_KO = lambda: Window.partition_by("k").order_by("o")  # noqa: E731


@pytest.mark.parametrize("fn", [
    lambda: F.row_number(), lambda: F.rank(), lambda: F.dense_rank(),
], ids=["row_number", "rank", "dense_rank"])
def test_ranking_functions(session, cpu_session, fn):
    host = _t()
    assert_tpu_and_cpu_are_equal(
        lambda s: s.create_dataframe(host).with_windows(
            r=fn().over(W_KO())), session, cpu_session)


def test_rank_with_ties(session, cpu_session):
    host = HostTable.from_pydict({
        "k": [1, 1, 1, 1, 2, 2], "o": [5, 5, 7, 9, 1, 1]})
    assert_tpu_and_cpu_are_equal(
        lambda s: s.create_dataframe(host).with_windows(
            rn=F.row_number().over(W_KO()),
            rk=F.rank().over(W_KO()),
            dr=F.dense_rank().over(W_KO())), session, cpu_session)


@pytest.mark.parametrize("off,default", [(1, None), (2, None), (1, -99)],
                         ids=["lag1", "lag2", "lag1_default"])
def test_lag_lead(session, cpu_session, off, default):
    host = _t(300, seed=2)
    assert_tpu_and_cpu_are_equal(
        lambda s: s.create_dataframe(host).with_windows(
            lg=F.lag("v", off, default).over(W_KO()),
            ld=F.lead("v", off, default).over(W_KO())),
        session, cpu_session)


def test_lag_string(session, cpu_session):
    host = _t(200, seed=3)
    assert_tpu_and_cpu_are_equal(
        lambda s: s.create_dataframe(host).with_windows(
            p=F.lag("s").over(W_KO())), session, cpu_session)


@pytest.mark.parametrize("make_agg", [
    lambda: F.sum("v"), lambda: F.count("v"), lambda: F.min("v"),
    lambda: F.max("v"), lambda: F.avg("d"),
], ids=["sum", "count", "min", "max", "avg"])
def test_whole_partition_aggs(session, cpu_session, make_agg):
    host = _t(350, seed=4)
    w = Window.partition_by("k")  # no order -> whole partition frame
    assert_tpu_and_cpu_are_equal(
        lambda s: s.create_dataframe(host).with_windows(
            a=make_agg().over(w)), session, cpu_session,
        approximate_float=True)


@pytest.mark.parametrize("make_agg", [
    lambda: F.sum("v"), lambda: F.count("v"), lambda: F.min("v"),
    lambda: F.max("v"), lambda: F.avg("d"),
], ids=["sum", "count", "min", "max", "avg"])
def test_running_aggs_default_range_frame(session, cpu_session, make_agg):
    """ORDER BY default frame = RANGE UNBOUNDED..CURRENT (peers included)."""
    host = _t(300, seed=5)
    assert_tpu_and_cpu_are_equal(
        lambda s: s.create_dataframe(host).with_windows(
            a=make_agg().over(W_KO())), session, cpu_session,
        approximate_float=True)


def test_running_rows_frame(session, cpu_session):
    host = _t(300, seed=6)
    w = W_KO().rows_between(None, 0)
    assert_tpu_and_cpu_are_equal(
        lambda s: s.create_dataframe(host).with_windows(
            rsum=F.sum("v").over(w), rmin=F.min("v").over(w)),
        session, cpu_session)


@pytest.mark.parametrize("lo,hi", [(-2, 2), (-3, 0), (0, 3), (None, 1)],
                         ids=["pm2", "m3_0", "0_p3", "unb_p1"])
def test_bounded_rows_frames(session, cpu_session, lo, hi):
    host = _t(250, seed=7)
    w = W_KO().rows_between(lo, hi)
    assert_tpu_and_cpu_are_equal(
        lambda s: s.create_dataframe(host).with_windows(
            bs=F.sum("v").over(w), bc=F.count("v").over(w),
            ba=F.avg("d").over(w)),
        session, cpu_session, approximate_float=True)


def test_window_runs_on_tpu(session):
    host = _t(100)
    assert_runs_on_tpu(
        lambda s: s.create_dataframe(host).with_windows(
            rn=F.row_number().over(W_KO()),
            sm=F.sum("v").over(W_KO())), session)


def test_bounded_minmax_runs_on_device(session, cpu_session):
    """Bounded rows min/max frames run on device via the sparse-table RMQ
    (GpuBatchedBoundedWindowExec analog; was an r1 fallback carve-out)."""
    from spark_rapids_tpu.overrides import wrap_plan
    host = _t(80)
    df = session.create_dataframe(host).with_windows(
        bm=F.min("v").over(W_KO().rows_between(-2, 2)),
        bx=F.max("v").over(W_KO().rows_between(-3, 1)),
        lead_min=F.min("v").over(W_KO().rows_between(1, 4)),
        tail_max=F.max("v").over(W_KO().rows_between(-1, None)),
        head_min=F.min("v").over(W_KO().rows_between(None, 2)),
    )
    meta = wrap_plan(df.plan, session.conf)
    assert meta.can_run_on_tpu, meta.explain(only_fallback=False)

    def build(s):
        return s.create_dataframe(host).with_windows(
            bm=F.min("v").over(W_KO().rows_between(-2, 2)),
            bx=F.max("v").over(W_KO().rows_between(-3, 1)),
            lead_min=F.min("v").over(W_KO().rows_between(1, 4)),
            tail_max=F.max("v").over(W_KO().rows_between(-1, None)),
            head_min=F.min("v").over(W_KO().rows_between(None, 2)),
        )
    assert_tpu_and_cpu_are_equal(build, session, cpu_session)


def test_wide_float_bounded_sum_runs_on_device(session, cpu_session):
    """Float both-bounded frames wider than the exact unroll window use
    segmented-prefix differences (was an r1 fallback carve-out)."""
    # corner-free doubles: +/-1e30 corners make prefix-difference sums
    # diverge from direct per-frame sums by design (variableFloatAgg class)
    host = gen_table({"k": IntGen(min_val=0, max_val=8),
                      "o": LongGen(min_val=-100, max_val=100),
                      "d": DoubleGen(corner_prob=0.0)}, 2000, seed=4)
    def build(s):
        return s.create_dataframe(host).with_windows(
            ws=F.sum("d").over(W_KO().rows_between(-600, 600)),
            wa=F.avg("d").over(W_KO().rows_between(-700, 10)))
    from spark_rapids_tpu.overrides import wrap_plan
    meta = wrap_plan(build(session).plan, session.conf)
    assert meta.can_run_on_tpu, meta.explain(only_fallback=False)
    assert_tpu_and_cpu_are_equal(build, session, cpu_session,
                                 approximate_float=True)


def test_mixed_specs_stay_aligned(session, cpu_session):
    """Two window exprs with DIFFERENT partition/order specs in one node."""
    host = _t(200, seed=9)
    assert_tpu_and_cpu_are_equal(
        lambda s: s.create_dataframe(host).with_windows(
            by_k=F.sum("v").over(Window.partition_by("k")),
            by_s=F.count("v").over(Window.partition_by("s"))),
        session, cpu_session)


def test_window_no_partition(session, cpu_session):
    """Global window (single partition)."""
    host = _t(150, seed=10)
    assert_tpu_and_cpu_are_equal(
        lambda s: s.create_dataframe(host).with_windows(
            rn=F.row_number().over(Window.order_by("o")),
            tot=F.sum("v").over(Window.partition_by())),
        session, cpu_session)


def test_window_then_filter_pipeline(session, cpu_session):
    """Classic top-N per group: window + filter + project."""
    from spark_rapids_tpu.ops.expr import col
    host = _t(400, seed=11)

    def build(s):
        return (s.create_dataframe(host)
                .with_windows(rn=F.row_number().over(W_KO()))
                .filter(col("rn") <= 3)
                .select("k", "o", "rn"))
    assert_tpu_and_cpu_are_equal(build, session, cpu_session)


def test_percent_rank_and_nth_value(session, cpu_session):
    host = _t(300)
    def build(s):
        return s.create_dataframe(host).with_windows(
            pr=F.percent_rank().over(W_KO()),
            nv=F.nth_value("v", 2).over(W_KO()),
            nv5=F.nth_value("v", 5).over(W_KO()))
    from spark_rapids_tpu.overrides import wrap_plan
    meta = wrap_plan(build(session).plan, session.conf)
    assert meta.can_run_on_tpu, meta.explain(only_fallback=False)
    assert_tpu_and_cpu_are_equal(build, session, cpu_session)


def test_empty_edge_frames_are_null(session, cpu_session):
    """Frames that are empty at partition edges must yield NULL, not a
    clipped 1-row frame (code-review r2: clip-before-emptiness bug)."""
    host = _t(120)
    def build(s):
        return s.create_dataframe(host).with_windows(
            trail=F.min("v").over(W_KO().rows_between(None, -2)),
            ahead=F.sum("v").over(W_KO().rows_between(5, 7)),
            tcnt=F.count("v").over(W_KO().rows_between(None, -2)))
    assert_tpu_and_cpu_are_equal(build, session, cpu_session)
