"""JsonToStructs / StructsToJson (reference: GpuJsonToStructs.scala,
GpuStructsToJson — SURVEY.md §2.3 #26, VERDICT r3 missing #6).

TPU-first from_json: device strings are dictionary-coded, so each DISTINCT
json document parses ONCE on host into per-field value/validity aux
arrays; the device gathers per code — O(dictionary) host work, zero
per-row parsing (the dictionary analog of the reference handing the whole
column to a CUDA JSON parser). Struct fields must be fixed-width for the
device struct representation; other schemas take the CPU path.

to_json formats on host per distinct struct ROW — output strings are
unbounded-cardinality, so it is CPU-path (device_supported False), the
same carve-out as date_format."""

from __future__ import annotations

import json
from typing import Optional

import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar import HostColumn, HostTable
from spark_rapids_tpu.columnar.nested import (
    StructData,
    fixed_np_dtype,
    struct_device_supported,
)
from spark_rapids_tpu.ops.common import UnaryExpression
from spark_rapids_tpu.ops.expr import DevVal, NodePrep, PrepCtx


def _coerce(v, dt: T.DataType):
    """PERMISSIVE-mode coercion of a parsed json value to a field type;
    None on mismatch."""
    try:
        if v is None:
            return None
        if isinstance(dt, T.BooleanType):
            return v if isinstance(v, bool) else None
        if isinstance(dt, T.IntegralType):
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                return None
            if isinstance(v, float) and not v.is_integer():
                return None
            iv = int(v)
            info = np.iinfo(dt.np_dtype)
            return iv if info.min <= iv <= info.max else None
        if isinstance(dt, (T.FloatType, T.DoubleType)):
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                return None
            return float(v)
        if isinstance(dt, T.StringType):
            return v if isinstance(v, str) else json.dumps(v)
    except (TypeError, ValueError, OverflowError):
        return None
    return None


def _parse_doc(s: Optional[str], st: T.StructType):
    """One json document -> (tuple of field values, row_valid). Spark
    PERMISSIVE mode: malformed/non-object input yields a NON-NULL row
    with every field null; only a null INPUT yields a null struct."""
    nulls = tuple(None for _ in st.fields)
    if s is None:
        return None, False
    try:
        obj = json.loads(s)
    except (json.JSONDecodeError, TypeError):
        return nulls, True
    if not isinstance(obj, dict):
        return nulls, True
    return tuple(_coerce(obj.get(f.name), f.data_type)
                 for f in st.fields), True


class JsonToStructs(UnaryExpression):
    """from_json(col, schema) — PERMISSIVE mode (malformed -> null row)."""

    def __init__(self, child, schema: T.StructType):
        super().__init__(child)
        self.schema = schema

    @property
    def data_type(self):
        return self.schema

    def key(self):
        return ("jsontostructs", self.schema.simple_string(),
                self.children[0].key())

    def with_children(self, children):
        return JsonToStructs(children[0], self.schema)

    @property
    def device_supported(self):
        return (isinstance(self.children[0].data_type, T.StringType)
                and struct_device_supported(self.schema))

    def eval_cpu(self, table: HostTable) -> HostColumn:
        c = self.children[0].eval_cpu(table)
        n = len(c)
        out = np.empty(n, dtype=object)
        validity = np.zeros(n, dtype=np.bool_)
        for i in range(n):
            if c.validity[i]:
                row, ok = _parse_doc(c.data[i], self.schema)
                if ok:
                    out[i] = row
                    validity[i] = True
        return HostColumn(self.schema, out, validity)

    def prep(self, pctx: PrepCtx, child_preps) -> NodePrep:
        d = child_preps[0].out_dict
        if d is None:
            d = np.array([], dtype=object)
        nd = max(len(d), 1)
        ok = np.zeros(nd, dtype=np.bool_)
        field_vals = []
        field_ok = []
        for f in self.schema.fields:
            field_vals.append(np.zeros(nd, dtype=fixed_np_dtype(f.data_type)))
            field_ok.append(np.zeros(nd, dtype=np.bool_))
        for i, s in enumerate(d):
            row, row_ok = _parse_doc(s, self.schema)
            ok[i] = row_ok
            if row_ok:
                for fi, v in enumerate(row):
                    if v is not None:
                        field_vals[fi][i] = v
                        field_ok[fi][i] = True
        slots = [pctx.add_aux(ok)]
        for fv, fo in zip(field_vals, field_ok):
            slots.append(pctx.add_aux(fv))
            slots.append(pctx.add_aux(fo))
        return NodePrep(aux_slots=tuple(slots))

    def eval_dev(self, ctx, child_vals, prep) -> DevVal:
        (c,) = child_vals
        ok = ctx.aux[prep.aux_slots[0]]
        codes = jnp.clip(c.data, 0, ok.shape[0] - 1)
        row_valid = c.validity & ok[codes]
        fields = []
        for fi in range(len(self.schema.fields)):
            fv = ctx.aux[prep.aux_slots[1 + 2 * fi]]
            fo = ctx.aux[prep.aux_slots[2 + 2 * fi]]
            fields.append((fv[codes], fo[codes] & row_valid))
        return DevVal(StructData(tuple(fields)), row_valid)


def _json_scalar(v, dt: T.DataType):
    if isinstance(dt, T.StringType):
        return json.dumps(v)
    if isinstance(dt, T.BooleanType):
        return "true" if v else "false"
    if isinstance(dt, (T.FloatType, T.DoubleType)):
        f = float(v)
        return json.dumps(int(f)) if f.is_integer() else json.dumps(f)
    return json.dumps(v.item() if hasattr(v, "item") else v)


class StructsToJson(UnaryExpression):
    """to_json(struct) — host formatting (unbounded string cardinality is
    the date_format carve-out; reference gates similar shapes)."""

    device_supported = False

    @property
    def data_type(self):
        return T.STRING

    def key(self):
        return ("structstojson", self.children[0].key())

    def eval_cpu(self, table: HostTable) -> HostColumn:
        c = self.children[0].eval_cpu(table)
        st: T.StructType = self.children[0].data_type
        n = len(c)
        out = np.empty(n, dtype=object)
        for i in range(n):
            if c.validity[i]:
                row = c.data[i]
                parts = []
                for fi, f in enumerate(st.fields):
                    v = (row.get(f.name) if isinstance(row, dict)
                         else row[fi])
                    if v is None:
                        continue  # Spark omits null fields
                    parts.append(
                        f"{json.dumps(f.name)}:{_json_scalar(v, f.data_type)}")
                out[i] = "{" + ",".join(parts) + "}"
        return HostColumn(T.STRING, out, c.validity.copy())
