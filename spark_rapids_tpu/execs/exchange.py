"""Shuffle exchange exec.

Reference (SURVEY.md §3.4): GpuShuffleExchangeExecBase — device partition
split (GpuPartitioning.sliceInternalOnGpuAndClose), serialized write through
the shuffle manager, then the read side's GpuShuffleCoalesceExec concats a
reduce partition's serialized tables ON HOST to the target size before one
device upload (GpuShuffleCoalesceExec.scala:43-229).

The exec yields one device batch per (non-empty) reduce partition."""

from __future__ import annotations

from time import perf_counter
from typing import List, Sequence

from spark_rapids_tpu.columnar import DeviceTable, HostTable
from spark_rapids_tpu.conf import RapidsConf
from spark_rapids_tpu.errors import ColumnarProcessingError
from spark_rapids_tpu.execs.base import TpuExec
from spark_rapids_tpu.ops.expr import Expression
from spark_rapids_tpu.shuffle.manager import get_shuffle_manager
from spark_rapids_tpu.shuffle.partitioning import (
    HashPartitioner,
    Partitioner,
    RangePartitioner,
    RoundRobinPartitioner,
    SinglePartitioner,
    split_by_partition,
)


def make_partitioner(mode: str, keys: Sequence[Expression],
                     num_partitions: int) -> Partitioner:
    mode = mode.lower()
    if mode == "hash":
        if not keys:
            raise ColumnarProcessingError("hash partitioning requires keys")
        return HashPartitioner(keys, num_partitions)
    if mode == "range":
        return RangePartitioner(keys, num_partitions)
    if mode == "roundrobin":
        return RoundRobinPartitioner(num_partitions)
    if mode == "single":
        return SinglePartitioner()
    raise ColumnarProcessingError(f"unknown partitioning {mode}")


class TpuShuffleExchangeExec(TpuExec):
    def __init__(self, child: TpuExec, mode: str, num_partitions: int,
                 keys: Sequence[Expression], conf: RapidsConf,
                 target_batch_bytes: int = 1 << 30):
        super().__init__()
        self.children = (child,)
        self.mode = mode
        self.num_partitions = 1 if mode == "single" else num_partitions
        self.keys = list(keys)
        self.conf = conf
        self.target_batch_bytes = target_batch_bytes

    def output_schema(self):
        return self.children[0].output_schema()

    def describe(self):
        return f"TpuShuffleExchange[{self.mode}, n={self.num_partitions}]"

    def execute(self):
        manager = get_shuffle_manager(self.conf)
        partitioner = make_partitioner(self.mode, self.keys, self.num_partitions)
        handle = manager.new_shuffle(self.num_partitions)
        try:
            t0 = perf_counter()
            batches = self.children[0].execute()
            if isinstance(partitioner, RangePartitioner):
                # range bounds must sample the WHOLE input, not the first
                # batch (Spark samples per-partition across the input)
                batches = list(batches)
                partitioner.compute_bounds_multi(batches)
            for batch in batches:
                parts = split_by_partition(batch, partitioner)
                handle.write_partitions(parts)
            self.add_metric("shuffleWriteTime", perf_counter() - t0)
            self.add_metric("shuffleBytesWritten", handle.bytes_written)

            reader = manager.reader(handle)
            t0 = perf_counter()
            for p in range(self.num_partitions):
                # GpuShuffleCoalesce: concat a partition's tables on host up
                # to the target batch size, one H2D upload per flush
                pending: List[HostTable] = []
                pending_bytes = 0
                for t in reader.read_partition(p):
                    pending.append(t)
                    pending_bytes += t.nbytes()
                    if pending_bytes >= self.target_batch_bytes:
                        yield self._upload(pending)
                        pending, pending_bytes = [], 0
                if pending:
                    yield self._upload(pending)
            self.add_metric("shuffleReadTime", perf_counter() - t0)
            self.add_metric("shuffleBytesRead", reader.bytes_read)
        finally:
            manager.remove_shuffle(handle)

    @staticmethod
    def _upload(tables: List[HostTable]) -> DeviceTable:
        host = tables[0] if len(tables) == 1 else HostTable.concat(tables)
        return DeviceTable.from_host(host)
