"""Chaos harness + runtime recovery tests.

Covers the unified fault registry (spark.rapids.test.faults), shuffle
fetch retry/backoff with per-peer exclusion, lost-map-output recompute
from plan lineage (forced peer eviction included), and the per-operator
circuit breaker demoting a deterministically crashing op to CPU."""

import threading
import time

import numpy as np
import pytest

from spark_rapids_tpu.columnar import HostColumn, HostTable
from spark_rapids_tpu.conf import RapidsConf
from spark_rapids_tpu.errors import (
    ColumnarProcessingError,
    CorruptFrameError,
    KernelCrashError,
    MapOutputLostError,
    RetryOOM,
    ShuffleFetchError,
    ShuffleTransportError,
)
from spark_rapids_tpu.runtime.faults import (
    CIRCUIT_BREAKER,
    FAULT_POINTS,
    FAULTS,
    RECOVERY,
    FaultRegistry,
    parse_fault_spec,
)
from spark_rapids_tpu import types as T


@pytest.fixture(autouse=True)
def _clean_fault_state():
    """Fault/breaker state is process-global by design (a demotion lasts
    the session); tests must not leak it into each other."""
    FAULTS.disarm()
    CIRCUIT_BREAKER.reset()
    yield
    FAULTS.disarm()
    CIRCUIT_BREAKER.reset()


def _table(n=64, seed=0):
    rng = np.random.default_rng(seed)
    return HostTable(["k", "v"], [
        HostColumn(T.LONG, rng.integers(0, 8, n).astype(np.int64)),
        HostColumn(T.DOUBLE, rng.random(n)),
    ])


# ---------------------------------------------------------------------------
# fault registry
# ---------------------------------------------------------------------------


def test_fault_spec_parsing_and_validation():
    armed = parse_fault_spec(
        "shuffle.fetch.metadata:fetch:0.5:7;"
        "exec.execute@Project:crash:3;"
        "dispatch.kernel:oom:1.0:9")
    assert [a.kind for a in armed] == ["fetch", "crash", "oom"]
    assert armed[0].prob == 0.5 and armed[0].remaining is None
    assert armed[1].op == "Project" and armed[1].remaining == 3
    assert armed[2].prob == 1.0  # "1.0" is a probability, "1" a count
    with pytest.raises(ColumnarProcessingError, match="unknown fault point"):
        parse_fault_spec("no.such.point:fetch:1")
    with pytest.raises(ColumnarProcessingError, match="unknown fault kind"):
        parse_fault_spec("dispatch.kernel:frobnicate:1")
    with pytest.raises(ColumnarProcessingError, match="bad fault spec"):
        parse_fault_spec("dispatch.kernel")


def test_fault_firing_kinds_and_counters():
    reg = FaultRegistry()
    reg.arm("dispatch.kernel:oom:1;"
            "exec.execute:crash:1;"
            "shuffle.fetch.metadata:fetch:1;"
            "shuffle.transport.request:disconnect:1")
    with pytest.raises(RetryOOM):
        reg.fire("dispatch.kernel")
    with pytest.raises(KernelCrashError):
        reg.fire("exec.execute", op="Project")
    with pytest.raises(ShuffleFetchError):
        reg.fire("shuffle.fetch.metadata")
    with pytest.raises(ShuffleTransportError):
        reg.fire("shuffle.transport.request")
    # counts exhausted: all silent now
    reg.fire("dispatch.kernel")
    reg.fire("exec.execute")
    assert reg.counters() == {
        "dispatch.kernel": 1, "exec.execute": 1,
        "shuffle.fetch.metadata": 1, "shuffle.transport.request": 1}


def test_fault_probability_is_seeded_deterministic():
    def fires(seed):
        reg = FaultRegistry()
        reg.arm(f"dispatch.kernel:fetch:0.3:{seed}")
        out = []
        for _ in range(50):
            try:
                reg.fire("dispatch.kernel")
                out.append(0)
            except ShuffleFetchError:
                out.append(1)
        return out

    a, b = fires(7), fires(7)
    assert a == b  # deterministic replay
    assert 0 < sum(a) < 50  # actually probabilistic
    assert fires(8) != a  # seed matters


def test_fault_op_filter_only_hits_matching_op():
    reg = FaultRegistry()
    reg.arm("exec.execute@Aggregate:crash:5")
    reg.fire("exec.execute", op="Project")  # silent: filtered out
    with pytest.raises(KernelCrashError) as ei:
        reg.fire("exec.execute", op="Aggregate")
    # attribution is the exec fault guards' job, not the registry's —
    # a raw fire carries no fault_op (helper-exec names must never
    # reach the circuit breaker)
    assert getattr(ei.value, "fault_op", None) is None
    assert reg.counters() == {"exec.execute@Aggregate": 1}


def test_corrupt_kind_damages_data_deterministically():
    reg = FaultRegistry()
    reg.arm("shuffle.fetch.stream:corrupt:2:11")
    blob = bytes(range(64))
    out1 = reg.fire("shuffle.fetch.stream", data=blob)
    out2 = reg.fire("shuffle.fetch.stream", data=blob)
    assert out1 != blob and len(out1) == len(blob)
    assert reg.fire("shuffle.fetch.stream", data=blob) == blob  # exhausted
    reg2 = FaultRegistry()
    reg2.arm("shuffle.fetch.stream:corrupt:2:11")
    assert reg2.fire("shuffle.fetch.stream", data=blob) == out1
    assert reg2.fire("shuffle.fetch.stream", data=blob) == out2


def test_suspended_preserves_schedule_and_counters():
    reg = FaultRegistry()
    reg.arm("dispatch.kernel:fetch:2")
    with pytest.raises(ShuffleFetchError):
        reg.fire("dispatch.kernel")
    with reg.suspended():
        assert not reg.armed
        reg.fire("dispatch.kernel")  # silent: nothing armed
        reg.arm("")  # what a fault-free session's execute() does
    # armed state, remaining count, and counters all survive intact
    with pytest.raises(ShuffleFetchError):
        reg.fire("dispatch.kernel")
    reg.fire("dispatch.kernel")  # count of 2 now exhausted
    assert reg.counters() == {"dispatch.kernel": 2}


def test_rearming_same_spec_preserves_schedule():
    reg = FaultRegistry()
    reg.arm("dispatch.kernel:fetch:1")
    with pytest.raises(ShuffleFetchError):
        reg.fire("dispatch.kernel")
    reg.arm("dispatch.kernel:fetch:1")  # same spec: no reset
    reg.fire("dispatch.kernel")  # still exhausted
    assert reg.counters()["dispatch.kernel"] == 1
    reg.arm("dispatch.kernel:fetch:2")  # different spec: fresh
    with pytest.raises(ShuffleFetchError):
        reg.fire("dispatch.kernel")


# ---------------------------------------------------------------------------
# TPAK integrity (corrupt-frame detection)
# ---------------------------------------------------------------------------


def test_tpak_crc_catches_corruption():
    from spark_rapids_tpu.shuffle.serializer import pack_table, unpack_table
    blob = pack_table(_table())
    t, consumed = unpack_table(blob)
    assert consumed == len(blob) and t.num_rows == 64
    flipped = bytearray(blob)
    flipped[len(blob) // 2] ^= 0xFF
    with pytest.raises(CorruptFrameError):
        unpack_table(bytes(flipped))
    with pytest.raises(CorruptFrameError):
        unpack_table(blob[: len(blob) - 2])  # truncated


# ---------------------------------------------------------------------------
# shuffle fetch retry / backoff / exclusion (p2p)
# ---------------------------------------------------------------------------


def _p2p_env(executor_id, driver=None, **overrides):
    from spark_rapids_tpu.shuffle.p2p import P2PShuffleEnv
    conf = {"spark.rapids.shuffle.fetch.retryWaitMs": "1",
            "spark.rapids.shuffle.fetch.maxRetries": "3"}
    conf.update(overrides)
    return P2PShuffleEnv(RapidsConf(conf), executor_id=executor_id,
                         driver=driver)


def test_fetch_retry_survives_transient_faults():
    env = _p2p_env("exec-rt-0")
    try:
        handle = env.new_shuffle(2)
        handle.write_partitions([_table(16, 1), _table(16, 2)])
        FAULTS.arm("shuffle.fetch.metadata:fetch:2")  # first 2 hits fail
        before = RECOVERY.snapshot()
        reader = env.reader(handle)
        rows = sum(t.num_rows for t in reader.read_partition(0))
        assert rows == 16
        assert RECOVERY.snapshot()["fetch_retries"] - \
            before["fetch_retries"] == 2
    finally:
        env.close()


def test_fetch_retry_backoff_is_exponential():
    env = _p2p_env("exec-rt-1",
                   **{"spark.rapids.shuffle.fetch.retryWaitMs": "20",
                      "spark.rapids.shuffle.fetch.backoffMultiplier": "3.0"})
    try:
        handle = env.new_shuffle(1)
        handle.write_partitions([_table(8, 3)])
        FAULTS.arm("shuffle.fetch.metadata:fetch:2")
        t0 = time.perf_counter()
        list(env.reader(handle).read_partition(0))
        elapsed = time.perf_counter() - t0
        # waits: 20ms then 60ms -> at least ~80ms total
        assert elapsed >= 0.08
    finally:
        env.close()


def test_fetch_exhaustion_is_map_output_lost_and_excludes_peer():
    driver = None
    from spark_rapids_tpu.shuffle.heartbeat import ShuffleHeartbeatManager
    driver = ShuffleHeartbeatManager()
    env_a = _p2p_env("exec-ex-a", driver=driver)
    env_b = _p2p_env("exec-ex-b", driver=driver)
    try:
        env_a.heartbeat.beat_once()
        assert "exec-ex-b" in env_a.peers()
        env_b.catalog.add_block((0, 0, 0), b"\x00" * 32)
        FAULTS.arm("shuffle.fetch.metadata:fetch:99")
        before = RECOVERY.snapshot()
        with pytest.raises(MapOutputLostError) as ei:
            env_a.fetch_partition_with_retry(0, 0, "exec-ex-b")
        assert ei.value.executor_id == "exec-ex-b"
        # peer is excluded from future fetch targets...
        assert "exec-ex-b" not in env_a.peers()
        assert RECOVERY.snapshot()["peer_exclusions"] > \
            before["peer_exclusions"]
        # ...and an excluded peer fails fast, without retries
        with pytest.raises(MapOutputLostError, match="excluded"):
            env_a.fetch_partition_with_retry(0, 0, "exec-ex-b")
    finally:
        env_a.close()
        env_b.close()


def test_chronically_flaky_peer_excluded_by_cumulative_budget():
    """Per-peer failure-count exclusion: a peer whose every fetch limps
    through after retries never exhausts a single call, but its
    CUMULATIVE failures cross the 4x-maxRetries budget and it is
    excluded anyway — recompute beats endless backoff."""
    from spark_rapids_tpu.shuffle.heartbeat import ShuffleHeartbeatManager
    driver = ShuffleHeartbeatManager()
    env_a = _p2p_env("exec-fl-a", driver=driver,
                     **{"spark.rapids.shuffle.fetch.maxRetries": "2"})
    env_b = _p2p_env("exec-fl-b", driver=driver)
    try:
        from spark_rapids_tpu.shuffle.serializer import pack_table
        env_a.heartbeat.beat_once()
        env_b.catalog.add_block((0, 0, 0), pack_table(_table(8, 6)))
        # budget = 4 * maxRetries = 8 cumulative failures; each fetch
        # fails twice then succeeds (2 < maxRetries+1, never exhausts),
        # so the NINTH failure (5th fetch) trips the budget
        for i in range(6):
            FAULTS.disarm()
            FAULTS.arm(f"shuffle.fetch.metadata:fetch:2:{i}")
            try:
                env_a.fetch_partition_with_retry(0, 0, "exec-fl-b")
            except MapOutputLostError as e:
                assert "chronically flaky" in str(e)
                break
        else:
            pytest.fail("cumulative failure budget never tripped")
        assert "exec-fl-b" not in env_a.peers()
    finally:
        env_a.close()
        env_b.close()


def test_rejoin_after_own_eviction_keeps_exclusions():
    """An executor that was itself evicted and rejoins must NOT re-trust
    peers it excluded for failing fetches: the driver's rejoin reply
    lists every live peer, which proves nothing about the excluded one.
    Only an actual re-registration (heartbeat delivery) restores trust."""
    import time as _t
    from spark_rapids_tpu.shuffle.heartbeat import ShuffleHeartbeatManager
    from spark_rapids_tpu.shuffle.transport import PeerInfo
    driver = ShuffleHeartbeatManager(heartbeat_timeout_s=0.15)
    env_a = _p2p_env("exec-rj-a", driver=driver)
    env_b = _p2p_env("exec-rj-b", driver=driver)
    try:
        env_a.heartbeat.beat_once()
        env_a.exclude_peer("exec-rj-b")
        assert "exec-rj-b" not in env_a.peers()
        # A misses its window; driver evicts it; B keeps beating
        _t.sleep(0.2)
        env_b.heartbeat.beat_once()
        assert "exec-rj-a" in driver.evict_dead()
        env_a.heartbeat.beat_or_recover()  # rejoin path
        assert "exec-rj-a" in driver.live_executors()
        # B is rediscovered but STILL excluded
        assert "exec-rj-b" in env_a._peers
        assert "exec-rj-b" not in env_a.peers()
        # a true re-registration of B restores trust
        env_a.heartbeat.beat_once()  # advance A's log cursor
        driver.register_executor(PeerInfo("exec-rj-b"))
        env_a.heartbeat.beat_once()
        assert "exec-rj-b" in env_a.peers()
    finally:
        env_a.close()
        env_b.close()


def test_local_executor_is_never_excluded():
    env = _p2p_env("exec-loc-0")
    try:
        handle = env.new_shuffle(1)
        handle.write_partitions([_table(8, 4)])
        FAULTS.arm("shuffle.fetch.metadata:fetch:99")
        with pytest.raises(MapOutputLostError):
            env.fetch_partition_with_retry(handle.shuffle_id, 0,
                                           env.executor_id)
        FAULTS.disarm()
        # local fetches keep working after exhaustion (recompute relies
        # on rewriting + refetching locally)
        out = env.fetch_partition_with_retry(handle.shuffle_id, 0,
                                             env.executor_id)
        assert sum(t.num_rows for _, _, t in out) == 8
    finally:
        env.close()


def test_corrupt_compressed_blob_is_retryable_not_fatal():
    """With a compression codec the TPAK CRC sits UNDER the compression,
    so the codec error is the only corruption signal — decode_blob must
    normalize it to the retryable kind for both read paths."""
    from spark_rapids_tpu.shuffle.manager import _compress, decode_blob
    from spark_rapids_tpu.shuffle.serializer import pack_table
    blob = _compress("zlib", pack_table(_table(16, 9)))
    t = decode_blob("zlib", blob)
    assert t.num_rows == 16
    damaged = bytearray(blob)
    damaged[len(blob) // 2] ^= 0xFF
    with pytest.raises(CorruptFrameError):
        decode_blob("zlib", bytes(damaged))
    # and end-to-end: a corrupt delivery under zlib refetches cleanly
    env = _p2p_env("exec-zc-0", **{
        "spark.rapids.shuffle.compression.codec": "zlib"})
    try:
        handle = env.new_shuffle(1)
        handle.write_partitions([_table(32, 12)])
        FAULTS.arm("shuffle.fetch.stream:corrupt:1")
        rows = sum(t.num_rows for t in env.reader(handle).read_partition(0))
        assert rows == 32
    finally:
        env.close()


def test_corrupt_frame_refetches_clean_copy():
    env = _p2p_env("exec-crc-0")
    try:
        handle = env.new_shuffle(1)
        handle.write_partitions([_table(32, 5)])
        # corrupt exactly one completed-block delivery; the CRC rejects
        # it and the retry refetches the intact catalog blob
        FAULTS.arm("shuffle.fetch.stream:corrupt:1")
        before = RECOVERY.snapshot()
        rows = sum(t.num_rows for t in env.reader(handle).read_partition(0))
        assert rows == 32
        assert RECOVERY.snapshot()["fetch_retries"] > \
            before["fetch_retries"]
    finally:
        env.close()


# ---------------------------------------------------------------------------
# bounce-buffer acquire timeout (satellite: no infinite hang)
# ---------------------------------------------------------------------------


def test_bounce_acquire_default_timeout_raises_retryable():
    from spark_rapids_tpu.shuffle.transport import BounceBufferManager
    pool = BounceBufferManager(32, 1, default_timeout=0.05)
    buf = pool.acquire()
    t0 = time.perf_counter()
    with pytest.raises(ShuffleFetchError, match="bounce"):
        pool.acquire()  # no explicit timeout -> pool default applies
    assert time.perf_counter() - t0 < 5.0
    pool.release(buf)
    # explicit None still means wait-forever semantics (releaser thread)
    got = []
    t = threading.Thread(
        target=lambda: got.append(pool.acquire(timeout=None)))
    t.start()
    t.join(timeout=2)
    assert got and got[0] is buf


def test_p2p_env_plumbs_bounce_timeout_from_conf():
    env = _p2p_env("exec-bt-0", **{
        "spark.rapids.shuffle.p2p.bounceAcquireTimeoutMs": "40"})
    try:
        assert env.recv_pool.default_timeout == pytest.approx(0.04)
        assert env.send_pool.default_timeout == pytest.approx(0.04)
    finally:
        env.close()


# ---------------------------------------------------------------------------
# lost-map recompute (forced peer eviction -> recompute, not failure)
# ---------------------------------------------------------------------------


def test_peer_eviction_triggers_map_output_recompute():
    """The acceptance scenario: a peer holding map output dies mid-query
    (driver evicts it); the read detects the missing maps, the exchange
    recomputes them from the retained lineage, and the partition read
    completes with every row — the query never fails."""
    from spark_rapids_tpu.shuffle.heartbeat import ShuffleHeartbeatManager
    driver = ShuffleHeartbeatManager(heartbeat_timeout_s=30.0)
    env_a = _p2p_env("exec-rc-a", driver=driver)
    env_b = _p2p_env("exec-rc-b", driver=driver)
    try:
        env_a.heartbeat.beat_once()
        handle = env_a.new_shuffle(2)
        t0, t1 = _table(16, 10), _table(16, 11)
        from spark_rapids_tpu.shuffle.partitioning import (
            HashPartitioner,
            split_by_partition,
        )
        from spark_rapids_tpu.columnar import DeviceTable
        from spark_rapids_tpu.ops.expr import col

        parter = HashPartitioner([col("k").bind([("k", T.LONG),
                                                 ("v", T.DOUBLE)])], 2)
        parts0 = split_by_partition(DeviceTable.from_host(t0), parter)
        parts1 = split_by_partition(DeviceTable.from_host(t1), parter)
        handle.write_partitions(parts0)
        handle.write_partitions(parts1)
        total_p0 = sum(t.num_rows
                       for t in env_a.reader(handle).read_partition(0))

        # map 1's blocks "live on" peer B: move them out of A's catalog
        for p in sorted(handle._written[1]):
            bid = (handle.shuffle_id, 1, p)
            blob = env_a.catalog.get_block(bid)
            env_b.catalog.add_block(bid, blob)
            env_a.catalog.remove_block(bid)
        # sanity: with B alive the full read still works (fetch from B)
        assert sum(t.num_rows for t in
                   env_a.reader(handle).read_partition(0)) == total_p0

        # FORCE EVICTION mid-query: driver declares B dead; A stops
        # targeting it
        env_a.on_peer_evicted("exec-rc-b")
        with pytest.raises(MapOutputLostError) as ei:
            list(env_a.reader(handle).read_partition(0))
        assert ei.value.map_ids == [1]

        # the exchange-side recovery: recompute map 1 from lineage
        # (batch 1 of the retained child) and retry the read
        before = RECOVERY.snapshot()
        handle.rewrite_map(1, parts1)
        RECOVERY.bump("recomputed_maps")
        rows = sum(t.num_rows
                   for t in env_a.reader(handle).read_partition(0))
        assert rows == total_p0  # every row of the dead peer's map is back
        assert RECOVERY.snapshot()["recomputed_maps"] > \
            before["recomputed_maps"]
    finally:
        env_a.close()
        env_b.close()


def test_exchange_recomputes_lost_maps_end_to_end(cpu_session):
    """Engine-level: a repartition query whose fetches exhaust their
    retries mid-read recomputes the missing map outputs from the plan
    lineage instead of failing (metric: recomputedMapOutputs)."""
    from spark_rapids_tpu import functions as F
    from spark_rapids_tpu.session import TpuSession

    data = {"k": (np.arange(200) % 16).astype(np.int64),
            "v": np.arange(200, dtype=np.float64)}

    def build(s):
        return (s.create_dataframe(dict(data)).repartition(4, "k")
                .group_by("k").agg(F.count("v").alias("c"),
                                   F.sum("v").alias("s")))

    s = TpuSession({
        "spark.rapids.shuffle.mode": "P2P",
        "spark.rapids.shuffle.localDeviceSplit.enabled": "false",
        "spark.rapids.shuffle.fetch.retryWaitMs": "1",
        "spark.rapids.shuffle.fetch.maxRetries": "1",
        # 2 straight fetch failures exhaust maxRetries=1 and declare the
        # (local) map outputs lost; the recompute rewrites them and the
        # retried read succeeds
        "spark.rapids.test.faults": "shuffle.fetch.metadata:fetch:2",
    })
    from tests.asserts import assert_tpu_and_cpu_are_equal
    assert_tpu_and_cpu_are_equal(build, s, cpu_session)
    ex = s._last_executable
    found = []

    def walk(e):
        m = getattr(e, "metrics", None)
        if m and "recomputedMapOutputs" in m:
            found.append(m["recomputedMapOutputs"])
        for c in getattr(e, "children", ()):
            walk(c)
        for attr in ("source", "tpu_exec", "cpu_node", "scan_node"):
            if getattr(e, attr, None) is not None:
                walk(getattr(e, attr))

    walk(ex)
    assert found and found[0] >= 1


# ---------------------------------------------------------------------------
# circuit breaker: deterministic kernel crash -> CPU demotion
# ---------------------------------------------------------------------------


def test_circuit_breaker_demotes_deterministic_crasher(cpu_session):
    """The acceptance scenario: an op that crashes EVERY time it runs on
    device is demoted to the CPU fallback path (with a recorded reason)
    and the query succeeds instead of failing forever."""
    from spark_rapids_tpu.ops.expr import col, lit
    from spark_rapids_tpu.session import TpuSession
    data = {"a": np.arange(32, dtype=np.int64)}

    def build(s):
        return s.create_dataframe(dict(data)).filter(col("a") > lit(10))

    s = TpuSession({
        # deterministic: the Filter op crashes on device, always
        "spark.rapids.test.faults": "exec.execute@Filter:crash:999",
        "spark.rapids.sql.runtimeFallback.maxFailures": "2",
    })
    from tests.asserts import assert_tpu_and_cpu_are_equal
    assert_tpu_and_cpu_are_equal(build, s, cpu_session)

    demoted = CIRCUIT_BREAKER.demoted_ops()
    assert "Filter" in demoted
    assert "circuit breaker" in demoted["Filter"]
    assert "injected kernel crash" in demoted["Filter"]
    # the fallback reason surfaces through explain like any other
    assert "circuit breaker" in s.explain(build(s).plan)
    # and the replay count is observable
    assert s.last_fault_replays >= 2


def test_runtime_fallback_disabled_surfaces_the_crash():
    from spark_rapids_tpu.ops.expr import col, lit
    from spark_rapids_tpu.session import TpuSession
    s = TpuSession({
        "spark.rapids.test.faults": "exec.execute@Filter:crash:999",
        "spark.rapids.sql.runtimeFallback.enabled": "false",
    })
    df = (s.create_dataframe({"a": np.arange(8, dtype=np.int64)})
          .filter(col("a") > lit(3)))
    with pytest.raises(KernelCrashError):
        df.collect_table()
    assert CIRCUIT_BREAKER.demoted_ops() == {}


def test_transient_crash_replays_without_demotion(cpu_session):
    """One-off crashes (count=1) recover by query replay alone — no
    demotion, and the op stays on device for later queries."""
    from spark_rapids_tpu.ops.expr import col, lit
    from spark_rapids_tpu.session import TpuSession
    data = {"a": np.arange(16, dtype=np.int64)}

    def build(s):
        return s.create_dataframe(dict(data)).filter(col("a") > lit(5))

    s = TpuSession({
        "spark.rapids.test.faults": "exec.execute@Filter:crash:1",
        "spark.rapids.sql.runtimeFallback.maxFailures": "2",
    })
    from tests.asserts import assert_tpu_and_cpu_are_equal
    assert_tpu_and_cpu_are_equal(build, s, cpu_session)
    assert CIRCUIT_BREAKER.demoted_ops() == {}
    assert s.last_fault_replays == 1
    assert s._last_executable.metrics.get("runtimeFaultReplays") == 1


# ---------------------------------------------------------------------------
# registry hygiene
# ---------------------------------------------------------------------------


def test_every_fault_point_names_an_existing_site():
    """The RL-FAULT-POINT contract, enforced here as well as in the lint
    CLI: the registry and the call sites cannot drift."""
    import ast
    import os

    import spark_rapids_tpu
    root = os.path.dirname(os.path.dirname(
        os.path.abspath(spark_rapids_tpu.__file__)))
    from spark_rapids_tpu.lint.repo_lint import (
        _check_fault_registry,
        _check_fault_sites,
        _iter_source_files,
    )
    calls, diags = {}, []
    for path in _iter_source_files(root):
        rel = os.path.relpath(path, root)
        if rel.startswith("spark_rapids_tpu/lint/"):
            continue
        with open(path) as f:
            _check_fault_sites(rel, ast.parse(f.read()), calls, diags)
    _check_fault_registry(calls, diags)
    assert diags == []
    assert set(calls) == set(FAULT_POINTS)
