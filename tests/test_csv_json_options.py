"""CSV/JSON Spark options matrix (reference analog: GpuCSVScan /
GpuJsonScan tagging + csv_test.py / json_test.py option coverage)."""

import os

import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.ops.expr import col, lit


def _write(tmp_path, name, text):
    p = os.path.join(tmp_path, name)
    with open(p, "w") as f:
        f.write(text)
    return p


def test_csv_sep_quote_comment_null(session, tmp_path):
    p = _write(tmp_path, "t.csv",
               "# a comment line\n"
               "a;b;c\n"
               "1;'x;y';NA\n"
               "# mid comment\n"
               "2;z;7\n")
    df = session.read_csv(
        p, sep=";", quote="'", comment="#", null_value="NA",
        schema=[("a", T.INT), ("b", T.STRING), ("c", T.INT)])
    rows = df.collect()
    assert rows == [(1, "x;y", None), (2, "z", 7)]


def test_csv_custom_float_spellings(session, tmp_path):
    p = _write(tmp_path, "f.csv", "x\nbad\n1.5\nP_INF\nN_INF\n")
    df = session.read_csv(
        p, nan_value="bad", positive_inf="P_INF", negative_inf="N_INF",
        schema=[("x", T.DOUBLE)])
    import math
    vals = [r[0] for r in df.collect()]
    assert math.isnan(vals[0]) and vals[1] == 1.5
    assert vals[2] == math.inf and vals[3] == -math.inf


def test_csv_headerless_and_whitespace(session, tmp_path):
    p = _write(tmp_path, "h.csv", "1,  padded  \n2,x\n")
    df = session.read_csv(
        p, header=False, ignore_leading_whitespace=True,
        ignore_trailing_whitespace=True,
        schema=[("i", T.INT), ("s", T.STRING)])
    assert df.collect() == [(1, "padded"), (2, "x")]


def test_csv_dropmalformed(session, tmp_path):
    p = _write(tmp_path, "m.csv", "a,b\n1,2\nonly_one_field\n3,4\n")
    df = session.read_csv(p, mode="DROPMALFORMED",
                          schema=[("a", T.INT), ("b", T.INT)])
    assert df.collect() == [(1, 2), (3, 4)]


def test_csv_timestamp_format(session, tmp_path):
    p = _write(tmp_path, "d.csv", "t\n2024/01/15 10:30:00\n")
    df = session.read_csv(
        p, timestamp_format="yyyy/MM/dd HH:mm:ss",
        schema=[("t", T.TIMESTAMP)])
    import datetime as dt
    assert df.collect()[0][0] == dt.datetime(2024, 1, 15, 10, 30)


def test_csv_bad_pattern_rejected(session, tmp_path):
    p = _write(tmp_path, "bad.csv", "t\nx\n")
    with pytest.raises(Exception, match="pattern"):
        session.read_csv(p, timestamp_format="QQQ-weird",
                         schema=[("t", T.TIMESTAMP)]).collect()


def test_json_multiline_array(session, tmp_path):
    p = _write(tmp_path, "m.json",
               '[{"a": 1, "b": "x"},\n {"a": 2, "b": "y"}]')
    df = session.read_json(p, multi_line=True,
                           schema=[("a", T.LONG), ("b", T.STRING)])
    assert df.collect() == [(1, "x"), (2, "y")]


def test_json_permissive_and_dropmalformed(session, tmp_path):
    text = '{"a": 1}\nnot json at all\n{"a": 3}\n'
    p1 = _write(tmp_path, "p.json", text)
    df = session.read_json(p1, schema=[("a", T.LONG)])
    assert [r[0] for r in df.collect()] == [1, None, 3]
    df2 = session.read_json(p1, mode="DROPMALFORMED",
                            schema=[("a", T.LONG)])
    assert [r[0] for r in df2.collect()] == [1, 3]
    with pytest.raises(Exception):
        session.read_json(p1, mode="FAILFAST",
                          schema=[("a", T.LONG)]).collect()


def test_json_primitives_as_string(session, tmp_path):
    p = _write(tmp_path, "s.json", '{"a": 1, "b": 2.5}\n{"a": 7, "b": 3}\n')
    df = session.read_json(p, primitives_as_string=True)
    rows = df.collect()
    assert all(isinstance(v, str) for r in rows for v in r if v is not None)


def test_csv_pattern_repeated_token_rejected(session, tmp_path):
    p = _write(tmp_path, "mm.csv", "t\nJuly 04, 2026\n")
    with pytest.raises(Exception, match="MMMM"):
        session.read_csv(p, timestamp_format="MMMM dd, yyyy",
                         schema=[("t", T.TIMESTAMP)]).collect()


def test_json_multiline_malformed_modes(session, tmp_path):
    p = _write(tmp_path, "bad.json", '[{"a": 1}, {"a": ')  # truncated
    rows = session.read_json(p, multi_line=True,
                             schema=[("a", T.LONG)]).collect()
    assert rows == [(None,)]  # PERMISSIVE: one all-null row
    rows = session.read_json(p, multi_line=True, mode="DROPMALFORMED",
                             schema=[("a", T.LONG)]).collect()
    assert rows == []
    with pytest.raises(Exception):
        session.read_json(p, multi_line=True, mode="FAILFAST",
                          schema=[("a", T.LONG)]).collect()


def test_csv_permissive_null_fills_ragged_rows(session, tmp_path):
    """Spark PERMISSIVE null-fills short rows rather than dropping them."""
    p = _write(tmp_path, "rag.csv", "a,b\n1,2\n3\n5,6\n")
    rows = sorted(session.read_csv(
        p, schema=[("a", T.INT), ("b", T.INT)]).collect())
    assert rows == [(1, 2), (3, None), (5, 6)]


def test_csv_dropmalformed_custom_float_drops_row(session, tmp_path):
    p = _write(tmp_path, "cf.csv", "x,y\n1.5,a\nxyz,b\n2.5,c\n")
    rows = session.read_csv(
        p, mode="DROPMALFORMED", nan_value="strange",
        schema=[("x", T.DOUBLE), ("y", T.STRING)]).collect()
    assert rows == [(1.5, "a"), (2.5, "c")]


def test_json_nan_constant_is_malformed(session, tmp_path):
    p = _write(tmp_path, "nan.json", '{"a": 1}\n{"a": NaN}\n{"a": 3}\n')
    rows = [r[0] for r in session.read_json(
        p, schema=[("a", T.LONG)]).collect()]
    assert rows == [1, None, 3]  # PERMISSIVE: NaN line -> null row


def test_filecache_distinguishes_options(tmp_path):
    from spark_rapids_tpu.io.filecache import FILE_CACHE
    from spark_rapids_tpu.session import TpuSession
    p = _write(str(tmp_path), "o.csv", "a\nNA\n5\n")
    s = TpuSession({"spark.rapids.filecache.enabled": "true"})
    FILE_CACHE.clear()
    r1 = s.read_csv(p, null_value="NA", schema=[("a", T.STRING)]).collect()
    r2 = s.read_csv(p, null_value="zz", schema=[("a", T.STRING)]).collect()
    assert r1 == [(None,), ("5",)]
    assert r2 == [("NA",), ("5",)]  # options must NOT share a cache entry


def test_csv_schema_inference_still_works(session, tmp_path):
    p = _write(tmp_path, "inf.csv", "a,b\n1,x\n2,y\n")
    assert session.read_csv(p).collect() == [(1, "x"), (2, "y")]


def test_csv_permissive_ragged_with_pruning(session, tmp_path):
    """Null-filled ragged fields map by the FILE's physical order even when
    columns are pruned (code-review: positional misalignment)."""
    p = _write(tmp_path, "prune.csv", "a,b\n1,2\n3\n")
    rows = sorted(session.read_csv(
        p, schema=[("a", T.INT), ("b", T.INT)], columns=["b"]).collect(),
        key=lambda r: (r[0] is None, r[0]))
    assert rows == [(2,), (None,)]


def test_parquet_filters_not_cached_together(tmp_path):
    from spark_rapids_tpu.io.filecache import FILE_CACHE
    from spark_rapids_tpu.session import TpuSession
    import os
    s = TpuSession({"spark.rapids.filecache.enabled": "true"})
    out = str(tmp_path / "pq")
    s.create_dataframe({"x": [1, 2, 3, 4]}).write_parquet(out)
    f = os.path.join(out, "part-00000.parquet")
    FILE_CACHE.clear()
    filtered = s.read_parquet(f, filters=[("x", ">", 2)]).count()
    unfiltered = s.read_parquet(f).count()
    assert filtered == 2 and unfiltered == 4
