"""JSON-lines scan + writer (reference: GpuJsonScan.scala /
GpuTextBasedPartitionReader — SURVEY.md §2.4)."""

from __future__ import annotations

import json as _json
from typing import List, Optional, Sequence

import pyarrow as pa
import pyarrow.json as pjson

from spark_rapids_tpu.columnar import HostTable
from spark_rapids_tpu.conf import RapidsConf, str_conf
from spark_rapids_tpu.io.arrow_convert import (
    arrow_schema_to_spark,
    decode_to_schema,
    spark_type_to_arrow,
)
from spark_rapids_tpu.io.common import FileScanNode
from spark_rapids_tpu.io.writer import write_partitioned
from spark_rapids_tpu.plan.nodes import Schema

JSON_READER_TYPE = str_conf(
    "spark.rapids.sql.format.json.reader.type", "AUTO",
    "PERFILE, COALESCING, MULTITHREADED or AUTO.")


class JsonScanNode(FileScanNode):
    format_name = "json"

    def __init__(self, paths, conf: RapidsConf, columns=None, reader_type=None,
                 schema: Optional[Schema] = None, **options):
        self.user_schema = schema
        super().__init__(paths, conf, columns=columns, reader_type=reader_type,
                         **options)

    def _conf_reader_type(self) -> str:
        return self.conf.get_entry(JSON_READER_TYPE)

    def _parse_opts(self):
        if not self.user_schema:
            return None
        return pjson.ParseOptions(explicit_schema=pa.schema([
            (n, spark_type_to_arrow(dt)) for n, dt in self.user_schema]))

    def file_schema(self, path: str) -> Schema:
        if self.user_schema:
            return list(self.user_schema)
        return arrow_schema_to_spark(
            pjson.read_json(path, parse_options=self._parse_opts()).schema)

    def read_file(self, path: str) -> HostTable:
        return decode_to_schema(pjson.read_json(path, parse_options=self._parse_opts()),
                                self.data_schema)


def write_json(table: HostTable, path: str,
               partition_by: Optional[Sequence[str]] = None) -> List[str]:
    """JSON-lines writer (Arrow has no JSON writer; rows serialize via the
    host columns directly)."""
    def _write_one(tbl: HostTable, file_path: str):
        cols = [c.to_pylist() for c in tbl.columns]
        with open(file_path, "w") as f:
            for i in range(tbl.num_rows):
                row = {n: cols[j][i] for j, n in enumerate(tbl.names)
                       if cols[j][i] is not None}
                f.write(_json.dumps(row, default=str) + "\n")
    return write_partitioned(table, path, _write_one, "json", partition_by)
